"""Mixed-workload CI smoke (round 19): scripts/loadgen.py --workload-mix
drives txt2img + img2img(mask) + controlnet + lora traffic through one live
multi-worker server and the scraped capability counters prove universal lane
batching — every kind seats in the shared dispatch stream (per-kind
``pa_serving_lane_capability_total`` deltas), zero inline fallbacks for
eligible shapes, run-delta batched fraction >= 0.8, prompts_lost == 0 — and
the evidence lands as ONE kind="mixed" ledger record. ``scripts/ci_tier1.sh``
runs this file as the explicit mixed-workload contract (slow-marked like the
loadgen e2e test, so the main tier-1 pytest pass doesn't pay the server
spin-up twice)."""

import json
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


def _mask_graph(graph):
    """img2img rung: a half-value SolidMask attached via SetLatentNoiseMask —
    the lane seats with the denoise-mask capability (kind=img2img_mask)."""
    g = json.loads(json.dumps(graph))
    g["10"] = {"class_type": "SolidMask",
               "inputs": {"value": 0.5, "width": 32, "height": 32}}
    g["11"] = {"class_type": "SetLatentNoiseMask",
               "inputs": {"samples": ["5", 0], "mask": ["10", 0]}}
    g["3"]["inputs"]["latent_image"] = ["11", 0]
    return g


def _lora_graph(graph, lora_path):
    """lora rung: LoraLoader between the checkpoint and the sampler — the
    serving delegate rides the request as batched low-rank factors."""
    g = json.loads(json.dumps(graph))
    g["12"] = {"class_type": "LoraLoader",
               "inputs": {"model": ["4", 0], "clip": ["4", 1],
                          "lora_name": str(lora_path),
                          "strength_model": 1.0, "strength_clip": 1.0}}
    g["3"]["inputs"]["model"] = ["12", 0]
    return g


def _controlnet_graph(graph, cn_path, hint_path):
    """controlnet rung: one shared trunk (every lane carries the same tiny
    net, so no ctrl-conflict bounces fragment the bucket)."""
    g = json.loads(json.dumps(graph))
    g["13"] = {"class_type": "TPULoadImage",
               "inputs": {"image_path": str(hint_path)}}
    g["14"] = {"class_type": "ControlNetLoader",
               "inputs": {"control_net_name": str(cn_path)}}
    g["15"] = {"class_type": "ControlNetApply",
               "inputs": {"conditioning": ["6", 0], "control_net": ["14", 0],
                          "image": ["13", 0], "strength": 0.6}}
    g["3"]["inputs"]["positive"] = ["15", 0]
    return g


def _synthesize_lora(tmp_path, ckpt):
    """Rank-2 kohya LoRA against a real attention projection of the tiny
    checkpoint (the test_stock_nodes delegate-test shape)."""
    from safetensors.numpy import save_file

    from comfyui_parallelanything_tpu.models import load_safetensors

    sd = load_safetensors(ckpt)
    target = next(
        k for k in sd
        if k.endswith("attn1.to_q.weight") and "input_blocks" in k
    ).removeprefix("model.diffusion_model.")
    out_d, in_d = sd[f"model.diffusion_model.{target}"].shape
    rng = np.random.default_rng(23)
    lora_path = tmp_path / "mix_style.safetensors"
    save_file({
        f"{target.removesuffix('.weight')}.lora_down.weight":
            rng.standard_normal((2, in_d)).astype(np.float32),
        f"{target.removesuffix('.weight')}.lora_up.weight":
            rng.standard_normal((out_d, 2)).astype(np.float32),
    }, str(lora_path))
    return lora_path


def _synthesize_controlnet(tmp_path):
    """Tiny ControlNet checkpoint for the (monkeypatched) tiny sd15 config
    (the test_host_graph synthesis shape)."""
    import jax
    from PIL import Image
    from safetensors.numpy import save_file

    import comfyui_parallelanything_tpu.models as models_pkg
    from comfyui_parallelanything_tpu.models import build_controlnet
    from tests.test_controlnet import _ldm_controlnet_sd, _randomized_cn

    cfg = models_pkg.sd15_config()
    cn = build_controlnet(cfg, jax.random.key(5), sample_shape=(1, 4, 4, 4))
    cn_sd = _ldm_controlnet_sd(cfg, _randomized_cn(cn, cfg).params)
    cn_path = tmp_path / "mix_cn.safetensors"
    save_file({k: np.ascontiguousarray(v) for k, v in cn_sd.items()},
              str(cn_path))
    hint_path = tmp_path / "mix_hint.png"
    Image.fromarray(
        (np.random.default_rng(3).uniform(0, 1, (32, 32, 3)) * 255)
        .astype(np.uint8)
    ).save(hint_path)
    return cn_path, hint_path


@pytest.mark.slow
class TestMixedWorkloadSmoke:
    def test_mixed_capability_traffic_shares_dispatch_stream(
            self, tmp_path, monkeypatch):
        from loadgen import (
            _append_ledger, run_load, workload_schedule, WORKLOAD_KINDS,
        )

        from comfyui_parallelanything_tpu.server import make_server
        from comfyui_parallelanything_tpu.serving import bucket as bucket_mod
        from tests.test_server import _stock_graph
        from tests.test_stock_nodes import _synthetic_stock_env

        out_dir = tmp_path / "out"
        srv, q = make_server(port=0, output_dir=str(out_dir), workers=4)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            paths = _synthetic_stock_env(tmp_path, monkeypatch)
            graph = _stock_graph(paths["ckpt"], str(out_dir))
            graph["3"]["inputs"]["steps"] = 6
            lora_path = _synthesize_lora(tmp_path, paths["ckpt"])
            cn_path, hint_path = _synthesize_controlnet(tmp_path)
            graphs = {
                "img2img": _mask_graph(graph),
                "lora": _lora_graph(graph, lora_path),
                "controlnet": _controlnet_graph(graph, cn_path, hint_path),
            }
            mix = {k: 1.0 / len(WORKLOAD_KINDS) for k in WORKLOAD_KINDS}

            # Warm pass: loader/encoders cached, base bucket program
            # compiled — the measured loop then exercises steady serving
            # (capability overlays still compile lazily inside it; lanes
            # queue behind the compile and co-batch after, so the shared
            # fraction survives).
            warm = run_load(base, graph, clients=1, requests=1, timeout=600,
                            seed_key="3:inputs:seed")
            assert warm["completed"] == 1, warm

            # A seed whose 12-draw schedule covers every kind (deterministic:
            # workload_schedule is pure in (seed, n)).
            seed = next(
                s for s in range(64)
                if set(workload_schedule(12, mix, seed=s)) ==
                set(WORKLOAD_KINDS)
            )
            with bucket_mod._batch_lock:
                stats0 = dict(bucket_mod._batch_stats)

            summary = run_load(
                base, graph, clients=6, requests=2, timeout=600,
                seed_key="3:inputs:seed", seed=seed,
                workload_mix=mix, workload_graphs=graphs,
            )
            print(json.dumps(summary))

            with bucket_mod._batch_lock:
                stats1 = dict(bucket_mod._batch_stats)

            assert summary["completed"] == 12 and summary["failed"] == 0, \
                summary
            assert not summary.get("prompts_lost"), summary
            assert summary["workload_mix"] == mix
            sched = workload_schedule(12, mix, seed=seed)
            want = {k: sched.count(k) for k in set(sched)}
            assert summary["workload_counts"] == want, summary

            # Every capability seated in the shared stream: the per-kind
            # lane-capability deltas tick for all four traffic kinds
            # (img2img traffic seats as the denoise-mask capability).
            caps = summary["lane_capability"] or {}
            for kind in ("txt2img", "img2img_mask", "controlnet", "lora"):
                assert caps.get(kind, 0) >= 1, (kind, caps, summary)

            # Zero inline fallbacks for eligible shapes, zero control-trunk
            # conflicts (one shared tiny net) — the "universal" in universal
            # lane batching. Absent counters scrape as None == never fired.
            assert not summary["serving_inline_fallbacks"], summary
            assert not summary["serving_ctrl_conflicts"], summary

            # Run-delta shared-dispatch fraction (this run's lane-steps, not
            # the process-lifetime gauge the summary carries): >= 0.8 of the
            # mixed traffic's lane-steps ride occupancy>1 dispatches.
            d_total = stats1["total"] - stats0["total"]
            d_shared = stats1["shared"] - stats0["shared"]
            assert d_total >= 12 * 6, (stats0, stats1)
            frac = d_shared / d_total
            assert frac >= 0.8, (frac, stats0, stats1)
            assert summary["dispatch_amortization"] >= 1.0, summary
            assert 0.0 < summary["serving_batched_fraction"] <= 1.0, summary

            # The kind="mixed" ledger record (hermetic: redirected to tmp —
            # the CLI path banks the same record via the repo ledger).
            ledger_dir = tmp_path / "ledger"
            monkeypatch.setenv("PA_LEDGER_DIR", str(ledger_dir))
            _append_ledger(summary, base, kind="mixed")
            records = [
                json.loads(line) for line in
                open(ledger_dir / "perf_ledger.jsonl")
            ]
            assert len(records) == 1
            rec = records[0]
            assert rec["kind"] == "mixed"
            assert rec["schema"] == "pa-perf-ledger/v1"
            assert rec["workload_counts"] == want
            assert rec["completed"] == 12
        finally:
            srv.shutdown()
            q.shutdown()
