"""Tests for shared ops: timestep embedding, attention backends, pallas flash kernel
(interpreter mode on the CPU platform)."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.ops import attention, timestep_embedding
from comfyui_parallelanything_tpu.ops.attention import _xla_attention
from comfyui_parallelanything_tpu.ops.pallas.flash_attention import flash_attention


class TestTimestepEmbedding:
    def test_shape_and_range(self):
        emb = timestep_embedding(jnp.arange(4, dtype=jnp.float32), 128)
        assert emb.shape == (4, 128)
        assert np.all(np.abs(np.asarray(emb)) <= 1.0 + 1e-6)

    def test_odd_dim(self):
        emb = timestep_embedding(jnp.ones((2,)), 65)
        assert emb.shape == (2, 65)

    def test_t_zero_finite(self):
        emb = timestep_embedding(jnp.zeros((1,)), 64)
        assert np.all(np.isfinite(np.asarray(emb)))


def _qkv(b=2, sq=64, sk=48, h=4, d=32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, sq, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, sk, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, sk, h, d)), jnp.float32)
    return q, k, v


class TestAttention:
    def test_xla_softmax_rows_sum(self):
        q, k, v = _qkv()
        out = attention(q, k, v)
        assert out.shape == q.shape

    def test_self_vs_manual(self):
        q, k, v = _qkv(b=1, sq=8, sk=8, h=1, d=4)
        out = np.asarray(attention(q, k, v))[0, :, 0, :]
        qm, km, vm = (np.asarray(a)[0, :, 0, :] for a in (q, k, v))
        logits = qm @ km.T / np.sqrt(4)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, probs @ vm, rtol=1e-5, atol=1e-6)


class TestBackendEnvOverride:
    """PA_TPU_ATTENTION_BACKEND seeds the startup backend (ops/attention.py
    _initial_backend) so a driving process can force the safe XLA path for
    every child it spawns after a failed hardware probe (scripts/tpu_watchdog)."""

    def test_env_forces_xla(self, monkeypatch):
        import importlib

        mod = importlib.import_module("comfyui_parallelanything_tpu.ops.attention")

        monkeypatch.setenv("PA_TPU_ATTENTION_BACKEND", "xla")
        assert mod._initial_backend() == "xla"

    def test_invalid_env_falls_back_to_auto(self, monkeypatch):
        import importlib

        mod = importlib.import_module("comfyui_parallelanything_tpu.ops.attention")

        monkeypatch.setenv("PA_TPU_ATTENTION_BACKEND", "cuda")
        assert mod._initial_backend() == "auto"

    def test_unset_env_is_auto(self, monkeypatch):
        import importlib

        mod = importlib.import_module("comfyui_parallelanything_tpu.ops.attention")

        monkeypatch.delenv("PA_TPU_ATTENTION_BACKEND", raising=False)
        assert mod._initial_backend() == "auto"

    def test_resolved_backends_records_actual_path(self):
        # Evidence labeling: after a call, resolved_backends() names the path
        # that actually served it ("auto" never appears) — bench.py stamps
        # this into every measured record.
        import importlib

        mod = importlib.import_module("comfyui_parallelanything_tpu.ops.attention")

        q, k, v = _qkv(b=1, sq=8, sk=8, h=1, d=4)
        mod.attention_local(q, k, v)  # CPU + unaligned shapes -> xla
        assert "xla" in mod.resolved_backends()
        assert "auto" not in mod.resolved_backends()


class TestChunkedAttention:
    """Memory-bounded XLA attention (lax.scan over query blocks): the only
    path that fits SD-class 1024² attention (40/64-dim heads, pallas-
    ineligible) on one chip — S×S logits never materialize."""

    def _mod(self):
        import importlib

        return importlib.import_module("comfyui_parallelanything_tpu.ops.attention")

    def test_matches_plain_xla(self, monkeypatch):
        att = self._mod()
        q, k, v = _qkv(b=2, sq=96, sk=64, h=2, d=16, seed=3)
        # Force several scan blocks: threshold smaller than the logits size.
        monkeypatch.setattr(att, "_CHUNK_THRESHOLD", 2 * 2 * 64 * 16)
        out = att._xla_chunked_attention(q, k, v, scale=16 ** -0.5)
        ref = att._xla_attention(q, k, v, scale=16 ** -0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)  # bf16-scale matmuls

    def test_non_divisible_sq_padding(self, monkeypatch):
        att = self._mod()
        q, k, v = _qkv(b=1, sq=53, sk=40, h=2, d=8, seed=4)  # 53 % block != 0
        monkeypatch.setattr(att, "_CHUNK_THRESHOLD", 1 * 2 * 40 * 16)
        out = att._xla_chunked_attention(q, k, v, scale=8 ** -0.5)
        ref = att._xla_attention(q, k, v, scale=8 ** -0.5)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)

    def test_small_shapes_fall_through_to_plain(self):
        att = self._mod()
        q, k, v = _qkv(b=1, sq=8, sk=8, h=1, d=4)
        # Default threshold is far above this shape: identical single-pass path.
        out = att._xla_chunked_attention(q, k, v, scale=0.5)
        ref = att._xla_attention(q, k, v, scale=0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)

    def test_auto_routes_big_logits_to_chunked(self, monkeypatch):
        att = self._mod()
        monkeypatch.setattr(att, "_CHUNK_THRESHOLD", 64)
        monkeypatch.setattr(att, "_RESOLVED", set())
        q, k, v = _qkv(b=1, sq=32, sk=32, h=2, d=8)
        att.attention_local(q, k, v)  # 1*2*32*32 = 2048 > 64 -> chunked
        assert att.resolved_backends() == ("xla_chunked",)

    def test_bf16_softmax_env_matches_f32_at_bf16_tolerance(self, monkeypatch):
        # The sd15_16 MFU-budget lever: bf16 logits+softmax halves the chunked
        # path's HBM traffic; numerics must stay within bf16 tolerances.
        att = self._mod()
        q, k, v = _qkv(b=2, sq=96, sk=64, h=2, d=16, seed=7)
        monkeypatch.setattr(att, "_CHUNK_THRESHOLD", 2 * 2 * 64 * 16)
        ref = att._xla_chunked_attention(q, k, v, scale=16 ** -0.5)
        monkeypatch.setenv("PA_ATTN_BF16_SOFTMAX", "1")
        out = att._xla_chunked_attention(q, k, v, scale=16 ** -0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)

    def test_chunk_elems_env_overrides_threshold(self, monkeypatch):
        att = self._mod()
        monkeypatch.setattr(att, "_RESOLVED", set())
        monkeypatch.setenv("PA_ATTN_CHUNK_ELEMS", "64")
        q, k, v = _qkv(b=1, sq=32, sk=32, h=2, d=8)
        att.attention_local(q, k, v)  # 2048 > 64 -> chunked
        assert att.resolved_backends() == ("xla_chunked",)
        assert att.chunk_config() == {
            "chunk_elems": 64, "bf16_softmax": False,
            # No degradation-ladder shrink in effect (round 14 evidence
            # labeling — a degraded process must not bank as configured).
            "degraded": False,
            # Per-field provenance: only the threshold came from the env.
            "sources": {"chunk_elems": "env", "bf16_softmax": "default"},
        }

    def test_persisted_chunk_tuning_honored(self, tmp_path, monkeypatch):
        # The watchdog's chunk sweep persists the measured winner; a fresh
        # process (no env) must serve it.
        import json as _json

        att = self._mod()
        path = tmp_path / "attn_chunk.json"
        path.write_text(_json.dumps(
            {"source": "measured", "chunk_elems": 128, "bf16_softmax": True}
        ))
        monkeypatch.setattr(att, "_CHUNK_TUNING_PATH", str(path))
        att._chunk_tuning.cache_clear()
        try:
            assert att._chunk_threshold() == 128
            assert att._softmax_dtype() == jnp.bfloat16
            cfg = att.chunk_config()
            assert cfg["sources"] == {"chunk_elems": "measured",
                                      "bf16_softmax": "measured"}
            assert cfg["chunk_elems"] == 128
            # Env still wins over the persisted table (the sweep itself).
            monkeypatch.setenv("PA_ATTN_CHUNK_ELEMS", "256")
            monkeypatch.setenv("PA_ATTN_BF16_SOFTMAX", "0")
            assert att._chunk_threshold() == 256
            assert att._softmax_dtype() == jnp.float32
        finally:
            att._chunk_tuning.cache_clear()

    def test_explicit_backend_name(self, monkeypatch):
        att = self._mod()
        att.set_attention_backend("xla_chunked")
        try:
            monkeypatch.setattr(att, "_RESOLVED", set())
            q, k, v = _qkv(b=1, sq=16, sk=16, h=1, d=4)
            out = att.attention_local(q, k, v)
            assert out.shape == q.shape
            assert att.resolved_backends() == ("xla_chunked",)
        finally:
            att.set_attention_backend("auto")

    def test_forced_pallas_jax_padded_dim_takes_xla_family(self, monkeypatch):
        # The watchdog's probe-failure fallback forces pallas_jax globally;
        # 40/64-dim heads (upstream kernel has no lane padding) must route to
        # the safe XLA family — including the chunked path for big logits —
        # not to the unprobed in-repo padded kernel.
        att = self._mod()
        att.set_attention_backend("pallas_jax")
        try:
            monkeypatch.setattr(att, "_RESOLVED", set())
            q, k, v = _qkv(b=1, sq=16, sk=16, h=1, d=4)  # 4 % 128 != 0
            out = att.attention_local(q, k, v)
            ref = att._xla_attention(q, k, v, scale=4 ** -0.5)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-2, atol=2e-2)
            assert att.resolved_backends() == ("xla",)
            monkeypatch.setattr(att, "_CHUNK_THRESHOLD", 64)
            monkeypatch.setattr(att, "_RESOLVED", set())
            att.attention_local(*_qkv(b=1, sq=32, sk=32, h=2, d=8))
            assert att.resolved_backends() == ("xla_chunked",)
        finally:
            att.set_attention_backend("auto")

    def test_forced_pallas_jax_unaligned_seq_takes_xla_family(self,
                                                              monkeypatch):
        # Upstream jax flash kernel asserts seq % block == 0 (no padding); a
        # forced pallas_jax on a 128-lane head but non-block-aligned sequence
        # (e.g. an unswept WAN-class latent length) must fall back to the XLA
        # family instead of crashing at trace time.
        att = self._mod()
        att.set_attention_backend("pallas_jax")
        try:
            monkeypatch.setattr(att, "_RESOLVED", set())
            q, k, v = _qkv(b=1, sq=40, sk=40, h=1, d=128)  # 40 % 128 != 0
            out = att.attention_local(q, k, v)
            ref = att._xla_attention(q, k, v, scale=128 ** -0.5)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-2, atol=2e-2)
            assert att.resolved_backends() == ("xla",)
            # Mixed alignment (aligned q, unaligned kv) is equally unsafe.
            monkeypatch.setattr(att, "_RESOLVED", set())
            q2, k2, v2 = _qkv(b=1, sq=128, sk=72, h=1, d=128)
            att.attention_local(q2, k2, v2)
            assert att.resolved_backends() == ("xla",)
        finally:
            att.set_attention_backend("auto")


class TestKernelTuning:
    """Data-driven block sizes / backend choice (ops/pallas/tuning.py): the
    mechanism bench_kernels.py --apply feeds on real hardware."""

    def _table(self, entries):
        return {"source": "measured", "block_q": 256, "block_k": 256,
                "entries": entries}

    def test_defaults_without_file(self, monkeypatch):
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        monkeypatch.setattr(tuning, "_PATH", "/nonexistent/tuning.json")
        tuning.kernel_tuning.cache_clear()
        try:
            assert tuning.best_blocks(4608) == (256, 256)
            assert tuning.pallas_wins(4608) is True  # default guess
        finally:
            tuning.kernel_tuning.cache_clear()

    def test_measured_entries_drive_choice(self, monkeypatch):
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        table = self._table([
            {"seq": 4608, "block_q": 512, "block_k": 256,
             "pallas_ms": 1.0, "xla_ms": 2.0},
            {"seq": 512, "block_q": 128, "block_k": 128,
             "pallas_ms": 3.0, "xla_ms": 1.0},  # kernel LOSES at short seq
        ])
        monkeypatch.setattr(tuning, "kernel_tuning", lambda: {**tuning._DEFAULT, **table})
        assert tuning.best_blocks(4000) == (512, 256)  # nearest: 4608
        assert tuning.best_blocks(600) == (128, 128)
        assert tuning.pallas_wins(4608) is True
        assert tuning.pallas_wins(384) is False  # nearest entry says xla

    def test_xla_oom_entry_counts_as_pallas_win(self, monkeypatch):
        # An entry whose XLA measurement failed (S×S logits OOM at video
        # lengths) marks a length where the fused kernel is MANDATORY.
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        table = self._table([
            {"seq": 4608, "block_q": 256, "block_k": 256,
             "pallas_ms": 2.0, "xla_ms": 1.5},        # xla narrowly wins
            {"seq": 32768, "block_q": 256, "block_k": 512,
             "pallas_ms": 40.0, "xla_ms": None},      # xla OOMed
        ])
        monkeypatch.setattr(
            tuning, "kernel_tuning", lambda: {**tuning._DEFAULT, **table}
        )
        assert tuning.pallas_wins(32768) is True   # never route 32k to xla
        assert tuning.pallas_wins(4608) is False

    def test_foreign_device_table_ignored(self, monkeypatch, tmp_path):
        # A v5e-measured table must not apply on a different TPU generation.
        import json as _json

        from comfyui_parallelanything_tpu.ops.pallas import tuning

        p = tmp_path / "tuning.json"
        p.write_text(_json.dumps({
            "device_kind": "TPU v99", "block_q": 512, "block_k": 512,
            "entries": [{"seq": 128, "block_q": 512, "block_k": 512,
                         "pallas_ms": 9.0, "xla_ms": 1.0}],
        }))
        monkeypatch.setattr(tuning, "_PATH", str(p))
        tuning.kernel_tuning.cache_clear()
        try:
            assert tuning.kernel_tuning()["source"] == "default"
            assert tuning.best_blocks(128) == (256, 256)
        finally:
            tuning.kernel_tuning.cache_clear()

    def test_write_and_reload_roundtrip(self, monkeypatch, tmp_path):
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        monkeypatch.setattr(tuning, "_PATH", str(tmp_path / "tuning.json"))
        tuning.kernel_tuning.cache_clear()
        try:
            import jax

            kind = jax.devices()[0].device_kind  # must match to be applied
            tuning.write_tuning({
                "device_kind": kind,
                "block_q": 512, "block_k": 128,
                "entries": [{"seq": 16384, "block_q": 512, "block_k": 128,
                             "pallas_ms": 5.0, "xla_ms": 50.0}],
            })
            t = tuning.kernel_tuning()
            assert t["source"] == "measured" and t["device_kind"] == kind
            assert tuning.best_blocks(20000) == (512, 128)
        finally:
            tuning.kernel_tuning.cache_clear()

    def test_padded_head_dim_gate(self, monkeypatch):
        # Non-128-aligned head dims (40/64 UNet heads) run the kernel
        # zero-padded — a FLOP tax that must PROVE itself: without a measured
        # entry for that dim class auto says no; with a measured win it says
        # yes; aligned dims keep the default-True guess.
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        monkeypatch.setattr(
            tuning, "kernel_tuning", lambda: {**tuning._DEFAULT, "entries": []}
        )
        assert tuning.pallas_wins(16384, 128) is True   # aligned: default guess
        assert tuning.pallas_wins(16384, 40) is False   # padded: needs proof

        table = self._table([
            {"seq": 16384, "head_dim": 40, "block_q": 512, "block_k": 256,
             "pallas_ms": 100.0, "xla_ms": 180.0},      # padded kernel wins
            {"seq": 4096, "head_dim": 64, "block_q": 256, "block_k": 256,
             "pallas_ms": 9.0, "xla_ms": 4.0},          # padded kernel loses
            {"seq": 4608, "block_q": 256, "block_k": 256,
             "pallas_ms": 1.0, "xla_ms": 2.0},          # aligned (no dim tag)
        ])
        monkeypatch.setattr(
            tuning, "kernel_tuning", lambda: {**tuning._DEFAULT, **table}
        )
        assert tuning.pallas_wins(16384, 40) is True
        assert tuning.pallas_wins(4096, 64) is False
        # Aligned queries must not be judged by padded-dim entries.
        assert tuning.pallas_wins(4608, 128) is True
        # Same-dim measurements drive block choice for that class.
        assert tuning.best_blocks(16384, 40) == (512, 256)
        assert tuning.best_blocks(4608, 128) == (256, 256)
        # A padded-dim win extrapolates at most 2x in seq: the 16k dim-40 win
        # must NOT route a 256-token dim-40 attention (never measured against
        # the cheap plain-XLA competitor there) through the padded kernel.
        assert tuning.pallas_wins(256, 40) is False
        assert tuning.pallas_wins(8192, 40) is True  # within 2x of 16384

    def test_padded_dim_blocks_never_inherit_aligned_winners(self, monkeypatch):
        # ADVICE r3: best_blocks for a padded dim with NO same-dim entry must
        # return the defaults, mirroring pallas_wins' filtering — under a
        # forced pallas backend the kernel would otherwise run blocks tuned
        # for the wrong dim class.
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        table = self._table([
            {"seq": 4608, "head_dim": 128, "block_q": 512, "block_k": 512,
             "pallas_ms": 1.0, "xla_ms": 2.0},
        ])
        monkeypatch.setattr(
            tuning, "kernel_tuning", lambda: {**tuning._DEFAULT, **table}
        )
        assert tuning.best_blocks(4608, head_dim=40) == (256, 256)
        assert tuning.best_blocks(4608, head_dim=128) == (512, 512)

    def test_fused_backend_picks_measured_winner(self, monkeypatch):
        # Two fused candidates (in-repo kernel vs jax's upstream one): auto
        # routes to whichever measured faster; padded dims always take the
        # in-repo kernel (upstream has no lane padding); a shape where ONLY
        # the upstream kernel produced a number (round-3's hang scenario)
        # still counts as a fused win over XLA.
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        table = self._table([
            {"seq": 4608, "head_dim": 128, "block_q": 256, "block_k": 256,
             "pallas_ms": None, "pallas_jax_ms": 3.0, "xla_ms": 9.0},
            {"seq": 16384, "head_dim": 128, "block_q": 256, "block_k": 256,
             "pallas_ms": 2.0, "pallas_jax_ms": 4.0, "xla_ms": 9.0},
        ])
        monkeypatch.setattr(
            tuning, "kernel_tuning", lambda: {**tuning._DEFAULT, **table}
        )
        assert tuning.fused_backend(4608, 128) == "pallas_jax"
        assert tuning.fused_backend(16384, 128) == "pallas"
        assert tuning.fused_backend(4608, 40) == "pallas"  # padded dim
        assert tuning.pallas_wins(4608, 128) is True  # jax-kernel-only entry
        # No measurements at all: default to the in-repo kernel.
        monkeypatch.setattr(tuning, "kernel_tuning", lambda: dict(tuning._DEFAULT))
        assert tuning.fused_backend(4608, 128) == "pallas"

    def test_aligned_blocks_ignore_padded_dim_entries(self, monkeypatch):
        # A partial sweep can leave ONLY padded-dim entries (per-shape
        # subprocess timeouts); aligned dims must then fall back to defaults,
        # not adopt blocks tuned under the padded-FLOP regime.
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        table = self._table([
            {"seq": 16384, "head_dim": 40, "block_q": 512, "block_k": 512,
             "pallas_ms": 100.0, "xla_ms": 180.0},
        ])
        monkeypatch.setattr(
            tuning, "kernel_tuning", lambda: {**tuning._DEFAULT, **table}
        )
        assert tuning.best_blocks(4608, 128) == (256, 256)  # defaults
        assert tuning.pallas_wins(4608, 128) is True        # default guess

    def test_auto_backend_respects_measured_loss(self, monkeypatch):
        # Auto mode must fall back to XLA for lengths where measurement says
        # the fused kernel loses — even on TPU with aligned shapes.
        import importlib

        # ops/__init__ rebinds the name `attention` to the function, shadowing
        # the submodule on attribute access — resolve the module explicitly.
        att = importlib.import_module("comfyui_parallelanything_tpu.ops.attention")
        from comfyui_parallelanything_tpu.ops.pallas import tuning

        calls = []
        monkeypatch.setattr(att, "_pallas_available", lambda: True)
        monkeypatch.setattr(
            tuning, "kernel_tuning",
            lambda: {**tuning._DEFAULT, "entries": [
                {"seq": 128, "block_q": 128, "block_k": 128,
                 "pallas_ms": 9.0, "xla_ms": 1.0},
            ]},
        )
        fa = importlib.import_module(
            "comfyui_parallelanything_tpu.ops.pallas.flash_attention"
        )
        real = fa.flash_attention
        monkeypatch.setattr(
            fa, "flash_attention",
            lambda *a, **kw: calls.append(kw) or real(*a, interpret=True, **kw),
        )
        q = jnp.ones((1, 128, 2, 128), jnp.float32)
        out = att.attention_local(q, q, q)
        assert out.shape == q.shape
        assert calls == []  # measured loss -> xla path, kernel never invoked


class TestFlashAttention:
    @pytest.mark.parametrize("sq,sk", [(64, 64), (100, 80), (256, 256), (300, 513)])
    def test_matches_xla(self, sq, sk):
        q, k, v = _qkv(b=1, sq=sq, sk=sk, h=2, d=32)
        got = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
        want = _xla_attention(q, k, v, scale=32**-0.5)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_cross_attention_shape(self):
        q, k, v = _qkv(b=2, sq=32, sk=77, h=4, d=16)
        got = flash_attention(q, k, v, interpret=True)
        assert got.shape == (2, 32, 4, 16)

    def test_lane_padding_exact_at_unet_head_dim(self):
        # 40-dim SD1.5 heads run the kernel zero-padded to 128 lanes; padding
        # is EXACT (padded K dims add zero to every logit, padded V columns
        # emit discarded zeros), so the result must match plain attention at
        # the original dim — the property that makes padded routing safe.
        q, k, v = _qkv(b=2, sq=128, sk=128, h=2, d=40)
        got = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
        assert got.shape == (2, 128, 2, 40)
        want = _xla_attention(q, k, v, scale=40**-0.5)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_long_sequence_many_k_blocks(self):
        # Video-length regime (scaled for interpreter mode): the k-block grid
        # dim walks 16 tiles; online-softmax state must stay exact across all
        # of them. On real TPU this shape runs with VMEM at O(block), not O(S).
        q, k, v = _qkv(b=1, sq=256, sk=4096, h=1, d=32)
        got = flash_attention(q, k, v, block_q=256, block_k=256, interpret=True)
        want = _xla_attention(q, k, v, scale=32**-0.5)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_flash_under_sequence_parallel_ulysses(self, cpu_devices):
        # The composition the WAN long-context path uses on TPU: Ulysses
        # all_to_all head scatter inside shard_map, flash kernel as the local
        # attention. Forcing the pallas backend (interpret on CPU) proves the
        # kernel traces and runs inside the shard_map body.
        from comfyui_parallelanything_tpu.ops.attention import (
            get_attention_backend,
            set_attention_backend,
        )
        from comfyui_parallelanything_tpu.parallel.mesh import AXIS_SEQ, build_mesh
        from comfyui_parallelanything_tpu.parallel.sequence import (
            sequence_parallel_attention,
        )

        mesh = build_mesh(cpu_devices[:4], {AXIS_SEQ: 4})
        rng = np.random.default_rng(19)
        q = jnp.asarray(rng.normal(size=(1, 64, 4, 32)), jnp.float32)
        kv = jnp.asarray(rng.normal(size=(1, 64, 4, 32)), jnp.float32)
        want = _xla_attention(q, kv, kv, scale=32**-0.5)
        prev = get_attention_backend()
        set_attention_backend("pallas")
        try:
            got = sequence_parallel_attention(q, kv, kv, mesh, method="ulysses")
        finally:
            set_attention_backend(prev)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_streamed_kv_block_invariance(self):
        # The k-block grid dimension streams K/V through VMEM; the result must be
        # independent of how the key sequence is tiled (VMEM stays O(block_k) even
        # at video lengths — the whole point of the streamed layout).
        q, k, v = _qkv(b=1, sq=128, sk=1000, h=1, d=32)
        fine = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
        coarse = flash_attention(q, k, v, block_q=128, block_k=512, interpret=True)
        want = _xla_attention(q, k, v, scale=32**-0.5)
        np.testing.assert_allclose(np.asarray(fine), np.asarray(want), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(coarse), np.asarray(want), rtol=2e-4, atol=2e-4)

    def test_bf16(self):
        q, k, v = _qkv(b=1, sq=64, sk=64, h=1, d=32)
        q, k, v = (a.astype(jnp.bfloat16) for a in (q, k, v))
        got = flash_attention(q, k, v, interpret=True)
        assert got.dtype == jnp.bfloat16
        want = _xla_attention(q, k, v, scale=32**-0.5)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=5e-2, atol=5e-2
        )
