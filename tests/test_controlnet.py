"""ControlNet: zero-init no-op, strength/percent gating, checkpoint
round-trip (inverse-synthesis, the test_convert_unet.py strategy), stock-shim
workflow, and parallelized composition on the virtual mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models import (
    apply_control,
    build_controlnet,
    build_unet,
    load_controlnet_checkpoint,
    sd15_config,
)
from comfyui_parallelanything_tpu.models.api import DiffusionModel
from comfyui_parallelanything_tpu.models.convert_unet import (
    convert_controlnet_checkpoint,
)
from tests.test_convert_unet import (
    _inv_conv,
    _inv_dense,
    _inv_res,
    _inv_transformer,
)


def _tiny_cfg():
    return sd15_config(
        model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
        context_dim=64, norm_groups=8, dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def tiny_pair():
    cfg = _tiny_cfg()
    base = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
    cn = build_controlnet(cfg, jax.random.key(1), sample_shape=(1, 8, 8, 4))
    return cfg, base, cn


def _randomized_cn(cn, cfg):
    """Zero convs initialize to zero (no-op by design); randomize them so the
    control path actually contributes."""
    params = dict(cn.params)
    k = jax.random.key(7)
    for name in list(params):
        if name.startswith("zero_conv") or name == "mid_out":
            k, sub = jax.random.split(k)
            params[name] = jax.tree.map(
                lambda a: jax.random.normal(sub, a.shape, a.dtype) * 0.1,
                params[name],
            )
    return DiffusionModel(apply=cn.apply, params=params, name="cn-rand",
                          config=cfg)


def _ldm_controlnet_sd(cfg, params) -> dict:
    """Inverse-synthesize an ldm-layout ControlNet state dict from our param
    tree (mirrors convert_controlnet_checkpoint)."""
    sd: dict = {}
    _inv_dense(params["time_embed_0"], "time_embed.0", sd)
    _inv_dense(params["time_embed_2"], "time_embed.2", sd)
    if cfg.adm_in_channels is not None:
        _inv_dense(params["label_embed_0"], "label_emb.0.0", sd)
        _inv_dense(params["label_embed_2"], "label_emb.0.2", sd)
    _inv_conv(params["input_conv"], "input_blocks.0.0", sd)

    def attn_at(level):
        return level in cfg.attention_levels and cfg.transformer_depth[level] > 0

    idx = 1
    for level in range(len(cfg.channel_mult)):
        for i in range(cfg.num_res_blocks):
            _inv_res(params[f"in_{level}_{i}_res"], f"input_blocks.{idx}.0", sd)
            if attn_at(level):
                _inv_transformer(
                    params[f"in_{level}_{i}_attn"], f"input_blocks.{idx}.1",
                    cfg.transformer_depth[level], sd,
                )
            idx += 1
        if level != len(cfg.channel_mult) - 1:
            _inv_conv(params[f"down_{level}"]["Conv_0"],
                      f"input_blocks.{idx}.0.op", sd)
            idx += 1
    _inv_res(params["mid_res1"], "middle_block.0", sd)
    if attn_at(len(cfg.channel_mult) - 1):
        _inv_transformer(params["mid_attn"], "middle_block.1",
                         cfg.transformer_depth[-1], sd)
        _inv_res(params["mid_res2"], "middle_block.2", sd)
    else:
        _inv_res(params["mid_res2"], "middle_block.1", sd)

    for i in range(8):
        _inv_conv(params[f"hint_{i}"], f"input_hint_block.{2 * i}", sd)
    n_zero = 1 + sum(
        cfg.num_res_blocks + (1 if lv != len(cfg.channel_mult) - 1 else 0)
        for lv in range(len(cfg.channel_mult))
    )
    for k in range(n_zero):
        _inv_conv(params[f"zero_conv_{k}"], f"zero_convs.{k}.0", sd)
    _inv_conv(params["mid_out"], "middle_block_out.0", sd)
    return sd


class TestControlSemantics:
    def test_zero_init_is_exact_noop(self, tiny_pair):
        cfg, base, cn = tiny_pair
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        out = apply_control(base, cn, hint, strength=1.0)(x, t, ctx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(base(x, t, ctx)), rtol=1e-6, atol=1e-6
        )

    def test_control_changes_output_and_strength_scales(self, tiny_pair):
        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        ref = np.asarray(base(x, t, ctx))
        on = np.asarray(apply_control(base, cn2, hint, 1.0)(x, t, ctx))
        off = np.asarray(apply_control(base, cn2, hint, 0.0)(x, t, ctx))
        assert not np.allclose(on, ref, atol=1e-4)
        np.testing.assert_allclose(off, ref, rtol=1e-6, atol=1e-6)

    def test_percent_window_gates_by_timestep(self, tiny_pair):
        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        composed = apply_control(base, cn2, hint, 1.0,
                                 start_percent=0.0, end_percent=0.5)
        # Early sampling (t≈999, progress≈0): inside the window → control on.
        t_early = jnp.array([990.0, 990.0])
        assert not np.allclose(
            np.asarray(composed(x, t_early, ctx)),
            np.asarray(base(x, t_early, ctx)), atol=1e-4,
        )
        # Late sampling (t≈0, progress≈1): outside → exact no-op.
        t_late = jnp.array([5.0, 5.0])
        np.testing.assert_allclose(
            np.asarray(composed(x, t_late, ctx)),
            np.asarray(base(x, t_late, ctx)), rtol=1e-6, atol=1e-6,
        )

    def test_module_validates_hint_grid(self, tiny_pair):
        # The raw module insists on the exact 8x grid (its contract)...
        cfg, base, cn = tiny_pair
        with pytest.raises(ValueError, match="8x the latent grid"):
            cn.apply(cn.params, jnp.zeros((1, 8, 8, 4)), jnp.zeros((1,)),
                     jnp.zeros((1, 5, 64)), hint=jnp.zeros((1, 32, 32, 3)))

    def test_apply_control_auto_resizes_hint(self, tiny_pair):
        # ...but apply_control resizes a mismatched hint to the generation
        # size first (stock common_upscale behavior): a 32px hint on an 8x8
        # latent (needs 64px) must equal pre-resizing it by hand.
        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        small = jax.random.uniform(jax.random.key(6), (1, 32, 32, 3))
        pre = jax.image.resize(small, (1, 64, 64, 3), method="bilinear")
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        np.testing.assert_allclose(
            np.asarray(apply_control(base, cn2, small, 1.0)(x, t, ctx)),
            np.asarray(apply_control(base, cn2, pre, 1.0)(x, t, ctx)),
            rtol=1e-6, atol=1e-6,
        )

    def test_per_sample_hints_rejected(self, tiny_pair):
        # Per-sample hint batches cannot survive DP splitting (the hint rides
        # the replicated params) — loud error, not silent repetition.
        cfg, base, cn = tiny_pair
        hints = jnp.zeros((2, 64, 64, 3))
        composed = apply_control(base, cn, hints)
        with pytest.raises(ValueError, match="ONE hint image"):
            composed.apply(
                composed.params, jnp.zeros((4, 8, 8, 4)),
                jnp.zeros((4,)), jnp.zeros((4, 5, 64)),
            )

    def test_stacked_controlnets_sum(self, tiny_pair):
        # Chained compositions accumulate residuals; a zero-strength outer
        # net is exactly the inner composition.
        cfg, base, cn = tiny_pair
        cn_a = _randomized_cn(cn, cfg)
        cn_b = build_controlnet(cfg, jax.random.key(11),
                                sample_shape=(1, 8, 8, 4))
        cn_b = _randomized_cn(cn_b, cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        only_a = apply_control(base, cn_a, hint, 1.0)
        both = apply_control(only_a, cn_b, hint, 1.0)
        both_off = apply_control(only_a, cn_b, hint, 0.0)
        np.testing.assert_allclose(
            np.asarray(both_off(x, t, ctx)), np.asarray(only_a(x, t, ctx)),
            rtol=1e-6, atol=1e-6,
        )
        assert not np.allclose(
            np.asarray(both(x, t, ctx)), np.asarray(only_a(x, t, ctx)),
            atol=1e-4,
        )


class TestControlNetConversion:
    def test_round_trip_and_forward_equivalence(self, tiny_pair, tmp_path):
        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        sd = _ldm_controlnet_sd(cfg, cn2.params)
        got = convert_controlnet_checkpoint(sd, cfg)
        fg, fw = dict(flatten_tree(got)), dict(flatten_tree(cn2.params))
        assert sorted(fg) == sorted(fw)
        for k in fw:
            np.testing.assert_allclose(fg[k], fw[k], rtol=1e-6, atol=1e-6,
                                       err_msg=str(k))

        # load_controlnet_checkpoint end to end, family sniffed (ctx 64 ≠ any
        # public width → sd15 default params? no: pass cfg since the tiny cfg
        # is not sniffable).
        from safetensors.numpy import save_file

        path = tmp_path / "cn.safetensors"
        save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
                  str(path))
        loaded = load_controlnet_checkpoint(str(path), cfg=cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (1, 8, 8, 4))
        t = jnp.array([300.0])
        ctx = jax.random.normal(jax.random.key(4), (1, 5, 64))
        want = apply_control(base, cn2, hint, 1.0)(x, t, ctx)
        got_out = apply_control(base, loaded, hint, 1.0)(x, t, ctx)
        np.testing.assert_allclose(np.asarray(got_out), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestSDXLControlNet:
    def test_adm_round_trip_and_forward(self):
        # SDXL-style controlnets carry the label_emb vector-conditioning path
        # (the sniffing loader keys off it); round-trip + forward equivalence
        # with y wired through.
        from comfyui_parallelanything_tpu.models.unet import UNetConfig

        cfg = UNetConfig(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, adm_in_channels=32, norm_groups=8,
            dtype=jnp.float32,
        )
        base = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        cn = build_controlnet(cfg, jax.random.key(1), sample_shape=(1, 8, 8, 4))
        cn = _randomized_cn(cn, cfg)
        sd = _ldm_controlnet_sd(cfg, cn.params)
        got = convert_controlnet_checkpoint(sd, cfg)
        fg, fw = dict(flatten_tree(got)), dict(flatten_tree(cn.params))
        assert sorted(fg) == sorted(fw)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (1, 8, 8, 4))
        t = jnp.array([300.0])
        ctx = jax.random.normal(jax.random.key(4), (1, 5, 64))
        y = jax.random.normal(jax.random.key(5), (1, 32))
        composed = apply_control(base, cn, hint, 1.0)
        out = composed(x, t, ctx, y=y)
        ref = base(x, t, ctx, y=y)
        assert out.shape == ref.shape
        assert not np.allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


class TestControlParallel:
    def test_composed_model_parallelizes(self, tiny_pair, cpu_devices):
        # The merged pytree (base + control + hint) places through parallelize
        # and the DP result matches the single-device composition.
        import comfyui_parallelanything_tpu as pa

        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        composed = apply_control(base, cn2, hint, 1.0)
        pm = pa.parallelize(
            composed, pa.DeviceChain.even([f"cpu:{i}" for i in range(8)])
        )
        x = jax.random.normal(jax.random.key(3), (8, 8, 8, 4))
        t = jnp.linspace(900.0, 100.0, 8)
        ctx = jax.random.normal(jax.random.key(4), (8, 5, 64))
        want = composed(x, t, ctx)
        got = pm(x, t, ctx)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


class TestControlPlumbing:
    def test_collect_control_reaches_combined_extras(self):
        # A ControlNetApply tag on the SECOND input of ConditioningCombine
        # rides the extras tuple — it must still compose (order-independent).
        from comfyui_parallelanything_tpu.nodes import _collect_control

        spec_a, spec_b = {"model": "A"}, {"model": "B"}
        positive = {
            "context": None,
            "control": (spec_a,),
            "extras": ({"context": None, "control": (spec_b,)},
                       {"context": None}),
        }
        assert _collect_control(positive) == (spec_a, spec_b)
        assert _collect_control({"context": None}) == ()

    def test_composition_cached_across_calls(self, tiny_pair):
        # Same specs → the SAME composed model object (placement + compiled
        # programs reused across prompts); changed strength → a fresh one.
        from comfyui_parallelanything_tpu.nodes import _model_with_control

        cfg, base, cn = tiny_pair
        hint = jnp.zeros((1, 64, 64, 3))
        spec = {"model": cn, "hint": hint, "strength": 1.0}
        m1 = _model_with_control(base, (spec,))
        m2 = _model_with_control(base, (spec,))
        assert m1 is m2
        m3 = _model_with_control(base, ({**spec, "strength": 0.5},))
        assert m3 is not m1


class TestControlWorkflow:
    def test_stock_controlnet_workflow_runs(self, tmp_path, monkeypatch):
        # Exported-style graph: ControlNetLoader → ControlNetApplyAdvanced
        # between the text encode and the KSampler; LoadImage supplies the
        # hint at pixel res.
        from PIL import Image

        from comfyui_parallelanything_tpu.host import run_workflow
        from tests.test_stock_nodes import (
            _synthetic_stock_env,
        )

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))

        # Tiny controlnet checkpoint for the tiny sd15 config (the env's
        # monkeypatched sd15_config), under models/controlnet/.
        import comfyui_parallelanything_tpu.models as models_pkg
        from safetensors.numpy import save_file

        cfg = models_pkg.sd15_config()
        cn = build_controlnet(cfg, jax.random.key(5), sample_shape=(1, 4, 4, 4))
        cn_dir = tmp_path / "models" / "controlnet"
        cn_dir.mkdir(parents=True)
        sd = _ldm_controlnet_sd(cfg, _randomized_cn(cn, cfg).params)
        save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
                  str(cn_dir / "tiny_cn.safetensors"))
        monkeypatch.setenv("PA_MODELS_DIR", str(tmp_path / "models"))

        # Hint image at the pixel resolution of the 32px workflow.
        in_dir = tmp_path / "input"
        in_dir.mkdir()
        Image.fromarray(
            (np.random.default_rng(0).uniform(size=(32, 32, 3)) * 255)
            .astype(np.uint8)
        ).save(in_dir / "hint.png")
        monkeypatch.setenv("PA_INPUT_DIR", str(in_dir))

        wf = {
            "4": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": paths["ckpt"]}},
            "5": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "a watercolor lighthouse",
                             "clip": ["4", 1]}},
            "7": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "blurry", "clip": ["4", 1]}},
            "10": {"class_type": "LoadImage", "inputs": {"image": "hint.png"}},
            "11": {"class_type": "ControlNetLoader",
                   "inputs": {"control_net_name": "tiny_cn.safetensors"}},
            "12": {"class_type": "ControlNetApplyAdvanced",
                   "inputs": {"positive": ["6", 0], "negative": ["7", 0],
                              "control_net": ["11", 0], "image": ["10", 0],
                              "strength": 0.8, "start_percent": 0.0,
                              "end_percent": 1.0}},
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": 7, "steps": 2, "cfg": 5.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0, "model": ["4", 0],
                             "positive": ["12", 0], "negative": ["12", 1],
                             "latent_image": ["5", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["4", 2]}},
        }
        out = run_workflow(wf)
        images = np.asarray(out["8"][0])
        assert images.shape[0] == 1 and np.isfinite(images).all()
        # The control actually steered the sample: rerun without ControlNet.
        wf_plain = {k: v for k, v in wf.items() if k not in ("10", "11", "12")}
        wf_plain["3"] = {**wf["3"], "inputs": {**wf["3"]["inputs"],
                                               "positive": ["6", 0],
                                               "negative": ["7", 0]}}
        plain = np.asarray(run_workflow(wf_plain)["8"][0])
        assert not np.allclose(images, plain, atol=1e-4)
