"""ControlNet: zero-init no-op, strength/percent gating, checkpoint
round-trip (inverse-synthesis, the test_convert_unet.py strategy), stock-shim
workflow, and parallelized composition on the virtual mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models import (
    apply_control,
    build_controlnet,
    build_unet,
    load_controlnet_checkpoint,
    sd15_config,
)
from comfyui_parallelanything_tpu.models.api import DiffusionModel
from comfyui_parallelanything_tpu.models.convert_unet import (
    convert_controlnet_checkpoint,
)
from tests.test_convert_unet import (
    _inv_conv,
    _inv_dense,
    _inv_res,
    _inv_transformer,
)


def _tiny_cfg():
    return sd15_config(
        model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
        context_dim=64, norm_groups=8, dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def tiny_pair():
    cfg = _tiny_cfg()
    base = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
    cn = build_controlnet(cfg, jax.random.key(1), sample_shape=(1, 8, 8, 4))
    return cfg, base, cn


def _randomized_cn(cn, cfg):
    """Zero convs initialize to zero (no-op by design); randomize them so the
    control path actually contributes."""
    params = dict(cn.params)
    k = jax.random.key(7)
    for name in list(params):
        if name.startswith("zero_conv") or name == "mid_out":
            k, sub = jax.random.split(k)
            params[name] = jax.tree.map(
                lambda a: jax.random.normal(sub, a.shape, a.dtype) * 0.1,
                params[name],
            )
    return DiffusionModel(apply=cn.apply, params=params, name="cn-rand",
                          config=cfg)


def _ldm_controlnet_sd(cfg, params) -> dict:
    """Inverse-synthesize an ldm-layout ControlNet state dict from our param
    tree (mirrors convert_controlnet_checkpoint)."""
    sd: dict = {}
    _inv_dense(params["time_embed_0"], "time_embed.0", sd)
    _inv_dense(params["time_embed_2"], "time_embed.2", sd)
    if cfg.adm_in_channels is not None:
        _inv_dense(params["label_embed_0"], "label_emb.0.0", sd)
        _inv_dense(params["label_embed_2"], "label_emb.0.2", sd)
    _inv_conv(params["input_conv"], "input_blocks.0.0", sd)

    def attn_at(level):
        return level in cfg.attention_levels and cfg.transformer_depth[level] > 0

    idx = 1
    for level in range(len(cfg.channel_mult)):
        for i in range(cfg.num_res_blocks):
            _inv_res(params[f"in_{level}_{i}_res"], f"input_blocks.{idx}.0", sd)
            if attn_at(level):
                _inv_transformer(
                    params[f"in_{level}_{i}_attn"], f"input_blocks.{idx}.1",
                    cfg.transformer_depth[level], sd,
                )
            idx += 1
        if level != len(cfg.channel_mult) - 1:
            _inv_conv(params[f"down_{level}"]["Conv_0"],
                      f"input_blocks.{idx}.0.op", sd)
            idx += 1
    _inv_res(params["mid_res1"], "middle_block.0", sd)
    if attn_at(len(cfg.channel_mult) - 1):
        _inv_transformer(params["mid_attn"], "middle_block.1",
                         cfg.transformer_depth[-1], sd)
        _inv_res(params["mid_res2"], "middle_block.2", sd)
    else:
        _inv_res(params["mid_res2"], "middle_block.1", sd)

    for i in range(8):
        _inv_conv(params[f"hint_{i}"], f"input_hint_block.{2 * i}", sd)
    n_zero = 1 + sum(
        cfg.num_res_blocks + (1 if lv != len(cfg.channel_mult) - 1 else 0)
        for lv in range(len(cfg.channel_mult))
    )
    for k in range(n_zero):
        _inv_conv(params[f"zero_conv_{k}"], f"zero_convs.{k}.0", sd)
    _inv_conv(params["mid_out"], "middle_block_out.0", sd)
    return sd


class TestControlSemantics:
    def test_zero_init_is_exact_noop(self, tiny_pair):
        cfg, base, cn = tiny_pair
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        out = apply_control(base, cn, hint, strength=1.0)(x, t, ctx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(base(x, t, ctx)), rtol=1e-6, atol=1e-6
        )

    def test_control_changes_output_and_strength_scales(self, tiny_pair):
        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        ref = np.asarray(base(x, t, ctx))
        on = np.asarray(apply_control(base, cn2, hint, 1.0)(x, t, ctx))
        off = np.asarray(apply_control(base, cn2, hint, 0.0)(x, t, ctx))
        assert not np.allclose(on, ref, atol=1e-4)
        np.testing.assert_allclose(off, ref, rtol=1e-6, atol=1e-6)

    def test_percent_window_gates_by_timestep(self, tiny_pair):
        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        composed = apply_control(base, cn2, hint, 1.0,
                                 start_percent=0.0, end_percent=0.5)
        # Early sampling (t≈999, progress≈0): inside the window → control on.
        t_early = jnp.array([990.0, 990.0])
        assert not np.allclose(
            np.asarray(composed(x, t_early, ctx)),
            np.asarray(base(x, t_early, ctx)), atol=1e-4,
        )
        # Late sampling (t≈0, progress≈1): outside → exact no-op.
        t_late = jnp.array([5.0, 5.0])
        np.testing.assert_allclose(
            np.asarray(composed(x, t_late, ctx)),
            np.asarray(base(x, t_late, ctx)), rtol=1e-6, atol=1e-6,
        )

    def test_module_validates_hint_grid(self, tiny_pair):
        # The raw module insists on the exact 8x grid (its contract)...
        cfg, base, cn = tiny_pair
        with pytest.raises(ValueError, match="8x the latent grid"):
            cn.apply(cn.params, jnp.zeros((1, 8, 8, 4)), jnp.zeros((1,)),
                     jnp.zeros((1, 5, 64)), hint=jnp.zeros((1, 32, 32, 3)))

    def test_apply_control_auto_resizes_hint(self, tiny_pair):
        # ...but apply_control resizes a mismatched hint to the generation
        # size first (stock common_upscale behavior): a 32px hint on an 8x8
        # latent (needs 64px) must equal pre-resizing it by hand.
        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        small = jax.random.uniform(jax.random.key(6), (1, 32, 32, 3))
        pre = jax.image.resize(small, (1, 64, 64, 3), method="bilinear")
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        np.testing.assert_allclose(
            np.asarray(apply_control(base, cn2, small, 1.0)(x, t, ctx)),
            np.asarray(apply_control(base, cn2, pre, 1.0)(x, t, ctx)),
            rtol=1e-6, atol=1e-6,
        )

    def test_per_sample_hints_rejected(self, tiny_pair):
        # Per-sample hint batches cannot survive DP splitting (the hint rides
        # the replicated params) — loud error, not silent repetition.
        cfg, base, cn = tiny_pair
        hints = jnp.zeros((2, 64, 64, 3))
        composed = apply_control(base, cn, hints)
        with pytest.raises(ValueError, match="ONE hint image"):
            composed.apply(
                composed.params, jnp.zeros((4, 8, 8, 4)),
                jnp.zeros((4,)), jnp.zeros((4, 5, 64)),
            )

    def test_stacked_controlnets_sum(self, tiny_pair):
        # Chained compositions accumulate residuals; a zero-strength outer
        # net is exactly the inner composition.
        cfg, base, cn = tiny_pair
        cn_a = _randomized_cn(cn, cfg)
        cn_b = build_controlnet(cfg, jax.random.key(11),
                                sample_shape=(1, 8, 8, 4))
        cn_b = _randomized_cn(cn_b, cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (2, 8, 8, 4))
        t = jnp.array([500.0, 100.0])
        ctx = jax.random.normal(jax.random.key(4), (2, 5, 64))
        only_a = apply_control(base, cn_a, hint, 1.0)
        both = apply_control(only_a, cn_b, hint, 1.0)
        both_off = apply_control(only_a, cn_b, hint, 0.0)
        np.testing.assert_allclose(
            np.asarray(both_off(x, t, ctx)), np.asarray(only_a(x, t, ctx)),
            rtol=1e-6, atol=1e-6,
        )
        assert not np.allclose(
            np.asarray(both(x, t, ctx)), np.asarray(only_a(x, t, ctx)),
            atol=1e-4,
        )


class TestControlNetConversion:
    def test_round_trip_and_forward_equivalence(self, tiny_pair, tmp_path):
        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        sd = _ldm_controlnet_sd(cfg, cn2.params)
        got = convert_controlnet_checkpoint(sd, cfg)
        fg, fw = dict(flatten_tree(got)), dict(flatten_tree(cn2.params))
        assert sorted(fg) == sorted(fw)
        for k in fw:
            np.testing.assert_allclose(fg[k], fw[k], rtol=1e-6, atol=1e-6,
                                       err_msg=str(k))

        # load_controlnet_checkpoint end to end, family sniffed (ctx 64 ≠ any
        # public width → sd15 default params? no: pass cfg since the tiny cfg
        # is not sniffable).
        from safetensors.numpy import save_file

        path = tmp_path / "cn.safetensors"
        save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
                  str(path))
        loaded = load_controlnet_checkpoint(str(path), cfg=cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (1, 8, 8, 4))
        t = jnp.array([300.0])
        ctx = jax.random.normal(jax.random.key(4), (1, 5, 64))
        want = apply_control(base, cn2, hint, 1.0)(x, t, ctx)
        got_out = apply_control(base, loaded, hint, 1.0)(x, t, ctx)
        np.testing.assert_allclose(np.asarray(got_out), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestSDXLControlNet:
    def test_adm_round_trip_and_forward(self):
        # SDXL-style controlnets carry the label_emb vector-conditioning path
        # (the sniffing loader keys off it); round-trip + forward equivalence
        # with y wired through.
        from comfyui_parallelanything_tpu.models.unet import UNetConfig

        cfg = UNetConfig(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, adm_in_channels=32, norm_groups=8,
            dtype=jnp.float32,
        )
        base = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        cn = build_controlnet(cfg, jax.random.key(1), sample_shape=(1, 8, 8, 4))
        cn = _randomized_cn(cn, cfg)
        sd = _ldm_controlnet_sd(cfg, cn.params)
        got = convert_controlnet_checkpoint(sd, cfg)
        fg, fw = dict(flatten_tree(got)), dict(flatten_tree(cn.params))
        assert sorted(fg) == sorted(fw)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (1, 8, 8, 4))
        t = jnp.array([300.0])
        ctx = jax.random.normal(jax.random.key(4), (1, 5, 64))
        y = jax.random.normal(jax.random.key(5), (1, 32))
        composed = apply_control(base, cn, hint, 1.0)
        out = composed(x, t, ctx, y=y)
        ref = base(x, t, ctx, y=y)
        assert out.shape == ref.shape
        assert not np.allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def _diffusers_from_ldm(cfg, sd):
    """Rename an ldm-layout ControlNet dict into the diffusers
    ``ControlNetModel`` layout (hand-written inverse of
    ``diffusers_controlnet_to_ldm`` so the test checks the remap against an
    independently-derived mapping, not against itself)."""
    inv_res = {"in_layers.0": "norm1", "in_layers.2": "conv1",
               "emb_layers.1": "time_emb_proj", "out_layers.0": "norm2",
               "out_layers.3": "conv2", "skip_connection": "conv_shortcut"}
    n_res = cfg.num_res_blocks
    mid_attn = (len(cfg.channel_mult) - 1 in cfg.attention_levels
                and cfg.transformer_depth[-1] > 0)
    out = {}
    for k, v in sd.items():
        parts = k.split(".")
        if parts[0] == "time_embed":
            nk = f"time_embedding.linear_{1 if parts[1] == '0' else 2}.{parts[-1]}"
        elif parts[0] == "label_emb":
            nk = f"add_embedding.linear_{1 if parts[2] == '0' else 2}.{parts[-1]}"
        elif parts[0] == "input_hint_block":
            i = int(parts[1]) // 2
            sub = ("conv_in" if i == 0 else
                   "conv_out" if i == 7 else f"blocks.{i - 1}")
            nk = f"controlnet_cond_embedding.{sub}.{parts[-1]}"
        elif parts[0] == "input_blocks":
            idx = int(parts[1])
            if idx == 0:
                nk = f"conv_in.{parts[-1]}"
            else:
                b, r = (idx - 1) // (n_res + 1), (idx - 1) % (n_res + 1)
                if parts[2] == "0" and parts[3] == "op":
                    nk = f"down_blocks.{b}.downsamplers.0.conv.{parts[-1]}"
                elif parts[2] == "0":
                    nk = (f"down_blocks.{b}.resnets.{r}."
                          f"{inv_res['.'.join(parts[3:-1])]}.{parts[-1]}")
                else:
                    nk = (f"down_blocks.{b}.attentions.{r}."
                          + ".".join(parts[3:]))
        elif parts[0] == "middle_block":
            pos = int(parts[1])
            if mid_attn and pos == 1:
                nk = "mid_block.attentions.0." + ".".join(parts[2:])
            elif parts[2] == "op":  # never happens in mid; keep explicit
                raise AssertionError(k)
            else:
                r = 0 if pos == 0 else 1
                nk = (f"mid_block.resnets.{r}."
                      f"{inv_res['.'.join(parts[2:-1])]}.{parts[-1]}")
        elif parts[0] == "zero_convs":
            nk = f"controlnet_down_blocks.{parts[1]}.{parts[-1]}"
        elif parts[0] == "middle_block_out":
            nk = f"controlnet_mid_block.{parts[-1]}"
        else:
            raise AssertionError(f"unmapped ldm key {k}")
        out[nk] = v
    return out


class TestDiffusersControlNet:
    """Diffusers ``ControlNetModel`` single-file layout — how most public SDXL
    controlnets ship. Stock ComfyUI remaps it inside its loader; here
    ``diffusers_controlnet_to_ldm`` + the ldm converter must land on the same
    params as the ldm path."""

    def test_remap_matches_ldm_path(self, tiny_pair):
        cfg, _, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        ldm = _ldm_controlnet_sd(cfg, cn2.params)
        from comfyui_parallelanything_tpu.models.convert_unet import (
            diffusers_controlnet_to_ldm,
        )

        remapped = diffusers_controlnet_to_ldm(_diffusers_from_ldm(cfg, ldm))
        assert sorted(remapped) == sorted(ldm)
        got = convert_controlnet_checkpoint(remapped, cfg)
        fg, fw = dict(flatten_tree(got)), dict(flatten_tree(cn2.params))
        assert sorted(fg) == sorted(fw)
        for k in fw:
            np.testing.assert_array_equal(fg[k], fw[k], err_msg=str(k))

    @staticmethod
    def _tiny_adm_cfg(monkeypatch):
        """Tiny label_emb-carrying config, patched in as the sniffed-SDXL
        target (the loader resolves ``sdxl_config`` through the models
        package namespace)."""
        import comfyui_parallelanything_tpu.models as models_pkg
        from comfyui_parallelanything_tpu.models.unet import UNetConfig

        cfg = UNetConfig(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, adm_in_channels=32, norm_groups=8,
            dtype=jnp.float32,
        )
        monkeypatch.setattr(models_pkg, "sdxl_config", lambda: cfg)
        return cfg

    def test_unrecognized_embedding_sublayer_raises(self, tiny_pair):
        # time_embedding.cond_proj (LCM-derived nets) must raise, not alias
        # onto linear_2's slot and silently corrupt the time embed.
        cfg, _, cn = tiny_pair
        from comfyui_parallelanything_tpu.models.convert_unet import (
            diffusers_controlnet_to_ldm,
        )

        sd = _diffusers_from_ldm(cfg, _ldm_controlnet_sd(cfg, cn.params))
        sd["time_embedding.cond_proj.weight"] = np.zeros((4, 4), np.float32)
        with pytest.raises(KeyError, match="unrecognized"):
            diffusers_controlnet_to_ldm(sd)

    def test_sdxl_diffusers_file_sniffs_and_runs(self, tmp_path, monkeypatch):
        # An SDXL-style (label_emb/add_embedding-carrying) diffusers-layout
        # file loads through the sniffing loader with no cfg, producing a
        # ControlNet whose composition with an adm base model samples.
        from safetensors.numpy import save_file

        cfg = self._tiny_adm_cfg(monkeypatch)
        base = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        cn = _randomized_cn(
            build_controlnet(cfg, jax.random.key(1), sample_shape=(1, 8, 8, 4)),
            cfg,
        )
        sd = _diffusers_from_ldm(cfg, _ldm_controlnet_sd(cfg, cn.params))
        assert any(k.startswith("add_embedding.") for k in sd)
        path = tmp_path / "sdxl_cn_diffusers.safetensors"
        save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
                  str(path))

        loaded = load_controlnet_checkpoint(str(path))  # cfg sniffed
        assert loaded.config.adm_in_channels == 32
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        x = jax.random.normal(jax.random.key(3), (1, 8, 8, 4))
        t = jnp.array([300.0])
        ctx = jax.random.normal(jax.random.key(4), (1, 5, 64))
        y = jax.random.normal(jax.random.key(5), (1, 32))
        want = apply_control(base, cn, hint, 1.0)(x, t, ctx, y=y)
        got = apply_control(base, loaded, hint, 1.0)(x, t, ctx, y=y)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_sdxl_ldm_file_sniffs(self, tmp_path, monkeypatch):
        # Same sniff through the ldm layout (label_emb.* keys), control_model.
        # prefix included — the other common SDXL controlnet export shape.
        from safetensors.numpy import save_file

        cfg = self._tiny_adm_cfg(monkeypatch)
        cn = build_controlnet(cfg, jax.random.key(1), sample_shape=(1, 8, 8, 4))
        sd = _ldm_controlnet_sd(cfg, cn.params)
        path = tmp_path / "sdxl_cn.safetensors"
        save_file({f"control_model.{k}": np.ascontiguousarray(v)
                   for k, v in sd.items()}, str(path))
        loaded = load_controlnet_checkpoint(str(path))
        assert loaded.config.adm_in_channels == 32


class TestSDXLComposedGraph:
    def test_sdxl_controlnet_graph_samples(self, tmp_path, monkeypatch):
        """A stock-export SDXL graph — single-file checkpoint (dual towers
        bundled), diffusers-layout SDXL ControlNet, ControlNetApplyAdvanced —
        samples end to end: adm vector (pooled + size embeds) flows through
        BOTH trunks of the composed jit program."""
        from PIL import Image

        import comfyui_parallelanything_tpu.models as models_pkg
        from comfyui_parallelanything_tpu.host import run_workflow
        from safetensors.numpy import save_file
        from tests.test_stock_nodes import _synthetic_sdxl_env

        env = _synthetic_sdxl_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))

        cfg = models_pkg.sdxl_config()  # the env's tiny factory
        cn = _randomized_cn(
            build_controlnet(cfg, jax.random.key(9), sample_shape=(1, 4, 4, 4)),
            cfg,
        )
        cn_dir = tmp_path / "models" / "controlnet"
        cn_dir.mkdir(parents=True)
        sd = _diffusers_from_ldm(cfg, _ldm_controlnet_sd(cfg, cn.params))
        save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
                  str(cn_dir / "tiny_xl_cn.safetensors"))
        monkeypatch.setenv("PA_MODELS_DIR", str(tmp_path / "models"))

        in_dir = tmp_path / "input"
        in_dir.mkdir()
        Image.fromarray(
            (np.random.default_rng(1).uniform(size=(32, 32, 3)) * 255)
            .astype(np.uint8)
        ).save(in_dir / "hint.png")
        monkeypatch.setenv("PA_INPUT_DIR", str(in_dir))

        wf = {
            "4": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": env["ckpt"]}},
            "5": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "a watercolor lighthouse",
                             "clip": ["4", 1]}},
            "7": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "blurry", "clip": ["4", 1]}},
            "10": {"class_type": "LoadImage", "inputs": {"image": "hint.png"}},
            "11": {"class_type": "ControlNetLoader",
                   "inputs": {"control_net_name": "tiny_xl_cn.safetensors"}},
            "12": {"class_type": "ControlNetApplyAdvanced",
                   "inputs": {"positive": ["6", 0], "negative": ["7", 0],
                              "control_net": ["11", 0], "image": ["10", 0],
                              "strength": 0.9, "start_percent": 0.0,
                              "end_percent": 1.0}},
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": 11, "steps": 2, "cfg": 5.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0, "model": ["4", 0],
                             "positive": ["12", 0], "negative": ["12", 1],
                             "latent_image": ["5", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["4", 2]}},
        }
        out = run_workflow(wf)
        images = np.asarray(out["8"][0])
        assert images.shape[0] == 1 and np.isfinite(images).all()
        # The control steered the sample.
        wf_plain = {k: v for k, v in wf.items() if k not in ("10", "11", "12")}
        wf_plain["3"] = {**wf["3"], "inputs": {**wf["3"]["inputs"],
                                               "positive": ["6", 0],
                                               "negative": ["7", 0]}}
        plain = np.asarray(run_workflow(wf_plain)["8"][0])
        assert not np.allclose(images, plain, atol=1e-4)


class TestControlParallel:
    def test_composed_model_parallelizes(self, tiny_pair, cpu_devices):
        # The merged pytree (base + control + hint) places through parallelize
        # and the DP result matches the single-device composition.
        import comfyui_parallelanything_tpu as pa

        cfg, base, cn = tiny_pair
        cn2 = _randomized_cn(cn, cfg)
        hint = jax.random.uniform(jax.random.key(2), (1, 64, 64, 3))
        composed = apply_control(base, cn2, hint, 1.0)
        pm = pa.parallelize(
            composed, pa.DeviceChain.even([f"cpu:{i}" for i in range(8)])
        )
        x = jax.random.normal(jax.random.key(3), (8, 8, 8, 4))
        t = jnp.linspace(900.0, 100.0, 8)
        ctx = jax.random.normal(jax.random.key(4), (8, 5, 64))
        want = composed(x, t, ctx)
        got = pm(x, t, ctx)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


class TestControlPlumbing:
    def test_collect_control_reaches_combined_extras(self):
        # A ControlNetApply tag on the SECOND input of ConditioningCombine
        # rides the extras tuple — it must still compose (order-independent).
        from comfyui_parallelanything_tpu.nodes import _collect_control

        spec_a, spec_b = {"model": "A"}, {"model": "B"}
        positive = {
            "context": None,
            "control": (spec_a,),
            "extras": ({"context": None, "control": (spec_b,)},
                       {"context": None}),
        }
        assert _collect_control(positive) == (spec_a, spec_b)
        assert _collect_control({"context": None}) == ()

    def test_composition_cached_across_calls(self, tiny_pair):
        # Same specs → the SAME composed model object (placement + compiled
        # programs reused across prompts); changed strength → a fresh one.
        from comfyui_parallelanything_tpu.nodes import _model_with_control

        cfg, base, cn = tiny_pair
        hint = jnp.zeros((1, 64, 64, 3))
        spec = {"model": cn, "hint": hint, "strength": 1.0}
        m1 = _model_with_control(base, (spec,))
        m2 = _model_with_control(base, (spec,))
        assert m1 is m2
        m3 = _model_with_control(base, ({**spec, "strength": 0.5},))
        assert m3 is not m1


class TestControlWorkflow:
    def test_stock_controlnet_workflow_runs(self, tmp_path, monkeypatch):
        # Exported-style graph: ControlNetLoader → ControlNetApplyAdvanced
        # between the text encode and the KSampler; LoadImage supplies the
        # hint at pixel res.
        from PIL import Image

        from comfyui_parallelanything_tpu.host import run_workflow
        from tests.test_stock_nodes import (
            _synthetic_stock_env,
        )

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))

        # Tiny controlnet checkpoint for the tiny sd15 config (the env's
        # monkeypatched sd15_config), under models/controlnet/.
        import comfyui_parallelanything_tpu.models as models_pkg
        from safetensors.numpy import save_file

        cfg = models_pkg.sd15_config()
        cn = build_controlnet(cfg, jax.random.key(5), sample_shape=(1, 4, 4, 4))
        cn_dir = tmp_path / "models" / "controlnet"
        cn_dir.mkdir(parents=True)
        sd = _ldm_controlnet_sd(cfg, _randomized_cn(cn, cfg).params)
        save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
                  str(cn_dir / "tiny_cn.safetensors"))
        monkeypatch.setenv("PA_MODELS_DIR", str(tmp_path / "models"))

        # Hint image at the pixel resolution of the 32px workflow.
        in_dir = tmp_path / "input"
        in_dir.mkdir()
        Image.fromarray(
            (np.random.default_rng(0).uniform(size=(32, 32, 3)) * 255)
            .astype(np.uint8)
        ).save(in_dir / "hint.png")
        monkeypatch.setenv("PA_INPUT_DIR", str(in_dir))

        wf = {
            "4": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": paths["ckpt"]}},
            "5": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "a watercolor lighthouse",
                             "clip": ["4", 1]}},
            "7": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "blurry", "clip": ["4", 1]}},
            "10": {"class_type": "LoadImage", "inputs": {"image": "hint.png"}},
            "11": {"class_type": "ControlNetLoader",
                   "inputs": {"control_net_name": "tiny_cn.safetensors"}},
            "12": {"class_type": "ControlNetApplyAdvanced",
                   "inputs": {"positive": ["6", 0], "negative": ["7", 0],
                              "control_net": ["11", 0], "image": ["10", 0],
                              "strength": 0.8, "start_percent": 0.0,
                              "end_percent": 1.0}},
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": 7, "steps": 2, "cfg": 5.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0, "model": ["4", 0],
                             "positive": ["12", 0], "negative": ["12", 1],
                             "latent_image": ["5", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["4", 2]}},
        }
        out = run_workflow(wf)
        images = np.asarray(out["8"][0])
        assert images.shape[0] == 1 and np.isfinite(images).all()
        # The control actually steered the sample: rerun without ControlNet.
        wf_plain = {k: v for k, v in wf.items() if k not in ("10", "11", "12")}
        wf_plain["3"] = {**wf["3"], "inputs": {**wf["3"]["inputs"],
                                               "positive": ["6", 0],
                                               "negative": ["7", 0]}}
        plain = np.asarray(run_workflow(wf_plain)["8"][0])
        assert not np.allclose(images, plain, atol=1e-4)
