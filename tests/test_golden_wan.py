"""WAN block golden parity vs a minimal torch reference (official WAN 2.1 design).

The torch block below follows the public Wan2.1 DiT block: 6-chunk adaLN modulation
(shared time vector + learned per-block bias), self-attention with full-inner-dim
q/k RMSNorm and 3-axis RoPE, affine-pre-norm cross-attention to text (ungated), and
a tanh-GELU FFN. Exported in the official ``blocks.{i}.*`` key layout, mapped with
``convert_wan.py``'s helpers, and compared activation-for-activation against
``models/wan.py`` — the architecture-level check round-trip inversion
(test_convert_wan.py) cannot provide.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models.convert_wan import _dense, _ln, _rms
from comfyui_parallelanything_tpu.models.wan import WanBlock, WanConfig

from test_golden_flux import t_apply_rope, t_attention, t_rope_freqs

torch = pytest.importorskip("torch")
tnn = torch.nn
F = torch.nn.functional

CFG = WanConfig(
    hidden_size=64,
    ffn_dim=128,
    num_heads=4,   # head_dim 16
    depth=1,
    dtype=jnp.float32,
)


class TWanRMSNorm(tnn.Module):
    def __init__(self, dim, eps=1e-6):
        super().__init__()
        self.eps = eps
        self.weight = tnn.Parameter(torch.randn(dim))

    def forward(self, x):
        x32 = x.float()
        n = x32 * torch.rsqrt(x32.pow(2).mean(-1, keepdim=True) + self.eps)
        return n * self.weight


class TWanAttention(tnn.Module):
    """Key container: .q/.k/.v/.o/.norm_q/.norm_k (official WAN attention keys)."""

    def __init__(self, dim):
        super().__init__()
        self.q = tnn.Linear(dim, dim)
        self.k = tnn.Linear(dim, dim)
        self.v = tnn.Linear(dim, dim)
        self.o = tnn.Linear(dim, dim)
        self.norm_q = TWanRMSNorm(dim)
        self.norm_k = TWanRMSNorm(dim)


class TWanBlock(tnn.Module):
    def __init__(self, dim, ffn_dim, heads):
        super().__init__()
        self.heads = heads
        self.dim = dim
        self.self_attn = TWanAttention(dim)
        self.cross_attn = TWanAttention(dim)
        self.norm3 = tnn.LayerNorm(dim, eps=1e-6)
        self.ffn = tnn.Sequential(
            tnn.Linear(dim, ffn_dim), tnn.GELU(approximate="tanh"),
            tnn.Linear(ffn_dim, dim),
        )
        self.modulation = tnn.Parameter(torch.randn(1, 6, dim))

    def forward(self, x, context, e, cos, sin):
        H = self.heads
        D = self.dim // H
        B, S, _ = x.shape
        L = context.shape[1]
        e = (e + self.modulation).float()
        shift1, scale1, gate1, shift2, scale2, gate2 = (
            e[:, i][:, None, :] for i in range(6)
        )

        def ln_plain(t):
            return F.layer_norm(t, (self.dim,), eps=1e-6)

        # self-attention, q/k RMSNorm over the full inner dim, then heads + rope
        h = ln_plain(x) * (1 + scale1) + shift1
        q = self.self_attn.norm_q(self.self_attn.q(h)).reshape(B, S, H, D)
        k = self.self_attn.norm_k(self.self_attn.k(h)).reshape(B, S, H, D)
        v = self.self_attn.v(h).reshape(B, S, H, D)
        q, k = t_apply_rope(q, cos, sin), t_apply_rope(k, cos, sin)
        attn = t_attention(q, k, v).reshape(B, S, -1)
        x = x + gate1 * self.self_attn.o(attn)

        # cross-attention to text: affine pre-norm, no rope, no gate
        h = self.norm3(x)
        q = self.cross_attn.norm_q(self.cross_attn.q(h)).reshape(B, S, H, D)
        k = self.cross_attn.norm_k(self.cross_attn.k(context)).reshape(B, L, H, D)
        v = self.cross_attn.v(context).reshape(B, L, H, D)
        attn = t_attention(q, k, v).reshape(B, S, -1)
        x = x + self.cross_attn.o(attn)

        # FFN, modulated + gated
        h = ln_plain(x) * (1 + scale2) + shift2
        return x + gate2 * self.ffn(h)


def _wan_block_params(sd, t):
    """The per-block mapping of convert_wan_checkpoint (same helpers, same keys)."""
    return {
        "self_q": _dense(sd, f"{t}.self_attn.q"),
        "self_k": _dense(sd, f"{t}.self_attn.k"),
        "self_v": _dense(sd, f"{t}.self_attn.v"),
        "self_o": _dense(sd, f"{t}.self_attn.o"),
        "self_q_norm": _rms(sd, f"{t}.self_attn.norm_q"),
        "self_k_norm": _rms(sd, f"{t}.self_attn.norm_k"),
        "cross_q": _dense(sd, f"{t}.cross_attn.q"),
        "cross_k": _dense(sd, f"{t}.cross_attn.k"),
        "cross_v": _dense(sd, f"{t}.cross_attn.v"),
        "cross_o": _dense(sd, f"{t}.cross_attn.o"),
        "cross_q_norm": _rms(sd, f"{t}.cross_attn.norm_q"),
        "cross_k_norm": _rms(sd, f"{t}.cross_attn.norm_k"),
        "norm3": _ln(sd, f"{t}.norm3"),
        "ffn_in": _dense(sd, f"{t}.ffn.0"),
        "ffn_out": _dense(sd, f"{t}.ffn.2"),
        "modulation": sd[f"{t}.modulation"].numpy(),
    }


def test_wan_block_golden_parity():
    torch.manual_seed(2)
    tblk = TWanBlock(CFG.hidden_size, CFG.ffn_dim, CFG.num_heads).eval()
    sd = {f"blocks.0.{k}": v.detach() for k, v in tblk.state_dict().items()}
    params = _wan_block_params(sd, "blocks.0")

    rng = np.random.default_rng(9)
    B, S, L = 2, 24, 7
    x = rng.normal(size=(B, S, CFG.hidden_size)).astype(np.float32)
    ctx = rng.normal(size=(B, L, CFG.hidden_size)).astype(np.float32)
    e = rng.normal(size=(B, 6, CFG.hidden_size)).astype(np.float32)
    ids = rng.integers(0, 4, size=(B, S, 3))
    axes = (4, 6, 6)  # sums to head_dim 16

    t_cos, t_sin = t_rope_freqs(torch.from_numpy(ids), axes, 10000.0)
    with torch.no_grad():
        want = tblk(
            torch.from_numpy(x), torch.from_numpy(ctx), torch.from_numpy(e),
            t_cos, t_sin,
        ).numpy()

    from comfyui_parallelanything_tpu.ops.rope import axis_rope_freqs

    cos, sin = axis_rope_freqs(jnp.asarray(ids), axes, 10000.0)
    got = np.asarray(
        WanBlock(CFG).apply(
            {"params": jax.tree.map(jnp.asarray, params)},
            jnp.asarray(x), jnp.asarray(ctx), jnp.asarray(e), (cos, sin),
        )
    )
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)
