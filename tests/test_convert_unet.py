"""SD UNet checkpoint conversion: synthesize an ldm-layout state dict by inverting
the converter's transforms from a live model's params, convert back, require exact
round-trip + forward equivalence (same strategy as test_convert.py for FLUX)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models.convert_unet import (
    convert_sd_unet_checkpoint,
    strip_prefix,
)
from comfyui_parallelanything_tpu.models.unet import (
    UNetConfig,
    _heads_for,
    build_unet,
    sd15_config,
)


@pytest.fixture(scope="module")
def tiny_sd():
    cfg = sd15_config(
        model_channels=32,
        channel_mult=(1, 2),
        num_res_blocks=1,
        attention_levels=(1,),
        transformer_depth=(0, 1),
        num_heads=4,
        context_dim=64,
        norm_groups=8,
        dtype=jnp.float32,
    )
    model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
    return cfg, model


@pytest.fixture(scope="module")
def tiny_sdxl():
    # SDXL shape: heads from channels//64? too big for CI — use explicit heads but
    # keep the adm vector-conditioning path and linear proj_in/out irrelevant here
    # (our module always uses conv1x1; the converter's linear branch is unit-tested
    # separately below).
    cfg = UNetConfig(
        model_channels=32,
        channel_mult=(1, 2),
        attention_levels=(1,),
        transformer_depth=(0, 2),
        num_res_blocks=1,
        num_heads=4,
        context_dim=64,
        adm_in_channels=32,
        norm_groups=8,
        dtype=jnp.float32,
    )
    model = build_unet(cfg, jax.random.key(1), sample_shape=(1, 16, 16, 4))
    return cfg, model


# ---- inverse transforms (test-side; mirror convert_unet.py) -------------------------


def _inv_dense(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["kernel"]).T
    if "bias" in p:
        sd[f"{key}.bias"] = np.asarray(p["bias"])


def _inv_conv(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["kernel"]).transpose(3, 2, 0, 1)
    if "bias" in p:
        sd[f"{key}.bias"] = np.asarray(p["bias"])


def _inv_norm(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["scale"])
    sd[f"{key}.bias"] = np.asarray(p["bias"])


def _inv_res(p, prefix, sd):
    _inv_norm(p["GroupNorm_0"], f"{prefix}.in_layers.0", sd)
    _inv_conv(p["Conv_0"], f"{prefix}.in_layers.2", sd)
    _inv_dense(p["Dense_0"], f"{prefix}.emb_layers.1", sd)
    _inv_norm(p["GroupNorm_1"], f"{prefix}.out_layers.0", sd)
    _inv_conv(p["Conv_1"], f"{prefix}.out_layers.3", sd)
    if "Conv_2" in p:
        _inv_conv(p["Conv_2"], f"{prefix}.skip_connection", sd)


def _inv_transformer(p, prefix, depth, sd):
    _inv_norm(p["GroupNorm_0"], f"{prefix}.norm", sd)
    _inv_conv(p["proj_in"], f"{prefix}.proj_in", sd)
    _inv_conv(p["proj_out"], f"{prefix}.proj_out", sd)
    for d in range(depth):
        blk = p[f"block_{d}"]
        t = f"{prefix}.transformer_blocks.{d}"
        _inv_norm(blk["LayerNorm_0"], f"{t}.norm1", sd)
        _inv_norm(blk["LayerNorm_1"], f"{t}.norm2", sd)
        _inv_norm(blk["LayerNorm_2"], f"{t}.norm3", sd)
        _inv_dense(blk["ff_in"], f"{t}.ff.net.0.proj", sd)
        _inv_dense(blk["ff_out"], f"{t}.ff.net.2", sd)
        for name in ("attn1", "attn2"):
            for qkv in ("q", "k", "v"):
                k = np.asarray(blk[f"{name}_{qkv}"]["kernel"])  # (C, H, D)
                sd[f"{t}.{name}.to_{qkv}.weight"] = (
                    k.transpose(1, 2, 0).reshape(-1, k.shape[0])
                )
            o = np.asarray(blk[f"{name}_o"]["kernel"])  # (H, D, C)
            sd[f"{t}.{name}.to_out.0.weight"] = o.reshape(-1, o.shape[-1]).T
            sd[f"{t}.{name}.to_out.0.bias"] = np.asarray(blk[f"{name}_o"]["bias"])


def _ldm_sd(cfg: UNetConfig, params) -> dict:
    sd: dict = {}
    _inv_dense(params["time_embed_0"], "time_embed.0", sd)
    _inv_dense(params["time_embed_2"], "time_embed.2", sd)
    if cfg.adm_in_channels is not None:
        _inv_dense(params["label_embed_0"], "label_emb.0.0", sd)
        _inv_dense(params["label_embed_2"], "label_emb.0.2", sd)
    _inv_conv(params["input_conv"], "input_blocks.0.0", sd)

    def attn_at(level):
        return level in cfg.attention_levels and cfg.transformer_depth[level] > 0

    idx = 1
    for level in range(len(cfg.channel_mult)):
        for i in range(cfg.num_res_blocks):
            _inv_res(params[f"in_{level}_{i}_res"], f"input_blocks.{idx}.0", sd)
            if attn_at(level):
                _inv_transformer(
                    params[f"in_{level}_{i}_attn"], f"input_blocks.{idx}.1",
                    cfg.transformer_depth[level], sd,
                )
            idx += 1
        if level != len(cfg.channel_mult) - 1:
            _inv_conv(params[f"down_{level}"]["Conv_0"], f"input_blocks.{idx}.0.op", sd)
            idx += 1

    from comfyui_parallelanything_tpu.models.unet import middle_depth

    _inv_res(params["mid_res1"], "middle_block.0", sd)
    if middle_depth(cfg) > 0:
        _inv_transformer(
            params["mid_attn"], "middle_block.1", middle_depth(cfg), sd
        )
        _inv_res(params["mid_res2"], "middle_block.2", sd)
    else:
        _inv_res(params["mid_res2"], "middle_block.1", sd)

    idx = 0
    for level in reversed(range(len(cfg.channel_mult))):
        for i in range(cfg.num_res_blocks + 1):
            _inv_res(params[f"out_{level}_{i}_res"], f"output_blocks.{idx}.0", sd)
            sub = 1
            if attn_at(level):
                _inv_transformer(
                    params[f"out_{level}_{i}_attn"], f"output_blocks.{idx}.{sub}",
                    cfg.transformer_depth[level], sd,
                )
                sub += 1
            if i == cfg.num_res_blocks and level != 0:
                _inv_conv(
                    params[f"up_{level}"]["Conv_0"],
                    f"output_blocks.{idx}.{sub}.conv", sd,
                )
            idx += 1

    _inv_norm(params["out_norm"], "out.0", sd)
    _inv_conv(params["out_conv"], "out.2", sd)
    return sd



def _assert_trees_equal(got, want):
    fg, fw = dict(flatten_tree(got)), dict(flatten_tree(want))
    assert sorted(fg) == sorted(fw), (
        f"missing: {sorted(set(fw) - set(fg))[:5]} extra: {sorted(set(fg) - set(fw))[:5]}"
    )
    for k in fw:
        np.testing.assert_allclose(fg[k], fw[k], rtol=1e-6, atol=1e-6, err_msg=str(k))


class TestSD15RoundTrip:
    def test_structure_and_values(self, tiny_sd):
        cfg, model = tiny_sd
        sd = _ldm_sd(cfg, model.params)
        got = convert_sd_unet_checkpoint(sd, cfg)
        _assert_trees_equal(got, model.params)

    def test_forward_equivalence(self, tiny_sd):
        cfg, model = tiny_sd
        params = convert_sd_unet_checkpoint(_ldm_sd(cfg, model.params), cfg)
        x = jax.random.normal(jax.random.key(2), (2, 16, 16, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(3), (2, 12, 64), jnp.float32)
        t = jnp.array([5.0, 9.0])
        want = model(x, t, ctx)
        got = model.apply(params, x, t, ctx)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


class TestSDXLShape:
    def test_adm_and_depth2_roundtrip(self, tiny_sdxl):
        cfg, model = tiny_sdxl
        sd = _ldm_sd(cfg, model.params)
        got = convert_sd_unet_checkpoint(sd, cfg)
        _assert_trees_equal(got, model.params)


class TestRefinerShape:
    def test_middle_override_roundtrip_and_forward(self):
        # The refiner's signature topology: NO attention at the deepest
        # encoder level but a transformer in the middle block
        # (transformer_depth_middle) — underivable from the per-level tuple.
        from comfyui_parallelanything_tpu.models import (
            build_unet,
            sdxl_refiner_config,
        )
        from comfyui_parallelanything_tpu.models.unet import middle_depth

        cfg = sdxl_refiner_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(0,), transformer_depth=(1, 0),
            transformer_depth_middle=2, num_heads=4, context_dim=64,
            adm_in_channels=32, norm_groups=8, dtype=jnp.float32,
        )
        assert middle_depth(cfg) == 2  # deepest level has none; middle does
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        assert "mid_attn" in model.params
        sd = _ldm_sd(cfg, model.params)
        assert "middle_block.1.transformer_blocks.1.attn1.to_q.weight" in sd
        got = convert_sd_unet_checkpoint(sd, cfg)
        _assert_trees_equal(got, model.params)
        x = jax.random.normal(jax.random.key(2), (1, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(3), (1, 5, 64), jnp.float32)
        t = jnp.array([7.0])
        y = jax.random.normal(jax.random.key(4), (1, 32), jnp.float32)
        want = model(x, t, ctx, y=y)
        got_out = model.apply(got, x, t, ctx, y=y)
        np.testing.assert_allclose(np.asarray(got_out), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_full_size_refiner_config(self):
        from comfyui_parallelanything_tpu.models import sdxl_refiner_config
        from comfyui_parallelanything_tpu.models.unet import middle_depth

        cfg = sdxl_refiner_config()
        assert cfg.model_channels == 384
        assert cfg.context_dim == 1280
        assert cfg.adm_in_channels == 2560
        assert cfg.transformer_depth == (0, 4, 4, 0)
        assert middle_depth(cfg) == 4


class TestHelpers:
    def test_strip_prefix(self):
        sd = {"model.diffusion_model.a.weight": 1, "first_stage_model.b": 2}
        out = strip_prefix(sd)
        assert out == {"a.weight": 1}

    def test_strip_prefix_passthrough_when_absent(self):
        sd = {"a.weight": 1}
        assert strip_prefix(sd) == sd

    def test_linear_proj_in_gains_spatial_dims(self):
        # SDXL stores proj_in/out as Linear; converter must emit a 1x1 conv kernel.
        from comfyui_parallelanything_tpu.models.convert_unet import _proj_1x1

        sd = {"p.weight": np.ones((6, 4), np.float32), "p.bias": np.zeros(6, np.float32)}
        out = _proj_1x1(sd, "p")
        assert out["kernel"].shape == (1, 1, 4, 6)

    def test_heads_for_sdxl_convention(self):
        cfg = UNetConfig(num_heads=-1)
        assert _heads_for(cfg, 640) == 10
