"""CLIP vision tower: golden equivalence against transformers'
CLIPVisionModelWithProjection, config sniffing, preprocessing, and the
CLIPVisionLoader/CLIPVisionEncode node surface."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from comfyui_parallelanything_tpu.models.vision import (
    CLIPVisionConfig,
    build_clip_vision,
    clip_preprocess,
    convert_clip_vision_checkpoint,
    load_clip_vision_checkpoint,
    sniff_vision_config,
)

TINY = CLIPVisionConfig(
    image_size=28, patch_size=7, hidden_size=32, num_layers=2, num_heads=4,
    intermediate_size=64, act="quick_gelu", projection_dim=16,
    dtype=jnp.float32,
)


def _hf_vision(cfg: CLIPVisionConfig, act: str):
    hf_cfg = transformers.CLIPVisionConfig(
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        image_size=cfg.image_size,
        patch_size=cfg.patch_size,
        hidden_act=act,
        projection_dim=cfg.projection_dim,
    )
    torch.manual_seed(0)
    return transformers.CLIPVisionModelWithProjection(hf_cfg).eval()


class TestGolden:
    @pytest.mark.parametrize("act", ["quick_gelu", "gelu"])
    def test_matches_transformers(self, act):
        cfg = dataclasses.replace(TINY, act=act)
        hf = _hf_vision(cfg, act)
        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        params, _ = convert_clip_vision_checkpoint(sd, cfg)
        model = build_clip_vision(cfg, params=params)

        rng = np.random.default_rng(1)
        img = rng.standard_normal(
            (2, cfg.image_size, cfg.image_size, 3)
        ).astype(np.float32)
        embeds, last, penultimate = model(jnp.asarray(img))

        with torch.no_grad():
            out = hf(
                pixel_values=torch.from_numpy(img).permute(0, 3, 1, 2),
                output_hidden_states=True,
            )
        np.testing.assert_allclose(
            np.asarray(embeds), out.image_embeds.numpy(), rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(last), out.last_hidden_state.numpy(),
            rtol=2e-4, atol=2e-4,
        )
        # transformers hidden_states[-2] is the raw penultimate stream.
        np.testing.assert_allclose(
            np.asarray(penultimate), out.hidden_states[-2].numpy(),
            rtol=2e-4, atol=2e-4,
        )

    def test_sniff_round_trip(self):
        hf = _hf_vision(TINY, "quick_gelu")
        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        cfg = sniff_vision_config(sd)
        assert (cfg.image_size, cfg.patch_size, cfg.hidden_size,
                cfg.num_layers, cfg.intermediate_size,
                cfg.projection_dim) == (28, 7, 32, 2, 64, 16)
        # num_heads for the tiny fixture comes from the fallback rule —
        # loaders for real towers use the family table; pass cfg explicitly.
        params, _ = convert_clip_vision_checkpoint(sd, TINY)
        assert "visual_proj" in params

    def test_sniff_head_table_for_public_towers(self):
        # ViT-H (1280) and bigG (1664) use 16 heads (widths 80/104), NOT the
        # 64-wide-head rule; ViT-B/L stay on it.
        from comfyui_parallelanything_tpu.models.vision import (
            sniff_vision_config,
        )

        def fake(hidden, layers):
            grid = 16 * 16
            return {
                "vision_model.embeddings.patch_embedding.weight":
                    np.zeros((hidden, 3, 14, 14), np.float32),
                "vision_model.embeddings.position_embedding.weight":
                    np.zeros((grid + 1, hidden), np.float32),
                "vision_model.encoder.layers.0.mlp.fc1.weight":
                    np.zeros((hidden * 4, hidden), np.float32),
                f"vision_model.encoder.layers.{layers - 1}.mlp.fc1.weight":
                    np.zeros((hidden * 4, hidden), np.float32),
            }

        assert sniff_vision_config(fake(1024, 24)).num_heads == 16
        assert sniff_vision_config(fake(1280, 32)).num_heads == 16
        assert sniff_vision_config(fake(1664, 48)).num_heads == 16
        assert sniff_vision_config(fake(768, 12)).num_heads == 12


class TestPreprocess:
    def test_resize_crop_and_normalize(self):
        img = jnp.ones((1, 50, 100, 3)) * 0.5
        out = clip_preprocess(img, size=28)
        assert out.shape == (1, 28, 28, 3)
        from comfyui_parallelanything_tpu.models.vision import (
            CLIP_MEAN,
            CLIP_STD,
        )

        want = (0.5 - np.asarray(CLIP_MEAN)) / np.asarray(CLIP_STD)
        np.testing.assert_allclose(
            np.asarray(out)[0, 14, 14], want, rtol=1e-5, atol=1e-5
        )


class TestNodes:
    def test_loader_and_encode(self, tmp_path, monkeypatch):
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.nodes_compat import (
            stock_node_mappings,
        )

        hf = _hf_vision(TINY, "quick_gelu")
        sd = {k: np.ascontiguousarray(v.detach().numpy())
              for k, v in hf.state_dict().items()}
        cv_dir = tmp_path / "models" / "clip_vision"
        cv_dir.mkdir(parents=True)
        save_file(sd, str(cv_dir / "tiny_vision.safetensors"))
        monkeypatch.setenv("PA_MODELS_DIR", str(tmp_path / "models"))

        maps = stock_node_mappings()
        # The sniffed head count differs for the tiny fixture; load through
        # the node (which sniffs) and patch heads via the sniffer.
        import comfyui_parallelanything_tpu.models.vision as vision_mod

        real_sniff = vision_mod.sniff_vision_config
        monkeypatch.setattr(
            vision_mod, "sniff_vision_config",
            lambda s: dataclasses.replace(real_sniff(s), num_heads=4,
                                          dtype=jnp.float32),
        )
        (wire,) = maps["CLIPVisionLoader"]().load_clip(
            clip_name="tiny_vision.safetensors"
        )
        img = jnp.asarray(
            np.random.default_rng(0).uniform(size=(1, 40, 40, 3)),
            jnp.float32,
        )
        (out,) = maps["CLIPVisionEncode"]().encode(wire, img)
        assert out["image_embeds"].shape == (1, 16)
        assert out["penultimate"].shape[0] == 1
        assert np.isfinite(np.asarray(out["image_embeds"])).all()


def _openclip_visual_sd(cfg, params):
    """Inverse-synthesize an OpenCLIP ``visual.*``-layout dict from our param
    tree (hand-written inverse of ``openclip_visual_to_hf`` so the remap is
    checked against an independently-derived mapping)."""
    sd = {
        # torch conv (out, in, kh, kw) from flax kernel (kh, kw, in, out)
        "conv1.weight": np.asarray(params["patch_embed"]["kernel"])
            .transpose(3, 2, 0, 1),
        "class_embedding": np.asarray(params["class_embedding"]),
        "positional_embedding": np.asarray(params["pos_emb"]),
        "ln_pre.weight": np.asarray(params["pre_ln"]["scale"]),
        "ln_pre.bias": np.asarray(params["pre_ln"]["bias"]),
        "ln_post.weight": np.asarray(params["post_ln"]["scale"]),
        "ln_post.bias": np.asarray(params["post_ln"]["bias"]),
        "proj": np.asarray(params["visual_proj"]["kernel"]),
    }
    for i in range(cfg.num_layers):
        blk = params[f"layers_{i}"]
        t = f"transformer.resblocks.{i}"
        sd[f"{t}.attn.in_proj_weight"] = np.concatenate(
            [np.asarray(blk[n]["kernel"]).T for n in "qkv"], axis=0
        )
        sd[f"{t}.attn.in_proj_bias"] = np.concatenate(
            [np.asarray(blk[n]["bias"]) for n in "qkv"]
        )
        sd[f"{t}.attn.out_proj.weight"] = np.asarray(blk["out"]["kernel"]).T
        sd[f"{t}.attn.out_proj.bias"] = np.asarray(blk["out"]["bias"])
        sd[f"{t}.mlp.c_fc.weight"] = np.asarray(blk["fc1"]["kernel"]).T
        sd[f"{t}.mlp.c_fc.bias"] = np.asarray(blk["fc1"]["bias"])
        sd[f"{t}.mlp.c_proj.weight"] = np.asarray(blk["fc2"]["kernel"]).T
        sd[f"{t}.mlp.c_proj.bias"] = np.asarray(blk["fc2"]["bias"])
        sd[f"{t}.ln_1.weight"] = np.asarray(blk["ln1"]["scale"])
        sd[f"{t}.ln_1.bias"] = np.asarray(blk["ln1"]["bias"])
        sd[f"{t}.ln_2.weight"] = np.asarray(blk["ln2"]["scale"])
        sd[f"{t}.ln_2.bias"] = np.asarray(blk["ln2"]["bias"])
    return sd


class TestOpenCLIPVisual:
    def test_remap_round_trip_and_forward(self):
        """The unclip checkpoints' bundled tower layout: OpenCLIP visual.*
        keys convert through the same path as HF ones (detected + remapped),
        landing on identical params."""
        import dataclasses

        import jax

        from comfyui_parallelanything_tpu.models.vision import (
            build_clip_vision,
        )
        from tree_utils import flatten_tree

        cfg = dataclasses.replace(TINY, act="gelu")
        enc = build_clip_vision(cfg, rng=jax.random.key(3))
        sd = _openclip_visual_sd(cfg, enc.params)
        got, got_cfg = convert_clip_vision_checkpoint(sd)
        # Sniffed config must land on the same tower (act keys off width).
        assert got_cfg.hidden_size == cfg.hidden_size
        assert got_cfg.num_layers == cfg.num_layers
        assert got_cfg.projection_dim == cfg.projection_dim
        fg, fw = dict(flatten_tree(got)), dict(flatten_tree(enc.params))
        assert sorted(fg) == sorted(fw)
        for k in fw:
            np.testing.assert_array_equal(np.asarray(fg[k]),
                                          np.asarray(fw[k]), err_msg=str(k))

    def test_unrecognized_key_raises(self):
        from comfyui_parallelanything_tpu.models.vision import (
            openclip_visual_to_hf,
        )

        with pytest.raises(KeyError, match="unrecognized"):
            openclip_visual_to_hf({"attnpool.weird": np.zeros(1)})
