"""WAN2.2 A14B timestep-boundary expert switching: routing correctness, sampler
integration (the host-loop samplers make the switch concrete per step), and the
dual-expert WanVideoPipeline path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models import (
    TimestepExpertSwitch,
    WAN22_T2V_BOUNDARY,
)
from comfyui_parallelanything_tpu.sampling.runner import run_sampler


def _tagged_model(tag: float):
    """Velocity model returning a constant, so which expert ran is readable off
    the integrated output."""

    def f(x, t, context=None, **kw):
        return jnp.full_like(x, tag)

    return f


class TestSwitch:
    def test_routes_by_boundary(self):
        sw = TimestepExpertSwitch(_tagged_model(1.0), _tagged_model(-1.0), 0.5)
        x = jnp.zeros((1, 4))
        hi = sw(x, jnp.array([0.9]))
        lo = sw(x, jnp.array([0.1]))
        assert float(hi[0, 0]) == 1.0 and float(lo[0, 0]) == -1.0

    def test_boundary_inclusive_high(self):
        sw = TimestepExpertSwitch(_tagged_model(1.0), _tagged_model(-1.0), 0.5)
        out = sw(jnp.zeros((1, 4)), jnp.array([0.5]))
        assert float(out[0, 0]) == 1.0

    def test_default_boundary_is_wan22_t2v(self):
        sw = TimestepExpertSwitch(None, None)
        assert sw.boundary == WAN22_T2V_BOUNDARY

    def test_flow_sampler_uses_both_experts(self):
        """With boundary 0.5 and a 4-step flow schedule, early steps integrate
        +1 velocity and late steps -1 — both experts must contribute."""
        sw = TimestepExpertSwitch(_tagged_model(1.0), _tagged_model(-1.0), 0.5)
        noise = jnp.zeros((1, 4, 4, 4))
        out = run_sampler(sw, noise, None, sampler="flow_euler", steps=4)
        only_high = run_sampler(
            _tagged_model(1.0), noise, None, sampler="flow_euler", steps=4
        )
        only_low = run_sampler(
            _tagged_model(-1.0), noise, None, sampler="flow_euler", steps=4
        )
        # dt < 0 integrating t: 1 → 0, so a +1-velocity (high) run lands LOWER.
        v = float(out[0, 0, 0, 0])
        assert float(only_high[0, 0, 0, 0]) < v < float(only_low[0, 0, 0, 0])

    def test_model_config_comes_from_high_expert(self):
        class Cfg:
            patch_size = (1, 2, 2)

        class M:
            config = Cfg()

            def __call__(self, *a, **k):
                return None

        sw = TimestepExpertSwitch(M(), _tagged_model(0.0))
        assert sw.model_config.patch_size == (1, 2, 2)

    def test_cleanup_reaches_both(self):
        calls = []

        class M:
            def __init__(self, tag):
                self.tag = tag

            def cleanup(self):
                calls.append(self.tag)

        TimestepExpertSwitch(M("hi"), M("lo")).cleanup()
        assert calls == ["hi", "lo"]


class TestDualExpertPipeline:
    def test_wan22_dual_expert_t2v(self):
        from comfyui_parallelanything_tpu.models import (
            T5Config,
            VideoVAEConfig,
            WanConfig,
            build_t5_encoder,
            build_video_vae,
            build_wan,
        )
        from comfyui_parallelanything_tpu.pipelines import WanVideoPipeline
        from test_tokenizer import _tiny_tokenizer

        ZC = 4
        wcfg = WanConfig(
            in_channels=ZC, out_channels=ZC, hidden_size=48, ffn_dim=96,
            num_heads=4, depth=2, text_dim=32, freq_dim=16, dtype=jnp.float32,
        )
        vcfg = VideoVAEConfig(
            base_channels=8, channel_mult=(1, 2, 2), num_res_blocks=1,
            temporal_downsample=(False, True), z_channels=ZC,
            latent_mean=(0.0,) * ZC, latent_std=(1.0,) * ZC, dtype=jnp.float32,
        )
        tcfg = T5Config(
            vocab_size=64, d_model=32, d_kv=8, d_ff=64, num_layers=2,
            num_heads=4, dtype=jnp.float32,
        )
        hi = build_wan(wcfg, jax.random.key(0), sample_shape=(1, 2, 4, 4, ZC), txt_len=6)
        lo = build_wan(wcfg, jax.random.key(9), sample_shape=(1, 2, 4, 4, ZC), txt_len=6)
        pipe = WanVideoPipeline(
            dit=hi,
            vae=build_video_vae(vcfg, jax.random.key(1), sample_thw=(3, 8, 8)),
            t5=build_t5_encoder(tcfg, jax.random.key(2), sample_len=8),
            t5_tokenizer=_tiny_tokenizer(),
            dit_low_noise=lo,
            boundary=0.5,
        )
        # shift=1.0 keeps the 3 model calls at t = 1, 2/3, 1/3 so boundary
        # 0.5 genuinely splits them (the default shift 5 piles all three above
        # 0.7 and the low expert would never fire).
        video = pipe(
            "hello", steps=3, cfg_scale=1.0, height=16, width=16, frames=5,
            shift=1.0,
        )
        assert video.shape == (1, 5, 16, 16, 3)
        assert np.isfinite(np.asarray(video)).all()
        # Single-expert run differs — the low-noise expert really participates.
        single = WanVideoPipeline(
            dit=hi, vae=pipe.vae, t5=pipe.t5, t5_tokenizer=pipe.t5_tokenizer,
        )("hello", steps=3, cfg_scale=1.0, height=16, width=16, frames=5, shift=1.0)
        assert not np.allclose(np.asarray(video), np.asarray(single))


class TestVideo2Video:
    def test_init_video_shifts_output(self):
        from comfyui_parallelanything_tpu.models import (
            T5Config, VideoVAEConfig, WanConfig, build_t5_encoder,
            build_video_vae, build_wan,
        )
        from comfyui_parallelanything_tpu.pipelines import WanVideoPipeline
        from test_tokenizer import _tiny_tokenizer

        ZC = 4
        pipe = WanVideoPipeline(
            dit=build_wan(
                WanConfig(in_channels=ZC, out_channels=ZC, hidden_size=48,
                          ffn_dim=96, num_heads=4, depth=1, text_dim=32,
                          freq_dim=16, dtype=jnp.float32),
                jax.random.key(0), sample_shape=(1, 2, 4, 4, ZC), txt_len=6,
            ),
            vae=build_video_vae(
                VideoVAEConfig(base_channels=8, channel_mult=(1, 2, 2),
                               num_res_blocks=1, temporal_downsample=(False, True),
                               z_channels=ZC, latent_mean=(0.0,) * ZC,
                               latent_std=(1.0,) * ZC, dtype=jnp.float32),
                jax.random.key(1), sample_thw=(3, 8, 8),
            ),
            t5=build_t5_encoder(
                T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                         num_layers=2, num_heads=4, dtype=jnp.float32),
                jax.random.key(2), sample_len=8,
            ),
            t5_tokenizer=_tiny_tokenizer(),
        )
        init = jnp.full((1, 5, 16, 16, 3), 0.5)
        kw = dict(steps=2, cfg_scale=1.0, height=16, width=16, frames=5,
                  rng=jax.random.key(3), shift=1.0)
        # The preservation target is what the (random-weight) VAE itself makes
        # of the init clip, not the raw pixels.
        from comfyui_parallelanything_tpu.models.vae import (
            images_to_vae_input, vae_output_to_images,
        )
        z0 = pipe.vae.encode(images_to_vae_input(init))
        target = np.asarray(vae_output_to_images(pipe.vae.decode(z0)))
        full = np.asarray(pipe("hello", **kw))
        weak = np.asarray(pipe("hello", init_video=init, denoise=0.25, **kw))
        assert weak.shape == (1, 5, 16, 16, 3)
        assert np.abs(weak - target).mean() < np.abs(full - target).mean()

    def test_denoise_without_init_video_rejected(self):
        from comfyui_parallelanything_tpu.pipelines import _encode_init

        with pytest.raises(ValueError, match="init_video"):
            _encode_init(None, None, 0.5, 1, (5, 16, 16), what="init_video")
