"""Tests for the FLUX-class MMDiT + flow sampler + parallel execution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models.flux import (
    FluxConfig,
    build_flux,
    flux_schnell_config,
)
from comfyui_parallelanything_tpu.sampling.flow import flow_euler_sample, flow_timesteps


@pytest.fixture(scope="module")
def tiny_flux():
    cfg = FluxConfig(
        in_channels=16,  # 4 latent ch × 2×2 patch
        hidden_size=64,
        num_heads=4,
        depth=2,
        depth_single_blocks=2,
        context_in_dim=32,
        vec_in_dim=16,
        axes_dim=(4, 6, 6),
        guidance_embed=True,
        dtype=jnp.float32,
    )
    return build_flux(
        cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=16, name="tiny-flux"
    )


def _inputs(batch, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    x = jax.random.normal(k1, (batch, 8, 8, 4), jnp.float32)
    ctx = jax.random.normal(k2, (batch, 16, 32), jnp.float32)
    y = jax.random.normal(k3, (batch, 16), jnp.float32)
    return x, ctx, y


class TestFluxForward:
    def test_shapes_and_finiteness(self, tiny_flux):
        x, ctx, y = _inputs(2)
        t = jnp.array([1.0, 0.5])
        out = tiny_flux(x, t, ctx, y=y)
        assert out.shape == (2, 8, 8, 4)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_block_lists_metadata(self, tiny_flux):
        # Pipeline-placement metadata parity with the reference's block-list walk
        # over double_blocks/single_blocks (1156).
        assert tiny_flux.block_lists == {"double_blocks": 2, "single_blocks": 2}

    def test_param_naming_has_block_indices(self, tiny_flux):
        names = set(tiny_flux.params.keys())
        assert "double_blocks_0" in names and "single_blocks_1" in names

    def test_guidance_sensitivity(self, tiny_flux):
        # guidance_embed=True must change the output when guidance changes.
        x, ctx, y = _inputs(1)
        t = jnp.ones((1,))
        a = tiny_flux(x, t, ctx, y=y, guidance=jnp.array([1.0]))
        b = tiny_flux(x, t, ctx, y=y, guidance=jnp.array([8.0]))
        assert float(jnp.max(jnp.abs(a - b))) > 1e-6

    def test_requires_context(self, tiny_flux):
        x, _, y = _inputs(1)
        with pytest.raises(ValueError):
            tiny_flux.apply(tiny_flux.params, x, jnp.ones((1,)), None, y=y)

    def test_schnell_has_no_guidance_params(self):
        cfg = flux_schnell_config(
            in_channels=16, hidden_size=32, num_heads=2, depth=1,
            depth_single_blocks=1, context_in_dim=16, vec_in_dim=8,
            axes_dim=(4, 6, 6), dtype=jnp.float32,
        )
        m = build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=8)
        assert "guidance_in" not in m.params


class TestFlowSampler:
    def test_timesteps_shift(self):
        ts = flow_timesteps(10, shift=3.0)
        assert ts.shape == (11,)
        assert float(ts[0]) == pytest.approx(1.0)
        assert float(ts[-1]) == pytest.approx(0.0)
        # Shift > 1 pushes interior steps toward t=1 (high noise).
        unshifted = flow_timesteps(10, shift=1.0)
        assert float(ts[5]) > float(unshifted[5])

    def test_sample_runs(self, tiny_flux):
        x, ctx, y = _inputs(2)
        out = flow_euler_sample(tiny_flux, x, ctx, steps=3, guidance=4.0, y=y)
        assert out.shape == x.shape
        assert np.all(np.isfinite(np.asarray(out)))


class TestFluxParallel:
    def test_sharded_equals_single(self, tiny_flux):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(tiny_flux, chain)
        x, ctx, y = _inputs(8)
        t = jnp.linspace(1.0, 0.1, 8)
        got = pm(x, t, ctx, y=y)
        want = tiny_flux(x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_sampled_flow_sharded(self, tiny_flux):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(4)])
        pm = parallelize(tiny_flux, chain)
        x, ctx, y = _inputs(4)
        got = flow_euler_sample(pm, x, ctx, steps=2, guidance=4.0, y=y)
        want = flow_euler_sample(tiny_flux, x, ctx, steps=2, guidance=4.0, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )
