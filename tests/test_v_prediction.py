"""v-parameterization (SD2.x-768): denoiser algebra, ddim equivalence, and the
config-carried prediction type reaching the samplers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.sampling.k_samplers import (
    EpsDenoiser,
    model_sigmas,
)
from comfyui_parallelanything_tpu.sampling.runner import run_sampler
from comfyui_parallelanything_tpu.sampling.schedules import (
    scaled_linear_schedule,
)


def _zero_model(x, t, context=None, **kw):
    return jnp.zeros_like(x)


class TestVDenoiser:
    def test_zero_v_output_gives_cskip_x(self):
        """With v=0, x0 = x/(sigma^2+1) exactly (the c_skip term alone)."""
        den = EpsDenoiser(_zero_model, None, prediction="v")
        x = jnp.full((1, 4, 4, 4), 3.0)
        sigma = jnp.float32(2.0)
        out = np.asarray(den(x, sigma))
        np.testing.assert_allclose(out, 3.0 / 5.0, rtol=1e-6)

    def test_zero_eps_output_gives_x(self):
        den = EpsDenoiser(_zero_model, None, prediction="eps")
        x = jnp.full((1, 4, 4, 4), 3.0)
        np.testing.assert_allclose(np.asarray(den(x, jnp.float32(2.0))), 3.0)

    def test_eps_and_v_consistent_on_equivalent_models(self):
        """An eps model and the v model derived from the same x0-prediction must
        produce the same denoised output: v = alpha*eps - sigma_t*x0 relation
        checked through the sigma-space wrapper."""
        acp = scaled_linear_schedule()
        table = model_sigmas(acp)
        x = jax.random.normal(jax.random.key(0), (1, 4, 4, 4))
        sigma = table[500]
        alpha_bar = acp[500]

        # Fix a ground-truth x0; build exact eps and v predictions for the
        # *scaled* input x_in = x/sqrt(sigma^2+1) = sqrt(alpha_bar)-scaled x_t.
        x0 = jnp.ones_like(x) * 0.3

        def eps_model(x_in, t, context=None, **kw):
            # x_t(discrete) = x_in; eps = (x_t - sqrt(a)x0)/sqrt(1-a)
            return (x_in - jnp.sqrt(alpha_bar) * x0) / jnp.sqrt(1 - alpha_bar)

        def v_model(x_in, t, context=None, **kw):
            eps = (x_in - jnp.sqrt(alpha_bar) * x0) / jnp.sqrt(1 - alpha_bar)
            return jnp.sqrt(alpha_bar) * eps - jnp.sqrt(1 - alpha_bar) * x0

        out_eps = np.asarray(EpsDenoiser(eps_model, None)(x, sigma))
        out_v = np.asarray(EpsDenoiser(v_model, None, prediction="v")(x, sigma))
        np.testing.assert_allclose(out_eps, out_v, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(out_eps, 0.3, rtol=1e-4, atol=1e-5)


class TestDdimV:
    def test_ddim_v_equals_eps_for_equivalent_models(self):
        from comfyui_parallelanything_tpu.sampling.ddim import ddim_sample

        acp = scaled_linear_schedule()
        x0 = 0.25

        def eps_model(x, t, context=None, **kw):
            a = acp[t.astype(jnp.int32)][:, None, None, None]
            return (x - jnp.sqrt(a) * x0) / jnp.sqrt(1 - a)

        def v_model(x, t, context=None, **kw):
            a = acp[t.astype(jnp.int32)][:, None, None, None]
            eps = (x - jnp.sqrt(a) * x0) / jnp.sqrt(1 - a)
            return jnp.sqrt(a) * eps - jnp.sqrt(1 - a) * x0

        noise = jax.random.normal(jax.random.key(1), (1, 4, 4, 4))
        out_e = np.asarray(ddim_sample(eps_model, noise, steps=4))
        out_v = np.asarray(ddim_sample(v_model, noise, steps=4, prediction="v"))
        np.testing.assert_allclose(out_e, out_v, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(out_e, x0, rtol=1e-3, atol=1e-4)

    def test_bad_prediction_rejected(self):
        with pytest.raises(ValueError, match="prediction"):
            EpsDenoiser(_zero_model, None, prediction="x0")

    def test_flow_rejects_prediction(self):
        with pytest.raises(ValueError, match="flow_euler"):
            run_sampler(
                _zero_model, jnp.zeros((1, 4, 4, 4)), None,
                sampler="flow_euler", steps=2, prediction="v",
            )


class TestConfigCarriesPrediction:
    def test_sd21_config(self):
        from comfyui_parallelanything_tpu.models import sd21_config

        assert sd21_config().prediction == "eps"
        assert sd21_config(prediction="v").prediction == "v"
        assert sd21_config().context_dim == 1024

    def test_run_sampler_prediction_changes_output(self):
        def model(x, t, context=None, **kw):
            return 0.3 * x + 0.1

        noise = jax.random.normal(jax.random.key(2), (1, 4, 4, 4))
        a = run_sampler(model, noise, None, sampler="euler", steps=3)
        b = run_sampler(
            model, noise, None, sampler="euler", steps=3, prediction="v"
        )
        assert not np.allclose(np.asarray(a), np.asarray(b))


class TestSD2TextTower:
    def test_open_clip_h_config(self):
        from comfyui_parallelanything_tpu.models import open_clip_h_config

        cfg = open_clip_h_config()
        assert (cfg.hidden_size, cfg.num_layers, cfg.num_heads) == (1024, 24, 16)
        assert cfg.act == "gelu" and cfg.projection_dim == 1024

    def test_pipeline_penultimate_conditioning(self):
        """An SD2-style pipeline (1024-ctx UNet + H tower, penultimate layer)
        produces an image end-to-end — the full sd21 path."""
        from comfyui_parallelanything_tpu.models import (
            CLIPTextConfig, VAEConfig, build_clip_text, build_unet, build_vae,
            sd21_config,
        )
        from comfyui_parallelanything_tpu.pipelines import StableDiffusionPipeline
        from test_tokenizer import _tiny_tokenizer

        tok = _tiny_tokenizer()
        ccfg = CLIPTextConfig(
            vocab_size=64, hidden_size=48, num_layers=2, num_heads=4, max_len=8,
            act="gelu", eos_id=tok.eos_id, dtype=jnp.float32,
        )
        ucfg = sd21_config(
            model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
            attention_levels=(0, 1), context_dim=48, num_heads=4, norm_groups=8,
            prediction="v", dtype=jnp.float32,
        )
        vcfg = VAEConfig(
            z_channels=4, base_channels=32, channel_mult=(1, 2),
            num_res_blocks=1, norm_groups=8, dtype=jnp.float32,
        )
        pipe = StableDiffusionPipeline(
            unet=build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4)),
            vae=build_vae(vcfg, jax.random.key(1), sample_hw=16),
            clip=build_clip_text(ccfg, jax.random.key(2)),
            tokenizer=tok,
            clip_layer="penultimate",
        )
        img = pipe("hello", steps=2, cfg_scale=1.0, height=16, width=16)
        assert img.shape == (1, 16, 16, 3)
        assert np.isfinite(np.asarray(img)).all()

    def test_bad_clip_layer_rejected(self):
        from comfyui_parallelanything_tpu.models import (
            CLIPTextConfig, VAEConfig, build_clip_text, build_unet, build_vae,
            sd15_config,
        )
        from comfyui_parallelanything_tpu.pipelines import StableDiffusionPipeline
        from test_tokenizer import _tiny_tokenizer

        tok = _tiny_tokenizer()
        pipe = StableDiffusionPipeline(
            unet=build_unet(
                sd15_config(model_channels=32, channel_mult=(1, 2),
                            transformer_depth=(1, 1), attention_levels=(0, 1),
                            context_dim=48, num_heads=4, norm_groups=8,
                            dtype=jnp.float32),
                jax.random.key(0), sample_shape=(1, 8, 8, 4)),
            vae=build_vae(
                VAEConfig(z_channels=4, base_channels=32, channel_mult=(1, 2),
                          num_res_blocks=1, norm_groups=8, dtype=jnp.float32),
                jax.random.key(1), sample_hw=16),
            clip=build_clip_text(
                CLIPTextConfig(vocab_size=64, hidden_size=48, num_layers=2,
                               num_heads=4, max_len=8, eos_id=tok.eos_id,
                               dtype=jnp.float32), jax.random.key(2)),
            tokenizer=tok,
            clip_layer="antepenultimate",
        )
        with pytest.raises(ValueError, match="clip_layer"):
            pipe("hello", steps=1, cfg_scale=1.0, height=16, width=16)

    def test_penultimate_ln_applied_for_sd2_towers(self):
        """open_clip_h towers apply ln_final to the penultimate stream (SD2's
        FrozenOpenCLIPEmbedder convention) — raw for SDXL-style towers."""
        import dataclasses

        from comfyui_parallelanything_tpu.models import (
            CLIPTextConfig, build_clip_text,
        )

        base = CLIPTextConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4, max_len=8,
            act="gelu", eos_id=63, dtype=jnp.float32,
        )
        tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (1, 8)))
        raw_enc = build_clip_text(base, jax.random.key(0))
        ln_enc = build_clip_text(
            dataclasses.replace(base, penultimate_ln=True), params=raw_enc.params
        )
        _, pen_raw, _ = raw_enc(tokens)
        _, pen_ln, _ = ln_enc(tokens)
        assert not np.allclose(np.asarray(pen_raw), np.asarray(pen_ln))
        # the normed stream has ~zero mean per position (LayerNorm property)
        means = np.asarray(pen_ln).mean(axis=-1)
        assert np.abs(means).max() < 0.2

    def test_text_encode_node_routes_penultimate_for_sd2(self):
        import dataclasses

        from comfyui_parallelanything_tpu.models import (
            CLIPTextConfig, build_clip_text,
        )
        from comfyui_parallelanything_tpu.nodes import TPUTextEncode
        from test_tokenizer import _tiny_tokenizer

        tok = _tiny_tokenizer()
        cfg = CLIPTextConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4, max_len=8,
            act="gelu", eos_id=tok.eos_id, penultimate_ln=True, dtype=jnp.float32,
        )
        enc = build_clip_text(cfg, jax.random.key(0))
        (cond,) = TPUTextEncode().encode(
            {"encoder": enc, "tokenizer": tok, "type": "clip"}, "hello"
        )
        np.testing.assert_array_equal(
            np.asarray(cond["context"]), np.asarray(cond["penultimate"])
        )
