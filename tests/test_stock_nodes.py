"""Stock-ComfyUI node-name shims (nodes_compat.py): a workflow exported from
a stock ComfyUI install — builtin class names, builtin input keys — runs
against this host unchanged.

The reference pack lives inside ComfyUI and gets the builtins for free
(any_device_parallel.py:1473-1483 registers only its own nodes); here the
builtin names are part of the host-parity surface. Family sniffing
(models/loader.sniff_model_family) replaces the stock loader's implicit
config detection.
"""

import json
import os

import numpy as np
import pytest

from comfyui_parallelanything_tpu.host import run_workflow
from comfyui_parallelanything_tpu.models.loader import sniff_model_family


class TestSniffModelFamily:
    def _flux_keys(self, dev=True, depth=19):
        sd = {f"double_blocks.{i}.img_attn.qkv.weight": np.zeros((1, 1))
              for i in range(depth)}
        sd["single_blocks.0.linear1.weight"] = np.zeros((1, 1))
        if dev:
            sd["guidance_in.in_layer.weight"] = np.zeros((1, 1))
        return sd

    def test_flux_dev_vs_schnell_vs_zimage(self):
        assert sniff_model_family(self._flux_keys(dev=True)) == "flux-dev"
        assert sniff_model_family(self._flux_keys(dev=False)) == "flux-schnell"
        # Z-image proxy: flux layout, no guidance embed, shallow double stack
        # (flux.py z_image_turbo_config depth 6/26).
        assert sniff_model_family(
            self._flux_keys(dev=False, depth=6)
        ) == "zimage-turbo"

    def test_prefixed_full_checkpoint_keys(self):
        sd = {f"model.diffusion_model.{k}": v
              for k, v in self._flux_keys().items()}
        sd["first_stage_model.decoder.conv_in.weight"] = np.zeros((1, 1))
        assert sniff_model_family(sd) == "flux-dev"

    def test_mmdit_variants(self):
        base = {f"joint_blocks.{i}.x_block.attn.qkv.weight": np.zeros((1, 1))
                for i in range(24)}
        assert sniff_model_family(base) == "sd3-medium"
        large = {f"joint_blocks.{i}.x_block.attn.qkv.weight": np.zeros((1, 1))
                 for i in range(38)}
        assert sniff_model_family(large) == "sd35-large"
        dual = dict(base)
        dual["joint_blocks.0.x_block.attn2.qkv.weight"] = np.zeros((1, 1))
        assert sniff_model_family(dual) == "sd35-medium"

    def test_wan_width(self):
        sd = {"blocks.0.self_attn.q.weight": np.zeros((1536, 1536))}
        assert sniff_model_family(sd) == "wan-1.3b"
        sd = {"blocks.0.self_attn.q.weight": np.zeros((5120, 5120))}
        assert sniff_model_family(sd) == "wan-14b"

    def test_unet_families(self):
        sdxl = {"input_blocks.0.0.weight": np.zeros((1, 1)),
                "label_emb.0.0.weight": np.zeros((1, 1))}
        assert sniff_model_family(sdxl) == "sdxl"
        sd15 = {
            "input_blocks.0.0.weight": np.zeros((1, 1)),
            "input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight":
                np.zeros((320, 768)),
        }
        assert sniff_model_family(sd15) == "sd15"
        sd21 = {
            "input_blocks.0.0.weight": np.zeros((1, 1)),
            "input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight":
                np.zeros((320, 1024)),
        }
        assert sniff_model_family(sd21) == "sd21"

    def test_unknown_raises(self):
        with pytest.raises(ValueError, match="cannot sniff"):
            sniff_model_family({"some.random.weight": np.zeros((1,))})

    def test_sniffs_synthetic_sd15_checkpoint(self, tmp_path, monkeypatch):
        # The same synthetic checkpoint the e2e test loads must sniff sd15.
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        from comfyui_parallelanything_tpu.models import load_safetensors

        assert sniff_model_family(load_safetensors(paths["ckpt"])) == "sd15"


def _synthetic_stock_env(tmp_path, monkeypatch):
    """Tiny sd15 checkpoint WITH bundled cond_stage_model CLIP (the stock
    loader extracts text encoders from the file), plus tokenizer tables wired
    through the PA_* env vars the shims read. Mirrors
    test_host_graph._synthetic_env, extended with the bundled tower."""
    import jax
    import jax.numpy as jnp
    from safetensors.numpy import save_file

    import comfyui_parallelanything_tpu.models as models_pkg
    import comfyui_parallelanything_tpu.models.text_encoders as te_mod
    from comfyui_parallelanything_tpu.models import build_unet, build_vae
    from tests.test_convert_unet import _ldm_sd
    from tests.test_text_encoders import TINY_CLIP, _hf_clip
    from tests.test_vae import TINY as TINY_VAE, _ldm_layout_sd

    real_sd15 = models_pkg.sd15_config

    def tiny_sd15():
        return real_sd15(
            model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
            attention_levels=(0, 1), context_dim=TINY_CLIP.hidden_size,
            num_heads=4, norm_groups=8, dtype=jnp.float32,
        )

    monkeypatch.setattr(models_pkg, "sd15_config", tiny_sd15)
    monkeypatch.setattr(models_pkg, "sd_vae_config", lambda: TINY_VAE)
    monkeypatch.setattr(te_mod, "clip_l_config", lambda: TINY_CLIP)

    ucfg = tiny_sd15()
    unet = build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
    vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
    hf = _hf_clip(TINY_CLIP, "quick_gelu")
    sd = {
        f"model.diffusion_model.{k}": np.ascontiguousarray(v)
        for k, v in _ldm_sd(ucfg, unet.params).items()
    }
    sd.update({
        f"first_stage_model.{k}": np.ascontiguousarray(v)
        for k, v in _ldm_layout_sd(TINY_VAE, vae.params).items()
    })
    # Bundled text tower, SD1.x layout: cond_stage_model.transformer.<HF keys>.
    sd.update({
        f"cond_stage_model.transformer.{k}":
            np.ascontiguousarray(v.detach().numpy())
        for k, v in hf.state_dict().items()
    })
    ckpt = tmp_path / "ckpt.safetensors"
    save_file(sd, str(ckpt))

    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {"[UNK]": 0, "a": 5, "watercolor": 6, "lighthouse": 7, "at": 8,
             "dawn": 9, "blurry": 10, "low": 11, "quality": 12}
    t = tokenizers.Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
    t.pre_tokenizer = Whitespace()
    tok_path = tmp_path / "tokenizer.json"
    t.save(str(tok_path))

    monkeypatch.setenv("PA_TOKENIZER_JSON", str(tok_path))
    return {"ckpt": str(ckpt), "tok": str(tok_path)}


class TestStockWorkflow:
    def _stock_workflow(self, ckpt):
        """API-format graph exactly as a stock ComfyUI export writes it:
        builtin class names, builtin input keys, [node, output] links."""
        return {
            "4": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": ckpt}},
            "5": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 2}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "a watercolor lighthouse at dawn",
                             "clip": ["4", 1]}},
            "7": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "blurry low quality",
                             "clip": ["4", 1]}},
            # seed beyond 2**63: stock seed widgets are 64-bit and the UI's
            # randomize fills [0, 2**64) — half of exported workflows carry a
            # seed jax.random.key would reject (ADVICE r3, folded by seed_key).
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": 2**63 + 7, "steps": 2, "cfg": 7.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0, "model": ["4", 0],
                             "positive": ["6", 0], "negative": ["7", 0],
                             "latent_image": ["5", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["4", 2]}},
            "9": {"class_type": "SaveImage",
                  "inputs": {"images": ["8", 0],
                             "filename_prefix": "ComfyUI"}},
        }

    def test_exported_stock_workflow_runs_unchanged(self, tmp_path, monkeypatch):
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = self._stock_workflow(paths["ckpt"])
        # SaveImage's stock form has no output_dir widget; point the TPU
        # node's default there via its own optional input (exported graphs
        # carry only filename_prefix — add output_dir like a host config).
        wf["9"]["inputs"]["output_dir"] = str(tmp_path / "out")

        out = run_workflow(wf)
        images = out["8"][0]
        assert images.shape[0] == 2 and images.shape[-1] == 3
        assert np.isfinite(np.asarray(images)).all()
        saved = out["9"][0]
        assert len(saved) == 2 and all(os.path.exists(p) for p in saved)

    def test_stock_conditioning_and_image_shims_run(self, tmp_path,
                                                    monkeypatch):
        # VERDICT r3 missing #4: regional prompting (SetArea → Combine),
        # prompt blending (Average), stock image resize, and PreviewImage —
        # one exported-style graph exercising all of them.
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = self._stock_workflow(paths["ckpt"])
        wf["9"]["inputs"]["output_dir"] = str(tmp_path / "out")
        wf.update({
            "10": {"class_type": "CLIPTextEncode",
                   "inputs": {"text": "blurry low quality",
                              "clip": ["4", 1]}},
            # Regional prompt: the second prompt scoped to the top-left 16px
            # (2 latent cells of the 32px graph), combined into the first.
            "11": {"class_type": "ConditioningSetArea",
                   "inputs": {"conditioning": ["10", 0], "width": 16,
                              "height": 16, "x": 0, "y": 0, "strength": 0.8}},
            "12": {"class_type": "ConditioningCombine",
                   "inputs": {"conditioning_1": ["6", 0],
                              "conditioning_2": ["11", 0]}},
            # Blend the two raw prompts too (exercises Average's lerp).
            "13": {"class_type": "ConditioningAverage",
                   "inputs": {"conditioning_to": ["12", 0],
                              "conditioning_from": ["10", 0],
                              "conditioning_to_strength": 0.7}},
            "14": {"class_type": "ImageScale",
                   "inputs": {"image": ["8", 0], "upscale_method": "bicubic",
                              "width": 48, "height": 40, "crop": "center"}},
            "15": {"class_type": "ImageScaleBy",
                   "inputs": {"image": ["8", 0],
                              "upscale_method": "lanczos", "scale_by": 0.5}},
            "16": {"class_type": "PreviewImage",
                   "inputs": {"images": ["14", 0]}},
        })
        wf["3"]["inputs"]["positive"] = ["13", 0]

        out = run_workflow(wf)
        assert np.isfinite(np.asarray(out["8"][0])).all()
        assert out["14"][0].shape[1:3] == (40, 48)
        h, w = np.asarray(out["8"][0]).shape[1:3]
        assert out["15"][0].shape[1:3] == (
            max(1, round(h * 0.5)), max(1, round(w * 0.5)))
        # Stock 0-sentinel: a zero dim keeps the source aspect ratio.
        from comfyui_parallelanything_tpu.nodes_compat import ImageScale

        (kept,) = ImageScale().upscale(
            np.zeros((1, 10, 20, 3), np.float32), "bilinear",
            width=40, height=0,
        )
        assert kept.shape[1:3] == (20, 40)
        with pytest.raises(ValueError, match="both be 0"):
            ImageScale().upscale(
                np.zeros((1, 10, 20, 3), np.float32), "bilinear",
                width=0, height=0,
            )
        previews = out["16"][0]
        assert previews and all(os.path.exists(p) for p in previews)
        assert all(os.sep + "temp" + os.sep in p for p in previews)

    def test_conditioning_zero_out_and_sdxl_encode(self, tmp_path,
                                                   monkeypatch):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        _, clip, _ = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )
        enc = NODE_CLASS_MAPPINGS["CLIPTextEncode"]()
        (cond,) = enc.run(clip=clip, text="a watercolor lighthouse")

        # ZeroOut: every embedding zeroed, extras included.
        zo = NODE_CLASS_MAPPINGS["ConditioningZeroOut"]()
        (z,) = zo.zero_out({**cond, "extras": (dict(cond),)})
        assert float(jnp.abs(z["context"]).max()) == 0.0
        assert float(jnp.abs(z["extras"][0]["context"]).max()) == 0.0
        assert z["context"].shape == cond["context"].shape

        # CLIPTextEncodeSDXL over a dual wire (same tiny tower as both L and
        # G — the shim's plumbing and the 2816-style size vector are what's
        # under test, not tower asymmetry).
        dual = {"type": "sdxl-dual", "l": clip, "g": clip}
        xl = NODE_CLASS_MAPPINGS["CLIPTextEncodeSDXL"]()
        (c,) = xl.encode(
            dual, width=512, height=768, crop_w=0, crop_h=0,
            target_width=1024, target_height=1024,
            text_g="a watercolor lighthouse", text_l="at dawn",
        )
        hidden = cond["penultimate"].shape[-1]
        assert c["context"].shape[-1] == 2 * hidden
        assert c["pooled"].shape[-1] == cond["pooled"].shape[-1] + 6 * 256
        with pytest.raises(ValueError, match="dual"):
            xl.encode(clip, 512, 512, 0, 0, 512, 512, "a", "b")

    def test_models_dir_resolution(self, tmp_path, monkeypatch):
        # ComfyUI folder layout: a bare name resolves via
        # $PA_MODELS_DIR/checkpoints/<name>.
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        models = tmp_path / "models" / "checkpoints"
        models.mkdir(parents=True)
        os.rename(paths["ckpt"], models / "tiny.safetensors")
        monkeypatch.setenv("PA_MODELS_DIR", str(tmp_path / "models"))

        wf = self._stock_workflow("tiny.safetensors")
        del wf["9"]  # no image save needed for the resolution check
        out = run_workflow(wf)
        assert out["8"][0].shape[0] == 2

    def test_clip_set_last_layer_tags_wire(self, tmp_path, monkeypatch):
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = self._stock_workflow(paths["ckpt"])
        del wf["9"]
        wf["10"] = {"class_type": "CLIPSetLastLayer",
                    "inputs": {"clip": ["4", 1], "stop_at_clip_layer": -2}}
        wf["6"]["inputs"]["clip"] = ["10", 0]
        out = run_workflow(wf)
        assert np.isfinite(np.asarray(out["8"][0])).all()

    def test_missing_tokenizer_fails_with_instructions(self, tmp_path,
                                                       monkeypatch):
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.delenv("PA_TOKENIZER_JSON")
        wf = self._stock_workflow(paths["ckpt"])
        with pytest.raises(Exception, match="PA_TOKENIZER_JSON"):
            run_workflow(wf)

    def test_stock_custom_sampling_graph_executes(self, tmp_path, monkeypatch):
        # The custom-sampling path exactly as a stock FLUX-style export wires
        # it: RandomNoise + KSamplerSelect + BasicScheduler + CFGGuider +
        # SamplerCustomAdvanced under their stock names and stock input keys.
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = {
            "ckpt": {"class_type": "CheckpointLoaderSimple",
                     "inputs": {"ckpt_name": paths["ckpt"]}},
            "pos": {"class_type": "CLIPTextEncode",
                    "inputs": {"text": "a watercolor lighthouse",
                               "clip": ["ckpt", 1]}},
            "neg": {"class_type": "CLIPTextEncode",
                    "inputs": {"text": "blurry", "clip": ["ckpt", 1]}},
            "latent": {"class_type": "EmptyLatentImage",
                       "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "noise": {"class_type": "RandomNoise",
                      "inputs": {"noise_seed": 11}},
            "sel": {"class_type": "KSamplerSelect",
                    "inputs": {"sampler_name": "euler"}},
            "sig": {"class_type": "BasicScheduler",
                    "inputs": {"model": ["ckpt", 0], "scheduler": "normal",
                               "steps": 2, "denoise": 1.0}},
            "guide": {"class_type": "CFGGuider",
                      "inputs": {"model": ["ckpt", 0], "positive": ["pos", 0],
                                 "negative": ["neg", 0], "cfg": 3.0}},
            "run": {"class_type": "SamplerCustomAdvanced",
                    "inputs": {"noise": ["noise", 0], "guider": ["guide", 0],
                               "sampler": ["sel", 0], "sigmas": ["sig", 0],
                               "latent_image": ["latent", 0]}},
            "dec": {"class_type": "VAEDecode",
                    "inputs": {"samples": ["run", 0], "vae": ["ckpt", 2]}},
        }
        out = run_workflow(wf)
        images = out["dec"][0]
        assert images.shape[0] == 1 and images.shape[-1] == 3
        assert np.isfinite(np.asarray(images)).all()

    def test_latent_upscale_absolute_dims(self, tmp_path, monkeypatch):
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        node = NODE_CLASS_MAPPINGS["LatentUpscale"]()
        (out,) = node.upscale(lat, "bilinear", width=128, height=128)
        # 128 px -> 16 latent; from 8 -> scale 2.
        assert out["samples"].shape == (1, 16, 16, 4)
        # Width-only change must NOT no-op: axes scale independently.
        (wide,) = node.upscale(lat, "bilinear", width=192, height=64)
        assert wide["samples"].shape == (1, 8, 24, 4)

    def test_lora_loader_rebakes_from_source(self, tmp_path, monkeypatch):
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.models import load_safetensors
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        model, clip, vae = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )

        # Rank-2 kohya LoRA against a real attention projection of the tiny
        # checkpoint (bake_lora matches the stripped ldm key).
        sd = load_safetensors(paths["ckpt"])
        target = next(
            k for k in sd
            if k.endswith("attn1.to_q.weight") and "input_blocks" in k
        ).removeprefix("model.diffusion_model.")
        out_d, in_d = sd[f"model.diffusion_model.{target}"].shape
        rng = np.random.default_rng(5)
        lora_path = tmp_path / "style.safetensors"
        save_file({
            f"{target.removesuffix('.weight')}.lora_down.weight":
                rng.standard_normal((2, in_d)).astype(np.float32),
            f"{target.removesuffix('.weight')}.lora_up.weight":
                rng.standard_normal((out_d, 2)).astype(np.float32),
        }, str(lora_path))

        node = NODE_CLASS_MAPPINGS["LoraLoader"]()
        patched, clip_out = node.load_lora(model, clip, str(lora_path), 1.0, 1.0)
        assert clip_out is clip
        import jax

        base = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(model.params)]
        )
        new = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(patched.params)]
        )
        assert base.shape == new.shape and not np.allclose(base, new)

        # Zero strength bakes nothing.
        zero, _ = node.load_lora(model, clip, str(lora_path), 0.0, 1.0)
        znew = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(zero.params)]
        )
        np.testing.assert_allclose(znew, base, rtol=1e-6, atol=1e-6)

        # Stacking: chained LoraLoaders compose — two strength-1 bakes of the
        # same LoRA equal one strength-2 bake (deltas are linear in strength).
        stacked, _ = node.load_lora(patched, clip, str(lora_path), 1.0, 1.0)
        snew = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(stacked.params)]
        )
        assert not np.allclose(snew, new)
        twice, _ = node.load_lora(model, clip, str(lora_path), 2.0, 1.0)
        tnew = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(twice.params)]
        )
        np.testing.assert_allclose(snew, tnew, rtol=1e-4, atol=1e-5)

        # Untagged models and missing files fail with instructions
        # (an absent LoRA must never silently return an unpatched model).
        with pytest.raises(ValueError, match="CheckpointLoaderSimple"):
            node.load_lora(object(), clip, str(lora_path), 1.0, 1.0)
        with pytest.raises(ValueError, match="not found"):
            node.load_lora(model, clip, "", 1.0, 1.0)
        with pytest.raises(ValueError, match="not found"):
            node.load_lora(model, clip, "ghost.safetensors", 1.0, 1.0)

    def test_lora_loader_strength_clip_bakes_text_tower(self, tmp_path,
                                                        monkeypatch):
        # A LoRA with kohya lora_te_* keys must rebuild the CLIP wire with the
        # deltas baked into the bundled tower (ADVICE/VERDICT r3: the
        # strength_clip divergence closed).
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.models import load_safetensors
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        model, clip, _ = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )
        sd = load_safetensors(paths["ckpt"])
        target = next(
            k for k in sd
            if k.startswith("cond_stage_model.") and
            k.endswith("self_attn.q_proj.weight")
        )
        out_d, in_d = sd[target].shape
        base_name = (
            target.removeprefix("cond_stage_model.transformer.")
            .removesuffix(".weight").replace(".", "_")
        )
        rng = np.random.default_rng(9)
        lora_path = tmp_path / "te.safetensors"
        save_file({
            f"lora_te_{base_name}.lora_down.weight":
                rng.standard_normal((2, in_d)).astype(np.float32),
            f"lora_te_{base_name}.lora_up.weight":
                rng.standard_normal((out_d, 2)).astype(np.float32),
        }, str(lora_path))

        node = NODE_CLASS_MAPPINGS["LoraLoader"]()
        import jax

        def flat(wire):
            return np.concatenate([
                np.ravel(np.asarray(v, np.float32))
                for v in jax.tree.leaves(wire["encoder"].params)
            ])

        _, clip_out = node.load_lora(model, clip, str(lora_path), 1.0, 1.0)
        assert clip_out is not clip
        assert not np.allclose(flat(clip_out), flat(clip))
        # strength_clip=0 leaves the wire untouched (identity, no rebuild).
        _, clip_zero = node.load_lora(model, clip, str(lora_path), 1.0, 0.0)
        assert clip_zero is clip
        # Upstream wire state (CLIPSetLastLayer's tag) survives the rebuild.
        _, clip_keep = node.load_lora(
            model, {**clip, "clip_skip": 2}, str(lora_path), 1.0, 1.0
        )
        assert clip_keep["clip_skip"] == 2
        assert not np.allclose(flat(clip_keep), flat(clip))
        # A CLIP wire NOT from this checkpoint's bundled towers (no
        # source_ckpt tag — e.g. DualCLIPLoader) is never clobbered by the
        # rebuild; te deltas are skipped with a warning instead.
        external = {k: v for k, v in clip.items() if k != "source_ckpt"}
        _, clip_ext = node.load_lora(model, external, str(lora_path), 1.0, 1.0)
        assert clip_ext is external

    def test_save_image_defaults_to_pa_output_dir(self, tmp_path, monkeypatch):
        # Stock exports carry only filename_prefix; images must land in the
        # host-configured root (the one the API server serves /view from).
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "served"))
        node = NODE_CLASS_MAPPINGS["SaveImage"]()
        (paths,) = node.run(
            images=np.zeros((1, 8, 8, 3), np.float32), filename_prefix="x"
        )
        assert all(p.startswith(str(tmp_path / "served")) for p in paths)
