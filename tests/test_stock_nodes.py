"""Stock-ComfyUI node-name shims (nodes_compat.py): a workflow exported from
a stock ComfyUI install — builtin class names, builtin input keys — runs
against this host unchanged.

The reference pack lives inside ComfyUI and gets the builtins for free
(any_device_parallel.py:1473-1483 registers only its own nodes); here the
builtin names are part of the host-parity surface. Family sniffing
(models/loader.sniff_model_family) replaces the stock loader's implicit
config detection.
"""

import json
import os

import numpy as np
import pytest

from comfyui_parallelanything_tpu.host import run_workflow
from comfyui_parallelanything_tpu.models.loader import sniff_model_family


class TestSniffModelFamily:
    def _flux_keys(self, dev=True, depth=19):
        sd = {f"double_blocks.{i}.img_attn.qkv.weight": np.zeros((1, 1))
              for i in range(depth)}
        sd["single_blocks.0.linear1.weight"] = np.zeros((1, 1))
        if dev:
            sd["guidance_in.in_layer.weight"] = np.zeros((1, 1))
        return sd

    def test_flux_dev_vs_schnell_vs_zimage(self):
        assert sniff_model_family(self._flux_keys(dev=True)) == "flux-dev"
        assert sniff_model_family(self._flux_keys(dev=False)) == "flux-schnell"
        # Z-image proxy: flux layout, no guidance embed, shallow double stack
        # (flux.py z_image_turbo_config depth 6/26).
        assert sniff_model_family(
            self._flux_keys(dev=False, depth=6)
        ) == "zimage-turbo"

    def test_prefixed_full_checkpoint_keys(self):
        sd = {f"model.diffusion_model.{k}": v
              for k, v in self._flux_keys().items()}
        sd["first_stage_model.decoder.conv_in.weight"] = np.zeros((1, 1))
        assert sniff_model_family(sd) == "flux-dev"

    def test_mmdit_variants(self):
        base = {f"joint_blocks.{i}.x_block.attn.qkv.weight": np.zeros((1, 1))
                for i in range(24)}
        assert sniff_model_family(base) == "sd3-medium"
        large = {f"joint_blocks.{i}.x_block.attn.qkv.weight": np.zeros((1, 1))
                 for i in range(38)}
        assert sniff_model_family(large) == "sd35-large"
        dual = dict(base)
        dual["joint_blocks.0.x_block.attn2.qkv.weight"] = np.zeros((1, 1))
        assert sniff_model_family(dual) == "sd35-medium"

    def test_wan_width(self):
        sd = {"blocks.0.self_attn.q.weight": np.zeros((1536, 1536))}
        assert sniff_model_family(sd) == "wan-1.3b"
        sd = {"blocks.0.self_attn.q.weight": np.zeros((5120, 5120))}
        assert sniff_model_family(sd) == "wan-14b"

    def test_unet_families(self):
        sdxl = {"input_blocks.0.0.weight": np.zeros((1, 1)),
                "label_emb.0.0.weight": np.zeros((1, 1))}
        assert sniff_model_family(sdxl) == "sdxl"
        sd15 = {
            "input_blocks.0.0.weight": np.zeros((1, 1)),
            "input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight":
                np.zeros((320, 768)),
        }
        assert sniff_model_family(sd15) == "sd15"
        sd21 = {
            "input_blocks.0.0.weight": np.zeros((1, 1)),
            "input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight":
                np.zeros((320, 1024)),
        }
        assert sniff_model_family(sd21) == "sd21"

    def test_unknown_raises(self):
        with pytest.raises(ValueError, match="cannot sniff"):
            sniff_model_family({"some.random.weight": np.zeros((1,))})

    def test_sniffs_synthetic_sd15_checkpoint(self, tmp_path, monkeypatch):
        # The same synthetic checkpoint the e2e test loads must sniff sd15.
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        from comfyui_parallelanything_tpu.models import load_safetensors

        assert sniff_model_family(load_safetensors(paths["ckpt"])) == "sd15"


def _synthetic_stock_env(tmp_path, monkeypatch):
    """Tiny sd15 checkpoint WITH bundled cond_stage_model CLIP (the stock
    loader extracts text encoders from the file), plus tokenizer tables wired
    through the PA_* env vars the shims read. Mirrors
    test_host_graph._synthetic_env, extended with the bundled tower."""
    import jax
    import jax.numpy as jnp
    from safetensors.numpy import save_file

    import comfyui_parallelanything_tpu.models as models_pkg
    import comfyui_parallelanything_tpu.models.text_encoders as te_mod
    from comfyui_parallelanything_tpu.models import build_unet, build_vae
    from tests.test_convert_unet import _ldm_sd
    from tests.test_text_encoders import TINY_CLIP, _hf_clip
    from tests.test_vae import TINY as TINY_VAE, _ldm_layout_sd

    real_sd15 = models_pkg.sd15_config

    def tiny_sd15():
        return real_sd15(
            model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
            attention_levels=(0, 1), context_dim=TINY_CLIP.hidden_size,
            num_heads=4, norm_groups=8, dtype=jnp.float32,
        )

    monkeypatch.setattr(models_pkg, "sd15_config", tiny_sd15)
    monkeypatch.setattr(models_pkg, "sd_vae_config", lambda: TINY_VAE)
    monkeypatch.setattr(te_mod, "clip_l_config", lambda: TINY_CLIP)

    ucfg = tiny_sd15()
    unet = build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
    vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
    hf = _hf_clip(TINY_CLIP, "quick_gelu")
    sd = {
        f"model.diffusion_model.{k}": np.ascontiguousarray(v)
        for k, v in _ldm_sd(ucfg, unet.params).items()
    }
    sd.update({
        f"first_stage_model.{k}": np.ascontiguousarray(v)
        for k, v in _ldm_layout_sd(TINY_VAE, vae.params).items()
    })
    # Bundled text tower, SD1.x layout: cond_stage_model.transformer.<HF keys>.
    sd.update({
        f"cond_stage_model.transformer.{k}":
            np.ascontiguousarray(v.detach().numpy())
        for k, v in hf.state_dict().items()
    })
    ckpt = tmp_path / "ckpt.safetensors"
    save_file(sd, str(ckpt))

    tok_path = _word_level_tokenizer(tmp_path, monkeypatch)
    return {"ckpt": str(ckpt), "tok": tok_path}


def _word_level_tokenizer(tmp_path, monkeypatch) -> str:
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {"[UNK]": 0, "a": 5, "watercolor": 6, "lighthouse": 7, "at": 8,
             "dawn": 9, "blurry": 10, "low": 11, "quality": 12}
    t = tokenizers.Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
    t.pre_tokenizer = Whitespace()
    tok_path = tmp_path / "tokenizer.json"
    t.save(str(tok_path))

    monkeypatch.setenv("PA_TOKENIZER_JSON", str(tok_path))
    return str(tok_path)


def _synthetic_sdxl_env(tmp_path, monkeypatch):
    """Tiny single-file SDXL checkpoint with BOTH bundled conditioner towers
    (HF CLIP-L under conditioner.embedders.0, OpenCLIP-G under
    conditioner.embedders.1) plus the VAE — the stock SDXL export layout,
    sniffed as family=sdxl by CheckpointLoaderSimple. The tiny widths are
    coupled the way the real family's are: context = L ⊕ G hidden,
    adm = G pooled + 6×256 size embeddings."""
    import jax
    import jax.numpy as jnp
    from safetensors.numpy import save_file

    import comfyui_parallelanything_tpu.models as models_pkg
    from comfyui_parallelanything_tpu.models import build_unet, build_vae
    from comfyui_parallelanything_tpu.models.text_encoders import (
        build_clip_text,
        open_clip_g_config,
    )
    from tests.test_convert_unet import _ldm_sd
    from tests.test_text_encoders import (
        TINY_CLIP,
        TestOpenCLIPConversion,
        _hf_clip,
    )
    from tests.test_vae import TINY as TINY_VAE, _ldm_layout_sd

    g_cfg = open_clip_g_config(
        vocab_size=100, hidden_size=64, num_layers=2, num_heads=4,
        max_len=16, projection_dim=64, dtype=jnp.float32,
    )
    real_xl = models_pkg.sdxl_config

    def tiny_xl():
        return real_xl(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=TINY_CLIP.hidden_size + g_cfg.hidden_size,
            adm_in_channels=g_cfg.projection_dim + 6 * 256,
            norm_groups=8, dtype=jnp.float32,
        )

    import comfyui_parallelanything_tpu.models.text_encoders as te_mod

    monkeypatch.setattr(models_pkg, "sdxl_config", tiny_xl)
    monkeypatch.setattr(models_pkg, "sdxl_vae_config", lambda: TINY_VAE)
    monkeypatch.setattr(models_pkg, "open_clip_g_config", lambda: g_cfg)
    monkeypatch.setattr(te_mod, "clip_l_config", lambda: TINY_CLIP)

    ucfg = tiny_xl()
    unet = build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
    vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
    hf = _hf_clip(TINY_CLIP, "quick_gelu")
    g_enc = build_clip_text(g_cfg, rng=jax.random.key(2))
    sd = {
        f"model.diffusion_model.{k}": np.ascontiguousarray(v)
        for k, v in _ldm_sd(ucfg, unet.params).items()
    }
    sd.update({
        f"first_stage_model.{k}": np.ascontiguousarray(v)
        for k, v in _ldm_layout_sd(TINY_VAE, vae.params).items()
    })
    sd.update({
        f"conditioner.embedders.0.transformer.{k}":
            np.ascontiguousarray(v.detach().numpy())
        for k, v in hf.state_dict().items()
    })
    sd.update({
        f"conditioner.embedders.1.model.{k}": np.ascontiguousarray(v)
        for k, v in TestOpenCLIPConversion._openclip_layout(
            g_cfg, g_enc.params
        ).items()
    })
    ckpt = tmp_path / "sdxl_ckpt.safetensors"
    save_file(sd, str(ckpt))
    tok_path = _word_level_tokenizer(tmp_path, monkeypatch)
    return {"ckpt": str(ckpt), "tok": tok_path}


def _synthetic_refiner_env(tmp_path, monkeypatch):
    """Tiny SDXL-REFINER single-file checkpoint: refiner-shaped UNet (no
    deepest-level attention, depth-carrying middle transformer, G-only
    1280-wide context so the family SNIFFS as sdxl-refiner), the bundled
    OpenCLIP-G tower under conditioner.embedders.0.model.*, and the VAE."""
    import jax
    import jax.numpy as jnp
    from safetensors.numpy import save_file

    import comfyui_parallelanything_tpu.models as models_pkg
    from comfyui_parallelanything_tpu.models import build_unet, build_vae
    from comfyui_parallelanything_tpu.models.text_encoders import (
        build_clip_text,
        open_clip_g_config,
    )
    from tests.test_convert_unet import _ldm_sd
    from tests.test_text_encoders import TestOpenCLIPConversion
    from tests.test_vae import TINY as TINY_VAE, _ldm_layout_sd

    g_cfg = open_clip_g_config(
        vocab_size=100, hidden_size=1280, num_layers=1, num_heads=8,
        max_len=16, intermediate_size=128, projection_dim=64,
        dtype=jnp.float32,
    )
    real_ref = models_pkg.sdxl_refiner_config

    def tiny_refiner():
        return real_ref(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1),
            transformer_depth_middle=1, num_heads=4,
            context_dim=g_cfg.hidden_size,
            adm_in_channels=g_cfg.projection_dim + 5 * 256,
            norm_groups=8, dtype=jnp.float32,
        )

    monkeypatch.setattr(models_pkg, "sdxl_refiner_config", tiny_refiner)
    monkeypatch.setattr(models_pkg, "sdxl_vae_config", lambda: TINY_VAE)
    monkeypatch.setattr(models_pkg, "open_clip_g_config", lambda: g_cfg)

    ucfg = tiny_refiner()
    unet = build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
    vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
    g_enc = build_clip_text(g_cfg, rng=jax.random.key(2))
    sd = {
        f"model.diffusion_model.{k}": np.ascontiguousarray(v)
        for k, v in _ldm_sd(ucfg, unet.params).items()
    }
    sd.update({
        f"first_stage_model.{k}": np.ascontiguousarray(v)
        for k, v in _ldm_layout_sd(TINY_VAE, vae.params).items()
    })
    sd.update({
        f"conditioner.embedders.0.model.{k}": np.ascontiguousarray(v)
        for k, v in TestOpenCLIPConversion._openclip_layout(
            g_cfg, g_enc.params
        ).items()
    })
    ckpt = tmp_path / "refiner_ckpt.safetensors"
    save_file(sd, str(ckpt))
    tok_path = _word_level_tokenizer(tmp_path, monkeypatch)
    return {"ckpt": str(ckpt), "tok": tok_path}


class TestStockWorkflow:
    def _stock_workflow(self, ckpt):
        """API-format graph exactly as a stock ComfyUI export writes it:
        builtin class names, builtin input keys, [node, output] links."""
        return {
            "4": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": ckpt}},
            "5": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 2}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "a watercolor lighthouse at dawn",
                             "clip": ["4", 1]}},
            "7": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "blurry low quality",
                             "clip": ["4", 1]}},
            # seed beyond 2**63: stock seed widgets are 64-bit and the UI's
            # randomize fills [0, 2**64) — half of exported workflows carry a
            # seed jax.random.key would reject (ADVICE r3, folded by seed_key).
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": 2**63 + 7, "steps": 2, "cfg": 7.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0, "model": ["4", 0],
                             "positive": ["6", 0], "negative": ["7", 0],
                             "latent_image": ["5", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["4", 2]}},
            "9": {"class_type": "SaveImage",
                  "inputs": {"images": ["8", 0],
                             "filename_prefix": "ComfyUI"}},
        }

    def test_exported_stock_workflow_runs_unchanged(self, tmp_path, monkeypatch):
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = self._stock_workflow(paths["ckpt"])
        # SaveImage's stock form has no output_dir widget; point the TPU
        # node's default there via its own optional input (exported graphs
        # carry only filename_prefix — add output_dir like a host config).
        wf["9"]["inputs"]["output_dir"] = str(tmp_path / "out")

        out = run_workflow(wf)
        images = out["8"][0]
        assert images.shape[0] == 2 and images.shape[-1] == 3
        assert np.isfinite(np.asarray(images)).all()
        saved = out["9"][0]
        assert len(saved) == 2 and all(os.path.exists(p) for p in saved)

    def test_stock_conditioning_and_image_shims_run(self, tmp_path,
                                                    monkeypatch):
        # VERDICT r3 missing #4: regional prompting (SetArea → Combine),
        # prompt blending (Average), stock image resize, and PreviewImage —
        # one exported-style graph exercising all of them.
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = self._stock_workflow(paths["ckpt"])
        wf["9"]["inputs"]["output_dir"] = str(tmp_path / "out")
        wf.update({
            "10": {"class_type": "CLIPTextEncode",
                   "inputs": {"text": "blurry low quality",
                              "clip": ["4", 1]}},
            # Regional prompt: the second prompt scoped to the top-left 16px
            # (2 latent cells of the 32px graph), combined into the first.
            "11": {"class_type": "ConditioningSetArea",
                   "inputs": {"conditioning": ["10", 0], "width": 16,
                              "height": 16, "x": 0, "y": 0, "strength": 0.8}},
            "12": {"class_type": "ConditioningCombine",
                   "inputs": {"conditioning_1": ["6", 0],
                              "conditioning_2": ["11", 0]}},
            # Blend the two raw prompts too (exercises Average's lerp).
            "13": {"class_type": "ConditioningAverage",
                   "inputs": {"conditioning_to": ["12", 0],
                              "conditioning_from": ["10", 0],
                              "conditioning_to_strength": 0.7}},
            "14": {"class_type": "ImageScale",
                   "inputs": {"image": ["8", 0], "upscale_method": "bicubic",
                              "width": 48, "height": 40, "crop": "center"}},
            "15": {"class_type": "ImageScaleBy",
                   "inputs": {"image": ["8", 0],
                              "upscale_method": "lanczos", "scale_by": 0.5}},
            "16": {"class_type": "PreviewImage",
                   "inputs": {"images": ["14", 0]}},
        })
        wf["3"]["inputs"]["positive"] = ["13", 0]

        out = run_workflow(wf)
        assert np.isfinite(np.asarray(out["8"][0])).all()
        assert out["14"][0].shape[1:3] == (40, 48)
        h, w = np.asarray(out["8"][0]).shape[1:3]
        assert out["15"][0].shape[1:3] == (
            max(1, round(h * 0.5)), max(1, round(w * 0.5)))
        # Stock 0-sentinel: a zero dim keeps the source aspect ratio.
        from comfyui_parallelanything_tpu.nodes_compat import ImageScale

        (kept,) = ImageScale().upscale(
            np.zeros((1, 10, 20, 3), np.float32), "bilinear",
            width=40, height=0,
        )
        assert kept.shape[1:3] == (20, 40)
        with pytest.raises(ValueError, match="both be 0"):
            ImageScale().upscale(
                np.zeros((1, 10, 20, 3), np.float32), "bilinear",
                width=0, height=0,
            )
        previews = out["16"][0]
        assert previews and all(os.path.exists(p) for p in previews)
        assert all(os.sep + "temp" + os.sep in p for p in previews)

    def test_conditioning_zero_out_and_sdxl_encode(self, tmp_path,
                                                   monkeypatch):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        _, clip, _ = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )
        enc = NODE_CLASS_MAPPINGS["CLIPTextEncode"]()
        (cond,) = enc.run(clip=clip, text="a watercolor lighthouse")

        # ZeroOut: every embedding zeroed, extras included.
        zo = NODE_CLASS_MAPPINGS["ConditioningZeroOut"]()
        (z,) = zo.zero_out({**cond, "extras": (dict(cond),)})
        assert float(jnp.abs(z["context"]).max()) == 0.0
        assert float(jnp.abs(z["extras"][0]["context"]).max()) == 0.0
        assert z["context"].shape == cond["context"].shape

        # CLIPTextEncodeSDXL over a dual wire (same tiny tower as both L and
        # G — the shim's plumbing and the 2816-style size vector are what's
        # under test, not tower asymmetry).
        dual = {"type": "sdxl-dual", "l": clip, "g": clip}
        xl = NODE_CLASS_MAPPINGS["CLIPTextEncodeSDXL"]()
        (c,) = xl.encode(
            dual, width=512, height=768, crop_w=0, crop_h=0,
            target_width=1024, target_height=1024,
            text_g="a watercolor lighthouse", text_l="at dawn",
        )
        hidden = cond["penultimate"].shape[-1]
        assert c["context"].shape[-1] == 2 * hidden
        assert c["pooled"].shape[-1] == cond["pooled"].shape[-1] + 6 * 256
        with pytest.raises(ValueError, match="dual"):
            xl.encode(clip, 512, 512, 0, 0, 512, 512, "a", "b")

    def test_models_dir_resolution(self, tmp_path, monkeypatch):
        # ComfyUI folder layout: a bare name resolves via
        # $PA_MODELS_DIR/checkpoints/<name>.
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        models = tmp_path / "models" / "checkpoints"
        models.mkdir(parents=True)
        os.rename(paths["ckpt"], models / "tiny.safetensors")
        monkeypatch.setenv("PA_MODELS_DIR", str(tmp_path / "models"))

        wf = self._stock_workflow("tiny.safetensors")
        del wf["9"]  # no image save needed for the resolution check
        out = run_workflow(wf)
        assert out["8"][0].shape[0] == 2

    def test_clip_set_last_layer_tags_wire(self, tmp_path, monkeypatch):
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = self._stock_workflow(paths["ckpt"])
        del wf["9"]
        wf["10"] = {"class_type": "CLIPSetLastLayer",
                    "inputs": {"clip": ["4", 1], "stop_at_clip_layer": -2}}
        wf["6"]["inputs"]["clip"] = ["10", 0]
        out = run_workflow(wf)
        assert np.isfinite(np.asarray(out["8"][0])).all()

    def test_missing_tokenizer_fails_with_instructions(self, tmp_path,
                                                       monkeypatch):
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.delenv("PA_TOKENIZER_JSON")
        wf = self._stock_workflow(paths["ckpt"])
        with pytest.raises(Exception, match="PA_TOKENIZER_JSON"):
            run_workflow(wf)

    def test_stock_custom_sampling_graph_executes(self, tmp_path, monkeypatch):
        # The custom-sampling path exactly as a stock FLUX-style export wires
        # it: RandomNoise + KSamplerSelect + BasicScheduler + CFGGuider +
        # SamplerCustomAdvanced under their stock names and stock input keys.
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = {
            "ckpt": {"class_type": "CheckpointLoaderSimple",
                     "inputs": {"ckpt_name": paths["ckpt"]}},
            "pos": {"class_type": "CLIPTextEncode",
                    "inputs": {"text": "a watercolor lighthouse",
                               "clip": ["ckpt", 1]}},
            "neg": {"class_type": "CLIPTextEncode",
                    "inputs": {"text": "blurry", "clip": ["ckpt", 1]}},
            "latent": {"class_type": "EmptyLatentImage",
                       "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "noise": {"class_type": "RandomNoise",
                      "inputs": {"noise_seed": 11}},
            "sel": {"class_type": "KSamplerSelect",
                    "inputs": {"sampler_name": "euler"}},
            "sig": {"class_type": "BasicScheduler",
                    "inputs": {"model": ["ckpt", 0], "scheduler": "normal",
                               "steps": 2, "denoise": 1.0}},
            "guide": {"class_type": "CFGGuider",
                      "inputs": {"model": ["ckpt", 0], "positive": ["pos", 0],
                                 "negative": ["neg", 0], "cfg": 3.0}},
            "run": {"class_type": "SamplerCustomAdvanced",
                    "inputs": {"noise": ["noise", 0], "guider": ["guide", 0],
                               "sampler": ["sel", 0], "sigmas": ["sig", 0],
                               "latent_image": ["latent", 0]}},
            "dec": {"class_type": "VAEDecode",
                    "inputs": {"samples": ["run", 0], "vae": ["ckpt", 2]}},
        }
        out = run_workflow(wf)
        images = out["dec"][0]
        assert images.shape[0] == 1 and images.shape[-1] == 3
        assert np.isfinite(np.asarray(images)).all()

    def test_latent_upscale_absolute_dims(self, tmp_path, monkeypatch):
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        lat = {"samples": np.zeros((1, 8, 8, 4), np.float32)}
        node = NODE_CLASS_MAPPINGS["LatentUpscale"]()
        (out,) = node.upscale(lat, "bilinear", width=128, height=128)
        # 128 px -> 16 latent; from 8 -> scale 2.
        assert out["samples"].shape == (1, 16, 16, 4)
        # Width-only change must NOT no-op: axes scale independently.
        (wide,) = node.upscale(lat, "bilinear", width=192, height=64)
        assert wide["samples"].shape == (1, 8, 24, 4)

    def test_lora_loader_rebakes_from_source(self, tmp_path, monkeypatch):
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.models import load_safetensors
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        model, clip, vae = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )

        # Rank-2 kohya LoRA against a real attention projection of the tiny
        # checkpoint (bake_lora matches the stripped ldm key).
        sd = load_safetensors(paths["ckpt"])
        target = next(
            k for k in sd
            if k.endswith("attn1.to_q.weight") and "input_blocks" in k
        ).removeprefix("model.diffusion_model.")
        out_d, in_d = sd[f"model.diffusion_model.{target}"].shape
        rng = np.random.default_rng(5)
        lora_path = tmp_path / "style.safetensors"
        save_file({
            f"{target.removesuffix('.weight')}.lora_down.weight":
                rng.standard_normal((2, in_d)).astype(np.float32),
            f"{target.removesuffix('.weight')}.lora_up.weight":
                rng.standard_normal((out_d, 2)).astype(np.float32),
        }, str(lora_path))

        node = NODE_CLASS_MAPPINGS["LoraLoader"]()
        patched, clip_out = node.load_lora(model, clip, str(lora_path), 1.0, 1.0)
        assert clip_out is clip
        import jax

        base = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(model.params)]
        )
        new = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(patched.params)]
        )
        assert base.shape == new.shape and not np.allclose(base, new)

        # Zero strength bakes nothing.
        zero, _ = node.load_lora(model, clip, str(lora_path), 0.0, 1.0)
        znew = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(zero.params)]
        )
        np.testing.assert_allclose(znew, base, rtol=1e-6, atol=1e-6)

        # Stacking: chained LoraLoaders compose — two strength-1 bakes of the
        # same LoRA equal one strength-2 bake (deltas are linear in strength).
        stacked, _ = node.load_lora(patched, clip, str(lora_path), 1.0, 1.0)
        snew = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(stacked.params)]
        )
        assert not np.allclose(snew, new)
        twice, _ = node.load_lora(model, clip, str(lora_path), 2.0, 1.0)
        tnew = np.concatenate(
            [np.ravel(v) for v in jax.tree.leaves(twice.params)]
        )
        np.testing.assert_allclose(snew, tnew, rtol=1e-4, atol=1e-5)

        # Untagged models and missing files fail with instructions
        # (an absent LoRA must never silently return an unpatched model).
        with pytest.raises(ValueError, match="CheckpointLoaderSimple"):
            node.load_lora(object(), clip, str(lora_path), 1.0, 1.0)
        with pytest.raises(ValueError, match="not found"):
            node.load_lora(model, clip, "", 1.0, 1.0)
        with pytest.raises(ValueError, match="not found"):
            node.load_lora(model, clip, "ghost.safetensors", 1.0, 1.0)

    def test_lora_loader_strength_clip_bakes_text_tower(self, tmp_path,
                                                        monkeypatch):
        # A LoRA with kohya lora_te_* keys must rebuild the CLIP wire with the
        # deltas baked into the bundled tower (ADVICE/VERDICT r3: the
        # strength_clip divergence closed).
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.models import load_safetensors
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        model, clip, _ = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )
        sd = load_safetensors(paths["ckpt"])
        target = next(
            k for k in sd
            if k.startswith("cond_stage_model.") and
            k.endswith("self_attn.q_proj.weight")
        )
        out_d, in_d = sd[target].shape
        base_name = (
            target.removeprefix("cond_stage_model.transformer.")
            .removesuffix(".weight").replace(".", "_")
        )
        rng = np.random.default_rng(9)
        lora_path = tmp_path / "te.safetensors"
        save_file({
            f"lora_te_{base_name}.lora_down.weight":
                rng.standard_normal((2, in_d)).astype(np.float32),
            f"lora_te_{base_name}.lora_up.weight":
                rng.standard_normal((out_d, 2)).astype(np.float32),
        }, str(lora_path))

        node = NODE_CLASS_MAPPINGS["LoraLoader"]()
        import jax

        def flat(wire):
            return np.concatenate([
                np.ravel(np.asarray(v, np.float32))
                for v in jax.tree.leaves(wire["encoder"].params)
            ])

        _, clip_out = node.load_lora(model, clip, str(lora_path), 1.0, 1.0)
        assert clip_out is not clip
        assert not np.allclose(flat(clip_out), flat(clip))
        # strength_clip=0 leaves the wire untouched (identity, no rebuild).
        _, clip_zero = node.load_lora(model, clip, str(lora_path), 1.0, 0.0)
        assert clip_zero is clip
        # Upstream wire state (CLIPSetLastLayer's tag) survives the rebuild.
        _, clip_keep = node.load_lora(
            model, {**clip, "clip_skip": 2}, str(lora_path), 1.0, 1.0
        )
        assert clip_keep["clip_skip"] == 2
        assert not np.allclose(flat(clip_keep), flat(clip))
        # A CLIP wire NOT from this checkpoint's bundled towers (no
        # source_ckpt tag — e.g. DualCLIPLoader) is never clobbered by the
        # rebuild; te deltas are skipped with a warning instead.
        external = {k: v for k, v in clip.items() if k != "source_ckpt"}
        _, clip_ext = node.load_lora(model, external, str(lora_path), 1.0, 1.0)
        assert clip_ext is external

    def test_lora_loader_attaches_serving_delegate(self, tmp_path,
                                                    monkeypatch):
        # Round 16 (universal lane batching): a clean 2-D LoRA bake carries a
        # serving delegate — (unpatched base, extracted factors) — so the
        # sampler can submit LoRA traffic as per-lane state of the BASE
        # model's bucket. The delegate's eager merge must reproduce the bake.
        import jax
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.models import load_safetensors
        from comfyui_parallelanything_tpu.models.lora import merge_lora_params
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS
        from comfyui_parallelanything_tpu.nodes import _split_lora_delegate

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        model, clip, _ = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )
        sd = load_safetensors(paths["ckpt"])
        target = next(
            k for k in sd
            if k.endswith("attn1.to_q.weight") and "input_blocks" in k
        ).removeprefix("model.diffusion_model.")
        out_d, in_d = sd[f"model.diffusion_model.{target}"].shape
        rng = np.random.default_rng(5)
        lora_path = tmp_path / "style.safetensors"
        save_file({
            f"{target.removesuffix('.weight')}.lora_down.weight":
                rng.standard_normal((2, in_d)).astype(np.float32),
            f"{target.removesuffix('.weight')}.lora_up.weight":
                rng.standard_normal((out_d, 2)).astype(np.float32),
        }, str(lora_path))

        node = NODE_CLASS_MAPPINGS["LoraLoader"]()
        patched, _ = node.load_lora(model, clip, str(lora_path), 1.0, 1.0)
        delegate = patched.lora_delegate
        assert delegate is not None
        assert delegate["base"] is model  # bucket identity == plain traffic
        # Factor merge on the base == the bake (this env's XLA CPU matmuls
        # run at bf16 scale — CLAUDE.md tolerance discipline).
        merged = merge_lora_params(model.params, delegate["factors"])
        for a, b in zip(jax.tree.leaves(merged),
                        jax.tree.leaves(patched.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=1e-4)
        # Chained links accumulate into ONE delegate against the same base.
        stacked, _ = node.load_lora(patched, clip, str(lora_path), 1.0, 1.0)
        assert stacked.lora_delegate["base"] is model
        merged2 = merge_lora_params(model.params,
                                    stacked.lora_delegate["factors"])
        for a, b in zip(jax.tree.leaves(merged2),
                        jax.tree.leaves(stacked.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=1e-4)

        # The sampler split: plain positive engages the delegate; inpaint
        # state (which the factor recompose can't thread) keeps the bake.
        got_model, got_lora = _split_lora_delegate(patched, {})
        assert got_model is model and got_lora is delegate["factors"]
        keep_model, keep_lora = _split_lora_delegate(
            patched, {"inpaint": {"mask": None, "masked_latent": None}}
        )
        assert keep_model is patched and keep_lora is None

        # A pair the bake itself skips (no UNet match) doesn't block the
        # delegate: factorization works off the WEIGHT DELTA, so whatever
        # the bake applied is exactly what the factors carry.
        ghost_path = tmp_path / "ghost.safetensors"
        save_file({
            f"{target.removesuffix('.weight')}.lora_down.weight":
                rng.standard_normal((2, in_d)).astype(np.float32),
            f"{target.removesuffix('.weight')}.lora_up.weight":
                rng.standard_normal((out_d, 2)).astype(np.float32),
            "ghost_block.lora_down.weight":
                rng.standard_normal((2, 8)).astype(np.float32),
            "ghost_block.lora_up.weight":
                rng.standard_normal((8, 2)).astype(np.float32),
        }, str(ghost_path))
        ghosted, _ = node.load_lora(model, clip, str(ghost_path), 1.0, 1.0)
        assert ghosted.lora_delegate is not None
        merged3 = merge_lora_params(model.params,
                                    ghosted.lora_delegate["factors"])
        for a, b in zip(jax.tree.leaves(merged3),
                        jax.tree.leaves(ghosted.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=1e-4)

    def test_save_image_defaults_to_pa_output_dir(self, tmp_path, monkeypatch):
        # Stock exports carry only filename_prefix; images must land in the
        # host-configured root (the one the API server serves /view from).
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "served"))
        node = NODE_CLASS_MAPPINGS["SaveImage"]()
        (paths,) = node.run(
            images=np.zeros((1, 8, 8, 3), np.float32), filename_prefix="x"
        )
        assert all(p.startswith(str(tmp_path / "served")) for p in paths)


class TestKSamplerAdvanced:
    """Stock KSamplerAdvanced semantics: step-window runs, leftover noise,
    add_noise-disabled continuation (the SDXL base→refiner template driver)."""

    def _toy(self):
        # Deterministic eps-style toy model (no params): enough for exact
        # split-vs-full trajectory equality under euler.
        return lambda x, t, context=None, **kw: x * 0.05

    def _conds(self):
        import jax.numpy as jnp

        return ({"context": jnp.zeros((1, 3, 5))},
                {"context": jnp.zeros((1, 3, 5))})

    def test_split_run_matches_full_window(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes import TPUKSamplerAdvanced

        pos, neg = self._conds()
        lat = {"samples": jnp.zeros((1, 8, 8, 4))}
        node = TPUKSamplerAdvanced()
        kw = dict(noise_seed=3, steps=4, cfg=1.0, sampler_name="euler",
                  scheduler="normal", positive=pos, negative=neg)
        (full,) = node.sample(
            self._toy(), add_noise="enable", latent_image=lat,
            start_at_step=0, end_at_step=10000,
            return_with_leftover_noise="disable", **kw,
        )
        (base,) = node.sample(
            self._toy(), add_noise="enable", latent_image=lat,
            start_at_step=0, end_at_step=2,
            return_with_leftover_noise="enable", **kw,
        )
        (cont,) = node.sample(
            self._toy(), add_noise="disable", latent_image=base,
            start_at_step=2, end_at_step=10000,
            return_with_leftover_noise="disable", **kw,
        )
        np.testing.assert_allclose(
            np.asarray(cont["samples"]), np.asarray(full["samples"]),
            rtol=1e-5, atol=1e-6,
        )
        # The base half still carries noise (sigma[2] > 0): it must differ
        # from the fully-denoised run.
        assert not np.allclose(
            np.asarray(base["samples"]), np.asarray(full["samples"])
        )

    def test_force_full_denoise_on_short_window(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes import TPUKSamplerAdvanced

        pos, neg = self._conds()
        lat = {"samples": jnp.zeros((1, 8, 8, 4))}
        node = TPUKSamplerAdvanced()
        kw = dict(noise_seed=3, steps=4, cfg=1.0, sampler_name="euler",
                  scheduler="normal", positive=pos, negative=neg,
                  add_noise="enable", latent_image=lat, start_at_step=0,
                  end_at_step=2)
        (leftover,) = node.sample(
            self._toy(), return_with_leftover_noise="enable", **kw
        )
        (forced,) = node.sample(
            self._toy(), return_with_leftover_noise="disable", **kw
        )
        assert not np.allclose(
            np.asarray(leftover["samples"]), np.asarray(forced["samples"])
        )

    def test_empty_window_returns_latent(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes import TPUKSamplerAdvanced

        pos, neg = self._conds()
        lat = {"samples": jnp.ones((1, 8, 8, 4))}
        (out,) = TPUKSamplerAdvanced().sample(
            self._toy(), add_noise="enable", noise_seed=0, steps=4, cfg=1.0,
            sampler_name="euler", scheduler="normal", positive=pos,
            negative=neg, latent_image=lat, start_at_step=3, end_at_step=3,
            return_with_leftover_noise="disable",
        )
        np.testing.assert_array_equal(
            np.asarray(out["samples"]), np.asarray(lat["samples"])
        )

    def test_base_refiner_template_runs_unchanged(self, tmp_path, monkeypatch):
        """The stock SDXL base→refiner API export shape — two checkpoint
        loaders, four text encodes, chained KSamplerAdvanced — runs as-is
        (the tiny sd15 synthetic checkpoint stands in for both stages; the
        node surface and window semantics are family-independent)."""
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = {
            "4": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": paths["ckpt"]}},
            "12": {"class_type": "CheckpointLoaderSimple",
                   "inputs": {"ckpt_name": paths["ckpt"]}},
            "5": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "a watercolor lighthouse", "clip": ["4", 1]}},
            "7": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "blurry", "clip": ["4", 1]}},
            "15": {"class_type": "CLIPTextEncode",
                   "inputs": {"text": "a watercolor lighthouse",
                              "clip": ["12", 1]}},
            "16": {"class_type": "CLIPTextEncode",
                   "inputs": {"text": "blurry", "clip": ["12", 1]}},
            "10": {"class_type": "KSamplerAdvanced",
                   "inputs": {"add_noise": "enable", "noise_seed": 721897,
                              "steps": 4, "cfg": 2.0,
                              "sampler_name": "euler", "scheduler": "normal",
                              "start_at_step": 0, "end_at_step": 2,
                              "return_with_leftover_noise": "enable",
                              "model": ["4", 0], "positive": ["6", 0],
                              "negative": ["7", 0], "latent_image": ["5", 0]}},
            "11": {"class_type": "KSamplerAdvanced",
                   "inputs": {"add_noise": "disable", "noise_seed": 0,
                              "steps": 4, "cfg": 2.0,
                              "sampler_name": "euler", "scheduler": "normal",
                              "start_at_step": 2, "end_at_step": 10000,
                              "return_with_leftover_noise": "disable",
                              "model": ["12", 0], "positive": ["15", 0],
                              "negative": ["16", 0],
                              "latent_image": ["10", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["11", 0], "vae": ["12", 2]}},
            "9": {"class_type": "SaveImage",
                  "inputs": {"images": ["8", 0], "filename_prefix": "refined",
                             "output_dir": str(tmp_path / "out")}},
        }
        out = run_workflow(wf)
        assert np.isfinite(np.asarray(out["8"][0])).all()
        assert all(os.path.exists(p) for p in out["9"][0])


class TestNewStockLoaders:
    def test_unet_loader_bare_diffusion_file(self, tmp_path, monkeypatch):
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.models import load_safetensors
        from comfyui_parallelanything_tpu.nodes_compat import UNETLoader

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        sd = load_safetensors(paths["ckpt"])
        bare = {
            k.removeprefix("model.diffusion_model."): np.ascontiguousarray(v)
            for k, v in sd.items()
            if k.startswith("model.diffusion_model.")
        }
        unet_path = tmp_path / "unet_only.safetensors"
        save_file(bare, str(unet_path))
        (model,) = UNETLoader().load_unet(str(unet_path))
        assert model.source["family"] == "sd15"
        assert hasattr(model, "apply") and hasattr(model, "params")

    def test_lora_loader_model_only(self, tmp_path, monkeypatch):
        import jax
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.models import load_safetensors
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        model, clip, _ = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )
        sd = load_safetensors(paths["ckpt"])
        target = next(
            k for k in sd
            if k.endswith("attn1.to_q.weight") and "input_blocks" in k
        ).removeprefix("model.diffusion_model.")
        out_d, in_d = sd[f"model.diffusion_model.{target}"].shape
        rng = np.random.default_rng(6)
        lora_path = tmp_path / "style.safetensors"
        save_file({
            f"{target.removesuffix('.weight')}.lora_down.weight":
                rng.standard_normal((2, in_d)).astype(np.float32),
            f"{target.removesuffix('.weight')}.lora_up.weight":
                rng.standard_normal((out_d, 2)).astype(np.float32),
        }, str(lora_path))
        node = NODE_CLASS_MAPPINGS["LoraLoaderModelOnly"]()
        (patched,) = node.load_lora_model_only(model, str(lora_path), 1.0)

        def flat(m):
            return np.concatenate(
                [np.ravel(v) for v in jax.tree.leaves(m.params)]
            )

        assert not np.allclose(flat(patched), flat(model))

    def test_vae_loader_image_layout(self, tmp_path, monkeypatch):
        import jax
        import jax.numpy as jnp
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.nodes_compat import VAELoader
        from tests.test_vae import TINY as TINY_VAE, _ldm_layout_sd
        from comfyui_parallelanything_tpu.models import build_vae

        vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
        vae_path = tmp_path / "ext_vae.safetensors"
        save_file(
            {k: np.ascontiguousarray(v)
             for k, v in _ldm_layout_sd(TINY_VAE, vae.params).items()},
            str(vae_path),
        )
        # The tiny config must be what sniffing resolves: pin it.
        import comfyui_parallelanything_tpu.models as models_pkg

        monkeypatch.setattr(models_pkg, "sd_vae_config", lambda: TINY_VAE)
        import comfyui_parallelanything_tpu.models.loader as loader_mod

        monkeypatch.setattr(
            loader_mod, "sniff_vae_config", lambda sd: TINY_VAE
        )
        (loaded,) = VAELoader().load(str(vae_path))
        z = loaded.encode(jnp.zeros((1, 16, 16, 3)), None)
        assert z.shape[-1] == TINY_VAE.z_channels

    def test_vae_loader_routes_wan_video_layout(self, tmp_path, monkeypatch):
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.nodes_compat import VAELoader
        import comfyui_parallelanything_tpu.models.loader as loader_mod

        path = tmp_path / "wan_vae.safetensors"
        save_file(
            {"decoder.upsamples.0.residual.0.gamma":
                 np.zeros((4, 1, 1, 1), np.float32)},
            str(path),
        )
        seen = {}

        def fake_load(p, cfg=None):
            seen["path"] = p
            return "video-vae"

        monkeypatch.setattr(loader_mod, "load_wan_vae_checkpoint", fake_load)
        (out,) = VAELoader().load(str(path))
        assert out == "video-vae" and seen["path"] == str(path)

    def test_vae_loader_missing_file(self):
        from comfyui_parallelanything_tpu.nodes_compat import VAELoader

        with pytest.raises(ValueError, match="not found"):
            VAELoader().load("ghost_vae.safetensors")

    def test_clip_loader_single_tower(self, tmp_path, monkeypatch):
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.nodes_compat import CLIPLoader
        import comfyui_parallelanything_tpu.models.text_encoders as te_mod
        from tests.test_text_encoders import TINY_CLIP, _hf_clip

        _synthetic_stock_env(tmp_path, monkeypatch)  # tokenizer env
        monkeypatch.setattr(te_mod, "clip_l_config", lambda: TINY_CLIP)
        hf = _hf_clip(TINY_CLIP, "quick_gelu")
        enc_path = tmp_path / "clip_l.safetensors"
        save_file(
            {k: np.ascontiguousarray(v.detach().numpy())
             for k, v in hf.state_dict().items()},
            str(enc_path),
        )
        (wire,) = CLIPLoader().load(str(enc_path), type="stable_diffusion")
        assert wire["encoder"] is not None and wire["tokenizer"] is not None

    def test_clip_loader_wan_needs_t5_tokenizer(self, monkeypatch):
        from comfyui_parallelanything_tpu.nodes_compat import CLIPLoader

        monkeypatch.delenv("PA_T5_TOKENIZER_JSON", raising=False)
        with pytest.raises(ValueError, match="PA_T5_TOKENIZER_JSON"):
            CLIPLoader().load("umt5_xxl.safetensors", type="wan")


class TestUnclip:
    def test_sniff_sd21_unclip(self):
        sd = {
            "input_blocks.0.0.weight": np.zeros((1, 4)),
            "label_emb.0.0.weight": np.zeros((1024, 2048)),
            "input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight":
                np.zeros((320, 1024)),
        }
        assert sniff_model_family(sd) == "sd21-unclip"
        # SDXL keeps sniffing sdxl (no transformer at input_blocks.1).
        sdxl = {"input_blocks.0.0.weight": np.zeros((1, 4)),
                "label_emb.0.0.weight": np.zeros((1, 2816))}
        assert sniff_model_family(sdxl) == "sdxl"

    def test_unclip_adm_vector(self):
        from comfyui_parallelanything_tpu.models.unet import unclip_adm

        tags = [{"embeds": np.ones((1, 24), np.float32), "strength": 1.0,
                 "noise_augmentation": 0.0}]
        y = unclip_adm(tags, 32)
        assert y.shape == (1, 32)
        # Zero augmentation at level 0 still q_samples with sqrt(acp[0])~1:
        # the embed half stays close to the input, the level half is the
        # sinusoidal embedding of 0.
        assert np.allclose(np.asarray(y[:, :24]), 1.0, atol=0.05)
        # Strength scales the whole vector.
        y2 = unclip_adm([{**tags[0], "strength": 2.0}], 32)
        np.testing.assert_allclose(
            np.asarray(y2), 2 * np.asarray(y), rtol=1e-5
        )
        # Multiple tags merge (re-augmented sum) without shape drift.
        y3 = unclip_adm(tags + [{**tags[0], "noise_augmentation": 0.5}], 32)
        assert y3.shape == (1, 32) and np.isfinite(np.asarray(y3)).all()

    def test_unclip_conditioning_node_tags_and_samples(self):
        import jax
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.models import build_unet, sd15_config
        from comfyui_parallelanything_tpu.nodes import TPUKSampler
        from comfyui_parallelanything_tpu.nodes_compat import unCLIPConditioning

        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
            attention_levels=(0, 1), context_dim=16, num_heads=4,
            norm_groups=8, adm_in_channels=32, prediction="v",
            dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        cvo = {"image_embeds": jnp.ones((1, 24)), "last_hidden": None,
               "penultimate": None}
        pos = {"context": jnp.zeros((1, 3, 16))}
        (tagged,) = unCLIPConditioning().apply_adm(pos, cvo, 1.0, 0.2)
        assert len(tagged["unclip"]) == 1
        # Chaining stacks.
        (tagged2,) = unCLIPConditioning().apply_adm(tagged, cvo, 0.5, 0.0)
        assert len(tagged2["unclip"]) == 2
        neg = {"context": jnp.zeros((1, 3, 16))}
        (out,) = TPUKSampler().sample(
            model, tagged, {"samples": jnp.zeros((2, 8, 8, 4))}, seed=1,
            steps=2, cfg=3.0, sampler_name="euler", scheduler="normal",
            negative=neg,
        )
        assert out["samples"].shape == (2, 8, 8, 4)
        assert np.isfinite(np.asarray(out["samples"])).all()


def _synthetic_wan_env(tmp_path, monkeypatch):
    """Tiny WAN i2v world for the stock template: bare DiT file (official
    Wan2.x layout incl. the img_emb CLIP branch), official-layout video VAE,
    UMT5 encoder + tokenizer.json, HF-layout CLIP-vision tower, start image —
    all wired through the same env vars / preset monkeypatches the shims read."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from PIL import Image
    from safetensors.numpy import save_file

    import comfyui_parallelanything_tpu.models as models_pkg
    import comfyui_parallelanything_tpu.models.video_vae as vv_mod
    from comfyui_parallelanything_tpu.models.wan import WanConfig, build_wan
    from tests.test_convert_wan import _official_layout_sd
    from tests.test_golden_video_vae import CFG as VCFG, TWanVAE
    from tests.test_text_encoders import TINY_T5
    from tests.test_vision import TINY as TINY_VIS, _hf_vision

    import torch

    # -- WAN i2v DiT (official layout, CLIP branch) -------------------------
    zc = VCFG.z_channels
    wcfg = WanConfig(
        in_channels=2 * zc + 4, out_channels=zc, hidden_size=48, ffn_dim=96,
        num_heads=4, depth=2, text_dim=TINY_T5.d_model, freq_dim=16,
        img_dim=TINY_VIS.hidden_size, dtype=jnp.float32,
    )
    dit = build_wan(
        wcfg, jax.random.key(0), sample_shape=(1, 2, 4, 4, 2 * zc + 4),
        txt_len=6,
    )
    dit_path = tmp_path / "wan_i2v_tiny.safetensors"
    save_file(
        {k: np.ascontiguousarray(v)
         for k, v in _official_layout_sd(wcfg, dit.params).items()},
        str(dit_path),
    )
    # The loader's family preset; in_channels/img_dim re-sniff off the file.
    base_cfg = dataclasses.replace(wcfg, in_channels=zc, img_dim=None)
    monkeypatch.setattr(models_pkg, "wan_1_3b_config", lambda: base_cfg)

    # -- WAN t2v DiT (bare-latent input, no CLIP branch) --------------------
    dit_t2v = build_wan(
        base_cfg, jax.random.key(7), sample_shape=(1, 2, 4, 4, zc), txt_len=6
    )
    t2v_path = tmp_path / "wan_t2v_tiny.safetensors"
    save_file(
        {k: np.ascontiguousarray(v)
         for k, v in _official_layout_sd(base_cfg, dit_t2v.params).items()},
        str(t2v_path),
    )

    # -- video VAE (official torch layout) ----------------------------------
    torch.manual_seed(11)
    tvae = TWanVAE(VCFG).eval()
    vae_path = tmp_path / "wan_vae_tiny.safetensors"
    save_file(
        {k: np.ascontiguousarray(v.detach().numpy())
         for k, v in tvae.state_dict().items()},
        str(vae_path),
    )
    monkeypatch.setattr(vv_mod, "wan_vae_config", lambda: VCFG)

    # -- UMT5 text encoder + tokenizer --------------------------------------
    import transformers

    t5_cfg = dataclasses.replace(TINY_T5, per_layer_bias=True)
    hf_cfg = transformers.UMT5Config(
        vocab_size=t5_cfg.vocab_size, d_model=t5_cfg.d_model,
        d_kv=t5_cfg.d_kv, d_ff=t5_cfg.d_ff, num_layers=t5_cfg.num_layers,
        num_heads=t5_cfg.num_heads,
        relative_attention_num_buckets=t5_cfg.relative_buckets,
        relative_attention_max_distance=t5_cfg.relative_max_distance,
        feed_forward_proj="gated-gelu", dropout_rate=0.0,
    )
    torch.manual_seed(1)
    hf_t5 = transformers.UMT5EncoderModel(hf_cfg).eval()
    umt5_path = tmp_path / "umt5_tiny.safetensors"
    save_file(
        {k: np.ascontiguousarray(v.detach().numpy())
         for k, v in hf_t5.state_dict().items()},
        str(umt5_path),
    )
    monkeypatch.setattr(models_pkg, "umt5_xxl_config", lambda: t5_cfg)

    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {"[UNK]": 0, "</s>": 1, "a": 5, "cat": 6, "walking": 7,
             "blurry": 8}
    t = tokenizers.Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
    t.pre_tokenizer = Whitespace()
    tok_path = tmp_path / "t5_tokenizer.json"
    t.save(str(tok_path))
    monkeypatch.setenv("PA_T5_TOKENIZER_JSON", str(tok_path))

    # -- CLIP vision tower (HF layout) --------------------------------------
    vis_path = tmp_path / "clip_vision_tiny.safetensors"
    hf_vis = _hf_vision(TINY_VIS, "quick_gelu")
    save_file(
        {k: np.ascontiguousarray(v.detach().numpy())
         for k, v in hf_vis.state_dict().items()},
        str(vis_path),
    )

    # -- start image ---------------------------------------------------------
    img_path = tmp_path / "start.png"
    Image.fromarray(
        (np.full((16, 16, 3), 0.5) * 255).astype(np.uint8)
    ).save(str(img_path))
    monkeypatch.setenv("PA_INPUT_DIR", str(tmp_path))

    return {
        "dit": str(dit_path), "dit_t2v": str(t2v_path),
        "vae": str(vae_path), "umt5": str(umt5_path),
        "vision": str(vis_path), "image": "start.png",
    }


class TestStockWanI2VWorkflow:
    def test_wan_i2v_template_runs_unchanged(self, tmp_path, monkeypatch):
        """The stock WAN image-to-video API export shape — UNETLoader +
        CLIPLoader(wan) + VAELoader + CLIPVisionLoader/Encode +
        WanImageToVideo + KSampler + VAEDecode + SaveAnimatedWEBP — runs
        as-is on the tiny synthetic WAN i2v world."""
        paths = _synthetic_wan_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = {
            "37": {"class_type": "UNETLoader",
                   "inputs": {"unet_name": paths["dit"],
                              "weight_dtype": "default"}},
            "38": {"class_type": "CLIPLoader",
                   "inputs": {"clip_name": paths["umt5"], "type": "wan"}},
            "39": {"class_type": "VAELoader",
                   "inputs": {"vae_name": paths["vae"]}},
            "49": {"class_type": "CLIPVisionLoader",
                   "inputs": {"clip_name": paths["vision"]}},
            "52": {"class_type": "LoadImage",
                   "inputs": {"image": paths["image"]}},
            "51": {"class_type": "CLIPVisionEncode",
                   "inputs": {"clip_vision": ["49", 0], "image": ["52", 0],
                              "crop": "none"}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "a cat walking", "clip": ["38", 0]}},
            "7": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "blurry", "clip": ["38", 0]}},
            "50": {"class_type": "WanImageToVideo",
                   "inputs": {"positive": ["6", 0], "negative": ["7", 0],
                              "vae": ["39", 0], "width": 16, "height": 16,
                              "length": 5, "batch_size": 1,
                              "clip_vision_output": ["51", 0],
                              "start_image": ["52", 0]}},
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": 7, "steps": 2, "cfg": 1.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0, "model": ["37", 0],
                             "positive": ["50", 0], "negative": ["50", 1],
                             "latent_image": ["50", 2]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["39", 0]}},
            "28": {"class_type": "SaveAnimatedWEBP",
                   "inputs": {"images": ["8", 0], "fps": 8.0,
                              "filename_prefix": "wan_i2v"}},
        }
        out = run_workflow(wf)
        video = np.asarray(out["8"][0])
        assert video.shape == (1, 5, 16, 16, 3) or video.shape == (5, 16, 16, 3)
        assert np.isfinite(video).all()
        assert all(os.path.exists(p) for p in out["28"][0])


class TestUnclipCheckpointLoader:
    def test_unclip_single_file_loads_all_four_wires(self, tmp_path,
                                                     monkeypatch):
        """A synthetic sd21-unclip single file — v-pred UNet with label_emb +
        1024-ctx, OpenCLIP-H text tower, VAE, AND the OpenCLIP-layout ViT
        image encoder under embedder.model.visual.* — loads through
        unCLIPCheckpointLoader into MODEL/CLIP/VAE/CLIP_VISION, and the
        vision wire encodes an image into CLIP_VISION_OUTPUT."""
        import jax
        import jax.numpy as jnp
        from safetensors.numpy import save_file

        import comfyui_parallelanything_tpu.models as models_pkg
        from comfyui_parallelanything_tpu.models import build_unet, build_vae
        from comfyui_parallelanything_tpu.models.text_encoders import (
            build_clip_text,
            open_clip_h_config,
        )
        from comfyui_parallelanything_tpu.models.vision import (
            CLIPVisionConfig,
            build_clip_vision,
        )
        from comfyui_parallelanything_tpu.nodes_compat import (
            CLIPVisionEncode,
            unCLIPCheckpointLoader,
        )
        from tests.test_convert_unet import _ldm_sd
        from tests.test_text_encoders import TestOpenCLIPConversion
        from tests.test_vae import TINY as TINY_VAE, _ldm_layout_sd
        from tests.test_vision import _openclip_visual_sd

        # Text tower must be 1024-wide: the UNet's ctx width IS the sniff key.
        h_cfg = open_clip_h_config(
            vocab_size=100, hidden_size=1024, num_layers=1, num_heads=8,
            max_len=16, intermediate_size=64, projection_dim=32,
            dtype=jnp.float32,
        )
        monkeypatch.setattr(models_pkg, "open_clip_h_config", lambda: h_cfg)
        monkeypatch.setattr(models_pkg, "sd_vae_config", lambda: TINY_VAE)
        real_sd21 = models_pkg.sd21_config

        def tiny_sd21(**kw):
            kw.pop("prediction", None)
            return real_sd21(
                model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
                attention_levels=(0, 1), transformer_depth=(1, 1),
                num_heads=4, context_dim=h_cfg.hidden_size, norm_groups=8,
                prediction="v", dtype=jnp.float32, **kw,
            )

        monkeypatch.setattr(models_pkg, "sd21_config", tiny_sd21)

        ucfg = tiny_sd21(adm_in_channels=48)
        unet = build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
        te = build_clip_text(h_cfg, rng=jax.random.key(2))
        v_cfg = CLIPVisionConfig(
            image_size=28, patch_size=7, hidden_size=32, num_layers=2,
            num_heads=4, intermediate_size=64, act="gelu",
            projection_dim=24, dtype=jnp.float32,
        )
        venc = build_clip_vision(v_cfg, rng=jax.random.key(3))

        sd = {
            f"model.diffusion_model.{k}": np.ascontiguousarray(v)
            for k, v in _ldm_sd(ucfg, unet.params).items()
        }
        sd.update({
            f"first_stage_model.{k}": np.ascontiguousarray(v)
            for k, v in _ldm_layout_sd(TINY_VAE, vae.params).items()
        })
        sd.update({
            f"cond_stage_model.model.{k}": np.ascontiguousarray(v)
            for k, v in TestOpenCLIPConversion._openclip_layout(
                h_cfg, te.params
            ).items()
        })
        sd.update({
            f"embedder.model.visual.{k}": np.ascontiguousarray(v)
            for k, v in _openclip_visual_sd(v_cfg, venc.params).items()
        })
        ckpt = tmp_path / "unclip.safetensors"
        save_file(sd, str(ckpt))
        _word_level_tokenizer(tmp_path, monkeypatch)

        model, clip, vae_w, clip_vision = (
            unCLIPCheckpointLoader().load(str(ckpt))
        )
        assert model.source["family"] == "sd21-unclip"
        assert model.config.prediction == "v"
        assert model.config.adm_in_channels == 48
        # The vision wire encodes — sniffed heads differ from the tiny
        # tower's (the head table keys real widths), so check shape/finite
        # rather than golden values; real towers sniff exactly.
        img = np.random.default_rng(0).uniform(size=(1, 28, 28, 3)).astype(
            np.float32
        )
        (cvo,) = CLIPVisionEncode().encode(clip_vision, img, crop="center")
        assert cvo["image_embeds"].shape == (1, 24)
        assert np.isfinite(np.asarray(cvo["image_embeds"])).all()
        # Not-an-unclip file raises with guidance.
        plain = {k: v for k, v in sd.items()
                 if not k.startswith("embedder.")}
        ckpt2 = tmp_path / "plain.safetensors"
        save_file(plain, str(ckpt2))
        with pytest.raises(ValueError, match="not an unCLIP"):
            unCLIPCheckpointLoader().load(str(ckpt2))


class TestStockWanT2VWorkflow:
    def test_wan_t2v_template_runs_unchanged(self, tmp_path, monkeypatch):
        """The stock WAN text-to-video API export shape — UNETLoader +
        CLIPLoader(wan) + VAELoader + EmptyHunyuanLatentVideo (the t2v
        latent entry) + KSampler + VAEDecode + SaveAnimatedWEBP — runs
        as-is on the tiny synthetic WAN world."""
        paths = _synthetic_wan_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = {
            "37": {"class_type": "UNETLoader",
                   "inputs": {"unet_name": paths["dit_t2v"],
                              "weight_dtype": "default"}},
            "38": {"class_type": "CLIPLoader",
                   "inputs": {"clip_name": paths["umt5"], "type": "wan"}},
            "39": {"class_type": "VAELoader",
                   "inputs": {"vae_name": paths["vae"]}},
            "6": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "a cat walking", "clip": ["38", 0]}},
            "7": {"class_type": "CLIPTextEncode",
                  "inputs": {"text": "blurry", "clip": ["38", 0]}},
            "40": {"class_type": "EmptyHunyuanLatentVideo",
                   "inputs": {"width": 16, "height": 16, "length": 5,
                              "batch_size": 1}},
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": 3, "steps": 2, "cfg": 1.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 1.0, "model": ["37", 0],
                             "positive": ["6", 0], "negative": ["7", 0],
                             "latent_image": ["40", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["39", 0]}},
            "28": {"class_type": "SaveAnimatedWEBP",
                   "inputs": {"images": ["8", 0], "fps": 8.0,
                              "filename_prefix": "wan_t2v"}},
        }
        out = run_workflow(wf)
        video = np.asarray(out["8"][0])
        assert video.shape[-1] == 3 and np.isfinite(video).all()
        assert all(os.path.exists(p) for p in out["28"][0])


class TestUnclipReviewFixes:
    def _adm_model(self):
        import jax
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.models import build_unet, sd15_config

        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
            attention_levels=(0, 1), context_dim=16, num_heads=4,
            norm_groups=8, adm_in_channels=32, prediction="v",
            dtype=jnp.float32,
        )
        return build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))

    def test_untagged_adm_model_samples_with_zero_adm(self):
        # A plain txt2img graph on an adm checkpoint (no unCLIPConditioning,
        # no pooled) must sample against a zeros adm vector like stock, not
        # crash on a missing/mis-sized y.
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes import TPUKSampler

        model = self._adm_model()
        (out,) = TPUKSampler().sample(
            model, {"context": jnp.zeros((1, 3, 16))},
            {"samples": jnp.zeros((1, 8, 8, 4))}, seed=0, steps=2, cfg=3.0,
            sampler_name="euler", scheduler="normal",
            negative={"context": jnp.zeros((1, 3, 16))},
        )
        assert np.isfinite(np.asarray(out["samples"])).all()

    def test_wrong_width_text_pooled_dropped_for_unclip_context(self):
        # context_dim 1024 marks the sd21-unclip family: the text tower's
        # pooled never feeds the adm head (stock drops it); tiny config here
        # has context 16, so emulate by patching the gate's width read.
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes import TPUKSampler

        model = self._adm_model()
        # Non-1024 context + wrong-width pooled → diagnosable error.
        with pytest.raises(ValueError, match="adm head expects"):
            TPUKSampler().sample(
                model,
                {"context": jnp.zeros((1, 3, 16)),
                 "pooled": jnp.zeros((1, 24))},
                {"samples": jnp.zeros((1, 8, 8, 4))}, seed=0, steps=1,
                cfg=1.0, sampler_name="euler", scheduler="normal",
            )

    def test_unclip_adm_uses_cosine_alpha_bar(self):
        # squaredcos_cap_v2, not the linear table: at level 500 the cosine
        # alpha-bar keeps ~0.49 of the signal (linear keeps ~0.08).
        from comfyui_parallelanything_tpu.models.unet import unclip_adm

        tags = [{"embeds": np.ones((1, 24), np.float32),
                 "noise_augmentation": 0.5}]
        y = np.asarray(unclip_adm(tags, 32))
        signal = float(np.mean(y[:, :24]))
        # sqrt(acp_cos[500]) ~ 0.70 of the unit embed; linear would be ~0.28.
        assert 0.5 < signal < 0.9, signal


class TestCLIPLoaderTokenBudget:
    def test_wan_t5_max_len_512(self, tmp_path, monkeypatch):
        import dataclasses

        import torch
        import transformers
        from safetensors.numpy import save_file

        import comfyui_parallelanything_tpu.models as models_pkg
        from comfyui_parallelanything_tpu.nodes_compat import CLIPLoader
        from tests.test_text_encoders import TINY_T5

        t5_cfg = dataclasses.replace(TINY_T5, per_layer_bias=True)
        hf_cfg = transformers.UMT5Config(
            vocab_size=t5_cfg.vocab_size, d_model=t5_cfg.d_model,
            d_kv=t5_cfg.d_kv, d_ff=t5_cfg.d_ff, num_layers=t5_cfg.num_layers,
            num_heads=t5_cfg.num_heads,
            relative_attention_num_buckets=t5_cfg.relative_buckets,
            relative_attention_max_distance=t5_cfg.relative_max_distance,
            feed_forward_proj="gated-gelu", dropout_rate=0.0,
        )
        torch.manual_seed(0)
        hf = transformers.UMT5EncoderModel(hf_cfg).eval()
        path = tmp_path / "umt5_tiny.safetensors"
        save_file({k: np.ascontiguousarray(v.detach().numpy())
                   for k, v in hf.state_dict().items()}, str(path))
        monkeypatch.setattr(models_pkg, "umt5_xxl_config", lambda: t5_cfg)

        tokenizers = pytest.importorskip("tokenizers")
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        t = tokenizers.Tokenizer(
            WordLevel({"[UNK]": 0, "</s>": 1, "a": 5}, unk_token="[UNK]")
        )
        t.pre_tokenizer = Whitespace()
        tok = tmp_path / "t5_tok.json"
        t.save(str(tok))
        monkeypatch.setenv("PA_T5_TOKENIZER_JSON", str(tok))
        (wire,) = CLIPLoader().load(str(path), type="wan")
        # WAN prompts tokenize at 512, not the CLIP default 77 (stock umt5
        # budget) — a long prompt must not silently truncate.
        assert wire["tokenizer"].max_len == 512


class TestUnclipNegativeSide:
    def test_wrong_width_negative_pooled_zeroed_for_unclip(self, monkeypatch):
        """The uncond half of CFG must get the same treatment as the cond
        half: a 1024-wide text pooled on the negative conditioning of an
        sd21-unclip-class model (context 1024) is dropped to zeros, not fed
        into label_emb."""
        import dataclasses

        import jax
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.models import build_unet, sd15_config
        from comfyui_parallelanything_tpu.nodes import TPUKSampler

        # context_dim 1024 marks the unclip family for the width gate; keep
        # every other dim tiny.
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
            attention_levels=(0, 1), context_dim=1024, num_heads=4,
            norm_groups=8, adm_in_channels=32, prediction="v",
            dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        (out,) = TPUKSampler().sample(
            model,
            {"context": jnp.zeros((1, 3, 1024))},
            {"samples": jnp.zeros((1, 8, 8, 4))}, seed=0, steps=2, cfg=3.0,
            sampler_name="euler", scheduler="normal",
            negative={"context": jnp.zeros((1, 3, 1024)),
                      "pooled": jnp.zeros((1, 1024))},  # text-tower width
        )
        assert np.isfinite(np.asarray(out["samples"])).all()


class TestI2VClipFeaOnClipless:
    def test_clip_fea_dropped_with_warning_on_wan22_checkpoint(self, caplog):
        """WAN2.1 template (clip_vision_output wired) reused on a WAN2.2-style
        i2v checkpoint (36 channels, no img_emb): stock ignores clip_fea —
        the composition drops it with a warning instead of raising
        mid-sampling."""
        import jax
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.models import build_wan
        from comfyui_parallelanything_tpu.models.wan import (
            WanConfig,
            apply_i2v_conditioning,
        )

        wcfg = WanConfig(
            in_channels=12, out_channels=4, hidden_size=48, ffn_dim=96,
            num_heads=4, depth=1, text_dim=32, freq_dim=16,
            dtype=jnp.float32,  # no img_dim: WAN2.2-style
        )
        dit = build_wan(
            wcfg, jax.random.key(0), sample_shape=(1, 2, 4, 4, 12), txt_len=6
        )
        cond = jnp.zeros((1, 2, 4, 4, 8))
        composed = apply_i2v_conditioning(
            dit, cond, clip_fea=jnp.ones((1, 5, 24))
        )
        out = composed.apply(
            composed.params, jnp.zeros((1, 2, 4, 4, 4)), jnp.array([0.5]),
            jnp.zeros((1, 6, 32)),
        )
        assert out.shape == (1, 2, 4, 4, 4)
        assert np.isfinite(np.asarray(out)).all()


class TestMaskAndUtilityShims:
    """The round-5 utility family: mask ops, batch utils, conditioning
    concat, the refiner text encode — the stock builtins inpaint/refiner
    template exports lean on beyond the core loop."""

    def _nodes(self):
        from comfyui_parallelanything_tpu.nodes_compat import (
            stock_node_mappings,
        )

        return stock_node_mappings()

    def test_conditioning_concat_token_axis(self):
        import jax.numpy as jnp

        n = self._nodes()
        to = {"context": jnp.ones((2, 3, 8)), "pooled": jnp.ones((2, 8))}
        frm = {"context": jnp.zeros((1, 5, 8))}
        (out,) = n["ConditioningConcat"]().concat(to, frm)
        assert out["context"].shape == (2, 8, 8)
        assert out["pooled"].shape == (2, 8)  # to's fields win
        with pytest.raises(ValueError, match="widths"):
            n["ConditioningConcat"]().concat(
                to, {"context": jnp.zeros((1, 5, 4))}
            )

    def test_refiner_encode_over_dual_wire(self, tmp_path, monkeypatch):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        env = _synthetic_sdxl_env(tmp_path, monkeypatch)
        _, clip, _ = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(env["ckpt"])
        )
        n = self._nodes()
        (c,) = n["CLIPTextEncodeSDXLRefiner"]().encode(
            clip, ascore=6.0, width=1024, height=1024,
            text="a watercolor lighthouse",
        )
        g_hidden = clip["g"]["encoder"].cfg.hidden_size
        g_pool = clip["g"]["encoder"].cfg.projection_dim
        assert c["context"].shape[-1] == g_hidden  # G stream alone
        assert c["pooled"].shape[-1] == g_pool + 5 * 256
        with pytest.raises(ValueError, match="G-tower"):
            n["CLIPTextEncodeSDXLRefiner"]().encode(
                {"encoder": None}, 6.0, 1024, 1024, "x"
            )

    def test_mask_family_roundtrip(self):
        import jax.numpy as jnp
        import numpy as np

        n = self._nodes()
        (m,) = n["SolidMask"]().solid(0.25, width=8, height=4)
        assert m.shape == (1, 4, 8) and float(m[0, 0, 0]) == 0.25
        (inv,) = n["InvertMask"]().invert(m)
        assert float(inv[0, 0, 0]) == 0.75
        (img,) = n["MaskToImage"]().mask_to_image(m)
        assert img.shape == (1, 4, 8, 3)
        (back,) = n["ImageToMask"]().image_to_mask(img, "green")
        np.testing.assert_allclose(np.asarray(back), np.asarray(m))
        # 3-channel image has no alpha: fully-opaque mask.
        (ones,) = n["ImageToMask"]().image_to_mask(img, "alpha")
        assert float(ones.min()) == 1.0

    def test_grow_mask_dilates_and_erodes(self):
        import jax.numpy as jnp
        import numpy as np

        n = self._nodes()
        m = jnp.zeros((1, 7, 7)).at[0, 3, 3].set(1.0)
        (grown,) = n["GrowMask"]().expand_mask(m, 1, tapered_corners=True)
        assert float(grown.sum()) == 5.0  # plus-shaped kernel
        (grown_sq,) = n["GrowMask"]().expand_mask(m, 1, tapered_corners=False)
        assert float(grown_sq.sum()) == 9.0  # full 3x3
        (shrunk,) = n["GrowMask"]().expand_mask(grown_sq, -1,
                                                tapered_corners=False)
        np.testing.assert_allclose(np.asarray(shrunk), np.asarray(m))
        (same,) = n["GrowMask"]().expand_mask(m, 0)
        np.testing.assert_allclose(np.asarray(same), np.asarray(m))

    def test_feather_and_composite(self):
        import jax.numpy as jnp
        import numpy as np

        n = self._nodes()
        (m,) = n["SolidMask"]().solid(1.0, width=8, height=8)
        (f,) = n["FeatherMask"]().feather(m, left=4, top=0, right=0, bottom=0)
        got = np.asarray(f)[0, 4, :4]
        np.testing.assert_allclose(got, [0.25, 0.5, 0.75, 1.0], atol=1e-6)

        dst = jnp.zeros((1, 6, 6)).at[:, :, :].set(0.5)
        src = jnp.ones((1, 2, 2))
        (add,) = n["MaskComposite"]().combine(dst, src, x=4, y=4,
                                              operation="add")
        assert float(add[0, 5, 5]) == 1.0 and float(add[0, 0, 0]) == 0.5
        (sub,) = n["MaskComposite"]().combine(dst, src, x=0, y=0,
                                              operation="subtract")
        assert float(sub[0, 0, 0]) == 0.0
        (xor,) = n["MaskComposite"]().combine(dst, src, x=0, y=0,
                                              operation="xor")
        # round(0.5) banker's-rounds to 0; xor(0, 1) = 1.
        assert float(xor[0, 0, 0]) == 1.0
        assert float(xor[0, 5, 5]) == 0.5  # outside the paste window: untouched

    def test_image_batch_and_latent_batch_utils(self):
        import jax.numpy as jnp

        n = self._nodes()
        a = jnp.zeros((2, 8, 8, 3))
        b = jnp.ones((1, 4, 4, 3))
        (batched,) = n["ImageBatch"]().batch(a, b)
        assert batched.shape == (3, 8, 8, 3)

        lat = {"samples": jnp.arange(4.0).reshape(4, 1, 1, 1),
               "noise_mask": jnp.ones((4, 2, 2, 1))}
        (rep,) = n["RepeatLatentBatch"]().repeat(lat, 2)
        assert rep["samples"].shape[0] == 8
        assert rep["noise_mask"].shape[0] == 8
        (sl,) = n["LatentFromBatch"]().frombatch(lat, batch_index=1, length=2)
        assert sl["samples"].shape[0] == 2
        assert float(sl["samples"][0, 0, 0, 0]) == 1.0
        assert sl["noise_mask"].shape[0] == 2

        # A mask batch smaller than the samples batch cycles up (stock
        # repeat_to_batch_size) before tiling/slicing — never lands empty or
        # at a batch matching neither the latents nor 1.
        short = {"samples": jnp.zeros((4, 1, 1, 1)),
                 "noise_mask": jnp.ones((2, 2, 2, 1))}
        (rep2,) = n["RepeatLatentBatch"]().repeat(short, 3)
        assert rep2["samples"].shape[0] == 12
        assert rep2["noise_mask"].shape[0] == 12
        (sl2,) = n["LatentFromBatch"]().frombatch(short, batch_index=2,
                                                  length=2)
        assert sl2["noise_mask"].shape[0] == 2

    def test_load_image_mask_channels(self, tmp_path, monkeypatch):
        import numpy as np
        from PIL import Image

        n = self._nodes()
        in_dir = tmp_path / "input"
        in_dir.mkdir()
        rgba = np.zeros((4, 4, 4), np.uint8)
        rgba[..., 0] = 255  # red
        rgba[..., 3] = 0    # fully transparent
        Image.fromarray(rgba, "RGBA").save(in_dir / "m.png")
        monkeypatch.setenv("PA_INPUT_DIR", str(in_dir))
        (alpha,) = n["LoadImageMask"]().load_image("m.png", "alpha")
        assert float(alpha.min()) == 1.0  # stock 1-alpha: transparent -> 1
        (red,) = n["LoadImageMask"]().load_image("m.png", "red")
        assert float(red.max()) == 1.0 and red.shape == (1, 4, 4)

    def test_refiner_checkpoint_sniffs_and_samples(self, tmp_path,
                                                   monkeypatch):
        """The real refiner story: a refiner-shaped single-file checkpoint
        sniffs as sdxl-refiner (G-only 1280 context, label_emb, no shallow
        attention), loads its bundled G tower as a plain CLIP wire, and a
        stock refiner graph (CLIPTextEncodeSDXLRefiner ×2 → KSampler)
        denoises."""
        from comfyui_parallelanything_tpu.host import run_workflow
        from comfyui_parallelanything_tpu.models import (
            load_safetensors,
            sniff_model_family,
        )

        env = _synthetic_refiner_env(tmp_path, monkeypatch)
        assert sniff_model_family(load_safetensors(env["ckpt"])) == \
            "sdxl-refiner"
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = {
            "4": {"class_type": "CheckpointLoaderSimple",
                  "inputs": {"ckpt_name": env["ckpt"]}},
            "5": {"class_type": "EmptyLatentImage",
                  "inputs": {"width": 32, "height": 32, "batch_size": 1}},
            "6": {"class_type": "CLIPTextEncodeSDXLRefiner",
                  "inputs": {"ascore": 6.0, "width": 1024, "height": 1024,
                             "text": "a watercolor lighthouse",
                             "clip": ["4", 1]}},
            "7": {"class_type": "CLIPTextEncodeSDXLRefiner",
                  "inputs": {"ascore": 2.5, "width": 1024, "height": 1024,
                             "text": "blurry", "clip": ["4", 1]}},
            "3": {"class_type": "KSampler",
                  "inputs": {"seed": 3, "steps": 2, "cfg": 4.0,
                             "sampler_name": "euler", "scheduler": "normal",
                             "denoise": 0.3, "model": ["4", 0],
                             "positive": ["6", 0], "negative": ["7", 0],
                             "latent_image": ["5", 0]}},
            "8": {"class_type": "VAEDecode",
                  "inputs": {"samples": ["3", 0], "vae": ["4", 2]}},
        }
        out = run_workflow(wf)
        images = np.asarray(out["8"][0])
        assert images.shape[0] == 1 and np.isfinite(images).all()

    def test_tiled_vae_nodes_match_untiled(self, tmp_path, monkeypatch):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from comfyui_parallelanything_tpu.models import build_vae
        from tests.test_vae import TINY as TINY_VAE

        n = self._nodes()
        vae = build_vae(TINY_VAE, jax.random.key(0), sample_hw=16)
        lat = jax.random.normal(
            jax.random.key(1), (1, 16, 16, TINY_VAE.z_channels)
        )
        # 2024+ stock exports carry overlap/temporal widgets — must be
        # accepted (host.py passes every workflow input as a kwarg).
        (tiled,) = n["VAEDecodeTiled"]().decode(
            {"samples": lat}, vae, tile_size=64, overlap=32,
            temporal_size=64, temporal_overlap=8,
        )
        from comfyui_parallelanything_tpu.models.vae import (
            vae_output_to_images,
        )

        plain = vae_output_to_images(vae.decode(lat))
        assert tiled.shape == plain.shape
        np.testing.assert_allclose(np.asarray(tiled), np.asarray(plain),
                                   atol=0.08)  # ramp-blend seams, bf16 dots
        px = jnp.clip(plain, 0.0, 1.0)
        (enc,) = n["VAEEncodeTiled"]().encode(px, vae, tile_size=64,
                                              overlap=32)
        # Factor-unaligned tile sizes floor gracefully through the owner
        # (encode_maybe_tiled), not a ValueError — 17 is unaligned for any
        # spatial factor > 1.
        (enc2,) = n["VAEEncodeTiled"]().encode(px, vae, tile_size=17)
        assert np.isfinite(np.asarray(enc2["samples"])).all()
        plain_z = vae.encode(
            jnp.asarray(px) * 2.0 - 1.0
        )
        assert enc["samples"].shape == plain_z.shape
        assert np.isfinite(np.asarray(enc["samples"])).all()

    def test_freeu_patch(self):
        import jax
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.models import build_unet, sd15_config

        n = self._nodes()
        # model_channels*4 / *2 widths must occur in the up path for the
        # patch to bite: full channel_mult ladder at tiny width.
        cfg = sd15_config(
            model_channels=8, channel_mult=(1, 2, 4, 4), num_res_blocks=1,
            attention_levels=(0,), transformer_depth=(1, 0, 0, 0),
            num_heads=2, context_dim=16, norm_groups=4, dtype=jnp.float32,
        )
        m = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        x = jax.random.normal(jax.random.key(1), (1, 16, 16, 4))
        t = jnp.array([300.0])
        ctx = jax.random.normal(jax.random.key(2), (1, 4, 16))
        base_out = np.asarray(m(x, t, ctx))

        # Neutral parameters (b=1, s=1) are an identity patch.
        (neutral,) = n["FreeU_V2"]().patch(m, b1=1.0, b2=1.0, s1=1.0, s2=1.0)
        np.testing.assert_allclose(np.asarray(neutral(x, t, ctx)), base_out,
                                   rtol=1e-4, atol=1e-4)
        # Real parameters change the output; params are shared, not copied.
        (patched,) = n["FreeU_V2"]().patch(m, b1=1.3, b2=1.4, s1=0.9, s2=0.2)
        assert patched.params is m.params
        assert not np.allclose(np.asarray(patched(x, t, ctx)), base_out,
                               atol=1e-4)
        (v1,) = n["FreeU"]().patch(m, b1=1.1, b2=1.2, s1=0.9, s2=0.2)
        out_v1 = np.asarray(v1(x, t, ctx))
        assert not np.allclose(out_v1, np.asarray(patched(x, t, ctx)),
                               atol=1e-4)  # v1 != v2 math
        with pytest.raises(ValueError, match="UNET"):
            n["FreeU_V2"]().patch(
                type("M", (), {"config": None, "params": {}})(),
                1.3, 1.4, 0.9, 0.2,
            )

    def test_rescale_cfg_patch_honored_by_sampler(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.models.api import DiffusionModel
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        n = self._nodes()

        def apply(p, x, t, context=None, **kw):
            # Per-SAMPLE context mean (cond/uncond halves differ under the
            # batched-CFG call) + a spatial gradient so the prediction has a
            # nonzero std for rescale_guidance to act on.
            m = jnp.mean(context, axis=(1, 2)).reshape((-1, 1, 1, 1))
            ramp = jnp.linspace(0.0, 1.0, x.shape[1]).reshape((1, -1, 1, 1))
            return x * 0.1 + m * (0.5 + ramp)

        m = DiffusionModel(apply=apply, params={}, name="toy")
        (tagged,) = n["RescaleCFG"]().patch(m, 0.9)
        assert tagged.sampler_prefs == {"cfg_rescale": 0.9}
        assert tagged is not m and m.sampler_prefs is None

        noise = jnp.ones((1, 8, 8, 4))
        ctx = jnp.ones((1, 3, 5))
        unc = jnp.zeros((1, 3, 5)) - 1.0
        kw = dict(sampler="euler", steps=3, cfg_scale=7.0,
                  uncond_context=unc, rng=None)
        base = run_sampler(m, noise, ctx, **kw)
        tagged_out = run_sampler(tagged, noise, ctx, **kw)
        explicit = run_sampler(m, noise, ctx, cfg_rescale=0.9, **kw)
        # The tag changes the result exactly like the explicit widget value.
        assert not np.allclose(np.asarray(tagged_out), np.asarray(base),
                               atol=1e-6)
        np.testing.assert_allclose(np.asarray(tagged_out),
                                   np.asarray(explicit), atol=1e-6)

        # The stock ordering wraps AFTER patching: prefs must survive
        # parallelize (the ParallelModel carries them through).
        import comfyui_parallelanything_tpu as pa

        pm = pa.parallelize(tagged, pa.DeviceChain.even(["cpu:0"]))
        assert pm.sampler_prefs == {"cfg_rescale": 0.9}
        pm_out = run_sampler(pm, noise, ctx, **kw)
        np.testing.assert_allclose(np.asarray(pm_out), np.asarray(explicit),
                                   atol=1e-5)
        # Guard: the sibling prediction patch must REJECT a wrapped model
        # with its written guidance, not an opaque TypeError.
        with pytest.raises(ValueError, match="before ParallelAnything"):
            n["ModelSamplingDiscrete"]().patch(pm, "v_prediction")
        pm.cleanup()

    def test_model_sampling_discrete(self):
        from comfyui_parallelanything_tpu.models import build_unet, sd15_config

        n = self._nodes()
        import jax
        import jax.numpy as jnp

        cfg = sd15_config(
            model_channels=8, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=2,
            context_dim=16, norm_groups=4, dtype=jnp.float32,
        )
        m = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        assert m.config.prediction == "eps"
        (v,) = n["ModelSamplingDiscrete"]().patch(m, "v_prediction",
                                                  zsnr=False)
        assert v.config.prediction == "v" and v.params is m.params
        assert m.config.prediction == "eps"  # original untouched
        (back,) = n["ModelSamplingDiscrete"]().patch(v, "eps")
        assert back.config.prediction == "eps"
        with pytest.raises(ValueError, match="not.*supported"):
            n["ModelSamplingDiscrete"]().patch(m, "lcm")

    def test_empty_video_latent(self):
        n = self._nodes()
        (lat,) = n["EmptyHunyuanLatentVideo"]().generate(
            width=848, height=480, length=25, batch_size=2
        )
        assert lat["samples"].shape == (2, 7, 60, 106, 16)
        # Off-schedule lengths floor to 4k+1 like stock (API submissions
        # bypass widget steps): 10 -> 9 pixel frames -> 3 latent frames.
        (lat2,) = n["EmptyHunyuanLatentVideo"]().generate(64, 64, 10)
        assert lat2["samples"].shape == (1, 3, 8, 8, 16)

    def test_conditioning_set_mask_node(self):
        import jax.numpy as jnp

        n = self._nodes()
        cond = {"context": jnp.ones((1, 3, 5)), "area": (4, 4, 0, 0),
                "extras": ({"context": jnp.ones((1, 2, 5))},)}
        mask = jnp.ones((1, 8, 8))
        (out,) = n["ConditioningSetMask"]().append(cond, mask, strength=0.5,
                                                   set_cond_area="default")
        # Stock keeps the area (the denoiser composes box × mask), stores
        # the mask strength under its OWN key (area strength and mask
        # strength multiply — a shared key would clobber), and maps the tag
        # over combined extras too (conditioning_set_values rule).
        assert out["area"] == (4, 4, 0, 0)
        assert "strength" not in out  # SetMask never touches area strength
        assert out["mask_strength"] == 0.5 and out["mask"].shape == (1, 8, 8)
        assert out["extras"][0]["mask"].shape == (1, 8, 8)
        assert out["extras"][0]["mask_strength"] == 0.5

    def test_sampler_custom_matches_advanced(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.models.api import DiffusionModel
        from comfyui_parallelanything_tpu.nodes import (
            TPUBasicScheduler,
            TPUKSamplerSelect,
            TPURandomNoise,
            TPUCFGGuider,
            TPUSamplerCustomAdvanced,
        )

        n = self._nodes()

        def apply(p, x, t, context=None, **kw):
            m = jnp.mean(context, axis=(1, 2)).reshape((-1, 1, 1, 1))
            return x * 0.05 + m
        model = DiffusionModel(apply=apply, params={},
                               config=type("C", (), {"prediction": "eps"})())
        pos = {"context": jnp.ones((1, 3, 5))}
        neg = {"context": jnp.zeros((1, 3, 5))}
        lat = {"samples": jnp.zeros((1, 8, 8, 4))}
        (samp,) = TPUKSamplerSelect().get_sampler("euler")
        (sig,) = TPUBasicScheduler().get_sigmas(model, "normal", 4, 1.0)
        (out, den) = n["SamplerCustom"]().sample(
            model, True, 11, 3.0, pos, neg, samp, sig, lat
        )
        (noise,) = TPURandomNoise().get_noise(11)
        (guider,) = TPUCFGGuider().get_guider(model, pos, neg, 3.0)
        (out2, _) = TPUSamplerCustomAdvanced().sample(
            noise, guider, samp, sig, lat
        )
        np.testing.assert_allclose(np.asarray(out["samples"]),
                                   np.asarray(out2["samples"]), atol=1e-6)
        assert np.isfinite(np.asarray(den["samples"])).all()

    def test_image_invert(self):
        import jax.numpy as jnp

        n = self._nodes()
        (inv,) = n["ImageInvert"]().invert(jnp.full((1, 2, 2, 3), 0.25))
        assert float(inv[0, 0, 0, 0]) == 0.75


class TestPatchSourcePreservation:
    def test_patches_keep_loader_source_tag(self, tmp_path, monkeypatch):
        """Every model-patch shim must keep the loader's source tag — the
        LoraLoader shims re-bake from the original file through it. `source`
        is a DiffusionModel FIELD precisely so dc.replace carries it."""
        from comfyui_parallelanything_tpu.nodes import NODE_CLASS_MAPPINGS

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        model, _, _ = (
            NODE_CLASS_MAPPINGS["CheckpointLoaderSimple"]().load(paths["ckpt"])
        )
        assert model.source["family"] == "sd15"
        from comfyui_parallelanything_tpu.nodes_compat import (
            FreeU_V2,
            ModelSamplingDiscrete,
            RescaleCFG,
        )

        (a,) = FreeU_V2().patch(model, 1.3, 1.4, 0.9, 0.2)
        (b,) = RescaleCFG().patch(a, 0.7)
        (c,) = ModelSamplingDiscrete().patch(b, "v_prediction")
        assert c.source == model.source
        assert c.sampler_prefs == {"cfg_rescale": 0.7}
        assert c.config.freeu is not None and c.config.prediction == "v"


class TestCustomSamplingSchedulers:
    def _nodes(self):
        from comfyui_parallelanything_tpu.nodes_compat import (
            stock_node_mappings,
        )

        return stock_node_mappings()

    def test_karras_and_exponential_nodes(self):
        n = self._nodes()
        (sig,) = n["KarrasScheduler"]().get_sigmas(
            steps=10, sigma_max=14.6, sigma_min=0.03, rho=7.0
        )
        s = np.asarray(sig)
        assert len(s) == 11 and s[-1] == 0.0 and np.all(np.diff(s[:-1]) < 0)
        assert s[0] == pytest.approx(14.6, rel=1e-4)
        (sig2,) = n["ExponentialScheduler"]().get_sigmas(
            steps=8, sigma_max=10.0, sigma_min=0.1
        )
        s2 = np.asarray(sig2)
        assert len(s2) == 9 and s2[-1] == 0.0
        assert s2[0] == pytest.approx(10.0, rel=1e-4)

    def test_sd_turbo_schedule(self):
        n = self._nodes()
        (sig,) = n["SDTurboScheduler"]().get_sigmas(None, steps=1,
                                                    denoise=1.0)
        s = np.asarray(sig)
        # One step from the TOP of the trained ladder, then 0.
        assert len(s) == 2 and s[-1] == 0.0
        from comfyui_parallelanything_tpu.sampling.k_samplers import (
            model_sigmas,
        )
        from comfyui_parallelanything_tpu.sampling.schedules import (
            scaled_linear_schedule,
        )

        table = np.asarray(model_sigmas(scaled_linear_schedule()))
        assert s[0] == pytest.approx(table[-1], rel=1e-5)
        # Stock offset rule: start = 10 − int(10·denoise); fractional rungs
        # floor (denoise=0.75 → start 3 → timestep 699 — the stock value).
        (sig2,) = n["SDTurboScheduler"]().get_sigmas(None, steps=2,
                                                     denoise=0.5)
        s2 = np.asarray(sig2)
        assert s2[0] == pytest.approx(table[499], rel=1e-5)
        assert len(s2) == 3 and np.all(np.diff(s2) < 0)
        (sig3,) = n["SDTurboScheduler"]().get_sigmas(None, steps=1,
                                                     denoise=0.75)
        assert np.asarray(sig3)[0] == pytest.approx(table[699], rel=1e-5)
        # Past-the-ladder slices TRUNCATE (no repeated sigmas — those NaN
        # the multistep SDE samplers).
        (sig4,) = n["SDTurboScheduler"]().get_sigmas(None, steps=8,
                                                     denoise=0.3)
        s4 = np.asarray(sig4)
        assert len(s4) == 4 and np.all(np.diff(s4) < 0)  # 3 rungs + 0
        import types
        flowish = types.SimpleNamespace(
            config=types.SimpleNamespace(prediction="flow"))
        with pytest.raises(ValueError, match="flow"):
            n["SDTurboScheduler"]().get_sigmas(flowish, steps=1)

    def test_named_sampler_nodes(self):
        n = self._nodes()
        for name, want in (("SamplerEulerAncestral", "euler_ancestral"),
                           ("SamplerDPMPP_2M_SDE", "dpmpp_2m_sde"),
                           ("SamplerDPMPP_SDE", "dpmpp_sde"),
                           ("SamplerDPMPP_3M_SDE", "dpmpp_3m_sde"),
                           ("SamplerLMS", "lms")):
            # Stock variants carry eta/s_noise widgets — absorbed.
            (wire,) = n[name]().get_sampler(eta=1.0, s_noise=1.0)
            assert wire == {"sampler": want}


class TestImageAndLatentOps:
    def _nodes(self):
        from comfyui_parallelanything_tpu.nodes_compat import (
            stock_node_mappings,
        )

        return stock_node_mappings()

    def test_image_crop_blur_sharpen(self):
        import jax.numpy as jnp

        n = self._nodes()
        img = jnp.zeros((1, 16, 16, 3)).at[:, 8, 8, :].set(1.0)
        (c,) = n["ImageCrop"]().crop(img, width=8, height=4, x=4, y=6)
        assert c.shape == (1, 4, 8, 3)
        (b,) = n["ImageBlur"]().blur(img, blur_radius=2, sigma=1.0)
        assert b.shape == img.shape
        # Blur spreads the impulse: center drops, neighbor rises.
        assert float(b[0, 8, 8, 0]) < 1.0 and float(b[0, 8, 9, 0]) > 0.0
        assert float(jnp.sum(b)) == pytest.approx(float(jnp.sum(img)),
                                                  rel=1e-3)  # energy kept
        (s,) = n["ImageSharpen"]().sharpen(img, sharpen_radius=2, sigma=1.0,
                                           alpha=1.0)
        assert s.shape == img.shape
        assert float(s[0, 8, 8, 0]) == 1.0  # clipped at 1 after boost

    def test_latent_math(self):
        import jax.numpy as jnp

        n = self._nodes()
        a = {"samples": jnp.ones((2, 4, 4, 4))}
        b = {"samples": jnp.full((1, 4, 4, 4), 2.0)}  # batch-1 cycles up
        (add,) = n["LatentAdd"]().op(a, b)
        assert float(add["samples"][1, 0, 0, 0]) == 3.0
        (sub,) = n["LatentSubtract"]().op(a, b)
        assert float(sub["samples"][0, 0, 0, 0]) == -1.0
        (mul,) = n["LatentMultiply"]().op(a, 0.5)
        assert float(mul["samples"][0, 0, 0, 0]) == 0.5
        (bl,) = n["LatentBlend"]().blend(a, b, 0.25)
        assert float(bl["samples"][0, 0, 0, 0]) == pytest.approx(
            1.0 * 0.25 + 2.0 * 0.75)
        (bat,) = n["LatentBatch"]().batch(a, b)
        assert bat["samples"].shape[0] == 3
        # Interpolate: ratio=1 returns samples1 exactly (direction and
        # magnitude both degenerate to a's).
        (it,) = n["LatentInterpolate"]().op(a, b, 1.0)
        np.testing.assert_allclose(np.asarray(it["samples"]),
                                   np.asarray(a["samples"]), atol=1e-6)
        # Midpoint of parallel latents: magnitudes lerp (1 and 2 -> 1.5).
        (mid,) = n["LatentInterpolate"]().op(a, b, 0.5)
        np.testing.assert_allclose(np.asarray(mid["samples"]),
                                   1.5 * np.ones((2, 4, 4, 4)), atol=1e-6)
        # Spatial mismatch resizes (stock reshape_latent_to).
        small = {"samples": jnp.ones((1, 2, 2, 4))}
        (add2,) = n["LatentAdd"]().op(a, small)
        assert add2["samples"].shape == (2, 4, 4, 4)


def test_latent_math_channel_mismatch_raises():
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.nodes_compat import stock_node_mappings

    n = stock_node_mappings()
    a = {"samples": jnp.ones((1, 4, 4, 4))}
    b = {"samples": jnp.ones((1, 4, 4, 16))}
    with pytest.raises(ValueError, match="channel counts differ"):
        n["LatentAdd"]().op(a, b)


def test_conditioning_set_area_percentage_and_flux_encode():
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.nodes_compat import stock_node_mappings

    n = stock_node_mappings()
    cond = {"context": jnp.ones((1, 3, 5)),
            "extras": ({"context": jnp.ones((1, 2, 5))},)}
    (out,) = n["ConditioningSetAreaPercentage"]().append(
        cond, width=0.5, height=0.25, x=0.1, y=0.2, strength=0.8
    )
    assert out["area_pct"] == (0.25, 0.5, 0.2, 0.1)
    assert out["extras"][0]["area_pct"] == (0.25, 0.5, 0.2, 0.1)
    # CLIPTextEncodeFlux rejects non-flux wires with guidance.
    with pytest.raises(ValueError, match="flux"):
        n["CLIPTextEncodeFlux"]().encode({"type": "clip"}, "a", "b", 3.5)


def test_area_forms_replace_each_other():
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.nodes_compat import stock_node_mappings

    n = stock_node_mappings()
    cond = {"context": jnp.ones((1, 3, 5))}
    (px,) = n["ConditioningSetArea"]().append(cond, 512, 512, 0, 0, 1.0)
    (pct,) = n["ConditioningSetAreaPercentage"]().append(
        px, width=0.25, height=0.25, x=0.0, y=0.0, strength=1.0
    )
    assert pct["area"] is None and pct["area_pct"] is not None
    (px2,) = n["ConditioningSetArea"]().append(pct, 256, 256, 0, 0, 1.0)
    assert px2["area_pct"] is None and px2["area"] == (32, 32, 0, 0)


def test_scale_to_megapixels_and_model_merge():
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.models import build_unet, sd15_config
    from comfyui_parallelanything_tpu.nodes_compat import stock_node_mappings

    n = stock_node_mappings()
    (img,) = n["ImageScaleToTotalPixels"]().upscale(
        jnp.zeros((1, 100, 400, 3)), "bilinear", 0.04  # 0.04 MP ≈ 41943 px
    )
    B, H, W, C = img.shape
    assert abs(H * W - 0.04 * 1024 * 1024) / (0.04 * 1024 * 1024) < 0.05
    assert abs(W / H - 4.0) < 0.2  # aspect preserved
    with pytest.raises(ValueError, match="upscale_method"):
        n["ImageScaleToTotalPixels"]().upscale(jnp.zeros((1, 8, 8, 3)),
                                               "hermite", 1.0)

    cfg = sd15_config(
        model_channels=8, channel_mult=(1, 2), num_res_blocks=1,
        attention_levels=(1,), transformer_depth=(0, 1), num_heads=2,
        context_dim=16, norm_groups=4, dtype=jnp.float32,
    )
    m1 = build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
    m2 = build_unet(cfg, jax.random.key(1), sample_shape=(1, 8, 8, 4))
    (merged,) = n["ModelMergeSimple"]().merge(m1, m2, 0.25)
    leaf1 = jax.tree.leaves(m1.params)[0]
    leaf2 = jax.tree.leaves(m2.params)[0]
    got = jax.tree.leaves(merged.params)[0]
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(leaf1) * 0.25
                               + np.asarray(leaf2) * 0.75, atol=1e-6)
    assert merged.source == {"merged": True}
    from comfyui_parallelanything_tpu.nodes_compat import LoraLoader
    with pytest.raises(ValueError, match="BEFORE"):
        LoraLoader().load_lora(merged, {"type": "clip"}, "x.safetensors")
    x = jnp.zeros((1, 8, 8, 4)); t = jnp.array([5.0])
    ctx = jnp.zeros((1, 3, 16))
    assert np.isfinite(np.asarray(merged(x, t, ctx))).all()
    # Cross-topology merge fails loudly.
    cfg2 = sd15_config(
        model_channels=8, channel_mult=(1, 2, 2), num_res_blocks=1,
        attention_levels=(1,), transformer_depth=(0, 1, 0), num_heads=2,
        context_dim=16, norm_groups=4, dtype=jnp.float32,
    )
    m3 = build_unet(cfg2, jax.random.key(2), sample_shape=(1, 8, 8, 4))
    with pytest.raises(ValueError, match="cannot merge"):
        n["ModelMergeSimple"]().merge(m1, m3, 0.5)


# ---------------------------------------------------------------------------
# SD3 stock surface: TripleCLIPLoader, DualCLIPLoader(type=sd3),
# ModelSamplingSD3/ModelSamplingFlux, and the stock SD3 template chain.
# ---------------------------------------------------------------------------


def _synthetic_sd3_towers(tmp_path, monkeypatch):
    """Tiny clip_l / clip_g / t5xxl tower files in the stock SD3 template
    naming, with tokenizer env vars wired and the tiny configs pinned. The
    widths are coupled the way the real family's are: T5 d_model (128) is the
    context width the CLIP L⊕G joint (64+64) pads to; pooled = 64+64."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import torch
    import transformers
    from safetensors.numpy import save_file

    import comfyui_parallelanything_tpu.models as models_pkg
    import comfyui_parallelanything_tpu.models.text_encoders as te_mod
    from comfyui_parallelanything_tpu.models.text_encoders import (
        build_clip_text,
        open_clip_g_config,
    )
    from tests.test_text_encoders import (
        TINY_CLIP,
        TINY_T5,
        TestOpenCLIPConversion,
        _hf_clip,
    )

    l_cfg = dataclasses.replace(TINY_CLIP, max_len=77)
    monkeypatch.setattr(te_mod, "clip_l_config", lambda: l_cfg)
    g_cfg = open_clip_g_config(
        vocab_size=100, hidden_size=64, num_layers=2, num_heads=4,
        max_len=77, projection_dim=64, dtype=jnp.float32,
    )
    monkeypatch.setattr(models_pkg, "open_clip_g_config", lambda: g_cfg)
    monkeypatch.setattr(te_mod, "open_clip_g_config", lambda: g_cfg)
    t5_cfg = dataclasses.replace(TINY_T5, d_model=128)
    monkeypatch.setattr(te_mod, "t5_xxl_config", lambda: t5_cfg)

    hf_l = _hf_clip(l_cfg, "quick_gelu")
    l_path = tmp_path / "clip_l.safetensors"
    save_file(
        {k: np.ascontiguousarray(v.detach().numpy())
         for k, v in hf_l.state_dict().items()},
        str(l_path),
    )

    g_enc = build_clip_text(g_cfg, rng=jax.random.key(2))
    g_path = tmp_path / "clip_g.safetensors"
    save_file(
        {k: np.ascontiguousarray(v)
         for k, v in TestOpenCLIPConversion._openclip_layout(
             g_cfg, g_enc.params
         ).items()},
        str(g_path),
    )

    hf_cfg = transformers.T5Config(
        vocab_size=t5_cfg.vocab_size, d_model=t5_cfg.d_model,
        d_kv=t5_cfg.d_kv, d_ff=t5_cfg.d_ff, num_layers=t5_cfg.num_layers,
        num_heads=t5_cfg.num_heads,
        relative_attention_num_buckets=t5_cfg.relative_buckets,
        relative_attention_max_distance=t5_cfg.relative_max_distance,
        feed_forward_proj="gated-gelu", dropout_rate=0.0,
    )
    torch.manual_seed(3)
    hf_t5 = transformers.T5EncoderModel(hf_cfg).eval()
    t5_path = tmp_path / "t5xxl_fp16.safetensors"
    save_file(
        {k: np.ascontiguousarray(v.detach().numpy())
         for k, v in hf_t5.state_dict().items()},
        str(t5_path),
    )

    _word_level_tokenizer(tmp_path, monkeypatch)  # PA_TOKENIZER_JSON
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {"[UNK]": 0, "</s>": 1, "a": 5, "watercolor": 6, "lighthouse": 7,
             "at": 8, "dawn": 9, "blurry": 10}
    t = tokenizers.Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
    t.pre_tokenizer = Whitespace()
    t5_tok = tmp_path / "t5_tokenizer.json"
    t.save(str(t5_tok))
    monkeypatch.setenv("PA_T5_TOKENIZER_JSON", str(t5_tok))

    return {"l": str(l_path), "g": str(g_path), "t5": str(t5_path)}


class TestTripleCLIPLoader:
    def test_loads_and_encodes_sd3_conditioning(self, tmp_path, monkeypatch):
        from comfyui_parallelanything_tpu.nodes import TPUTextEncode
        from comfyui_parallelanything_tpu.nodes_compat import TripleCLIPLoader

        paths = _synthetic_sd3_towers(tmp_path, monkeypatch)
        # Scrambled widget order: classification is by name/keys, not slot.
        (clip,) = TripleCLIPLoader().load(paths["t5"], paths["g"], paths["l"])
        assert clip["type"] == "sd3-triple"
        assert clip["t5"] is not None

        (cond,) = TPUTextEncode().encode(clip, "a watercolor lighthouse")
        # context: CLIP joint (77 tokens, padded 64+64→128) ‖ T5 (77, 128)
        assert cond["context"].shape == (1, 154, 128)
        assert cond["pooled"].shape == (1, 128)
        assert np.isfinite(np.asarray(cond["context"])).all()
        # The T5 half must be the live stream, not padding.
        assert float(np.abs(np.asarray(cond["context"][:, 77:])).max()) > 0

    def test_key_signature_classification(self, tmp_path, monkeypatch):
        """Files with no name markers classify off the safetensors keys."""
        import shutil

        from comfyui_parallelanything_tpu.nodes_compat import (
            TripleCLIPLoader,
            _classify_text_tower,
        )

        paths = _synthetic_sd3_towers(tmp_path, monkeypatch)
        a = tmp_path / "towerA.safetensors"  # t5 keys
        b = tmp_path / "towerB.safetensors"  # open-clip keys
        c = tmp_path / "towerC.safetensors"  # HF CLIP keys, width 64
        shutil.copy(paths["t5"], a)
        shutil.copy(paths["g"], b)
        shutil.copy(paths["l"], c)
        assert _classify_text_tower(str(a), str(a)) == "t5"
        assert _classify_text_tower(str(b), str(b)) == "open-clip-g"
        assert _classify_text_tower(str(c), str(c)) == "clip-l"
        (clip,) = TripleCLIPLoader().load(str(b), str(c), str(a))
        assert clip["type"] == "sd3-triple" and clip["t5"] is not None

    def test_duplicate_and_missing_towers_raise(self, tmp_path, monkeypatch):
        from comfyui_parallelanything_tpu.nodes_compat import TripleCLIPLoader

        paths = _synthetic_sd3_towers(tmp_path, monkeypatch)
        with pytest.raises(ValueError, match="two t5 files"):
            TripleCLIPLoader().load(paths["t5"], paths["t5"], paths["l"])

    def test_dual_clip_loader_sd3_two_tower_form(self, tmp_path, monkeypatch):
        """DualCLIPLoader(type=sd3): CLIP-L + G, no T5 — context is the
        padded joint alone; a clip_g file in slot 1 corrects swapped wiring."""
        from comfyui_parallelanything_tpu.nodes import TPUTextEncode
        from comfyui_parallelanything_tpu.nodes_compat import DualCLIPLoader

        paths = _synthetic_sd3_towers(tmp_path, monkeypatch)
        (clip,) = DualCLIPLoader().load(paths["g"], paths["l"], type="sd3")
        assert clip["type"] == "sd3-triple" and clip["t5"] is None
        (cond,) = TPUTextEncode().encode(clip, "a watercolor lighthouse")
        # No T5 stream: the joint pads to the real family's 4096.
        assert cond["context"].shape == (1, 77, 4096)
        assert cond["pooled"].shape == (1, 128)

    def test_dual_clip_loader_sd3_clip_plus_t5_pairings(self, tmp_path,
                                                        monkeypatch):
        """DualCLIPLoader(type=sd3) with the common clip+t5xxl pairings:
        stock classifies the two files from their contents, so the T5 file
        must land on the t5 slot (not mis-load as a CLIP tower) and the
        missing CLIP tower zero-fills at encode."""
        from comfyui_parallelanything_tpu.nodes import TPUTextEncode
        from comfyui_parallelanything_tpu.nodes_compat import DualCLIPLoader

        paths = _synthetic_sd3_towers(tmp_path, monkeypatch)
        # clip_l + t5xxl (either order): g stays None.
        (clip,) = DualCLIPLoader().load(paths["t5"], paths["l"], type="sd3")
        assert clip["type"] == "sd3-triple"
        assert clip["g"] is None
        assert clip["l"] is not None and clip["t5"] is not None
        (cond,) = TPUTextEncode().encode(clip, "a watercolor lighthouse")
        # CLIP joint (L only, padded to the tiny T5's 128) ‖ T5 stream.
        assert cond["context"].shape == (1, 154, 128)
        # Missing G pooled zero-fills at the canonical 1280: 64 + 1280.
        assert cond["pooled"].shape == (1, 1344)
        assert float(np.abs(np.asarray(cond["pooled"][:, 64:])).max()) == 0.0
        # The T5 half must be the live stream, not padding.
        assert float(np.abs(np.asarray(cond["context"][:, 77:])).max()) > 0
        # clip_g + t5xxl: l stays None, pooled = zeros(768) ⊕ G's 64.
        (clip2,) = DualCLIPLoader().load(paths["g"], paths["t5"], type="sd3")
        assert clip2["l"] is None and clip2["t5"] is not None
        (cond2,) = TPUTextEncode().encode(clip2, "a watercolor lighthouse")
        assert cond2["pooled"].shape == (1, 832)
        assert float(np.abs(np.asarray(cond2["pooled"][:, :768])).max()) == 0.0
        # ALIGNMENT: the missing L still occupies its LEADING joint slot as
        # zeros (canonical 768, clamped to the tiny geometry: min(768,
        # 128−64) = 64), so G's live features keep their trained offset
        # instead of shifting to column 0.
        assert cond2["context"].shape == (1, 154, 128)
        clip_rows = np.asarray(cond2["context"][:, :77])
        assert float(np.abs(clip_rows[..., :64]).max()) == 0.0
        assert float(np.abs(clip_rows[..., 64:]).max()) > 0

    def test_dual_clip_loader_sd3_duplicate_towers_raise(self, tmp_path,
                                                         monkeypatch):
        import pytest

        from comfyui_parallelanything_tpu.nodes_compat import DualCLIPLoader

        paths = _synthetic_sd3_towers(tmp_path, monkeypatch)
        with pytest.raises(ValueError, match="two t5 files"):
            DualCLIPLoader().load(paths["t5"], paths["t5"], type="sd3")


class TestModelSamplingShiftPatches:
    def _model(self, prefs=None):
        from types import SimpleNamespace

        return SimpleNamespace(
            sampler_prefs=prefs,
            config=SimpleNamespace(prediction="flow"),
        )

    def test_sd3_patch_sets_pref_and_resolution_order(self):
        from comfyui_parallelanything_tpu.nodes import _shift_from_prefs
        from comfyui_parallelanything_tpu.nodes_compat import ModelSamplingSD3

        (m,) = ModelSamplingSD3().patch(self._model(), shift=3.0)
        assert m.sampler_prefs["shift"] == 3.0
        # Widget default yields to the patch; an explicit value wins.
        assert _shift_from_prefs(m, 1.15) == 3.0
        assert _shift_from_prefs(m, 2.0) == 2.0
        assert _shift_from_prefs(self._model(), 1.15) == 1.15

    def test_flux_patch_log_interpolates_over_tokens(self):
        import math

        from comfyui_parallelanything_tpu.nodes_compat import ModelSamplingFlux

        (m,) = ModelSamplingFlux().patch(self._model())  # 1024² defaults
        assert m.sampler_prefs["shift"] == pytest.approx(math.exp(1.15))
        (m2,) = ModelSamplingFlux().patch(self._model(), width=256, height=256)
        assert m2.sampler_prefs["shift"] == pytest.approx(math.exp(0.5))

    def test_dataclass_model_keeps_type_and_existing_prefs(self):
        import dataclasses

        from comfyui_parallelanything_tpu.nodes_compat import ModelSamplingSD3

        @dataclasses.dataclass
        class M:
            sampler_prefs: dict | None = None

        (m,) = ModelSamplingSD3().patch(
            M(sampler_prefs={"cfg_rescale": 0.5}), shift=5.0
        )
        assert isinstance(m, M)
        assert m.sampler_prefs == {"cfg_rescale": 0.5, "shift": 5.0}

    def test_basic_scheduler_honors_pref(self):
        from comfyui_parallelanything_tpu.nodes import TPUBasicScheduler

        (s_pref,) = TPUBasicScheduler().get_sigmas(
            self._model({"shift": 3.0}), "normal", 8, 1.0
        )
        (s_expl,) = TPUBasicScheduler().get_sigmas(
            self._model(), "normal", 8, 1.0, shift=3.0
        )
        np.testing.assert_allclose(np.asarray(s_pref), np.asarray(s_expl))
        (s_plain,) = TPUBasicScheduler().get_sigmas(
            self._model(), "normal", 8, 1.0
        )
        assert not np.allclose(np.asarray(s_pref), np.asarray(s_plain))


class TestStockSD3Template:
    def test_sd3_template_chain(self, tmp_path, monkeypatch):
        """The stock SD3 template node chain — UNETLoader (MMDiT file sniffed
        sd3-medium) + TripleCLIPLoader + CLIPTextEncode ×2 + ModelSamplingSD3
        + EmptySD3LatentImage + KSampler — runs with stock names/inputs."""
        import dataclasses

        import jax
        import jax.numpy as jnp
        from safetensors.numpy import save_file

        import comfyui_parallelanything_tpu.models as models_pkg
        from comfyui_parallelanything_tpu import nodes_compat
        from comfyui_parallelanything_tpu.models.mmdit import (
            MMDiTConfig,
            build_mmdit,
        )
        from tests.test_mmdit import _official_layout_sd

        paths = _synthetic_sd3_towers(tmp_path, monkeypatch)
        mcfg = MMDiTConfig(
            in_channels=16, depth=2, context_in_dim=128, pooled_dim=128,
            pos_embed_max=16, qk_norm=True, dtype=jnp.float32,
        )
        mm = build_mmdit(
            mcfg, jax.random.key(0), sample_shape=(1, 8, 8, 16), txt_len=6
        )
        mm_path = tmp_path / "sd3_tiny.safetensors"
        save_file(
            {k: np.ascontiguousarray(v)
             for k, v in _official_layout_sd(mcfg, mm.params).items()},
            str(mm_path),
        )
        monkeypatch.setattr(models_pkg, "sd3_medium_config", lambda: mcfg)

        n = nodes_compat.stock_node_mappings()
        (model,) = n["UNETLoader"]().load_unet(str(mm_path))
        (clip,) = n["TripleCLIPLoader"]().load(
            paths["l"], paths["g"], paths["t5"]
        )
        (pos,) = n["CLIPTextEncode"]().run(
            clip=clip, text="a watercolor lighthouse at dawn"
        )
        (neg,) = n["CLIPTextEncode"]().run(clip=clip, text="blurry")
        (model,) = n["ModelSamplingSD3"]().patch(model, shift=3.0)
        (lat,) = n["EmptySD3LatentImage"]().generate(64, 64, 1)
        assert lat["samples"].shape == (1, 8, 8, 16)
        (out,) = n["KSampler"]().run(
            model=model, positive=pos, negative=neg, latent_image=lat,
            seed=0, steps=2, cfg=3.0, sampler_name="euler",
            scheduler="normal",
        )
        assert out["samples"].shape == (1, 8, 8, 16)
        assert np.isfinite(np.asarray(out["samples"])).all()


class TestLatentTransforms:
    def _lat(self, arr, mask=None):
        d = {"samples": arr}
        if mask is not None:
            d["noise_mask"] = mask
        return d

    def test_flip_axes_and_mask_follow(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes_compat import LatentFlip

        x = jnp.arange(2 * 3 * 4 * 2, dtype=jnp.float32).reshape(2, 3, 4, 2)
        m = jnp.arange(2 * 3 * 4 * 1, dtype=jnp.float32).reshape(2, 3, 4, 1)
        (v,) = LatentFlip().flip(self._lat(x, m), "x-axis: vertically")
        np.testing.assert_array_equal(np.asarray(v["samples"]),
                                      np.asarray(x)[:, ::-1])
        np.testing.assert_array_equal(np.asarray(v["noise_mask"]),
                                      np.asarray(m)[:, ::-1])
        (h,) = LatentFlip().flip(self._lat(x), "y-axis: horizontally")
        np.testing.assert_array_equal(np.asarray(h["samples"]),
                                      np.asarray(x)[:, :, ::-1])
        # Video latents (NTHWC): the same −3/−2 spatial axes.
        v5 = jnp.arange(2 * 2 * 3 * 4 * 2, dtype=jnp.float32).reshape(
            2, 2, 3, 4, 2
        )
        (out5,) = LatentFlip().flip(self._lat(v5), "x-axis: vertically")
        np.testing.assert_array_equal(np.asarray(out5["samples"]),
                                      np.asarray(v5)[:, :, ::-1])

    def test_rotate_clockwise_quarters_compose(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes_compat import LatentRotate

        x = jnp.arange(1 * 2 * 3 * 1, dtype=jnp.float32).reshape(1, 2, 3, 1)
        (r90,) = LatentRotate().rotate(self._lat(x), "90 degrees")
        assert r90["samples"].shape == (1, 3, 2, 1)
        # Clockwise: the top-left element lands top-right.
        np.testing.assert_array_equal(
            np.asarray(r90["samples"])[0, :, :, 0],
            np.rot90(np.asarray(x)[0, :, :, 0], k=-1),
        )
        (r270,) = LatentRotate().rotate(r90, "270 degrees")
        np.testing.assert_array_equal(np.asarray(r270["samples"]),
                                      np.asarray(x))
        (r0,) = LatentRotate().rotate(self._lat(x), "none")
        np.testing.assert_array_equal(np.asarray(r0["samples"]), np.asarray(x))

    def test_crop_clamps_to_bounds(self):
        import jax.numpy as jnp

        from comfyui_parallelanything_tpu.nodes_compat import LatentCrop

        x = jnp.arange(1 * 16 * 16 * 4, dtype=jnp.float32).reshape(1, 16, 16, 4)
        (c,) = LatentCrop().crop(self._lat(x), width=32, height=16, x=8, y=16)
        assert c["samples"].shape == (1, 2, 4, 4)
        np.testing.assert_array_equal(np.asarray(c["samples"]),
                                      np.asarray(x)[:, 2:4, 1:5])
        # Stock boundary rule: the origin clamps to (dim − 8) latent units and
        # the slice truncates — an out-of-range window yields a
        # smaller-than-requested latent anchored at the clamp, it does NOT
        # slide back to preserve the requested size.
        (c2,) = LatentCrop().crop(self._lat(x), width=96, height=96,
                                  x=512, y=512)
        assert c2["samples"].shape == (1, 8, 8, 4)
        np.testing.assert_array_equal(np.asarray(c2["samples"]),
                                      np.asarray(x)[:, 8:, 8:])
        # In-range origin with an oversized window: truncated, not shrunk to
        # fit beforehand (requested 12 latent cols from col 8 of 16 → 8).
        (c3,) = LatentCrop().crop(self._lat(x), width=96, height=16,
                                  x=64, y=0)
        assert c3["samples"].shape == (1, 2, 8, 4)
        np.testing.assert_array_equal(np.asarray(c3["samples"]),
                                      np.asarray(x)[:, 0:2, 8:])

    def test_save_load_round_trip_and_legacy_rescale(self, tmp_path,
                                                     monkeypatch):
        import jax.numpy as jnp
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.nodes_compat import (
            LoadLatent,
            SaveLatent,
        )

        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        monkeypatch.setenv("PA_INPUT_DIR", str(tmp_path / "out"))
        # Non-square + distinct channel count so a layout mix-up cannot hide.
        x = jnp.linspace(-2, 2, 1 * 2 * 6 * 4).reshape(1, 2, 6, 4)
        ui = SaveLatent().save(self._lat(x), "latents/ComfyUI")
        fname = ui["ui"]["latents"][0]
        # The FILE stores the public stock layout: channels-first NCHW.
        from safetensors.numpy import load_file

        on_disk = load_file(
            str(tmp_path / "out" / "latents" / fname)
        )
        assert on_disk["latent_tensor"].shape == (1, 4, 2, 6)
        np.testing.assert_allclose(
            on_disk["latent_tensor"],
            np.moveaxis(np.asarray(x, np.float32), -1, 1), atol=1e-7,
        )
        (lat,) = LoadLatent().load(os.path.join("latents", fname))
        np.testing.assert_allclose(np.asarray(lat["samples"]), np.asarray(x),
                                   atol=1e-7)
        # Legacy (pre-version-marker) dumps are stock files too — NCHW,
        # stored scaled by 0.18215.
        legacy = tmp_path / "out" / "legacy.latent"
        save_file(
            {"latent_tensor":
             np.moveaxis(np.asarray(x, np.float32), -1, 1) * 0.18215},
            str(legacy),
        )
        (lat2,) = LoadLatent().load("legacy.latent")
        np.testing.assert_allclose(np.asarray(lat2["samples"]),
                                   np.asarray(x), atol=1e-5)
        with pytest.raises(ValueError, match="not found"):
            LoadLatent().load("ghost.latent")
