"""Integration tests for the orchestrator routing table + SPMD data-parallel path on
the virtual 8-device CPU mesh — the sharded-vs-single equivalence deliverable of
SURVEY §7 step 3 (and the routing parity of parallel_forward, 1287-1315)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, ParallelConfig, parallelize
from comfyui_parallelanything_tpu.parallel.orchestrator import (
    ParallelModel,
    _PlatformGroup,
)
from comfyui_parallelanything_tpu.parallel.mesh import build_mesh, place_params


def toy_apply(params, x, t, context=None, **kwargs):
    """A stand-in diffusion forward: forward(x, timesteps, context, **kwargs), batch
    on dim0 (the convention at any_device_parallel.py:1287)."""
    h = x @ params["w"] + params["b"]
    h = h * jnp.cos(t)[:, None]
    if context is not None:
        h = h + context.sum(axis=-1, keepdims=True)
    if "y" in kwargs and kwargs["y"] is not None:
        h = h + kwargs["y"]
    return h


@pytest.fixture(scope="module")
def toy():
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(size=(4, 4)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
    }
    return toy_apply, params


def _inputs(batch, with_context=True, seed=1):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(batch, 4)), jnp.float32)
    t = jnp.asarray(rng.uniform(0, 1, size=(batch,)), jnp.float32)
    c = jnp.asarray(rng.normal(size=(batch, 3)), jnp.float32) if with_context else None
    return x, t, c


def even_chain(n):
    return DeviceChain.even([f"cpu:{i}" for i in range(n)])


class TestDataParallel:
    def test_sharded_matches_single_device(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(8))
        assert isinstance(pm, ParallelModel)
        x, t, c = _inputs(16)
        got = pm(x, t, c)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_uneven_batch_padding(self, toy):
        # batch=21 on 8 devices: pad to 24, slice back — the Z_Image Turbo batch.
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(8))
        x, t, c = _inputs(21)
        got = pm(x, t, c)
        assert got.shape == (21, 4)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_kwargs_split_and_broadcast(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(4))
        x, t, c = _inputs(8)
        y = jnp.ones((8, 4))
        got = pm(x, t, c, y=y)
        want = apply_fn(params, x, t, c, y=y)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_output_is_batch_sharded(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(8))
        x, t, c = _inputs(16)
        got = pm(x, t, c)
        # The result is a global array; XLA kept it sharded (no host gather).
        assert isinstance(got, jax.Array)


class TestRouting:
    def test_batch_smaller_than_devices_shrinks_mesh(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(8))
        x, t, c = _inputs(4)
        got = pm(x, t, c)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_batch_smaller_strict_parity_single_device(self, toy):
        # Reference parity: batch < devices → single device (1307-1315).
        apply_fn, params = toy
        cfg = ParallelConfig(pad_small_batches=False)
        pm = parallelize((apply_fn, params), even_chain(8), cfg)
        x, t, c = _inputs(4)
        got = pm(x, t, c)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_workload_split_disabled_single_device(self, toy):
        apply_fn, params = toy
        cfg = ParallelConfig(workload_split=False)
        pm = parallelize((apply_fn, params), even_chain(8), cfg)
        x, t, c = _inputs(16)
        got = pm(x, t, c)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_batch_one_no_pipeline_falls_to_single(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(8))
        x, t, c = _inputs(1)
        got = pm(x, t, c)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


class TestSetupSemantics:
    def test_zero_percentage_chain_returns_model_unchanged(self, toy):
        # Parity: sum(pct) <= 0 aborts, model returned untouched (1019-1027).
        apply_fn, params = toy
        chain = DeviceChain((type(next(iter(even_chain(1)))) ("cpu", 0.0),))
        model = (apply_fn, params)
        out = parallelize(model, chain)
        assert out is model

    def test_invalid_devices_skipped(self, toy):
        apply_fn, params = toy
        chain = DeviceChain.from_pairs([("cpu:0", 50), ("cpu:99", 50)])
        pm = parallelize((apply_fn, params), chain)
        assert isinstance(pm, ParallelModel)
        assert pm.devices == ("cpu:0",)

    def test_duplicate_devices_merge(self, toy):
        apply_fn, params = toy
        chain = DeviceChain.from_pairs([("cpu:0", 25), ("cpu:0", 25), ("cpu:1", 50)])
        pm = parallelize((apply_fn, params), chain)
        assert pm.devices == ("cpu:0", "cpu:1")
        assert pm.weights == (0.5, 0.5)

    def test_object_model_unwrap(self, toy):
        apply_fn, params = toy

        @dataclasses.dataclass
        class Model:
            params: object

            def apply(self, params, x, t, context=None, **kw):
                return toy_apply(params, x, t, context, **kw)

        pm = parallelize(Model(params), even_chain(2))
        assert isinstance(pm, ParallelModel)

    def test_bad_model_type_raises(self):
        with pytest.raises(TypeError):
            parallelize(42, even_chain(2))

    def test_rebalance_shifts_weights_after_memory_change(self, toy, monkeypatch):
        # Parity (deferred): the reference re-reads free VRAM every step and blends
        # 0.7*user + 0.3*mem (737-766, 1317-1322); here rebalance() does the same
        # on demand between sampler runs.
        from comfyui_parallelanything_tpu.parallel import orchestrator as orch

        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(4))
        assert pm.weights == (0.25, 0.25, 0.25, 0.25)
        # Synthetic memory pressure: devices 2/3 report half the free bytes.
        fake = {0: 8 << 30, 1: 8 << 30, 2: 4 << 30, 3: 4 << 30}
        monkeypatch.setattr(orch, "free_memory_bytes", lambda d: fake[d.id])
        new = pm.rebalance()
        np.testing.assert_allclose(sum(new), 1.0, rtol=1e-6)
        np.testing.assert_allclose(new[0], 0.7 * 0.25 + 0.3 * (8 / 24), rtol=1e-6)
        np.testing.assert_allclose(new[2], 0.7 * 0.25 + 0.3 * (4 / 24), rtol=1e-6)
        assert pm._pipeline_runner is None  # stage placement re-balances lazily
        # Blend is against the ORIGINAL user weights — a second rebalance with the
        # same readings is a fixed point, not a compounding drift.
        again = pm.rebalance()
        np.testing.assert_allclose(again, new, rtol=1e-6)
        # Execution stays correct after the shift.
        x, t, c = _inputs(8)
        got = pm(x, t, c)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(apply_fn(params, x, t, c)), rtol=1e-5, atol=1e-6
        )

    def test_rebalance_noop_when_auto_balance_off(self, toy, monkeypatch):
        # Parity: the reference gates the per-step VRAM re-blend on
        # auto_balance_ref (any_device_parallel.py:1317-1322) — with it off,
        # explicit user weights must survive rebalance() untouched.
        from comfyui_parallelanything_tpu.parallel import orchestrator as orch

        apply_fn, params = toy
        chain = DeviceChain.from_pairs(
            [("cpu:0", 60.0), ("cpu:1", 25.0), ("cpu:2", 10.0), ("cpu:3", 5.0)]
        )
        pm = parallelize(
            (apply_fn, params), chain, ParallelConfig(auto_memory_balance=False)
        )
        before = pm.weights
        np.testing.assert_allclose(before, (0.60, 0.25, 0.10, 0.05), rtol=1e-6)
        fake = {0: 8 << 30, 1: 1 << 30, 2: 1 << 30, 3: 1 << 30}
        monkeypatch.setattr(orch, "free_memory_bytes", lambda d: fake[d.id])
        assert pm.rebalance() == before
        assert pm.weights == before

    def test_reentrant_rewrap(self, toy):
        # Parity: setup_parallel on an already-parallel model tears down the old
        # setup and rebuilds with the new chain (any_device_parallel.py:1006-1013).
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(8))
        x, t, c = _inputs(16)
        pm(x, t, c)
        old_groups = pm._groups
        pm2 = parallelize(pm, even_chain(4))
        # Old wrapper was torn down...
        assert not pm.active
        assert all(g.params is None for g in old_groups)
        # ...and the new one routes over the new chain with correct results.
        assert isinstance(pm2, ParallelModel)
        assert pm2.devices == ("cpu:0", "cpu:1", "cpu:2", "cpu:3")
        assert pm2.active
        got = pm2(x, t, c)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)
        assert len(got.sharding.device_set) == 4

    def test_reentrant_rewrap_unusable_chain_returns_torn_down_model(self, toy):
        # Reference ordering: the re-entrancy teardown (1006-1013) runs before the
        # weight-normalization abort (1019-1027) — an unusable new chain still
        # leaves the previous setup torn down, and the model keeps working via the
        # single-device path.
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(8))
        out = parallelize(pm, [("cpu:0", 0.0)])
        assert out is pm
        assert not pm.active
        x, t, c = _inputs(4)
        got = pm(x, t, c)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(apply_fn(params, x, t, c)), rtol=1e-5, atol=1e-6
        )

    def test_gc_teardown_honors_purge_flags(self, toy, monkeypatch):
        # Parity: weakref.finalize(model, cleanup_parallel_model, ...) at
        # any_device_parallel.py:1459 — dropping every reference to the wrapped
        # MODEL must still honor purge_cache/purge_models.
        import gc

        from comfyui_parallelanything_tpu.parallel import orchestrator as orch

        purges = []
        monkeypatch.setattr(
            orch, "aggressive_cleanup",
            lambda clear_compile_cache=False: purges.append(clear_compile_cache),
        )
        apply_fn, params = toy
        pm = parallelize(
            (apply_fn, params), even_chain(2),
            ParallelConfig(purge_cache=True, purge_models=True),
        )
        fin = pm._finalizer
        del pm
        gc.collect()
        assert not fin.alive
        assert True in purges  # purge_models=True → compile caches cleared

        # purge_cache=False → GC teardown does NOT purge.
        purges.clear()
        pm2 = parallelize(
            (apply_fn, params), even_chain(2), ParallelConfig(purge_cache=False)
        )
        del pm2
        gc.collect()
        assert purges == []

    def test_explicit_cleanup_detaches_finalizer(self, toy):
        import gc

        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(2))
        pm.cleanup()
        assert not pm._finalizer.alive  # detached: no double-teardown at GC
        del pm
        gc.collect()

    def test_cleanup(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(4))
        x, t, c = _inputs(8)
        pm(x, t, c)
        pm.cleanup()
        assert not pm.active
        # Post-teardown calls still work, routed single-device (the reference restores
        # the original forward at teardown, 224-229).
        got = pm(x, t, c)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


class TestReviewRegressions:
    """Regressions for the findings of the first code review: container inputs under
    padding, static (non-array) kwargs, dict outputs under padding, and post-OOM
    memory behavior."""

    def test_container_input_with_padding(self, toy):
        # list-shaped x with batch=21 on 8 devices → pad path must tree-map, not
        # jnp-op the list.
        _, params = toy

        def apply_fn(params, x, t, context=None, **kw):
            a, b = x
            return a @ params["w"] + b @ params["w"]

        pm = parallelize((apply_fn, params), even_chain(8))
        rng = np.random.default_rng(3)
        a = jnp.asarray(rng.normal(size=(21, 4)), jnp.float32)
        b = jnp.asarray(rng.normal(size=(21, 4)), jnp.float32)
        t = jnp.linspace(0, 1, 21)
        got = pm([a, b], t)
        want = apply_fn(params, [a, b], t)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_static_string_kwarg_all_routes(self, toy):
        # Non-array kwargs must bake as jit statics on both DP and single routes.
        _, params = toy

        def apply_fn(params, x, t, context=None, mode="linear", **kw):
            h = x @ params["w"]
            if mode == "double":
                h = h * 2.0
            return h

        x, t, _ = _inputs(16, with_context=False)
        for cfg in [ParallelConfig(), ParallelConfig(workload_split=False)]:
            pm = parallelize((apply_fn, params), even_chain(8), cfg)
            got = pm(x, t, mode="double")
            want = apply_fn(params, x, t, mode="double")
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
            )

    def test_dict_output_unpadded(self, toy):
        # Dict outputs must be sliced back to the true batch after padding.
        _, params = toy

        def apply_fn(params, x, t, context=None, **kw):
            return {"sample": x @ params["w"], "aux": jnp.float32(1.0)}

        pm = parallelize((apply_fn, params), even_chain(8))
        x, t, _ = _inputs(21, with_context=False)
        got = pm(x, t)
        assert got["sample"].shape == (21, 4)

    def test_dict_output_hybrid_concat(self, toy):
        _, params = toy

        def apply_fn(params, x, t, context=None, **kw):
            return {"sample": x @ params["w"]}

        devs = jax.devices("cpu")
        groups = []
        for dev_slice, w, name in [(devs[:4], 0.5, "cpu"), (devs[4:8], 0.5, "cpu2")]:
            mesh = build_mesh(dev_slice, {"data": len(dev_slice)})
            groups.append(
                _PlatformGroup(
                    platform=name,
                    devices=list(dev_slice),
                    device_strs=[f"cpu:{d.id}" for d in dev_slice],
                    device_weights=[w / 4] * 4,
                    mesh=mesh,
                    params=place_params(params, mesh),
                )
            )
        pm = ParallelModel(
            apply_fn=apply_fn,
            params=params,
            chain=even_chain(8),
            config=ParallelConfig(auto_memory_balance=False),
            groups=groups,
            weights=(0.5, 0.5),
        )
        x, t, _ = _inputs(16, with_context=False)
        got = pm(x, t)
        assert got["sample"].shape == (16, 4)
        want = apply_fn(params, x, t)
        np.testing.assert_allclose(
            np.asarray(got["sample"]), np.asarray(want["sample"]), rtol=1e-5, atol=1e-6
        )

    def test_demote_frees_replicas_then_single_works(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(8))
        x, t, c = _inputs(16)
        pm(x, t, c)
        pm._demote()
        assert not pm.active
        assert all(g.params is None for g in pm._groups)
        got = pm(x, t, c)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)
        pm.reactivate()
        assert pm.active
        got2 = pm(x, t, c)
        np.testing.assert_allclose(np.asarray(got2), np.asarray(want), rtol=1e-5, atol=1e-6)


class TestHybridMultiGroup:
    def test_auto_reactivation_after_n_steps(self, toy):
        # VERDICT r2 item 6: reactivate_after=N resumes parallel execution
        # after N single-device steps instead of serializing the rest of a run.
        apply_fn, params = toy
        pm = parallelize(
            (apply_fn, params), even_chain(4), ParallelConfig(reactivate_after=3)
        )
        pm._demote()
        assert not pm.active
        x, t, c = _inputs(8)
        expect = np.asarray(apply_fn(params, x, t, c))
        for i in range(3):
            got = pm(x, t, c)  # N=3 single-device steps run demoted
            assert not pm.active
        got = pm(x, t, c)  # next call reactivates, runs parallel again
        assert pm.active
        assert pm._groups[0].params is not None
        np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-5, atol=1e-6)

    def test_reactivate_rolls_back_partial_placement(self, toy, monkeypatch):
        # A placement failure on a later group must free the groups placed in
        # the same attempt — a failed retry can't pin extra replicas through
        # the memory-pressured demoted period.
        import copy

        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(4))
        # Fake a second platform group so reactivate places two groups.
        g2 = copy.copy(pm._groups[0])
        g2.devices = list(pm._groups[0].devices)
        pm._groups.append(g2)
        pm._demote()
        assert all(g.params is None for g in pm._groups)
        calls = []

        def fake_place(p, mesh):
            calls.append(1)
            if len(calls) == 2:
                raise RuntimeError("RESOURCE_EXHAUSTED: fake")
            return p

        monkeypatch.setattr(pm, "_place", fake_place)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            pm.reactivate()
        assert not pm.active
        assert all(g.params is None for g in pm._groups)  # rolled back
        pm._groups.pop()

    def test_cleaned_up_model_never_auto_reactivates(self, toy):
        # cleanup() is terminal: neither the step counter nor rebalance() may
        # resurrect placements the user explicitly tore down.
        apply_fn, params = toy
        pm = parallelize(
            (apply_fn, params), even_chain(4), ParallelConfig(reactivate_after=1)
        )
        pm.cleanup()
        x, t, c = _inputs(8)
        for _ in range(3):
            pm(x, t, c)
        assert not pm.active
        pm.rebalance()
        assert not pm.active

    def test_cleanup_on_demoted_model_purges(self, toy, monkeypatch):
        # A demoted model still holds a lead copy / compile caches — cleanup()
        # must run the purge even though active is already False.
        from comfyui_parallelanything_tpu.parallel import orchestrator as orch

        purges = []
        monkeypatch.setattr(
            orch, "aggressive_cleanup",
            lambda clear_compile_cache=False: purges.append(clear_compile_cache),
        )
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(2))
        pm._demote()
        x, t, c = _inputs(4)
        pm(x, t, c)  # builds the lead-device fallback placement
        assert pm._lead_params is not None
        purges.clear()
        pm.cleanup()
        assert pm._lead_params is None
        assert purges  # purge_cache honored despite prior demotion

    def test_demotion_permanent_by_default(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(4))
        pm._demote()
        x, t, c = _inputs(8)
        for _ in range(5):
            pm(x, t, c)
        assert not pm.active  # reference-documented default: manual reactivate

    def test_rebalance_reactivates_demoted_chain(self, toy):
        apply_fn, params = toy
        pm = parallelize((apply_fn, params), even_chain(4))
        pm._demote()
        pm.rebalance()
        assert pm.active

    def test_two_group_weighted_dispatch(self, toy):
        """Exercise the heterogeneous two-program path by hand-building two platform
        groups out of CPU devices (70/30 weighted host scatter + async concat)."""
        apply_fn, params = toy
        devs = jax.devices("cpu")
        groups = []
        for dev_slice, w, name in [(devs[:4], 0.7, "cpu"), (devs[4:8], 0.3, "cpu2")]:
            mesh = build_mesh(dev_slice, {"data": len(dev_slice)})
            groups.append(
                _PlatformGroup(
                    platform=name,
                    devices=list(dev_slice),
                    device_strs=[f"cpu:{d.id}" for d in dev_slice],
                    device_weights=[w / 4] * 4,
                    mesh=mesh,
                    params=place_params(params, mesh),
                )
            )
        pm = ParallelModel(
            apply_fn=apply_fn,
            params=params,
            chain=even_chain(8),
            config=ParallelConfig(auto_memory_balance=False),
            groups=groups,
            weights=(0.7, 0.3),
        )
        x, t, c = _inputs(20)
        got = pm(x, t, c)
        assert got.shape == (20, 4)
        want = apply_fn(params, x, t, c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)
