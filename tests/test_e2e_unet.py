"""The end-to-end slice (SURVEY §7 step 3 / BASELINE config 1): a small SD-class UNet
+ DDIM sampler over a CPU device-chain, sharded run vs single-device run produce the
same image (numerically equivalent — XLA fuses the sharded and single-device programs
differently, so exact bitwise equality does not hold even on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models import build_unet, sd15_config
from comfyui_parallelanything_tpu.sampling import ddim_sample


@pytest.fixture(scope="module")
def tiny_unet():
    # SD1.5 topology shrunk for CI: same block structure, tiny widths.
    cfg = sd15_config(
        model_channels=32,
        channel_mult=(1, 2),
        num_res_blocks=1,
        attention_levels=(1,),
        transformer_depth=(0, 1),
        num_heads=4,
        context_dim=64,
        norm_groups=8,
        dtype=jnp.float32,
    )
    return build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4), name="tiny")


def _noise_and_context(batch, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    x = jax.random.normal(k1, (batch, 16, 16, 4), jnp.float32)
    ctx = jax.random.normal(k2, (batch, 12, 64), jnp.float32)
    uncond = jax.random.normal(k3, (batch, 12, 64), jnp.float32)
    return x, ctx, uncond


class TestUNetForward:
    def test_shapes(self, tiny_unet):
        x, ctx, _ = _noise_and_context(2)
        out = tiny_unet(x, jnp.array([5.0, 9.0]), ctx)
        assert out.shape == (2, 16, 16, 4)
        assert out.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(out)))

    def test_param_count_scales_with_config(self, tiny_unet):
        assert tiny_unet.n_params() > 100_000


class TestEndToEnd:
    def test_sampled_image_sharded_equals_single(self, tiny_unet):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(tiny_unet, chain)
        x, ctx, uncond = _noise_and_context(8)

        img_single = ddim_sample(
            tiny_unet, x, ctx, steps=3, cfg_scale=3.0, uncond_context=uncond
        )
        img_sharded = ddim_sample(
            pm, x, ctx, steps=3, cfg_scale=3.0, uncond_context=uncond
        )
        assert img_sharded.shape == (8, 16, 16, 4)
        # Tolerance is relative to the output scale (|values| up to ~35): the
        # sharded and single-device programs fuse differently, and 3 DDIM steps
        # compound the per-step drift.
        np.testing.assert_allclose(
            np.asarray(img_sharded), np.asarray(img_single), rtol=2e-3, atol=2e-2
        )

    def test_cfg_doubles_feed_the_mesh(self, tiny_unet):
        # batch 4 with CFG → forward batch 8 across 8 devices.
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(tiny_unet, chain)
        x, ctx, uncond = _noise_and_context(4)
        img = ddim_sample(pm, x, ctx, steps=2, cfg_scale=5.0, uncond_context=uncond)
        assert img.shape == (4, 16, 16, 4)
        assert np.all(np.isfinite(np.asarray(img)))
