"""ComfyUI-compatible HTTP API (server.py): POST /prompt → history → /view,
over the real workflow host with a persistent cross-prompt cache."""

import json
import time
import urllib.request

import numpy as np
import pytest

from comfyui_parallelanything_tpu.server import make_server
from tests.test_stock_nodes import _synthetic_stock_env


@pytest.fixture
def server(tmp_path, monkeypatch):
    out_dir = tmp_path / "out"
    srv, q = make_server(port=0, output_dir=str(out_dir))
    thread = __import__("threading").Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base, q, str(out_dir)
    srv.shutdown()
    q.shutdown()


@pytest.fixture
def server_mt(tmp_path, monkeypatch):
    """Multi-worker server: 2 concurrent prompt workers + the installed
    continuous-batching scheduler (the serving-mode configuration)."""
    out_dir = tmp_path / "out"
    srv, q = make_server(port=0, output_dir=str(out_dir), workers=2)
    thread = __import__("threading").Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base, q, str(out_dir)
    srv.shutdown()
    q.shutdown()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        ct = r.headers.get("Content-Type", "")
        body = r.read()
    return json.loads(body) if "json" in ct else body


def _post(base, path, payload=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _wait_history(base, pid, timeout=300):
    t0 = time.time()
    while time.time() - t0 < timeout:
        hist = _get(base, f"/history/{pid}")
        if pid in hist:
            return hist[pid]
        time.sleep(0.5)
    raise TimeoutError(f"prompt {pid} never completed")


def _stock_graph(ckpt, out_dir):
    return {
        "4": {"class_type": "CheckpointLoaderSimple",
              "inputs": {"ckpt_name": ckpt}},
        "5": {"class_type": "EmptyLatentImage",
              "inputs": {"width": 32, "height": 32, "batch_size": 1}},
        "6": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "a watercolor lighthouse", "clip": ["4", 1]}},
        "3": {"class_type": "KSampler",
              "inputs": {"seed": 3, "steps": 2, "cfg": 1.0,
                         "sampler_name": "euler", "scheduler": "normal",
                         "denoise": 1.0, "model": ["4", 0],
                         "positive": ["6", 0], "latent_image": ["5", 0]}},
        "8": {"class_type": "VAEDecode",
              "inputs": {"samples": ["3", 0], "vae": ["4", 2]}},
        "9": {"class_type": "SaveImage",
              "inputs": {"images": ["8", 0], "filename_prefix": "api",
                         "output_dir": out_dir}},
    }


class TestServer:
    def test_prompt_history_view_roundtrip(self, server, tmp_path, monkeypatch):
        base, q, out_dir = server
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = _stock_graph(paths["ckpt"], out_dir)

        resp = _post(base, "/prompt", {"prompt": wf})
        assert "prompt_id" in resp
        entry = _wait_history(base, resp["prompt_id"])
        assert entry["status"]["status_str"] == "success", entry["status"]
        images = entry["outputs"]["9"]["images"]
        assert len(images) == 1
        png = _get(
            base,
            f"/view?filename={images[0]['filename']}"
            f"&subfolder={images[0]['subfolder']}",
        )
        assert png[:8] == b"\x89PNG\r\n\x1a\n"

        # Second prompt reuses the cache: the checkpoint node must not
        # re-execute (same signature), only the edited subgraph.
        wf2 = json.loads(json.dumps(wf))
        wf2["3"]["inputs"]["seed"] = 4
        sig_keys = set(q.cache.results)
        resp2 = _post(base, "/prompt", {"prompt": wf2})
        entry2 = _wait_history(base, resp2["prompt_id"])
        assert entry2["status"]["status_str"] == "success"
        assert set(q.cache.results) >= sig_keys  # loader entry survived

    def test_error_lands_in_history(self, server):
        base, _, _ = server
        resp = _post(base, "/prompt", {"prompt": {
            "1": {"class_type": "NoSuchNode", "inputs": {}}
        }})
        entry = _wait_history(base, resp["prompt_id"])
        assert entry["status"]["status_str"] == "error"
        assert "NoSuchNode" in entry["status"]["message"]

    def test_bad_request_rejected(self, server):
        base, _, _ = server
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(base, "/prompt", {"not_prompt": 1})
        assert err.value.code == 400

    def test_object_info_exposes_registry(self, server):
        base, _, _ = server
        info = _get(base, "/object_info/KSampler")
        assert info["KSampler"]["display_name"]
        assert "seed" in json.dumps(info["KSampler"]["input"])
        everything = _get(base, "/object_info")
        assert {"CheckpointLoaderSimple", "TPUKSampler",
                "ParallelAnything"} <= set(everything)

    def test_view_path_escape_rejected(self, server):
        base, _, _ = server
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(base, "/view?filename=../../etc/passwd")
        assert err.value.code == 403

    def test_queue_and_interrupt(self, server):
        base, q, _ = server
        state = _get(base, "/queue")
        assert state == {"queue_running": [], "queue_pending": []}
        assert _post(base, "/interrupt")["dropped"] == 0

    def test_system_stats_lists_devices(self, server):
        base, _, _ = server
        stats = _get(base, "/system_stats")
        assert isinstance(stats["devices"], list) and stats["devices"]

    def _ws_connect(self, base, raw=False):
        """Open /ws; returns (sock, read_event) — RFC 6455 client handshake.
        ``raw=True`` returns frames as (opcode, payload bytes) instead of
        parsed JSON (binary preview frames are not JSON)."""
        import base64 as b64
        import socket
        import struct

        port = int(base.rsplit(":", 1)[1])
        sock = socket.create_connection(("127.0.0.1", port), timeout=120)
        key = b64.b64encode(b"0123456789abcdef").decode()
        sock.sendall(
            (f"GET /ws HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
             f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n"
             "\r\n").encode()
        )
        f = sock.makefile("rb")
        assert b"101" in f.readline()
        while f.readline() not in (b"\r\n", b""):
            pass

        def read_frame():
            hdr = f.read(2)
            n = hdr[1] & 0x7F
            if n == 126:
                n = struct.unpack(">H", f.read(2))[0]
            elif n == 127:
                n = struct.unpack(">Q", f.read(8))[0]
            return hdr[0] & 0x0F, f.read(n)

        def read_event():
            opcode, payload = read_frame()
            assert opcode == 0x1, f"expected text frame, got opcode {opcode}"
            return json.loads(payload)

        return sock, (read_frame if raw else read_event)

    def test_websocket_node_and_progress_events(self, server, tmp_path,
                                                monkeypatch):
        # The full frontend protocol: per-node `executing` events in graph
        # order and per-sampler-step `progress` events (VERDICT r3 missing #3)
        # — what a stock ComfyUI client renders its progress bars from.
        base, _, out_dir = server
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = _stock_graph(paths["ckpt"], out_dir)
        sock, read_event = self._ws_connect(base)
        pid = _post(base, "/prompt", {"prompt": wf})["prompt_id"]
        events = []
        for _ in range(200):
            evt = read_event()
            events.append(evt)
            if (evt["type"] == "executing"
                    and evt["data"].get("node") is None
                    and evt["data"].get("prompt_id") == pid):
                break
        else:
            raise AssertionError("no completion event")
        sock.close()

        executing = [e["data"]["node"] for e in events
                     if e["type"] == "executing" and e["data"]["node"]]
        # Every graph node executes exactly once, deps before dependents.
        assert set(executing) == set(wf)
        assert executing.index("4") < executing.index("3") < executing.index("9")
        progress = [e["data"] for e in events if e["type"] == "progress"]
        assert [p["value"] for p in progress] == [1, 2]  # steps=2
        assert all(p["max"] == 2 and p["prompt_id"] == pid for p in progress)
        assert all(p["node"] == "3" for p in progress)  # tagged to the KSampler
        executed = [e["data"] for e in events if e["type"] == "executed"]
        assert [d["node"] for d in executed] == ["9"]  # the SaveImage node
        assert executed[0]["output"]["images"][0]["filename"]

        # Second prompt with one edit: unchanged upstream nodes are announced
        # as cache-served via execution_cached.
        sock, read_event = self._ws_connect(base)
        wf2 = json.loads(json.dumps(wf))
        wf2["3"]["inputs"]["seed"] = 99
        pid2 = _post(base, "/prompt", {"prompt": wf2})["prompt_id"]
        cached = None
        for _ in range(200):
            evt = read_event()
            if evt["type"] == "execution_cached":
                cached = evt["data"]
            if (evt["type"] == "executing"
                    and evt["data"].get("node") is None
                    and evt["data"].get("prompt_id") == pid2):
                break
        sock.close()
        assert cached is not None and cached["prompt_id"] == pid2
        # The loader/encoders survive the seed edit; the sampler chain reruns.
        assert "4" in cached["nodes"] and "3" not in cached["nodes"]

    def test_interrupt_stops_running_prompt(self, server, tmp_path,
                                            monkeypatch):
        # POST /interrupt must stop the RUNNING prompt between sampler steps
        # (cooperative flag), not just drop pending ones — ComfyUI's Cancel.
        base, _, out_dir = server
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = _stock_graph(paths["ckpt"], out_dir)
        wf["3"]["inputs"]["steps"] = 500  # long enough to interrupt mid-loop
        sock, read_event = self._ws_connect(base)
        pid = _post(base, "/prompt", {"prompt": wf})["prompt_id"]
        # Wait until the sampler is demonstrably inside its loop.
        for _ in range(200):
            evt = read_event()
            if evt["type"] == "progress":
                break
        else:
            raise AssertionError("sampler never reported progress")
        _post(base, "/interrupt")
        saw_interrupt_event = False
        for _ in range(600):
            evt = read_event()
            if evt["type"] == "execution_interrupted":
                assert evt["data"]["prompt_id"] == pid
                saw_interrupt_event = True
            if (evt["type"] == "executing"
                    and evt["data"].get("node") is None):
                break
        sock.close()
        assert saw_interrupt_event
        entry = _wait_history(base, pid)
        assert entry["status"]["status_str"] == "interrupted"
        assert entry["status"]["completed"] is False

    def test_websocket_completion_events(self, server):
        # The ComfyUI API-client pattern: open /ws, POST /prompt, block on
        # the 'executing' event with node=None and the prompt_id — no
        # history polling.
        import base64 as b64
        import socket
        import struct

        base, _, _ = server
        port = int(base.rsplit(":", 1)[1])
        sock = socket.create_connection(("127.0.0.1", port), timeout=60)
        key = b64.b64encode(b"0123456789abcdef").decode()
        sock.sendall(
            (f"GET /ws HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
             f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n"
             "\r\n").encode()
        )
        f = sock.makefile("rb")
        status = f.readline()
        assert b"101" in status
        while f.readline() not in (b"\r\n", b""):  # drain handshake headers
            pass

        def read_event():
            hdr = f.read(2)
            n = hdr[1] & 0x7F
            if n == 126:
                n = struct.unpack(">H", f.read(2))[0]
            return json.loads(f.read(n))

        # An intentionally failing prompt still completes with events.
        resp = _post(base, "/prompt", {"prompt": {
            "1": {"class_type": "NoSuchNode", "inputs": {}}
        }})
        pid = resp["prompt_id"]
        seen = []
        for _ in range(6):
            evt = read_event()
            seen.append(evt["type"])
            if (evt["type"] == "executing"
                    and evt["data"]["node"] is None
                    and evt["data"]["prompt_id"] == pid):
                break
        else:
            raise AssertionError(f"no completion event; saw {seen}")
        assert "status" in seen  # queue-change event arrived too
        sock.close()


class TestServingServer:
    """Round 7: the serving-mode server (workers>1 + continuous batching) and
    the protocol additions that ride along (per-prompt delete, 429, /metrics)."""

    def test_concurrent_ws_event_ordering(self, server_mt, tmp_path,
                                          monkeypatch):
        """Two clients submit concurrently to a 2-worker server: every event
        stream stays correctly tagged — each prompt's `progress` values count
        1..N in order under its own prompt_id and node id, `executed` and the
        completion signal carry the right prompt_id — even while both prompts
        execute (and co-batch) simultaneously."""
        base, q, out_dir = server_mt
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf0 = _stock_graph(paths["ckpt"], out_dir)
        wf0["3"]["inputs"]["steps"] = 1
        # Warm the workflow cache (loader/encoders) so the two concurrent
        # prompts share ONE model object — the same-bucket co-batching case.
        warm = _post(base, "/prompt", {"prompt": wf0})["prompt_id"]
        assert _wait_history(base, warm)["status"]["status_str"] == "success"

        wf1 = _stock_graph(paths["ckpt"], out_dir)
        # 8 steps: wide enough a window that the second prompt reliably
        # joins the first one's in-flight batch (the sharing assertion).
        wf1["3"]["inputs"]["steps"] = 8
        wf1["3"]["inputs"]["seed"] = 76
        wf2 = json.loads(json.dumps(wf1))
        wf2["3"]["inputs"]["seed"] = 77

        dispatches_before = q.scheduler.total_dispatches()
        sock1, read1 = TestServer()._ws_connect(base)
        sock2, read2 = TestServer()._ws_connect(base)
        pid1 = _post(base, "/prompt", {"prompt": wf1})["prompt_id"]
        pid2 = _post(base, "/prompt", {"prompt": wf2})["prompt_id"]

        def collect(read_event, pids):
            events, done = [], set()
            for _ in range(600):
                evt = read_event()
                events.append(evt)
                if (evt["type"] == "executing"
                        and evt["data"].get("node") is None):
                    done.add(evt["data"]["prompt_id"])
                    if done >= pids:
                        return events
            raise AssertionError("not all prompts completed on this socket")

        events = collect(read1, {pid1, pid2})
        events2 = collect(read2, {pid1, pid2})
        sock1.close()
        sock2.close()

        for evs in (events, events2):
            for pid in (pid1, pid2):
                progress = [e["data"] for e in evs
                            if e["type"] == "progress"
                            and e["data"]["prompt_id"] == pid]
                # Per-prompt ordering survives concurrency: 1..4, each event
                # tagged to the prompt's own KSampler node.
                assert [p["value"] for p in progress] == list(range(1, 9))
                assert all(p["max"] == 8 and p["node"] == "3"
                           for p in progress)
                executed = [e["data"] for e in evs
                            if e["type"] == "executed"
                            and e["data"]["prompt_id"] == pid]
                assert [d["node"] for d in executed] == ["9"]
                starts = [e for e in evs if e["type"] == "execution_start"
                          and e["data"]["prompt_id"] == pid]
                assert len(starts) == 1
            # Both prompts started before either finished (they really ran
            # concurrently — 2 workers, one shared batch).
            idx_start = [i for i, e in enumerate(evs)
                         if e["type"] == "execution_start"]
            idx_done = [i for i, e in enumerate(evs)
                        if e["type"] == "executing"
                        and e["data"].get("node") is None]
            assert max(idx_start) < min(idx_done)
        for pid in (pid1, pid2):
            entry = _wait_history(base, pid)
            assert entry["status"]["status_str"] == "success", entry["status"]
        # The overlapping samplers shared step dispatches (continuous
        # batching actually engaged): 2 concurrent 8-step prompts cost
        # under the 16 dispatches serial execution would need.
        assert q.scheduler is not None
        delta = q.scheduler.total_dispatches() - dispatches_before
        assert 1 <= delta < 16, delta

    def test_queue_delete_cancels_running_prompt(self, server, tmp_path,
                                                 monkeypatch):
        """Stock POST /queue {"delete": [pid]}: per-prompt cancel of the
        RUNNING prompt — stops at the next step boundary via its own scope
        event (not the all-or-nothing /interrupt)."""
        base, _, out_dir = server
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = _stock_graph(paths["ckpt"], out_dir)
        wf["3"]["inputs"]["steps"] = 500
        sock, read_event = TestServer()._ws_connect(base)
        pid = _post(base, "/prompt", {"prompt": wf})["prompt_id"]
        for _ in range(200):
            if read_event()["type"] == "progress":
                break
        else:
            raise AssertionError("sampler never reported progress")
        resp = _post(base, "/queue", {"delete": [pid]})
        assert resp["deleted"] == 1
        sock.close()
        entry = _wait_history(base, pid)
        assert entry["status"]["status_str"] == "interrupted"

    def test_queue_delete_drops_pending_only_target(self, server, tmp_path,
                                                    monkeypatch):
        """Deleting a queued prompt leaves its neighbors to run."""
        base, _, out_dir = server
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = _stock_graph(paths["ckpt"], out_dir)
        wf["3"]["inputs"]["steps"] = 200  # keeps the single worker busy
        pid_busy = _post(base, "/prompt", {"prompt": wf})["prompt_id"]
        wf2 = json.loads(json.dumps(wf))
        wf2["3"]["inputs"].update(seed=9, steps=2)
        wf3 = json.loads(json.dumps(wf))
        wf3["3"]["inputs"].update(seed=10, steps=2)
        pid2 = _post(base, "/prompt", {"prompt": wf2})["prompt_id"]
        pid3 = _post(base, "/prompt", {"prompt": wf3})["prompt_id"]
        assert _post(base, "/queue", {"delete": [pid2]})["deleted"] == 1
        _post(base, "/queue", {"delete": [pid_busy]})  # unblock the worker
        assert _wait_history(base, pid2)["status"]["status_str"] == "interrupted"
        assert _wait_history(base, pid3)["status"]["status_str"] == "success"

    def test_bounded_queue_returns_429(self, tmp_path, monkeypatch):
        base_srv, q = make_server(port=0, output_dir=str(tmp_path / "out"),
                                  max_pending=1)
        thread = __import__("threading").Thread(
            target=base_srv.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{base_srv.server_address[1]}"
        try:
            paths = _synthetic_stock_env(tmp_path, monkeypatch)
            wf = _stock_graph(paths["ckpt"], str(tmp_path / "out"))
            wf["3"]["inputs"]["steps"] = 300
            pid_busy = _post(base, "/prompt", {"prompt": wf})["prompt_id"]
            _wait_running(base, pid_busy)
            # Worker busy; depth 1 queue takes exactly one more.
            wf2 = json.loads(json.dumps(wf))
            wf2["3"]["inputs"]["seed"] = 8
            _post(base, "/prompt", {"prompt": wf2})
            wf3 = json.loads(json.dumps(wf))
            wf3["3"]["inputs"]["seed"] = 9
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(base, "/prompt", {"prompt": wf3})
            assert err.value.code == 429
        finally:
            _post(base, "/interrupt")
            base_srv.shutdown()
            q.shutdown()

    def test_metrics_endpoint_prometheus_text(self, server):
        base, _, _ = server
        body = _get(base, "/metrics")
        text = body.decode() if isinstance(body, bytes) else body
        assert "pa_server_queue_pending" in text
        assert "# TYPE pa_server_queue_pending gauge" in text


def _wait_running(base, pid, timeout=60):
    t0 = time.time()
    while time.time() - t0 < timeout:
        state = _get(base, "/queue")
        if pid in state["queue_running"]:
            return
        time.sleep(0.05)
    raise TimeoutError(f"{pid} never started running")


class TestLatentPreviews:
    def test_opt_in_preview_frames_arrive_mid_sampling(self, server, tmp_path,
                                                       monkeypatch):
        """extra_data.preview=true → per-step binary WS frames in the stock
        layout (>II event-type 1 PREVIEW_IMAGE + format 2 PNG + PNG bytes),
        decodable and latent-grid-sized; without the flag, zero binary frames
        (previews are opt-in — VERDICT r4 next-7)."""
        import io
        import struct

        from PIL import Image

        base, _, out_dir = server
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        wf = _stock_graph(paths["ckpt"], out_dir)

        sock, read_frame = TestServer()._ws_connect(base, raw=True)
        pid = _post(
            base, "/prompt", {"prompt": wf, "extra_data": {"preview": True}}
        )["prompt_id"]
        previews, done = [], False
        for _ in range(300):
            opcode, payload = read_frame()
            if opcode == 0x2:
                previews.append(payload)
                continue
            evt = json.loads(payload)
            if (evt["type"] == "executing"
                    and evt["data"].get("node") is None
                    and evt["data"].get("prompt_id") == pid):
                done = True
                break
        sock.close()
        assert done and len(previews) == 2  # one per sampler step
        etype, fmt = struct.unpack(">II", previews[0][:8])
        assert (etype, fmt) == (1, 2)  # PREVIEW_IMAGE, PNG
        img = Image.open(io.BytesIO(previews[0][8:]))
        # 32px request / 8 (EmptyLatentImage grid) = 4px latent, upscaled by
        # an integer factor; mode RGB.
        assert img.mode == "RGB"
        assert img.size[0] == img.size[1] and img.size[0] % 4 == 0

        # Default run: no binary frames.
        sock, read_frame = TestServer()._ws_connect(base, raw=True)
        pid2 = _post(base, "/prompt", {"prompt": {
            **json.loads(json.dumps(wf)),
            "3": {**wf["3"], "inputs": {**wf["3"]["inputs"], "seed": 5}},
        }})["prompt_id"]
        binaries = 0
        for _ in range(300):
            opcode, payload = read_frame()
            if opcode == 0x2:
                binaries += 1
                continue
            evt = json.loads(payload)
            if (evt["type"] == "executing"
                    and evt["data"].get("node") is None
                    and evt["data"].get("prompt_id") == pid2):
                break
        sock.close()
        assert binaries == 0

    def test_latent_to_rgb_shapes(self):
        import numpy as np

        from comfyui_parallelanything_tpu.utils.latent_preview import (
            latent_to_rgb,
            preview_png,
        )

        for shape in [(2, 8, 6, 4), (1, 8, 6, 16), (1, 8, 6, 5),
                      (1, 3, 8, 6, 4)]:
            rgb = latent_to_rgb(np.random.default_rng(0).normal(size=shape))
            assert rgb.shape == (8, 6, 3)
            assert rgb.min() >= 0.0 and rgb.max() <= 1.0
        png = preview_png(np.zeros((1, 4, 4, 4), np.float32))
        assert png[:4] == b"\x89PNG"


class TestUploadImage:
    def _multipart(self, fields):
        boundary = "----patest123"
        parts = []
        for name, (filename, content, ctype) in fields.items():
            head = f'Content-Disposition: form-data; name="{name}"'
            if filename:
                head += f'; filename="{filename}"'
            parts.append(
                f"--{boundary}\r\n{head}\r\n"
                f"Content-Type: {ctype}\r\n\r\n".encode() + content + b"\r\n"
            )
        body = b"".join(parts) + f"--{boundary}--\r\n".encode()
        return body, f"multipart/form-data; boundary={boundary}"

    def _upload(self, base, body, ctype):
        req = urllib.request.Request(
            base + "/upload/image", data=body,
            headers={"Content-Type": ctype}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    def test_upload_roundtrip_and_dedupe(self, server, tmp_path, monkeypatch):
        import numpy as np
        from PIL import Image
        import io

        base, _, _ = server
        in_dir = tmp_path / "input"
        monkeypatch.setenv("PA_INPUT_DIR", str(in_dir))
        buf = io.BytesIO()
        Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(buf, "PNG")
        png = buf.getvalue()

        body, ctype = self._multipart(
            {"image": ("up.png", png, "image/png")})
        out = self._upload(base, body, ctype)
        assert out == {"name": "up.png", "subfolder": "", "type": "input"}
        assert (in_dir / "up.png").read_bytes() == png

        # Re-upload without overwrite: stock dedupe suffix.
        out2 = self._upload(base, body, ctype)
        assert out2["name"] == "up (1).png"
        # overwrite=true clobbers in place.
        body3, ctype3 = self._multipart({
            "image": ("up.png", png, "image/png"),
            "overwrite": ("", b"true", "text/plain"),
        })
        out3 = self._upload(base, body3, ctype3)
        assert out3["name"] == "up.png"
        # Path components are flattened away.
        body4, ctype4 = self._multipart(
            {"image": ("../../evil.png", png, "image/png")})
        out4 = self._upload(base, body4, ctype4)
        assert "/" not in out4["name"] and out4["name"].endswith("evil.png")
        assert (in_dir / out4["name"]).exists()

    def test_upload_rejects_non_multipart(self, server):
        base, _, _ = server
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, "/upload/image", {"not": "multipart"})
        assert ei.value.code == 400
