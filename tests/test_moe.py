"""MoE FFN + expert parallelism (beyond-reference; SURVEY §2e marks EP absent)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.ops.moe import MoEFFN, expert_sharding
from comfyui_parallelanything_tpu.parallel.mesh import AXIS_MODEL, build_mesh


@pytest.fixture(scope="module")
def moe():
    m = MoEFFN(n_experts=4, d_ff=32, dtype=jnp.float32)
    x = jnp.zeros((1, 8, 16), jnp.float32)
    params = m.init(jax.random.key(0), x)["params"]
    return m, params


class TestMoEFFN:
    def test_shapes(self, moe):
        m, params = moe
        x = jax.random.normal(jax.random.key(1), (2, 8, 16), jnp.float32)
        y = m.apply({"params": params}, x)
        assert y.shape == x.shape
        assert np.all(np.isfinite(np.asarray(y)))

    def test_matches_numpy_reference(self, moe):
        # Full closed-form check: per-token top-1 routing, chosen expert's FFN,
        # scaled by the winning softmax prob.
        m, params = moe
        x = jax.random.normal(jax.random.key(2), (1, 6, 16), jnp.float32)
        y = np.asarray(m.apply({"params": params}, x))
        xn = np.asarray(x)[0]
        gate = np.asarray(params["gate"])
        w_in, b_in = np.asarray(params["w_in"]), np.asarray(params["b_in"])
        w_out, b_out = np.asarray(params["w_out"]), np.asarray(params["b_out"])
        logits = xn @ gate
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        want = np.zeros_like(xn)
        for t in range(xn.shape[0]):
            e = int(probs[t].argmax())
            h = np.asarray(jax.nn.gelu(jnp.asarray(xn[t] @ w_in[e] + b_in[e])))
            want[t] = (h @ w_out[e] + b_out[e]) * probs[t, e]
        np.testing.assert_allclose(y[0], want, rtol=1e-4, atol=1e-4)

    def test_routing_is_input_dependent(self, moe):
        m, params = moe
        x = jax.random.normal(jax.random.key(3), (1, 64, 16), jnp.float32)
        logits = np.asarray(x)[0] @ np.asarray(params["gate"])
        assert len(set(logits.argmax(-1))) > 1  # multiple experts actually used


class TestExpertParallel:
    def test_expert_weights_sharded(self, moe, cpu_devices):
        m, params = moe
        mesh = build_mesh(cpu_devices[:4], {AXIS_MODEL: 4})
        placed = expert_sharding(params, mesh, AXIS_MODEL)
        # (E, D, F) shards on E: each device holds 1 of 4 experts.
        assert placed["w_in"].addressable_shards[0].data.shape == (1, 16, 32)
        assert len(placed["gate"].sharding.device_set) == 4  # replicated router

    def test_ep_matches_unsharded(self, moe, cpu_devices):
        m, params = moe
        mesh = build_mesh(cpu_devices[:4], {AXIS_MODEL: 4})
        placed = expert_sharding(params, mesh, AXIS_MODEL)
        x = jax.random.normal(jax.random.key(4), (2, 8, 16), jnp.float32)
        want = m.apply({"params": params}, x)
        got = jax.jit(lambda p, x: m.apply({"params": p}, x))(placed, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )
