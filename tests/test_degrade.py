"""Degradation ladder (utils/degrade.py + the call sites that own the
rungs): every rung is counted/ledgered/logged, the serving OOM ladder
(width halve → attn-chunk shrink → inline fallback) re-seats or sheds
without losing a request, compile failures fall back to the eager loop, the
streaming re-carve rung absorbs an injected prefetch OOM on a REAL streamed
model, and rung exhaustion ends in a clean error + postmortem."""

from __future__ import annotations

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.sampling.runner import run_sampler
from comfyui_parallelanything_tpu.serving import ContinuousBatchingScheduler
from comfyui_parallelanything_tpu.utils import degrade, faults, tracing
from comfyui_parallelanything_tpu.utils.metrics import registry

TOL = dict(rtol=2e-3, atol=1e-4)


@pytest.fixture(autouse=True)
def _ledger_redirect(tmp_path, monkeypatch):
    """Degradation rungs LEDGER by design (kind="degradation" records) —
    a test-provoked rung must land in a temp ledger, never the repo's."""
    monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path / "ledger"))


def tiny_model(x, t, context=None, **kw):
    c = jnp.mean(context, axis=tuple(range(1, context.ndim)))
    c = c.reshape((-1,) + (1,) * (x.ndim - 1))
    tt = t.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.tanh(x * 0.9 + c * 0.1) * (0.5 + 0.1 * tt / 1000.0)


def mk_inputs(seed, batch=1):
    r = np.random.default_rng(seed)
    noise = jnp.asarray(r.normal(size=(batch, 8, 8, 4)).astype(np.float32))
    ctx = jnp.asarray(r.normal(size=(batch, 6, 16)).astype(np.float32))
    return noise, ctx


def _rung_count(rung: str, **extra) -> float:
    return registry.get("pa_degradation_total",
                        {"rung": rung, **extra}) or 0.0


def _bg(fn, *args):
    t = threading.Thread(target=fn, args=args, daemon=True)
    t.start()
    return t


def _wait_enqueued(s, n, timeout=20):
    t0 = time.time()
    while time.time() - t0 < timeout:
        with s._lock:
            tot = sum(len(b.queue) + len(b.active_lanes())
                      for b in s.buckets.values())
        if tot >= n:
            return
        time.sleep(0.005)
    raise TimeoutError(f"never saw {n} enqueued requests")


def _oom_once(bucket):
    """Wrap a bucket's dispatch to raise an OOM-shaped error exactly once."""
    real = bucket.dispatch
    fired = []

    def boom():
        if not fired:
            fired.append(1)
            raise RuntimeError("RESOURCE_EXHAUSTED: synthetic dispatch OOM")
        return real()

    bucket.dispatch = boom
    return fired


class TestRungAccounting:
    def test_unknown_rung_asserts(self):
        with pytest.raises(AssertionError):
            degrade.record_rung("not-a-rung", "nope")

    def test_record_rung_counts_ledgers_and_traces(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        tracing.enable()
        try:
            before = _rung_count("stream-recarve")
            degrade.record_rung("stream-recarve", "unit-test rung",
                               stages_before=2, stages_after=4)
            assert _rung_count("stream-recarve") == before + 1
            events = [e for e in tracing.export()["traceEvents"]
                      if e.get("ph") == "X" and e["name"] == "degradation"]
            assert events and events[-1]["args"]["rung"] == "stream-recarve"
            ledger = tmp_path / "perf_ledger.jsonl"
            recs = [json.loads(l) for l in
                    ledger.read_text().strip().splitlines()]
            mine = [r for r in recs if r.get("kind") == "degradation"]
            assert mine and mine[-1]["rung"] == "stream-recarve"
            assert mine[-1]["stages_after"] == 4
        finally:
            tracing.disable()

    def test_ladder_exhausted_writes_postmortem(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        bundle = degrade.ladder_exhausted(
            "stream-recarve", RuntimeError("RESOURCE_EXHAUSTED: terminal"),
            detail="unit",
        )
        assert bundle and os.path.isdir(bundle)
        assert bundle.startswith(str(tmp_path))
        info = json.load(open(os.path.join(bundle, "error.json")))
        assert info["extra"]["ladder"] == "stream-recarve"

    def test_compile_failure_classifier(self):
        assert degrade.is_compile_failure(
            RuntimeError("injected compile failure (program=loop:k)")
        )
        assert degrade.is_compile_failure(
            RuntimeError("XlaRuntimeError: INTERNAL: during compilation")
        )
        # OOM has its own ladder; generic runtime errors re-raise.
        assert not degrade.is_compile_failure(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        )
        assert not degrade.is_compile_failure(ValueError("bad shape"))


class TestServingLadder:
    def _run_pair(self, sched, plans):
        """Submit the plans through run_sampler worker threads; returns
        {seed: result} after drain."""
        results = {}

        def worker(seed, steps):
            noise, ctx = mk_inputs(seed)
            results[seed] = run_sampler(
                tiny_model, noise, ctx, sampler="euler", steps=steps
            )

        threads = [_bg(worker, s, n) for s, n in plans]
        _wait_enqueued(sched, len(plans))
        sched.drain(timeout=120)
        for t in threads:
            t.join(60)
        assert len(results) == len(plans), results
        return results

    def test_oom_halves_width_and_reseats(self):
        """Rung 1: a dispatch OOM at width 4 re-buckets every request at
        width 2 (restart from step 0 — the failover replay discipline) and
        the results still match serial."""
        plans = [(11, 4), (12, 5)]
        serial = {
            s: run_sampler(tiny_model, *mk_inputs(s), sampler="euler", steps=n)
            for s, n in plans
        }
        sched = ContinuousBatchingScheduler(max_width=4, auto=False).install()
        try:
            before = _rung_count("lane-width-halve")
            results = {}

            def worker(seed, steps):
                noise, ctx = mk_inputs(seed)
                results[seed] = run_sampler(
                    tiny_model, noise, ctx, sampler="euler", steps=steps
                )

            threads = [_bg(worker, s, n) for s, n in plans]
            _wait_enqueued(sched, len(plans))
            [b] = sched.buckets.values()
            _oom_once(b)
            sched.drain(timeout=120)
            for t in threads:
                t.join(60)
            assert _rung_count("lane-width-halve") == before + 1
            # The shed width sticks for this shape: the replacement bucket
            # (and any future submission) runs at half width.
            widths = {bk.width for bk in sched.buckets.values()}
            assert widths == {2}, widths
            assert sched._width_caps and set(
                sched._width_caps.values()) == {2}
            for s, _ in plans:
                np.testing.assert_allclose(
                    np.asarray(results[s]), np.asarray(serial[s]), **TOL
                )
        finally:
            sched.uninstall()
            sched.shutdown()

    def test_oom_at_width_one_shrinks_attn_chunk(self):
        """Rung 2: width already 1 → the chunked-attention threshold halves,
        compiled loop programs are rebuilt, the request re-seats."""
        import importlib

        # ops/__init__ re-exports an `attention` FUNCTION that shadows the
        # submodule attribute; importlib returns the real module.
        attention = importlib.import_module(
            "comfyui_parallelanything_tpu.ops.attention"
        )
        attention.reset_chunk_shrink()
        sched = ContinuousBatchingScheduler(max_width=1, auto=False).install()
        try:
            before = _rung_count("attn-chunk-shrink")
            t0 = attention._chunk_threshold()
            results = {}

            def worker():
                noise, ctx = mk_inputs(21)
                results[21] = run_sampler(
                    tiny_model, noise, ctx, sampler="euler", steps=3
                )

            th = _bg(worker)
            _wait_enqueued(sched, 1)
            [b] = sched.buckets.values()
            assert b.width == 1
            _oom_once(b)
            sched.drain(timeout=120)
            th.join(60)
            assert _rung_count("attn-chunk-shrink") == before + 1
            assert attention._chunk_threshold() == max(
                attention._CHUNK_FLOOR, t0 // 2
            )
            assert 21 in results
        finally:
            attention.reset_chunk_shrink()
            sched.uninstall()
            sched.shutdown()

    def test_oom_ladder_exhausted_falls_back_inline(self, monkeypatch):
        """Rung 3: width 1 AND chunk at the floor → the request is shed to
        the inline eager path (DegradedToInline caught in run_sampler) —
        the prompt still completes, the inline-fallback rung is counted."""
        import importlib

        attention = importlib.import_module(
            "comfyui_parallelanything_tpu.ops.attention"
        )
        monkeypatch.setattr(attention, "_CHUNK_SHRINK", 1 << 30)
        assert attention.shrink_chunk_threshold() is None  # floor reached
        serial = run_sampler(tiny_model, *mk_inputs(31), sampler="euler",
                             steps=3)
        sched = ContinuousBatchingScheduler(max_width=1, auto=False).install()
        try:
            before = _rung_count("inline-fallback")
            results = {}

            def worker():
                noise, ctx = mk_inputs(31)
                results[31] = run_sampler(
                    tiny_model, noise, ctx, sampler="euler", steps=3
                )

            th = _bg(worker)
            _wait_enqueued(sched, 1)
            [b] = sched.buckets.values()
            b.dispatch = lambda: (_ for _ in ()).throw(
                RuntimeError("RESOURCE_EXHAUSTED: terminal OOM")
            )
            sched.pump()   # ladder: nothing left → DegradedToInline
            th.join(60)    # worker finishes on the inline path
            assert _rung_count("inline-fallback") == before + 1
            np.testing.assert_allclose(
                np.asarray(results[31]), np.asarray(serial), **TOL
            )
        finally:
            sched.uninstall()
            sched.shutdown()

    def test_compile_failure_sheds_to_inline(self):
        """The compile rung, serving form: a lane-program compile failure
        resolves every request DegradedToInline (run_sampler runs the eager
        loop) — never a user-facing crash."""
        serial = run_sampler(tiny_model, *mk_inputs(41), sampler="euler",
                             steps=3)
        sched = ContinuousBatchingScheduler(max_width=4, auto=False).install()
        try:
            before = _rung_count("compile-eager")
            results = {}

            def worker():
                noise, ctx = mk_inputs(41)
                results[41] = run_sampler(
                    tiny_model, noise, ctx, sampler="euler", steps=3
                )

            th = _bg(worker)
            _wait_enqueued(sched, 1)
            [b] = sched.buckets.values()
            b.dispatch = lambda: (_ for _ in ()).throw(
                RuntimeError("injected compile failure (program=loop:lane)")
            )
            sched.pump()
            th.join(60)
            assert _rung_count("compile-eager") == before + 1
            np.testing.assert_allclose(
                np.asarray(results[41]), np.asarray(serial), **TOL
            )
        finally:
            sched.uninstall()
            sched.shutdown()


TINY_FLUX_KW = dict(
    in_channels=16, hidden_size=64, num_heads=4, depth=2,
    depth_single_blocks=4, context_in_dim=32, vec_in_dim=16,
    axes_dim=(4, 6, 6), guidance_embed=False,
)


@pytest.fixture(scope="module")
def flux_model():
    from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux

    cfg = FluxConfig(dtype=jnp.float32, **TINY_FLUX_KW)
    return build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4),
                      txt_len=16)


def _flux_inputs(batch=2):
    x = jax.random.normal(jax.random.key(1), (batch, 8, 8, 4))
    t = jnp.linspace(900.0, 1.0, batch)
    ctx = jax.random.normal(jax.random.key(2),
                            (batch, 16, TINY_FLUX_KW["context_in_dim"]))
    y = jax.random.normal(jax.random.key(3),
                          (batch, TINY_FLUX_KW["vec_in_dim"]))
    return x, t, ctx, y


class TestStreamRecarveRung:
    def test_injected_prefetch_oom_recarves_and_matches(
        self, flux_model, monkeypatch, tmp_path
    ):
        """The stream ladder end to end, REAL streamed model: an injected
        prefetch OOM (utils/faults.py site) re-carves the schedule —
        forward completes, output matches the bare apply, rung counted."""
        from comfyui_parallelanything_tpu import (
            DeviceChain,
            ParallelConfig,
            parallelize,
        )
        from comfyui_parallelanything_tpu.models.loader import params_nbytes

        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAULT_PLAN", json.dumps({"faults": [
            {"site": "stream-prefetch-oom", "nth": 2, "count": 1},
        ]}))
        faults.reload()
        try:
            before = _rung_count("stream-recarve")
            x, t, ctx, y = _flux_inputs()
            want = flux_model.apply(flux_model.params, x, t, ctx, y=y)
            pm = parallelize(
                flux_model, DeviceChain.even(["cpu:0"]),
                ParallelConfig(
                    weight_sharding="stream",
                    hbm_budget_bytes=params_nbytes(flux_model.params),
                ),
            )
            n0 = pm._get_streaming_runner().n_stages
            got = pm(x, t, ctx, y=y)
            assert pm._stream_runner.n_stages > n0
            assert _rung_count("stream-recarve") == before + 1
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), **TOL
            )
            assert faults.fired().get("stream-prefetch-oom") == 1
        finally:
            monkeypatch.delenv("PA_FAULT_PLAN")
            faults.reload()

    def test_exhaustion_is_clean_error_with_postmortem(
        self, flux_model, monkeypatch, tmp_path
    ):
        """Rung exhaustion: a carve already at one segment per stage has no
        finer rung — the injected OOM surfaces as a clean RESOURCE_EXHAUSTED
        with a postmortem bundle, never a spin."""
        from comfyui_parallelanything_tpu import (
            DeviceChain,
            ParallelConfig,
            parallelize,
        )
        from comfyui_parallelanything_tpu.models.loader import params_nbytes

        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAULT_PLAN", json.dumps({"faults": [
            {"site": "stream-prefetch-oom", "nth": 1, "count": None},
        ]}))
        faults.reload()
        try:
            pm = parallelize(
                flux_model, DeviceChain.even(["cpu:0"]),
                ParallelConfig(
                    weight_sharding="stream",
                    # Tiny budget → the carve starts at one segment per
                    # stage: the ladder has no rung to take.
                    hbm_budget_bytes=params_nbytes(flux_model.params) // 16,
                ),
            )
            x, t, ctx, y = _flux_inputs()
            with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
                pm(x, t, ctx, y=y)
            pms = [d for d in (tmp_path / "postmortem").iterdir()
                   if "degrade-exhausted-stream-recarve" in d.name]
            assert pms, list((tmp_path / "postmortem").iterdir())
        finally:
            monkeypatch.delenv("PA_FAULT_PLAN")
            faults.reload()
