"""Universal lane batching (round 16): img2img denoise masks, multi-cond CFG,
delegated ControlNet, and per-lane LoRA as per-lane state inside the ONE
compiled lane-step program — co-batched in one bucket, never recompiling on
traffic mix, occupancy-deterministic, and degradation-safe. All off-hardware
(CPU + the 8-device virtual mesh) with deterministic manual pumping."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models.api import DiffusionModel
from comfyui_parallelanything_tpu.models.controlnet import apply_control
from comfyui_parallelanything_tpu.models.lora import combine_factors
from comfyui_parallelanything_tpu.sampling.runner import run_sampler
from comfyui_parallelanything_tpu.serving import ContinuousBatchingScheduler
from comfyui_parallelanything_tpu.utils.metrics import registry

# bf16-scale tolerances (CLAUDE.md): cross-program legs (inline vs lane) only.
# Same-program legs assert bitwise equality instead.
TOL = dict(rtol=2e-3, atol=1e-4)


def mk_base(seed=0):
    """Per-sample-independent denoiser WITH a params pytree (so LoRA factors
    have a 2-D leaf to address) and a ``control`` consumption point (so the
    delegated ControlNet residuals have somewhere to land)."""
    r = np.random.default_rng(seed)
    params = {"proj": {"kernel": jnp.asarray(
        r.normal(size=(4, 4)).astype(np.float32)) * 0.2}}

    def apply(p, x, t, context=None, control=None, **kw):
        c = jnp.mean(context, axis=(1, 2)).reshape((-1, 1, 1, 1))
        h = x @ p["proj"]["kernel"]
        if control is not None:
            h = h + control["middle"][0]
        tt = t.reshape((-1, 1, 1, 1))
        return jnp.tanh(h + 0.1 * c) * (0.5 + 0.1 * tt / 1000.0)

    return DiffusionModel(apply=apply, params=params, name="capbase")


def mk_ctrl():
    """Tiny control trunk: hint mean → one middle residual (per-sample
    independent, like the base)."""
    params = {"g": jnp.float32(0.5)}

    def capply(p, x, t, context=None, *, hint, y=None):
        hm = jnp.mean(hint, axis=(1, 2, 3)).reshape((-1, 1, 1, 1))
        return {"middle": (p["g"] * hm * jnp.ones_like(x),)}

    return DiffusionModel(apply=capply, params=params, name="capctrl")


def mk_inputs(seed, batch=1):
    r = np.random.default_rng(seed)
    noise = jnp.asarray(r.normal(size=(batch, 8, 8, 4)).astype(np.float32))
    ctx = jnp.asarray(r.normal(size=(batch, 6, 16)).astype(np.float32))
    return noise, ctx


def _fixtures(seed=99):
    """One coherent capability kit: init/mask for img2img, hint + merged
    control model, a 2-LoRA factor map, an extra cond."""
    base = mk_base()
    r = np.random.default_rng(seed)
    init = jnp.asarray(r.normal(size=(1, 8, 8, 4)).astype(np.float32))
    mask = jnp.asarray((r.random(size=(1, 8, 8, 1)) > 0.5).astype(np.float32))
    hint = jnp.asarray(r.random(size=(1, 64, 64, 3)).astype(np.float32))
    merged = apply_control(base, mk_ctrl(), hint, strength=0.7)
    f1 = {"proj/kernel": (
        jnp.asarray(r.normal(size=(2, 4)).astype(np.float32)) * 0.1,
        jnp.asarray(r.normal(size=(4, 2)).astype(np.float32)) * 0.1)}
    f2 = {"proj/kernel": (
        jnp.asarray(r.normal(size=(1, 4)).astype(np.float32)) * 0.1,
        jnp.asarray(r.normal(size=(4, 1)).astype(np.float32)) * 0.1)}
    ctx2 = jnp.asarray(r.normal(size=(1, 6, 16)).astype(np.float32))
    return dict(base=base, init=init, mask=mask, hint=hint, merged=merged,
                lora1=f1, lora2=combine_factors([f1, f2]), ctx2=ctx2)


@pytest.fixture
def sched():
    s = ContinuousBatchingScheduler(max_width=4, auto=False).install()
    try:
        yield s
    finally:
        s.uninstall()
        s.shutdown()


def _bg(fn, *args):
    t = threading.Thread(target=fn, args=args, daemon=True)
    t.start()
    return t


def _wait_enqueued(s, n, timeout=20):
    t0 = time.time()
    while time.time() - t0 < timeout:
        with s._lock:
            tot = sum(
                len(b.queue) + len(b.active_lanes())
                for b in s.buckets.values()
            )
        if tot >= n:
            return
        time.sleep(0.005)
    raise TimeoutError(f"never saw {n} enqueued requests")


def _run_plans(s, plans):
    """plans: {name: (model, seed, kwargs)} → {name: result}, all submitted
    concurrently, seated before the first pump, drained to completion."""
    results = {}

    def worker(name, model, seed, kw):
        noise, ctx = mk_inputs(seed)
        results[name] = run_sampler(model, noise, ctx, **kw)

    threads = [_bg(worker, k, m, seed, kw) for k, (m, seed, kw) in plans.items()]
    _wait_enqueued(s, len(plans))
    s.drain(timeout=120)
    for t in threads:
        t.join(60)
    assert len(results) == len(plans), sorted(results)
    return results


def _metric_sum(name, **match):
    """Sum a labeled counter across label sets matching ``match`` items
    (bucket labels vary per test model/shape)."""
    m = registry._metrics.get(name)
    if not m:
        return 0
    want = {(str(k), str(v)) for k, v in match.items()}
    return sum(v for key, v in m["values"].items() if want <= set(key))


def _cap_count(kind):
    return _metric_sum("pa_serving_lane_capability_total", kind=kind)


def _fallback_count():
    return _metric_sum("pa_serving_inline_fallback_total")


class TestUniversalLaneBatching:
    def test_mixed_capability_bucket_matches_solo(self, sched):
        """Acceptance: an img2img-masked lane, a ControlNet lane, a 2-LoRA
        lane, and a plain txt2img lane co-batch in ONE bucket; total dispatch
        count equals the max per-lane eval count (+ join slack); every latent
        matches its inline solo twin; no lane fell back inline."""
        fx = _fixtures()
        base = fx["base"]
        plans = {
            "masked": (base, 1, dict(sampler="euler", steps=4,
                                     init_latent=fx["init"], denoise=0.8,
                                     latent_mask=fx["mask"])),
            "control": (fx["merged"], 2, dict(sampler="euler", steps=6)),
            "lora2": (base, 3, dict(sampler="euler", steps=8,
                                    lora=fx["lora2"])),
            "plain": (base, 4, dict(sampler="euler", steps=5)),
        }
        sched.uninstall()
        inline = {k: run_sampler(m, *mk_inputs(seed), **kw)
                  for k, (m, seed, kw) in plans.items()}
        sched.install()
        caps_before = {k: _cap_count(k) for k in
                       ("img2img_mask", "controlnet", "lora", "txt2img")}
        fb_before = _fallback_count()
        results = _run_plans(sched, plans)
        assert len(sched.buckets) == 1, (
            "capability mix must share ONE bucket "
            f"{[b.label for b in sched.buckets.values()]}"
        )
        assert sched.total_dispatches() <= 8 + 2  # max steps + join slack
        for k in plans:
            np.testing.assert_allclose(np.asarray(results[k]),
                                       np.asarray(inline[k]), **TOL,
                                       err_msg=k)
        # Seat accounting: each capability ticked its kind; eligible mixed
        # traffic never fell back inline.
        for kind in ("img2img_mask", "controlnet", "lora", "txt2img"):
            assert _cap_count(kind) >= caps_before[kind] + 1, kind
        assert _fallback_count() == fb_before

    def test_traffic_mix_never_recompiles(self, sched):
        """Bucket-key discipline: adding a masked lane to a bucket that
        already ran plain traffic reuses the SAME bucket (the mask axis is
        always-on, so txt2img↔img2img mixes share one program)."""
        fx = _fixtures()
        base = fx["base"]
        _run_plans(sched, {"p1": (base, 11, dict(sampler="euler", steps=3))})
        assert len(sched.buckets) == 1
        _run_plans(sched, {
            "masked": (base, 12, dict(sampler="euler", steps=3,
                                      init_latent=fx["init"], denoise=0.8,
                                      latent_mask=fx["mask"])),
            "p2": (base, 13, dict(sampler="euler", steps=4)),
        })
        assert len(sched.buckets) == 1  # same key — no new bucket, no refit


class TestCapabilityEquivalenceMatrix:
    """Every capability × {eps, flow} × a ragged co-batched partner × CFG —
    the round-10 equivalence-matrix discipline extended to round 16."""

    CAPS = ("mask", "multi_cond", "control", "lora")

    @pytest.mark.parametrize("prediction", ["eps", "flow"])
    @pytest.mark.parametrize("cap", CAPS)
    def test_capability_lane_matches_solo(self, sched, cap, prediction):
        fx = _fixtures()
        base = fx["base"]
        uncond = jnp.asarray(
            np.random.default_rng(5).normal(size=(1, 6, 16)).astype(np.float32))
        cfg = dict(cfg_scale=3.0, uncond_context=uncond)
        model, kw = {
            "mask": (base, dict(sampler="euler", steps=4,
                                prediction=prediction, init_latent=fx["init"],
                                denoise=0.8, latent_mask=fx["mask"], **cfg)),
            "multi_cond": (base, dict(
                sampler="euler", steps=5, prediction=prediction,
                extra_conds=({"context": fx["ctx2"], "strength": 0.7,
                              "area": (4, 8, 0, 0)},), **cfg)),
            "control": (fx["merged"], dict(sampler="euler", steps=6,
                                           prediction=prediction, **cfg)),
            "lora": (base, dict(sampler="euler", steps=7,
                                prediction=prediction, lora=fx["lora1"],
                                **cfg)),
        }[cap]
        sched.uninstall()
        inline = run_sampler(model, *mk_inputs(21), **kw)
        sched.install()
        results = _run_plans(sched, {
            "cap": (model, 21, kw),
            # Ragged partner: different sampler family, different step count.
            "partner": (base, 22, dict(sampler="heun", steps=3,
                                       prediction=prediction, **cfg)),
        })
        assert len(sched.buckets) == 1
        np.testing.assert_allclose(np.asarray(results["cap"]),
                                   np.asarray(inline), **TOL)


class TestOccupancyDeterminism:
    def test_lora_and_masked_lanes_bitwise_across_occupancy(self, sched):
        """Same-program legs are BITWISE: a LoRA lane and a masked lane
        co-batched alone produce bit-identical latents to the same pair
        co-batched with two extra plain lanes (identity LoRA rows and
        zero-mask rows are structural no-ops, and the per-step noise key is
        fold_in(rng, i) regardless of lane index)."""
        fx = _fixtures()
        base = fx["base"]
        rng = jax.random.key(3)
        pair = {
            "lora": (base, 31, dict(sampler="euler_ancestral", steps=5,
                                    rng=rng, lora=fx["lora1"])),
            "masked": (base, 32, dict(sampler="euler", steps=5,
                                      init_latent=fx["init"], denoise=0.8,
                                      latent_mask=fx["mask"])),
        }
        first = _run_plans(sched, pair)
        full = _run_plans(sched, dict(pair, **{
            "p1": (base, 33, dict(sampler="euler", steps=5)),
            "p2": (base, 34, dict(sampler="euler", steps=4)),
        }))
        for k in pair:
            np.testing.assert_array_equal(np.asarray(first[k]),
                                          np.asarray(full[k]), err_msg=k)


class TestCapabilityDegradation:
    def test_oom_on_mixed_bucket_reseats_capabilities_bitwise(self):
        """Satellite: a dispatch OOM on a mixed-capability bucket width-halves
        and re-seats; the re-seated lanes reconstruct their capability state
        from step 0 and finish bit-identical to a clean run at the post-halve
        width (same program shape → same-program leg)."""
        fx = _fixtures()
        base = fx["base"]
        pair = {
            "lora": (base, 41, dict(sampler="euler", steps=5,
                                    lora=fx["lora1"])),
            "masked": (base, 42, dict(sampler="euler", steps=6,
                                      init_latent=fx["init"], denoise=0.8,
                                      latent_mask=fx["mask"])),
        }
        clean = ContinuousBatchingScheduler(max_width=2, auto=False).install()
        try:
            want = _run_plans(clean, pair)
        finally:
            clean.uninstall()
            clean.shutdown()
        s = ContinuousBatchingScheduler(max_width=4, auto=False).install()
        try:
            results = {}

            def worker(name, model, seed, kw):
                noise, ctx = mk_inputs(seed)
                results[name] = run_sampler(model, noise, ctx, **kw)

            threads = [_bg(worker, k, m, seed, kw)
                       for k, (m, seed, kw) in pair.items()]
            _wait_enqueued(s, 2)
            [b] = s.buckets.values()
            real = b.dispatch
            state = {"done": False}

            def boom():
                if not state["done"]:
                    state["done"] = True
                    raise RuntimeError("RESOURCE_EXHAUSTED: synthetic OOM")
                return real()

            b.dispatch = boom
            s.drain(timeout=120)
            for t in threads:
                t.join(60)
            assert len(results) == 2, sorted(results)
            widths = {bk.width for bk in s.buckets.values()}
            assert widths == {2}, widths
            for k in pair:
                np.testing.assert_array_equal(np.asarray(results[k]),
                                              np.asarray(want[k]), err_msg=k)
        finally:
            s.uninstall()
            s.shutdown()

    def test_conflicting_control_trunks_bounce_to_inline(self, sched):
        """One control-trunk identity per bucket epoch: a SECOND ControlNet
        (different params) arriving at the same bucket sheds to the inline
        path — and still completes correctly — instead of perturbing the
        seated control lane."""
        fx = _fixtures()
        base = fx["base"]
        other = apply_control(base, mk_ctrl(), fx["hint"] * 0.5, strength=0.3)
        plans = {
            "c1": (fx["merged"], 51, dict(sampler="euler", steps=5)),
            "c2": (other, 52, dict(sampler="euler", steps=5)),
        }
        sched.uninstall()
        inline = {k: run_sampler(m, *mk_inputs(seed), **kw)
                  for k, (m, seed, kw) in plans.items()}
        sched.install()
        results = _run_plans(sched, plans)
        for k in plans:
            np.testing.assert_allclose(np.asarray(results[k]),
                                       np.asarray(inline[k]), **TOL,
                                       err_msg=k)
        assert _metric_sum("pa_serving_ctrl_conflict_total") >= 1

    def test_ineligible_extras_fall_back_inline_with_counter(self, sched):
        """An extra cond with a different sequence length cannot share the
        lane program's role blocks: the run completes inline and ticks
        pa_serving_inline_fallback_total{reason=ineligible}."""
        base = mk_base()
        bad_extra = ({"context": jnp.zeros((1, 9, 16), jnp.float32),
                      "strength": 0.5},)
        before = registry.get(
            "pa_serving_inline_fallback_total",
            {"reason": "ineligible", "sampler": "euler"}) or 0
        noise, ctx = mk_inputs(61)
        got = run_sampler(base, noise, ctx, sampler="euler", steps=3,
                          extra_conds=bad_extra)
        sched.uninstall()
        want = run_sampler(base, noise, ctx, sampler="euler", steps=3,
                           extra_conds=bad_extra)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert registry.get(
            "pa_serving_inline_fallback_total",
            {"reason": "ineligible", "sampler": "euler"}) == before + 1


class TestMeshCapabilities:
    def test_masked_and_lora_lanes_on_virtual_mesh(self, sched, cpu_devices):
        """The capability axes compose with data sharding on the 8-device
        virtual mesh (lane axis = batch axis, width rounds to the mesh's
        data width)."""
        rng = np.random.default_rng(0)
        params = {"proj": {"kernel": jnp.asarray(
            rng.normal(size=(4, 4)), jnp.float32) * 0.2}}

        def apply(p, x, t, context=None, **kw):
            c = jnp.mean(context, axis=(1, 2)).reshape((-1, 1, 1, 1))
            h = x @ p["proj"]["kernel"]
            tt = t.reshape((-1, 1, 1, 1))
            return jnp.tanh(h + 0.1 * c) * (0.5 + 0.1 * tt / 1000.0)

        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize((apply, params), chain)
        fx = _fixtures()
        plans = {
            "masked": (pm, 71, dict(sampler="euler", steps=4,
                                    init_latent=fx["init"], denoise=0.8,
                                    latent_mask=fx["mask"])),
            "lora": (pm, 72, dict(sampler="euler", steps=5,
                                  lora=fx["lora1"])),
            "plain": (pm, 73, dict(sampler="euler", steps=6)),
        }
        sched.uninstall()
        inline = {k: run_sampler(m, *mk_inputs(seed), **kw)
                  for k, (m, seed, kw) in plans.items()}
        sched.install()
        results = _run_plans(sched, plans)
        [bucket] = sched.buckets.values()
        assert bucket.width == 8  # rounded to the mesh's data width
        for k in plans:
            np.testing.assert_allclose(np.asarray(results[k]),
                                       np.asarray(inline[k]), **TOL,
                                       err_msg=k)
