"""Weight-streaming executor (parallel/streaming.py + the orchestrator's
weights-don't-fit routing rung).

The contract under test, all off-hardware (the round-3 lesson: no code path
may execute first on an unattended live tunnel):

- streamed execution matches resident execution on the virtual 8-device mesh
  for BOTH a toy-FLUX topology and an SD1.5 topology (the UNet's staged
  PipelineSpec, models/unet.py);
- the residency accounting bounds peak streamed-weight bytes at ≤ 2 stages
  for a model whose total weights exceed the configured HBM budget;
- a streaming OOM re-carves at smaller stage size (the stream-mode demotion)
  instead of falling back to a full-pytree placement that cannot exist;
- streaming survives the full sampler: the eager denoise loop drives the
  per-stage programs every step, and ``compile_loop=True`` falls back (one
  XLA program would close over the full pytree).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, ParallelConfig, parallelize
from comfyui_parallelanything_tpu.models import build_unet, sd15_config
from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux
from comfyui_parallelanything_tpu.models.loader import carve_stages, params_nbytes
from comfyui_parallelanything_tpu.parallel.streaming import (
    StreamingRunner,
    build_streaming_runner,
)

TINY_FLUX = FluxConfig(
    in_channels=16,  # 4 latent ch x 2x2 patch
    hidden_size=64, num_heads=4, depth=2, depth_single_blocks=4,
    context_in_dim=32, vec_in_dim=16, axes_dim=(4, 6, 6),
    guidance_embed=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def flux_model():
    return build_flux(
        TINY_FLUX, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=16
    )


@pytest.fixture(scope="module")
def unet_model():
    cfg = sd15_config(
        model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
        attention_levels=(0, 1), context_dim=48, num_heads=4, norm_groups=8,
        dtype=jnp.float32,
    )
    return build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))


def _flux_inputs(batch):
    x = jax.random.normal(jax.random.key(1), (batch, 8, 8, 4))
    t = jnp.linspace(900.0, 1.0, batch)
    ctx = jax.random.normal(
        jax.random.key(2), (batch, 16, TINY_FLUX.context_in_dim)
    )
    y = jax.random.normal(jax.random.key(3), (batch, TINY_FLUX.vec_in_dim))
    return x, t, ctx, y


def _stream_pm(model, budget_frac=3, **cfg_kw):
    budget = params_nbytes(model.params) // budget_frac
    return parallelize(
        model, DeviceChain.even(["cpu:0"]),
        ParallelConfig(
            weight_sharding="stream", hbm_budget_bytes=budget, **cfg_kw
        ),
    )


class TestStreamedMatchesResident:
    def test_flux_topology_vs_8dev_mesh(self, flux_model, cpu_devices):
        """Streamed single-chip output == the resident 8-device DP output ==
        the bare apply, within bf16-scale tolerances (CLAUDE.md)."""
        batch = 8
        x, t, ctx, y = _flux_inputs(batch)
        bare = flux_model.apply(flux_model.params, x, t, ctx, y=y)
        resident = parallelize(
            flux_model, DeviceChain.even([f"cpu:{i}" for i in range(8)])
        )
        res = resident(x, t, ctx, y=y)
        pm = _stream_pm(flux_model)
        assert pm.is_streaming
        got = pm(x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(bare), rtol=2e-3, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(res), rtol=2e-3, atol=1e-4
        )

    def test_sd15_topology(self, unet_model):
        """The UNet's staged PipelineSpec (skip connections in the carry)
        streams correctly — SD-family models stream too, not just the
        block-list DiTs."""
        x = jax.random.normal(jax.random.key(1), (2, 16, 16, 4))
        t = jnp.linspace(900.0, 1.0, 2)
        ctx = jax.random.normal(jax.random.key(2), (2, 7, 48))
        want = unet_model.apply(unet_model.params, x, t, ctx)
        pm = _stream_pm(unet_model)
        got = pm(x, t, ctx)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=1e-4
        )
        assert pm._stream_runner.n_stages >= 2

    def test_overlap_off_debug_mode(self, flux_model):
        x, t, ctx, y = _flux_inputs(2)
        want = flux_model.apply(flux_model.params, x, t, ctx, y=y)
        pm = _stream_pm(flux_model, stream_overlap=False)
        got = pm(x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=1e-4
        )

    def test_batch_one_also_streams(self, flux_model):
        # batch==1 must NOT fall into pipeline block placement (which would
        # place the full pytree across devices) — streaming owns every batch.
        x, t, ctx, y = _flux_inputs(1)
        pm = _stream_pm(flux_model)
        got = pm(x, t, ctx, y=y)
        want = flux_model.apply(flux_model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=1e-4
        )
        assert pm._pipeline_runner is None


class TestResidencyBound:
    def test_peak_le_two_stages_when_weights_exceed_budget(self, flux_model):
        """The acceptance bound: for a model whose total weights exceed the
        configured HBM budget, peak streamed-weight bytes ≤ 2 stages."""
        total = params_nbytes(flux_model.params)
        budget = total // 3  # weights 3x the budget — cannot sit resident
        pm = parallelize(
            flux_model, DeviceChain.even(["cpu:0"]),
            ParallelConfig(hbm_budget_bytes=budget),  # replicate → auto-route
        )
        assert pm.is_streaming, "weights-don't-fit auto-routing must engage"
        x, t, ctx, y = _flux_inputs(2)
        pm(x, t, ctx, y=y)
        runner = pm._stream_runner
        tracker = runner.tracker
        assert runner.streamed_nbytes > budget  # the premise: doesn't fit
        assert runner.n_stages >= 2
        assert tracker.peak_bytes <= 2 * runner.max_stage_nbytes
        # Every stage retired: nothing left in the ring between calls.
        assert tracker.live_bytes == 0 and not tracker.live_tags
        # Resident prepare/finalize params are accounted separately and are
        # small next to the streamed stack.
        assert 0 < tracker.resident_bytes < runner.streamed_nbytes

    def test_two_calls_keep_the_bound(self, flux_model):
        pm = _stream_pm(flux_model)
        x, t, ctx, y = _flux_inputs(2)
        pm(x, t, ctx, y=y)
        pm(x, t, ctx, y=y)
        runner = pm._stream_runner
        assert runner.tracker.peak_bytes <= 2 * runner.max_stage_nbytes
        assert runner.tracker.live_bytes == 0

    def test_carve_stages_contiguous_and_bounded(self, flux_model):
        spec = flux_model.pipeline_spec
        sizes = [
            params_nbytes({k: flux_model.params[k] for k in seg.param_keys})
            for seg in spec.segments
        ]
        cap = max(sizes)  # every stage can hold >= 1 segment
        ranges = carve_stages(spec, flux_model.params, max_stage_bytes=cap)
        assert ranges[0][0] == 0 and ranges[-1][1] == len(spec.segments)
        for (s0, e0), (s1, _) in zip(ranges, ranges[1:]):
            assert e0 == s1  # contiguous, no overlap
        for s, e in ranges:
            # multi-segment stages respect the cap (single-segment stages are
            # the atomic unit and may exceed it by construction)
            if e - s > 1:
                assert sum(sizes[s:e]) <= cap


class TestStreamDemotion:
    def test_oom_recarves_to_more_stages(self, flux_model, monkeypatch):
        # Generous budget → coarse carve (few stages), so a re-carve has room
        # to halve the stage size before bottoming out at one segment each.
        pm = _stream_pm(flux_model, budget_frac=1)
        x, t, ctx, y = _flux_inputs(2)
        first = pm._get_streaming_runner()
        n0 = first.n_stages
        calls = {"n": 0}
        orig = StreamingRunner.__call__

        def flaky(self, *a, **kw):
            if self is first and calls["n"] == 0:
                calls["n"] += 1
                raise RuntimeError("RESOURCE_EXHAUSTED: fake streaming OOM")
            return orig(self, *a, **kw)

        monkeypatch.setattr(StreamingRunner, "__call__", flaky)
        got = pm(x, t, ctx, y=y)
        assert pm._stream_runner is not first
        assert pm._stream_runner.n_stages > n0
        want = flux_model.apply(flux_model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=1e-4
        )

    def test_recarve_bottoms_out_at_one_segment_per_stage(self, flux_model):
        runner = StreamingRunner(
            flux_model.pipeline_spec, flux_model.params,
            jax.devices("cpu")[0], max_stage_bytes=1,
        )
        assert runner.n_stages == len(flux_model.pipeline_spec.segments)
        assert runner.recarved() is None

    def test_recarve_refuses_no_progress_carve(self, flux_model):
        """When the byte cap is pinned by a lone oversized segment, halving
        it reproduces the identical carve — recarved() must return None
        (progress guarantee) or the _stream_call retry loop would respin a
        deterministic OOM forever."""
        spec = flux_model.pipeline_spec
        sizes = [
            params_nbytes({k: flux_model.params[k] for k in seg.param_keys})
            for seg in spec.segments
        ]
        # Cap below every segment: one segment per stage EXCEPT forced via a
        # cap just under the max segment — the max segment sits alone while
        # smaller neighbors still merge only if they fit; construct the
        # pinned case directly with cap = max segment size - 1.
        runner = StreamingRunner(
            spec, flux_model.params, jax.devices("cpu")[0],
            max_stage_bytes=max(sizes) - 1,
        )
        deeper = runner.recarved()
        # Either a strictly finer carve exists, or None — never an equal one.
        if deeper is not None:
            assert deeper.n_stages > runner.n_stages
        else:
            assert runner.max_stage_nbytes == max(sizes)

    def test_non_oom_errors_propagate(self, flux_model, monkeypatch):
        pm = _stream_pm(flux_model)
        monkeypatch.setattr(
            StreamingRunner, "__call__",
            lambda self, *a, **kw: (_ for _ in ()).throw(
                RuntimeError("unrelated failure")
            ),
        )
        with pytest.raises(RuntimeError, match="unrelated"):
            pm(*_flux_inputs(2)[:3], y=_flux_inputs(2)[3])


class TestRoutingAndGuards:
    def test_stream_requires_pipeline_spec(self):
        def f(p, x, t, context=None, **kw):
            return x * p["s"]

        with pytest.raises(ValueError, match="PipelineSpec"):
            parallelize(
                (f, {"s": jnp.float32(2.0)}), DeviceChain.even(["cpu:0"]),
                ParallelConfig(weight_sharding="stream"),
            )

    def test_no_auto_route_when_weights_fit(self, flux_model):
        pm = parallelize(
            flux_model, DeviceChain.even(["cpu:0"]),
            ParallelConfig(
                hbm_budget_bytes=params_nbytes(flux_model.params) * 10
            ),
        )
        assert not pm.is_streaming

    def test_traceable_and_single_stay_streamed(self, flux_model):
        pm = _stream_pm(flux_model)
        assert pm.traceable() is None  # no one-program path may exist
        x, t, ctx, y = _flux_inputs(2)
        got = pm.single(x, t, ctx, y=y)  # escape hatch streams too
        want = flux_model.apply(flux_model.params, x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=1e-4
        )

    def test_cleanup_drops_runner(self, flux_model):
        pm = _stream_pm(flux_model)
        pm(*_flux_inputs(1)[:3], y=_flux_inputs(1)[3])
        pm.cleanup()
        assert pm._stream_runner is None

    def test_build_streaming_runner_none_without_spec(self):
        assert build_streaming_runner(
            None, {}, jax.devices("cpu")[0]
        ) is None


class TestSamplerSurvivesStreaming:
    def test_full_sampler_eager_and_compile_loop_fallback(self, flux_model):
        """The whole denoise loop drives the per-stage programs each step;
        compile_loop=True silently (logged) falls back to the same eager
        path — both match the resident model's sampler output."""
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        batch = 2
        noise = jax.random.normal(jax.random.key(5), (batch, 8, 8, 4))
        _, _, ctx, y = _flux_inputs(batch)
        want = run_sampler(
            flux_model, noise, ctx, sampler="dpmpp_2m", steps=3, y=y
        )
        pm = _stream_pm(flux_model)
        eager = run_sampler(pm, noise, ctx, sampler="dpmpp_2m", steps=3, y=y)
        np.testing.assert_allclose(
            np.asarray(eager), np.asarray(want), rtol=2e-3, atol=1e-4
        )
        compiled = run_sampler(
            pm, noise, ctx, sampler="dpmpp_2m", steps=3, y=y,
            compile_loop=True,
        )
        np.testing.assert_allclose(
            np.asarray(compiled), np.asarray(want), rtol=2e-3, atol=1e-4
        )
        # The residency bound held across every sampler step.
        runner = pm._stream_runner
        assert runner.tracker.peak_bytes <= 2 * runner.max_stage_nbytes
        assert runner.tracker.live_bytes == 0
