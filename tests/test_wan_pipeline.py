"""WAN text→video pipeline: prompt → UMT5-class encode → flow-matching denoise
(routed through the parallel scheduler) → causal 3D VAE decode, on tiny models.
Also covers the video nodes (TPUEmptyVideoLatent) and the parallelized path over
the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import comfyui_parallelanything_tpu as pa
from comfyui_parallelanything_tpu.models import (
    T5Config,
    VideoVAEConfig,
    WanConfig,
    build_t5_encoder,
    build_video_vae,
    build_wan,
)
from comfyui_parallelanything_tpu.pipelines import WanVideoPipeline

from test_tokenizer import _tiny_tokenizer

ZC = 4


@pytest.fixture(scope="module")
def wan_pipe():
    tok = _tiny_tokenizer()
    tcfg = T5Config(
        vocab_size=64, d_model=32, d_kv=8, d_ff=64, num_layers=2, num_heads=4,
        dtype=jnp.float32,
    )
    wcfg = WanConfig(
        in_channels=ZC, out_channels=ZC, hidden_size=48, ffn_dim=96,
        num_heads=4, depth=2, text_dim=32, freq_dim=16, dtype=jnp.float32,
    )
    vcfg = VideoVAEConfig(
        base_channels=8, channel_mult=(1, 2, 2), num_res_blocks=1,
        temporal_downsample=(False, True), z_channels=ZC,
        latent_mean=(0.0,) * ZC, latent_std=(1.0,) * ZC, dtype=jnp.float32,
    )
    return WanVideoPipeline(
        dit=build_wan(
            wcfg, jax.random.key(0), sample_shape=(1, 2, 4, 4, ZC), txt_len=6
        ),
        vae=build_video_vae(vcfg, jax.random.key(1), sample_thw=(3, 8, 8)),
        t5=build_t5_encoder(tcfg, jax.random.key(2), sample_len=8),
        t5_tokenizer=tok,
    )


class TestWanVideoPipeline:
    def test_prompt_to_video_shape_and_range(self, wan_pipe):
        # tf=2 → frames must be odd; 5 frames → 3 latent frames.
        video = wan_pipe(
            "hello", steps=2, cfg_scale=1.0, height=16, width=16, frames=5,
            shift=3.0,
        )
        assert video.shape == (1, 5, 16, 16, 3)
        a = np.asarray(video)
        assert np.isfinite(a).all() and a.min() >= 0.0 and a.max() <= 1.0

    def test_cfg_changes_output(self, wan_pipe):
        kw = dict(steps=2, height=16, width=16, frames=5, rng=jax.random.key(3))
        base = np.asarray(wan_pipe("hello", cfg_scale=1.0, **kw))
        cfg = np.asarray(
            wan_pipe("hello", negative_prompt="world", cfg_scale=5.0, **kw)
        )
        assert not np.allclose(base, cfg)

    def test_off_schedule_frames_rejected(self, wan_pipe):
        with pytest.raises(ValueError, match="1 mod"):
            wan_pipe("hello", steps=1, frames=4, height=16, width=16)

    def test_bad_resolution_rejected(self, wan_pipe):
        with pytest.raises(ValueError, match="multiples"):
            wan_pipe("hello", steps=1, frames=5, height=20, width=16)

    def test_parallelized_video_batch(self, wan_pipe):
        """Batch=2 video over the 8-device chain routes through the DP/pipeline
        scheduler exactly like the reference's wrapped forward."""
        chain = pa.DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = pa.parallelize(wan_pipe.dit, chain)
        pipe = WanVideoPipeline(
            dit=pm, vae=wan_pipe.vae, t5=wan_pipe.t5,
            t5_tokenizer=wan_pipe.t5_tokenizer,
        )
        video = pipe(
            ["hello", "world"], steps=2, cfg_scale=1.0, height=16, width=16,
            frames=5,
        )
        assert video.shape == (2, 5, 16, 16, 3)
        assert np.isfinite(np.asarray(video)).all()


class TestVideoNodes:
    def test_empty_video_latent_shapes(self):
        from comfyui_parallelanything_tpu.nodes import TPUEmptyVideoLatent

        (latent,) = TPUEmptyVideoLatent().generate(
            width=64, height=32, frames=9, batch_size=2, channels=16
        )
        # wan schedule: tf=4 → 9 frames → 3 latent frames; f=8 spatial.
        assert latent["samples"].shape == (2, 3, 4, 8, 16)

    def test_empty_video_latent_rejects_off_schedule(self):
        from comfyui_parallelanything_tpu.nodes import TPUEmptyVideoLatent

        with pytest.raises(ValueError, match="1 mod"):
            TPUEmptyVideoLatent().generate(
                width=64, height=32, frames=8, batch_size=1
            )

    def test_vae_decode_node_handles_video(self, wan_pipe):
        from comfyui_parallelanything_tpu.nodes import TPUVAEDecode

        z = jax.random.normal(jax.random.key(5), (1, 3, 4, 4, ZC))
        (img,) = TPUVAEDecode().decode(wan_pipe.vae, {"samples": z})
        assert img.shape == (1, 5, 16, 16, 3)
        a = np.asarray(img)
        assert a.min() >= 0.0 and a.max() <= 1.0


class TestImageToVideo:
    @pytest.fixture(scope="class")
    def i2v_pipe(self, wan_pipe):
        """Same VAE/T5 as the module pipe but an i2v DiT (in = 2*zc + 4)."""
        wcfg = WanConfig(
            in_channels=2 * ZC + 4, out_channels=ZC, hidden_size=48, ffn_dim=96,
            num_heads=4, depth=2, text_dim=32, freq_dim=16, dtype=jnp.float32,
        )
        dit = build_wan(
            wcfg, jax.random.key(4), sample_shape=(1, 2, 4, 4, 2 * ZC + 4),
            txt_len=6,
        )
        return WanVideoPipeline(
            dit=dit, vae=wan_pipe.vae, t5=wan_pipe.t5,
            t5_tokenizer=wan_pipe.t5_tokenizer,
        )

    def test_image_to_video(self, i2v_pipe):
        img = jnp.full((1, 16, 16, 3), 0.6)
        video = i2v_pipe(
            "hello", steps=2, cfg_scale=1.0, height=16, width=16, frames=5,
            image=img,
        )
        assert video.shape == (1, 5, 16, 16, 3)
        assert np.isfinite(np.asarray(video)).all()

    def test_image_changes_output(self, i2v_pipe):
        kw = dict(steps=2, cfg_scale=1.0, height=16, width=16, frames=5,
                  rng=jax.random.key(8))
        a = np.asarray(i2v_pipe("hello", image=jnp.zeros((1, 16, 16, 3)), **kw))
        b = np.asarray(i2v_pipe("hello", image=jnp.ones((1, 16, 16, 3)), **kw))
        assert not np.allclose(a, b)

    def test_t2v_model_rejected_for_i2v(self, wan_pipe):
        with pytest.raises(ValueError, match="i2v"):
            wan_pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16, frames=5,
                image=jnp.zeros((1, 16, 16, 3)),
            )

    def test_image_shape_mismatch_rejected(self, i2v_pipe):
        with pytest.raises(ValueError, match="image is"):
            i2v_pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16, frames=5,
                image=jnp.zeros((1, 8, 8, 3)),
            )


class TestI2VWithCFG:
    def test_i2v_under_default_cfg(self, wan_pipe):
        """CFG doubles the forward batch; the i2v cond tensor must ride along
        for both halves (this is the pipeline's DEFAULT cfg_scale path)."""
        wcfg = WanConfig(
            in_channels=2 * ZC + 4, out_channels=ZC, hidden_size=48, ffn_dim=96,
            num_heads=4, depth=1, text_dim=32, freq_dim=16, dtype=jnp.float32,
        )
        pipe = WanVideoPipeline(
            dit=build_wan(
                wcfg, jax.random.key(4), sample_shape=(1, 2, 4, 4, 2 * ZC + 4),
                txt_len=6,
            ),
            vae=wan_pipe.vae, t5=wan_pipe.t5,
            t5_tokenizer=wan_pipe.t5_tokenizer,
        )
        video = pipe(
            "hello", negative_prompt="world", steps=2, cfg_scale=5.0,
            height=16, width=16, frames=5, image=jnp.full((1, 16, 16, 3), 0.4),
        )
        assert video.shape == (1, 5, 16, 16, 3)
        assert np.isfinite(np.asarray(video)).all()

    def test_denoise_without_init_video_rejected_at_pipeline(self, wan_pipe):
        with pytest.raises(ValueError, match="init_video"):
            wan_pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16, frames=5,
                denoise=0.5,
            )


class TestVideoInpaint:
    def test_mask_preserves_region(self, wan_pipe):
        from comfyui_parallelanything_tpu.models.vae import (
            images_to_vae_input, vae_output_to_images,
        )

        init = jnp.full((1, 5, 16, 16, 3), 0.5)
        # regenerate only the top half of every frame
        m = jnp.zeros((1, 5, 16, 16)).at[:, :, :8].set(1.0)
        video = np.asarray(wan_pipe(
            "hello", steps=2, cfg_scale=1.0, height=16, width=16, frames=5,
            init_video=init, mask=m, shift=1.0,
        ))
        assert video.shape == (1, 5, 16, 16, 3)
        # Keep region must land on the VAE round-trip of the init clip (the
        # final masked-callback pin is the un-noised init latent); a dropped
        # latent_mask would fail this.
        target = np.asarray(vae_output_to_images(
            wan_pipe.vae.decode(wan_pipe.vae.encode(images_to_vae_input(init)))
        ))
        kept_err = np.abs(video[:, :, 10:] - target[:, :, 10:]).mean()
        unmasked = np.asarray(wan_pipe(
            "hello", steps=2, cfg_scale=1.0, height=16, width=16, frames=5,
            shift=1.0,
        ))
        unmasked_err = np.abs(unmasked[:, :, 10:] - target[:, :, 10:]).mean()
        assert kept_err < unmasked_err, (kept_err, unmasked_err)

    def test_mask_frame_count_resizes_to_schedule(self, wan_pipe):
        """A mask with a different frame count resizes onto the pipeline's
        latent frame grid instead of crashing mid-sampler."""
        init = jnp.full((1, 5, 16, 16, 3), 0.5)
        m = jnp.ones((1, 9, 16, 16))
        video = wan_pipe(
            "hello", steps=1, cfg_scale=1.0, height=16, width=16, frames=5,
            init_video=init, mask=m,
        )
        assert video.shape == (1, 5, 16, 16, 3)

    def test_mask_without_init_video_rejected(self, wan_pipe):
        with pytest.raises(ValueError, match="init_video"):
            wan_pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16, frames=5,
                mask=jnp.ones((1, 5, 16, 16)),
            )


class TestI2VClipVision:
    """WAN2.1-style i2v (img_dim set): clip_vision_output rides the img_emb
    branch through the pipeline and the WanImageToVideo stock node."""

    @pytest.fixture(scope="class")
    def i2v_clip_pipe(self, wan_pipe):
        wcfg = WanConfig(
            in_channels=2 * ZC + 4, out_channels=ZC, hidden_size=48,
            ffn_dim=96, num_heads=4, depth=2, text_dim=32, freq_dim=16,
            img_dim=24, dtype=jnp.float32,
        )
        dit = build_wan(
            wcfg, jax.random.key(6), sample_shape=(1, 2, 4, 4, 2 * ZC + 4),
            txt_len=6,
        )
        return WanVideoPipeline(
            dit=dit, vae=wan_pipe.vae, t5=wan_pipe.t5,
            t5_tokenizer=wan_pipe.t5_tokenizer,
        )

    def _cvo(self, b=1):
        return {
            "penultimate": jax.random.normal(
                jax.random.key(11), (b, 5, 24), jnp.float32
            )
        }

    def test_clip_vision_output_changes_video(self, i2v_clip_pipe):
        kw = dict(steps=2, cfg_scale=1.0, height=16, width=16, frames=5,
                  rng=jax.random.key(12), image=jnp.full((1, 16, 16, 3), 0.5))
        a = np.asarray(i2v_clip_pipe("hello", **kw))
        b = np.asarray(
            i2v_clip_pipe("hello", clip_vision_output=self._cvo(), **kw)
        )
        assert a.shape == b.shape == (1, 5, 16, 16, 3)
        assert not np.allclose(a, b)
        assert np.isfinite(b).all()

    def test_clip_vision_under_cfg(self, i2v_clip_pipe):
        video = i2v_clip_pipe(
            "hello", negative_prompt="world", steps=2, cfg_scale=5.0,
            height=16, width=16, frames=5,
            image=jnp.full((1, 16, 16, 3), 0.4),
            clip_vision_output=self._cvo(),
        )
        assert np.isfinite(np.asarray(video)).all()

    def test_clip_vision_on_clipless_model_rejected(self, i2v_pipe_factory):
        pipe = i2v_pipe_factory
        with pytest.raises(ValueError, match="img_emb"):
            pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16,
                frames=5, image=jnp.zeros((1, 16, 16, 3)),
                clip_vision_output=self._cvo(),
            )

    def test_clip_vision_without_image_rejected(self, i2v_clip_pipe):
        with pytest.raises(ValueError, match="start image"):
            i2v_clip_pipe(
                "hello", steps=1, cfg_scale=1.0, height=16, width=16,
                frames=5, clip_vision_output=self._cvo(),
            )

    @pytest.fixture(scope="class")
    def i2v_pipe_factory(self, wan_pipe):
        wcfg = WanConfig(
            in_channels=2 * ZC + 4, out_channels=ZC, hidden_size=48,
            ffn_dim=96, num_heads=4, depth=1, text_dim=32, freq_dim=16,
            dtype=jnp.float32,
        )
        dit = build_wan(
            wcfg, jax.random.key(7), sample_shape=(1, 2, 4, 4, 2 * ZC + 4),
            txt_len=6,
        )
        return WanVideoPipeline(
            dit=dit, vae=wan_pipe.vae, t5=wan_pipe.t5,
            t5_tokenizer=wan_pipe.t5_tokenizer,
        )


class TestWanImageToVideoNode:
    def test_node_builds_latent_and_tags(self, wan_pipe):
        from comfyui_parallelanything_tpu.nodes_compat import WanImageToVideo

        pos = {"context": jnp.zeros((1, 6, 32))}
        neg = {"context": jnp.zeros((1, 6, 32))}
        cvo = {"penultimate": jnp.ones((1, 5, 24))}
        p2, n2, lat = WanImageToVideo().encode(
            pos, neg, wan_pipe.vae, width=16, height=16, length=5,
            batch_size=2, start_image=jnp.full((1, 16, 16, 3), 0.5),
            clip_vision_output=cvo,
        )
        # tf=2 in the tiny VAE: 5 frames -> 3 latent frames; f=4 spatial.
        f = wan_pipe.vae.spatial_factor
        assert lat["samples"].shape == (2, 3, 16 // f, 16 // f, ZC)
        assert "i2v" in p2 and "i2v" in n2
        cond = p2["i2v"]["cond"]
        assert cond.shape == (1, 3, 16 // f, 16 // f, 4 + ZC)
        m = np.asarray(cond[..., :4])
        # Only the first latent frame is given (F=1): all 4 fold channels on.
        assert m[:, 0].min() == 1.0 and m[:, 1:].max() == 0.0
        assert p2["i2v"]["clip_fea"] is cvo["penultimate"]

    def test_node_samples_through_ksampler(self, wan_pipe):
        """The i2v tag composes into the model inside TPUKSampler: a full
        node-path denoise run on a clip-branch i2v DiT."""
        from comfyui_parallelanything_tpu.nodes import TPUKSampler
        from comfyui_parallelanything_tpu.nodes_compat import WanImageToVideo

        wcfg = WanConfig(
            in_channels=2 * ZC + 4, out_channels=ZC, hidden_size=48,
            ffn_dim=96, num_heads=4, depth=1, text_dim=32, freq_dim=16,
            img_dim=24, dtype=jnp.float32,
        )
        dit = build_wan(
            wcfg, jax.random.key(8), sample_shape=(1, 2, 4, 4, 2 * ZC + 4),
            txt_len=6,
        )
        pos = {"context": jnp.zeros((1, 6, 32))}
        neg = {"context": jnp.zeros((1, 6, 32))}
        p2, n2, lat = WanImageToVideo().encode(
            pos, neg, wan_pipe.vae, width=16, height=16, length=5,
            batch_size=1, start_image=jnp.full((1, 16, 16, 3), 0.5),
            clip_vision_output={"penultimate": jnp.ones((1, 5, 24))},
        )
        (out,) = TPUKSampler().sample(
            dit, p2, lat, seed=0, steps=2, cfg=1.0,
            sampler_name="euler", scheduler="normal", negative=n2,
        )
        assert out["samples"].shape == lat["samples"].shape
        assert np.isfinite(np.asarray(out["samples"])).all()
