"""SD3/SD3.5 MMDiT joint-block golden parity vs a minimal torch reference.

The torch reference follows the public SAI MMDiT design: per-stream adaLN (SAI
6-chunk order: shift/scale/gate for attn, then for mlp), fused qkv with optional
per-head-dim q/k RMSNorm (SD3.5), joint attention over [context ‖ x], per-stream
proj + tanh-GELU MLP, and a pre-only final context block (qkv in, no out path).
Exported in the official ``joint_blocks.{i}.{x,context}_block`` key layout, mapped
with ``convert_mmdit.py``'s helpers, compared activation-for-activation against
``models/mmdit.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models.convert_mmdit import _attn_in, _dense
from comfyui_parallelanything_tpu.models.mmdit import JointBlock, MMDiTConfig

from test_golden_flux import t_attention

torch = pytest.importorskip("torch")
tnn = torch.nn
F = torch.nn.functional

CFG = MMDiTConfig(
    in_channels=4,
    patch_size=2,
    depth=2,            # hidden 128, heads 2, head_dim 64
    context_in_dim=32,
    pooled_dim=24,
    pos_embed_max=8,
    qk_norm=True,       # exercise the SD3.5 per-head q/k RMS path
    dtype=jnp.float32,
)
H_ = CFG.hidden_size


class TRMS(tnn.Module):
    def __init__(self, dim):
        super().__init__()
        self.weight = tnn.Parameter(torch.randn(dim))

    def forward(self, x):
        x32 = x.float()
        n = x32 * torch.rsqrt(x32.pow(2).mean(-1, keepdim=True) + 1e-6)
        return n * self.weight


class TAttn(tnn.Module):
    """Keys: .qkv / .ln_q.weight / .ln_k.weight / .proj."""

    def __init__(self, h, head_dim, pre_only=False):
        super().__init__()
        self.qkv = tnn.Linear(h, 3 * h)
        self.ln_q = TRMS(head_dim)
        self.ln_k = TRMS(head_dim)
        if not pre_only:
            self.proj = tnn.Linear(h, h)


class TMlp(tnn.Module):
    def __init__(self, h, mlp_dim):
        super().__init__()
        self.fc1 = tnn.Linear(h, mlp_dim)
        self.fc2 = tnn.Linear(mlp_dim, h)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate="tanh"))


class TStreamBlock(tnn.Module):
    def __init__(self, h, heads, mlp_dim, pre_only=False, dual=False):
        super().__init__()
        self.heads = heads
        self.pre_only = pre_only
        self.dual = dual
        n_mods = 2 if pre_only else (9 if dual else 6)
        self.adaLN_modulation = tnn.Sequential(tnn.SiLU(), tnn.Linear(h, n_mods * h))
        self.attn = TAttn(h, h // heads, pre_only)
        if dual:
            self.attn2 = TAttn(h, h // heads)
        if not pre_only:
            self.mlp = TMlp(h, mlp_dim)


def _ln(x, h):
    return F.layer_norm(x, (h,), eps=1e-6)


def _mods(blk, vec, n):
    return blk.adaLN_modulation(vec.float())[:, None, :].chunk(n, dim=-1)


def _qkv_heads(blk, x, heads, shift, scale):
    b, s, h = x.shape
    d = h // heads
    hn = _ln(x, h).float() * (1 + scale) + shift
    qkv = blk.attn.qkv(hn).reshape(b, s, 3, heads, d)
    q = blk.attn.ln_q(qkv[:, :, 0])
    k = blk.attn.ln_k(qkv[:, :, 1])
    return hn, q, k, qkv[:, :, 2]


def t_joint_block(xb, cb, x, ctx, vec, heads, pre_only):
    h = x.shape[-1]
    if xb.dual:
        # SAI mmdit-x 9-chunk order: attn triple, mlp triple, attn2 triple; both
        # attention inputs modulate the SAME pre-norm output.
        xs1, xc1, xg1, xs2, xc2, xg2, x2s, x2c, x2g = _mods(xb, vec, 9)
        b, s, _ = x.shape
        d = h // heads
        h2 = _ln(x, h).float() * (1 + x2c) + x2s
        qkv2 = xb.attn2.qkv(h2).reshape(b, s, 3, heads, d)
        q2 = xb.attn2.ln_q(qkv2[:, :, 0])
        k2 = xb.attn2.ln_k(qkv2[:, :, 1])
        v2 = qkv2[:, :, 2]
    else:
        xs1, xc1, xg1, xs2, xc2, xg2 = _mods(xb, vec, 6)
    _, xq, xk, xv = _qkv_heads(xb, x, heads, xs1, xc1)
    if pre_only:
        cs1, cc1 = _mods(cb, vec, 2)
    else:
        cs1, cc1, cg1, cs2, cc2, cg2 = _mods(cb, vec, 6)
    _, cq, ck, cv = _qkv_heads(cb, ctx, heads, cs1, cc1)

    ctx_len = ctx.shape[1]
    q = torch.cat([cq, xq], dim=1)
    k = torch.cat([ck, xk], dim=1)
    v = torch.cat([cv, xv], dim=1)
    attn = t_attention(q, k, v).reshape(q.shape[0], q.shape[1], -1)
    ctx_a, x_a = attn[:, :ctx_len], attn[:, ctx_len:]

    x = x + xg1 * xb.attn.proj(x_a)
    if xb.dual:
        a2 = t_attention(q2, k2, v2).reshape(q2.shape[0], q2.shape[1], -1)
        x = x + x2g * xb.attn2.proj(a2)
    x = x + xg2 * xb.mlp(_ln(x, h).float() * (1 + xc2) + xs2)
    if pre_only:
        return x, ctx
    ctx = ctx + cg1 * cb.attn.proj(ctx_a)
    ctx = ctx + cg2 * cb.mlp(_ln(ctx, h).float() * (1 + cc2) + cs2)
    return x, ctx


def _block_params(sd, i, pre_only, dual=False):
    xb = f"joint_blocks.{i}.x_block"
    cb = f"joint_blocks.{i}.context_block"
    blk = {
        "x_adaln": {"lin": _dense(sd, f"{xb}.adaLN_modulation.1")},
        "x_attn_in": _attn_in(sd, f"{xb}.attn", CFG),
        "x_attn_proj": _dense(sd, f"{xb}.attn.proj"),
        "x_mlp_in": _dense(sd, f"{xb}.mlp.fc1"),
        "x_mlp_out": _dense(sd, f"{xb}.mlp.fc2"),
        "ctx_adaln": {"lin": _dense(sd, f"{cb}.adaLN_modulation.1")},
        "ctx_attn_in": _attn_in(sd, f"{cb}.attn", CFG),
    }
    if dual:
        blk["x_attn_in2"] = _attn_in(sd, f"{xb}.attn2", CFG)
        blk["x_attn2_proj"] = _dense(sd, f"{xb}.attn2.proj")
    if not pre_only:
        blk["ctx_attn_proj"] = _dense(sd, f"{cb}.attn.proj")
        blk["ctx_mlp_in"] = _dense(sd, f"{cb}.mlp.fc1")
        blk["ctx_mlp_out"] = _dense(sd, f"{cb}.mlp.fc2")
    return blk


@pytest.mark.parametrize("pre_only", [False, True])
def test_joint_block_golden_parity(pre_only):
    torch.manual_seed(4)
    mlp_dim = int(H_ * CFG.mlp_ratio)
    xb = TStreamBlock(H_, CFG.num_heads, mlp_dim, pre_only=False).eval()
    cb = TStreamBlock(H_, CFG.num_heads, mlp_dim, pre_only=pre_only).eval()
    sd = {f"joint_blocks.0.x_block.{k}": v.detach() for k, v in xb.state_dict().items()}
    sd.update(
        {f"joint_blocks.0.context_block.{k}": v.detach()
         for k, v in cb.state_dict().items()}
    )
    params = _block_params(sd, 0, pre_only)

    rng = np.random.default_rng(21)
    B, S, L = 2, 12, 6
    x = rng.normal(size=(B, S, H_)).astype(np.float32)
    ctx = rng.normal(size=(B, L, H_)).astype(np.float32)
    vec = rng.normal(size=(B, H_)).astype(np.float32)

    with torch.no_grad():
        w_x, w_ctx = t_joint_block(
            xb, cb, torch.from_numpy(x), torch.from_numpy(ctx),
            torch.from_numpy(vec), CFG.num_heads, pre_only,
        )
    got_x, got_ctx = JointBlock(CFG, pre_only=pre_only).apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        jnp.asarray(x), jnp.asarray(ctx), jnp.asarray(vec),
    )
    np.testing.assert_allclose(np.asarray(got_x), w_x.numpy(), rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(got_ctx), w_ctx.numpy(), rtol=5e-4, atol=5e-4)


def test_dual_attention_block_golden_parity():
    # SD3.5-medium mmdit-x: second self-attention over the x stream, 9-chunk
    # x-side adaLN, fed from the same pre-norm output.
    torch.manual_seed(6)
    mlp_dim = int(H_ * CFG.mlp_ratio)
    xb = TStreamBlock(H_, CFG.num_heads, mlp_dim, dual=True).eval()
    cb = TStreamBlock(H_, CFG.num_heads, mlp_dim).eval()
    sd = {f"joint_blocks.0.x_block.{k}": v.detach() for k, v in xb.state_dict().items()}
    sd.update(
        {f"joint_blocks.0.context_block.{k}": v.detach()
         for k, v in cb.state_dict().items()}
    )
    params = _block_params(sd, 0, pre_only=False, dual=True)

    rng = np.random.default_rng(23)
    B, S, L = 2, 12, 6
    x = rng.normal(size=(B, S, H_)).astype(np.float32)
    ctx = rng.normal(size=(B, L, H_)).astype(np.float32)
    vec = rng.normal(size=(B, H_)).astype(np.float32)

    with torch.no_grad():
        w_x, w_ctx = t_joint_block(
            xb, cb, torch.from_numpy(x), torch.from_numpy(ctx),
            torch.from_numpy(vec), CFG.num_heads, pre_only=False,
        )
    got_x, got_ctx = JointBlock(CFG, pre_only=False, dual_attn=True).apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        jnp.asarray(x), jnp.asarray(ctx), jnp.asarray(vec),
    )
    np.testing.assert_allclose(np.asarray(got_x), w_x.numpy(), rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(got_ctx), w_ctx.numpy(), rtol=5e-4, atol=5e-4)


def test_converter_infers_dual_attention_layers():
    # The converter must refuse a config that disagrees with the checkpoint's
    # actual attn2 layout (silently dropping weights is the failure this guards).
    from comfyui_parallelanything_tpu.models.convert_mmdit import (
        convert_mmdit_checkpoint,
    )

    torch.manual_seed(8)
    sd = {"joint_blocks.0.x_block.attn2.qkv.weight": torch.randn(3 * H_, H_)}
    with pytest.raises(ValueError, match="x_block_self_attn_layers"):
        convert_mmdit_checkpoint(sd, CFG)  # CFG declares no dual layers
