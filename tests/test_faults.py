"""Unified fault-injection registry (utils/faults.py) + shared retry policy
(utils/retry.py): plan parsing, the one arming rule, deterministic seeded
firing schedules, the legacy PA_FAIL_INJECT aliases, the tier-1 no-op
contract for the disabled path, and the backoff/jitter/deadline math every
fleet loop now rides."""

from __future__ import annotations

import json

import pytest

from comfyui_parallelanything_tpu.utils import faults, retry
from comfyui_parallelanything_tpu.utils.faults import (
    FAULT_SITES,
    FaultPlanError,
    FaultRegistry,
    FaultSpec,
    parse_plan,
)


def _schedule(reg: FaultRegistry, site: str, n: int, key: str = "") -> list[bool]:
    """Fire pattern over n consecutive eligible hits."""
    return [reg.check(site, key=key) is not None for _ in range(n)]


class TestPlanParsing:
    def test_dict_and_list_forms(self):
        seed, specs = parse_plan('{"seed": 3, "faults": '
                                 '[{"site": "slow-host", "nth": 2}]}')
        assert seed == 3 and len(specs) == 1
        assert specs[0].site == "slow-host" and specs[0].nth == 2
        seed2, specs2 = parse_plan('[{"site": "slow-host"}]')
        assert seed2 == 0 and len(specs2) == 1

    def test_unknown_site_fails_loudly(self):
        """A typo'd site must fail at parse — a plan that silently never
        fires is worse than no plan."""
        with pytest.raises(FaultPlanError, match="unknown fault site"):
            parse_plan('[{"site": "strem-prefetch-oom"}]')

    def test_bad_json_fails_loudly(self):
        with pytest.raises(FaultPlanError, match="not JSON"):
            parse_plan("{nope")

    def test_entry_must_carry_site(self):
        with pytest.raises(FaultPlanError, match="'site'"):
            parse_plan('[{"match": "x"}]')

    def test_every_site_documented(self):
        for site, doc in FAULT_SITES.items():
            assert doc, site


class TestFiringSemantics:
    def test_nth_and_count_window(self):
        reg = FaultRegistry(specs=[FaultSpec(site="slow-host", nth=3, count=2)])
        assert _schedule(reg, "slow-host", 6) == [
            False, False, True, True, False, False,
        ]

    def test_count_none_fires_forever_from_nth(self):
        reg = FaultRegistry(
            specs=[FaultSpec(site="mid-step-crash", nth=2, count=None)]
        )
        assert _schedule(reg, "mid-step-crash", 4) == [
            False, True, True, True,
        ]

    def test_match_substring_filters_key(self):
        reg = FaultRegistry(specs=[
            FaultSpec(site="backend-http", match="POST /prompt", nth=1),
        ])
        assert reg.check("backend-http", key="GET /health") is None
        act = reg.check("backend-http", key="POST /prompt")
        assert act is not None and act.hit == 1

    def test_site_mismatch_never_fires(self):
        reg = FaultRegistry(specs=[FaultSpec(site="slow-host", nth=1)])
        assert reg.check("backend-http", key="POST /prompt") is None

    def test_seeded_schedule_deterministic(self):
        """The chaos contract: same plan (same seed) → identical firing
        schedule; the derived nth is a pure function of (seed, site, match)
        inside [1, 4]."""
        plan = {"seed": 11, "faults": [{"site": "slow-host"},
                                       {"site": "backend-http"}]}
        seed, specs = parse_plan(json.dumps(plan))
        r1 = FaultRegistry(seed=seed, specs=specs)
        r2 = FaultRegistry(seed=seed, specs=parse_plan(json.dumps(plan))[1])
        for site in ("slow-host", "backend-http"):
            assert _schedule(r1, site, 8) == _schedule(r2, site, 8)
        for spec in specs:
            assert 1 <= spec.resolved_nth(seed) <= 4
            assert spec.resolved_nth(seed) == spec.resolved_nth(seed)

    def test_fired_counts_and_reset(self):
        reg = FaultRegistry(specs=[FaultSpec(site="slow-host", nth=1, count=1)])
        assert _schedule(reg, "slow-host", 3) == [True, False, False]
        assert reg.fired() == {"slow-host": 1}
        reg.reset()
        assert reg.fired() == {}
        assert reg.check("slow-host") is not None  # re-armed

    def test_fired_fault_counts_metric(self):
        from comfyui_parallelanything_tpu.utils.metrics import registry

        before = registry.get("pa_fault_injected_total",
                              {"site": "slow-host"}) or 0.0
        reg = FaultRegistry(specs=[FaultSpec(site="slow-host", nth=1)])
        assert reg.check("slow-host") is not None
        after = registry.get("pa_fault_injected_total", {"site": "slow-host"})
        assert after == before + 1

    def test_fired_fault_records_span(self):
        from comfyui_parallelanything_tpu.utils import tracing

        tracing.enable()
        try:
            reg = FaultRegistry(specs=[FaultSpec(site="slow-host", nth=1)])
            assert reg.check("slow-host", key="p1") is not None
            events = [e for e in tracing.export()["traceEvents"]
                      if e.get("ph") == "X" and e["name"] == "fault-injected"]
            assert events and events[-1]["cat"] == "faults"
            assert events[-1]["args"]["site"] == "slow-host"
        finally:
            tracing.disable()

    def test_oom_error_matches_oom_classifier(self):
        from comfyui_parallelanything_tpu.utils.telemetry import looks_like_oom

        reg = FaultRegistry(specs=[FaultSpec(site="mid-step-crash", nth=1)])
        act = reg.check("mid-step-crash")
        assert looks_like_oom(faults.oom_error(act))


class TestArmingRule:
    def test_plan_without_redirect_never_fires(self, monkeypatch):
        """The one rule: an armed plan requires the evidence/ledger
        redirect — injected failures must never pollute real evidence."""
        monkeypatch.delenv("PA_EVIDENCE_DIR", raising=False)
        monkeypatch.delenv("PA_LEDGER_DIR", raising=False)
        monkeypatch.setenv("PA_FAULT_PLAN", '[{"site": "slow-host", "nth": 1}]')
        reg = FaultRegistry.from_env()
        assert not reg.armed
        assert reg.check("slow-host") is None

    def test_plan_with_redirect_armed(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAULT_PLAN", '[{"site": "slow-host", "nth": 1}]')
        reg = FaultRegistry.from_env()
        assert reg.armed and reg.check("slow-host") is not None

    def test_no_env_is_noop(self, monkeypatch):
        monkeypatch.delenv("PA_FAULT_PLAN", raising=False)
        monkeypatch.delenv("PA_FAIL_INJECT", raising=False)
        reg = FaultRegistry.from_env()
        assert not reg.armed and reg.check("slow-host") is None

    def test_module_disabled_path_is_noop(self, monkeypatch):
        """The tier-1 contract: with nothing armed, the module-level hook is
        a flag read returning None and the counter never moves."""
        from comfyui_parallelanything_tpu.utils.metrics import registry

        monkeypatch.delenv("PA_FAULT_PLAN", raising=False)
        monkeypatch.delenv("PA_FAIL_INJECT", raising=False)
        faults.reload()
        before = registry.get("pa_fault_injected_total") or 0.0
        for site in FAULT_SITES:
            assert faults.check(site, key="anything") is None
        assert not faults.active()
        assert (registry.get("pa_fault_injected_total") or 0.0) == before

    def test_refresh_tracks_env_changes(self, monkeypatch, tmp_path):
        monkeypatch.delenv("PA_FAULT_PLAN", raising=False)
        monkeypatch.delenv("PA_FAIL_INJECT", raising=False)
        faults.reload()
        assert not faults.refresh().armed
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAIL_INJECT", "nan:2")
        assert faults.refresh().armed
        assert faults.refresh().lane_nan_target() == 2
        monkeypatch.delenv("PA_FAIL_INJECT", raising=False)
        assert not faults.refresh().armed


class TestLegacyAliases:
    def test_nan_lane_alias(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAIL_INJECT", "nan:3")
        monkeypatch.delenv("PA_FAULT_PLAN", raising=False)
        reg = FaultRegistry.from_env()
        assert reg.lane_nan_target() == 3
        # The alias parses to a lane-nan spec ONLY — bench's crash site
        # must never fire for a nan: value (the round-11 contract).
        assert reg.check("mid-step-crash") is None

    def test_oom_alias_is_crash_from_step_three(self, monkeypatch, tmp_path):
        """bench.py's historical contract: PA_FAIL_INJECT=oom fails from the
        third step on (warmup steps 1–2 survive for the postmortem)."""
        monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAIL_INJECT", "oom")
        monkeypatch.delenv("PA_FAULT_PLAN", raising=False)
        reg = FaultRegistry.from_env()
        assert _schedule(reg, "mid-step-crash", 4) == [
            False, False, True, True,
        ]
        assert reg.lane_nan_target() is None

    def test_plan_wins_over_legacy(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path))
        monkeypatch.setenv("PA_FAIL_INJECT", "oom")
        monkeypatch.setenv("PA_FAULT_PLAN", '[{"site": "slow-host", "nth": 1}]')
        reg = FaultRegistry.from_env()
        assert reg.check("mid-step-crash") is None
        assert reg.check("slow-host") is not None


class TestRetryPolicy:
    def test_backoff_growth_and_cap(self):
        p = retry.RetryPolicy(base_s=0.1, cap_s=1.0, multiplier=2.0,
                              jitter=0.0)
        assert p.backoff_s(0) == pytest.approx(0.1)
        assert p.backoff_s(1) == pytest.approx(0.2)
        assert p.backoff_s(10) == pytest.approx(1.0)  # capped

    def test_jitter_deterministic_and_downward(self):
        p = retry.RetryPolicy(base_s=1.0, cap_s=1.0, jitter=0.5)
        a = p.backoff_s(0, key="host-a")
        assert a == p.backoff_s(0, key="host-a")  # same (key, attempt)
        assert 0.5 <= a <= 1.0                    # jitters DOWNWARD only
        # Distinct keys de-synchronize.
        vals = {round(p.backoff_s(0, key=f"h{i}"), 9) for i in range(16)}
        assert len(vals) > 1

    def test_attempts_respects_max(self):
        p = retry.RetryPolicy(max_attempts=3, base_s=0.0, jitter=0.0)
        slept = []
        n = list(p.attempts(sleep=slept.append))
        assert n == [0, 1, 2]
        assert len(slept) == 2  # no sleep after the final attempt

    def test_deadline_stops_attempts(self):
        p = retry.RetryPolicy(max_attempts=100, base_s=10.0, jitter=0.0,
                              deadline_s=5.0)
        clock = [0.0]
        slept = []

        def fake_sleep(s):
            slept.append(s)
            clock[0] += s

        n = list(p.attempts(sleep=fake_sleep, now=lambda: clock[0]))
        assert len(n) == 2          # 0, sleep(min(10, 5)) → deadline spent
        assert slept == [5.0]       # clamped to the remaining budget

    def test_call_retries_then_raises_last(self):
        p = retry.RetryPolicy(max_attempts=3, base_s=0.0, jitter=0.0)
        calls = []

        def flaky():
            calls.append(1)
            raise OSError(f"boom {len(calls)}")

        with pytest.raises(OSError, match="boom 3"):
            p.call(flaky)
        assert len(calls) == 3

    def test_call_returns_first_success(self):
        p = retry.RetryPolicy(max_attempts=5, base_s=0.0, jitter=0.0)
        calls = []

        def second_try():
            calls.append(1)
            if len(calls) < 2:
                raise OSError("once")
            return "ok"

        assert p.call(second_try) == "ok"
        assert len(calls) == 2

    def test_call_does_not_retry_foreign_exceptions(self):
        p = retry.RetryPolicy(max_attempts=5, base_s=0.0)
        with pytest.raises(ValueError):
            p.call(lambda: (_ for _ in ()).throw(ValueError("not transient")),
                   retry_on=(OSError,))


class TestHeartbeatBackoff:
    def test_unreachable_router_backs_off(self):
        """The satellite fix: consecutive beat failures grow the wait toward
        the cap instead of hot-looping the fixed cadence; one success snaps
        back."""
        from comfyui_parallelanything_tpu.fleet import HeartbeatClient

        hb = HeartbeatClient("http://127.0.0.1:9", "h", "http://x",
                             interval_s=0.5)
        assert hb.next_wait_s() == 0.5
        assert not hb.beat_once(timeout=0.2)
        w1 = hb.next_wait_s()
        assert not hb.beat_once(timeout=0.2)
        w2 = hb.next_wait_s()
        assert w1 >= 0.5 and w2 > w1
        hb._failures = 0  # what a successful beat does
        assert hb.next_wait_s() == 0.5
