"""SD3/SD3.5 MMDiT: forward shapes, pos-table cropping, converter round-trip
(inverse-synthesis, like test_convert_wan.py), pipeline smoke over the mesh,
and the SD3 conditioning assembly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

import comfyui_parallelanything_tpu as pa
from comfyui_parallelanything_tpu.models.convert_mmdit import (
    convert_mmdit_checkpoint,
)
from comfyui_parallelanything_tpu.models.mmdit import (
    MMDiTConfig,
    build_mmdit,
    sd3_medium_config,
    sd35_large_config,
    sincos_pos_embed,
)

TINY = MMDiTConfig(
    in_channels=4, depth=2, context_in_dim=32, pooled_dim=16,
    pos_embed_max=16, qk_norm=True, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny_mmdit():
    return build_mmdit(TINY, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=6)


class TestForward:
    def test_shapes_and_presets(self, tiny_mmdit):
        x = jax.random.normal(jax.random.key(1), (2, 8, 8, 4))
        out = tiny_mmdit(
            x, jnp.linspace(1.0, 0.1, 2),
            jax.random.normal(jax.random.key(2), (2, 6, 32)),
            y=jax.random.normal(jax.random.key(3), (2, 16)),
        )
        assert out.shape == (2, 8, 8, 4)
        assert np.isfinite(np.asarray(out)).all()
        assert sd3_medium_config().hidden_size == 1536
        assert sd35_large_config().depth == 38 and sd35_large_config().qk_norm

    def test_pos_table_crop_changes_with_resolution(self, tiny_mmdit):
        """Different latent sizes read different center crops of the table, so
        the same token grid position gets consistent embeddings."""
        c = jax.random.normal(jax.random.key(2), (1, 6, 32))
        t = jnp.array([0.5])
        out8 = tiny_mmdit(jnp.zeros((1, 8, 8, 4)), t, c)
        out16 = tiny_mmdit(jnp.zeros((1, 16, 16, 4)), t, c)
        assert out8.shape == (1, 8, 8, 4) and out16.shape == (1, 16, 16, 4)

    def test_oversize_grid_rejected(self, tiny_mmdit):
        with pytest.raises(ValueError, match="pos table"):
            tiny_mmdit(
                jnp.zeros((1, 40, 40, 4)), jnp.array([0.5]),
                jnp.zeros((1, 6, 32)),
            )

    def test_sincos_table_shape(self):
        t = sincos_pos_embed(8, 64)
        assert t.shape == (64, 64)
        assert np.isfinite(t).all()

    def test_parallelized_over_mesh(self, tiny_mmdit):
        chain = pa.DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = pa.parallelize(tiny_mmdit, chain)
        x = jax.random.normal(jax.random.key(4), (8, 8, 8, 4))
        c = jax.random.normal(jax.random.key(5), (8, 6, 32))
        out = pm(x, jnp.linspace(1.0, 0.1, 8), c)
        assert out.shape == (8, 8, 8, 4)
        # batch==1 → joint blocks placed as pipeline stages
        x1 = x[:1]
        out1 = pm(x1, jnp.array([0.5]), c[:1])
        assert out1.shape == (1, 8, 8, 4)
        assert pm._pipeline_runner is not None


def _inv_dense(p, key, sd):
    sd[f"{key}.weight"] = np.asarray(p["kernel"]).T
    if "bias" in p:
        sd[f"{key}.bias"] = np.asarray(p["bias"])


def _inv_qkv(p, key, sd, cfg):
    k = np.asarray(p["qkv"]["kernel"])  # (dim, 3, H, D)
    sd[f"{key}.qkv.weight"] = k.reshape(cfg.hidden_size, -1).T
    sd[f"{key}.qkv.bias"] = np.asarray(p["qkv"]["bias"]).reshape(-1)
    if "ln_q" in p:
        sd[f"{key}.ln_q.weight"] = np.asarray(p["ln_q"])
        sd[f"{key}.ln_k.weight"] = np.asarray(p["ln_k"])


def _official_layout_sd(cfg: MMDiTConfig, params) -> dict:
    sd: dict = {}
    k = np.asarray(params["x_in"]["kernel"])  # (p*p*C, dim)
    p_ = cfg.patch_size
    sd["x_embedder.proj.weight"] = (
        k.reshape(p_, p_, cfg.in_channels, -1).transpose(3, 2, 0, 1)
    )
    sd["x_embedder.proj.bias"] = np.asarray(params["x_in"]["bias"])
    sd["pos_embed"] = np.asarray(params["pos_embed"]["table"])[None]
    _inv_dense(params["context_in"], "context_embedder", sd)
    _inv_dense(params["time_in"]["in_layer"], "t_embedder.mlp.0", sd)
    _inv_dense(params["time_in"]["out_layer"], "t_embedder.mlp.2", sd)
    _inv_dense(params["vector_in"]["in_layer"], "y_embedder.mlp.0", sd)
    _inv_dense(params["vector_in"]["out_layer"], "y_embedder.mlp.2", sd)
    _inv_dense(params["final_mod"], "final_layer.adaLN_modulation.1", sd)
    _inv_dense(params["final_proj"], "final_layer.linear", sd)
    for i in range(cfg.depth):
        blk = params[f"blocks_{i}"]
        xb = f"joint_blocks.{i}.x_block"
        cb = f"joint_blocks.{i}.context_block"
        _inv_dense(blk["x_adaln"]["lin"], f"{xb}.adaLN_modulation.1", sd)
        _inv_qkv(blk["x_attn_in"], f"{xb}.attn", sd, cfg)
        _inv_dense(blk["x_attn_proj"], f"{xb}.attn.proj", sd)
        _inv_dense(blk["x_mlp_in"], f"{xb}.mlp.fc1", sd)
        _inv_dense(blk["x_mlp_out"], f"{xb}.mlp.fc2", sd)
        if "x_attn_in2" in blk:  # SD3.5-medium dual attention
            _inv_qkv(blk["x_attn_in2"], f"{xb}.attn2", sd, cfg)
            _inv_dense(blk["x_attn2_proj"], f"{xb}.attn2.proj", sd)
        _inv_dense(blk["ctx_adaln"]["lin"], f"{cb}.adaLN_modulation.1", sd)
        _inv_qkv(blk["ctx_attn_in"], f"{cb}.attn", sd, cfg)
        if "ctx_attn_proj" in blk:
            _inv_dense(blk["ctx_attn_proj"], f"{cb}.attn.proj", sd)
            _inv_dense(blk["ctx_mlp_in"], f"{cb}.mlp.fc1", sd)
            _inv_dense(blk["ctx_mlp_out"], f"{cb}.mlp.fc2", sd)
    return sd


class TestConverter:
    def test_round_trip_bitwise(self, tiny_mmdit):
        sd = _official_layout_sd(TINY, tiny_mmdit.params)
        converted = convert_mmdit_checkpoint(sd, TINY)
        ref = dict(flatten_tree(tiny_mmdit.params))
        got = dict(flatten_tree(converted))
        assert set(ref) == set(got), set(ref) ^ set(got)
        for key, val in ref.items():
            np.testing.assert_array_equal(
                np.asarray(val), np.asarray(got[key]), err_msg=str(key)
            )

    def test_converted_forward_matches(self, tiny_mmdit):
        sd = {
            f"model.diffusion_model.{k}": v
            for k, v in _official_layout_sd(TINY, tiny_mmdit.params).items()
        }
        m2 = build_mmdit(TINY, params=convert_mmdit_checkpoint(sd, TINY))
        x = jax.random.normal(jax.random.key(6), (1, 8, 8, 4))
        c = jax.random.normal(jax.random.key(7), (1, 6, 32))
        np.testing.assert_allclose(
            np.asarray(m2(x, jnp.array([0.7]), c)),
            np.asarray(tiny_mmdit(x, jnp.array([0.7]), c)),
            rtol=1e-6, atol=1e-6,
        )

    def test_dual_attention_config_mismatch_rejected(self, tiny_mmdit):
        sd = _official_layout_sd(TINY, tiny_mmdit.params)
        sd["joint_blocks.0.x_block.attn2.qkv.weight"] = np.zeros((1, 1))
        with pytest.raises(ValueError, match="x_block_self_attn_layers"):
            convert_mmdit_checkpoint(sd, TINY)

    def test_dual_attention_round_trip_and_loader_alignment(self):
        # SD3.5-medium (mmdit-x): dual-attention layers survive synthesis →
        # conversion bitwise, and the loader aligns a generic config to the
        # checkpoint's actual attn2 layout.
        import dataclasses

        from comfyui_parallelanything_tpu.models.loader import load_mmdit_checkpoint

        cfg = dataclasses.replace(TINY, x_block_self_attn_layers=(0,))
        model = build_mmdit(cfg, jax.random.key(3), sample_shape=(1, 8, 8, 4),
                            txt_len=6)
        sd = _official_layout_sd(cfg, model.params)
        converted = convert_mmdit_checkpoint(sd, cfg)
        ref = dict(flatten_tree(model.params))
        got = dict(flatten_tree(converted))
        assert set(ref) == set(got), set(ref) ^ set(got)
        assert any("x_attn_in2" in k for k in got)
        # Loader with the NON-dual generic config still loads it correctly.
        m2 = load_mmdit_checkpoint(sd, TINY)
        x = jax.random.normal(jax.random.key(6), (1, 8, 8, 4))
        c = jax.random.normal(jax.random.key(7), (1, 6, 32))
        np.testing.assert_allclose(
            np.asarray(m2(x, jnp.array([0.7]), c)),
            np.asarray(model(x, jnp.array([0.7]), c)),
            rtol=1e-6, atol=1e-6,
        )


class TestSd3Conditioning:
    def test_assembly_shapes(self):
        from comfyui_parallelanything_tpu.models import sd3_text_conditioning

        pen_l = jnp.ones((2, 7, 8))
        pen_g = jnp.ones((2, 7, 12))
        t5 = jnp.ones((2, 5, 32))
        ctx, y = sd3_text_conditioning(
            pen_l, pen_g, jnp.ones((2, 8)), jnp.ones((2, 12)), t5,
            context_dim=32,
        )
        assert ctx.shape == (2, 12, 32)  # 7 clip + 5 t5 tokens
        assert y.shape == (2, 20)
        # clip rows zero-padded past 8+12=20
        assert float(jnp.abs(ctx[:, :7, 20:]).max()) == 0.0

    def test_overwide_clip_rejected(self):
        from comfyui_parallelanything_tpu.models import sd3_text_conditioning

        with pytest.raises(ValueError, match="exceeds"):
            sd3_text_conditioning(
                jnp.ones((1, 7, 30)), jnp.ones((1, 7, 30)),
                jnp.ones((1, 30)), jnp.ones((1, 30)), None, context_dim=32,
            )


class TestSd3Pipeline:
    def test_prompt_to_image(self, tiny_mmdit):
        from comfyui_parallelanything_tpu.models import (
            CLIPTextConfig, VAEConfig, build_clip_text, build_vae,
        )
        from test_tokenizer import _tiny_tokenizer

        tok = _tiny_tokenizer()
        clip_l = build_clip_text(
            CLIPTextConfig(vocab_size=64, hidden_size=12, num_layers=1,
                           num_heads=2, max_len=8, eos_id=tok.eos_id,
                           dtype=jnp.float32),
            jax.random.key(1),
        )
        clip_g = build_clip_text(
            CLIPTextConfig(vocab_size=64, hidden_size=20, num_layers=1,
                           num_heads=2, max_len=8, eos_id=tok.eos_id,
                           act="gelu", dtype=jnp.float32),
            jax.random.key(2),
        )
        # pooled_dim must equal l+g hidden (12+20=32); context 32 matches the
        # tiny MMDiT; tune a matching DiT.
        cfg = MMDiTConfig(
            in_channels=4, depth=2, context_in_dim=32, pooled_dim=32,
            pos_embed_max=16, qk_norm=True, dtype=jnp.float32,
        )
        dit = build_mmdit(cfg, jax.random.key(3), sample_shape=(1, 8, 8, 4),
                          txt_len=8)
        vae = build_vae(
            VAEConfig(z_channels=4, base_channels=16, channel_mult=(1, 2),
                      num_res_blocks=1, norm_groups=8, dtype=jnp.float32),
            jax.random.key(4), sample_hw=16,
        )
        pipe = pa.Sd3Pipeline(
            dit=dit, vae=vae, clip=clip_l, clip_g=clip_g, tokenizer=tok,
        )
        img = pipe("hello", steps=2, cfg_scale=1.0, height=16, width=16)
        assert img.shape == (1, 16, 16, 3)
        assert np.isfinite(np.asarray(img)).all()
        # true CFG path
        img2 = pipe(
            "hello", negative_prompt="world", steps=2, cfg_scale=4.0,
            height=16, width=16,
        )
        assert not np.allclose(np.asarray(img), np.asarray(img2))


class TestSd3Nodes:
    def test_conditioning_combine_sd3(self):
        from comfyui_parallelanything_tpu.nodes import TPUConditioningCombine

        a = {"penultimate": jnp.ones((1, 7, 8)), "pooled": jnp.ones((1, 8))}
        b = {"penultimate": jnp.ones((1, 7, 12)), "pooled": jnp.ones((1, 12))}
        c = {"context": jnp.ones((1, 5, 4096))}
        (cond,) = TPUConditioningCombine().combine(a, b, "sd3", conditioning_c=c)
        assert cond["context"].shape == (1, 12, 4096)
        assert cond["pooled"].shape == (1, 20)
        # without T5: clip joint only
        (cond2,) = TPUConditioningCombine().combine(a, b, "sd3")
        assert cond2["context"].shape == (1, 7, 4096)

    def test_combine_sd3_missing_tower_rejected(self):
        from comfyui_parallelanything_tpu.nodes import TPUConditioningCombine

        with pytest.raises(ValueError, match="sd3 mode"):
            TPUConditioningCombine().combine(
                {"context": jnp.ones((1, 7, 8))},
                {"penultimate": jnp.ones((1, 7, 12)), "pooled": jnp.ones((1, 12))},
                "sd3",
            )

    def test_t5_without_tokenizer_rejected(self, tiny_mmdit):
        from comfyui_parallelanything_tpu.models import (
            CLIPTextConfig, VAEConfig, build_clip_text, build_vae,
        )
        from test_tokenizer import _tiny_tokenizer

        tok = _tiny_tokenizer()
        clip = build_clip_text(
            CLIPTextConfig(vocab_size=64, hidden_size=8, num_layers=1,
                           num_heads=2, max_len=8, eos_id=tok.eos_id,
                           dtype=jnp.float32), jax.random.key(0))
        pipe = pa.Sd3Pipeline(
            dit=tiny_mmdit, vae=None, clip=clip, clip_g=clip, tokenizer=tok,
            t5=object(),  # set but no tokenizer
        )
        with pytest.raises(ValueError, match="t5_tokenizer"):
            pipe.encode_prompt(["hello"])


class TestSincosOrder:
    def test_width_axis_first(self):
        """SAI convention: at (h, w) the table is [emb(w) | emb(h)] — two
        positions sharing w agree in the first half, sharing h in the second."""
        t = sincos_pos_embed(4, 8).reshape(4, 4, 8)
        np.testing.assert_array_equal(t[0, 2, :4], t[3, 2, :4])  # same w
        np.testing.assert_array_equal(t[2, 0, 4:], t[2, 3, 4:])  # same h
        assert not np.allclose(t[0, 2, 4:], t[3, 2, 4:])
