"""Workflow-graph executor: ComfyUI API-format JSON → node execution over
NODE_CLASS_MAPPINGS — the L5 host layer the reference borrows from ComfyUI,
standalone here. An end-to-end graph (device chain → parallelize → empty latent
→ ksampler) runs a real sampled latent across the virtual mesh."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.host import WorkflowError, run_workflow


class ToyModelNode:
    """Custom node (the extension mechanism hosts allow): emits a tiny
    diffusion MODEL so graph tests don't need checkpoint files."""

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "build"

    def build(self):
        from comfyui_parallelanything_tpu.models import build_unet, sd15_config

        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
            attention_levels=(0, 1), context_dim=48, num_heads=4, norm_groups=8,
            dtype=jnp.float32,
        )
        return (build_unet(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4)),)


class ToyConditioningNode:
    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "encode"

    def encode(self, seed: int = 0):
        ctx = jax.random.normal(jax.random.key(seed), (1, 6, 48))
        return ({"context": ctx},)


CUSTOM = {"ToyModel": ToyModelNode, "ToyConditioning": ToyConditioningNode}


def _chain_workflow():
    return {
        "1": {"class_type": "ParallelDevice",
              "inputs": {"device_id": "cpu:0", "percentage": 50.0}},
        "2": {"class_type": "ParallelDevice",
              "inputs": {"device_id": "cpu:1", "percentage": 50.0,
                         "previous_devices": ["1", 0]}},
    }


class TestExecutor:
    def test_chain_graph(self):
        out = run_workflow(_chain_workflow())
        chain = out["2"][0]
        assert [e["device"] for e in chain] == ["cpu:0", "cpu:1"]

    def test_literal_vs_link_distinction(self):
        # A 2-list of [str, int] is a link; scalars and other lists are literals.
        wf = _chain_workflow()
        out = run_workflow(wf)
        assert out["1"][0][0]["percentage"] == 50.0

    def test_unknown_class_raises(self):
        with pytest.raises(WorkflowError, match="unknown class_type"):
            run_workflow({"1": {"class_type": "NoSuchNode", "inputs": {}}})

    def test_pending_interrupt_stops_before_next_node(self):
        # A Cancel landing inside a non-sampler node must stop the graph at
        # the next NODE boundary, not only at sampler-step boundaries
        # (ComfyUI's per-node interrupt check).
        from comfyui_parallelanything_tpu.utils.progress import (
            Interrupted,
            clear_interrupt,
            request_interrupt,
        )

        request_interrupt()
        try:
            with pytest.raises(Interrupted, match="before node"):
                run_workflow(_chain_workflow())
        finally:
            clear_interrupt()
        # The flag was consumed: the next run proceeds normally.
        assert run_workflow(_chain_workflow())["2"][0]

    def test_unknown_link_target_raises(self):
        wf = {"1": {"class_type": "ParallelDevice",
                    "inputs": {"device_id": "cpu:0", "percentage": 50.0,
                               "previous_devices": ["99", 0]}}}
        with pytest.raises(WorkflowError, match="unknown node id"):
            run_workflow(wf)

    def test_cycle_raises(self):
        wf = {
            "1": {"class_type": "ParallelDevice",
                  "inputs": {"device_id": "cpu:0", "percentage": 50.0,
                             "previous_devices": ["2", 0]}},
            "2": {"class_type": "ParallelDevice",
                  "inputs": {"device_id": "cpu:1", "percentage": 50.0,
                             "previous_devices": ["1", 0]}},
        }
        with pytest.raises(WorkflowError, match="cycle"):
            run_workflow(wf)

    def test_out_of_range_output_raises(self):
        wf = _chain_workflow()
        wf["2"]["inputs"]["previous_devices"] = ["1", 3]
        with pytest.raises(WorkflowError, match="3 .* 1 output"):
            run_workflow(wf)

    def test_widget_list_literal_not_mistaken_for_link(self):
        # A declared widget whose literal value is a 2-list must NOT resolve as
        # a link (ComfyUI decides link-vs-literal from INPUT_TYPES; so do we).
        seen = {}

        class Sizer:
            RETURN_TYPES = ("X",)
            FUNCTION = "go"

            @classmethod
            def INPUT_TYPES(cls):
                return {"required": {"size": ("INT", {}),
                                     "pair": ("FLOAT", {})}}

            def go(self, size, pair):
                seen["pair"] = pair
                return (size,)

        wf = {"7": {"class_type": "Sizer", "inputs": {"size": 3, "pair": [64, 0]}}}
        out = run_workflow(wf, {"Sizer": Sizer})
        assert out["7"] == (3,)
        assert seen["pair"] == [64, 0]  # stayed a literal

    def test_linked_primitive_widget_resolves(self):
        # ComfyUI's convert-widget-to-input: a declared INT widget wired from
        # another node's output arrives as [node_id, idx] and MUST resolve as a
        # link (ComfyUI's executor treats any link-shaped value as a link
        # regardless of INPUT_TYPES).
        class SeedSource:
            RETURN_TYPES = ("INT",)
            FUNCTION = "go"

            def go(self):
                return (1234,)

        class Consumer:
            RETURN_TYPES = ("X",)
            FUNCTION = "go"

            @classmethod
            def INPUT_TYPES(cls):
                return {"required": {"seed": ("INT", {})}}

            def go(self, seed):
                return (seed,)

        wf = {
            "a": {"class_type": "SeedSource", "inputs": {}},
            "b": {"class_type": "Consumer", "inputs": {"seed": ["a", 0]}},
        }
        out = run_workflow(wf, {"SeedSource": SeedSource, "Consumer": Consumer})
        assert out["b"] == (1234,)

    def test_deep_chain_no_recursion_limit(self):
        # Link resolution is iterative: a linear chain far beyond Python's
        # recursion limit executes (no RecursionError escaping as a crash).
        class Inc:
            RETURN_TYPES = ("X",)
            FUNCTION = "go"

            @classmethod
            def INPUT_TYPES(cls):
                return {"required": {"x": ("X", {})}}

            def go(self, x):
                return (x + 1,)

        n = 3000
        wf = {"0": {"class_type": "Inc", "inputs": {"x": -1}}}
        for i in range(1, n):
            wf[str(i)] = {"class_type": "Inc", "inputs": {"x": [str(i - 1), 0]}}
        out = run_workflow(wf, {"Inc": Inc})
        assert out[str(n - 1)] == (n - 1,)

    def test_node_error_carries_node_id(self):
        wf = {"9": {"class_type": "ParallelDevice",
                    "inputs": {"percentage": 50.0}}}  # missing device_id
        with pytest.raises(WorkflowError, match="node 9"):
            run_workflow(wf)

    def test_output_cache_skips_execution(self):
        ran = []

        class Probe:
            RETURN_TYPES = ("X",)
            FUNCTION = "go"

            def go(self):
                ran.append(1)
                return ("value",)

        wf = {"1": {"class_type": "Probe", "inputs": {}}}
        seed = {"1": ("cached",)}
        out = run_workflow(wf, {"Probe": Probe}, outputs=seed)
        assert out["1"] == ("cached",) and not ran

    def test_json_file_roundtrip(self, tmp_path):
        p = tmp_path / "wf.json"
        p.write_text(json.dumps(_chain_workflow()))
        out = run_workflow(str(p))
        assert len(out["2"][0]) == 2


class TestHiddenInputs:
    def test_prompt_and_unique_id_injected(self):
        # ComfyUI executor semantics: "hidden" INPUT_TYPES entries are filled
        # by the HOST — PROMPT gets the whole workflow dict, UNIQUE_ID the
        # executing node's id.
        seen = {}

        class Probe:
            RETURN_TYPES = ("X",)
            FUNCTION = "go"

            @classmethod
            def INPUT_TYPES(cls):
                return {"required": {},
                        "hidden": {"prompt": "PROMPT", "uid": "UNIQUE_ID"}}

            def go(self, prompt=None, uid=None):
                seen.update(prompt=prompt, uid=uid)
                return (1,)

        wf = {"p9": {"class_type": "Probe", "inputs": {}}}
        run_workflow(wf, {"Probe": Probe})
        assert seen["uid"] == "p9"
        assert seen["prompt"]["p9"]["class_type"] == "Probe"

    def test_save_image_embeds_workflow_prompt(self, tmp_path):
        # A saved PNG carries the workflow under the 'prompt' chunk (the host
        # convention for drag-back-into-graph restoration).
        import json as _json

        from PIL import Image

        class Gen:
            RETURN_TYPES = ("IMAGE",)
            FUNCTION = "go"

            def go(self):
                return (jnp.ones((1, 4, 4, 3)) * 0.25,)

        wf = {
            "g": {"class_type": "Gen", "inputs": {}},
            "s": {"class_type": "TPUSaveImage",
                  "inputs": {"images": ["g", 0], "filename_prefix": "w",
                             "output_dir": str(tmp_path)}},
        }
        out = run_workflow(wf, {"Gen": Gen})
        (path,) = out["s"][0]
        embedded = _json.loads(Image.open(path).text["prompt"])
        assert embedded["s"]["class_type"] == "TPUSaveImage"
        assert embedded["g"]["class_type"] == "Gen"


class TestWorkflowCache:
    class _Model:
        """Teardownable output (the shape ParallelModel exposes)."""

        def __init__(self):
            self.active = True

        def cleanup(self):
            self.active = False

    def _classes(self, built):
        outer = self

        class Build:
            RETURN_TYPES = ("MODEL",)
            FUNCTION = "go"

            @classmethod
            def INPUT_TYPES(cls):
                return {"required": {"tag": ("STRING", {})}}

            def go(self, tag):
                m = outer._Model()
                built.append((tag, m))
                return (m,)

        class Use:
            RETURN_TYPES = ("X",)
            FUNCTION = "go"

            @classmethod
            def INPUT_TYPES(cls):
                return {"required": {"model": ("MODEL", {})}}

            def go(self, model):
                return (model,)

        return {"Build": Build, "Use": Use}

    def _wf(self, tag):
        return {
            "m": {"class_type": "Build", "inputs": {"tag": tag}},
            "u": {"class_type": "Use", "inputs": {"model": ["m", 0]}},
        }

    def test_unchanged_graph_reuses_cache(self):
        from comfyui_parallelanything_tpu.host import WorkflowCache

        built = []
        classes = self._classes(built)
        cache = WorkflowCache()
        run_workflow(self._wf("a"), classes, outputs=cache)
        run_workflow(self._wf("a"), classes, outputs=cache)
        assert len(built) == 1  # second run fully cached
        assert built[0][1].active

    def test_changed_input_evicts_and_tears_down(self):
        # Editing the model node re-executes it AND tears down the superseded
        # model — the host-side analogue of the reference's finalizer firing
        # when ComfyUI replaces a MODEL (any_device_parallel.py:1459).
        from comfyui_parallelanything_tpu.host import WorkflowCache

        built = []
        classes = self._classes(built)
        cache = WorkflowCache()
        run_workflow(self._wf("a"), classes, outputs=cache)
        out2 = run_workflow(self._wf("b"), classes, outputs=cache)
        assert [t for t, _ in built] == ["a", "b"]
        assert not built[0][1].active  # old model torn down on eviction
        assert built[1][1].active
        assert out2["u"][0] is built[1][1]  # downstream re-ran on the new model

    def test_dropped_node_evicts(self):
        from comfyui_parallelanything_tpu.host import WorkflowCache

        built = []
        classes = self._classes(built)
        cache = WorkflowCache()
        run_workflow(self._wf("a"), classes, outputs=cache)
        run_workflow({"other": {"class_type": "Build", "inputs": {"tag": "z"}}},
                     classes, outputs=cache)
        assert not built[0][1].active  # entry for removed node torn down
        assert "m" not in cache.results and "u" not in cache.results

    def test_passthrough_eviction_spares_shared_model(self):
        # A downstream node that RETURNS the model it received (the standard
        # ComfyUI MODEL pass-through) shares the object with its upstream
        # cache entry. Editing only the downstream node's literal must evict
        # and re-run it WITHOUT tearing down the still-cached upstream model.
        from comfyui_parallelanything_tpu.host import WorkflowCache

        built = []
        classes = self._classes(built)
        outer = self

        class Tag:
            RETURN_TYPES = ("MODEL",)
            FUNCTION = "go"

            @classmethod
            def INPUT_TYPES(cls):
                return {"required": {"model": ("MODEL", {}),
                                     "note": ("STRING", {})}}

            def go(self, model, note):
                return (model,)  # pass-through

        classes["Tag"] = Tag

        def wf(note):
            return {
                "m": {"class_type": "Build", "inputs": {"tag": "a"}},
                "t": {"class_type": "Tag",
                      "inputs": {"model": ["m", 0], "note": note}},
            }

        cache = WorkflowCache()
        run_workflow(wf("one"), classes, outputs=cache)
        model = built[0][1]
        run_workflow(wf("two"), classes, outputs=cache)
        assert len(built) == 1          # upstream Build stayed cached
        assert model.active             # shared model NOT torn down
        assert cache.results["t"][0] is model
        del outer

    def test_downstream_only_change_keeps_upstream_cache(self):
        from comfyui_parallelanything_tpu.host import WorkflowCache

        built = []
        classes = self._classes(built)
        cache = WorkflowCache()
        wf = self._wf("a")
        run_workflow(wf, classes, outputs=cache)
        wf2 = self._wf("a")
        wf2["u2"] = {"class_type": "Use", "inputs": {"model": ["m", 0]}}
        run_workflow(wf2, classes, outputs=cache)
        assert len(built) == 1  # upstream model untouched
        assert built[0][1].active


class TestShippedExampleWorkflow:
    """The committed examples/*.json must stay runnable: execute them through
    host.py against a synthetic tiny checkpoint (inverse-synthesis layout, the
    tests' standard pattern), with only the things a user would edit rewritten
    — file paths, device ids, sizes/steps. Every node class in the shipped
    artifacts executes for real."""

    def _synthetic_env(self, tmp_path, monkeypatch):
        """Tiny sd15 checkpoint + CLIP encoder + tokenizer on disk, with the
        family preset factories monkeypatched to the matching tiny configs.
        Returns (paths dict, vae spatial factor)."""
        import jax.numpy as jnp
        from safetensors.numpy import save_file

        import comfyui_parallelanything_tpu.models as models_pkg
        import comfyui_parallelanything_tpu.models.text_encoders as te_mod
        from comfyui_parallelanything_tpu.models import build_unet, build_vae
        from tests.test_convert_unet import _ldm_sd
        from tests.test_text_encoders import TINY_CLIP, _hf_clip
        from tests.test_vae import TINY as TINY_VAE, _ldm_layout_sd

        real_sd15 = models_pkg.sd15_config

        def tiny_sd15():
            return real_sd15(
                model_channels=32, channel_mult=(1, 2), transformer_depth=(1, 1),
                attention_levels=(0, 1), context_dim=TINY_CLIP.hidden_size,
                num_heads=4, norm_groups=8, dtype=jnp.float32,
            )

        monkeypatch.setattr(models_pkg, "sd15_config", tiny_sd15)
        monkeypatch.setattr(models_pkg, "sd_vae_config", lambda: TINY_VAE)
        monkeypatch.setattr(te_mod, "clip_l_config", lambda: TINY_CLIP)

        # Synthetic full checkpoint: diffusion + bundled VAE subtrees, in the
        # torch/ldm key layout the converters consume.
        ucfg = tiny_sd15()
        unet = build_unet(ucfg, jax.random.key(0), sample_shape=(1, 8, 8, 4))
        vae = build_vae(TINY_VAE, jax.random.key(1), sample_hw=16)
        sd = {
            f"model.diffusion_model.{k}": np.ascontiguousarray(v)
            for k, v in _ldm_sd(ucfg, unet.params).items()
        }
        sd.update(
            {
                f"first_stage_model.{k}": np.ascontiguousarray(v)
                for k, v in _ldm_layout_sd(TINY_VAE, vae.params).items()
            }
        )
        ckpt = tmp_path / "ckpt.safetensors"
        save_file(sd, str(ckpt))

        # Synthetic CLIP encoder (HF text_model layout) + tokenizer.json.
        hf = _hf_clip(TINY_CLIP, "quick_gelu")
        clip_sd = {
            k: np.ascontiguousarray(v.detach().numpy())
            for k, v in hf.state_dict().items()
        }
        enc_path = tmp_path / "clip.safetensors"
        save_file(clip_sd, str(enc_path))

        tokenizers = pytest.importorskip("tokenizers")
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        vocab = {"[UNK]": 0, "a": 5, "watercolor": 6, "lighthouse": 7, "at": 8,
                 "dawn": 9, "blurry": 10, "low": 11, "quality": 12}
        t = tokenizers.Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
        t.pre_tokenizer = Whitespace()
        tok_path = tmp_path / "tokenizer.json"
        t.save(str(tok_path))
        paths = {
            "ckpt": str(ckpt), "clip": str(enc_path), "tok": str(tok_path),
            "max_len": TINY_CLIP.max_len,
        }
        return paths, vae.spatial_factor

    def _rewrite_common(self, wf, paths):
        wf["checkpoint"]["inputs"]["ckpt_path"] = paths["ckpt"]
        wf["clip"]["inputs"]["encoder_path"] = paths["clip"]
        wf["clip"]["inputs"]["tokenizer_json"] = paths["tok"]
        wf["clip"]["inputs"]["max_len"] = paths["max_len"]
        wf["dev0"]["inputs"]["device_id"] = "cpu:0"
        wf["dev1"]["inputs"]["device_id"] = "cpu:1"
        wf["sampler"]["inputs"]["steps"] = 2
        return wf

    def test_example_sd15_txt2img_executes(self, cpu_devices, tmp_path, monkeypatch):
        import os

        paths, factor = self._synthetic_env(tmp_path, monkeypatch)
        wf = self._rewrite_common(
            json.load(open("examples/workflow_sd15_txt2img.json")), paths
        )
        wf["latent"]["inputs"].update(width=32, height=32, batch_size=4)
        wf["save"]["inputs"]["output_dir"] = str(tmp_path / "out")

        out = run_workflow(wf)
        images = out["decode"][0]
        # TPUEmptyLatent assumes the SD factor-8 latent grid; the tiny VAE
        # upsamples by its own (smaller) factor — assert consistently.
        hw = 32 // 8 * factor
        assert images.shape == (4, hw, hw, 3)
        assert np.isfinite(np.asarray(images)).all()
        assert out["parallel"][0].devices == ("cpu:0", "cpu:1")
        saved = out["save"][0]
        assert len(saved) == 4 and all(os.path.exists(p) for p in saved)

    def test_example_custom_sampling_executes(self, cpu_devices, tmp_path,
                                              monkeypatch):
        import os

        paths, factor = self._synthetic_env(tmp_path, monkeypatch)
        wf = json.load(open("examples/workflow_custom_sampling.json"))
        wf["checkpoint"]["inputs"]["ckpt_path"] = paths["ckpt"]
        wf["clip"]["inputs"]["encoder_path"] = paths["clip"]
        wf["clip"]["inputs"]["tokenizer_json"] = paths["tok"]
        wf["clip"]["inputs"]["max_len"] = paths["max_len"]
        wf["dev0"]["inputs"]["device_id"] = "cpu:0"
        wf["dev1"]["inputs"]["device_id"] = "cpu:1"
        wf["sigmas"]["inputs"]["steps"] = 2
        wf["latent"]["inputs"].update(width=32, height=32, batch_size=4)
        wf["save"]["inputs"]["output_dir"] = str(tmp_path / "out")

        out = run_workflow(wf)
        images = out["decode"][0]
        hw = 32 // 8 * factor
        assert images.shape == (4, hw, hw, 3)
        assert np.isfinite(np.asarray(images)).all()
        saved = out["save"][0]
        assert len(saved) == 4 and all(os.path.exists(p) for p in saved)

    def test_example_sd15_controlnet_executes(self, cpu_devices, tmp_path,
                                              monkeypatch):
        import os

        from PIL import Image
        from safetensors.numpy import save_file

        import comfyui_parallelanything_tpu.models as models_pkg
        from comfyui_parallelanything_tpu.models import build_controlnet
        from tests.test_controlnet import _ldm_controlnet_sd, _randomized_cn

        paths, factor = self._synthetic_env(tmp_path, monkeypatch)
        # Tiny ControlNet checkpoint for the (monkeypatched) tiny sd15 config.
        cfg = models_pkg.sd15_config()
        cn = build_controlnet(cfg, jax.random.key(5), sample_shape=(1, 4, 4, 4))
        cn_sd = _ldm_controlnet_sd(cfg, _randomized_cn(cn, cfg).params)
        cn_path = tmp_path / "cn.safetensors"
        save_file({k: np.ascontiguousarray(v) for k, v in cn_sd.items()},
                  str(cn_path))
        hint_path = tmp_path / "hint.png"
        Image.fromarray(
            (np.random.default_rng(3).uniform(0, 1, (32, 32, 3)) * 255)
            .astype(np.uint8)
        ).save(hint_path)

        wf = self._rewrite_common(
            json.load(open("examples/workflow_sd15_controlnet.json")), paths
        )
        wf["latent"]["inputs"].update(width=32, height=32, batch_size=2)
        wf["hint"]["inputs"]["image_path"] = str(hint_path)
        wf["controlnet"]["inputs"]["ckpt_path"] = str(cn_path)
        wf["save"]["inputs"]["output_dir"] = str(tmp_path / "out")

        out = run_workflow(wf)
        images = out["decode"][0]
        hw = 32 // 8 * factor
        assert images.shape == (2, hw, hw, 3)
        assert np.isfinite(np.asarray(images)).all()
        saved = out["save"][0]
        assert len(saved) == 2 and all(os.path.exists(p) for p in saved)

    def test_example_sd15_img2img_executes(self, cpu_devices, tmp_path, monkeypatch):
        import os

        from PIL import Image

        paths, factor = self._synthetic_env(tmp_path, monkeypatch)
        src = tmp_path / "input.png"
        Image.fromarray(
            (np.random.default_rng(0).uniform(0, 1, (16, 16, 3)) * 255).astype(
                np.uint8
            )
        ).save(src)
        wf = self._rewrite_common(
            json.load(open("examples/workflow_sd15_img2img.json")), paths
        )
        wf["source"]["inputs"]["image_path"] = str(src)
        wf["save"]["inputs"]["output_dir"] = str(tmp_path / "out")

        out = run_workflow(wf)
        images = out["decode"][0]
        lat = 16 // factor  # encode downsamples by the tiny VAE's factor
        assert out["sampler"][0]["samples"].shape[1:3] == (lat, lat)
        assert images.shape == (1, lat * factor, lat * factor, 3)
        assert np.isfinite(np.asarray(images)).all()
        saved = out["save"][0]
        assert len(saved) == 1 and os.path.exists(saved[0])


    def test_example_inpaint_outpaint_executes(self, cpu_devices, tmp_path,
                                               monkeypatch):
        import os

        from PIL import Image

        paths, factor = self._synthetic_env(tmp_path, monkeypatch)
        src = tmp_path / "input.png"
        Image.fromarray(
            (np.random.default_rng(0).uniform(0, 1, (16, 16, 3)) * 255).astype(
                np.uint8
            )
        ).save(src)
        wf = self._rewrite_common(
            json.load(open("examples/workflow_sd15_inpaint_outpaint.json")),
            paths,
        )
        wf["source"]["inputs"]["image_path"] = str(src)
        # Tiny-scale the outpaint extension to the synthetic world.
        wf["outpaint_pad"]["inputs"].update(left=8, right=8, feathering=4)
        wf["save"]["inputs"]["output_dir"] = str(tmp_path / "out")

        out = run_workflow(wf)
        images = out["paste_back"][0]
        # 16px source + 8px pad each side; decode returns the padded frame.
        assert images.shape == (1, 16, 32, 3)
        assert np.isfinite(np.asarray(images)).all()
        # The source interior survives the paste-back (mask is 0 there away
        # from the feather band).
        src_px = np.asarray(Image.open(src), np.float32)[None] / 255.0
        np.testing.assert_allclose(
            np.asarray(images[:, 4:12, 14:18, :]),
            src_px[:, 4:12, 6:10, :], atol=0.35,
        )
        saved = out["save"][0]
        assert len(saved) == 1 and os.path.exists(saved[0])

    def test_example_hiresfix_executes(self, cpu_devices, tmp_path,
                                       monkeypatch):
        import os

        import jax
        from safetensors.numpy import save_file

        from comfyui_parallelanything_tpu.models.upscale import (
            UpscaleConfig,
            build_upscaler,
        )
        from tests.test_upscale import _modern_sd

        import jax.numpy as jnp

        paths, factor = self._synthetic_env(tmp_path, monkeypatch)
        ucfg = UpscaleConfig(nf=8, nb=1, gc=4, scale=4, dtype=jnp.float32)
        up = build_upscaler(ucfg, jax.random.key(7))
        up_path = tmp_path / "esrgan_tiny.safetensors"
        save_file(
            {k: np.ascontiguousarray(v)
             for k, v in _modern_sd(ucfg, up.params).items()},
            str(up_path),
        )
        wf = self._rewrite_common(
            json.load(open("examples/workflow_sd15_hiresfix.json")), paths
        )
        wf["latent"]["inputs"].update(width=32, height=32, batch_size=1)
        wf["hires_pass"]["inputs"]["steps"] = 2
        wf["esrgan"]["inputs"]["ckpt_path"] = str(up_path)
        wf["final_upscale"]["inputs"]["tile"] = 0
        wf["save"]["inputs"]["output_dir"] = str(tmp_path / "out")

        out = run_workflow(wf)
        hw = 32 // 8 * factor  # base latent grid through the tiny VAE
        base = out["decode"][0]
        assert base.shape == (1, 2 * hw, 2 * hw, 3)  # latent-upscaled 2x
        final = out["final_upscale"][0]
        assert final.shape == (1, 8 * hw, 8 * hw, 3)  # ESRGAN x4 on top
        assert np.isfinite(np.asarray(final)).all()
        saved = out["save"][0]
        assert len(saved) == 1 and os.path.exists(saved[0])


class TestEndToEndGraph:
    def test_full_sampling_workflow(self, cpu_devices):
        # The reference's whole value proposition as one JSON file: build a
        # chain, parallelize the model, sample a latent — every denoise step
        # rides the mesh.
        wf = {
            "dev1": {"class_type": "ParallelDevice",
                     "inputs": {"device_id": "cpu:0", "percentage": 25.0}},
            "dev2": {"class_type": "ParallelDevice",
                     "inputs": {"device_id": "cpu:1", "percentage": 25.0,
                                "previous_devices": ["dev1", 0]}},
            "dev3": {"class_type": "ParallelDevice",
                     "inputs": {"device_id": "cpu:2", "percentage": 25.0,
                                "previous_devices": ["dev2", 0]}},
            "dev4": {"class_type": "ParallelDevice",
                     "inputs": {"device_id": "cpu:3", "percentage": 25.0,
                                "previous_devices": ["dev3", 0]}},
            "model": {"class_type": "ToyModel", "inputs": {}},
            "par": {"class_type": "ParallelAnything",
                    "inputs": {"model": ["model", 0],
                               "parallel_devices": ["dev4", 0],
                               "workload_split": True,
                               "auto_vram_balance": True,
                               "purge_cache": True,
                               "purge_models": False}},
            "pos": {"class_type": "ToyConditioning", "inputs": {"seed": 1}},
            "lat": {"class_type": "TPUEmptyLatent",
                    "inputs": {"width": 64, "height": 64, "batch_size": 4}},
            "samp": {"class_type": "TPUKSampler",
                     "inputs": {"model": ["par", 0], "positive": ["pos", 0],
                                "latent": ["lat", 0], "seed": 3, "steps": 2,
                                "cfg": 1.0, "sampler_name": "euler",
                                "scheduler": "karras"}},
        }
        out = run_workflow(wf, CUSTOM)
        latent = out["samp"][0]["samples"]
        assert latent.shape == (4, 8, 8, 4)
        assert np.isfinite(np.asarray(latent)).all()
        # The MODEL that sampled is the parallel wrapper over the 4-dev chain.
        pm = out["par"][0]
        assert pm.devices == ("cpu:0", "cpu:1", "cpu:2", "cpu:3")


class TestCustomSamplingWorkflow:
    """A custom-sampling graph in API-format JSON — the node wiring exported
    FLUX workflows use (RandomNoise + KSamplerSelect + BasicScheduler +
    BasicGuider + SamplerCustomAdvanced) — executes through the host."""

    def test_custom_sampling_json_graph(self):
        wf = {
            "m": {"class_type": "ToyModel", "inputs": {}},
            "c": {"class_type": "ToyConditioning", "inputs": {"seed": 4}},
            "n": {"class_type": "TPURandomNoise", "inputs": {"noise_seed": 11}},
            "s": {"class_type": "TPUKSamplerSelect",
                  "inputs": {"sampler_name": "euler"}},
            "sig": {"class_type": "TPUBasicScheduler",
                    "inputs": {"model": ["m", 0], "scheduler": "normal",
                               "steps": 3, "denoise": 1.0}},
            "g": {"class_type": "TPUBasicGuider",
                  "inputs": {"model": ["m", 0], "conditioning": ["c", 0]}},
            "lat": {"class_type": "TPUEmptyLatent",
                    "inputs": {"width": 64, "height": 64, "batch_size": 1}},
            "out": {"class_type": "TPUSamplerCustomAdvanced",
                    "inputs": {"noise": ["n", 0], "guider": ["g", 0],
                               "sampler": ["s", 0], "sigmas": ["sig", 0],
                               "latent_image": ["lat", 0]}},
        }
        out = run_workflow(wf, CUSTOM)
        latent = out["out"][0]["samples"]
        assert latent.shape == (1, 8, 8, 4)
        assert np.isfinite(np.asarray(latent)).all()


class TestShippedStockExample:
    def test_example_stock_txt2img_executes(self, tmp_path, monkeypatch):
        """The stock-named example (pure ComfyUI builtin class names, the
        shape a stock export has) runs through the compat shims against the
        synthetic checkpoint — only user-editable fields rewritten."""
        import os

        from tests.test_stock_nodes import _synthetic_stock_env

        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        monkeypatch.setenv("PA_OUTPUT_DIR", str(tmp_path / "out"))
        wf = json.load(open("examples/workflow_stock_sd15_txt2img.json"))
        wf["4"]["inputs"]["ckpt_name"] = paths["ckpt"]
        wf["5"]["inputs"].update(width=32, height=32, batch_size=1)
        wf["3"]["inputs"]["steps"] = 2
        out = run_workflow(wf)
        images = np.asarray(out["8"][0])
        assert images.shape[0] == 1 and np.isfinite(images).all()
        assert all(os.path.exists(p) for p in out["9"][0])
