"""End-to-end serving load test (slow-marked — excluded from the tier-1 gate):
scripts/loadgen.py's closed loop driven against an in-process multi-worker
server, proving the whole path POST /prompt → workers → continuous-batching
scheduler → shared dispatches → /history under genuine concurrent load."""

import json
import sys
import threading
import os

import pytest

from comfyui_parallelanything_tpu.server import make_server
from tests.test_stock_nodes import _synthetic_stock_env
from tests.test_server import _stock_graph

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


@pytest.mark.slow
def test_loadgen_closed_loop_against_inprocess_server(tmp_path, monkeypatch):
    from loadgen import run_load

    out_dir = tmp_path / "out"
    srv, q = make_server(port=0, output_dir=str(out_dir), workers=4)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        paths = _synthetic_stock_env(tmp_path, monkeypatch)
        graph = _stock_graph(paths["ckpt"], str(out_dir))
        graph["3"]["inputs"]["steps"] = 6

        # Warm pass: loader/encoders cached, bucket program compiled — the
        # measured loop then exercises steady-state serving.
        warm = run_load(base, graph, clients=1, requests=1, timeout=600,
                        seed_key="3:inputs:seed")
        assert warm["completed"] == 1, warm

        # MIXED workload (round 10): round-robin the sampler family across
        # prompts — different samplers still share the dispatch stream, and
        # the amortization fields prove it from the scraped counters alone.
        summary = run_load(
            base, graph, clients=3, requests=2, timeout=600,
            seed_key="3:inputs:seed",
            samplers=["euler", "heun", "dpmpp_2m", "euler_ancestral"],
            sampler_key="3:inputs:sampler_name",
        )
        print(json.dumps(summary))
        assert summary["completed"] == 6, summary
        assert summary["failed"] == 0, summary
        assert summary["latency_p50_s"] > 0
        assert summary["latency_p95_s"] >= summary["latency_p50_s"]
        # Continuous batching engaged across sampler families: 6 prompts × 6
        # steps ≥ 36 serial evals (heun lanes take 11); the closed loop keeps
        # 3 in flight, so shared lockstep dispatches must come in well under
        # serial, and the amortization counters must show actual sharing.
        assert summary["serving_dispatches"] is not None
        assert 6 <= summary["serving_dispatches"] < 36, summary
        assert summary["serving_lane_steps"] >= summary["serving_dispatches"]
        assert summary["dispatch_amortization"] >= 1.0, summary
        assert 0.0 < summary["serving_batched_fraction"] <= 1.0, summary
        assert summary["samplers"] == [
            "euler", "heun", "dpmpp_2m", "euler_ancestral",
        ]
    finally:
        srv.shutdown()
        q.shutdown()
