"""SLO plane (utils/slo.py, round 15): declared objectives, window/burn-rate
accounting, the pa_slo_* stage decomposition fed from the server/serving/host
measurement points, the Prometheus-text readers the router and loadgen share,
and the PA_SLO=0 no-op contract (the tracer/sentinel/roofline discipline)."""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request

import pytest

from comfyui_parallelanything_tpu.utils import slo
from comfyui_parallelanything_tpu.utils.metrics import MetricsRegistry, registry


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Process-global state: every test starts with a fresh metrics registry,
    the default objectives, and PA_SLO unset (enabled)."""
    monkeypatch.delenv("PA_SLO", raising=False)
    monkeypatch.delenv("PA_SLO_OBJECTIVES", raising=False)
    registry.reset()
    slo.registry.reset()
    yield
    registry.reset()
    slo.registry.reset()


class TestObjectives:
    def test_defaults_and_env_parse(self, monkeypatch):
        assert [o.name for o in slo.objectives_from_env()] == \
            [o.name for o in slo.DEFAULT_OBJECTIVES]
        monkeypatch.setenv("PA_SLO_OBJECTIVES", json.dumps([
            {"name": "fast", "threshold_s": 0.5, "target": 0.9,
             "window_s": 60},
            {"name": "slow", "threshold_s": 5.0},
        ]))
        objs = slo.objectives_from_env()
        assert [o.name for o in objs] == ["fast", "slow"]
        assert objs[0].threshold_s == 0.5 and objs[0].target == 0.9
        assert objs[0].window_s == 60
        assert objs[1].target == 0.95  # default

    def test_malformed_objectives_fail_loudly(self):
        with pytest.raises(ValueError):
            slo.parse_objectives("not json{")
        with pytest.raises(ValueError):
            slo.parse_objectives(json.dumps({"name": "x"}))  # not a list
        with pytest.raises(ValueError):
            slo.parse_objectives(json.dumps([{"name": "x"}]))  # no threshold

    def test_request_bounds_align_thresholds(self):
        objs = [slo.Objective(name="a", threshold_s=0.123),
                slo.Objective(name="b", threshold_s=2.5)]
        bounds = slo.request_bounds(objs)
        assert 0.123 in bounds and 2.5 in bounds
        assert list(bounds) == sorted(bounds)
        # the default ladder survives intact
        assert set(slo.STAGE_BOUNDS) <= set(bounds)


class TestWindowAccounting:
    def test_burn_rate_math(self):
        reg = slo.SloRegistry(objectives=[
            slo.Objective(name="t", threshold_s=0.1, target=0.9,
                          window_s=3600),
        ])
        for _ in range(9):
            reg.observe_request(0.05)   # good
        reg.observe_request(1.0)        # bad
        [v] = reg.verdicts()
        assert v["requests"] == 10 and v["bad"] == 1
        assert v["bad_fraction"] == pytest.approx(0.1)
        # budget = 1 - 0.9 = 0.1; bad fraction 0.1 → burning exactly at
        # the allowed rate: burn 1.0, budget exhausted, still (just) ok.
        assert v["burn_rate"] == pytest.approx(1.0)
        assert v["budget_remaining"] == pytest.approx(0.0)
        assert v["ok"] is True
        reg.observe_request(2.0)        # now over budget
        [v] = reg.verdicts()
        assert v["burn_rate"] > 1.0 and v["ok"] is False
        assert reg.burn_rate("t") == v["burn_rate"]

    def test_empty_window_vacuously_ok(self):
        reg = slo.SloRegistry(objectives=[
            slo.Objective(name="t", threshold_s=0.1),
        ])
        [v] = reg.verdicts()
        assert v["requests"] == 0 and v["burn_rate"] == 0.0 and v["ok"]

    def test_window_expiry(self):
        reg = slo.SloRegistry(objectives=[
            slo.Objective(name="t", threshold_s=0.1, target=0.5,
                          window_s=0.05),
        ])
        reg.observe_request(9.0)  # bad
        [v] = reg.verdicts()
        assert v["bad"] == 1
        time.sleep(0.08)
        [v] = reg.verdicts()      # the bad event aged out of the window
        assert v["requests"] == 0 and v["ok"]

    def test_histograms_and_gauges_emitted(self):
        slo.observe_request(0.01)
        slo.observe_stage("admission", 0.002)
        assert registry.get("pa_slo_request_seconds") is not None
        assert registry.get("pa_slo_stage_seconds",
                            {"stage": "admission"}) is not None
        slo.registry.publish_gauges()
        text = registry.render()
        assert re.search(r'^pa_slo_burn_rate\{objective="[^"]+"\} ', text,
                         re.M)
        assert re.search(r"^pa_slo_budget_remaining\{", text, re.M)
        # threshold-aligned bucket edge (default objective: 30s)
        assert re.search(r'^pa_slo_request_seconds_bucket\{le="30"\} ',
                         text, re.M)


class TestDisabledNoOp:
    def test_pa_slo_0_is_noop(self, monkeypatch):
        monkeypatch.setenv("PA_SLO", "0")
        assert not slo.enabled()
        slo.observe_request(1.0)
        slo.observe_stage("eval", 1.0)
        slo.registry.publish_gauges()
        assert registry.get("pa_slo_request_seconds") is None
        assert registry.get("pa_slo_stage_seconds", {"stage": "eval"}) is None
        assert "pa_slo_" not in registry.render()


class TestTextReaders:
    def _render(self, objs=None):
        r = MetricsRegistry()
        bounds = slo.request_bounds(objs or [
            slo.Objective(name="t", threshold_s=0.1, target=0.75),
        ])
        for host, vals in (("h0", (0.05, 0.05, 0.09, 2.0)),
                           ("h1", (0.02, 0.3, 0.4, 0.45))):
            for v in vals:
                r.histogram("pa_slo_request_seconds", v,
                            labels={"host": host}, bounds=bounds)
        return r.render()

    def test_fraction_under_exact_at_edge(self):
        text = self._render()
        # global: 4 of 8 under 0.1 (edge-aligned → exact)
        fraction, total = slo.fraction_under(
            text, "pa_slo_request_seconds", 0.1)
        assert total == 8 and fraction == pytest.approx(0.5)
        # per-host filter
        fraction, total = slo.fraction_under(
            text, "pa_slo_request_seconds", 0.1, labels={"host": "h0"})
        assert total == 4 and fraction == pytest.approx(0.75)

    def test_fraction_under_mixed_ladders_per_series(self):
        """Hosts declaring DIFFERENT objectives expose different bucket
        ladders for one metric; the reader must evaluate each series on its
        own ladder and aggregate by count — summing cumulative counts
        across ladders is non-monotone at edges only one host has (a 2-of-2
        host must not drag a 98-of-98 host down to 2%)."""
        ra, rb = MetricsRegistry(), MetricsRegistry()
        bounds_a = slo.request_bounds([
            slo.Objective(name="t", threshold_s=0.3),
        ])
        for v in (0.2, 0.2):
            ra.histogram("pa_slo_request_seconds", v, labels={"host": "a"},
                         bounds=bounds_a)
        for _ in range(98):  # default ladder: no 0.3 edge (0.25, 0.5)
            rb.histogram("pa_slo_request_seconds", 0.05,
                         labels={"host": "b"})
        text = ra.render() + rb.render()
        fraction, total = slo.fraction_under(
            text, "pa_slo_request_seconds", 0.3)
        assert total == 100
        assert fraction == pytest.approx(1.0)

    def test_verdicts_from_text(self):
        objs = [slo.Objective(name="t", threshold_s=0.1, target=0.75)]
        text = self._render(objs)
        [v] = slo.verdicts_from_text(text, objs)
        assert v["requests"] == 8
        assert v["achieved_fraction"] == pytest.approx(0.5)
        assert v["ok"] is False  # 0.5 < target 0.75
        [vh] = slo.verdicts_from_text(text, objs, labels={"host": "h0"})
        assert vh["achieved_fraction"] == pytest.approx(0.75)
        assert vh["ok"] is True
        # absent histogram → explicit unknown, not a crash
        [vn] = slo.verdicts_from_text("", objs)
        assert vn["achieved_fraction"] is None and vn["ok"] is None

    def test_label_filtered_quantile_matches_registry(self):
        r = MetricsRegistry()
        import numpy as np

        rng = np.random.default_rng(3)
        for v in rng.uniform(0.001, 2.0, size=150):
            r.histogram("pa_x_seconds", float(v), labels={"stage": "eval"})
        for v in rng.uniform(5.0, 40.0, size=50):
            r.histogram("pa_x_seconds", float(v), labels={"stage": "decode"})
        text = r.render()
        for stage in ("eval", "decode"):
            got = slo.histogram_quantile(text, "pa_x_seconds", 95,
                                         labels={"stage": stage})
            want = r.quantile("pa_x_seconds", 95, labels={"stage": stage})
            assert got == pytest.approx(want), stage


class _MiniSampler:
    CATEGORY = "test"
    RETURN_TYPES = ("INT",)
    FUNCTION = "run"

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"seed": ("INT", {"default": 0})}}

    def run(self, seed):
        time.sleep(0.002)
        return (int(seed),)


class _MiniDecode:
    CATEGORY = "test"
    RETURN_TYPES = ("INT",)
    FUNCTION = "run"

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"x": ("INT", {"default": 0})}}

    def run(self, x):
        time.sleep(0.002)
        return (int(x),)


class TestStageInstrumentation:
    def test_workflow_nodes_feed_eval_and_decode(self):
        from comfyui_parallelanything_tpu.host import run_workflow

        graph = {
            "1": {"class_type": "_MiniSampler", "inputs": {"seed": 1}},
            "2": {"class_type": "_MiniDecode", "inputs": {"x": ["1", 0]}},
        }
        run_workflow(graph, class_mappings={
            "_MiniSampler": _MiniSampler, "_MiniDecode": _MiniDecode,
        })
        ev = registry.get("pa_slo_stage_seconds", {"stage": "eval"})
        de = registry.get("pa_slo_stage_seconds", {"stage": "decode"})
        assert ev is not None and ev[1] == 1  # (sum, count)
        assert de is not None and de[1] == 1
        assert ev[0] >= 0.002 and de[0] >= 0.002

    def test_server_observes_admission_and_request(self, tmp_path):
        from comfyui_parallelanything_tpu.server import make_server

        srv, q = make_server(
            port=0, output_dir=str(tmp_path / "out"),
            class_mappings={"_MiniSampler": _MiniSampler},
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            body = json.dumps({"prompt": {
                "1": {"class_type": "_MiniSampler", "inputs": {"seed": 3}},
            }}).encode()
            req = urllib.request.Request(
                base + "/prompt", data=body,
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                pid = json.loads(r.read())["prompt_id"]
            t0 = time.time()
            while time.time() - t0 < 30:
                with urllib.request.urlopen(
                    base + f"/history/{pid}", timeout=30
                ) as r:
                    if pid in json.loads(r.read()):
                        break
                time.sleep(0.02)
            adm = registry.get("pa_slo_stage_seconds", {"stage": "admission"})
            assert adm is not None and adm[1] >= 1
            req_h = registry.get("pa_slo_request_seconds")
            assert req_h is not None and req_h[1] >= 1
            # scrape-time burn gauges on GET /metrics
            with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
                text = r.read().decode()
            assert re.search(r"^pa_slo_burn_rate\{", text, re.M)
            assert re.search(
                r'^pa_slo_stage_seconds_bucket\{.*stage="admission"', text,
                re.M,
            )
        finally:
            srv.shutdown()
            srv.server_close()
            q.shutdown()
