"""Traffic twin (fleet/twin.py + scripts/twin_report.py, round 15): seeded
arrival processes, the discrete-event queueing simulation, the tiered
per-host capacity model (roofline prediction → measured service p50 → mean),
record replay, and the twin gate's SKIP/OK/FAIL/bank discipline."""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from comfyui_parallelanything_tpu.fleet import twin

REPO = Path(__file__).resolve().parents[1]


class TestArrivals:
    def test_poisson_deterministic_and_rate(self):
        a = twin.gen_arrivals("poisson", rps=10, duration_s=50, seed=7)
        b = twin.gen_arrivals("poisson", rps=10, duration_s=50, seed=7)
        assert a == b and a == sorted(a)
        assert all(0 <= t < 50 for t in a)
        assert len(a) / 50 == pytest.approx(10, rel=0.15)
        c = twin.gen_arrivals("poisson", rps=10, duration_s=50, seed=8)
        assert c != a  # a different seed is a different schedule

    def test_onoff_bursty_but_same_offered_load(self):
        a = twin.gen_arrivals("onoff", rps=10, duration_s=60, seed=3,
                              on_s=1.0, off_s=1.0)
        assert len(a) / 60 == pytest.approx(10, rel=0.2)
        # every arrival lands in an ON window ([2k, 2k+1))
        assert all((t % 2.0) < 1.0 for t in a)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            twin.gen_arrivals("diurnal", rps=1, duration_s=1)

    def test_journal_replay_and_arrivals_doc_roundtrip(self, tmp_path):
        jpath = tmp_path / "journal.jsonl"
        with open(jpath, "w") as f:
            for i, ts in enumerate((100.0, 100.5, 102.25)):
                f.write(json.dumps({"ev": "submit", "pid": f"p{i}",
                                    "ts": ts}) + "\n")
            f.write(json.dumps({"ev": "dispatch", "pid": "p0",
                                "ts": 103.0}) + "\n")
            f.write("torn{garbage\n")
        offsets = twin.arrivals_from_journal(str(jpath))
        assert offsets == [0.0, 0.5, 2.25]  # submits only, rebased
        doc = twin.load_arrivals(str(jpath))
        assert doc["kind"] == "replay"
        assert doc["rungs"][0]["offsets"] == offsets
        # save/load of a generated schedule
        out = tmp_path / "arrivals.json"
        twin.save_arrivals(str(out), [{"rps": 5, "duration_s": 2,
                                       "offsets": [0.1, 0.4]}],
                           kind="poisson", seed=7)
        doc2 = twin.load_arrivals(str(out))
        assert doc2["schema"] == twin.ARRIVALS_SCHEMA
        assert doc2["rungs"][0]["offsets"] == [0.1, 0.4]


class TestSimulation:
    def _hosts(self, n=2, service=0.1, workers=1):
        return [{"host_id": f"h{i}", "service_s": service,
                 "workers": workers} for i in range(n)]

    def test_queueing_grows_with_load(self):
        """The open-loop point: past saturation, p95 blows up — the twin
        must reproduce the knee the closed loop can never see."""
        hosts = self._hosts(n=2, service=0.1)  # capacity ≈ 20 rps
        low = twin.simulate(
            twin.gen_arrivals("poisson", rps=5, duration_s=30, seed=1), hosts)
        high = twin.simulate(
            twin.gen_arrivals("poisson", rps=40, duration_s=30, seed=1),
            hosts)
        assert low["latency_p95_s"] < 0.3
        assert high["latency_p95_s"] > 5 * low["latency_p95_s"]
        assert high["queue_wait_mean_s"] > low["queue_wait_mean_s"]

    def test_more_workers_absorb_more(self):
        arrivals = twin.gen_arrivals("poisson", rps=30, duration_s=20, seed=2)
        one = twin.simulate(arrivals, self._hosts(n=2, workers=1))
        four = twin.simulate(arrivals, self._hosts(n=2, workers=4))
        assert four["latency_p95_s"] < one["latency_p95_s"]

    def test_deterministic_and_balanced(self):
        arrivals = twin.gen_arrivals("poisson", rps=20, duration_s=10, seed=4)
        s1 = twin.simulate(arrivals, self._hosts())
        s2 = twin.simulate(arrivals, self._hosts())
        assert s1 == s2
        assert s1["requests"] == len(arrivals) == sum(s1["hosts"].values())
        # both hosts served (least-start placement spreads a saturating load)
        assert all(v > 0 for v in s1["hosts"].values())

    def test_overhead_shifts_latency_only(self):
        arrivals = twin.gen_arrivals("poisson", rps=5, duration_s=10, seed=5)
        base = twin.simulate(arrivals, self._hosts())
        off = twin.simulate(arrivals, self._hosts(), overhead_s=0.25)
        assert off["latency_p50_s"] == pytest.approx(
            base["latency_p50_s"] + 0.25)
        assert off["queue_wait_mean_s"] == base["queue_wait_mean_s"]


class TestRoleTandem:
    """Round 20: host rows carrying ``role`` turn the simulation into the
    disaggregated encode→denoise→decode tandem (fleet/roles.py's pools with
    stage hand-off edges); an all-``all`` fleet stays on the single-queue
    path bit-for-bit."""

    def _role_hosts(self, n_denoise=2):
        return (
            [{"host_id": "enc", "service_s": 0.01, "workers": 1,
              "role": "encode"}]
            + [{"host_id": f"den{i}", "service_s": 0.10, "workers": 1,
                "role": "denoise"} for i in range(n_denoise)]
            + [{"host_id": "dec", "service_s": 0.02, "workers": 1,
                "role": "decode"}]
        )

    def test_all_role_rows_match_roleless_rows_bitwise(self):
        arrivals = twin.gen_arrivals("poisson", rps=10, duration_s=10, seed=6)
        plain = [{"host_id": f"h{i}", "service_s": 0.05, "workers": 2}
                 for i in range(3)]
        tagged = [dict(h, role="all") for h in plain]
        assert twin.simulate(arrivals, plain) == twin.simulate(
            arrivals, tagged)

    def test_tandem_latency_is_the_stage_sum_at_low_load(self):
        arrivals = twin.gen_arrivals("poisson", rps=2, duration_s=20, seed=7)
        s = twin.simulate(arrivals, self._role_hosts())
        assert s["requests"] == len(arrivals)
        # Unqueued request = one visit per stage pool: 0.01 + 0.10 + 0.02.
        assert s["latency_p50_s"] == pytest.approx(0.13, abs=0.02)
        # Every stage pool served; each request denoises exactly once.
        assert s["hosts"]["enc"] == len(arrivals)
        assert s["hosts"]["dec"] == len(arrivals)
        assert s["hosts"]["den0"] + s["hosts"]["den1"] == len(arrivals)

    def test_generalist_covers_stages_with_no_dedicated_host(self):
        arrivals = twin.gen_arrivals("poisson", rps=2, duration_s=10, seed=8)
        hosts = [
            {"host_id": "den", "service_s": 0.05, "workers": 1,
             "role": "denoise"},
            {"host_id": "gen", "service_s": 0.05, "workers": 1,
             "role": "all"},
        ]
        s = twin.simulate(arrivals, hosts)
        assert s["requests"] == len(arrivals)
        # encode + decode have only the generalist — it serves every
        # request at least twice.
        assert s["hosts"]["gen"] >= 2 * len(arrivals)

    def test_widening_the_bottleneck_pool_absorbs_the_load(self):
        """The twin-level readout of suggest_pool_split: denoise saturates
        first (capacity 10 rps at 0.1 s service) — one more denoise host is
        the fix, the per-role scaling knob."""
        arrivals = twin.gen_arrivals("poisson", rps=15, duration_s=20, seed=9)
        narrow = twin.simulate(arrivals, self._role_hosts(n_denoise=1))
        wide = twin.simulate(arrivals, self._role_hosts(n_denoise=2))
        assert wide["latency_p95_s"] < narrow["latency_p95_s"] / 2


class TestCapacityTiers:
    def test_measured_and_mean_tiers(self):
        rec = {
            "service_p50_s": 0.2,
            "hosts": {
                "h0": {"service_p50_s": 0.1, "workers": 2},
                "h1": {"workers": 1},              # falls back to the mean
                "h2": "not-a-row",                 # ignored
            },
        }
        rows = {h["host_id"]: h for h in twin.host_service_times(rec)}
        assert rows["h0"]["service_s"] == 0.1
        assert rows["h0"]["source"] == "measured"
        assert rows["h0"]["workers"] == 2
        assert rows["h1"]["service_s"] == 0.2
        assert rows["h1"]["source"] == "mean"
        assert "h2" not in rows

    def test_roofline_tier_with_calibration(self):
        rec = {"hosts": {"h0": {
            "flops": 1e12, "bytes_accessed": 1e9, "workers": 1,
            "platform": "cpu",
        }}}
        [row] = twin.host_service_times(rec, calib={})
        assert row["source"] == "roofline"
        # CPU pseudo-spec: compute-bound at 1e12 / 2e12 = 0.5 s
        assert row["service_s"] == pytest.approx(0.5, rel=0.05)
        [scaled] = twin.host_service_times(rec, calib={
            "rung:openloop|cpu|*": {"scale": 2.0, "n": 4},
        })
        assert scaled["service_s"] == pytest.approx(2 * row["service_s"])

    def test_no_capacity_evidence_is_empty(self):
        assert twin.host_service_times({"hosts": {"h0": {}}}) == []


def _openloop_record(measured_from_twin=True, band=0.25):
    """A synthetic openloop ledger record whose measured curve either
    matches the twin's own prediction (OK) or wildly disagrees (FAIL)."""
    hosts = [{"host_id": "h0", "service_s": 0.1, "workers": 1},
             {"host_id": "h1", "service_s": 0.1, "workers": 1}]
    curve = []
    for rps in (5.0, 15.0):
        arrivals = twin.gen_arrivals("poisson", rps=rps, duration_s=10,
                                     seed=7)
        sim = twin.simulate(arrivals, hosts, overhead_s=0.05)
        measured = (sim["latency_p95_s"] if measured_from_twin else
                    sim["latency_p95_s"] * 10 + 5)
        curve.append({
            "rps": rps, "rps_offered": round(len(arrivals) / 10, 4),
            "duration_s": 10, "arrivals": len(arrivals),
            "completed": len(arrivals),
            "latency_p50_s": sim["latency_p50_s"],
            "latency_p95_s": round(measured, 6),
            "latency_p99_s": sim["latency_p99_s"],
        })
    return {
        "schema": "pa-perf-ledger/v1", "kind": "openloop",
        "base": "http://test:1", "ts": 1.0,
        "openloop": {"kind": "poisson", "seed": 7, "curve": curve,
                     "client_overhead_s": 0.05, "twin_band": band},
        "twin_band": band,
        "hosts": {"h0": {"service_p50_s": 0.1, "workers": 1},
                  "h1": {"service_p50_s": 0.1, "workers": 1}},
        "service_p50_s": 0.1,
    }


class TestReplayRecord:
    def test_replay_matches_itself(self):
        rep = twin.replay_record(_openloop_record())
        assert rep is not None
        assert rep["p95_err_max"] == pytest.approx(0.0, abs=1e-6)
        assert len(rep["rungs"]) == 2
        assert {h["source"] for h in rep["hosts"]} == {"measured"}

    def test_unreplayable_records(self):
        assert twin.replay_record({}) is None
        assert twin.replay_record({"openloop": {"curve": []}}) is None
        rec = _openloop_record()
        rec.pop("hosts")
        rec.pop("service_p50_s")
        assert twin.replay_record(rec) is None


class TestTwinReportScript:
    def _run(self, ledger_dir, *args):
        return subprocess.run(
            [sys.executable, str(REPO / "scripts" / "twin_report.py"),
             "--ledger", str(ledger_dir), *args],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "PALLAS_AXON_POOL_IPS": ""},
        )

    def _write_ledger(self, tmp_path, records):
        d = tmp_path / "ledger"
        d.mkdir(parents=True, exist_ok=True)
        with open(d / "perf_ledger.jsonl", "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return d

    def test_skip_on_empty_ledger(self, tmp_path):
        d = self._write_ledger(tmp_path, [
            {"schema": "pa-perf-ledger/v1", "kind": "bench", "value": 1.0},
        ])
        proc = self._run(d, "--check")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "SKIP" in proc.stdout

    def test_check_ok_and_fail(self, tmp_path):
        d = self._write_ledger(tmp_path, [_openloop_record()])
        proc = self._run(d, "--check")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK" in proc.stdout
        d2 = self._write_ledger(
            tmp_path / "bad", [_openloop_record(measured_from_twin=False)])
        proc = self._run(d2, "--check")
        assert proc.returncode == 1
        assert "FAIL" in proc.stdout

    def test_latest_record_wins(self, tmp_path):
        # An old out-of-band record is superseded by a newer in-band one.
        d = self._write_ledger(tmp_path, [
            _openloop_record(measured_from_twin=False),
            _openloop_record(),
        ])
        proc = self._run(d, "--check")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_bank_writes_twin_bank(self, tmp_path):
        d = self._write_ledger(tmp_path, [_openloop_record()])
        proc = self._run(d, "--bank")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        bank = json.loads((d / "twin_bank.json").read_text())
        assert bank["schema"] == "pa-twin-bank/v1"
        [group] = bank["groups"].values()
        assert group["p95_err_max"] == pytest.approx(0.0, abs=1e-6)
        assert group["band"] == 0.25
