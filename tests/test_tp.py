"""GSPMD tensor parallelism (2-D data × model mesh). Beyond-reference capability:
the reference's README states "No model parallelism" (README.md:212); here the mesh
abstraction carries it (SURVEY §5.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, ParallelConfig, parallelize
from comfyui_parallelanything_tpu.models import build_unet, sd15_config
from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux


@pytest.fixture(scope="module")
def tiny_flux():
    # hidden 128 so the MLP kernels (128×512 = 2^16) clear place_params_tp's
    # min-size threshold and genuinely shard.
    cfg = FluxConfig(
        in_channels=16, hidden_size=128, num_heads=4, depth=2, depth_single_blocks=2,
        context_in_dim=32, vec_in_dim=16, axes_dim=(8, 12, 12), guidance_embed=False,
        dtype=jnp.float32,
    )
    return build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=16)


class TestTensorParallel:
    def test_2d_mesh_built(self, tiny_flux):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(tiny_flux, chain, ParallelConfig(tensor_parallel=4))
        mesh = pm._groups[0].mesh
        assert mesh.shape == {"data": 2, "model": 4}

    def test_tp_matches_replicate(self, tiny_flux):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm_tp = parallelize(tiny_flux, chain, ParallelConfig(tensor_parallel=4))
        x = jax.random.normal(jax.random.key(1), (4, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (4, 16, 32), jnp.float32)
        t = jnp.linspace(1.0, 0.2, 4)
        got = pm_tp(x, t, ctx)
        want = tiny_flux(x, t, ctx)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )

    def test_weights_sharded_on_model_axis(self, tiny_flux):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(tiny_flux, chain, ParallelConfig(tensor_parallel=2))
        leaves = jax.tree.leaves(pm._groups[0].params)
        sharded = [
            l for l in leaves
            if l.size >= 2**16 and l.addressable_shards[0].data.size < l.size
        ]
        assert sharded, "expected large weights sharded over the model axis"

    def test_indivisible_tp_raises(self, tiny_flux):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(3)])
        with pytest.raises(ValueError, match="does not divide"):
            parallelize(tiny_flux, chain, ParallelConfig(tensor_parallel=2))

    def test_tp_fsdp_conflict_raises(self, tiny_flux):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        with pytest.raises(ValueError, match="does not compose"):
            parallelize(
                tiny_flux, chain,
                ParallelConfig(tensor_parallel=2, weight_sharding="fsdp"),
            )

    def test_tp_batch1_runs_sharded(self, tiny_flux):
        # batch==1 under TP must run the sharded program — never pipeline stage
        # placement or a full lead-device copy (the weights only fit sharded).
        chain = DeviceChain.even([f"cpu:{i}" for i in range(8)])
        pm = parallelize(tiny_flux, chain, ParallelConfig(tensor_parallel=8))
        assert pm._groups[0].mesh.shape == {"data": 1, "model": 8}
        x = jax.random.normal(jax.random.key(5), (1, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(6), (1, 16, 32), jnp.float32)
        got = pm(x, jnp.array([0.5]), ctx)
        assert pm._pipeline_runner is None and pm._lead_params is None
        want = tiny_flux(x, jnp.array([0.5]), ctx)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )

    def test_tp_with_unet(self):
        cfg = sd15_config(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attention_levels=(1,), transformer_depth=(0, 1), num_heads=4,
            context_dim=64, norm_groups=8, dtype=jnp.float32,
        )
        model = build_unet(cfg, jax.random.key(0), sample_shape=(1, 16, 16, 4))
        chain = DeviceChain.even([f"cpu:{i}" for i in range(4)])
        pm = parallelize(model, chain, ParallelConfig(tensor_parallel=2))
        x = jax.random.normal(jax.random.key(1), (4, 16, 16, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (4, 12, 64), jnp.float32)
        got = pm(x, jnp.ones((4,)), ctx)
        want = model(x, jnp.ones((4,)), ctx)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )
