"""WAN-class video DiT: shapes, temporal structure, parallel execution, pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models.wan import WanConfig, build_wan
from comfyui_parallelanything_tpu.parallel.pipeline import build_pipeline_runner


@pytest.fixture(scope="module")
def tiny_wan():
    cfg = WanConfig(
        in_channels=4, out_channels=4, hidden_size=48, ffn_dim=96, num_heads=4,
        depth=2, text_dim=32, freq_dim=32, dtype=jnp.float32,
    )
    return build_wan(
        cfg, jax.random.key(0), sample_shape=(1, 2, 8, 8, 4), txt_len=8, name="tiny-wan"
    )


def _inputs(batch, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(k1, (batch, 2, 8, 8, 4), jnp.float32)
    ctx = jax.random.normal(k2, (batch, 8, 32), jnp.float32)
    return x, ctx


class TestWanForward:
    def test_shapes_and_finiteness(self, tiny_wan):
        x, ctx = _inputs(2)
        out = tiny_wan(x, jnp.array([0.9, 0.3]), ctx)
        assert out.shape == (2, 2, 8, 8, 4)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_axes_dim_sums_to_head_dim(self, tiny_wan):
        cfg = tiny_wan.config
        assert sum(cfg.axes_dim) == cfg.head_dim

    def test_temporal_position_matters(self, tiny_wan):
        # Swapping frames must change per-frame outputs (3-axis RoPE is live).
        x, ctx = _inputs(1)
        t = jnp.array([0.5])
        out = np.asarray(tiny_wan(x, t, ctx))
        out_swapped = np.asarray(tiny_wan(x[:, ::-1], t, ctx))
        assert not np.allclose(out[:, 0], out_swapped[:, 1], atol=1e-5)

    def test_context_matters(self, tiny_wan):
        x, ctx = _inputs(1)
        t = jnp.array([0.5])
        a = tiny_wan(x, t, ctx)
        b = tiny_wan(x, t, ctx * 2.0)
        assert float(jnp.max(jnp.abs(a - b))) > 1e-6

    def test_block_list_metadata(self, tiny_wan):
        assert tiny_wan.block_lists == {"blocks": 2}


class TestWanParallel:
    def test_sharded_equals_single(self, tiny_wan):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(4)])
        pm = parallelize(tiny_wan, chain)
        x, ctx = _inputs(4)
        t = jnp.linspace(1.0, 0.2, 4)
        got = pm(x, t, ctx)
        want = tiny_wan(x, t, ctx)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )

    def test_pipeline_staged_equals_monolithic(self, tiny_wan, cpu_devices):
        runner = build_pipeline_runner(
            tiny_wan.pipeline_spec, tiny_wan.params, cpu_devices[:2], [0.5, 0.5]
        )
        assert runner is not None and runner.n_stages == 2
        x, ctx = _inputs(1)
        t = jnp.array([0.4])
        got = runner(x, t, ctx)
        want = tiny_wan(x, t, ctx)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )
