"""Pipeline (batch==1) block-placement mode: staged execution across devices must
reproduce the monolithic forward (reference semantics: any_device_parallel.py:1152-1198,
routing at 1295-1305)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu import DeviceChain, parallelize
from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux
from comfyui_parallelanything_tpu.parallel.pipeline import build_pipeline_runner
from comfyui_parallelanything_tpu.parallel.split import block_ranges


@pytest.fixture(scope="module")
def staged_flux():
    cfg = FluxConfig(
        in_channels=16,
        hidden_size=64,
        num_heads=4,
        depth=3,
        depth_single_blocks=5,  # 8 segments total over up to 8 devices
        context_in_dim=32,
        vec_in_dim=16,
        axes_dim=(4, 6, 6),
        guidance_embed=True,
        dtype=jnp.float32,
    )
    return build_flux(
        cfg, jax.random.key(7), sample_shape=(1, 8, 8, 4), txt_len=16, name="staged"
    )


def _inputs(batch=1, seed=3):
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    x = jax.random.normal(k1, (batch, 8, 8, 4), jnp.float32)
    ctx = jax.random.normal(k2, (batch, 16, 32), jnp.float32)
    y = jax.random.normal(k3, (batch, 16), jnp.float32)
    return x, ctx, y


class TestPipelineRunner:
    def test_staged_equals_monolithic(self, staged_flux, cpu_devices):
        runner = build_pipeline_runner(
            staged_flux.pipeline_spec,
            staged_flux.params,
            cpu_devices[:4],
            [0.25, 0.25, 0.25, 0.25],
        )
        assert runner is not None and runner.n_stages == 4
        x, ctx, y = _inputs()
        t = jnp.array([0.7])
        got = runner(x, t, ctx, y=y)
        want = staged_flux(x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )

    def test_uneven_weights_place_proportionally(self, staged_flux, cpu_devices):
        # 8 segments at 50/25/25 → 4/2/2 blocks per stage.
        runner = build_pipeline_runner(
            staged_flux.pipeline_spec,
            staged_flux.params,
            cpu_devices[:3],
            [0.5, 0.25, 0.25],
        )
        assert [len(s.labels) for s in runner.stages] == [4, 2, 2]
        x, ctx, y = _inputs()
        t = jnp.array([0.3])
        got = runner(x, t, ctx, y=y)
        want = staged_flux(x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )

    def test_zero_weight_device_holds_no_stage(self, staged_flux, cpu_devices):
        runner = build_pipeline_runner(
            staged_flux.pipeline_spec,
            staged_flux.params,
            cpu_devices[:3],
            [0.5, 0.0, 0.5],
        )
        assert runner.n_stages == 2

    def test_single_device_returns_none(self, staged_flux, cpu_devices):
        assert (
            build_pipeline_runner(
                staged_flux.pipeline_spec, staged_flux.params, cpu_devices[:1], [1.0]
            )
            is None
        )

    def test_model_without_spec_returns_none(self, cpu_devices):
        assert build_pipeline_runner(None, {}, cpu_devices[:2], [0.5, 0.5]) is None


class TestRouterIntegration:
    def test_batch1_routes_through_pipeline(self, staged_flux):
        chain = DeviceChain.even([f"cpu:{i}" for i in range(4)])
        pm = parallelize(staged_flux, chain)
        x, ctx, y = _inputs(batch=1)
        t = jnp.array([0.5])
        got = pm(x, t, ctx, y=y)
        assert pm._pipeline_runner is not None  # lazy build happened
        want = staged_flux(x, t, ctx, y=y)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3
        )

    def test_workload_split_off_skips_pipeline(self, staged_flux):
        from comfyui_parallelanything_tpu import ParallelConfig

        chain = DeviceChain.even([f"cpu:{i}" for i in range(4)])
        pm = parallelize(
            staged_flux, chain, ParallelConfig(workload_split=False)
        )
        x, ctx, y = _inputs(batch=1)
        out = pm(x, jnp.array([0.5]), ctx, y=y)
        assert pm._pipeline_runner is None
        assert out.shape == x.shape

    def test_block_ranges_cover_all_segments(self):
        ranges = block_ranges(8, [0.5, 0.25, 0.25])
        assert ranges[0] == (0, 4) and ranges[-1][1] == 8

    def test_batch1_without_spec_runs_single_device(self):
        # A bare (apply_fn, params) model has no pipeline spec; batch==1 must route
        # single-device (reference 1156-1166 / 1307-1315), not padded data-parallel.
        import jax.numpy as jnp
        from comfyui_parallelanything_tpu import parallelize

        def f(p, x, t, context=None, **kw):
            return x * p["s"]

        pm = parallelize(
            (f, {"s": jnp.float32(2.0)}),
            DeviceChain.even([f"cpu:{i}" for i in range(4)]),
        )
        out = pm(jnp.ones((1, 4)), jnp.zeros((1,)))
        assert out.shape == (1, 4)
        assert pm._pipeline_runner is None
        assert len(out.sharding.device_set) == 1  # not spread over the mesh

    def test_pipeline_handles_static_kwargs(self, staged_flux):
        # Non-array kwargs must compile-time bake in pipeline mode too (the
        # orchestrator's kwargs contract).
        chain = DeviceChain.even([f"cpu:{i}" for i in range(4)])
        pm = parallelize(staged_flux, chain)
        x, ctx, y = _inputs(batch=1)
        out = pm(x, jnp.array([0.5]), ctx, y=y, debug_tag="a-string")
        assert out.shape == x.shape
