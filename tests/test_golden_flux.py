"""FLUX golden parity vs a minimal torch reference implementation.

Round-trip converter tests (test_convert.py) validate layout transposes but cannot
catch an architectural misreading — wrong norm order, wrong modulation split, wrong
RoPE pairing. This applies the text-encoder strategy (test_text_encoders.py) to the
diffusion core: a from-scratch torch implementation of the FLUX architecture (the
public BFL design: double img/txt streams with joint attention, fused single blocks,
adaLN modulation, multi-axis interleaved RoPE, tanh-approx GELU, eps=1e-6 norms),
randomly initialized, exported in the official flux1-dev state-dict layout, run
through ``convert_flux_checkpoint``, and compared activation-for-activation against
``models/flux.py``.

The torch modules here are written against the publicly documented architecture —
the reference node pack contains no model code at all (it wraps ComfyUI's), so this
is the ground truth a user's checkpoint actually follows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models.convert import convert_flux_checkpoint
from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux

torch = pytest.importorskip("torch")
tnn = torch.nn
F = torch.nn.functional

CFG = FluxConfig(
    in_channels=16,
    hidden_size=64,
    num_heads=4,          # head_dim 16
    depth=1,
    depth_single_blocks=2,
    mlp_ratio=4.0,
    context_in_dim=32,
    vec_in_dim=24,
    axes_dim=(4, 6, 6),   # sums to head_dim
    theta=10000.0,
    guidance_embed=True,
    patch_size=2,
    dtype=jnp.float32,
)


# ---------------------------------------------------------------------------------
# Torch reference (official FLUX architecture, official state-dict key layout)
# ---------------------------------------------------------------------------------


class TRMSNorm(tnn.Module):
    def __init__(self, dim):
        super().__init__()
        self.scale = tnn.Parameter(torch.randn(dim))

    def forward(self, x):
        x32 = x.float()
        n = x32 * torch.rsqrt(x32.pow(2).mean(-1, keepdim=True) + 1e-6)
        return n * self.scale


class TQKNorm(tnn.Module):
    def __init__(self, dim):
        super().__init__()
        self.query_norm = TRMSNorm(dim)
        self.key_norm = TRMSNorm(dim)


class TSelfAttention(tnn.Module):
    """Key container: .qkv / .norm.{query,key}_norm.scale / .proj."""

    def __init__(self, h, heads):
        super().__init__()
        self.qkv = tnn.Linear(h, 3 * h)
        self.norm = TQKNorm(h // heads)
        self.proj = tnn.Linear(h, h)


class TModulation(tnn.Module):
    def __init__(self, h, n_sets):
        super().__init__()
        self.lin = tnn.Linear(h, 3 * n_sets * h)
        self.n_chunks = 3 * n_sets

    def forward(self, vec):
        out = self.lin(F.silu(vec.float()))[:, None, :]
        return out.chunk(self.n_chunks, dim=-1)


class TMLPEmbedder(tnn.Module):
    def __init__(self, in_dim, h):
        super().__init__()
        self.in_layer = tnn.Linear(in_dim, h)
        self.out_layer = tnn.Linear(h, h)

    def forward(self, x):
        return self.out_layer(F.silu(self.in_layer(x)))


def t_timestep_embedding(t, dim, time_factor=1000.0, max_period=10000.0):
    t = time_factor * t.float()
    half = dim // 2
    freqs = torch.exp(
        -np.log(max_period) * torch.arange(half, dtype=torch.float32) / half
    )
    args = t[:, None] * freqs[None, :]
    return torch.cat([torch.cos(args), torch.sin(args)], dim=-1)


def t_rope_freqs(ids, axes_dim, theta):
    cos_parts, sin_parts = [], []
    for i, dim in enumerate(axes_dim):
        half = dim // 2
        freqs = theta ** (-torch.arange(half, dtype=torch.float32) / half)
        angles = ids[..., i].float()[..., None] * freqs
        cos_parts.append(torch.cos(angles))
        sin_parts.append(torch.sin(angles))
    return torch.cat(cos_parts, dim=-1), torch.cat(sin_parts, dim=-1)


def t_apply_rope(x, cos, sin):
    # (B, S, H, D), interleaved pairs; cos/sin (B, S, D//2) broadcast over heads.
    b, s, h, d = x.shape
    xp = x.float().reshape(b, s, h, d // 2, 2)
    xe, xo = xp[..., 0], xp[..., 1]
    c = cos[:, :, None, :]
    sn = sin[:, :, None, :]
    out = torch.stack([xe * c - xo * sn, xe * sn + xo * c], dim=-1)
    return out.reshape(b, s, h, d)


def t_attention(q, k, v):
    # f32 softmax attention on (B, S, H, D), matching ops/attention._xla_attention.
    d = q.shape[-1]
    logits = torch.einsum("bqhd,bkhd->bhqk", q, k).float() / np.sqrt(d)
    probs = torch.softmax(logits, dim=-1)
    return torch.einsum("bhqk,bkhd->bqhd", probs, v)


def t_modulate(x, shift, scale):
    return x.float() * (1.0 + scale) + shift


def _ln(x, h):
    return F.layer_norm(x, (h,), eps=1e-6)


class TDoubleBlock(tnn.Module):
    def __init__(self, h, heads, mlp_dim):
        super().__init__()
        self.h, self.heads = h, heads
        self.img_mod = TModulation(h, 2)
        self.txt_mod = TModulation(h, 2)
        self.img_attn = TSelfAttention(h, heads)
        self.txt_attn = TSelfAttention(h, heads)
        self.img_mlp = tnn.Sequential(
            tnn.Linear(h, mlp_dim), tnn.GELU(approximate="tanh"), tnn.Linear(mlp_dim, h)
        )
        self.txt_mlp = tnn.Sequential(
            tnn.Linear(h, mlp_dim), tnn.GELU(approximate="tanh"), tnn.Linear(mlp_dim, h)
        )

    def _qkv(self, attn, x):
        b, s, _ = x.shape
        qkv = attn.qkv(x).reshape(b, s, 3, self.heads, self.h // self.heads)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        return attn.norm.query_norm(q), attn.norm.key_norm(k), v

    def forward(self, img, txt, vec, cos, sin):
        h = self.h
        ims1, isc1, ig1, ims2, isc2, ig2 = self.img_mod(vec)
        tms1, tsc1, tg1, tms2, tsc2, tg2 = self.txt_mod(vec)

        iq, ik, iv = self._qkv(self.img_attn, t_modulate(_ln(img, h), ims1, isc1))
        tq, tk, tv = self._qkv(self.txt_attn, t_modulate(_ln(txt, h), tms1, tsc1))
        q = t_apply_rope(torch.cat([tq, iq], dim=1), cos, sin)
        k = t_apply_rope(torch.cat([tk, ik], dim=1), cos, sin)
        v = torch.cat([tv, iv], dim=1)
        attn = t_attention(q, k, v).reshape(q.shape[0], q.shape[1], -1)
        txt_len = txt.shape[1]
        txt_a, img_a = attn[:, :txt_len], attn[:, txt_len:]

        img = img + ig1 * self.img_attn.proj(img_a)
        txt = txt + tg1 * self.txt_attn.proj(txt_a)
        img = img + ig2 * self.img_mlp(t_modulate(_ln(img, h), ims2, isc2))
        txt = txt + tg2 * self.txt_mlp(t_modulate(_ln(txt, h), tms2, tsc2))
        return img, txt


class TSingleBlock(tnn.Module):
    def __init__(self, h, heads, mlp_dim):
        super().__init__()
        self.h, self.heads, self.mlp_dim = h, heads, mlp_dim
        self.modulation = TModulation(h, 1)
        self.linear1 = tnn.Linear(h, 3 * h + mlp_dim)
        self.linear2 = tnn.Linear(h + mlp_dim, h)
        self.norm = TQKNorm(h // heads)

    def forward(self, x, vec, cos, sin):
        h, heads = self.h, self.heads
        shift, scale, gate = self.modulation(vec)
        x_n = t_modulate(_ln(x, h), shift, scale)
        fused = self.linear1(x_n)
        qkv, mlp = fused[..., : 3 * h], fused[..., 3 * h :]
        b, s, _ = x.shape
        qkv = qkv.reshape(b, s, 3, heads, h // heads)
        q = self.norm.query_norm(qkv[:, :, 0])
        k = self.norm.key_norm(qkv[:, :, 1])
        v = qkv[:, :, 2]
        q, k = t_apply_rope(q, cos, sin), t_apply_rope(k, cos, sin)
        attn = t_attention(q, k, v).reshape(b, s, -1)
        out = self.linear2(torch.cat([attn, F.gelu(mlp, approximate="tanh")], dim=-1))
        return x + gate * out


class TFinalLayer(tnn.Module):
    def __init__(self, h, out_dim):
        super().__init__()
        self.adaLN_modulation = tnn.Sequential(tnn.SiLU(), tnn.Linear(h, 2 * h))
        self.linear = tnn.Linear(h, out_dim)


class TFlux(tnn.Module):
    def __init__(self, cfg: FluxConfig):
        super().__init__()
        self.cfg = cfg
        h = cfg.hidden_size
        mlp = int(h * cfg.mlp_ratio)
        self.img_in = tnn.Linear(cfg.in_channels, h)
        self.txt_in = tnn.Linear(cfg.context_in_dim, h)
        self.time_in = TMLPEmbedder(256, h)
        self.vector_in = TMLPEmbedder(cfg.vec_in_dim, h)
        if cfg.guidance_embed:
            self.guidance_in = TMLPEmbedder(256, h)
        self.double_blocks = tnn.ModuleList(
            [TDoubleBlock(h, cfg.num_heads, mlp) for _ in range(cfg.depth)]
        )
        self.single_blocks = tnn.ModuleList(
            [TSingleBlock(h, cfg.num_heads, mlp) for _ in range(cfg.depth_single_blocks)]
        )
        self.final_layer = TFinalLayer(h, cfg.in_channels)

    def forward(self, x, timesteps, context, y, guidance):
        cfg = self.cfg
        B, Hh, Ww, C = x.shape
        p = cfg.patch_size
        hp, wp = Hh // p, Ww // p

        img = x.reshape(B, hp, p, wp, p, C).permute(0, 1, 3, 2, 4, 5)
        img = img.reshape(B, hp * wp, p * p * C)
        img = self.img_in(img)
        txt = self.txt_in(context)

        vec = self.time_in(t_timestep_embedding(timesteps, 256))
        if cfg.guidance_embed:
            vec = vec + self.guidance_in(t_timestep_embedding(guidance, 256))
        vec = vec + self.vector_in(y)

        txt_len = txt.shape[1]
        txt_ids = torch.zeros(B, txt_len, 3, dtype=torch.int64)
        hh = torch.arange(hp)[:, None].expand(hp, wp)
        ww = torch.arange(wp)[None, :].expand(hp, wp)
        grid = torch.stack([torch.zeros_like(hh), hh, ww], dim=-1).reshape(1, hp * wp, 3)
        ids = torch.cat([txt_ids, grid.expand(B, hp * wp, 3)], dim=1)
        cos, sin = t_rope_freqs(ids, cfg.axes_dim, cfg.theta)

        for blk in self.double_blocks:
            img, txt = blk(img, txt, vec, cos, sin)
        x_seq = torch.cat([txt, img], dim=1)
        for blk in self.single_blocks:
            x_seq = blk(x_seq, vec, cos, sin)
        img = x_seq[:, txt_len:]

        shift, scale = self.final_layer.adaLN_modulation(vec.float())[:, None, :].chunk(
            2, dim=-1
        )
        img = t_modulate(_ln(img, cfg.hidden_size), shift, scale)
        img = self.final_layer.linear(img)
        img = img.reshape(B, hp, wp, p, p, C).permute(0, 1, 3, 2, 4, 5)
        return img.reshape(B, Hh, Ww, C)


# ---------------------------------------------------------------------------------
# The golden comparison
# ---------------------------------------------------------------------------------


@pytest.fixture(scope="module")
def torch_flux():
    torch.manual_seed(0)
    return TFlux(CFG).eval()


def test_full_forward_golden_parity(torch_flux):
    sd = {k: v.detach() for k, v in torch_flux.state_dict().items()}
    params = convert_flux_checkpoint(sd, CFG)
    model = build_flux(CFG, params=params, sample_shape=(1, 8, 8, 4), txt_len=8)

    rng = np.random.default_rng(7)
    x = rng.normal(size=(2, 8, 8, 4)).astype(np.float32)
    t = np.array([0.9, 0.3], np.float32)
    ctx = rng.normal(size=(2, 8, CFG.context_in_dim)).astype(np.float32)
    y = rng.normal(size=(2, CFG.vec_in_dim)).astype(np.float32)
    g = np.array([3.5, 4.0], np.float32)

    with torch.no_grad():
        want = torch_flux(
            torch.from_numpy(x), torch.from_numpy(t), torch.from_numpy(ctx),
            torch.from_numpy(y), torch.from_numpy(g),
        ).numpy()
    got = np.asarray(
        model.apply(model.params, jnp.asarray(x), jnp.asarray(t), jnp.asarray(ctx),
                    y=jnp.asarray(y), guidance=jnp.asarray(g))
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_double_block_golden_parity(torch_flux):
    # Block-level isolation: feed identical hidden states straight into block 0 of
    # both implementations, so a failure localizes to the double block itself.
    sd = {k: v.detach() for k, v in torch_flux.state_dict().items()}
    params = convert_flux_checkpoint(sd, CFG)
    model = build_flux(CFG, params=params, sample_shape=(1, 8, 8, 4), txt_len=8)

    rng = np.random.default_rng(11)
    B, S_img, S_txt, h = 2, 16, 8, CFG.hidden_size
    img = rng.normal(size=(B, S_img, h)).astype(np.float32)
    txt = rng.normal(size=(B, S_txt, h)).astype(np.float32)
    vec = rng.normal(size=(B, h)).astype(np.float32)
    ids = rng.integers(0, 5, size=(B, S_txt + S_img, 3))

    t_cos, t_sin = t_rope_freqs(torch.from_numpy(ids), CFG.axes_dim, CFG.theta)
    with torch.no_grad():
        w_img, w_txt = torch_flux.double_blocks[0](
            torch.from_numpy(img), torch.from_numpy(txt), torch.from_numpy(vec),
            t_cos, t_sin,
        )

    from comfyui_parallelanything_tpu.models.flux import FluxModel
    from comfyui_parallelanything_tpu.ops.rope import axis_rope_freqs

    cos, sin = axis_rope_freqs(jnp.asarray(ids), CFG.axes_dim, CFG.theta)
    module = FluxModel(CFG)
    carry = {
        "img": jnp.asarray(img), "txt": jnp.asarray(txt), "vec": jnp.asarray(vec),
        "rope_cos": cos, "rope_sin": sin,
    }
    out = module.apply(
        {"params": model.params}, carry, 0, method=FluxModel.double_step
    )
    np.testing.assert_allclose(np.asarray(out["img"]), w_img.numpy(), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out["txt"]), w_txt.numpy(), rtol=2e-4, atol=2e-4)


def test_single_block_golden_parity(torch_flux):
    sd = {k: v.detach() for k, v in torch_flux.state_dict().items()}
    params = convert_flux_checkpoint(sd, CFG)
    model = build_flux(CFG, params=params, sample_shape=(1, 8, 8, 4), txt_len=8)

    rng = np.random.default_rng(13)
    B, S_txt, S_img, h = 2, 8, 16, CFG.hidden_size
    txt = rng.normal(size=(B, S_txt, h)).astype(np.float32)
    img = rng.normal(size=(B, S_img, h)).astype(np.float32)
    vec = rng.normal(size=(B, h)).astype(np.float32)
    ids = rng.integers(0, 5, size=(B, S_txt + S_img, 3))

    x_seq = np.concatenate([txt, img], axis=1)
    t_cos, t_sin = t_rope_freqs(torch.from_numpy(ids), CFG.axes_dim, CFG.theta)
    with torch.no_grad():
        want = torch_flux.single_blocks[1](
            torch.from_numpy(x_seq), torch.from_numpy(vec), t_cos, t_sin
        ).numpy()

    from comfyui_parallelanything_tpu.models.flux import FluxModel
    from comfyui_parallelanything_tpu.ops.rope import axis_rope_freqs

    cos, sin = axis_rope_freqs(jnp.asarray(ids), CFG.axes_dim, CFG.theta)
    module = FluxModel(CFG)
    carry = {
        "img": jnp.asarray(img), "txt": jnp.asarray(txt), "vec": jnp.asarray(vec),
        "rope_cos": cos, "rope_sin": sin,
    }
    out = module.apply(
        {"params": model.params}, carry, 1, method=FluxModel.single_step
    )
    got = np.concatenate([np.asarray(out["txt"]), np.asarray(out["img"])], axis=1)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
