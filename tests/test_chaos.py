"""Chaos smoke (scripts/chaos.py — the ci_tier1.sh gate): a seeded fault
plan (backend-http 5xx + slow-host) fired against a 2-backend fleet while
the primary router is killed mid-denoise (standby takeover off the durable
journal) and one backend is killed — gated on prompts_lost == 0, every
latent bitwise-equal to the fault-free baseline, bounded p95, and every
injected fault attributable; plus the stream-OOM phase on a real
weight-streamed model (the re-carve ladder absorbs it).

Marked slow-adjacent but kept in tier 1 deliberately: the fleet's one
non-negotiable — the front door never loses a prompt — must break the build
the moment it breaks."""

from __future__ import annotations

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


@pytest.fixture(autouse=True)
def _evidence_redirect(tmp_path, monkeypatch):
    """The one arming rule (utils/faults.py): chaos artifacts must never
    land in the repo's real evidence."""
    monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path / "evidence"))
    monkeypatch.delenv("PA_LEDGER_DIR", raising=False)
    from comfyui_parallelanything_tpu.utils import faults

    faults.reload()
    yield
    monkeypatch.delenv("PA_FAULT_PLAN", raising=False)
    faults.reload()


class TestChaosSmoke:
    def test_fleet_phase_router_and_backend_kill_zero_lost(self, tmp_path):
        from chaos import run_fleet_chaos

        verdict = run_fleet_chaos(
            n_backends=2, clients=3, requests=2, seed=7, work_s=0.4,
            lease_ttl_s=0.75, root=str(tmp_path / "chaos"),
        )
        assert verdict["ok"], verdict["failures"]
        assert verdict["prompts_lost"] == 0
        assert verdict["completed"] == verdict["total_prompts"]
        assert verdict["faults_fired"] >= 3  # 5xx + slow-host + journal
        # Round 15: the journal-corruption fault is part of the default
        # matrix — a garbled dispatch record mid-run, with the takeover
        # still losing zero prompts (asserted above via prompts_lost).
        assert verdict["faults_by_site"].get("journal-corrupt", 0) >= 1
        assert verdict["chaos_p95_s"] <= verdict["p95_bound_s"]

    def test_partition_phase_both_directions_zero_lost(self, tmp_path):
        """Round-20 satellite: a persistent network partition cuts one
        denoise host off mid-run in BOTH directions (router→backend
        dispatch/poll and backend→router heartbeat); its in-flight prompts
        fail over with zero lost and bitwise survivors, and both directions
        are attributable (fault fires + dropped heartbeats)."""
        from chaos import run_partition_chaos

        # Defaults (3 backends, 3 clients x 3 requests, 0.5 s work): enough
        # waves that the mid-run arm always catches the victim with work
        # in flight — smaller runs can land the partition between waves.
        verdict = run_partition_chaos(
            n_backends=3, clients=3, requests=3, seed=11, work_s=0.5,
            root=str(tmp_path / "chaos"),
        )
        assert verdict["ok"], verdict["failures"]
        assert verdict["prompts_lost"] == 0
        assert verdict["completed"] == verdict["total_prompts"]
        assert verdict["faults_fired"] >= 1          # router→backend cut
        assert verdict["heartbeats_dropped"] >= 1    # backend→router cut
        assert verdict["failovers"] >= 1
        assert verdict["chaos_p95_s"] <= verdict["p95_bound_s"]

    def test_stream_oom_phase_recarve_absorbs(self):
        from chaos import run_stream_oom_chaos

        verdict = run_stream_oom_chaos()
        assert verdict["ok"], verdict["failures"]
        assert verdict["stages_after"] > verdict["stages_before"]
        assert verdict["recarve_rungs"] >= 1

    def test_journal_corruption_modes(self, tmp_path, monkeypatch):
        """The fault's disk shapes (unit view): truncate tears the tail
        (that record lost; the NEXT append concatenates into one more
        unparseable line — the real crash+restart disk state); garble
        damages exactly one record, neighbors intact. Replay/fold skips
        the damage either way."""
        from comfyui_parallelanything_tpu.fleet import PromptJournal
        from comfyui_parallelanything_tpu.utils import faults

        # truncate: the torn dispatch eats itself AND the next line
        monkeypatch.setenv("PA_FAULT_PLAN", json.dumps({"seed": 0, "faults": [
            {"site": "journal-corrupt", "match": "dispatch", "nth": 1,
             "count": 1, "mode": "truncate"},
        ]}))
        faults.reload()
        j = PromptJournal(str(tmp_path / "torn.jsonl"))
        j.append("submit", "p1", graph={"1": {}}, key="k", number=1)
        j.append("dispatch", "p1", host="h0", backend_pid="b1", attempt=1)
        j.append("resolve", "p1", status="done", entry={"status": {}})
        j.close()
        assert faults.fired().get("journal-corrupt") == 1
        table = j.replay()
        # the resolve concatenated onto the torn dispatch: both lost —
        # p1 folds back to submit phase, which a takeover REPLAYS (the
        # zero-lost property: corruption degrades to replay, never loss)
        assert table["p1"]["phase"] == "submit"
        assert table["p1"]["graph"] == {"1": {}}

        # garble: one record wide, neighbors parse
        monkeypatch.setenv("PA_FAULT_PLAN", json.dumps({"seed": 0, "faults": [
            {"site": "journal-corrupt", "match": "dispatch", "nth": 1,
             "count": 1, "mode": "garble"},
        ]}))
        faults.reload()
        j2 = PromptJournal(str(tmp_path / "garbled.jsonl"))
        j2.append("submit", "p1", graph={"1": {}}, key="k", number=1)
        j2.append("dispatch", "p1", host="h0", backend_pid="b1", attempt=1)
        j2.append("submit", "p2", graph={"2": {}}, key="k2", number=2)
        j2.close()
        table = j2.replay()
        assert table["p1"]["phase"] == "submit"   # dispatch record garbled
        assert table["p2"]["phase"] == "submit"   # neighbor intact
        assert table["p2"]["graph"] == {"2": {}}

    def test_journal_corruption_mid_takeover_zero_lost(self, tmp_path,
                                                       monkeypatch):
        """The chaos-matrix satellite, isolated: a dispatch record is
        garbled in the primary's journal, the primary dies mid-denoise,
        and the standby's torn-tail fold still takes over with ZERO lost
        prompts — the corrupted prompt replays from its surviving submit
        record."""
        import threading

        from tests.test_fleet import (
            _Backend,
            _graph,
            _post,
            _wait,
            _wait_entry,
        )

        from comfyui_parallelanything_tpu.fleet import (
            FleetRegistry,
            PromptJournal,
            Scoreboard,
            make_router,
        )
        from comfyui_parallelanything_tpu.utils import faults

        monkeypatch.setenv("PA_FAULT_PLAN", json.dumps({"seed": 0, "faults": [
            {"site": "journal-corrupt", "match": "dispatch", "nth": 2,
             "count": 1, "mode": "garble"},
        ]}))
        faults.reload()
        backends = [_Backend(tmp_path, f"jc-host-{i}") for i in range(2)]
        jpath = str(tmp_path / "journal.jsonl")
        mk = dict(
            backends=[(b.host_id, b.base) for b in backends],
            saturation_depth=2, monitor_s=0.05,
        )
        srv1, primary = make_router(
            port=0, fleet_registry=FleetRegistry(ttl_s=3.0),
            scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0,
                                  fail_after=2, timeout_s=2.0),
            journal=PromptJournal(jpath), lease_ttl_s=0.5, **mk,
        )
        threading.Thread(target=srv1.serve_forever, daemon=True).start()
        base1 = f"http://127.0.0.1:{srv1.server_address[1]}"
        srv2, standby = make_router(
            port=0, fleet_registry=FleetRegistry(ttl_s=3.0),
            scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0,
                                  fail_after=2, timeout_s=2.0),
            journal=PromptJournal(jpath), standby=True, lease_ttl_s=0.5,
            **mk,
        )
        threading.Thread(target=srv2.serve_forever, daemon=True).start()
        base2 = f"http://127.0.0.1:{srv2.server_address[1]}"
        try:
            _wait(lambda: all(primary.scoreboard.healthy(b.host_id)
                              for b in backends),
                  what="backends healthy on the primary")
            # Two mid-denoise prompts; the SECOND's dispatch record is
            # garbled (nth=2) — after takeover it must replay from its
            # submit record.
            pids = [
                _post(base1, "/prompt",
                      {"prompt": _graph(80 + i, work_s=2.0)})["prompt_id"]
                for i in range(2)
            ]
            _wait(lambda: faults.fired().get("journal-corrupt", 0) >= 1,
                  what="journal-corrupt fault fired")
            _wait(lambda: sum(len(b.q.running) for b in backends) >= 1,
                  what="work running mid-denoise")
            srv1.shutdown()
            srv1.server_close()
            primary.shutdown()
            _wait(lambda: standby.active, timeout=15,
                  what="standby takeover over the corrupted journal")
            for pid in pids:
                entry = _wait_entry(base2, pid, timeout=60)
                assert entry["status"]["status_str"] == "success", entry
            assert standby.stats()["lost"] == 0
        finally:
            srv2.shutdown()
            srv2.server_close()
            standby.shutdown()
            for b in backends:
                b.stop()

    def test_seeded_plan_fires_identically(self):
        """Fault-plan determinism at the chaos-runner level: the default
        plan for one seed resolves to one firing schedule."""
        from chaos import default_plan

        from comfyui_parallelanything_tpu.utils.faults import (
            FaultRegistry,
            parse_plan,
        )
        import json as _json

        for seed in (7, 8):
            seed_a, specs_a = parse_plan(_json.dumps(default_plan(seed)))
            seed_b, specs_b = parse_plan(_json.dumps(default_plan(seed)))
            ra = FaultRegistry(seed=seed_a, specs=specs_a)
            rb = FaultRegistry(seed=seed_b, specs=specs_b)
            for _ in range(8):
                assert (ra.check("slow-host", key="p") is None) == (
                    rb.check("slow-host", key="p") is None
                )
                assert (
                    ra.check("backend-http", key="POST /prompt") is None
                ) == (rb.check("backend-http", key="POST /prompt") is None)
