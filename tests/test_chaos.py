"""Chaos smoke (scripts/chaos.py — the ci_tier1.sh gate): a seeded fault
plan (backend-http 5xx + slow-host) fired against a 2-backend fleet while
the primary router is killed mid-denoise (standby takeover off the durable
journal) and one backend is killed — gated on prompts_lost == 0, every
latent bitwise-equal to the fault-free baseline, bounded p95, and every
injected fault attributable; plus the stream-OOM phase on a real
weight-streamed model (the re-carve ladder absorbs it).

Marked slow-adjacent but kept in tier 1 deliberately: the fleet's one
non-negotiable — the front door never loses a prompt — must break the build
the moment it breaks."""

from __future__ import annotations

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


@pytest.fixture(autouse=True)
def _evidence_redirect(tmp_path, monkeypatch):
    """The one arming rule (utils/faults.py): chaos artifacts must never
    land in the repo's real evidence."""
    monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path / "evidence"))
    monkeypatch.delenv("PA_LEDGER_DIR", raising=False)
    from comfyui_parallelanything_tpu.utils import faults

    faults.reload()
    yield
    monkeypatch.delenv("PA_FAULT_PLAN", raising=False)
    faults.reload()


class TestChaosSmoke:
    def test_fleet_phase_router_and_backend_kill_zero_lost(self, tmp_path):
        from chaos import run_fleet_chaos

        verdict = run_fleet_chaos(
            n_backends=2, clients=3, requests=2, seed=7, work_s=0.4,
            lease_ttl_s=0.75, root=str(tmp_path / "chaos"),
        )
        assert verdict["ok"], verdict["failures"]
        assert verdict["prompts_lost"] == 0
        assert verdict["completed"] == verdict["total_prompts"]
        assert verdict["faults_fired"] >= 2  # 5xx + slow-host both fired
        assert verdict["chaos_p95_s"] <= verdict["p95_bound_s"]

    def test_stream_oom_phase_recarve_absorbs(self):
        from chaos import run_stream_oom_chaos

        verdict = run_stream_oom_chaos()
        assert verdict["ok"], verdict["failures"]
        assert verdict["stages_after"] > verdict["stages_before"]
        assert verdict["recarve_rungs"] >= 1

    def test_seeded_plan_fires_identically(self):
        """Fault-plan determinism at the chaos-runner level: the default
        plan for one seed resolves to one firing schedule."""
        from chaos import default_plan

        from comfyui_parallelanything_tpu.utils.faults import (
            FaultRegistry,
            parse_plan,
        )
        import json as _json

        for seed in (7, 8):
            seed_a, specs_a = parse_plan(_json.dumps(default_plan(seed)))
            seed_b, specs_b = parse_plan(_json.dumps(default_plan(seed)))
            ra = FaultRegistry(seed=seed_a, specs=specs_a)
            rb = FaultRegistry(seed=seed_b, specs=specs_b)
            for _ in range(8):
                assert (ra.check("slow-host", key="p") is None) == (
                    rb.check("slow-host", key="p") is None
                )
                assert (
                    ra.check("backend-http", key="POST /prompt") is None
                ) == (rb.check("backend-http", key="POST /prompt") is None)
