"""End-to-end span tracing + observability satellites (round 8):

- utils/tracing.py: disabled-path no-op contract (no spans allocated, the
  null singleton, empty buffers), Chrome trace-event export validity
  (required keys, per-tid nesting), prompt correlation (span kwarg,
  inheritance, progress-scope fallback);
- utils/metrics.py histogram kind: Prometheus ``_bucket``/``_sum``/``_count``
  exposition (golden-text parse, label escaping, bucket monotonicity) and
  quantile read-side; scripts/loadgen.py's scraped-quantile twin;
- utils/logging.py ContextFilter: prompt_id/span_id stamped into records;
- serving + streaming instrumentation: lane-wait/step/lane spans on the
  submitter's timeline, stream-stage spans with overlap efficiency in (0,1];
- server GET /trace; scripts/trace_summary.py pinned against
  utils/tracing.trace_aggregates on the same fixture.
"""

from __future__ import annotations

import json
import logging
import re
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.utils import tracing
from comfyui_parallelanything_tpu.utils.logging import ContextFilter, get_logger
from comfyui_parallelanything_tpu.utils.metrics import MetricsRegistry, registry
from comfyui_parallelanything_tpu.utils.progress import progress_scope

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _tracer_clean():
    """Tracing is process-global: every test starts and ends disabled with a
    fresh buffer, so span leakage cannot couple tests."""
    tracing.disable()
    tracing.tracer.clear()
    yield
    tracing.disable()
    tracing.tracer.clear()


def _x_events(export=None, **kw):
    export = tracing.export(**kw) if export is None else export
    return [e for e in export["traceEvents"] if e.get("ph") == "X"]


def _assert_nested_per_tid(events):
    """Chrome X events on one tid must properly nest: sweeping by start time,
    every span is either contained in or disjoint from the open span above it
    (1 µs float-rounding slack)."""
    by_tid: dict[int, list] = {}
    for e in events:
        by_tid.setdefault(e["tid"], []).append(e)
    for tid, evs in by_tid.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list = []
        for e in evs:
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"] - 1e-3:
                stack.pop()
            if stack:
                assert e["ts"] + e["dur"] <= (
                    stack[-1]["ts"] + stack[-1]["dur"] + 1.0
                ), f"tid {tid}: span {e} escapes parent {stack[-1]}"
            stack.append(e)


class TestTracerCore:
    def test_disabled_is_noop(self):
        """The tier-1 disabled-overhead contract: span() returns the shared
        null singleton (no Span allocated), record() writes nothing, no
        per-thread buffer is ever registered — the hot path is one flag
        check."""
        assert not tracing.on()
        s = tracing.span("anything", cat="x", foo=1)
        assert s is tracing._NULL
        assert tracing.span("other") is s  # the SAME object: nothing allocated
        with s as inner:
            assert inner is s
            inner.set(bar=2)  # attribute attach is a no-op too
        tracing.record("x", 0.0, 1.0, foo="bar")
        assert tracing.tracer._buffers == {}  # no buffer was ever touched
        assert _x_events() == []
        assert tracing.current_span_id() is None

    def test_disabled_hot_paths_allocate_no_spans(self):
        """An eager sampler run with tracing off must leave the tracer
        untouched — the instrumented hot paths (sampler-run wrapper, step
        callbacks) are all behind the single flag check."""
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler

        def model(x, t, context=None, **kw):
            return x * 0.9

        noise = jnp.ones((1, 4, 4, 4))
        ctx = jnp.ones((1, 3, 8))
        out = run_sampler(model, noise, ctx, sampler="euler", steps=2)
        assert out.shape == noise.shape
        assert tracing.tracer._buffers == {}

    def test_export_shape_and_nesting(self):
        tracing.enable()
        with tracing.span("prompt", cat="server", prompt_id="p1"):
            with tracing.span("workflow-node", cat="graph", node="3"):
                with tracing.span("sampler-run", cat="sampling"):
                    pass
            with tracing.span("workflow-node", cat="graph", node="4"):
                pass
        trace = tracing.export()
        xs = _x_events(trace)
        assert len(xs) == 4
        for e in xs:
            for key in ("ph", "ts", "dur", "pid", "tid", "name"):
                assert key in e, (key, e)
            assert e["ph"] == "X" and e["dur"] >= 0
            # prompt correlation inherited down the whole subtree
            assert e["args"]["prompt_id"] == "p1"
        _assert_nested_per_tid(xs)
        # thread metadata present (Perfetto track naming)
        metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        assert any(m["name"] == "thread_name" for m in metas)
        # the whole export is valid JSON for the Chrome trace loader
        json.loads(json.dumps(trace))

    def test_prompt_filter_and_cross_thread_record(self):
        tracing.enable()
        with tracing.span("prompt", prompt_id="keep"):
            time.sleep(0.001)
        with tracing.span("prompt", prompt_id="drop"):
            pass
        # dispatcher-style record onto another thread's tid
        done = threading.Event()
        main_tid = threading.get_ident()

        def dispatcher():
            t0 = tracing.now_us()
            tracing.record("step", t0, 5.0, cat="serving", tid=main_tid,
                           prompt_id="keep", lane=0)
            done.set()

        threading.Thread(target=dispatcher).start()
        assert done.wait(5)
        kept = _x_events(prompt_id="keep")
        assert {e["name"] for e in kept} == {"prompt", "step"}
        step = next(e for e in kept if e["name"] == "step")
        assert step["tid"] == main_tid  # landed on the prompt's timeline
        assert all(e["args"]["prompt_id"] == "keep" for e in kept)
        assert not any(
            e["args"].get("prompt_id") == "drop"
            for e in _x_events(prompt_id="keep")
        )

    def test_progress_scope_fallback(self):
        """A thread with no span context inherits its prompt from the
        per-thread progress scope — the server's correlation path."""
        tracing.enable()
        with progress_scope(prompt_id="scope-p"):
            assert tracing.current_prompt_id() == "scope-p"
            with tracing.span("workflow-node", cat="graph"):
                pass
            # nested scope without prompt_id stays on the same prompt
            with progress_scope(hook=lambda v, m: None):
                assert tracing.current_prompt_id() == "scope-p"
        [e] = _x_events()
        assert e["args"]["prompt_id"] == "scope-p"

    def test_ring_buffer_bounded(self):
        tracing.enable(capacity=16)
        for i in range(64):
            tracing.record("tick", float(i), 1.0)
        assert len(_x_events()) == 16  # old spans fell off, no growth


class TestHistogram:
    def test_exposition_golden_parse(self):
        """GET /metrics-shaped output must parse: TYPE lines, escaped labels,
        monotone cumulative buckets ending at +Inf == _count."""
        r = MetricsRegistry()
        labels = {"bucket": 'mo"del\nx', "lane": "0"}
        for v in (0.004, 0.004, 0.3, 7.0, 500.0):
            r.histogram("pa_t_step_seconds", v, labels=labels, help="t")
        r.counter("pa_t_total", 2, labels={"bucket": "b"})
        r.gauge("pa_t_gauge", 1.5)
        r.observe("pa_t_summary", 0.5)
        text = r.render()
        assert "# TYPE pa_t_step_seconds histogram" in text
        line_re = re.compile(
            r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
            r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{([a-zA-Z_][a-zA-Z0-9_]*="
            r'"(\\.|[^"\\])*")(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})? '
            r"-?[0-9.eE+-]+(e[+-]?[0-9]+)?)$"
        )
        for line in text.strip().splitlines():
            assert line_re.match(line), f"unparseable exposition line: {line!r}"
        # bucket monotonicity + +Inf == _count
        buckets = re.findall(
            r'^pa_t_step_seconds_bucket\{[^}]*le="([^"]+)"[^}]*\} (\S+)$',
            text, re.M,
        )
        counts = [float(c) for _, c in buckets]
        assert counts == sorted(counts)
        assert buckets[-1][0] == "+Inf"
        count = float(re.search(
            r"^pa_t_step_seconds_count\{[^}]*\} (\S+)$", text, re.M
        ).group(1))
        assert counts[-1] == count == 5.0
        # raw newline/quote must not survive into the text unescaped
        assert 'mo\\"del\\nx' in text

    def test_explicit_bounds_first_touch_wins(self):
        """Round 15: a histogram may declare its bucket ladder at first
        touch (the SLO plane aligns edges to declared thresholds so a
        verdict is a bucket read); later bounds are ignored (one ladder per
        metric — exposition stays mergeable) and the default ladder is
        untouched for everyone else."""
        r = MetricsRegistry()
        r.histogram("pa_b_seconds", 0.2, bounds=(0.1, 0.25, 30.0, 60.0))
        r.histogram("pa_b_seconds", 31.0, bounds=(1.0, 2.0))  # ignored
        r.histogram("pa_b_seconds", 0.05, labels={"stage": "x"})
        text = r.render()
        # the declared ladder renders (threshold 30 an exact edge), for
        # EVERY label set of the metric
        for le in ("0.1", "0.25", "30", "60", "+Inf"):
            le_re = re.escape(le)
            assert re.search(
                rf'^pa_b_seconds_bucket\{{le="{le_re}"\}} ', text, re.M), le
            assert re.search(
                rf'^pa_b_seconds_bucket\{{stage="x",le="{le_re}"\}} ',
                text, re.M), le
        assert 'le="1"' not in text and 'le="2.5"' not in text
        # cumulative reads: 0.05 and 0.2 under 0.25; 31 lands in the 60
        # bucket (not +Inf)
        m = re.search(r'^pa_b_seconds_bucket\{le="0.25"\} (\S+)$', text, re.M)
        assert float(m.group(1)) == 1.0  # unlabeled set: only the 0.2
        # quantile rides the declared ladder
        assert 0.1 < r.quantile("pa_b_seconds", 40) <= 0.25
        assert r.quantile("pa_b_seconds", 99) <= 60.0
        # an untouched metric keeps the default ladder
        r.histogram("pa_default_seconds", 0.004)
        assert re.search(r'^pa_default_seconds_bucket\{le="0.001"\} ',
                         r.render(), re.M)

    def test_get_and_quantile(self):
        r = MetricsRegistry()
        for _ in range(99):
            r.histogram("h", 0.004)
        r.histogram("h", 40.0)
        s, c = r.get("h")
        assert c == 100 and s == pytest.approx(99 * 0.004 + 40.0)
        p50 = r.quantile("h", 50)
        assert 0.0025 < p50 <= 0.005  # inside the 0.004 bucket
        p95 = r.quantile("h", 95)
        assert p95 <= 0.005
        assert r.quantile("h", 99.9) > 25.0
        assert r.quantile("missing", 50) is None

    def test_loadgen_scraped_quantile_matches_registry(self):
        sys.path.insert(0, str(REPO / "scripts"))
        try:
            from loadgen import _histogram_quantile
        finally:
            sys.path.pop(0)
        r = MetricsRegistry()
        rng = np.random.default_rng(0)
        for v in rng.uniform(0.001, 2.0, size=200):
            r.histogram("pa_s_seconds", float(v), labels={"bucket": "b1"})
        for v in rng.uniform(0.001, 2.0, size=100):
            r.histogram("pa_s_seconds", float(v), labels={"bucket": "b2"})
        text = r.render()
        for q in (50, 95):
            scraped = _histogram_quantile(text, "pa_s_seconds", q)
            assert scraped == pytest.approx(r.quantile("pa_s_seconds", q))


class TestLoggingCorrelation:
    def _capture(self):
        logger = get_logger()
        records: list[str] = []

        class _Sink(logging.Handler):
            def emit(self, rec):
                records.append(self.format(rec))

        sink = _Sink()
        sink.setFormatter(logging.Formatter(
            "prompt=%(prompt_id)s span=%(span_id)s %(message)s"
        ))
        sink.addFilter(ContextFilter())
        logger.addHandler(sink)
        return logger, sink, records

    def test_records_stamped_from_span_context(self):
        tracing.enable()
        logger, sink, records = self._capture()
        try:
            logger.info("outside")
            with tracing.span("prompt", prompt_id="pX") as s:
                logger.info("inside")
                assert records[-1] == f"prompt=pX span={s.span_id} inside"
        finally:
            logger.removeHandler(sink)
        assert records[0] == "prompt=- span=- outside"

    def test_records_stamped_from_progress_scope(self):
        logger, sink, records = self._capture()
        try:
            with progress_scope(prompt_id="pScope"):
                logger.info("scoped")
        finally:
            logger.removeHandler(sink)
        assert records[-1] == "prompt=pScope span=- scoped"

    def test_default_handler_format_carries_correlation(self):
        logger = get_logger()
        fmt = logger.handlers[0].formatter._fmt
        assert "%(prompt_id)s" in fmt and "%(span_id)s" in fmt


def _tiny_model(x, t, context=None, **kw):
    c = jnp.mean(context, axis=tuple(range(1, context.ndim)))
    c = c.reshape((-1,) + (1,) * (x.ndim - 1))
    tt = t.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.tanh(x * 0.9 + c * 0.1) * (0.5 + 0.1 * tt / 1000.0)


class TestServingSpans:
    def test_lane_wait_step_lane_on_submitter_timeline(self):
        from comfyui_parallelanything_tpu.sampling.runner import run_sampler
        from comfyui_parallelanything_tpu.serving import (
            ContinuousBatchingScheduler,
        )

        tracing.enable()
        sched = ContinuousBatchingScheduler(max_width=4, auto=False).install()
        try:
            tids = {}

            def worker(seed, steps):
                with tracing.span("prompt", prompt_id=f"p{seed}"):
                    tids[seed] = threading.get_ident()
                    r = np.random.default_rng(seed)
                    noise = jnp.asarray(
                        r.normal(size=(1, 8, 8, 4)).astype(np.float32))
                    ctx = jnp.asarray(
                        r.normal(size=(1, 6, 16)).astype(np.float32))
                    run_sampler(_tiny_model, noise, ctx, sampler="euler",
                                steps=steps)

            threads = [threading.Thread(target=worker, args=a, daemon=True)
                       for a in [(1, 2), (2, 3)]]
            for t in threads:
                t.start()
            t0 = time.time()
            while time.time() - t0 < 20:
                with sched._lock:
                    n = sum(len(b.queue) + len(b.active_lanes())
                            for b in sched.buckets.values())
                if n >= 2:
                    break
                time.sleep(0.005)
            sched.drain()
            for t in threads:
                t.join(20)
        finally:
            sched.uninstall()
            sched.shutdown()
        xs = _x_events()
        for seed, steps in [(1, 2), (2, 3)]:
            mine = [e for e in xs if e["args"].get("prompt_id") == f"p{seed}"]
            names = [e["name"] for e in mine]
            assert names.count("step") == steps, names
            assert "lane-wait" in names and "lane" in names
            # every span of this prompt sits on the submitter's own timeline,
            # even though the dispatcher thread recorded the serving ones
            assert {e["tid"] for e in mine} == {tids[seed]}
            _assert_nested_per_tid(mine)
        # dispatcher-side occupancy span carries the masked-lane count
        disp = [e for e in xs if e["name"] == "serving-dispatch"]
        assert disp and all(
            e["args"]["occupancy"] + e["args"]["masked_lanes"]
            == e["args"]["width"] for e in disp
        )
        # trace/metrics consistency: the histograms populated too
        text = registry.render()
        assert re.search(r"^pa_serving_step_seconds_bucket\{", text, re.M)
        assert re.search(r"^pa_serving_lane_wait_seconds_bucket\{", text, re.M)


class TestStreamingSpans:
    @pytest.fixture(scope="class")
    def flux_model(self):
        from comfyui_parallelanything_tpu.models.flux import (
            FluxConfig,
            build_flux,
        )

        cfg = FluxConfig(
            in_channels=16, hidden_size=64, num_heads=4, depth=2,
            depth_single_blocks=4, context_in_dim=32, vec_in_dim=16,
            axes_dim=(4, 6, 6), guidance_embed=False, dtype=jnp.float32,
        )
        return build_flux(
            cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=16
        )

    @pytest.mark.parametrize("overlap", [True, False])
    def test_stream_stage_spans_and_overlap_efficiency(self, flux_model,
                                                       overlap):
        from comfyui_parallelanything_tpu.models.loader import params_nbytes
        from comfyui_parallelanything_tpu.parallel.streaming import (
            build_streaming_runner,
        )

        tracing.enable()
        runner = build_streaming_runner(
            flux_model.pipeline_spec, flux_model.params,
            jax.devices("cpu")[0],
            hbm_budget_bytes=params_nbytes(flux_model.params) // 3,
            overlap=overlap,
        )
        x = jnp.zeros((1, 8, 8, 4))
        t = jnp.ones((1,))
        ctx = jnp.zeros((1, 16, 32))
        y = jnp.zeros((1, 16))
        out = runner(x, t, ctx, y=y)
        jax.block_until_ready(out)
        xs = _x_events()
        names = {e["name"] for e in xs}
        assert {"stream-run", "stream-stage-prefetch",
                "stream-stage-compute"} <= names
        n_stages = runner.n_stages
        computes = [e for e in xs if e["name"] == "stream-stage-compute"]
        prefetches = [e for e in xs if e["name"] == "stream-stage-prefetch"]
        assert len(computes) == n_stages  # every stage's compute is spanned
        assert len(prefetches) == n_stages
        assert {e["args"]["stage"] for e in computes} == set(range(n_stages))
        assert all(e["args"]["nbytes"] > 0 for e in prefetches)
        # exposed transfer is booked separately from compute (the semantic
        # stream_overlap_efficiency depends on): one pre-dispatch wait per
        # stage, disjoint from every compute span
        waits = [e for e in xs if e["name"] == "stream-prefetch-wait"]
        assert {e["args"]["stage"] for e in waits} == set(range(n_stages))
        for w in waits:
            for c in computes:
                assert (w["ts"] + w["dur"] <= c["ts"] + 1.0
                        or w["ts"] >= c["ts"] + c["dur"] - 1.0), (w, c)
        eff = tracing.stream_overlap_efficiency(xs)
        assert eff is not None and 0.0 < eff <= 1.0
        _assert_nested_per_tid(xs)
        # the /metrics twin landed
        got = registry.get(
            "pa_stream_overlap_efficiency",
            {"device": str(jax.devices("cpu")[0])},
        )
        assert got is not None and 0.0 < got <= 1.0

    def test_no_spans_when_disabled(self, flux_model):
        from comfyui_parallelanything_tpu.models.loader import params_nbytes
        from comfyui_parallelanything_tpu.parallel.streaming import (
            build_streaming_runner,
        )

        runner = build_streaming_runner(
            flux_model.pipeline_spec, flux_model.params,
            jax.devices("cpu")[0],
            hbm_budget_bytes=params_nbytes(flux_model.params) // 3,
        )
        out = runner(jnp.zeros((1, 8, 8, 4)), jnp.ones((1,)),
                     jnp.zeros((1, 16, 32)), y=jnp.zeros((1, 16)))
        jax.block_until_ready(out)
        assert tracing.tracer._buffers == {}


class TestTraceSummaryScript:
    def _fixture_trace(self, tmp_path) -> Path:
        """A captured-fixture trace exercising every aggregate: one streamed
        run, serving lane-waits, and sequential steps with host gaps."""
        tracing.enable()
        t0 = tracing.now_us()
        tracing.record("stream-run", t0, 1000.0, cat="stream")
        tracing.record("stream-stage-prefetch", t0, 60.0, cat="stream",
                       stage=0, nbytes=100)
        tracing.record("stream-stage-compute", t0 + 100, 400.0, cat="stream",
                       stage=0, nbytes=100)
        tracing.record("stream-stage-compute", t0 + 550, 300.0, cat="stream",
                       stage=1, nbytes=100)
        tracing.record("lane-wait", t0, 2_000_000.0, cat="serving")
        tracing.record("lane-wait", t0, 1_000_000.0, cat="serving")
        with tracing.span("prompt", prompt_id="pf"):
            tracing.record("step", t0 + 2000, 100.0, cat="sampling", step=1)
            tracing.record("step", t0 + 2400, 100.0, cat="sampling", step=2)
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(tracing.export()))
        return path

    def test_summary_matches_tracing_aggregates(self, tmp_path):
        path = self._fixture_trace(tmp_path)
        expect = tracing.trace_aggregates(tracing.export())
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "trace_summary.py"),
             str(path), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        summary = json.loads(proc.stdout)
        # the stdlib re-implementation is pinned against the in-package math
        for key in ("stream_overlap_efficiency", "lane_wait_p95",
                    "host_gap_ms"):
            assert summary[key] == pytest.approx(expect[key]), key
        assert summary["stream_overlap_efficiency"] == pytest.approx(0.7)
        assert summary["lane_wait_p95"] == pytest.approx(2.0)
        assert summary["host_gap_ms"] == pytest.approx(0.3)
        assert summary["layers"]["stream"]["spans"] == 4
        assert summary["spans"] == len(_x_events())

    def test_human_output_and_prompt_filter(self, tmp_path):
        path = self._fixture_trace(tmp_path)
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "trace_summary.py"),
             str(path)],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert "stream_overlap_efficiency:" in proc.stdout
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "trace_summary.py"),
             str(path), "--json", "--prompt-id", "pf"],
            capture_output=True, text=True, timeout=120,
        )
        summary = json.loads(proc.stdout)
        assert summary["spans"] == 3  # prompt span + its 2 steps
        assert summary["stream_overlap_efficiency"] is None


class _EchoNode:
    """Minimal declarative node for server round-trips without any model."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"x": ("INT", {"default": 0})}}

    RETURN_TYPES = ("INT",)
    FUNCTION = "run"

    def run(self, x):
        return (x + 1,)


class TestServerTraceEndpoint:
    @pytest.fixture
    def server(self, tmp_path):
        from comfyui_parallelanything_tpu.server import make_server

        srv, q = make_server(
            port=0, output_dir=str(tmp_path / "out"),
            class_mappings={"Echo": _EchoNode}, trace=True,
        )
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        yield base, q
        srv.shutdown()
        q.shutdown()

    def test_trace_endpoint_serves_prompt_timeline(self, server):
        import urllib.request

        base, q = server

        def get(path):
            with urllib.request.urlopen(base + path, timeout=30) as r:
                return json.loads(r.read())

        body = json.dumps({"prompt": {
            "1": {"class_type": "Echo", "inputs": {"x": 1}},
            "2": {"class_type": "Echo", "inputs": {"x": ["1", 0]}},
        }}).encode()
        req = urllib.request.Request(
            base + "/prompt", data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            pid = json.loads(r.read())["prompt_id"]
        t0 = time.time()
        while time.time() - t0 < 60:
            if pid in get(f"/history/{pid}"):
                break
            time.sleep(0.05)
        trace = get(f"/trace?prompt_id={pid}")
        assert trace["enabled"] is True
        xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        names = [e["name"] for e in xs]
        assert names.count("prompt") == 1
        assert names.count("workflow-node") == 2  # both Echo nodes spanned
        prompt = next(e for e in xs if e["name"] == "prompt")
        for e in xs:
            assert e["args"]["prompt_id"] == pid
            assert e["tid"] == prompt["tid"]
        _assert_nested_per_tid(xs)
        # unfiltered export includes it too; bogus filter excludes everything
        assert any(
            e.get("args", {}).get("prompt_id") == pid
            for e in get("/trace")["traceEvents"] if e.get("ph") == "X"
        )
        assert [e for e in get("/trace?prompt_id=nope")["traceEvents"]
                if e.get("ph") == "X"] == []
