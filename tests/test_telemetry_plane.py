"""Continuous telemetry plane (round 22): the metric-history ring
(utils/timeseries.py), the online anomaly sentinel (utils/anomaly.py),
the server/router history surfaces, and the ops console — byte bounds,
reset-aware readers, deterministic detectors, dead-host staleness, and
the PA_HISTORY_BYTES=0 / PA_ANOMALY=0 null paths."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from comfyui_parallelanything_tpu.fleet import (
    FleetRegistry,
    Scoreboard,
    make_router,
)
from comfyui_parallelanything_tpu.server import make_server
from comfyui_parallelanything_tpu.utils import anomaly, timeseries
from comfyui_parallelanything_tpu.utils.metrics import registry

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _get(base, path, timeout=15):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _post(base, path, payload=None, timeout=15):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch, tmp_path):
    """Every test starts with a fresh ring/sentinel and manual cadence
    (background samplers pinned to an hour so ticks are explicit); any
    ledger/postmortem a firing emits lands in the test's tmp dir."""
    monkeypatch.setenv("PA_HISTORY_INTERVAL_S", "3600")
    monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path / "evidence"))
    monkeypatch.delenv("PA_LEDGER_DIR", raising=False)
    timeseries.ring.reset()
    anomaly.sentinel.reset(seed=0)
    yield
    timeseries.ring.reset()
    anomaly.sentinel.reset(seed=0)


class TestHistoryRing:
    def test_byte_bound_holds_under_churn(self):
        r = timeseries.HistoryRing(budget=8 * 1024)
        for i in range(400):
            r.record({"pa_churn_total": {
                "type": "counter", "bounds": None,
                "values": {f'k="{j}"': float(i + j) for j in range(8)},
            }}, ts=1000.0 + i)
        st = r.stats()
        assert st["bytes"] <= 8 * 1024
        assert st["downsampled"] > 0
        # The window SPAN survives downsampling: first/last kept.
        pts = r._families["pa_churn_total"]["points"]
        assert pts[0][0] == pytest.approx(1000.0)
        assert pts[-1][0] == pytest.approx(1399.0)

    def test_timestamps_strictly_monotone(self):
        r = timeseries.HistoryRing(budget=1 << 20)
        # A stepped wall clock (same ts, then BACKWARD) never produces an
        # out-of-order window.
        for ts in (100.0, 100.0, 50.0, 200.0):
            r.record({"pa_x_total": {"type": "counter", "bounds": None,
                                     "values": {"": 1.0}}}, ts=ts)
        stamps = [ts for ts, _ in r._families["pa_x_total"]["points"]]
        assert stamps == sorted(stamps)
        assert len(set(stamps)) == len(stamps)

    def test_counter_reset_aware_delta_and_rate(self):
        r = timeseries.HistoryRing(budget=1 << 20)
        # 10 → 14 → restart at 2 → 5: growth is 4 + 2 + 3 = 9, never
        # negative, never the raw 5 - 10.
        for i, v in enumerate((10.0, 14.0, 2.0, 5.0)):
            r.record({"pa_r_total": {"type": "counter", "bounds": None,
                                     "values": {"": v}}}, ts=100.0 + i)
        assert r.delta("pa_r_total") == pytest.approx(9.0)
        assert r.rate("pa_r_total") == pytest.approx(9.0 / 3.0)

    def test_delta_credits_family_born_mid_window(self):
        r = timeseries.HistoryRing(budget=1 << 20)
        r.record({"pa_old_total": {"type": "counter", "bounds": None,
                                   "values": {"": 7.0}}}, ts=100.0)
        r.record({"pa_old_total": {"type": "counter", "bounds": None,
                                   "values": {"": 7.0}},
                  "pa_born_total": {"type": "counter", "bounds": None,
                                    "values": {'site="x"': 3.0}}}, ts=101.0)
        # Born mid-window → counted from 0. Present at ring start → its
        # pre-existing value is NOT growth.
        assert r.delta("pa_born_total") == pytest.approx(3.0)
        assert r.delta("pa_old_total") == pytest.approx(0.0)

    def test_windowed_histogram_quantile(self):
        r = timeseries.HistoryRing(budget=1 << 20)
        for i in range(6):
            registry.histogram("pa_tq_seconds", 0.01 if i < 5 else 5.0,
                               labels={"k": "v"})
            r.snapshot(ts=1000.0 + i)
        q = r.quantile_at("pa_tq_seconds", 95, window_s=600)
        assert q is not None and q > 1.0
        # A window covering only the quiet prefix reads quiet.
        assert r.window(window_s=600)["families"]["pa_tq_seconds"]["type"] \
            == "histogram"

    def test_disabled_budget_is_noop(self, monkeypatch):
        monkeypatch.setenv("PA_HISTORY_BYTES", "0")
        assert not timeseries.enabled()
        r = timeseries.HistoryRing()  # budget read from env
        assert r.snapshot() == 0
        r.mark_phase("p")
        assert r.stats()["points"] == 0 and r._phases == []
        assert r.window()["enabled"] is False

    def test_window_families_filter_and_phases(self):
        r = timeseries.HistoryRing(budget=1 << 20)
        r.mark_phase("rung-1", "begin", ts=999.0)
        r.record({"pa_a_total": {"type": "counter", "bounds": None,
                                 "values": {"": 1.0}},
                  "pa_b_total": {"type": "counter", "bounds": None,
                                 "values": {"": 1.0}}}, ts=1000.0)
        doc = r.window(families="pa_a")
        assert list(doc["families"]) == ["pa_a_total"]
        assert doc["phases"][0]["label"] == "rung-1"
        assert doc["stats"]["points"] == 2
        assert r.phase_at() == "rung-1"
        r.mark_phase("rung-1", "end", ts=1001.0)
        r.record({"pa_a_total": {"type": "counter", "bounds": None,
                                 "values": {"": 2.0}}}, ts=1002.0)
        assert r.phase_at() is None


class TestSentinel:
    def _feed(self, seed):
        """One deterministic series: 8 quiet disk-append ticks, then a
        stall + a fired fault site. Returns the firing sequence."""
        registry.reset()
        ring = timeseries.HistoryRing(budget=1 << 20)
        s = anomaly.AnomalySentinel(seed=seed)
        sigs = []
        for i in range(8):
            registry.histogram("pa_disk_append_seconds", 0.001,
                               labels={"target": "journal"})
            ring.snapshot(ts=1000.0 + i)
            sigs += [e["signal"] for e in s.observe(ring, ts=1000.0 + i)]
        registry.counter("pa_fault_injected_total",
                         labels={"site": "slow-disk"})
        registry.histogram("pa_disk_append_seconds", 1.5,
                           labels={"target": "journal"})
        ring.snapshot(ts=1010.0)
        events = s.observe(ring, ts=1010.0)
        sigs += [e["signal"] for e in events]
        return sigs, events

    def test_detector_fires_deterministically_and_attributes(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PA_EVIDENCE_DIR", str(tmp_path))
        sigs1, events = self._feed(seed=7)
        sigs2, _ = self._feed(seed=7)
        assert sigs1 == sigs2 == ["disk_append_p95"]
        ev = events[0]
        assert ev["attributed"] is True
        assert ev["attributed_to"]["faults"] == ["slow-disk"]
        assert ev["observed"] > ev["baseline"]
        # Auto-forensics: the bundle carries the history window.
        pm = ev["postmortem"]
        err = json.load(open(os.path.join(pm, "error.json")))
        hist = err["extra"]["history"]
        assert hist["schema"] == timeseries.HISTORY_SCHEMA
        assert "pa_disk_append_seconds" in hist["families"]
        # The firing also left a kind="anomaly" ledger record the
        # attribution gate (scripts/anomaly_report.py) reads.
        ledger = os.path.join(str(tmp_path), "ledger", "perf_ledger.jsonl")
        recs = [json.loads(line) for line in open(ledger)]
        anoms = [r for r in recs if r.get("kind") == "anomaly"]
        assert anoms and anoms[-1]["signal"] == "disk_append_p95"
        assert anoms[-1]["attributed"] is True
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "anomaly_report.py"),
             "--check", "--ledger", ledger],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr

    def test_unattributed_firing_fails_the_gate(self, tmp_path):
        rec = {"schema": "pa-perf-ledger/v1", "kind": "anomaly",
               "signal": "burn_rate", "observed": 9.0, "baseline": 0.1,
               "attributed": False,
               "attributed_to": {"faults": [], "phase": None}}
        ledger = tmp_path / "perf_ledger.jsonl"
        ledger.write_text(json.dumps(rec) + "\n")
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "anomaly_report.py"),
             "--check", "--ledger", str(ledger)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 1, out.stdout + out.stderr
        # Empty ledger is SKIP, never a failure.
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "anomaly_report.py"),
             "--check", "--ledger", str(empty)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0 and "SKIP" in out.stdout

    def test_trend_detector_queue_growth(self):
        ring = timeseries.HistoryRing(budget=1 << 20)
        s = anomaly.AnomalySentinel(seed=1)
        fired = []
        for i, depth in enumerate((0, 1, 2, 0, 2, 5, 9, 14)):
            ring.record({"pa_server_queue_pending": {
                "type": "gauge", "bounds": None,
                "values": {"": float(depth)}}}, ts=1000.0 + i)
            fired += s.observe(ring, ts=1000.0 + i)
        assert [e["signal"] for e in fired] == ["queue_depth"]
        # The dip at i=3 means the monotone run starts at 0 (i=3): the
        # detector fired only once the rise cleared min_rise over k
        # all-positive deltas.
        assert fired[0]["observed"] == 14.0

    def test_pa_anomaly_0_is_noop(self, monkeypatch):
        monkeypatch.setenv("PA_ANOMALY", "0")
        assert not anomaly.enabled()
        registry.reset()
        ring = timeseries.HistoryRing(budget=1 << 20)
        assert anomaly.observe(ring) == []
        anomaly.sentinel.publish_gauges()
        assert registry.get("pa_anomaly_active",
                            {"signal": "burn_rate", "host": ""}) is None
        assert anomaly.sentinel.snapshot()["enabled"] is False

    def test_baseline_frozen_while_firing(self):
        d = anomaly.BandDetector(z_max=4.0, warmup=2, min_sigma=0.01)
        for _ in range(5):
            d.update(1.0)
        base = d.baseline()
        assert d.update(100.0) is True
        assert d.baseline() == base  # anomaly can't teach the detector
        assert d.update(1.0) is True  # still firing (clear_k=2)
        assert d.update(1.0) is False


class _Work:
    CATEGORY = "test"
    RETURN_TYPES = ("INT",)
    FUNCTION = "run"

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"seed": ("INT", {"default": 0})}}

    def run(self, seed):
        return (int(seed),)


class TestHistoryHTTP:
    @pytest.fixture
    def fleet(self, tmp_path):
        backends = []
        for i in range(2):
            srv, q = make_server(
                port=0, output_dir=str(tmp_path / f"h{i}"),
                class_mappings={"Work": _Work}, host_id=f"host-{i}",
            )
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            backends.append(
                (f"host-{i}", f"http://127.0.0.1:{srv.server_address[1]}",
                 srv, q))
        srv, router = make_router(
            port=0, backends=[(t, b) for t, b, _, _ in backends],
            fleet_registry=FleetRegistry(ttl_s=3.0),
            scoreboard=Scoreboard(poll_s=0.1, stale_after_s=5.0,
                                  fail_after=2, timeout_s=2.0),
            saturation_depth=1, monitor_s=0.05, max_attempts=4,
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        t0 = time.monotonic()
        while not all(router.scoreboard.healthy(t) for t, *_ in backends):
            assert time.monotonic() - t0 < 30, "backends never healthy"
            time.sleep(0.02)
        yield base, router, backends
        srv.shutdown()
        srv.server_close()
        router.shutdown()
        for _, _, s, q in backends:
            try:
                s.shutdown()
                s.server_close()
            except OSError:
                pass
            q.shutdown()

    def test_server_history_route_and_phase_post(self, fleet):
        base, router, backends = fleet
        _, bbase, _, _ = backends[0]
        _post(bbase, "/history/phase", {"label": "warm", "state": "begin"})
        registry.gauge("pa_server_queue_pending", 2.0)
        timeseries.ring.snapshot()
        doc = _get(bbase, "/metrics/history?window=600")
        assert doc["schema"] == timeseries.HISTORY_SCHEMA
        assert doc["host"] == "host-0"
        assert "pa_server_queue_pending" in doc["families"]
        assert doc["phases"][0]["label"] == "warm"
        # family filter narrows the families section
        doc = _get(bbase, "/metrics/history?family=pa_server")
        assert all(n.startswith("pa_server") for n in doc["families"])
        with pytest.raises(urllib.error.HTTPError):
            _get(bbase, "/metrics/history?window=nope")

    def test_health_carries_anomaly_section(self, fleet):
        _, _, backends = fleet
        doc = _get(backends[0][1], "/health")
        assert doc["anomaly"]["schema"] == anomaly.ANOMALY_SCHEMA
        assert "disk_append_p95" in doc["anomaly"]["watchlist"]

    def test_fleet_history_merges_and_marks_dead_host_stale(self, fleet):
        base, router, backends = fleet
        timeseries.ring.snapshot()
        doc = _get(base, "/fleet/history?window=600")
        assert doc["schema"] == "pa-fleet-history/v1"
        assert set(doc["hosts"]) == {"host-0", "host-1"}
        for h in doc["hosts"].values():
            assert h["stale"] is False
            assert h["window"]["schema"] == timeseries.HISTORY_SCHEMA
        # Router-side phase fan-out stamps every live host.
        got = _post(base, "/history/phase", {"label": "rung-0"})
        assert set(got["stamped"]) >= {"host-0", "host-1"}
        # Kill one backend: its section degrades to the cached window,
        # marked stale — never a blocking fetch, never a hole.
        tag, bbase, srv, q = backends[0]
        srv.shutdown()
        srv.server_close()
        q.interrupt()
        t0 = time.monotonic()
        while not router.scoreboard.dead(tag):
            assert time.monotonic() - t0 < 30, "kill never detected"
            time.sleep(0.05)
        doc = _get(base, "/fleet/history")
        assert doc["hosts"][tag]["stale"] is True
        assert doc["hosts"][tag]["window"] is not None  # cached, not blank
        assert doc["hosts"]["host-1"]["stale"] is False

    def test_console_once_json_smoke(self, fleet):
        base, router, backends = fleet
        registry.gauge("pa_server_queue_pending", 1.0)
        timeseries.ring.snapshot()
        registry.gauge("pa_server_queue_pending", 3.0)
        timeseries.ring.snapshot()
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "console.py"),
             "--base", base, "--once", "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        frame = json.loads(out.stdout)
        assert frame["schema"] == "pa-console/v1"
        assert set(frame["hosts"]) >= {"host-0", "host-1"}
        h = frame["hosts"]["host-0"]
        assert h["signals"]["queue"]["spark"]
        assert h["signals"]["queue"]["last"] is not None
        assert len(h["signals"]["queue"]["series"]) >= 2
        assert h["stale"] is False
        # Human mode renders the same frame without ANSI garbage.
        out = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "console.py"),
             "--base", base, "--once"],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0 and "host-0" in out.stdout
