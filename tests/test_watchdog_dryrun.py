"""End-to-end dry-run of the TPU-evidence watchdog against a mocked TPU.

Round-3 post-mortem (VERDICT r3): the one live tunnel window was lost to three
infrastructure bugs because the watchdog → measure → kernel-sweep → retune
pipeline had never executed end to end anywhere. This test runs the REAL
``scripts/tpu_watchdog.py`` process — real subprocess tree, real bench.py
children, real artifact writes — with the platform check faked to CPU
(``PA_FAKE_TPU_PLATFORM=cpu``), every artifact redirected to a temp dir
(``PA_EVIDENCE_DIR`` / ``PA_TUNING_PATH``), and every rung shrunk to the smoke
workload (``PA_BENCH_TINY=1``).

What must hold by exit:
- the watchdog terminates on its own ("all attemptable TPU evidence banked");
- all six ladder rungs banked, the README-repro headline (zimage_21) FIRST;
- the kernel sweep ran and ``--apply`` wrote a measured tuning table;
- the sampler-loop bench banked;
- rungs banked before the tuning table landed were re-run once after it
  (the retune flow);
- BASELINE.md's measured section was re-rendered — in the temp dir;
- the repo's real evidence files were never touched (the fake-platform guard).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dryrun_env(evidence: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PA_TPU_ATTENTION_BACKEND", None)
    # One host device: the dry-run tests pipeline control flow, not sharding
    # (the 8-device mesh path has its own suite), and single-device children
    # compile noticeably faster.
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    ).strip()
    env["PA_FAKE_TPU_PLATFORM"] = "cpu"
    env["PA_EVIDENCE_DIR"] = evidence
    env["PA_TUNING_PATH"] = os.path.join(evidence, "tuning.json")
    env["PA_BENCH_TINY"] = "1"
    env["KERNEL_SWEEP"] = "0"
    env["BENCH_STEPS"] = "3"
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = _REPO + (os.pathsep + existing if existing else "")
    return env


def _records(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_watchdog_banks_everything_end_to_end(tmp_path):
    evidence = str(tmp_path / "evidence")
    os.makedirs(evidence)
    # No BASELINE.md seeded here on purpose: render_measured.py must seed its
    # evidence-dir copy from the repo's file on first run.

    real_measured = os.path.join(_REPO, "BASELINE_measured.json")
    real_before = open(real_measured).read() if os.path.exists(real_measured) else None

    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "tpu_watchdog.py"),
         "--interval", "1"],
        env=_dryrun_env(evidence), cwd=_REPO,
        capture_output=True, text=True, timeout=1500,
    )
    log = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"watchdog died:\n{log[-4000:]}"
    assert "all attemptable TPU evidence banked" in log, log[-4000:]

    # --- rung evidence: all six banked, headline first, honestly labeled ---
    recs = _records(os.path.join(evidence, "BASELINE_measured.json"))
    banked = [r for r in recs if r.get("platform") == "cpu"
              and not r.get("invalid")]
    rung_order = [r["rung"] for r in banked]
    assert rung_order[0] == "zimage_21", (
        f"headline rung must bank first, got order {rung_order}")
    assert set(rung_order) >= {"zimage_21", "sd15_16", "sdxl_8", "hybrid_sd15",
                               "flux_16", "flux_16_int8", "wan_video"}, rung_order
    assert all(r.get("dryrun") for r in banked), "fake-platform records must " \
        "carry the dryrun marker"
    # The microbatch path ran (tiny rungs declare 2 sequential chunks).
    assert any(r.get("microbatch_chunks") == 2 for r in banked)

    # --- kernel sweep: KERNEL_BENCH lines + measured tuning table ---
    kern = _records(os.path.join(evidence, "KERNEL_BENCH.json"))
    assert {r.get("shape") for r in kern} >= {"tiny_128d", "tiny_40d"}
    with open(os.path.join(evidence, "tuning.json")) as f:
        table = json.load(f)
    assert table["source"] == "measured"
    assert table["entries"], "apply must persist per-shape entries"
    dims = {e.get("head_dim") for e in table["entries"]}
    assert {128, 40} <= dims, f"both dim classes must be measured, got {dims}"

    # --- retune: rungs banked before the table got ONE re-run after it ---
    table_ts = os.path.getmtime(os.path.join(evidence, "tuning.json"))
    for rung in ("sd15_16", "sdxl_8"):
        times = [r["ts"] for r in banked if r["rung"] == rung]
        assert len(times) == 2, f"{rung}: expected bank + retune, got {times}"
        assert min(times) < table_ts < max(times), (
            f"{rung}: retune must postdate the tuning table")

    # --- sampler-loop bench banked ---
    samp = _records(os.path.join(evidence, "SAMPLER_LOOP_BENCH.json"))
    assert samp and samp[0]["compiled_s"] > 0

    # --- human-readable render landed in the evidence dir ---
    md = open(os.path.join(evidence, "BASELINE.md")).read()
    body = md.split("<!-- measured:begin -->")[1].split("<!-- measured:end -->")[0]
    assert "zimage_21" in body and "tiny_128d" in body

    # --- the fake-platform guard: no DRYRUN record may leak into the repo's
    # real evidence file. A concurrently-running REAL banking session (the
    # round-long watchdog, VERDICT item 1) may legitimately append real
    # records while this test runs, so assert append-only + no leaked dryrun
    # markers rather than byte equality.
    real_after = open(real_measured).read() if os.path.exists(real_measured) else None
    if real_before is not None:
        assert real_after is not None and real_after.startswith(real_before), (
            "repo evidence was rewritten (not appended) during the dry-run")
        appended = real_after[len(real_before):]
    else:
        appended = real_after or ""
    for line in filter(str.strip, appended.splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # concurrent writer mid-append — not a leak verdict
        assert not rec.get("dryrun"), (
            f"dryrun record leaked into repo evidence: {rec}")
    assert not os.path.exists(os.path.join(_REPO, "evidence"))


def test_oom_deepens_microbatch_ladder_without_striking():
    """The OOM-recovery ladder: a resource-exhausted failure advances the
    rung's BENCH_MICROBATCH depth for the next same-window attempt instead of
    burning a strike (VERDICT r3 next-1 fallback)."""
    sys.path.insert(0, os.path.join(_REPO, "scripts"))
    import tpu_watchdog as wd

    wd._MB_IDX.clear()
    try:
        assert wd._rung_env("zimage_21") == {}
        assert wd._looks_oom({"fallback_stderr": "xx RESOURCE_EXHAUSTED yy"})
        assert wd._looks_oom({"error": "Out of memory allocating 1g"})
        assert not wd._looks_oom({"fallback_stderr": "segmentation fault"})
        assert wd._deepen("zimage_21")
        assert wd._rung_env("zimage_21") == {"BENCH_MICROBATCH": "7"}
        assert wd._deepen("zimage_21")
        assert wd._rung_env("zimage_21") == {"BENCH_MICROBATCH": "21"}
        assert not wd._deepen("zimage_21")  # ladder exhausted -> strikes resume
        assert wd._rung_env("wan_video") == {}  # no ladder for this rung
    finally:
        wd._MB_IDX.clear()


def test_chunk_sweep_gating(tmp_path, monkeypatch):
    """The chunked-attention sweep runs only when the sweep rung's latest TPU
    record still uses the chunked path, and reads as banked once a measured
    table is persisted."""
    sys.path.insert(0, os.path.join(_REPO, "scripts"))
    import tpu_watchdog as wd

    evidence = tmp_path / "evidence"
    evidence.mkdir()
    tuning = tmp_path / "attn_chunk.json"
    monkeypatch.setenv("PA_EVIDENCE_DIR", str(evidence))
    monkeypatch.setenv("PA_ATTN_CHUNK_TUNING", str(tuning))
    wd._FAILS.pop("chunk_sweep", None)

    assert not wd._chunk_sweep_due()  # no records at all
    measured = evidence / "BASELINE_measured.json"
    with open(measured, "w") as f:
        f.write(json.dumps({"rung": "sd15_16", "platform": "tpu",
                            "attention_backend": "xla+xla_chunked",
                            "ts": 1.0}) + "\n")
    assert wd._chunk_sweep_due()
    # A later record served by the fused kernel ends the sweep's relevance.
    with open(measured, "a") as f:
        f.write(json.dumps({"rung": "sd15_16", "platform": "tpu",
                            "attention_backend": "pallas",
                            "ts": 2.0}) + "\n")
    assert not wd._chunk_sweep_due()
    with open(measured, "a") as f:
        f.write(json.dumps({"rung": "sd15_16", "platform": "tpu",
                            "attention_backend": "xla+xla_chunked",
                            "ts": 3.0}) + "\n")
    assert wd._chunk_sweep_due()
    tuning.write_text(json.dumps({"source": "measured", "chunk_elems": 2**29,
                                  "bf16_softmax": True}))
    assert wd.chunk_sweep_banked()
    # Banked but unconfirmed (no default-env record postdates the table):
    # the sweep stays due — the confirmation run is the resume point.
    assert wd._chunk_sweep_due() and not wd._chunk_confirmed()
    table_ts = os.path.getmtime(tuning)
    with open(measured, "a") as f:
        f.write(json.dumps({"rung": "sd15_16", "platform": "tpu",
                            "attention_backend": "xla+xla_chunked",
                            "ts": table_ts + 60}) + "\n")
    assert wd._chunk_confirmed()
    assert not wd._chunk_sweep_due()


def test_chunk_sweep_state_resumes(tmp_path, monkeypatch):
    """CHUNK_SWEEP.json parsing: measured combos are skipped on resume,
    twice-failed combos read as capped, partial lines are tolerated."""
    sys.path.insert(0, os.path.join(_REPO, "scripts"))
    import tpu_watchdog as wd

    evidence = tmp_path / "evidence"
    evidence.mkdir()
    monkeypatch.setenv("PA_EVIDENCE_DIR", str(evidence))
    combo = {"PA_ATTN_CHUNK_ELEMS": str(2**29)}
    with open(evidence / "CHUNK_SWEEP.json", "w") as f:
        f.write(json.dumps({"attn_env": {}, "platform": "tpu",
                            "value": 2.5}) + "\n")
        f.write(json.dumps({"attn_env": combo, "platform": "cpu"}) + "\n")
        f.write(json.dumps({"attn_env": combo, "platform": "cpu"}) + "\n")
        f.write('{"truncated...\n')
    done, fails = wd._chunk_sweep_state()
    assert wd._combo_key({}) in done
    assert fails[wd._combo_key(combo)] == 2


def test_bench_microbatch_override_rounds_to_divisor(tmp_path):
    """BENCH_MICROBATCH=5 on a batch-8 tiny rung must round up to the next
    divisor (8), never crash on indivisibility."""
    env = _dryrun_env(str(tmp_path))
    env["BENCH_CONFIG"] = "sd15_16"
    env["BENCH_MICROBATCH"] = "5"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--inner"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["microbatch_chunks"] == 8  # next divisor of batch 8 above 5


def test_fake_platform_refuses_real_evidence_dir():
    """The PA_FAKE_TPU_PLATFORM guard: without PA_EVIDENCE_DIR, bench.py must
    refuse to run at all rather than risk a faked record in the real files."""
    env = dict(os.environ)
    env["PA_FAKE_TPU_PLATFORM"] = "cpu"
    env.pop("PA_EVIDENCE_DIR", None)
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = _REPO + (os.pathsep + existing if existing else "")
    proc = subprocess.run(
        [sys.executable, "-c", "import bench"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode != 0
    assert "PA_EVIDENCE_DIR" in proc.stderr


def test_chunk_sweep_run_path_banks_winner_and_confirms(tmp_path, monkeypatch):
    """The sweep's RUN path, rehearsed off-hardware (the round-3 lesson:
    never let a pipeline's first execution be an unattended live window):
    measured combos skip on resume, the winner persists with only its own
    keys, losing combos stay out of BASELINE_measured.json, and exactly one
    default-env confirmation record banks."""
    sys.path.insert(0, os.path.join(_REPO, "scripts"))
    import importlib

    import tpu_watchdog as wd
    importlib.reload(wd)  # fresh _FAILS/_MB_IDX state

    evidence = tmp_path / "evidence"
    evidence.mkdir()
    tuning = tmp_path / "attn_chunk.json"
    monkeypatch.setenv("PA_EVIDENCE_DIR", str(evidence))
    monkeypatch.setenv("PA_ATTN_CHUNK_TUNING", str(tuning))

    # Pre-seed ONE measured combo (the default) — the sweep must resume past
    # it, not re-run it.
    with open(evidence / "CHUNK_SWEEP.json", "w") as f:
        f.write(json.dumps({"attn_env": {}, "platform": "tpu",
                            "value": 2.5, "ts": 1.0}) + "\n")

    calls = []
    # Keys via the code-under-test's own _combo_key so a key-format drift
    # cannot silently turn every lookup into a miss. The 2**29+bf16 combo
    # wins; a lookup miss would yield 99.0 and fail the winner assertions.
    values = {
        wd._combo_key({}): 2.5,
        wd._combo_key({"PA_ATTN_CHUNK_ELEMS": "536870912"}): 2.0,
        wd._combo_key({"PA_ATTN_CHUNK_ELEMS": "536870912",
                       "PA_ATTN_BF16_SOFTMAX": "1"}): 1.2,
        wd._combo_key({"PA_ATTN_CHUNK_ELEMS": "1073741824",
                       "PA_ATTN_BF16_SOFTMAX": "1"}): 1.5,
    }

    import measure_tpu

    def fake_run_rung(rung, timeout=0, extra_env=None):
        assert rung == "sd15_16"
        combo = {k: v for k, v in (extra_env or {}).items()
                 if k.startswith("PA_ATTN_")}
        calls.append(combo)
        if not combo and calls.count({}) >= 1 and tuning.exists():
            # The CONFIRMATION run: no PA_ATTN_ env (the persisted table
            # serves it) — it measures the winner's configuration.
            return {"rung": rung, "platform": "tpu", "value": 1.2}
        return {"rung": rung, "platform": "tpu",
                "value": values.get(wd._combo_key(combo), 99.0)}

    monkeypatch.setattr(measure_tpu, "run_rung", fake_run_rung)
    monkeypatch.setattr(wd, "_run_script", lambda *a, **k: None)

    wd._run_chunk_sweep()

    # Three live combo runs (default was pre-seeded) + one confirmation.
    assert len(calls) == 4 and calls[-1] == {}
    table = json.loads(tuning.read_text())
    assert table["source"] == "measured"
    assert table["chunk_elems"] == 2**29 and table["bf16_softmax"] is True
    assert wd.chunk_sweep_banked() and wd._chunk_confirmed()
    # Only the confirmation record landed in the rung evidence file — and
    # it carries the SHIPPING configuration's value, not a losing combo's.
    recs = _records(os.path.join(str(evidence), "BASELINE_measured.json"))
    assert len(recs) == 1 and recs[0]["rung"] == "sd15_16"
    assert recs[0]["value"] == 1.2
    # A second invocation goes straight to... nothing: banked + confirmed.
    assert not wd._chunk_sweep_due()
