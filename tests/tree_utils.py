"""Shared test helpers for param-pytree comparisons."""

import numpy as np


def flatten_tree(tree, prefix=()):
    """Nested dict → ((path, np.ndarray), ...) pairs, depth-first."""
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from flatten_tree(v, prefix + (k,))
    else:
        yield prefix, np.asarray(tree)
