"""Checkpoint conversion + LoRA baking (SURVEY §7 hard parts 2 & 5).

Strategy: synthesize a torch-layout FLUX state dict by *inverting* the converter's
layout transforms from a freshly-initialized model's params, convert it back, and
require exact structural + numerical round-trip. LoRA baking is checked against the
closed-form ``W + s·(alpha/r)·up@down``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tree_utils import flatten_tree

from comfyui_parallelanything_tpu.models.convert import (
    bake_lora,
    convert_flux_checkpoint,
    is_float8_dtype,
    linear_kernel,
    qkv_kernel,
    to_numpy,
)
from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux


@pytest.fixture(scope="module")
def tiny():
    cfg = FluxConfig(
        in_channels=16, hidden_size=32, num_heads=2, depth=2, depth_single_blocks=2,
        context_in_dim=16, vec_in_dim=8, axes_dim=(4, 6, 6), guidance_embed=True,
        dtype=jnp.float32,
    )
    model = build_flux(cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=8)
    return cfg, model


def _inv_dense(params, key_prefix, sd):
    sd[f"{key_prefix}.weight"] = np.asarray(params["kernel"]).T
    if "bias" in params:
        sd[f"{key_prefix}.bias"] = np.asarray(params["bias"])


def _inv_mlp_embedder(params, prefix, sd):
    _inv_dense(params["in_layer"], f"{prefix}.in_layer", sd)
    _inv_dense(params["out_layer"], f"{prefix}.out_layer", sd)


def _torch_layout_sd(cfg: FluxConfig, params) -> dict:
    """Model params → official FLUX checkpoint layout (the converter's inverse)."""
    sd: dict = {}
    _inv_dense(params["img_in"], "img_in", sd)
    _inv_dense(params["txt_in"], "txt_in", sd)
    _inv_mlp_embedder(params["time_in"], "time_in", sd)
    _inv_mlp_embedder(params["vector_in"], "vector_in", sd)
    if cfg.guidance_embed:
        _inv_mlp_embedder(params["guidance_in"], "guidance_in", sd)
    for i in range(cfg.depth):
        blk = params[f"double_blocks_{i}"]
        t = f"double_blocks.{i}"
        for s in ("img", "txt"):
            _inv_dense(blk[f"{s}_mod"]["lin"], f"{t}.{s}_mod.lin", sd)
            k = np.asarray(blk[f"{s}_attn_qkv"]["kernel"])  # (in, 3, H, D)
            sd[f"{t}.{s}_attn.qkv.weight"] = (
                k.transpose(1, 2, 3, 0).reshape(-1, k.shape[0])
            )
            sd[f"{t}.{s}_attn.qkv.bias"] = np.asarray(
                blk[f"{s}_attn_qkv"]["bias"]
            ).reshape(-1)
            sd[f"{t}.{s}_attn.norm.query_norm.scale"] = np.asarray(
                blk[f"{s}_attn_norm"]["query_norm"]
            )
            sd[f"{t}.{s}_attn.norm.key_norm.scale"] = np.asarray(
                blk[f"{s}_attn_norm"]["key_norm"]
            )
            _inv_dense(blk[f"{s}_attn_proj"], f"{t}.{s}_attn.proj", sd)
            _inv_dense(blk[f"{s}_mlp_in"], f"{t}.{s}_mlp.0", sd)
            _inv_dense(blk[f"{s}_mlp_out"], f"{t}.{s}_mlp.2", sd)
    for i in range(cfg.depth_single_blocks):
        blk = params[f"single_blocks_{i}"]
        t = f"single_blocks.{i}"
        _inv_dense(blk["modulation"]["lin"], f"{t}.modulation.lin", sd)
        _inv_dense(blk["linear1"], f"{t}.linear1", sd)
        _inv_dense(blk["linear2"], f"{t}.linear2", sd)
        sd[f"{t}.norm.query_norm.scale"] = np.asarray(blk["norm"]["query_norm"])
        sd[f"{t}.norm.key_norm.scale"] = np.asarray(blk["norm"]["key_norm"])
    _inv_dense(params["final_mod"], "final_layer.adaLN_modulation.1", sd)
    _inv_dense(params["final_proj"], "final_layer.linear", sd)
    return sd


def _tree_paths(tree, prefix=()):
    if isinstance(tree, dict):
        out = []
        for k, v in tree.items():
            out.extend(_tree_paths(v, prefix + (k,)))
        return out
    return [(prefix, np.asarray(tree).shape)]


class TestFluxRoundTrip:
    def test_structure_and_values(self, tiny):
        cfg, model = tiny
        sd = _torch_layout_sd(cfg, model.params)
        got = convert_flux_checkpoint(sd, cfg)
        assert sorted(_tree_paths(got)) == sorted(_tree_paths(model.params))
        flat_got = dict(flatten_tree(got))
        flat_want = dict(flatten_tree(model.params))
        for k in flat_want:
            np.testing.assert_allclose(
                flat_got[k], np.asarray(flat_want[k]), rtol=1e-6, atol=1e-6,
                err_msg=str(k),
            )

    def test_converted_params_run_forward(self, tiny):
        # Both sides run through the SAME jitted program: converted params must be
        # bitwise substitutes for the originals. (Comparing a jitted forward against
        # an eager one instead would measure XLA fusion noise amplified through the
        # random-init blocks — ~2.6e-3 on this tiny config — not converter fidelity.)
        cfg, model = tiny
        sd = _torch_layout_sd(cfg, model.params)
        params = convert_flux_checkpoint(sd, cfg)
        x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4), jnp.float32)
        ctx = jax.random.normal(jax.random.key(2), (1, 8, 16), jnp.float32)
        y = jax.random.normal(jax.random.key(3), (1, 8), jnp.float32)
        f = jax.jit(model.apply)
        want = f(model.params, x, jnp.array([0.5]), ctx, y=y)
        got = f(params, x, jnp.array([0.5]), ctx, y=y)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)



class TestLoRABaking:
    def test_kohya_style_closed_form(self):
        rng = np.random.default_rng(0)
        w = rng.standard_normal((8, 6)).astype(np.float32)
        down = rng.standard_normal((2, 6)).astype(np.float32)  # (r, in)
        up = rng.standard_normal((8, 2)).astype(np.float32)  # (out, r)
        sd = {"blocks.0.proj.weight": w}
        lora = {
            "blocks.0.proj.lora_down.weight": down,
            "blocks.0.proj.lora_up.weight": up,
            "blocks.0.proj.alpha": np.float32(4.0),
        }
        merged = bake_lora(sd, lora, strength=0.5)
        want = w + 0.5 * (4.0 / 2.0) * (up @ down)
        np.testing.assert_allclose(merged["blocks.0.proj.weight"], want, rtol=1e-6)

    def test_diffusers_style_and_underscore_matching(self):
        rng = np.random.default_rng(1)
        w = rng.standard_normal((4, 4)).astype(np.float32)
        down = rng.standard_normal((1, 4)).astype(np.float32)
        up = rng.standard_normal((4, 1)).astype(np.float32)
        sd = {"double_blocks.0.img_attn.proj.weight": w}
        lora = {
            "lora_unet_double_blocks_0_img_attn_proj.lora_A.weight": down,
            "lora_unet_double_blocks_0_img_attn_proj.lora_B.weight": up,
        }
        merged = bake_lora(sd, lora)
        want = w + up @ down  # no alpha → scale 1
        np.testing.assert_allclose(
            merged["double_blocks.0.img_attn.proj.weight"], want, rtol=1e-6
        )

    def test_unmatched_lora_skipped(self):
        sd = {"a.weight": np.zeros((2, 2), np.float32)}
        lora = {
            "nonexistent.lora_down.weight": np.zeros((1, 2), np.float32),
            "nonexistent.lora_up.weight": np.zeros((2, 1), np.float32),
        }
        merged = bake_lora(sd, lora)
        np.testing.assert_array_equal(merged["a.weight"], sd["a.weight"])


class TestDtypeHandling:
    def test_fp8_names_detected(self):
        assert is_float8_dtype("torch.float8_e4m3fn")
        assert is_float8_dtype("float8_e5m2")
        assert not is_float8_dtype("torch.float16")

    def test_torch_bf16_and_fp8_upcast(self):
        torch = pytest.importorskip("torch")
        t = torch.randn(3, 3, dtype=torch.bfloat16)
        out = to_numpy(t)
        assert out.dtype == np.float32
        if hasattr(torch, "float8_e4m3fn"):
            t8 = torch.randn(3, 3).to(torch.float8_e4m3fn)
            out8 = to_numpy(t8)
            assert out8.dtype == np.float32

    def test_layout_transforms(self):
        w = np.arange(12, dtype=np.float32).reshape(4, 3)
        assert linear_kernel(w).shape == (3, 4)
        k = qkv_kernel(np.zeros((3 * 2 * 4, 5), np.float32), heads=2, head_dim=4)
        assert k.shape == (5, 3, 2, 4)
