"""VAE golden parity vs a minimal torch AutoencoderKL (ldm layout).

Full-model activation comparison against a from-scratch torch implementation of
the public kl-f8 autoencoder design: GroupNorm(eps=1e-6)+SiLU resnet blocks,
single-head 1×1-conv spatial attention in the mid block, asymmetric (0,1)×(0,1)
stride-2 downsampling, nearest-×2 upsampling, and quant/post-quant 1×1 convs.
Exported in the official ``encoder.down.{l}.block.{i}`` / ``decoder.up...`` key
layout and converted with ``convert_vae.py`` — the architecture-level check that
round-trip inversion cannot provide (wrong pad side or norm order would survive a
round trip; it cannot survive this).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models.convert_vae import convert_vae_checkpoint
from comfyui_parallelanything_tpu.models.vae import AutoencoderKL, VAEConfig

torch = pytest.importorskip("torch")
tnn = torch.nn
F = torch.nn.functional

CFG = VAEConfig(
    in_channels=3,
    z_channels=4,
    base_channels=32,
    channel_mult=(1, 2),
    num_res_blocks=1,
    norm_groups=8,
    scaling_factor=0.18215,
    use_quant_conv=True,
    dtype=jnp.float32,
)


def _gn(groups, ch):
    return tnn.GroupNorm(groups, ch, eps=1e-6)


class TResnetBlock(tnn.Module):
    def __init__(self, in_ch, out_ch, groups):
        super().__init__()
        self.norm1 = _gn(groups, in_ch)
        self.conv1 = tnn.Conv2d(in_ch, out_ch, 3, padding=1)
        self.norm2 = _gn(groups, out_ch)
        self.conv2 = tnn.Conv2d(out_ch, out_ch, 3, padding=1)
        if in_ch != out_ch:
            self.nin_shortcut = tnn.Conv2d(in_ch, out_ch, 1)

    def forward(self, x):
        h = self.conv1(F.silu(self.norm1(x)))
        h = self.conv2(F.silu(self.norm2(h)))
        if hasattr(self, "nin_shortcut"):
            x = self.nin_shortcut(x)
        return x + h


class TAttnBlock(tnn.Module):
    def __init__(self, ch, groups):
        super().__init__()
        self.norm = _gn(groups, ch)
        self.q = tnn.Conv2d(ch, ch, 1)
        self.k = tnn.Conv2d(ch, ch, 1)
        self.v = tnn.Conv2d(ch, ch, 1)
        self.proj_out = tnn.Conv2d(ch, ch, 1)

    def forward(self, x):
        h = self.norm(x)
        q, k, v = self.q(h), self.k(h), self.v(h)
        b, c, hh, ww = q.shape
        q = q.reshape(b, c, hh * ww).permute(0, 2, 1)
        k = k.reshape(b, c, hh * ww)
        w = torch.softmax(torch.bmm(q, k) / np.sqrt(c), dim=-1)  # (b, hw_q, hw_k)
        v = v.reshape(b, c, hh * ww)
        h = torch.bmm(v, w.permute(0, 2, 1)).reshape(b, c, hh, ww)
        return x + self.proj_out(h)


class TDownsample(tnn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = tnn.Conv2d(ch, ch, 3, stride=2, padding=0)

    def forward(self, x):
        return self.conv(F.pad(x, (0, 1, 0, 1)))


class TUpsample(tnn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = tnn.Conv2d(ch, ch, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2.0, mode="nearest"))


class _Level(tnn.Module):
    pass


class _Mid(tnn.Module):
    pass


class TEncoder(tnn.Module):
    def __init__(self, cfg: VAEConfig):
        super().__init__()
        g = cfg.norm_groups
        chans = [cfg.base_channels * m for m in cfg.channel_mult]
        self.conv_in = tnn.Conv2d(cfg.in_channels, cfg.base_channels, 3, padding=1)
        self.down = tnn.ModuleList()
        ch = cfg.base_channels
        for level, out_ch in enumerate(chans):
            lvl = _Level()
            lvl.block = tnn.ModuleList()
            for _ in range(cfg.num_res_blocks):
                lvl.block.append(TResnetBlock(ch, out_ch, g))
                ch = out_ch
            if level != len(chans) - 1:
                lvl.downsample = TDownsample(ch)
            self.down.append(lvl)
        self.mid = _Mid()
        self.mid.block_1 = TResnetBlock(ch, ch, g)
        self.mid.attn_1 = TAttnBlock(ch, g)
        self.mid.block_2 = TResnetBlock(ch, ch, g)
        self.norm_out = _gn(g, ch)
        self.conv_out = tnn.Conv2d(ch, 2 * cfg.z_channels, 3, padding=1)

    def forward(self, x):
        h = self.conv_in(x)
        for level, lvl in enumerate(self.down):
            for blk in lvl.block:
                h = blk(h)
            if hasattr(lvl, "downsample"):
                h = lvl.downsample(h)
        h = self.mid.block_2(self.mid.attn_1(self.mid.block_1(h)))
        return self.conv_out(F.silu(self.norm_out(h)))


class TDecoder(tnn.Module):
    def __init__(self, cfg: VAEConfig):
        super().__init__()
        g = cfg.norm_groups
        chans = [cfg.base_channels * m for m in cfg.channel_mult]
        ch = chans[-1]
        self.conv_in = tnn.Conv2d(cfg.z_channels, ch, 3, padding=1)
        self.mid = _Mid()
        self.mid.block_1 = TResnetBlock(ch, ch, g)
        self.mid.attn_1 = TAttnBlock(ch, g)
        self.mid.block_2 = TResnetBlock(ch, ch, g)
        # ldm registers up levels in ascending index order but RUNS them reversed.
        self.up = tnn.ModuleList()
        up_levels = []
        for level in reversed(range(len(chans))):
            out_ch = chans[level]
            lvl = _Level()
            lvl.block = tnn.ModuleList()
            for _ in range(cfg.num_res_blocks + 1):
                lvl.block.append(TResnetBlock(ch, out_ch, g))
                ch = out_ch
            if level != 0:
                lvl.upsample = TUpsample(ch)
            up_levels.insert(0, lvl)
        for lvl in up_levels:
            self.up.append(lvl)
        self.norm_out = _gn(g, chans[0])
        self.conv_out = tnn.Conv2d(chans[0], cfg.in_channels, 3, padding=1)

    def forward(self, z):
        h = self.conv_in(z)
        h = self.mid.block_2(self.mid.attn_1(self.mid.block_1(h)))
        for level in reversed(range(len(self.up))):
            lvl = self.up[level]
            for blk in lvl.block:
                h = blk(h)
            if hasattr(lvl, "upsample"):
                h = lvl.upsample(h)
        return self.conv_out(F.silu(self.norm_out(h)))


class TAutoencoderKL(tnn.Module):
    def __init__(self, cfg: VAEConfig):
        super().__init__()
        self.encoder = TEncoder(cfg)
        self.decoder = TDecoder(cfg)
        self.quant_conv = tnn.Conv2d(2 * cfg.z_channels, 2 * cfg.z_channels, 1)
        self.post_quant_conv = tnn.Conv2d(cfg.z_channels, cfg.z_channels, 1)


@pytest.fixture(scope="module")
def pair():
    torch.manual_seed(5)
    tvae = TAutoencoderKL(CFG).eval()
    sd = {k: v.detach() for k, v in tvae.state_dict().items()}
    params = convert_vae_checkpoint(sd, CFG)
    return tvae, params


def test_encoder_moments_golden_parity(pair):
    tvae, params = pair
    rng = np.random.default_rng(31)
    x = rng.uniform(-1, 1, size=(2, 16, 16, 3)).astype(np.float32)
    with torch.no_grad():
        h = tvae.quant_conv(
            tvae.encoder(torch.from_numpy(x.transpose(0, 3, 1, 2)))
        ).numpy().transpose(0, 2, 3, 1)
    want_mean, want_logvar = np.split(h, 2, axis=-1)
    mean, logvar = AutoencoderKL(CFG).apply(
        {"params": params}, jnp.asarray(x), method=AutoencoderKL.moments
    )
    np.testing.assert_allclose(np.asarray(mean), want_mean, rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(
        np.asarray(logvar), np.clip(want_logvar, -30, 20), rtol=5e-4, atol=5e-4
    )


def test_decoder_golden_parity(pair):
    tvae, params = pair
    rng = np.random.default_rng(33)
    z_raw = rng.normal(size=(2, 4, 4, CFG.z_channels)).astype(np.float32)
    with torch.no_grad():
        want = tvae.decoder(
            tvae.post_quant_conv(torch.from_numpy(z_raw.transpose(0, 3, 1, 2)))
        ).numpy().transpose(0, 2, 3, 1)
    # decode() applies the scaling factor first; feed it the scaled latent so the
    # raw z entering post_quant_conv matches the torch path.
    z_scaled = (z_raw - CFG.shift_factor) * CFG.scaling_factor
    got = np.asarray(
        AutoencoderKL(CFG).apply(
            {"params": params}, jnp.asarray(z_scaled), method=AutoencoderKL.decode
        )
    )
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)
