"""SD UNet golden parity vs minimal torch reference blocks (ldm layout).

The res-block and spatial-transformer torch references below follow the public
ldm/openaimodel design the single-file SD checkpoints serialize: ResBlock as
in_layers(GN→SiLU→Conv) + emb_layers(SiLU→Linear) + out_layers(GN→SiLU→Conv) with a
1×1 skip, and SpatialTransformer as GN→1×1 proj_in→BasicTransformerBlock stack
(pre-LN attn1/attn2/GEGLU-ff)→1×1 proj_out with residual. Converted with the
internal helpers of ``convert_unet.py`` and compared activation-for-activation
against ``models/unet.py`` — the architecture-level check that round-trip
inversion (test_convert_unet.py) cannot provide.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_parallelanything_tpu.models.convert_unet import (
    _res_block,
    _spatial_transformer,
)
from comfyui_parallelanything_tpu.models.unet import (
    ResBlock,
    SpatialTransformer,
    UNetConfig,
    sd15_config,
)

torch = pytest.importorskip("torch")
tnn = torch.nn
F = torch.nn.functional

CFG = sd15_config(
    model_channels=32,
    channel_mult=(1, 2),
    num_res_blocks=1,
    attention_levels=(1,),
    transformer_depth=(0, 2),
    num_heads=4,
    context_dim=48,
    norm_groups=8,
    dtype=jnp.float32,
)


class TResBlock(tnn.Module):
    """ldm openaimodel ResBlock (keys: in_layers/emb_layers/out_layers/skip)."""

    def __init__(self, ch, emb_dim, out_ch, groups):
        super().__init__()
        self.in_layers = tnn.Sequential(
            tnn.GroupNorm(groups, ch), tnn.SiLU(), tnn.Conv2d(ch, out_ch, 3, padding=1)
        )
        self.emb_layers = tnn.Sequential(tnn.SiLU(), tnn.Linear(emb_dim, out_ch))
        self.out_layers = tnn.Sequential(
            tnn.GroupNorm(groups, out_ch), tnn.SiLU(), tnn.Identity(),
            tnn.Conv2d(out_ch, out_ch, 3, padding=1),
        )
        self.skip_connection = (
            tnn.Conv2d(ch, out_ch, 1) if ch != out_ch else tnn.Identity()
        )

    def forward(self, x, emb):
        h = self.in_layers(x)
        h = h + self.emb_layers(emb)[:, :, None, None]
        h = self.out_layers(h)
        return self.skip_connection(x) + h


class TCrossAttention(tnn.Module):
    def __init__(self, q_dim, kv_dim, heads, head_dim):
        super().__init__()
        inner = heads * head_dim
        self.heads, self.head_dim = heads, head_dim
        self.to_q = tnn.Linear(q_dim, inner, bias=False)
        self.to_k = tnn.Linear(kv_dim, inner, bias=False)
        self.to_v = tnn.Linear(kv_dim, inner, bias=False)
        self.to_out = tnn.Sequential(tnn.Linear(inner, q_dim))

    def forward(self, x, context=None):
        ctx = x if context is None else context
        b, s, _ = x.shape
        sk = ctx.shape[1]

        def heads_view(t, sl):
            return t.reshape(b, sl, self.heads, self.head_dim)

        q = heads_view(self.to_q(x), s)
        k = heads_view(self.to_k(ctx), sk)
        v = heads_view(self.to_v(ctx), sk)
        logits = torch.einsum("bqhd,bkhd->bhqk", q, k).float() / np.sqrt(self.head_dim)
        probs = torch.softmax(logits, dim=-1)
        o = torch.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, -1)
        return self.to_out(o)


class TGEGLU(tnn.Module):
    def __init__(self, ch):
        super().__init__()
        self.proj = tnn.Linear(ch, ch * 8)

    def forward(self, x):
        a, gate = self.proj(x).chunk(2, dim=-1)
        return a * F.gelu(gate)  # exact erf gelu — the ldm convention


class TBasicTransformerBlock(tnn.Module):
    def __init__(self, ch, ctx_dim, heads, head_dim):
        super().__init__()
        self.attn1 = TCrossAttention(ch, ch, heads, head_dim)
        self.attn2 = TCrossAttention(ch, ctx_dim, heads, head_dim)
        self.ff = tnn.Sequential()
        self.ff.net = tnn.Sequential(TGEGLU(ch), tnn.Identity(), tnn.Linear(ch * 4, ch))
        self.norm1 = tnn.LayerNorm(ch)
        self.norm2 = tnn.LayerNorm(ch)
        self.norm3 = tnn.LayerNorm(ch)

    def forward(self, x, context):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), context)
        x = x + self.ff.net(self.norm3(x))
        return x


class TSpatialTransformer(tnn.Module):
    def __init__(self, ch, ctx_dim, depth, heads, head_dim, groups):
        super().__init__()
        self.norm = tnn.GroupNorm(groups, ch, eps=1e-6)
        self.proj_in = tnn.Conv2d(ch, ch, 1)
        self.transformer_blocks = tnn.ModuleList(
            [TBasicTransformerBlock(ch, ctx_dim, heads, head_dim) for _ in range(depth)]
        )
        self.proj_out = tnn.Conv2d(ch, ch, 1)

    def forward(self, x, context):
        b, c, hh, ww = x.shape
        h = self.proj_in(self.norm(x))
        h = h.reshape(b, c, hh * ww).permute(0, 2, 1)
        for blk in self.transformer_blocks:
            h = blk(h, context)
        h = h.permute(0, 2, 1).reshape(b, c, hh, ww)
        return x + self.proj_out(h)


def _nchw(x_nhwc):
    return torch.from_numpy(np.ascontiguousarray(x_nhwc.transpose(0, 3, 1, 2)))


def test_res_block_golden_parity():
    torch.manual_seed(0)
    ch, out_ch, emb_dim = 32, 64, 128
    tblk = TResBlock(ch, emb_dim, out_ch, groups=CFG.norm_groups).eval()
    sd = {f"res.{k}": v.detach() for k, v in tblk.state_dict().items()}
    params = _res_block(sd, "res", has_skip=True)

    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 8, 8, ch)).astype(np.float32)
    emb = rng.normal(size=(2, emb_dim)).astype(np.float32)
    with torch.no_grad():
        want = tblk(_nchw(x), torch.from_numpy(emb)).numpy().transpose(0, 2, 3, 1)
    got = np.asarray(
        ResBlock(CFG, out_ch).apply(
            {"params": jax.tree.map(jnp.asarray, params)}, jnp.asarray(x), jnp.asarray(emb)
        )
    )
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)


def test_spatial_transformer_golden_parity():
    torch.manual_seed(1)
    ch, heads = 32, 4
    depth, head_dim = 2, ch // 4
    tst = TSpatialTransformer(
        ch, CFG.context_dim, depth, heads, head_dim, groups=CFG.norm_groups
    ).eval()
    sd = {f"st.{k}": v.detach() for k, v in tst.state_dict().items()}
    params = _spatial_transformer(sd, "st", depth, heads, head_dim)

    rng = np.random.default_rng(5)
    x = rng.normal(size=(2, 8, 8, ch)).astype(np.float32)
    ctx = rng.normal(size=(2, 7, CFG.context_dim)).astype(np.float32)
    with torch.no_grad():
        want = (
            tst(_nchw(x), torch.from_numpy(ctx)).numpy().transpose(0, 2, 3, 1)
        )
    got = np.asarray(
        SpatialTransformer(CFG, ch, depth).apply(
            {"params": jax.tree.map(jnp.asarray, params)},
            jnp.asarray(x), jnp.asarray(ctx),
        )
    )
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)
