"""Auto-parallel planner (parallel/planner.py): the roofline-scored search
over (mesh dp×tp × weight mode × stage-carve × attention) that replaced the
orchestrator's hand routing ladder.

Covers the ISSUE-14 acceptance matrix: every banked rung's geometry plans
at-least-as-well as the hand rules by predicted score (and flux_stream
STRICTLY better — the stage-carve win), infeasible plans are never
selected, ``PA_PLANNER=0`` routes bitwise-identically to the hand ladder,
shadow mode records without enacting, plan actuals calibrate back through
``fit_calibration``, the attention axis agrees with ``attention_local``'s
trace-time resolution, and ``scripts/plan_report.py --check`` gates the
ledger records bench/dryrun append.
"""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from comfyui_parallelanything_tpu.parallel import planner

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Approximate byte/FLOP geometry of the banked bench rungs on an 8-chip
# v5e slice (BASELINE.md ladder): enough fidelity for the decision the
# planner must reproduce — weights that fit replicate everywhere, the
# streamed flagship does not.
V5E_BUDGET = int(0.9 * 16 * 2**30)
RUNG_GEOMETRY = {
    # rung: (weights_bytes, flops_per_dispatch, bytes_accessed, batch) —
    # bench passes all of these from the shared step-cost accessor.
    "sd15_16": (1_720_000_000, 1.1e13, 4.0e10, 16),
    "sdxl_8": (5_100_000_000, 1.5e13, 6.0e10, 8),
    "zimage_21": (11_600_000_000, 3.4e13, 9.0e10, 7),
    "flux_16_int8": (12_300_000_000, 2.4e13, 8.0e10, 4),
    "wan_video": (2_800_000_000, 8.0e12, 2.5e10, 1),
    "smoke": (120_000_000, 6.0e10, 1.2e9, 8),
}

# flux_stream: full 19/38 flux-dev int8 segment profile (19 double blocks
# ~300 MB, 38 single ~160 MB) against the round-5 usable-HBM budget.
FLUX_STREAM_SEG = tuple([300_000_000] * 19 + [160_000_000] * 38)
FLUX_STREAM_BUDGET = int(10.8 * 2**30)


def _plan_rung(rung, n_devices=8, pinned=None):
    w, flops, nbytes, batch = RUNG_GEOMETRY[rung]
    return planner.plan(
        planner.PlanInputs(
            n_devices=n_devices, platform="axon", device_kind="TPU v5e",
            weights_bytes=w, budget_bytes=V5E_BUDGET, flops=flops,
            bytes_accessed=nbytes, batch=batch, rung=rung,
        ),
        pinned_mode=pinned,
    )


class TestPlanMatrix:
    @pytest.mark.parametrize("rung", sorted(RUNG_GEOMETRY))
    def test_banked_rungs_match_or_beat_hand(self, rung):
        """Acceptance: on every banked rung the planner is at least as good
        as the hand rules by its own predicted score, and for the resident
        rungs it REPRODUCES the hand choice (replicate over the full
        mesh)."""
        d = _plan_rung(rung)
        assert d["plan_wins"], (rung, d["chosen"], d["hand"])
        assert d["chosen"]["predicted_s"] <= d["hand"]["predicted_s"] + 1e-12
        assert d["chosen"]["mode"] == "replicate", (rung, d["chosen"])
        assert d["chosen"]["dp"] == 8 and d["chosen"]["tp"] == 1
        assert not d["divergent"]

    def test_flux_stream_carve_strictly_beats_hand(self):
        """The strict-win acceptance: at the flagship's real byte geometry
        the stream-carve search finds a finer carve whose predicted step
        beats the hand budget-cap carve (smaller fill exposure)."""
        d = planner.plan(
            planner.PlanInputs(
                n_devices=1, platform="axon", device_kind="TPU v5e",
                weights_bytes=sum(FLUX_STREAM_SEG),
                budget_bytes=FLUX_STREAM_BUDGET,
                segment_bytes=FLUX_STREAM_SEG, batch=4, seq_len=4608,
                head_dim=128, heads=24, rung="flux_stream",
            ),
            pinned_mode="stream",
        )
        assert d["chosen"]["mode"] == "stream"
        assert d["divergent"]
        assert d["chosen"]["predicted_s"] < d["hand"]["predicted_s"]
        assert d["chosen"]["n_stages"] > d["hand"]["n_stages"]

    def test_candidate_table_covers_the_plan_space(self):
        d = _plan_rung("sd15_16")
        modes = {c["mode"] for c in d["candidates"]}
        assert {"replicate", "tp", "fsdp"} <= modes
        tps = {c["tp"] for c in d["candidates"] if c["mode"] == "tp"}
        assert {2, 4, 8} <= tps  # every dp×tp factorization of 8


class TestFeasibilityPruning:
    def test_infeasible_replicate_never_selected(self):
        """Weights past the budget: replicate is enumerated, marked
        infeasible, and never chosen — the search routes to a placement
        that fits (fsdp on a mesh, stream single-chip)."""
        seg = tuple([2_000_000_000] * 8)
        d = planner.plan(planner.PlanInputs(
            n_devices=8, platform="axon", device_kind="TPU v5e",
            weights_bytes=sum(seg), budget_bytes=int(4 * 2**30),
            segment_bytes=seg, batch=8, rung="oversized",
        ))
        rep = [c for c in d["candidates"] if c["mode"] == "replicate"]
        assert rep and not rep[0]["feasible"]
        assert d["chosen"]["feasible"]
        assert d["chosen"]["mode"] != "replicate"

    def test_stream_carves_respect_double_buffer_budget(self):
        d = planner.plan(planner.PlanInputs(
            n_devices=1, platform="axon", device_kind="TPU v5e",
            weights_bytes=sum(FLUX_STREAM_SEG),
            budget_bytes=FLUX_STREAM_BUDGET,
            segment_bytes=FLUX_STREAM_SEG, rung="flux_stream",
        ), pinned_mode="stream")
        for c in d["candidates"]:
            if c["feasible"]:
                assert 2 * c["max_stage_bytes"] <= FLUX_STREAM_BUDGET

    def test_no_feasible_candidate_falls_back_to_hand(self):
        """A single oversized segment under a tiny budget: nothing honors
        the bound, so the decision falls back to the hand plan (bounded
        degradation, the carve_stages atomic-unit rule) and says so."""
        d = planner.plan(planner.PlanInputs(
            n_devices=1, platform="axon", device_kind="TPU v5e",
            weights_bytes=8_000_000_000, budget_bytes=1_000_000_000,
            segment_bytes=(8_000_000_000,), rung="atomic",
        ), pinned_mode="stream")
        assert d["fallback"] == "no-feasible-candidate"
        assert d["chosen"] == d["hand"]


class TestCalibrationFeedback:
    def test_plan_actuals_fit_and_reprice(self, tmp_path, monkeypatch):
        """kind=plan records with actuals fit ``plan:<rung>`` calibration
        scales (utils/roofline.fit_calibration), and the planner applies
        the banked scale to its candidate scores — the sharpening loop."""
        from comfyui_parallelanything_tpu.utils import roofline

        recs = [
            {"schema": "pa-perf-ledger/v1", "kind": "plan",
             "rung": "sd15_16", "platform": "axon",
             "plan_predicted_raw_s": 0.5, "plan_actual_s": 1.0,
             "plan_flops": 1.1e13}
            for _ in range(3)
        ]
        scales = roofline.fit_calibration(recs)
        key = roofline.calib_key(
            "plan:sd15_16", "axon", roofline.shape_bucket(1.1e13)
        )
        assert scales[key]["scale"] == pytest.approx(2.0)
        monkeypatch.setenv("PA_LEDGER_DIR", str(tmp_path))
        assert roofline.save_calibration(scales)
        d = _plan_rung("sd15_16")
        assert d["chosen"]["calib_scale"] == pytest.approx(2.0)
        assert d["chosen"]["predicted_s"] == pytest.approx(
            d["chosen"]["predicted_raw_s"] * 2.0
        )

    def test_dryrun_marked_plan_records_never_fit(self):
        from comfyui_parallelanything_tpu.utils import roofline

        recs = [{"schema": "pa-perf-ledger/v1", "kind": "plan",
                 "rung": "r", "platform": "cpu", "dryrun": True,
                 "plan_predicted_raw_s": 0.5, "plan_actual_s": 1.0}]
        assert roofline.fit_calibration(recs) == {}


class TestAttentionAxis:
    def test_backend_plan_matches_trace_time_resolution(self, monkeypatch):
        """Drift gate: the planner's attention decision and the actual
        ``attention_local`` trace-time resolution are the same ladder."""
        import importlib

        import jax.numpy as jnp

        att = importlib.import_module(
            "comfyui_parallelanything_tpu.ops.attention"
        )
        q = jnp.zeros((1, 8, 2, 4), jnp.float32)
        for env, expect in ((None, "xla"), ("64", "xla_chunked")):
            if env is None:
                monkeypatch.delenv("PA_ATTN_CHUNK_ELEMS", raising=False)
            else:
                monkeypatch.setenv("PA_ATTN_CHUNK_ELEMS", env)
            plan = att.backend_plan(8, head_dim=4, batch=1, heads=2)
            assert plan["backend"] == expect, plan
            before = set(att.resolved_backends())
            att.attention_local(q, q, q)
            resolved = set(att.resolved_backends()) - before or {expect}
            assert plan["backend"] in resolved | {expect}

    def test_backend_plan_carries_the_banked_tables(self, monkeypatch):
        import importlib

        att = importlib.import_module(
            "comfyui_parallelanything_tpu.ops.attention"
        )
        plan = att.backend_plan(4608, head_dim=128, batch=4, heads=24)
        assert plan["backend"] == "xla_chunked"  # no TPU: fused ineligible
        assert plan["chunk_elems"] > 0
        names = {c["backend"] for c in plan["candidates"]}
        assert names == {"pallas", "pallas_jax", "xla", "xla_chunked"}
        assert plan["sources"]["chunk_elems"] in ("env", "default", "measured")


# ---------------------------------------------------------------------------
# orchestrator integration: enact / shadow / off
# ---------------------------------------------------------------------------


@pytest.fixture
def flux_model():
    import jax
    import jax.numpy as jnp

    from comfyui_parallelanything_tpu.models.flux import FluxConfig, build_flux

    cfg = FluxConfig(
        in_channels=16, hidden_size=64, num_heads=4, depth=2,
        depth_single_blocks=6, context_in_dim=32, vec_in_dim=16,
        axes_dim=(4, 6, 6), guidance_embed=False, dtype=jnp.float32,
    )
    return build_flux(
        cfg, jax.random.key(0), sample_shape=(1, 8, 8, 4), txt_len=16
    )


def _flux_inputs(batch=2):
    import jax.numpy as jnp

    x = jnp.ones((batch, 8, 8, 4), jnp.float32) * 0.1
    t = jnp.linspace(1.0, 0.1, batch)
    ctx = jnp.zeros((batch, 16, 32), jnp.float32)
    y = jnp.zeros((batch, 16), jnp.float32)
    return x, t, ctx, y


class TestOrchestratorIntegration:
    def test_planner_off_routes_identically_and_attaches_no_plan(
        self, flux_model, monkeypatch
    ):
        """PA_PLANNER=0 is the bitwise hand fallback: same routing, same
        outputs, no plan attached."""
        import jax

        from comfyui_parallelanything_tpu import DeviceChain, parallelize

        chain = DeviceChain.even(
            [f"cpu:{d.id}" for d in jax.devices("cpu")[:8]]
        )
        x, t, ctx, y = _flux_inputs(16)
        monkeypatch.setenv("PA_PLANNER", "1")
        pm_on = parallelize(flux_model, chain)
        out_on = np.asarray(pm_on(x, t, ctx, y=y))
        assert pm_on.plan is not None
        assert pm_on.plan["chosen"]["mode"] == "replicate"
        monkeypatch.setenv("PA_PLANNER", "0")
        pm_off = parallelize(flux_model, chain)
        out_off = np.asarray(pm_off(x, t, ctx, y=y))
        assert pm_off.plan is None
        assert (out_on == out_off).all(), (
            "planner-on replicate routing must be bitwise-identical to the "
            "hand ladder"
        )

    def test_weights_dont_fit_plans_stream_with_enacted_carve(
        self, flux_model, monkeypatch
    ):
        from comfyui_parallelanything_tpu import (
            DeviceChain,
            ParallelConfig,
            parallelize,
        )
        from comfyui_parallelanything_tpu.models.loader import params_nbytes

        monkeypatch.setenv("PA_PLANNER", "1")
        budget = params_nbytes(flux_model.params) // 3
        pm = parallelize(
            flux_model, DeviceChain.even(["cpu:0"]),
            ParallelConfig(hbm_budget_bytes=budget),
        )
        assert pm.is_streaming
        assert pm.plan["chosen"]["mode"] == "stream"
        x, t, ctx, y = _flux_inputs(1)
        pm(x, t, ctx, y=y)
        runner = pm._stream_runner
        assert runner.n_stages >= 2
        # The enacted carve is never COARSER than the hand budget-cap carve
        # (a divergent planned carve only ever refines; the toy model's
        # atomic block segments may individually exceed the cap — the same
        # carve_stages degradation the hand path has).
        monkeypatch.setenv("PA_PLANNER", "0")
        pm_hand = parallelize(
            flux_model, DeviceChain.even(["cpu:0"]),
            ParallelConfig(hbm_budget_bytes=budget),
        )
        pm_hand(x, t, ctx, y=y)
        assert runner.n_stages >= pm_hand._stream_runner.n_stages
        assert (
            runner.max_stage_nbytes <= pm_hand._stream_runner.max_stage_nbytes
        )

    def test_shadow_mode_records_without_enacting(
        self, flux_model, monkeypatch
    ):
        from comfyui_parallelanything_tpu import (
            DeviceChain,
            ParallelConfig,
            parallelize,
        )
        from comfyui_parallelanything_tpu.models.loader import params_nbytes

        budget = params_nbytes(flux_model.params) // 3
        monkeypatch.setenv("PA_PLANNER", "0")
        pm_hand = parallelize(
            flux_model, DeviceChain.even(["cpu:0"]),
            ParallelConfig(weight_sharding="stream", hbm_budget_bytes=budget),
        )
        hand_stages = pm_hand._get_streaming_runner().n_stages
        monkeypatch.setenv("PA_PLANNER", "shadow")
        pm = parallelize(
            flux_model, DeviceChain.even(["cpu:0"]),
            ParallelConfig(weight_sharding="stream", hbm_budget_bytes=budget),
        )
        assert pm.plan is not None and pm.plan["mode_flag"] == "shadow"
        # Shadow never touches the carve: identical to the hand build.
        assert pm.config.stream_stages is None
        assert pm._get_streaming_runner().n_stages == hand_stages

    def test_pipeline_carve_is_byte_balanced_and_equivalent(
        self, flux_model, monkeypatch
    ):
        """batch==1 block placement under the planner: the planned ranges
        are byte-balanced (pm.plan['pipeline']), the runner uses them, and
        the output matches the hand weight-proportional carve (placement
        moves no math)."""
        import jax

        from comfyui_parallelanything_tpu import DeviceChain, parallelize

        chain = DeviceChain.even(
            [f"cpu:{d.id}" for d in jax.devices("cpu")[:4]]
        )
        x, t, ctx, y = _flux_inputs(1)
        monkeypatch.setenv("PA_PLANNER", "0")
        pm_hand = parallelize(flux_model, chain)
        want = np.asarray(pm_hand(x, t, ctx, y=y))
        monkeypatch.setenv("PA_PLANNER", "1")
        pm = parallelize(flux_model, chain)
        got = np.asarray(pm(x, t, ctx, y=y))
        pipe = pm.plan.get("pipeline")
        assert pipe is not None
        assert pipe["max_stage_bytes"] <= pipe["hand_max_stage_bytes"]
        runner = pm._pipeline_runner
        assert runner is not None and runner.n_stages >= 2
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=1e-4)

    def test_pipeline_carve_not_enacted_in_shadow_mode(
        self, flux_model, monkeypatch
    ):
        """Shadow mode records the pipeline-carve axis but must ENACT the
        hand weight-proportional carve — stage placement bitwise-identical
        to PA_PLANNER=0 (the rollout contract)."""
        import jax

        from comfyui_parallelanything_tpu import DeviceChain, parallelize

        chain = DeviceChain.even(
            [f"cpu:{d.id}" for d in jax.devices("cpu")[:4]]
        )
        x, t, ctx, y = _flux_inputs(1)
        monkeypatch.setenv("PA_PLANNER", "0")
        pm_off = parallelize(flux_model, chain)
        pm_off(x, t, ctx, y=y)
        off_stages = [s.labels for s in pm_off._pipeline_runner.stages]
        monkeypatch.setenv("PA_PLANNER", "shadow")
        pm_sh = parallelize(flux_model, chain)
        pm_sh(x, t, ctx, y=y)
        assert pm_sh.plan is not None
        assert pm_sh.plan["mode_flag"] == "shadow"
        sh_stages = [s.labels for s in pm_sh._pipeline_runner.stages]
        assert sh_stages == off_stages

    def test_ledger_record_drops_actual_for_shadow_divergence(
        self, monkeypatch
    ):
        """A shadow-mode DIVERGENT decision's chosen plan never ran: the
        measured actual (which belongs to the enacted hand plan) must not
        bank against the chosen plan's prediction — it would poison the
        plan:<rung> calibration fit."""
        monkeypatch.setenv("PA_PLANNER", "shadow")
        d = planner.plan(
            planner.PlanInputs(
                n_devices=1, platform="axon", device_kind="TPU v5e",
                weights_bytes=sum(FLUX_STREAM_SEG),
                budget_bytes=FLUX_STREAM_BUDGET,
                segment_bytes=FLUX_STREAM_SEG, batch=4, seq_len=4608,
                rung="flux_stream",
            ),
            pinned_mode="stream",
        )
        assert d["divergent"] and d["mode_flag"] == "shadow"
        rec = planner.ledger_record(d, actual_s=1.0)
        assert rec["plan_actual_s"] is None and rec["plan_ratio"] is None
        # Enacted decisions keep their actuals.
        monkeypatch.setenv("PA_PLANNER", "1")
        d_on = planner.plan(
            planner.PlanInputs(
                n_devices=1, platform="axon", device_kind="TPU v5e",
                weights_bytes=sum(FLUX_STREAM_SEG),
                budget_bytes=FLUX_STREAM_BUDGET,
                segment_bytes=FLUX_STREAM_SEG, batch=4, seq_len=4608,
                rung="flux_stream",
            ),
            pinned_mode="stream",
        )
        rec_on = planner.ledger_record(d_on, actual_s=1.0)
        assert rec_on["plan_actual_s"] == 1.0

    def test_explicit_fsdp_and_tp_are_never_overridden(
        self, flux_model, monkeypatch
    ):
        import jax

        from comfyui_parallelanything_tpu import (
            DeviceChain,
            ParallelConfig,
            parallelize,
        )

        monkeypatch.setenv("PA_PLANNER", "1")
        chain = DeviceChain.even(
            [f"cpu:{d.id}" for d in jax.devices("cpu")[:8]]
        )
        pm = parallelize(
            flux_model, chain, ParallelConfig(weight_sharding="fsdp")
        )
        assert pm.plan is None  # pinned decision: the planner stays out
        assert pm.config.weight_sharding == "fsdp"
        pm_tp = parallelize(
            flux_model, chain, ParallelConfig(tensor_parallel=2)
        )
        assert pm_tp.plan is None
        assert pm_tp.config.tensor_parallel == 2

    def test_streaming_runner_rejects_carve_past_the_cap(self, flux_model):
        """build_streaming_runner composition rule: an explicit n_stages
        whose balanced carve would blow the 2-buffer byte cap falls back to
        the cap carve."""
        import jax

        from comfyui_parallelanything_tpu.models.loader import params_nbytes
        from comfyui_parallelanything_tpu.parallel.streaming import (
            build_streaming_runner,
        )

        budget = params_nbytes(flux_model.params) // 3
        dev = jax.devices("cpu")[0]
        capped = build_streaming_runner(
            flux_model.pipeline_spec, flux_model.params, dev,
            hbm_budget_bytes=budget,
        )
        # n_stages=2 → stages of ~half the pytree each, far past the cap of
        # budget*2/5 = ~2/15 of the pytree: the cap carve must win.
        planned = build_streaming_runner(
            flux_model.pipeline_spec, flux_model.params, dev,
            hbm_budget_bytes=budget, n_stages=2,
        )
        assert planned.n_stages == capped.n_stages
        assert planned.max_stage_nbytes == capped.max_stage_nbytes


class TestSurfaces:
    def test_health_plan_section_and_gauges(self, monkeypatch):
        from comfyui_parallelanything_tpu.utils.metrics import registry
        from comfyui_parallelanything_tpu.utils.telemetry import (
            health_snapshot,
        )

        monkeypatch.setenv("PA_PLANNER", "1")
        before = registry.get("pa_planner_decisions_total") or 0
        d = _plan_rung("sd15_16")
        snap = health_snapshot().get("plan")
        assert snap is not None and snap["mode"] == "on"
        assert snap["decisions"] >= 1
        assert snap["last"]["chosen"]["mode"] == d["chosen"]["mode"]
        assert (registry.get("pa_planner_decisions_total") or 0) > before
        assert registry.get("pa_planner_hand_predicted_s") is not None

    def test_ledger_record_and_summary_shape(self):
        d = _plan_rung("sd15_16")
        rec = planner.ledger_record(d, actual_s=0.02)
        assert rec["rung"] == "sd15_16" and rec["plan_mode"] == "replicate"
        assert rec["plan_actual_s"] == 0.02
        assert rec["plan_ratio"] == pytest.approx(
            d["chosen"]["predicted_s"] / 0.02, rel=1e-3
        )
        assert rec["plan_wins"] and isinstance(rec["plan_candidates"], list)
        summary = planner.plan_summary(d)
        assert summary["chosen"]["mode"] == "replicate"
        assert summary["source"] == "planner"
        assert planner.plan_summary(None) is None


class TestPlanReportGate:
    def _run(self, tmp_path, records, check=True):
        ledger = tmp_path / "ledger"
        ledger.mkdir(exist_ok=True)
        with open(ledger / "perf_ledger.jsonl", "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        env = dict(os.environ)
        env["PA_LEDGER_DIR"] = str(ledger)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        return subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts", "plan_report.py")]
            + (["--check"] if check else []),
            env=env, capture_output=True, text=True, timeout=60,
        )

    def _rec(self, **kw):
        base = {
            "schema": "pa-perf-ledger/v1", "kind": "plan", "rung": "r",
            "platform": "cpu", "plan_mode": "replicate", "plan_dp": 8,
            "plan_tp": 1, "plan_predicted_s": 0.01,
            "plan_predicted_raw_s": 0.01, "plan_hand_mode": "replicate",
            "plan_hand_predicted_s": 0.01, "plan_actual_s": 0.02,
        }
        base.update(kw)
        return base

    def test_skip_on_plan_free_ledger(self, tmp_path):
        proc = self._run(tmp_path, [{"schema": "pa-perf-ledger/v1",
                                     "kind": "bench", "rung": "smoke"}])
        assert proc.returncode == 0 and "SKIP" in proc.stdout

    def test_green_on_match_or_beat(self, tmp_path):
        proc = self._run(tmp_path, [self._rec()])
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_fails_when_plan_loses_to_hand(self, tmp_path):
        proc = self._run(tmp_path, [self._rec(
            plan_predicted_s=0.02, plan_hand_predicted_s=0.01,
            plan_actual_s=None,
        )])
        assert proc.returncode == 1 and "WORSE" in proc.stdout

    def test_fails_on_out_of_band_ratio(self, tmp_path):
        proc = self._run(tmp_path, [self._rec(
            plan_predicted_s=0.05, plan_hand_predicted_s=0.05,
            plan_actual_s=0.01,
        )])
        assert proc.returncode == 1 and "ratio" in proc.stdout

    def test_latest_record_wins(self, tmp_path):
        bad = self._rec(plan_predicted_s=0.02, plan_hand_predicted_s=0.01,
                        plan_actual_s=None)
        good = self._rec()
        proc = self._run(tmp_path, [bad, good])
        assert proc.returncode == 0, proc.stdout


def test_carve_ranges_pure_arithmetic():
    """loader.carve_ranges (the factored carve the planner shares with the
    streaming executor): byte-cap packing, count balancing, oversized
    atomic segments."""
    from comfyui_parallelanything_tpu.models.loader import carve_ranges

    sizes = [4, 4, 4, 4]
    assert carve_ranges(sizes, max_stage_bytes=8) == [(0, 2), (2, 4)]
    assert carve_ranges(sizes, n_stages=4) == [
        (0, 1), (1, 2), (2, 3), (3, 4)
    ]
    # A lone oversized segment stays an atomic stage.
    assert carve_ranges([100, 1, 1], max_stage_bytes=2) == [(0, 1), (1, 3)]
    assert carve_ranges([5], n_stages=3) == [(0, 1)]
