"""Roofline-scored auto-parallel planner: search the plan space, not rules.

The ROADMAP's oldest carried-forward item (round 11) and the direct
analogue of topology-aware auto-parallel planning for diffusion-transformer
inference (PAPERS.md: AoiZora, arxiv 2606.17566; MPMD stage-carve search,
arxiv 2412.14374). The reference's entire "planner" is a static free-VRAM
weighting (any_device_parallel.py:737-766); this module replaces the
orchestrator's hand-written routing ladder (replicate → dp → pipeline →
stream, fixed mesh factorization) with a cost-model search:

- **enumerate** candidate plans: mesh factorizations of the device count
  into dp×tp, weight mode (replicate / fsdp-shard / stream, with byte-carve
  candidates from ``models/loader.carve_ranges`` — the same arithmetic the
  streaming executor carves with), pipeline stage carves for the batch==1
  block-placement path, and the attention axis
  (``ops.attention.backend_plan`` — the banked chunk-sweep and
  pallas-vs-xla tuning tables become a planner input);
- **prune** HBM-infeasible plans against the residency budget
  (``devices.memory.usable_hbm_bytes`` / ``ParallelConfig.hbm_budget_bytes``
  — infeasible candidates stay in the score table, marked, and are never
  selected);
- **score** survivors through the calibrated roofline
  (``utils/roofline.py``: ``max(compute, memory) + comms`` per platform
  spec, the ICI collective term for tp/fsdp gather traffic, the ``h2d_bw``
  host→HBM term for streamed weights, and the banked
  ``ledger/roofline_calib.json`` scale for ``plan:<rung>`` keys — measured
  actuals feed back through ``fit_calibration``, so the planner sharpens
  per platform);
- **route** ``parallelize()`` through the winner, keeping the hand rules
  as the ``PA_PLANNER=0`` fallback AND as a shadow comparator: every
  decision records chosen-vs-hand plan and the per-candidate score table
  (``pa_planner_*`` gauges, the ``plan`` section of ``GET /health``, and —
  when bench/dryrun measure the decision — a ``kind="plan"`` perf-ledger
  record carrying predicted-vs-actual).

Flag discipline (``PA_PLANNER``): ``"0"``/``"false"`` disables the planner
entirely — ``parallelize`` routes through the unmodified hand ladder,
bitwise-identical to the pre-planner code; ``"shadow"`` runs the full
search and records the decision but ENACTS the hand plan (the rollout
mode: divergences surface in the ledger before they touch routing);
anything else (the default) enacts the winner. Divergence hysteresis: the
planner only overrides the hand plan when its candidate predicts at least
:data:`_HYSTERESIS` better — cost models are approximate, routing churn is
not free, and "plan ≥ hand on every rung" is the acceptance contract.

Ledger discipline: this module never writes the perf ledger on its own —
``parallelize`` runs inside tests hundreds of times per suite, and the
committed ledger is evidence, not a log. The decision lives in-process
(:func:`snapshot`, gauges); bench.py and the dryrun append the
``kind="plan"`` record explicitly, stamped with the measured actual.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any

from ..utils.roofline import (
    calibration_scale,
    collective_time_s,
    load_calibration,
    platform_spec,
    shape_bucket,
)

# Divergence hysteresis: the planner abandons the hand plan only for a
# >2% predicted win (see module docstring).
_HYSTERESIS = 0.02

# Per-stage dispatch/jit-call overhead the stream-carve model charges each
# stage (host dispatch + prefetch issue; calibration absorbs the truth).
_STAGE_OVERHEAD_S = 5e-4

# Activation headroom fraction of the HBM budget resident placements
# reserve — the streaming builder's 2/5-per-buffer carve leaves 1/5 for
# activations; resident feasibility keeps the same 1/5 reserve.
_ACT_HEADROOM = 0.2

# Nominal tokens-per-step for the FLOPs fallback (2 FLOPs per weight byte
# per token ≈ 2·params·tokens at bf16 storage): absolute magnitude only
# matters for the compute-vs-transfer comparison inside one decision, and
# every candidate shares it.
_NOMINAL_TOKENS = 4096


def mode() -> str:
    """``"off"`` (PA_PLANNER=0/false — the bitwise hand-rule fallback),
    ``"shadow"`` (search + record, enact hand), or ``"on"`` (default)."""
    raw = os.environ.get("PA_PLANNER", "").strip().lower()
    if raw in ("0", "false", "off"):
        return "off"
    if raw == "shadow":
        return "shadow"
    return "on"


def enabled() -> bool:
    return mode() != "off"


@dataclasses.dataclass(frozen=True)
class PlanInputs:
    """Everything a plan decision is a pure function of. Byte/FLOP facts
    come from the caller (orchestrator/bench) so :func:`plan` itself stays
    deterministic and unit-testable without models or devices."""

    n_devices: int
    platform: str = "cpu"
    device_kind: str = ""
    weights_bytes: int = 0
    # Per-device usable HBM budget; None/0 = unknown (CPU backends report
    # none) — feasibility pruning then admits every resident candidate,
    # exactly like the hand ladder's budget check.
    budget_bytes: int | None = None
    segment_bytes: tuple[int, ...] = ()
    flops: float | None = None          # one model forward (per dispatch)
    bytes_accessed: float | None = None
    batch: int | None = None
    seq_len: int | None = None          # attention-axis hints (optional)
    head_dim: int | None = None
    heads: int | None = None
    rung: str = ""                      # context tag for records/calibration


def _flops_of(inp: PlanInputs) -> float:
    if inp.flops and inp.flops > 0:
        return float(inp.flops)
    tokens = max(1, int(inp.batch or 1)) * int(inp.seq_len or _NOMINAL_TOKENS)
    # bf16 storage ≈ params = bytes/2; 2 FLOPs per param per token —
    # ordering inside one decision is what matters, and every candidate
    # shares the estimate.
    return float(max(1, inp.weights_bytes)) * tokens


def _act_bytes_of(inp: PlanInputs) -> float:
    if inp.bytes_accessed and inp.bytes_accessed > inp.weights_bytes:
        return float(inp.bytes_accessed) - float(inp.weights_bytes)
    # Fallback: activation traffic a quarter of weight traffic — diffusion
    # steps are weight-read dominated at serving batch sizes.
    return 0.25 * float(max(1, inp.weights_bytes))


def _divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def _candidate(mode_: str, inp: PlanInputs, spec: dict, calib: dict, *,
               dp: int, tp: int, feasible: bool, why: str,
               compute_s: float, memory_s: float, comms_s: float,
               transfer_s: float = 0.0, fill_s: float = 0.0,
               overhead_s: float = 0.0, n_stages: int | None = None,
               max_stage_bytes: int | None = None) -> dict:
    raw = max(compute_s, memory_s, transfer_s) + comms_s + fill_s + overhead_s
    scale = calibration_scale(
        calib, f"plan:{inp.rung or '?'}", inp.platform,
        shape_bucket(_flops_of(inp)),
    )
    bound = "comms" if comms_s > max(compute_s, memory_s, transfer_s) else (
        "transfer" if transfer_s > max(compute_s, memory_s)
        else "memory" if memory_s > compute_s else "compute"
    )
    return {
        "mode": mode_, "dp": int(dp), "tp": int(tp),
        "n_stages": n_stages, "max_stage_bytes": max_stage_bytes,
        "feasible": bool(feasible), "why": why,
        "compute_s": round(compute_s, 9), "memory_s": round(memory_s, 9),
        "comms_s": round(comms_s, 9), "transfer_s": round(transfer_s, 9),
        "fill_s": round(fill_s, 9), "overhead_s": round(overhead_s, 9),
        "bound": bound,
        "predicted_raw_s": round(raw, 9),
        "predicted_s": round(raw * scale, 9),
        "calib_scale": scale,
    }


def _resident_candidate(inp: PlanInputs, spec: dict, calib: dict,
                        dp: int, tp: int, mode_: str, why: str) -> dict:
    """Score one resident placement. ``replicate``: full weights per chip,
    no collectives. ``tp``: weights 1/tp per chip, per-step activation
    all-reduce over the model axis. ``fsdp``: weights 1/n per chip, the
    full weight pytree all-gathered per step over ICI."""
    n = dp * tp
    flops = _flops_of(inp)
    act = _act_bytes_of(inp)
    w = float(inp.weights_bytes)
    compute_s = flops / n / spec["peak_flops"]
    if mode_ == "replicate":
        comms = 0.0
    elif mode_ == "tp":
        # Per-step activation all-reduces over the model axis (the GSPMD
        # partials of each sharded matmul) — first-order: the per-device
        # activation traffic crosses the tp group once.
        comms = collective_time_s(act / dp, tp, spec)
    else:  # fsdp
        # Every step all-gathers the full weight pytree (ZeRO-3 per-use
        # gather) — each chip still READS full weights from HBM after,
        # only the stored shard is 1/n.
        comms = collective_time_s(w, n, spec)
    hbm_reads = (w if mode_ != "tp" else w / tp) + act / max(1, dp)
    memory_s = hbm_reads / spec["hbm_bw"]
    budget = inp.budget_bytes or 0
    if budget <= 0:
        feasible = True
    elif mode_ == "replicate":
        feasible = w <= budget
    elif mode_ == "tp":
        feasible = w / tp <= budget * (1 - _ACT_HEADROOM)
    else:  # fsdp: stored shard + one layer's gather buffer headroom
        feasible = w / n <= budget * (1 - _ACT_HEADROOM) / 2
    return _candidate(
        mode_, inp, spec, calib, dp=dp, tp=tp, feasible=feasible, why=why,
        compute_s=compute_s, memory_s=memory_s, comms_s=comms,
    )


def _stream_candidates(inp: PlanInputs, spec: dict, calib: dict,
                       hand_only: bool = False) -> list[dict]:
    """Stream carve candidates: the hand carve (budget·2/5 byte cap — what
    ``build_streaming_runner`` does today) plus byte-balanced carves at
    other stage counts from ``loader.carve_ranges``. Single-device by
    construction (the streaming executor runs the lead chip); the cost
    model is the double-buffered schedule itself: steady state
    ``max(compute, weights/h2d)``, plus the stage-0 fill the overlap can
    never hide, plus per-stage dispatch overhead — more stages shrink the
    fill and grow the overhead, which is exactly the tradeoff the search
    walks."""
    from ..models.loader import carve_ranges

    if not inp.segment_bytes:
        return []
    sizes = list(inp.segment_bytes)
    w = float(sum(sizes))
    flops = _flops_of(inp)
    act = _act_bytes_of(inp)
    budget = inp.budget_bytes or 0
    cap = max(1, int(budget) * 2 // 5) if budget > 0 else None
    compute_s = max(flops / spec["peak_flops"],
                    (w + act) / spec["hbm_bw"])
    h2d = spec.get("h2d_bw") or 10e9
    transfer_s = w / h2d

    def build(ranges, why) -> dict:
        stage_bytes = [sum(sizes[s:e]) for s, e in ranges]
        max_stage = max(stage_bytes)
        fill_s = stage_bytes[0] / h2d
        overhead_s = len(ranges) * _STAGE_OVERHEAD_S
        # Feasibility: two buffers of the largest stage + activation
        # headroom must fit the budget — the 2/5 carve rule inverted. A
        # lone oversized segment is still servable (the atomic-unit
        # degradation carve_ranges documents) but only when no finer
        # feasible carve exists; mark it infeasible so the search prefers
        # carves that honor the bound.
        feasible = budget <= 0 or 2 * max_stage <= budget * (1 - _ACT_HEADROOM)
        return _candidate(
            "stream", inp, spec, calib, dp=1, tp=1,
            feasible=feasible, why=why,
            compute_s=compute_s, memory_s=0.0, comms_s=0.0,
            transfer_s=transfer_s, fill_s=fill_s, overhead_s=overhead_s,
            n_stages=len(ranges), max_stage_bytes=max_stage,
        )

    out: list[dict] = []
    seen: set[tuple] = set()

    def add(ranges, why):
        key = tuple(ranges)
        if key in seen:
            return
        seen.add(key)
        out.append(build(ranges, why))

    if cap is not None:
        add(carve_ranges(sizes, max_stage_bytes=cap),
            "hand carve: budget*2/5 byte cap")
    else:
        # No budget: the hand ladder's StreamingRunner default is a
        # 4-stage byte-balanced carve (build_streaming_runner).
        add(carve_ranges(sizes, n_stages=4),
            "hand carve: default 4-stage balance (no budget)")
    if hand_only:
        return out
    for n in (2, 4, 8, 16, len(sizes)):
        if 2 <= n <= len(sizes):
            add(carve_ranges(sizes, n_stages=n),
                f"byte-balanced carve into {n} stage(s)")
    return out


def _count_ranges(n_items: int, n_parts: int) -> list[tuple[int, int]]:
    """Contiguous count-balanced ranges — what the weight-proportional
    pipeline carve degenerates to on a uniform-weight chain (the hand
    behavior the planned byte-balanced carve is compared against)."""
    n_parts = max(1, min(n_items, n_parts))
    base, rem = divmod(n_items, n_parts)
    ranges, start = [], 0
    for i in range(n_parts):
        size = base + (1 if i < rem else 0)
        ranges.append((start, start + size))
        start += size
    return [r for r in ranges if r[0] != r[1]]


def _pipeline_plan(inp: PlanInputs, spec: dict) -> dict | None:
    """The batch==1 block-placement carve axis: byte-balanced stage ranges
    vs the hand count-balanced carve. The pipeline's critical path is the
    largest stage (every stage runs serially, memory-bound per device), so
    the score is max-stage bytes over HBM bandwidth — byte balance wins
    whenever segments are uneven."""
    from ..models.loader import carve_ranges

    if len(inp.segment_bytes) < 2 or inp.n_devices < 2:
        return None
    sizes = list(inp.segment_bytes)
    planned = carve_ranges(sizes, n_stages=inp.n_devices)
    hand = _count_ranges(len(sizes), inp.n_devices)

    def max_stage(ranges):
        return max(sum(sizes[s:e]) for s, e in ranges)

    bw = spec["hbm_bw"]
    pred = max_stage(planned) / bw
    hand_pred = max_stage(hand) / bw
    return {
        "ranges": [list(r) for r in planned],
        "hand_ranges": [list(r) for r in hand],
        "max_stage_bytes": max_stage(planned),
        "hand_max_stage_bytes": max_stage(hand),
        "predicted_s": round(pred, 9),
        "hand_predicted_s": round(hand_pred, 9),
        # The same hysteresis contract as the top-level choice: the planned
        # carve may only be ENACTED (orchestrator._get_pipeline_runner)
        # when it actually differs and predicts clearly better. "enact" is
        # the INTENT; the orchestrator sets "enacted" (and bumps
        # pa_planner_pipeline_carve_total) at the moment a batch==1 runner
        # really builds with the planned ranges.
        "enact": (planned != hand
                  and pred < hand_pred * (1 - _HYSTERESIS)),
        "enacted": False,
    }


def hand_plan(inp: PlanInputs, spec: dict, calib: dict,
              pinned_mode: str | None = None) -> dict:
    """The PA_PLANNER=0 ladder as a scored candidate — the shadow
    comparator every decision records: replicate over every device, except
    weights-don't-fit with a PipelineSpec → stream at the budget-derived
    carve (orchestrator.parallelize's exact auto-routing)."""
    budget = inp.budget_bytes or 0
    streams = _stream_candidates(inp, spec, calib, hand_only=True)
    if pinned_mode == "stream" or (
        budget > 0 and inp.weights_bytes > budget and inp.segment_bytes
    ):
        if streams:
            hand = dict(streams[0])
            hand["why"] = "hand ladder: " + hand["why"]
            return hand
    return _resident_candidate(
        inp, spec, calib, dp=inp.n_devices, tp=1, mode_="replicate",
        why="hand ladder: replicate over every chain device",
    )


def plan(inp: PlanInputs, pinned_mode: str | None = None) -> dict:
    """One decision: enumerate → prune → score → choose, with the hand plan
    as the recorded shadow. ``pinned_mode="stream"`` restricts the space to
    the stream-carve axis (an explicit ``weight_sharding="stream"`` pins
    the mode; the carve is still searched). Pure in ``inp`` + the banked
    tables (calibration store, attention tuning files)."""
    spec = platform_spec(inp.device_kind, inp.platform)
    calib = load_calibration()
    n = max(1, int(inp.n_devices))

    candidates: list[dict] = []
    if pinned_mode == "stream":
        candidates.extend(_stream_candidates(inp, spec, calib))
    else:
        for tp in _divisors(n):
            dp = n // tp
            if tp == 1:
                candidates.append(_resident_candidate(
                    inp, spec, calib, dp=dp, tp=1, mode_="replicate",
                    why=f"replicate, dp={dp}",
                ))
            else:
                candidates.append(_resident_candidate(
                    inp, spec, calib, dp=dp, tp=tp, mode_="tp",
                    why=f"2-D mesh dp={dp} x tp={tp} (GSPMD)",
                ))
        if n > 1:
            candidates.append(_resident_candidate(
                inp, spec, calib, dp=n, tp=1, mode_="fsdp",
                why=f"fsdp: weights 1/{n} per chip, per-step all-gather",
            ))
        candidates.extend(_stream_candidates(inp, spec, calib))

    hand = hand_plan(inp, spec, calib, pinned_mode=pinned_mode)
    feasible = [c for c in candidates if c["feasible"]]
    fallback = None
    if feasible:
        best = min(feasible, key=lambda c: c["predicted_s"])
        # Hysteresis: diverge from the hand plan only for a clear win.
        if best["predicted_s"] >= hand["predicted_s"] * (1 - _HYSTERESIS):
            chosen = hand
        else:
            chosen = best
    else:
        chosen = hand
        fallback = "no-feasible-candidate"

    attn = None
    if inp.seq_len:
        try:
            from ..ops.attention import backend_plan

            attn = backend_plan(
                int(inp.seq_len), head_dim=inp.head_dim,
                batch=int(inp.batch or 1), heads=int(inp.heads or 1),
            )
        except Exception:
            attn = None

    pipeline = (
        _pipeline_plan(inp, spec)
        if chosen["mode"] in ("replicate",) else None
    )
    decision = {
        "rung": inp.rung or None,
        "platform": inp.platform,
        "device_kind": inp.device_kind or None,
        "n_devices": n,
        "weights_bytes": int(inp.weights_bytes),
        "budget_bytes": int(inp.budget_bytes) if inp.budget_bytes else None,
        "flops": _flops_of(inp),
        "flops_source": "hint" if inp.flops else "weights-estimate",
        "pinned_mode": pinned_mode,
        "chosen": chosen,
        "hand": hand,
        "candidates": candidates,
        "pipeline": pipeline,
        "attn": attn,
        # Top-level routing divergence (mode/mesh/carve key). The pipeline
        # carve is its OWN dimension: "enact" above records the intent
        # (differs + clears hysteresis), and the orchestrator stamps
        # ``pipeline["enacted"]`` only when the batch==1 runner actually
        # builds with the planned ranges — whether that ever happens
        # depends on runtime facts (batch==1 traffic, uniform weights)
        # this pure decision cannot see, so folding intent into
        # ``divergent`` would report routing changes that never occurred.
        "divergent": _plan_key(chosen) != _plan_key(hand),
        "plan_wins": chosen["predicted_s"] <= hand["predicted_s"] + 1e-12,
        "fallback": fallback,
        "mode_flag": mode(),
    }
    _record_decision(decision)
    return decision


def _plan_key(c: dict) -> tuple:
    return (c["mode"], c["dp"], c["tp"], c.get("n_stages"))


# ---------------------------------------------------------------------------
# in-process decision registry + gauges + health section
# ---------------------------------------------------------------------------


class _State:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.decisions = 0      # guarded-by: _lock
        self.divergences = 0    # guarded-by: _lock
        self.last: dict | None = None  # guarded-by: _lock

    def reset(self) -> None:
        with self._lock:
            self.decisions = 0
            self.divergences = 0
            self.last = None


state = _State()


def _record_decision(decision: dict) -> None:
    with state._lock:
        state.decisions += 1
        if decision["divergent"]:
            state.divergences += 1
        state.last = decision
    try:
        from ..utils.metrics import registry

        registry.counter(
            "pa_planner_decisions_total",
            help="auto-parallel plan decisions taken (parallel/planner.py)",
        )
        if decision["divergent"]:
            registry.counter(
                "pa_planner_divergence_total",
                help="decisions where the scored winner overrode the "
                     "hand-rule plan",
            )
        registry.gauge(
            "pa_planner_predicted_s", decision["chosen"]["predicted_s"],
            labels={"mode": decision["chosen"]["mode"]},
            help="calibrated roofline prediction of the chosen plan's step",
        )
        registry.gauge(
            "pa_planner_hand_predicted_s", decision["hand"]["predicted_s"],
            help="the shadow hand-rule plan's predicted step (chosen <= "
                 "hand is the acceptance contract)",
        )
        registry.gauge(
            "pa_planner_candidates", len(decision["candidates"]),
            help="candidate plans enumerated for the last decision",
        )
    except Exception:
        pass


def _compact(c: dict | None) -> dict | None:
    if not isinstance(c, dict):
        return None
    return {k: c.get(k) for k in (
        "mode", "dp", "tp", "n_stages", "max_stage_bytes", "feasible",
        "predicted_s", "predicted_raw_s", "bound", "why",
    )}


def plan_summary(decision: dict | None) -> dict | None:
    """The compact plan view a bench JSON line carries (null when the
    planner is off or never engaged)."""
    if not isinstance(decision, dict):
        return None
    return {
        "source": "planner" if decision["mode_flag"] == "on" else "shadow",
        "chosen": _compact(decision["chosen"]),
        "hand_predicted_s": decision["hand"]["predicted_s"],
        "divergent": decision["divergent"],
        "plan_wins": decision["plan_wins"],
        "candidates": len(decision["candidates"]),
        "attn_backend": (decision.get("attn") or {}).get("backend"),
    }


def snapshot() -> dict:
    """The ``plan`` section of ``GET /health``."""
    with state._lock:
        last = state.last
        return {
            "mode": mode(),
            "decisions": state.decisions,
            "divergences": state.divergences,
            "last": None if last is None else {
                "rung": last["rung"],
                "n_devices": last["n_devices"],
                "chosen": _compact(last["chosen"]),
                "hand": _compact(last["hand"]),
                "divergent": last["divergent"],
                "plan_wins": last["plan_wins"],
                "candidates": len(last["candidates"]),
            },
        }


def ledger_record(decision: dict, actual_s: float | None = None) -> dict:
    """Flatten a decision into the ``kind="plan"`` perf-ledger record
    (scripts/plan_report.py gates it; ``fit_calibration`` reads
    ``plan_predicted_raw_s``/``plan_actual_s`` back). The caller appends it
    via ``telemetry.append_ledger_record(rec, "plan")`` — see the module
    docstring's ledger discipline.

    Shadow guard: in shadow mode a DIVERGENT decision's chosen plan never
    ran — the measured actual belongs to the enacted hand plan, and pairing
    it with the chosen plan's raw prediction would poison the
    ``plan:<rung>`` calibration fit. The actual is dropped from the record
    there (the decision itself still banks in full)."""
    chosen, hand = decision["chosen"], decision["hand"]
    if actual_s and decision["divergent"] and decision["mode_flag"] != "on":
        actual_s = None
    rec = {
        "rung": decision["rung"] or "?",
        "platform": decision["platform"],
        "n_devices": decision["n_devices"],
        "weights_bytes": decision["weights_bytes"],
        "budget_bytes": decision["budget_bytes"],
        "plan_mode": chosen["mode"],
        "plan_dp": chosen["dp"],
        "plan_tp": chosen["tp"],
        "plan_stages": chosen.get("n_stages"),
        "plan_predicted_s": chosen["predicted_s"],
        "plan_predicted_raw_s": chosen["predicted_raw_s"],
        "plan_flops": decision["flops"],
        "plan_hand_mode": hand["mode"],
        "plan_hand_stages": hand.get("n_stages"),
        "plan_hand_predicted_s": hand["predicted_s"],
        "plan_divergent": decision["divergent"],
        "plan_wins": decision["plan_wins"],
        "plan_pinned_mode": decision["pinned_mode"],
        "plan_mode_flag": decision["mode_flag"],
        "plan_candidates": [_compact(c) for c in decision["candidates"]],
        "plan_attn": (decision.get("attn") or {}).get("backend"),
        # The pipeline-carve axis, its own dimension (see plan()): intent
        # vs actually-applied, with the byte scores behind them.
        "plan_pipeline": (
            None if not decision.get("pipeline") else {
                k: decision["pipeline"][k]
                for k in ("enact", "enacted", "max_stage_bytes",
                          "hand_max_stage_bytes")
            }
        ),
        "plan_actual_s": (
            round(float(actual_s), 6) if actual_s else None
        ),
        "plan_ratio": (
            round(chosen["predicted_s"] / float(actual_s), 4)
            if actual_s else None
        ),
    }
    return rec
