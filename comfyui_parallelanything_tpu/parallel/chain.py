"""The DEVICE_CHAIN data type: an ordered list of (device, percentage) links.

Reference semantics (any_device_parallel.py):
- ParallelDevice.add_device (819-832) copies the incoming chain and appends
  ``{"device": str, "percentage": float, "weight": pct/100}`` — the ``weight`` key is
  dead data (setup_parallel renormalizes from ``percentage`` only, 1019-1027), so this
  implementation does not carry it.
- ParallelDeviceList.create_list (872-882) builds up to 4 entries at once, dropping
  entries whose percentage is <= 0 (876-882).
- setup_parallel normalizes weights as ``pct_i / sum(pct)`` and aborts when the sum is
  <= 0 (1019-1027).

The chain is immutable; builders return new chains (the reference copies the incoming
list for the same reason, 821-824).
"""

from __future__ import annotations

import dataclasses
from collections.abc import Iterable, Sequence

import jax

from ..devices.discovery import device_platform, get_device
from .split import normalize_weights


@dataclasses.dataclass(frozen=True)
class DeviceLink:
    """One link: a device identifier string plus its workload percentage."""

    device: str
    percentage: float

    def __post_init__(self) -> None:
        if not isinstance(self.device, str) or not self.device:
            raise ValueError(f"device must be a non-empty string, got {self.device!r}")


@dataclasses.dataclass(frozen=True)
class DeviceChain:
    """An ordered, immutable chain of DeviceLinks — the DEVICE_CHAIN value."""

    links: tuple[DeviceLink, ...] = ()

    # -- builders ----------------------------------------------------------------

    def add(self, device: str, percentage: float) -> "DeviceChain":
        """Append one link, returning a new chain (parity: add_device, 819-832)."""
        return DeviceChain(self.links + (DeviceLink(device, float(percentage)),))

    @classmethod
    def from_pairs(cls, pairs: Iterable[tuple[str, float]]) -> "DeviceChain":
        """Build a chain from (device, pct) pairs, dropping pct <= 0 entries
        (parity: ParallelDeviceList.create_list, 872-882)."""
        links = tuple(
            DeviceLink(dev, float(pct)) for dev, pct in pairs if float(pct) > 0
        )
        return cls(links)

    @classmethod
    def even(cls, devices: Sequence[str]) -> "DeviceChain":
        """Convenience: an even split over the given devices."""
        n = len(devices)
        if n == 0:
            return cls()
        return cls(tuple(DeviceLink(d, 100.0 / n) for d in devices))

    # -- views -------------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.links)

    def __iter__(self):
        return iter(self.links)

    def __bool__(self) -> bool:
        return bool(self.links)

    @property
    def devices(self) -> tuple[str, ...]:
        return tuple(l.device for l in self.links)

    @property
    def percentages(self) -> tuple[float, ...]:
        return tuple(l.percentage for l in self.links)

    @property
    def platforms(self) -> tuple[str, ...]:
        return tuple(device_platform(d) for d in self.devices)

    @property
    def is_homogeneous(self) -> bool:
        """True when every link lives on the same platform — the case where weighted
        splits degenerate to even SPMD sharding (SURVEY §7 translation table)."""
        return len(set(self.platforms)) <= 1

    def normalized_weights(self) -> tuple[float, ...] | None:
        """``pct_i / sum(pct)``, or None when the sum is <= 0 — the caller must then
        leave the model untouched (parity: 1019-1027)."""
        return normalize_weights(self.percentages)

    def jax_devices(self) -> tuple[jax.Device, ...]:
        """Resolve every link to a live jax.Device. Raises ValueError on any invalid
        entry (the reference instead skips invalid devices in its replica loop,
        1037-1042; resolution here happens before mesh construction, where silent
        skipping would corrupt the sharding layout — callers wanting skip semantics
        use `validated()`)."""
        return tuple(get_device(d) for d in self.devices)

    def validated(self) -> "DeviceChain":
        """Drop links that fail device resolution, mirroring the reference's
        skip-invalid-device behavior (1037-1042). Weight renormalization happens
        naturally downstream since weights derive from surviving percentages."""
        good = []
        for link in self.links:
            try:
                get_device(link.device)
            except ValueError:
                continue
            good.append(link)
        return DeviceChain(tuple(good))

    def deduplicated(self) -> "DeviceChain":
        """Merge repeated devices by summing their percentages. The reference allows
        the same device twice (each gets its own replica + thread); under SPMD a mesh
        must not contain a device twice, so repeated links fold into one with the
        combined workload share — same effective split arithmetic."""
        acc: dict[str, float] = {}
        order: list[str] = []
        for link in self.links:
            if link.device not in acc:
                order.append(link.device)
                acc[link.device] = 0.0
            acc[link.device] += link.percentage
        return DeviceChain(tuple(DeviceLink(d, acc[d]) for d in order))
