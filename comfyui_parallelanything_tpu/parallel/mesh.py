"""Mesh construction: the DEVICE_CHAIN → `jax.sharding.Mesh` bridge.

The reference's "mesh" is an implicit list of torch devices each holding a full replica
(any_device_parallel.py:1056-1128). Here the chain maps to a named device mesh and all
communication becomes XLA collectives over it (SURVEY §2f). Axis vocabulary:

- ``data``  — batch sharding (the reference's only split axis, dim0: 1222-1237)
- ``seq``   — sequence/context parallelism (ring attention / Ulysses; absent in the
  reference, first-class here)
- ``model`` — tensor parallelism (absent in the reference; the mesh abstraction must
  not preclude it, SURVEY §5.7)
- ``stage`` — pipeline stages for the batch==1 block-placement mode (1152-1198)

A chain with N devices builds a 1-D ``data`` mesh by default; callers may fold the same
devices into any 2-D ``(data, seq)`` / ``(data, model)`` layout.
"""

from __future__ import annotations

from collections.abc import Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"
AXIS_STAGE = "stage"


def mesh_axis_names() -> tuple[str, ...]:
    """The canonical axis vocabulary, outermost first."""
    return (AXIS_DATA, AXIS_SEQ, AXIS_MODEL, AXIS_STAGE)


def build_mesh(
    devices: Sequence[jax.Device],
    axis_shape: dict[str, int] | None = None,
) -> Mesh:
    """Build a Mesh over ``devices``.

    ``axis_shape`` maps axis name → size, in the order given; sizes must multiply to
    ``len(devices)``. Default: a 1-D ``data`` mesh over all devices.
    """
    devs = list(devices)
    if not devs:
        raise ValueError("cannot build a mesh over zero devices")
    if axis_shape is None:
        axis_shape = {AXIS_DATA: len(devs)}
    sizes = tuple(axis_shape.values())
    if int(np.prod(sizes)) != len(devs):
        raise ValueError(
            f"axis sizes {axis_shape} do not multiply to device count {len(devs)}"
        )
    arr = np.array(devs, dtype=object).reshape(sizes)
    return Mesh(arr, tuple(axis_shape.keys()))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding that replicates a value to every mesh device — the SPMD replacement for
    the reference's per-device model cloning (safe_model_clone, 586-722)."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = AXIS_DATA, ndim: int | None = None) -> NamedSharding:
    """Sharding that splits dim0 over ``axis`` — the SPMD replacement for the
    reference's host-side torch.split scatter (1222-1250)."""
    del ndim  # dim0-only, like the reference; trailing dims unconstrained
    return NamedSharding(mesh, P(axis))


# Cap on transfer bytes in flight during big-pytree placement. A whole-pytree
# jax.device_put dispatches every leaf's transfer at once; on a 16 GiB chip a
# ~12 GiB model leaves no headroom for the staging the concurrent transfers
# need (round-3 evidence: flux_16_int8 OOM'd while *placing* the int8 pytree,
# BASELINE_measured.json fallback_stderr). Draining the queue every N bytes is
# the reference's incremental key-by-key state-dict copy trick
# (any_device_parallel.py:639-665) applied to device_put.
_MAX_INFLIGHT_BYTES = 1 << 30


def streamed_tree_put(tree, sharding_for_leaf, max_inflight_bytes=_MAX_INFLIGHT_BYTES):
    """Place a pytree leaf-by-leaf with bounded in-flight transfer bytes.

    ``sharding_for_leaf`` maps each leaf to its target ``Sharding`` (or device).
    Transfers still overlap (XLA dispatch is async) but the queue is drained
    with ``block_until_ready`` whenever the un-acknowledged bytes exceed the
    cap, so placement-time device peak stays ~total + cap instead of
    total + all-concurrent staging.
    """
    leaves, treedef = jax.tree.flatten(tree)
    placed, inflight, inflight_bytes = [], [], 0
    for leaf in leaves:
        out = jax.device_put(leaf, sharding_for_leaf(leaf))
        placed.append(out)
        nbytes = getattr(out, "nbytes", 0)
        if nbytes:
            inflight.append(out)
            inflight_bytes += nbytes
        if inflight_bytes >= max_inflight_bytes:
            jax.block_until_ready(inflight)
            inflight, inflight_bytes = [], 0
    return jax.tree.unflatten(treedef, placed)


def place_params(params, mesh: Mesh) -> object:
    """Replicate a parameter pytree onto the mesh, streamed leaf-by-leaf.

    This is the entire replacement for the reference's replica build loop + incremental
    state-dict copy (1056-1128, 636-665): XLA broadcasts each buffer over ICI, there is
    no 2× host peak, and the pytree remains a single logical value.
    """
    sharding = replicated(mesh)
    return streamed_tree_put(params, lambda _: sharding)


def fsdp_spec(shape: tuple[int, ...], axis: str, n: int, min_size: int = 2**16) -> P:
    """FSDP PartitionSpec for one weight: shard the largest divisible dimension over
    ``axis``; small or indivisible weights replicate.

    Beyond-reference capability the hardware demands: a FLUX-dev-class model in bf16
    (~24 GB) cannot hold a full replica per 16 GB v5e chip, so the reference's
    replicate-everything DP (README.md:167 'full model per device') is physically
    impossible there. Sharding each weight over the data axis (ZeRO-3 / FSDP) keeps
    per-chip weight memory at 1/N; XLA inserts the all-gathers at use sites and
    overlaps them with compute.
    """
    if not shape:
        return P()
    total = 1
    for s in shape:
        total *= s
    if total < min_size:
        return P()  # not worth the all-gather choreography
    best = max(range(len(shape)), key=lambda i: (shape[i] % n == 0, shape[i]))
    if shape[best] % n:
        return P()
    spec = [None] * len(shape)
    spec[best] = axis
    return P(*spec)


def place_params_sharded(
    params, mesh: Mesh, axis: str, min_size: int = 2**16
) -> object:
    """Place a parameter pytree with per-leaf largest-divisible-axis sharding over
    ``axis`` (the shared policy behind both FSDP and GSPMD tensor parallelism —
    the two differ only in WHICH mesh axis carries the shards):

    - over the ``data`` axis (FSDP / ZeRO-3): batch computation needs whole
      weights, so XLA all-gathers them per use; per-chip weight memory is 1/N;
    - over the ``model`` axis (TP): the axis is unused by batch sharding, so XLA
      partitions the matmul contractions themselves (partial products +
      reduce-scatter/all-reduce) — Megatron-shaped execution without hand-written
      collectives (absent in the reference: "No model parallelism", README.md:212).
    """
    n = mesh.shape[axis]

    def sharding_for(leaf):
        spec = fsdp_spec(tuple(getattr(leaf, "shape", ())), axis, n, min_size)
        return NamedSharding(mesh, spec)

    return streamed_tree_put(params, sharding_for)


def place_params_fsdp(params, mesh: Mesh, axis: str = AXIS_DATA) -> object:
    """FSDP placement: ``place_params_sharded`` over the data axis."""
    return place_params_sharded(params, mesh, axis)


def sharded_shardings(shape_tree, mesh: Mesh, axis: str, min_size: int = 2**16):
    """Per-leaf ``NamedSharding`` tree for a ShapeDtypeStruct pytree, using the
    same largest-divisible-axis policy as ``place_params_sharded``."""
    n = mesh.shape[axis]
    return jax.tree.map(
        lambda sd: NamedSharding(mesh, fsdp_spec(tuple(sd.shape), axis, n, min_size)),
        shape_tree,
    )


def sharded_byte_math(
    shape_tree, mesh: Mesh, axis: str, itemsize: int = 2, min_size: int = 2**16
) -> tuple[int, int]:
    """(per_device_bytes, total_bytes) the FSDP policy would place, computed from
    abstract shapes alone — the big-model placement proof that needs zero RAM
    (used by both the driver dryrun and test_fsdp; ``itemsize=2`` = the bf16
    checkpoint layout the converters produce)."""
    shardings = sharded_shardings(shape_tree, mesh, axis, min_size)
    per_device = total = 0
    for sd, sh in zip(jax.tree.leaves(shape_tree), jax.tree.leaves(shardings)):
        per_device += int(np.prod(sh.shard_shape(tuple(sd.shape)), dtype=np.int64)) * itemsize
        total += int(np.prod(tuple(sd.shape), dtype=np.int64)) * itemsize
    return per_device, total


def materialize_params_sharded(
    shape_tree, mesh: Mesh, axis: str = AXIS_DATA, min_size: int = 2**16
):
    """Create a zero-valued parameter pytree *directly in* its FSDP sharding.

    This is the big-model creation path: a FLUX-dev-class pytree (~24 GB bf16)
    must never exist unsharded — not on the host, not on any single chip. Each
    leaf is produced by a jitted zeros program whose ``out_shardings`` is the
    FSDP spec, so every device only ever allocates its 1/N shard. Checkpoint
    loaders overwrite these buffers shard-by-shard (the reference's analogue is
    the incremental state-dict copy at any_device_parallel.py:636-665, which
    still needs a full host copy — this path needs none).
    """
    import jax.numpy as jnp

    shardings = sharded_shardings(shape_tree, mesh, axis, min_size)

    def init():
        return jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), shape_tree)

    return jax.jit(init, out_shardings=shardings)()


def place_params_tp(params, mesh: Mesh, axis: str = AXIS_MODEL) -> object:
    """Tensor-parallel placement: ``place_params_sharded`` over the model axis."""
    return place_params_sharded(params, mesh, axis)
