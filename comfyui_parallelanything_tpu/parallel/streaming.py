"""Double-buffered weight-streaming execution: run models whose weights exceed HBM.

The flagship workload this repo is benchmarked on (FLUX-dev, bf16 ~24 GiB /
int8 ~12 GiB) does not fit the chip's usable HBM (<10.8 GiB, BASELINE.md
round-5 finding), so neither the reference's replicate-everything placement
(README.md:167) nor this repo's resident pipeline placement can ever run it
single-chip. The ZeRO-Inference / DeepSpeed-Inference answer is to keep the
weights HOST-side and stream them through the chip layer by layer, overlapping
the next layer's transfer with the current layer's compute (PAPERS.md:
ZeRO-Offload lineage; GPipe-style stage overlap).

This module is that scheduler, built on the staging the models already
declare: a ``PipelineSpec`` (models/api.py) partitions the forward into
prepare → per-block segments → finalize, and ``models/loader.carve_stages``
groups contiguous segments into byte-bounded *stages*. Execution on ONE
device:

- params live host-side (``loader.pin_params_host`` — ``pinned_host`` memory
  kind where supported, plain numpy otherwise); prepare/finalize params (the
  small non-block remainder) are placed resident once at build time;
- a double-buffered prefetch ring streams stage *k+1*'s sub-pytree into HBM
  (async ``jax.device_put``) while stage *k*'s jitted program computes;
- stage *k−1*'s buffers are donated back on retirement: once its compute has
  provably finished (the backpressure block below), its device arrays are
  explicitly deleted, so peak HBM ≈ 2 stages of weights + activations;
- backpressure: before dispatching the NEXT prefetch the host blocks on the
  previous stage's output. Without it the async dispatch queue would let the
  host race every transfer into flight at once — exactly the concurrent-
  staging OOM ``mesh.streamed_tree_put`` exists to prevent (round-3
  evidence: flux_16_int8 OOM'd during placement);
- ``overlap=False`` is the debug mode: every transfer and compute is blocked
  to completion in program order, so a failure points at one stage instead of
  an async queue.

Residency is accounted through ``devices.memory.ResidencyTracker`` — tests
assert the 2-stage bound off-hardware (tests/test_streaming.py), the round-3
lesson that no code path may execute first on an unattended live tunnel.

The orchestrator routes here when weights don't fit the HBM budget
(orchestrator.parallelize: weights-don't-fit → stream), and re-carves with
smaller stages on a streaming OOM — the stream-mode analogue of the step-OOM
demotion (any_device_parallel.py:1435-1448; there is nothing below streaming
to demote TO, so the degradation axis is stage size, not device count).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax

from ..devices.memory import ResidencyTracker
from ..models.api import PipelineSpec
from ..models.loader import carve_stages, params_nbytes, pin_params_host
from ..utils import faults, numerics, tracing
from ..utils.logging import get_logger, log_placement
from ..utils.telemetry import instrument_jit, watermark
from .split import partition_kwargs, static_kwargs_key


@dataclasses.dataclass
class _Stage:
    keys: tuple[str, ...]          # top-level param keys this stage streams
    fn: Callable[[Any, dict], dict]  # jitted: all of the stage's segments
    nbytes: int
    labels: tuple[str, ...]


def _delete_buffers(tree) -> None:
    """Donate retired stage buffers back to the allocator immediately.

    Called only after the consuming compute has completed (the backpressure
    block), so ``delete()`` never invalidates an in-flight argument; errors
    are swallowed because deletion is an optimization over refcount-freeing,
    not a correctness requirement."""
    for leaf in jax.tree.leaves(tree):
        try:
            leaf.delete()
        except Exception:
            pass


class StreamingRunner:
    """Callable ``(x, timesteps, context=None, **kwargs) -> output`` executing
    the staged forward on ONE device with double-buffered weight streaming.

    Built once per (spec, params, device, carve); every call re-streams the
    stage weights from host — that is the point: the model's full pytree
    never resides in HBM, only ~2 stages of it at any moment.
    """

    def __init__(
        self,
        spec: PipelineSpec,
        params: Any,
        device: jax.Device,
        *,
        max_stage_bytes: int | None = None,
        n_stages: int | None = None,
        overlap: bool = True,
        host_params_pinned: bool = False,
    ):
        self.device = device
        self.overlap = overlap
        self.tracker = ResidencyTracker()
        self._spec = spec
        self._max_stage_bytes = max_stage_bytes

        def subset(keys):
            missing = [k for k in keys if k not in params]
            if missing:
                raise KeyError(
                    f"pipeline spec references param keys not in the pytree: "
                    f"{missing}"
                )
            return {k: params[k] for k in keys}

        # Host-resident master copy (pinned where supported). The caller may
        # pass an already-pinned pytree (recarve path) to skip the re-pin.
        self._host_params = (
            params if host_params_pinned else pin_params_host(params, device)
        )
        # prepare/finalize params are the small non-block remainder — resident
        # on the device for the runner's lifetime, like the reference's
        # non-block layers that never leave the lead device (SURVEY §3.4).
        self._prepare_params = jax.device_put(
            subset(spec.prepare_keys), device
        )
        self._finalize_params = jax.device_put(
            subset(spec.finalize_keys), device
        )
        self.tracker.add_resident(
            params_nbytes(self._prepare_params)
            + params_nbytes(self._finalize_params)
        )
        self._prepare_jits: dict[tuple, Any] = {}
        self._finalize_jits: dict[tuple, Any] = {}

        ranges = carve_stages(
            spec, self._host_params, max_stage_bytes=max_stage_bytes,
            n_stages=n_stages,
        )
        self.stages: list[_Stage] = []
        for s, e in ranges:
            keys: list[str] = []
            for i in range(s, e):
                for k in spec.segments[i].param_keys:
                    if k not in keys:
                        keys.append(k)
            seg_fns = tuple(spec.segments[i].fn for i in range(s, e))

            def stage_fn(stage_params, carry, _fns=seg_fns):
                for f in _fns:
                    carry = f(stage_params, carry)
                return carry

            self.stages.append(
                _Stage(
                    keys=tuple(keys),
                    # palint: allow[recompile-hazard] the byte-carve range IS
                    # program identity (a re-carve is a new program), bounded
                    # by the carve count
                    fn=instrument_jit(stage_fn, f"stream-stage[{s}:{e})"),
                    nbytes=params_nbytes(
                        {k: self._host_params[k] for k in keys}
                    ),
                    labels=tuple(
                        spec.segments[i].label for i in range(s, e)
                    ),
                )
            )
        log_placement(
            str(device),
            f"weight streaming: {len(self.stages)} stages over "
            f"{len(spec.segments)} segments, max stage "
            f"{max(st.nbytes for st in self.stages) / 2**20:.1f} MiB, "
            f"double-buffered ({'overlap' if overlap else 'no-overlap debug'})",
        )

    # -- introspection -----------------------------------------------------

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    @property
    def max_stage_nbytes(self) -> int:
        return max(st.nbytes for st in self.stages)

    @property
    def streamed_nbytes(self) -> int:
        return sum(st.nbytes for st in self.stages)

    def recarved(self) -> "StreamingRunner | None":
        """A runner over the SAME host-pinned params with stage granularity
        halved — the streaming OOM demotion. None when no STRICTLY finer
        carve exists: at one segment per stage, or when the byte cap is
        pinned by a lone oversized segment (halving the cap then reproduces
        the identical carve — without this progress check the _stream_call
        retry loop would respin a deterministic OOM forever)."""
        if len(self.stages) >= len(self._spec.segments):
            return None
        cap = max(1, self.max_stage_nbytes // 2)
        ranges = carve_stages(
            self._spec, self._host_params, max_stage_bytes=cap
        )
        if len(ranges) <= len(self.stages):
            return None
        return StreamingRunner(
            self._spec, self._host_params, self.device,
            max_stage_bytes=cap, overlap=self.overlap,
            host_params_pinned=True,
        )

    # -- per-static jit caches (the PipelineRunner discipline) -------------

    def _prepare_for(self, static: dict):
        key = static_kwargs_key(static)
        fn = self._prepare_jits.get(key)
        if fn is None:
            prepare = self._spec.prepare
            bound = dict(static)

            def wrapped(params, x, t, context, traced):
                return prepare(params, x, t, context, **traced, **bound)

            fn = instrument_jit(wrapped, "stream-prepare")
            self._prepare_jits[key] = fn
        return fn

    def _finalize_for(self, out_shape: tuple[int, ...]):
        fn = self._finalize_jits.get(out_shape)
        if fn is None:
            finalize = self._spec.finalize

            def wrapped(params, carry):
                return finalize(params, carry, out_shape)

            fn = instrument_jit(wrapped, "stream-finalize")
            self._finalize_jits[out_shape] = fn
        return fn

    # -- the double-buffered schedule --------------------------------------

    def _publish_residency(self) -> None:
        """The pa_hbm_stream_* gauge view of the tracker (utils/metrics.py);
        refreshed at every placement/retirement so /metrics always shows the
        live streamed-weight footprint against its 2-stage bound."""
        try:
            from ..devices.memory import _device_label

            # Same platform:id label vocabulary as the pa_hbm_bytes_* device
            # gauges, so residency joins against capacity on the device label.
            self.tracker.publish_gauges(
                _device_label(self.device),
                bound_bytes=2 * self.max_stage_nbytes,
            )
        except Exception:
            pass

    def _check_stage(self, idx: int, value, where: str = "stream-stage") -> None:
        """Numerics sentinel (utils/numerics.py): per-stage output stats so a
        bad stage is NAMED — called only at boundaries the schedule already
        synchronizes (the backpressure block / the caller's own sync), so the
        sentinel adds no sync of its own to the double-buffered schedule."""
        try:
            nf = numerics.tree_nonfinite(value)
        except Exception:  # noqa: BLE001 — observation must never kill the run
            return
        if nf:
            stage = self.stages[idx] if 0 <= idx < len(self.stages) else None
            numerics.sentinel.record_event(
                where, stage=idx, device=str(self.device), nonfinite=int(nf),
                blocks=",".join(stage.labels) if stage is not None else "",
            )

    def _place_stage(self, idx: int):
        stage = self.stages[idx]
        # Fault site (utils/faults.py): an injected prefetch OOM raises the
        # same RESOURCE_EXHAUSTED shape a real allocator failure would, so
        # the orchestrator's re-carve ladder is rehearsed end to end
        # (chaos runs gate on the prompt still completing).
        act = faults.check("stream-prefetch-oom", key=str(idx))
        if act is not None:
            raise faults.oom_error(act)
        placed = jax.device_put(
            {k: self._host_params[k] for k in stage.keys}, self.device
        )
        self.tracker.place(idx, stage.nbytes)
        self._publish_residency()
        if not self.overlap:
            jax.block_until_ready(placed)
        return placed

    def _retire_stage(self, idx: int, ring: dict) -> None:
        """Drop stage ``idx``'s device buffers — only ever called after its
        compute has completed, so the explicit delete is safe."""
        placed = ring.pop(idx, None)
        if placed is None:
            return
        _delete_buffers(placed)
        self.tracker.retire(idx)
        self._publish_residency()

    def __call__(self, x, timesteps, context=None, **kwargs):
        from ..ops.attention import sequence_ctx_key

        if sequence_ctx_key() is not None:
            raise ValueError(
                "weight streaming does not compose with an active "
                "sequence_parallel context (stage programs are pinned to one "
                "device); exit the context or run a resident placement"
            )
        traced, static = partition_kwargs(kwargs)
        dev = self.device
        trace_on = tracing.on()
        # Span vocabulary (utils/tracing.py): one ``stream-run`` per call;
        # ``stream-stage-prefetch`` per device_put (async issue under
        # overlap, blocking in debug mode); ``stream-prefetch-wait`` for the
        # pre-dispatch block on the CURRENT stage's placed weights — the
        # EXPOSED transfer time double-buffering failed to hide (~0 when
        # overlap works; the ISSUE's blocked-on-prefetch wait). Traced runs
        # only: the compute is data-dependent on the transfer and the host's
        # next action is this dispatch, so the block shifts no work — but an
        # untraced run keeps the original sync-free schedule. ``stream-wait``
        # is the backpressure block on stage k-1's output;
        # ``stream-stage-compute`` runs from dispatch (weights already
        # on-device, so transfer stalls are excluded) to the moment the
        # output is KNOWN done, observed at the next backpressure block.
        # ``trace_aggregates`` turns these into stream_overlap_efficiency.
        t_run0 = tracing.now_us() if trace_on else 0.0
        comp_us = [0.0]  # Σ stage-compute span time → the overlap-eff gauge

        def record_compute(stage_idx: int, ts: float, **attrs) -> None:
            dur = tracing.now_us() - ts
            comp_us[0] += dur
            tracing.record(
                "stream-stage-compute", ts, dur, cat="stream",
                stage=stage_idx, nbytes=self.stages[stage_idx].nbytes,
                **attrs,
            )
        with tracing.span("stream-run", cat="stream", stages=len(self.stages),
                          device=str(dev), overlap=self.overlap):
            with tracing.span("stream-prepare", cat="stream"):
                carry = self._prepare_for(static)(
                    self._prepare_params,
                    jax.device_put(x, dev),
                    jax.device_put(timesteps, dev),
                    jax.device_put(context, dev) if context is not None else None,
                    {k: jax.device_put(v, dev) for k, v in traced.items()},
                )
            with tracing.span("stream-stage-prefetch", cat="stream", stage=0,
                              nbytes=self.stages[0].nbytes,
                              blocking=not self.overlap):
                ring: dict[int, Any] = {0: self._place_stage(0)}
            prev_out = None  # output of stage k-1 — the backpressure handle
            pending = None   # (stage idx, dispatch ts) of the open compute span
            try:
                for k, stage in enumerate(self.stages):
                    if prev_out is not None:
                        # Wait for stage k-1's compute: its weights are provably
                        # consumed (retire donates them) and at most TWO stages
                        # are ever in HBM — without this block the async queue
                        # would admit every remaining prefetch at once.
                        with tracing.span("stream-wait", cat="stream",
                                          stage=k - 1, blocked_on="compute"):
                            # palint: allow[host-sync] the 2-stage HBM
                            # backpressure block — booked as stream-wait,
                            # never compute (the bound's load-bearing sync)
                            jax.block_until_ready(prev_out)
                        if numerics.on():
                            # The output is provably ready (the block above),
                            # so this reduction is pure post-hoc accounting.
                            self._check_stage(k - 1, prev_out)
                        if pending is not None:
                            record_compute(pending[0], pending[1])
                            pending = None
                        self._retire_stage(k - 1, ring)
                        if trace_on:
                            # Per-phase HBM watermark (traced runs only: the
                            # untraced schedule stays probe-free). This is
                            # the boundary where residency is at its 2-stage
                            # peak — the honest sample point.
                            watermark.sample([self.device])
                    if k + 1 < len(self.stages):
                        with tracing.span(
                            "stream-stage-prefetch", cat="stream", stage=k + 1,
                            nbytes=self.stages[k + 1].nbytes,
                            blocking=not self.overlap,
                        ):
                            ring[k + 1] = self._place_stage(k + 1)
                    if trace_on:
                        # EXPOSED transfer: how long stage k's own weights
                        # keep the (otherwise idle) device waiting past this
                        # point. ~0 when double-buffering hid the transfer;
                        # the whole point of the overlap-efficiency number is
                        # that this wait must NOT be booked as compute. The
                        # block is trace-mode-only and shifts no work: the
                        # compute below is data-dependent on these very
                        # buffers, and dispatching it is the host's next act.
                        with tracing.span("stream-prefetch-wait", cat="stream",
                                          stage=k, blocked_on="prefetch"):
                            # palint: allow[host-sync] trace-mode-only block
                            # booking EXPOSED transfer as wait, not compute
                            # (the PR 3 discipline's defining site)
                            jax.block_until_ready(ring[k])
                    t_dispatch = tracing.now_us() if trace_on else 0.0
                    carry = stage.fn(ring[k], carry)
                    if not self.overlap:
                        # palint: allow[host-sync] overlap-off DEBUG mode
                        # serializes by contract (round 6)
                        jax.block_until_ready(carry)
                        if trace_on:
                            record_compute(k, t_dispatch)
                    elif trace_on:
                        pending = (k, t_dispatch)
                    prev_out = carry
                with tracing.span("stream-finalize", cat="stream"):
                    out = self._finalize_for(tuple(x.shape))(
                        self._finalize_params, carry
                    )
                if pending is not None:
                    # The last stage's completion is never awaited here (it
                    # retires by refcount); close its span at finalize
                    # dispatch, marked as an async tail.
                    record_compute(pending[0], pending[1], async_tail=True)
                    pending = None
                if trace_on:
                    # The /metrics twin of the trace-derived aggregate:
                    # fraction of this streamed run spent in stage compute.
                    from ..utils.metrics import registry

                    run_us = tracing.now_us() - t_run0
                    if run_us > 0:
                        registry.gauge(
                            "pa_stream_overlap_efficiency",
                            min(1.0, comp_us[0] / run_us),
                            labels={"device": str(dev)},
                            help="stage-compute fraction of streamed-run wall "
                                 "time (1.0 = transfers fully hidden)",
                        )
                # The last stage retires by refcount once its compute
                # completes — deleting here would need a blocking sync on the
                # output instead.
                last = len(self.stages) - 1
                if last in ring:
                    ring.pop(last)
                    self.tracker.retire(last)
                    self._publish_residency()
                if numerics.on():
                    # Tail check (last stage + finalize — neither is awaited
                    # by the backpressure loop): the sentinel's pull doubles
                    # as the sync the caller was about to perform anyway.
                    self._check_stage(last, out, where="stream-output")
                return out
            finally:
                # Failure path (OOM mid-schedule): release whatever the ring
                # still holds so the recarved retry starts from a clean
                # allocator.
                for idx in list(ring):
                    self._retire_stage(idx, ring)


def build_streaming_runner(
    spec: PipelineSpec | None,
    params: Any,
    device: jax.Device,
    *,
    hbm_budget_bytes: int | None = None,
    n_stages: int | None = None,
    overlap: bool = True,
) -> StreamingRunner | None:
    """Build the weight-streaming runner, or None when the model declares no
    pipeline spec (nothing to carve — the router must then fail placement the
    ordinary way). ``hbm_budget_bytes`` sizes the stages: two buffers plus
    activation headroom must fit, so each stage is capped at 2/5 of the
    budget (2 × 2/5 weights + 1/5 activations/temps). An explicit
    ``n_stages`` (the planner's chosen carve, parallel/planner.py) wins
    over the byte cap only when its byte-balanced carve still fits the
    cap — a planned carve must never widen the double-buffer bound."""
    if spec is None or not spec.segments:
        return None
    max_stage_bytes = None
    if hbm_budget_bytes:
        max_stage_bytes = max(1, int(hbm_budget_bytes) * 2 // 5)
    if n_stages and max_stage_bytes:
        from ..models.loader import carve_ranges, segment_nbytes

        sizes = segment_nbytes(spec, params)
        ranges = carve_ranges(sizes, n_stages=int(n_stages))
        if max(sum(sizes[s:e]) for s, e in ranges) <= max_stage_bytes:
            max_stage_bytes = None  # the planned carve honors the cap
        else:
            n_stages = None  # planned carve would blow the budget; cap rules
    runner = StreamingRunner(
        spec, params, device,
        max_stage_bytes=max_stage_bytes, n_stages=n_stages, overlap=overlap,
    )
    get_logger().info(
        "weight streaming enabled: %.2f GiB streamed + %.2f MiB resident "
        "through %d stages on %s",
        runner.streamed_nbytes / 2**30,
        runner.tracker.resident_bytes / 2**20,
        runner.n_stages, device,
    )
    return runner
