"""Multi-host (DCN) support: process initialization, hybrid meshes, global arrays.

The reference is strictly single-process — its "backend" is threads + PCIe copies in
one interpreter (SURVEY §2f), and multi-node is out of its reach. TPU-natively,
multi-host is the same SPMD program over a bigger mesh: ``jax.distributed`` brings up
the process group over DCN, every process contributes its local chips, and XLA routes
collectives over ICI within a slice and DCN across slices. These helpers wrap that
bring-up so the rest of the framework (orchestrator, sequence parallel) is
host-count-agnostic:

- ``initialize_distributed`` — env-driven ``jax.distributed.initialize`` (no-op when
  single-process or already initialized);
- ``hybrid_mesh`` — (dcn_axis, ici_axes) mesh via ``mesh_utils`` so the slow axis
  (usually ``data``) crosses hosts and fast axes (``seq``/``model``) stay on ICI;
- ``host_local_batch`` — per-host input shards → one global jax.Array
  (``jax.make_array_from_process_local_data``), the multi-host analogue of the
  host-side scatter in the orchestrator's hybrid path.
"""

from __future__ import annotations

import os
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.logging import get_logger
from .mesh import AXIS_DATA


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Bring up the JAX process group. Returns True when running multi-process.

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``); on TPU pods with all three absent,
    ``jax.distributed.initialize()`` auto-detects from the TPU metadata. A plain
    single-process run (no env, no args, no TPU pod) is a no-op.
    """
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_str = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(num_str) if num_str else None
    )
    pid_str = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(pid_str) if pid_str else None
    )
    if coordinator_address is None and num_processes is None:
        return jax.process_count() > 1  # single-process (or already initialized)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Already initialized — idempotent bring-up. JAX phrases this as
        # "distributed.initialize should only be called once".
        msg = str(e).lower()
        if "once" not in msg and "already" not in msg:
            raise
    get_logger().info(
        "distributed: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )
    return jax.process_count() > 1


def process_count() -> int:
    return jax.process_count()


def is_multihost() -> bool:
    return jax.process_count() > 1


def hybrid_mesh(
    ici_axes: dict[str, int] | None = None,
    dcn_axis: str = AXIS_DATA,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh whose ``dcn_axis`` spans processes (slow, DCN) and whose ``ici_axes``
    split each process's local devices (fast, ICI).

    Single-process: degenerates to a mesh over the local devices with the same axis
    names (dcn axis size 1 — callers need no special-casing). Example on 4 hosts ×
    8 chips: ``hybrid_mesh({"seq": 8})`` → mesh {"data": 4, "seq": 8} where batch
    sharding crosses DCN and sequence parallelism stays on ICI.
    """
    ici_axes = dict(ici_axes) if ici_axes else {}
    devices = list(devices) if devices is not None else jax.devices()
    n_proc = jax.process_count()
    if len(devices) != n_proc * (len(devices) // n_proc) or (
        n_proc > 1 and len(devices) != jax.device_count()
    ):
        # Multi-process meshes must span the GLOBAL device list (every process
        # passes the same jax.devices()); a jax.local_devices() subset would
        # shape the mesh for n_proc× more devices than it holds.
        raise ValueError(
            f"devices must be the global device list across all {n_proc} "
            f"processes (got {len(devices)}, expected {jax.device_count()}); "
            "pass jax.devices(), not jax.local_devices()"
        )
    local = len(devices) // n_proc
    ici_total = 1
    for v in ici_axes.values():
        ici_total *= v
    if local % ici_total:
        raise ValueError(
            f"ici axes {ici_axes} do not divide the {local} per-process devices"
        )
    # Remaining local parallelism folds into the dcn axis (data sharding within a
    # host is still ICI-fast; the axis is simply "everything that isn't an inner
    # axis"), matching the common data-outer/model-inner recipe.
    dcn_size = n_proc * (local // ici_total)
    if is_multihost():
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(local // ici_total, *ici_axes.values()),
            dcn_mesh_shape=(n_proc, *([1] * len(ici_axes))),
            devices=devices,
        ).reshape(dcn_size, *ici_axes.values())
    else:
        arr = np.array(devices, dtype=object).reshape(dcn_size, *ici_axes.values())
    return Mesh(arr, (dcn_axis, *ici_axes.keys()))


def host_local_batch(
    local_array: np.ndarray, mesh: Mesh, axis: str = AXIS_DATA
) -> jax.Array:
    """Per-process input shard → one global array sharded on ``axis``.

    Each process passes its own slice of the global batch (dim0); the result is a
    single jax.Array whose global dim0 is the concatenation across processes —
    the DCN-scale analogue of the reference's host-side torch.split scatter
    (1222-1250). Single-process: equivalent to ``device_put`` with the sharding.
    """
    sharding = NamedSharding(mesh, P(axis))
    if not is_multihost():
        return jax.device_put(np.asarray(local_array), sharding)
    global_dim0 = local_array.shape[0] * jax.process_count()
    global_shape = (global_dim0, *local_array.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_array), global_shape
    )
