"""Pure split arithmetic: weight normalization, weighted batch splits, memory-blended
weights, pipeline block ranges, and pytree batch chunking.

These are the deterministic, device-free kernels of the scheduler, extracted test-first
(SURVEY §4, §7 step 2). Reference semantics with citations into
any_device_parallel.py:

- weight normalization ``pct/sum`` with sum<=0 abort ............ 1019-1027
- static DP split ``max(1, int(batch*w))``, last-takes-remainder . 1317-1322
- VRAM-blended weights ``0.7*user + 0.3*mem_share`` .............. 737-766
- pipeline block ranges, last device absorbs remainder ........... 1168-1178
- batch size probe (tensor dim0 / first tensor in container / 1) . 1210-1220
- batch split on dim0, non-tensors replicated .................... 1222-1250
- kwargs rule: split iff leaf dim0 == batch, else broadcast ...... 1252-1267
- result concat on dim0, tuple outputs element-wise, non-tensors
  passed through from chunk 0 ................................... 1269-1285

Documented divergence from the reference (deliberate bug fixes, SURVEY §7 step 2):
the reference's static path can produce sum(split) != batch — ``max(1, int(b*w))`` can
overshoot when many small weights each round up to 1, and the CPU-only VRAM path
(738-739) has no remainder fixup at all. Here every integer split goes through a
largest-remainder apportionment that always sums exactly to the total with sizes >= 0;
zero-size assignments mean "device inactive for this batch" and are dropped by the
caller, mirroring the reference's active-device list (1324-1337).
"""

from __future__ import annotations

from collections.abc import Mapping, Sequence
from typing import Any

import jax
import numpy as np


# --------------------------------------------------------------------------------------
# Weights
# --------------------------------------------------------------------------------------


def normalize_weights(percentages: Sequence[float]) -> tuple[float, ...] | None:
    """``pct_i / sum(pct)``; None when ``sum <= 0`` (caller aborts, parity 1019-1027)."""
    total = float(sum(percentages))
    if total <= 0.0:
        return None
    return tuple(float(p) / total for p in percentages)


def blend_memory_weights(
    user_weights: Sequence[float],
    free_bytes: Sequence[int],
    alpha: float = 0.7,
) -> tuple[float, ...]:
    """Blend user weights with live free-memory shares: ``alpha*user + (1-alpha)*mem``.

    Parity: auto_split_batch (737-766) blends 0.7*user_weight + 0.3*vram_share
    (753-759) and renormalizes (761-762). When no device reports memory (CPU-only
    chain), returns the user weights unchanged (738-739).
    """
    if len(user_weights) != len(free_bytes):
        raise ValueError("user_weights and free_bytes must have equal length")
    total_free = float(sum(free_bytes))
    if total_free <= 0.0:
        return tuple(float(w) for w in user_weights)
    blended = [
        alpha * float(w) + (1.0 - alpha) * (float(f) / total_free)
        for w, f in zip(user_weights, free_bytes)
    ]
    norm = normalize_weights(blended)
    assert norm is not None  # blended sum > 0 because alpha > 0 and sum(user) == 1
    return norm


def blend_speed_weights(
    user_weights: Sequence[float],
    step_times_s: Sequence[float],
    alpha: float = 0.7,
) -> tuple[float, ...]:
    """Blend user weights with per-device SPEED shares:
    ``alpha*user + (1-alpha)*inverse-step-time share`` — the memory blend's
    twin over the roofline platform specs (``utils/roofline.
    nominal_step_time_s``), closing the ROADMAP "speed-aware hybrid
    blending" carry-over: the banked hybrid_sd15 (82.6 s/it) showed a
    VRAM-only split hands a ~40x-slower CPU link work as if it were an
    equal peer.

    Homogeneous chains are a NO-OP by construction (all step times equal →
    user weights returned unchanged), so even SPMD sharding and explicit
    user splits on same-platform meshes are never perturbed — only
    heterogeneous chains, where unequal speed is the whole point, shift.
    Zero/negative times (no spec) also fall back to the user weights."""
    if len(user_weights) != len(step_times_s):
        raise ValueError("user_weights and step_times_s must have equal length")
    times = [float(t) for t in step_times_s]
    if not times or min(times) <= 0.0 or max(times) == min(times):
        return tuple(float(w) for w in user_weights)
    inv = [1.0 / t for t in times]
    total = sum(inv)
    blended = [
        alpha * float(w) + (1.0 - alpha) * (s / total)
        for w, s in zip(user_weights, inv)
    ]
    norm = normalize_weights(blended)
    assert norm is not None  # alpha > 0 and sum(user) == 1
    return norm


# --------------------------------------------------------------------------------------
# Integer apportionment
# --------------------------------------------------------------------------------------


def largest_remainder_split(total: int, weights: Sequence[float]) -> tuple[int, ...]:
    """Apportion ``total`` items over ``weights`` so sizes are >= 0 and sum exactly to
    ``total`` (largest-remainder / Hamilton method).

    This replaces the reference's ``max(1, int(batch*w))`` + last-takes-remainder
    (1317-1322), which can overflow the batch; divergence documented in the module
    docstring.
    """
    if total < 0:
        raise ValueError(f"total must be >= 0, got {total}")
    n = len(weights)
    if n == 0:
        return ()
    wsum = float(sum(weights))
    if wsum <= 0.0:
        # Degenerate: treat as even split.
        weights = [1.0] * n
        wsum = float(n)
    quotas = [total * float(w) / wsum for w in weights]
    sizes = [int(q) for q in quotas]
    short = total - sum(sizes)
    # Hand the shortfall to the largest fractional remainders; ties break toward the
    # earlier (higher-priority) link, matching the reference's lead-device-first order.
    order = sorted(range(n), key=lambda i: (-(quotas[i] - sizes[i]), i))
    for i in order[:short]:
        sizes[i] += 1
    return tuple(sizes)


def weighted_batch_split(batch: int, weights: Sequence[float]) -> tuple[int, ...]:
    """Per-device batch sizes for the DP path. Sizes may be 0 (device inactive); the
    caller drops those, mirroring the active-device list at 1324-1337."""
    return largest_remainder_split(batch, weights)


def block_ranges(n_blocks: int, weights: Sequence[float]) -> tuple[tuple[int, int], ...]:
    """Contiguous half-open ``[start, end)`` block ranges per device, proportional to
    weights (parity: 1168-1178 — last device absorbs the remainder; here the
    largest-remainder fix distributes it, divergence documented above). Ranges of zero
    length are valid and mean the device holds no pipeline stage."""
    sizes = largest_remainder_split(n_blocks, weights)
    ranges = []
    start = 0
    for s in sizes:
        ranges.append((start, start + s))
        start += s
    return tuple(ranges)


# --------------------------------------------------------------------------------------
# Pytree batch chunking (host-side path: hybrid chains + parity tests)
# --------------------------------------------------------------------------------------


def _is_array(x: Any) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def is_arraylike(v: Any) -> bool:
    """Duck-typed array check (tracers included) — broader than ``_is_array``,
    which the split-math above keeps strict so jit statics never split."""
    return hasattr(v, "shape") and hasattr(v, "dtype")


def pad_leaf(a, pad: int):
    """Pad dim0 by repeating the last element (sliced off after the SPMD call)."""
    if pad == 0:
        return a
    import jax.numpy as jnp

    return jnp.concatenate([a, jnp.repeat(a[-1:], pad, axis=0)], axis=0)


def slice_padded(out, batch: int, padded: int):
    """Un-pad: slice dim0 back to ``batch`` on every array leaf that carries the
    padded batch dimension (dicts/tuples/lists handled by tree mapping)."""
    if padded == batch:
        return out

    def fix(leaf):
        if is_arraylike(leaf) and leaf.ndim > 0 and leaf.shape[0] == padded:
            return leaf[:batch]
        return leaf

    return jax.tree.map(fix, out)


def batch_size_of(x: Any) -> int:
    """Batch size of a forward input: dim0 of an array, else dim0 of the first array
    inside a list/tuple, else 1 (parity: get_batch_size, 1210-1220)."""
    if _is_array(x) and x.ndim > 0:
        return int(x.shape[0])
    if isinstance(x, (list, tuple)):
        for item in x:
            if _is_array(item) and item.ndim > 0:
                return int(item.shape[0])
    return 1


def _split_array(x: Any, sizes: Sequence[int]) -> list[Any]:
    offsets = np.cumsum([0] + list(sizes))
    return [x[offsets[i] : offsets[i + 1]] for i in range(len(sizes))]


def split_tree(x: Any, sizes: Sequence[int]) -> list[Any]:
    """Split a value into len(sizes) chunks along dim0.

    Arrays split on dim0; lists/tuples split element-wise; dicts split value-wise;
    anything else is replicated to every chunk (parity: split_batch / move semantics,
    1222-1250 — non-tensor elements of containers are replicated).
    """
    n = len(sizes)
    if _is_array(x) and x.ndim > 0 and x.shape[0] == sum(sizes):
        return _split_array(x, sizes)
    if isinstance(x, (list, tuple)):
        per_item = [split_tree(item, sizes) for item in x]
        return [type(x)(item[i] for item in per_item) for i in range(n)]
    if isinstance(x, Mapping):
        per_key = {k: split_tree(v, sizes) for k, v in x.items()}
        return [{k: v[i] for k, v in per_key.items()} for i in range(n)]
    return [x] * n


def split_kwargs(
    kwargs: Mapping[str, Any], batch: int, sizes: Sequence[int]
) -> list[dict[str, Any]]:
    """Per-chunk kwargs: a kwarg splits iff it is an array whose dim0 == batch;
    everything else broadcasts to every chunk (parity: split_kwargs, 1252-1267)."""
    n = len(sizes)
    out: list[dict[str, Any]] = [dict() for _ in range(n)]
    for k, v in kwargs.items():
        if _is_array(v) and v.ndim > 0 and v.shape[0] == batch:
            for i, chunk in enumerate(_split_array(v, sizes)):
                out[i][k] = chunk
        else:
            for i in range(n):
                out[i][k] = v
    return out


def partition_kwargs(kwargs: Mapping[str, Any]) -> tuple[dict, dict]:
    """Split kwargs into (traced, static): arrays trace through jit, everything else
    is compile-time baked — one compiled program per distinct static combination
    (the reference forwards all kwargs dynamically into torch, 1348-1356, which is
    meaningless under XLA tracing)."""
    traced, static = {}, {}
    for k, v in kwargs.items():
        (traced if _is_array(v) else static)[k] = v
    return traced, static


def static_kwargs_key(static: Mapping[str, Any]) -> tuple:
    """Hashable cache key for a static-kwargs dict. Unhashable values key by id() —
    safe only because every cache entry's compiled closure holds the value strongly,
    so its id cannot be reused by a different object while the entry lives."""
    items = []
    for k in sorted(static):
        v = static[k]
        try:
            hash(v)
        except TypeError:
            v = id(v)
        items.append((k, v))
    return tuple(items)


def concat_results(chunks: Sequence[Any]) -> Any:
    """Concatenate per-device outputs along dim0.

    Arrays concat on dim0; tuple/list outputs concat element-wise; non-array outputs
    pass through from chunk 0 (parity: concatenate_results, 1269-1285).
    """
    if not chunks:
        raise ValueError("no chunks to concatenate")
    first = chunks[0]
    if _is_array(first):
        import jax.numpy as jnp

        return jnp.concatenate(list(chunks), axis=0)
    if isinstance(first, (list, tuple)):
        return type(first)(
            concat_results([c[i] for c in chunks]) for i in range(len(first))
        )
    if isinstance(first, Mapping):
        return {k: concat_results([c[k] for c in chunks]) for k in first}
    return first
