"""The orchestrator: wrap a diffusion model once, run every step parallel.

This is the TPU-native counterpart of ParallelAnything.setup_parallel + the injected
``parallel_forward`` closure (any_device_parallel.py:917-1471). The reference clones the
torch module to every device and monkey-patches ``model.forward`` with a thread-fan-out
scheduler; here the model is a pure apply function + parameter pytree, "replication" is
a `NamedSharding` placement, and the per-step scheduler is a routing table in front of
jit-compiled SPMD programs.

Routing parity (parallel_forward, 1287-1315):

- ``batch == 1`` and ``workload_split``  → pipeline block-placement mode (1295-1305)
- ``batch < active devices`` or ``not workload_split`` → single-device (1307-1315)
- otherwise → data parallel (1317-1433)
- OOM at a step → aggressive cleanup, then whole-batch single-device retry (1435-1448)

Setup parity (setup_parallel):

- weight normalization with sum<=0 abort → model returned unchanged (1019-1027)
- memory-aware weight blending 0.7/0.3 (737-766) — measured ONCE at setup, because on
  TPU every new split shape is a recompile (SURVEY §7 hard part 3); the reference
  re-reads VRAM every step at zero cost, which XLA's compilation model forbids.
- placement OOM → drop a device, renormalize survivors, retry (1114-1128). The SPMD
  analogue drops the *last* chain device (an SPMD placement fails as a whole, so the
  specific failing device is unobservable — documented divergence); surviving weights
  renormalize and the model's reported chain reflects only survivors.
- teardown/lifecycle (211-282, 1459) → ``ParallelModel.cleanup()`` + GC.

Documented divergences from the reference (deliberate):

- Step-OOM demotes the model to single-device execution *permanently* (until
  ``reactivate()``), freeing the replicated params first. The reference retries the
  parallel path every step (1435-1448) — cheap on CUDA, but on TPU an OOM for a given
  shape is deterministic, so retrying re-OOMs every sampler step.
- When ``1 < batch < n_devices`` the reference drops to a single device (1307-1315);
  default here pads the batch up to the mesh size instead (``pad_small_batches=True``)
  so e.g. batch=4 on 8 cores still runs 4-way faster than one core. Set it False for
  strict parity.
- Non-array kwargs (strings, bools, python objects) are treated as *static*: baked
  into the compiled program, one compile per distinct combination. The reference
  forwards them dynamically into torch (1348-1356) — meaningless under XLA tracing.

Weighted splits on homogeneous meshes degenerate to even SPMD sharding (uneven splits
only exist to serve devices of unequal speed/memory; TPU cores are identical). Weighted
splits survive for heterogeneous chains (e.g. tpu+cpu), executed as one SPMD program
per platform group with a host-side weighted scatter/concat — the one place the
reference's fan-out shape survives (SURVEY §7 hard part 1).
"""

from __future__ import annotations

import dataclasses
import weakref
from collections.abc import Callable, Mapping, Sequence
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..devices.discovery import device_platform
from ..devices.memory import free_memory_bytes
from ..utils.cleanup import aggressive_cleanup
from ..utils.logging import (
    get_logger,
    log_degradation,
    log_placement,
    log_setup_summary,
)
from .chain import DeviceChain, DeviceLink
from .mesh import AXIS_DATA, build_mesh, place_params, place_params_fsdp
from .split import (
    batch_size_of,
    pad_leaf as _pad_leaf,
    slice_padded as _slice_padded,
    blend_memory_weights,
    blend_speed_weights,
    largest_remainder_split,
    normalize_weights,
    partition_kwargs,
    split_kwargs,
    split_tree,
    static_kwargs_key,
    concat_results,
)


def _is_resource_exhausted(err: BaseException) -> bool:
    msg = str(err)
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg or "OOM" in msg


def _gc_teardown(purge_cache: bool, purge_models: bool) -> None:
    """Finalizer body — the reference's cleanup_parallel_model fired from
    weakref.finalize (any_device_parallel.py:1459, 211-282). Runs only when a
    ParallelModel is garbage-collected without an explicit cleanup(). Must be
    shutdown-safe: finalizers also fire at interpreter exit, when log streams
    may already be closed and module state torn down."""
    import sys

    if sys.is_finalizing():
        return  # process exit frees everything anyway
    try:
        logger = get_logger()
        # A test harness (or daemonized host) may have closed the stream a
        # handler holds before GC runs; logging would print an internal error
        # rather than raise, so check explicitly.
        streams_ok = all(
            not getattr(getattr(h, "stream", None), "closed", False)
            for h in logger.handlers
        )
        if streams_ok:
            logger.info("parallel model garbage-collected; teardown per purge flags")
        if purge_cache:
            aggressive_cleanup(clear_compile_cache=purge_models)
    except Exception:
        pass


def _is_arraylike(v) -> bool:
    return isinstance(v, (jax.Array, np.ndarray))


def _pad_tree(tree, batch, padded):
    """Repeat-pad every batch-dim array leaf of a tree from ``batch`` to
    ``padded`` rows (non-arrays and non-batch leaves pass through)."""
    if padded == batch:
        return tree
    return jax.tree.map(
        lambda l: _pad_leaf(l, padded - batch)
        if _is_arraylike(l) and l.ndim > 0 and l.shape[0] == batch
        else l,
        tree,
    )


def _device_step_times(devices) -> list[float]:
    """Per-device nominal step time from the roofline platform specs
    (utils/roofline.nominal_step_time_s) — the speed signal
    ``blend_speed_weights`` folds into heterogeneous-chain splits. Reads
    only static spec tables: no device work, no measurement, so it is safe
    at setup time (the reference re-reads VRAM per step; specs don't move)."""
    from ..utils import roofline

    return [
        roofline.nominal_step_time_s(
            getattr(d, "device_kind", "") or "",
            getattr(d, "platform", "cpu") or "cpu",
        )
        for d in devices
    ]


def _split_inputs(batch, sizes, x, timesteps, context, kwargs):
    """Per-chunk (x, timesteps, context, kwargs) under the shared
    split-or-broadcast contract: a value splits on dim0 iff it carries the
    batch, else it broadcasts to every chunk (parity 1252-1267). One
    implementation for the hybrid scatter and microbatched pipeline paths."""
    xs = split_tree(x, sizes)
    ts = (
        split_tree(timesteps, sizes)
        if batch_size_of(timesteps) == batch
        else [timesteps] * len(sizes)
    )
    cs = (
        split_tree(context, sizes)
        if context is not None and batch_size_of(context) == batch
        else [context] * len(sizes)
    )
    kws = split_kwargs(kwargs, batch, sizes)
    return list(zip(xs, ts, cs, kws))


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """The orchestrator's knobs — exactly the reference's widget surface (SURVEY §5.6).

    ``workload_split``     — enable batch splitting / pipeline mode (893-896, default True)
    ``auto_memory_balance`` — blend user weights with free device memory (897-900;
        widget default True wins over the python-signature default False, SURVEY §5.6)
    ``purge_cache`` / ``purge_models`` — cleanup aggressiveness at teardown (901-908)
    ``pad_small_batches``  — see "documented divergences" in the module docstring
    ``weight_sharding``    — "replicate" (reference parity: full model per device,
        README.md:167), "fsdp" (shard each weight over the data axis; required
        when the model doesn't fit one chip — e.g. FLUX-dev bf16 on v5e), or
        "stream" (weights stay host-pinned and stream through the lead device
        double-buffered — parallel/streaming.py; the single-chip answer when
        even 1/N of the sharded model, or a chip to shard over, is missing)
    ``tensor_parallel``    — size of the ``model`` mesh axis; >1 builds a 2-D
        (data × model) mesh per group and shards weights over ``model`` so XLA
        partitions the matmuls themselves (GSPMD TP). Must divide each group's
        device count; composes with batch sharding, not with fsdp.
    """

    workload_split: bool = True
    auto_memory_balance: bool = True
    # Blend per-platform nominal step time (utils/roofline.py platform
    # specs) into heterogeneous-chain weights the way free memory is
    # blended above (round 17, ROADMAP "speed-aware hybrid blending"): a
    # tpu+cpu chain's split must reflect that the CPU is ~40x SLOWER, not
    # that it has spare RAM. Homogeneous chains are a structural no-op
    # (equal specs → equal speed shares → user weights unchanged).
    auto_speed_balance: bool = True
    purge_cache: bool = True
    purge_models: bool = False
    data_axis: str = AXIS_DATA
    pad_small_batches: bool = True
    weight_sharding: str = "replicate"
    tensor_parallel: int = 1
    # After a step-OOM demotion, automatically attempt reactivate() once this
    # many single-device steps have run (None = permanent demotion until manual
    # reactivate()/rebalance(), the documented default — an XLA OOM for a given
    # shape is deterministic, so eager per-step retry like the reference's
    # 1435-1448 would re-OOM every step; a counted backoff lets a TRANSIENT
    # host-side RESOURCE_EXHAUSTED — e.g. during a hybrid-chain host concat —
    # stop permanently serializing a long run). On a failed attempt the counter
    # restarts, giving exponential-free periodic retry.
    reactivate_after: int | None = None
    # Weight-streaming knobs (weight_sharding="stream", or the automatic
    # weights-don't-fit routing in parallelize):
    # ``hbm_budget_bytes`` — device HBM budget the placement decision and the
    #   stage carve use; None reads devices.memory.usable_hbm_bytes (the
    #   PA_HBM_BUDGET_BYTES override, else 90% of reported capacity). On
    #   backends reporting no memory (CPU tests) pass it explicitly.
    # ``stream_overlap`` — False serializes every transfer/compute (the
    #   streaming debug mode; parallel/streaming.py module docstring).
    hbm_budget_bytes: int | None = None
    stream_overlap: bool = True
    # Planner-chosen stream stage COUNT (parallel/planner.py stream-carve
    # axis): when set, the streaming runner carves byte-balanced into this
    # many stages instead of the budget-derived byte cap — but only if the
    # resulting largest stage still fits the 2-buffer budget
    # (build_streaming_runner falls back to the cap otherwise). None (the
    # default, and the PA_PLANNER=0 behavior) keeps the hand carve.
    stream_stages: int | None = None
    # >1 enables GPipe-style THROUGHPUT pipelining for batch>1 (beyond the
    # reference, whose pipeline mode is batch==1 layer placement only, SURVEY
    # §2e): the batch splits into this many microbatches streamed through the
    # per-device stage programs without host blocking — XLA's per-device
    # execution queues overlap microbatch j's later stages with j+1's earlier
    # ones. Useful when weights are stage-placed because a full replica does
    # not fit (the FSDP alternative without per-step all-gather traffic).
    pipeline_microbatches: int = 0


@dataclasses.dataclass
class _PlatformGroup:
    """One homogeneous sub-program: a mesh over same-platform devices + placed params.

    ``device_strs``/``device_weights`` stay index-aligned with ``devices`` so that
    dropping a device on placement OOM also drops its workload share (the reference's
    renormalize-survivors, 1114-1128).
    """

    platform: str
    devices: list[jax.Device]
    device_strs: list[str]
    device_weights: list[float]
    # Pre-blend user weights, kept so rebalance() can re-blend against *fresh*
    # memory readings instead of compounding blend-on-blend drift.
    user_weights: list[float] = dataclasses.field(default_factory=list)
    mesh: Any = None
    params: Any = None  # pytree placed replicated on this group's mesh

    @property
    def weight(self) -> float:
        return float(sum(self.device_weights))

    def drop_last_device(self) -> str:
        self.mesh = None
        self.params = None
        self.devices.pop()
        self.device_weights.pop()
        if self.user_weights:
            self.user_weights.pop()
        return self.device_strs.pop()


def _group_mesh(devices, config: "ParallelConfig"):
    """1-D data mesh, or 2-D (data x model) when tensor_parallel > 1."""
    n = len(devices)
    tp = max(1, int(config.tensor_parallel))
    if tp == 1:
        return build_mesh(devices, {config.data_axis: n})
    if config.weight_sharding == "fsdp":
        raise ValueError("tensor_parallel does not compose with weight_sharding='fsdp'")
    if n % tp:
        raise ValueError(
            f"tensor_parallel={tp} does not divide the group's {n} device(s)"
        )
    from .mesh import AXIS_MODEL

    return build_mesh(devices, {config.data_axis: n // tp, AXIS_MODEL: tp})


def _place_for(config: "ParallelConfig", params, mesh):
    """Single placement policy for setup, _place and reactivate: returns
    (placed_pytree, description)."""
    if config.weight_sharding == "fsdp":
        return (
            place_params_fsdp(params, mesh, config.data_axis),
            "fsdp-sharded parameter pytree",
        )
    if config.tensor_parallel > 1:
        from .mesh import place_params_tp

        return (
            place_params_tp(params, mesh),
            f"tensor-parallel parameter pytree (model axis ×{config.tensor_parallel})",
        )
    return place_params(params, mesh), "replicated parameter pytree"


class ParallelModel:
    """The wrapped model: call it like the model's forward, it routes and runs SPMD.

    Callable as ``model(x, timesteps, context=None, **kwargs)`` — the diffusion forward
    convention the reference's injected forward assumes (1287), batch dim is dim0.
    """

    def __init__(
        self,
        apply_fn: Callable[..., Any],
        params: Any,
        chain: DeviceChain,
        config: ParallelConfig,
        groups: list[_PlatformGroup],
        weights: tuple[float, ...],
        pipeline_spec: Any = None,
        model_config: Any = None,
        sampler_prefs: dict | None = None,
        streaming: bool = False,
        plan: dict | None = None,
    ):
        self._apply = apply_fn
        self._host_params = params
        self.chain = chain
        self.config = config
        # Weight-streaming mode (weights-don't-fit routing or an explicit
        # weight_sharding="stream"): groups hold NO placed params; every call
        # routes through the double-buffered StreamingRunner on the lead
        # device (parallel/streaming.py) and the full pytree never exists in
        # HBM — so neither the lead-copy fallback nor whole-loop compilation
        # may ever materialize it.
        self._stream = bool(streaming)
        self._stream_runner: Any = None
        # The wrapped model's own config (FluxConfig/UNetConfig/...), distinct from
        # the ParallelConfig above — pipelines read patch_size etc. through this.
        self.model_config = model_config
        # Model-level sampling preferences carried through from the wrapped
        # model (api.DiffusionModel.sampler_prefs) — samplers read them here.
        self.sampler_prefs = sampler_prefs
        self._groups = groups
        self.weights = weights
        # The planner decision this wrap routed through (parallel/planner.py)
        # — None when PA_PLANNER=0, the chain was ineligible (hybrid
        # multi-group, pinned fsdp/tp), or the planner predates this model.
        # bench.py reads it onto the JSON line; /health's ``plan`` section
        # shows the process-wide last decision.
        self.plan = plan
        self._pipeline_spec = pipeline_spec
        self._pipeline_runner: Any = None  # built lazily on first pipeline-path use
        self._jits: dict[tuple, Callable] = {}
        self._lead_params = None  # lazy single-device placement (fallback path)
        self.active = True
        self._steps_demoted = 0  # single-device steps since a step-OOM demotion
        self._demoted = False    # active=False via step-OOM (reactivatable)
        self._cleaned = False    # active=False via cleanup() (terminal)
        # GC-teardown parity (any_device_parallel.py:1459 registers
        # weakref.finalize(model, cleanup_parallel_model, ...)): a host graph
        # that simply DROPS the wrapped MODEL — exactly the ComfyUI pattern the
        # reference defends against — still honors the purge flags. The placed
        # arrays themselves free by refcount with the instance; the finalizer's
        # job is the cache purges + the teardown log event. It must not hold
        # ``self`` (that would keep the model alive forever), so it captures
        # only the two flags.
        self._finalizer = weakref.finalize(
            self, _gc_teardown, config.purge_cache, config.purge_models
        )

    # -- introspection (parity with the reference's tag attrs, 1452-1457) ----------

    @property
    def devices(self) -> tuple[str, ...]:
        return tuple(s for g in self._groups for s in g.device_strs)

    @property
    def lead_device(self) -> jax.Device:
        return self._groups[0].devices[0]

    @property
    def n_devices(self) -> int:
        return sum(len(g.devices) for g in self._groups)

    @property
    def is_streaming(self) -> bool:
        """True when this model executes via the weight-streaming runner
        (weights host-pinned, double-buffered through the lead device)."""
        return self._stream

    # -- compiled-apply cache ------------------------------------------------------

    def _jit_for(self, static: Mapping[str, Any]) -> Callable:
        # The ambient sequence_parallel context is read at trace time inside
        # ops.attention, so it must be part of the compile-cache key — otherwise
        # whichever context was active at first trace would be silently baked in.
        from ..ops.attention import sequence_ctx_key

        key = (sequence_ctx_key(), static_kwargs_key(static))
        fn = self._jits.get(key)
        if fn is None:
            apply = self._apply
            bound = dict(static)

            def wrapped(params, x, t, context, traced_kwargs):
                return apply(params, x, t, context, **traced_kwargs, **bound)

            from ..utils.telemetry import instrument_jit

            fn = instrument_jit(wrapped, "parallel-apply")
            self._jits[key] = fn
        return fn

    # -- execution -----------------------------------------------------------------

    def _data_width(self) -> int:
        """Total size of the data axis across groups — the unit batch routing
        compares against (== device count for 1-D meshes; smaller under TP)."""
        return sum(
            g.mesh.shape[self.config.data_axis] if g.mesh is not None else len(g.devices)
            for g in self._groups
        )

    def __call__(self, x, timesteps, context=None, **kwargs):
        from ..ops.attention import sequence_ctx_key

        if self._stream:
            # Weight streaming is the ONLY placement that fits — every batch
            # size, every path (the demote/single fallbacks below would
            # re-materialize the full pytree on one chip, the thing that
            # cannot exist).
            return self._stream_call(x, timesteps, context, kwargs)
        if not self.active:
            ra = self.config.reactivate_after
            if (
                self._demoted
                and not self._cleaned
                and ra is not None
                and self._steps_demoted >= ra
            ):
                # N single-device steps have RUN since the demotion; this call
                # attempts the parallel path again. Gated on _demoted so an
                # explicitly cleaned-up model is never resurrected behind the
                # user's back.
                ran = self._steps_demoted
                try:
                    self.reactivate()
                    log_degradation(
                        "reactivate",
                        f"parallel execution resumed after {ran} "
                        "single-device step(s)",
                    )
                except Exception as e:  # noqa: BLE001
                    if not _is_resource_exhausted(e):
                        raise
                    # Still too tight — stay demoted, retry in another N steps.
                    self._steps_demoted = 0
            if not self.active:
                self._steps_demoted += 1
                return self.single(x, timesteps, context, **kwargs)
        batch = batch_size_of(x)
        n = self._data_width()
        try:
            if self.config.tensor_parallel > 1 and self.config.workload_split:
                # TP premise: weights only fit sharded — pipeline stage placement
                # and lead-device fallbacks would re-materialize full weights.
                # Every batch (incl. batch==1, where the data axis may be 1) runs
                # the sharded program.
                return self._data_parallel(batch, x, timesteps, context, kwargs)
            mb = self.config.pipeline_microbatches
            if mb > 1 and self.config.workload_split and batch >= mb and n > 1:
                # Opt-in GPipe-style throughput pipelining (see ParallelConfig):
                # microbatches stream through the stage chain; async dispatch
                # overlaps them across stage devices. Falls through to normal
                # routing when the model declares no pipeline spec or a
                # sequence_parallel context pins the attention mesh.
                if sequence_ctx_key() is None:
                    runner = self._get_pipeline_runner()
                    if runner is not None:
                        return self._pipeline_microbatch(
                            runner, mb, batch, x, timesteps, context, kwargs
                        )
            if batch == 1 and self.config.workload_split and n > 1:
                # Pipeline block-placement mode (reference 1295-1305); a model that
                # declares no stages runs single-device (1156-1166) — padded DP on a
                # 1-sample batch would just compute the same sample on every device.
                # Under an active sequence_parallel context the pipeline is skipped
                # entirely: stage programs are pinned to single devices and cannot
                # host a seq-mesh shard_map — the single-device path (whose jit
                # cache IS ctx-keyed) lets the requested context parallelism run.
                if sequence_ctx_key() is None:
                    runner = self._get_pipeline_runner()
                    if runner is not None:
                        return runner(x, timesteps, context, **kwargs)
                return self.single(x, timesteps, context, **kwargs)
            if not self.config.workload_split or n <= 1:
                return self.single(x, timesteps, context, **kwargs)
            if batch < n and not self.config.pad_small_batches:
                # Strict parity: batch < devices → single device (1307-1315).
                return self.single(x, timesteps, context, **kwargs)
            return self._data_parallel(batch, x, timesteps, context, kwargs)
        except Exception as e:  # noqa: BLE001 — OOM fallback, parity 1435-1448
            if not _is_resource_exhausted(e):
                raise
            log_degradation(
                "step-oom",
                f"{type(e).__name__}; freeing replicas, demoting to single-device",
            )
            self._demote()
            return self.single(x, timesteps, context, **kwargs)

    def _get_streaming_runner(self):
        """Build the weight-streaming runner on first use (placing the
        resident prepare/finalize params costs device memory, same laziness
        argument as _get_pipeline_runner)."""
        if self._stream_runner is None:
            from ..devices.memory import usable_hbm_bytes
            from .streaming import build_streaming_runner

            budget = self.config.hbm_budget_bytes
            if not budget:
                budget = usable_hbm_bytes(self.lead_device) or None
            self._stream_runner = build_streaming_runner(
                self._pipeline_spec, self._host_params, self.lead_device,
                hbm_budget_bytes=budget, overlap=self.config.stream_overlap,
                n_stages=self.config.stream_stages,
            )
            if self._stream_runner is None:
                raise ValueError(
                    "weight streaming requires a model with a PipelineSpec "
                    "(the staged decomposition the stream is carved from); "
                    "this model declares none"
                )
        return self._stream_runner

    def _stream_call(self, x, timesteps, context, kwargs):
        """Streamed execution with the stream-mode OOM demotion: a
        RESOURCE_EXHAUSTED re-carves the schedule at half the stage size and
        retries (deterministic for a given shape, like every XLA OOM — see
        the module docstring's demotion note), until stages bottom out at
        one segment each."""
        while True:
            runner = self._get_streaming_runner()
            try:
                return runner(x, timesteps, context, **kwargs)
            except Exception as e:  # noqa: BLE001 — OOM demotion, stream form
                if not _is_resource_exhausted(e):
                    raise
                deeper = runner.recarved()
                if deeper is None:
                    # Ladder exhausted (one segment per stage already):
                    # bounded degradation ends in a clean, attributable
                    # failure — postmortem bundle + the original error.
                    from ..utils import degrade

                    degrade.ladder_exhausted(
                        "stream-recarve", e,
                        detail=f"{runner.n_stages} stages, no finer carve",
                    )
                    raise
                from ..utils import degrade

                degrade.record_rung(
                    "stream-recarve",
                    f"{type(e).__name__}; re-carving weight stream "
                    f"{runner.n_stages} → {deeper.n_stages} stages",
                    stages_before=runner.n_stages,
                    stages_after=deeper.n_stages,
                )
                aggressive_cleanup(clear_compile_cache=False)
                self._stream_runner = deeper

    def _pipeline_microbatch(self, runner, mb, batch, x, timesteps, context, kwargs):
        """GPipe-style throughput pipelining over the stage chain.

        Every microbatch is dispatched through the per-device stage programs
        WITHOUT host blocking: each stage is an async program pinned to its own
        device, so XLA's per-device execution queues run microbatch j's later
        stages concurrently with j+1's earlier ones — the host only blocks on
        the final concat's consumers. The reference has no analogue (its
        pipeline mode is batch==1 only; SURVEY §2e calls it layer placement,
        not throughput pipelining)."""
        # Uniform chunk shapes: pad the batch up to mb * ceil(batch/mb) so
        # every microbatch compiles ONE set of stage/prepare/finalize programs
        # (uneven largest-remainder sizes would double every XLA compile).
        per = -(-batch // mb)
        padded = per * mb
        if padded != batch:
            x, timesteps, context, kwargs = (
                _pad_tree(v, batch, padded)
                for v in (x, timesteps, context, kwargs)
            )
        chunks = _split_inputs(padded, [per] * mb, x, timesteps, context, kwargs)
        outs = [runner(xi, ti, ci, **ki) for xi, ti, ci, ki in chunks]
        return _slice_padded(concat_results(outs), batch, padded)

    def _get_pipeline_runner(self):
        """Build the stage-placement runner on first use — placing per-stage param
        sub-pytrees costs device memory, so it only happens once a pipeline-path
        call (batch==1, or batch>1 with pipeline_microbatches) actually arrives
        (the reference pre-wraps at setup, 1152-1198)."""
        if self._pipeline_runner is None and self._pipeline_spec is not None:
            from .pipeline import build_pipeline_runner

            devices = [d for g in self._groups for d in g.devices]
            # Planner-chosen byte-balanced stage carve (parallel/planner.py
            # pipeline axis) — only when the decision was ENACTED (mode
            # "on", never shadow) AND the carve cleared the planner's
            # hysteresis ("enact"), and only on uniform-weight chains:
            # explicit uneven user weights (or a rebalance that shifted
            # them) keep the weight-proportional hand carve, which is what
            # those weights mean.
            ranges = None
            pipe_plan = (self.plan or {}).get("pipeline") \
                if isinstance(self.plan, dict) else None
            w = list(self.weights)
            if (
                pipe_plan and pipe_plan.get("enact") and w
                and (self.plan or {}).get("mode_flag") == "on"
                and max(w) - min(w) < 1e-9
                and len(pipe_plan.get("ranges") or []) <= len(devices)
            ):
                ranges = [tuple(r) for r in pipe_plan["ranges"]]
                # The carve REALLY applies now — stamp the decision (the
                # /health and ledger views read the shared dict) and count
                # it, so observability reflects enacted routing changes,
                # never mere intent (planner._pipeline_plan docstring).
                pipe_plan["enacted"] = True
                try:
                    from ..utils.metrics import registry as _metrics

                    _metrics.counter(
                        "pa_planner_pipeline_carve_total",
                        help="batch==1 pipeline runners built with the "
                             "planner's byte-balanced stage carve instead "
                             "of the weight-proportional hand carve",
                    )
                except Exception:
                    pass
            self._pipeline_runner = build_pipeline_runner(
                self._pipeline_spec, self._host_params, devices,
                list(self.weights), ranges=ranges,
            )
            if self._pipeline_runner is None:
                self._pipeline_spec = None  # unpipelineable; don't retry every step
        return self._pipeline_runner

    # The reference keeps ``_original_forward`` callable on the lead device
    # (1380-1383); ``single`` is that escape hatch.
    def single(self, x, timesteps, context=None, **kwargs):
        # Streaming premise: the full pytree does not fit ANY single chip —
        # the escape hatch is the streamed schedule itself, never a lead copy.
        if self._stream:
            return self._stream_call(x, timesteps, context, kwargs)
        # FSDP/TP premise: the full pytree does NOT fit one chip, so the fallback
        # cannot be a lead-device copy. Run over the group mesh with inputs
        # replicated instead — params stay 1/N per chip, XLA gathers per-use.
        g = self._groups[0]
        sharded_weights = (
            self.config.weight_sharding == "fsdp" or self.config.tensor_parallel > 1
        )
        if sharded_weights and g.params is not None:
            traced, static = partition_kwargs(kwargs)
            repl = NamedSharding(g.mesh, P())

            def put_repl(v):
                return jax.tree.map(
                    lambda l: jax.device_put(l, repl) if _is_arraylike(l) else l, v
                )

            fn = self._jit_for(static)
            return fn(
                g.params, put_repl(x), put_repl(timesteps), put_repl(context),
                put_repl(traced),
            )
        traced, static = partition_kwargs(kwargs)

        def put(v):
            return jax.tree.map(
                lambda l: jax.device_put(l, self.lead_device) if _is_arraylike(l) else l,
                v,
            )

        fn = self._jit_for(static)
        return fn(self._lead(), put(x), put(timesteps), put(context), put(traced))

    def _lead(self):
        """Lazy full-pytree copy on the lead device — the shared placement for
        the eager single() fallback and traceable()'s single-device spec."""
        if self._lead_params is None:
            from .mesh import streamed_tree_put

            self._lead_params = streamed_tree_put(
                self._host_params, lambda _: self.lead_device
            )
        return self._lead_params

    def _data_parallel(self, batch, x, timesteps, context, kwargs):
        if len(self._groups) == 1:
            return self._dp_on_group(self._groups[0], batch, x, timesteps, context, kwargs)
        # Heterogeneous chain: weighted host-side scatter over platform groups, one
        # async SPMD program each, concat on host order (SURVEY §7 hard part 1).
        gweights = normalize_weights([g.weight for g in self._groups])
        assert gweights is not None
        sizes = largest_remainder_split(batch, gweights)
        chunks = _split_inputs(batch, sizes, x, timesteps, context, kwargs)
        outs = []
        for g, size, (xg, tg, cg, kg) in zip(self._groups, sizes, chunks):
            if size == 0:
                continue  # inactive group this batch (active-device list, 1324-1337)
            outs.append(self._dp_on_group(g, size, xg, tg, cg, kg))
        # Every group's program was dispatched asynchronously above; now gather each
        # output to the lead device (the reference's move-to-lead, 1408) and concat.
        outs = [
            jax.tree.map(
                lambda l: jax.device_put(l, self.lead_device) if _is_arraylike(l) else l,
                o,
            )
            for o in outs
        ]
        return concat_results(outs)

    def _dp_on_group(self, group: _PlatformGroup, batch, x, timesteps, context, kwargs):
        n = group.mesh.shape[self.config.data_axis]
        padded = batch + ((-batch) % n)
        sharded = NamedSharding(group.mesh, P(self.config.data_axis))
        repl = NamedSharding(group.mesh, P())

        def place(v):
            """Batch-dim leaves pad+shard; other array leaves replicate; the rest
            pass through (they become jit statics via kwargs partitioning or are
            non-batch pytree leaves)."""

            def leaf(l):
                if not _is_arraylike(l):
                    return l
                if l.ndim > 0 and l.shape[0] == batch:
                    return jax.device_put(_pad_leaf(l, padded - batch), sharded)
                return jax.device_put(l, repl)

            return jax.tree.map(leaf, v)

        traced, static = partition_kwargs(kwargs)
        fn = self._jit_for(static)
        out = fn(group.params, place(x), place(timesteps), place(context), place(traced))
        return _slice_padded(out, batch, padded)

    # -- whole-loop compilation handle (sampling/compiled.py) ----------------------

    def traceable(self):
        """A ``TraceSpec`` letting a sampler compile its ENTIRE denoise loop as
        one XLA program over this chain, or None when that cannot be a single
        program (heterogeneous multi-group chains need host-side scatter; an
        ambient sequence_parallel context pins shard_map meshes this path does
        not carry). Trades away per-step elasticity (step-OOM demotion,
        1435-1448) for zero per-step dispatch — the opt-in documented on
        ``run_sampler(compile_loop=True)``."""
        from ..ops.attention import sequence_ctx_key
        from ..sampling.compiled import TraceSpec

        if self._stream:
            # One XLA program would close over the FULL weight pytree — the
            # exact allocation streaming exists to avoid. The sampler loop
            # stays eager and drives the per-stage programs each step
            # (sampling/runner.py logs the fallback).
            return None
        if sequence_ctx_key() is not None:
            return None
        if len(self._groups) != 1:
            return None
        g = self._groups[0]
        sharded = (
            self.config.weight_sharding == "fsdp" or self.config.tensor_parallel > 1
        )
        if g.params is not None:
            if self.active and self.config.workload_split and self._data_width() > 1:
                return TraceSpec(
                    apply=self._apply, params=g.params, mesh=g.mesh,
                    data_axis=self.config.data_axis,
                )
            if sharded:
                # Sharded weights are the ONLY placement that fits — run the
                # loop over the group mesh with replicated inputs (the single()
                # premise), whether active or step-OOM-demoted; a lead-device
                # copy would re-materialize the full pytree on one chip.
                return TraceSpec(apply=self._apply, params=g.params)
        return TraceSpec(apply=self._apply, params=self._lead())

    def serving_bucket_width(self, requested: int) -> int:
        """How many concurrent serving lanes one step dispatch may co-batch
        for this chain (serving/scheduler.py consults this at admission).

        Stream-mode chains stay width-1: every step already re-streams the
        full weight pytree under a carved HBM budget, and co-batched lanes
        would multiply the activation peak that budget was carved against —
        they keep step-boundary scheduling (cancel, metrics, ragged retire)
        without co-batching. Hybrid multi-group chains and active
        sequence-parallel contexts are width-1 for the same reason they are
        not whole-loop traceable: no single step program exists to widen.
        Single-group chains take the requested width; the scheduler rounds it
        to the data-axis width so padded lanes shard evenly over the mesh."""
        if self._stream or self.traceable() is None:
            return 1
        return max(1, int(requested))

    # -- degradation (parity 1435-1448, divergence documented above) ---------------

    def _demote(self) -> None:
        self.active = False
        self._demoted = True
        self._steps_demoted = 0
        keep = (
            self.config.weight_sharding == "fsdp" or self.config.tensor_parallel > 1
        )
        for g in self._groups:
            if not keep:
                # Replicate mode frees the per-device replicas (the lead copy
                # takes over). FSDP/TP keep the sharded pytree: it is the ONLY
                # placement that fits, and single() runs on it with replicated
                # inputs.
                g.params = None
        self._pipeline_runner = None
        aggressive_cleanup(clear_compile_cache=True)
        self._jits.clear()

    def _place(self, params, mesh):
        placed, _ = _place_for(self.config, params, mesh)
        return placed

    def reactivate(self) -> None:
        """Re-place replicas and resume parallel execution after a demotion.
        Called manually, from rebalance(), or automatically after
        ``config.reactivate_after`` single-device steps. All-or-nothing: a
        placement failure on a later group rolls back the groups placed in
        THIS attempt, so a failed retry never leaves extra replicas pinned
        through the (memory-pressured) demoted period."""
        if self._stream:
            # Stream mode never demotes (OOM re-carves the schedule instead)
            # and a group placement would materialize the full pytree — the
            # allocation that cannot exist. No-op.
            return
        self._steps_demoted = 0
        placed_now: list = []
        try:
            for g in self._groups:
                if g.params is None:
                    g.mesh = _group_mesh(g.devices, self.config)
                    g.params = self._place(self._host_params, g.mesh)
                    placed_now.append(g)
        except Exception:
            for g in placed_now:
                g.params = None
                g.mesh = None
            raise
        self.active = True
        self._demoted = False

    # -- periodic re-balance (parity: per-step VRAM re-read, 737-766/1317-1322) ----

    def rebalance(self) -> tuple[float, ...]:
        """Re-read free device memory and re-blend workload weights.

        The reference re-reads VRAM *every step* (any_device_parallel.py:737-766,
        blended at 1317-1322) — free on CUDA, but on TPU a changed split shape is
        a recompile, so the deferred analogue runs on demand between sampler runs.
        Re-blends the *original* user weights (kept per group) against a fresh
        memory reading — not the already-blended values, which would compound —
        and resets the lazy pipeline runner so batch==1 stage placement also
        re-balances on next use. Returns the new normalized weights. No-op on
        chains where no device reports memory (blend falls back to user weights),
        and when ``auto_memory_balance`` is off — the reference gates the
        per-step VRAM re-blend on ``auto_balance_ref`` the same way
        (any_device_parallel.py:1317-1322), so explicit user weights are never
        silently overridden by memory stats.
        """
        if self._demoted and not self._cleaned:
            # An explicit rebalance signals intent to resume parallel execution
            # after a step-OOM demotion (VERDICT r2: nothing ever reactivated
            # automatically); failure to re-place keeps the single-device path.
            # Never resurrects an explicitly cleaned-up model.
            try:
                self.reactivate()
            except Exception as e:  # noqa: BLE001
                if not _is_resource_exhausted(e):
                    raise
        if not self.config.auto_memory_balance \
                and not self.config.auto_speed_balance:
            return self.weights
        user = [w for g in self._groups for w in g.user_weights]
        base = normalize_weights(user)
        if base is None:
            return self.weights
        devs = [d for g in self._groups for d in g.devices]
        new = base
        if self.config.auto_memory_balance:
            free = [free_memory_bytes(d) for d in devs]
            new = blend_memory_weights(new, free)
        if self.config.auto_speed_balance:
            # The SPEED half of the re-blend (round 17): same discipline as
            # memory — re-blended from the ORIGINAL user weights, platform
            # specs read fresh (they are static, but the env-var fallback
            # for tunneled device kinds is not).
            new = blend_speed_weights(new, _device_step_times(devs))
        i = 0
        for g in self._groups:
            for j in range(len(g.device_weights)):
                g.device_weights[j] = new[i]
                i += 1
        self.weights = tuple(new)
        # Stage ranges are weight-proportional; rebuild lazily on next batch==1.
        self._pipeline_runner = None
        return self.weights

    # -- lifecycle (parity: cleanup_parallel_model, 211-282) -----------------------

    def cleanup(self) -> None:
        """Teardown: drop placed replicas and compile caches per the purge
        flags. Idempotent; also runs fully on a step-OOM-demoted model (it may
        still hold sharded params, a lead copy, and compile caches)."""
        # Explicit teardown supersedes the GC finalizer (don't purge twice).
        fin = getattr(self, "_finalizer", None)
        if fin is not None:
            fin.detach()
        if self._cleaned:
            return
        self._cleaned = True
        self.active = False
        for g in self._groups:
            g.params = None
        self._lead_params = None
        self._pipeline_runner = None
        self._stream_runner = None
        self._jits.clear()
        if self.config.purge_cache:
            aggressive_cleanup(clear_compile_cache=self.config.purge_models)
        get_logger().info("parallel teardown complete")


# --------------------------------------------------------------------------------------
# setup_parallel analogue
# --------------------------------------------------------------------------------------


def model_config_of(model) -> Any:
    """The underlying model's own config (FluxConfig/UNetConfig/WanConfig/...),
    whether ``model`` is bare or a ParallelModel — whose ``.config`` is the
    ParallelConfig, with the wrapped config kept on ``.model_config``."""
    cfg = getattr(model, "model_config", None)
    if cfg is None:
        cfg = getattr(model, "config", None)
    return cfg


def _unwrap_model(model) -> tuple[Callable[..., Any], Any]:
    """Accept ``(apply_fn, params)`` or any object with ``.apply`` + ``.params`` —
    the duck-typed analogue of the ModelPatcher unwrap (921-930)."""
    if isinstance(model, tuple) and len(model) == 2 and callable(model[0]):
        return model
    apply_fn = getattr(model, "apply", None)
    params = getattr(model, "params", None)
    if callable(apply_fn) and params is not None:
        return apply_fn, params
    raise TypeError(
        "model must be (apply_fn, params) or expose .apply/.params; "
        f"got {type(model).__name__}"
    )


def _plan_inputs(params, pipeline_spec, devices, config: "ParallelConfig",
                 hints) -> "Any":
    """Assemble the planner's pure inputs from the wrap's facts (byte
    profile, budget, device identity) plus the caller's optional hints
    (bench passes the rung's measured FLOPs/bytes and batch; model wraps
    without hints plan from the weight bytes alone)."""
    from ..devices.memory import usable_hbm_bytes
    from ..models.loader import params_nbytes, segment_nbytes
    from .planner import PlanInputs

    hints = dict(hints or {})
    budget = config.hbm_budget_bytes or usable_hbm_bytes(devices[0]) or None
    seg: tuple = ()
    if pipeline_spec is not None and getattr(pipeline_spec, "segments", None):
        try:
            seg = tuple(segment_nbytes(pipeline_spec, params))
        except Exception:  # non-dict param containers: plan without the axis
            seg = ()
    lead = devices[0]
    return PlanInputs(
        n_devices=len(devices),
        platform=getattr(lead, "platform", "cpu") or "cpu",
        device_kind=getattr(lead, "device_kind", "") or "",
        weights_bytes=params_nbytes(params),
        budget_bytes=int(budget) if budget else None,
        segment_bytes=seg,
        flops=hints.get("flops"),
        bytes_accessed=hints.get("bytes_accessed"),
        batch=hints.get("batch"),
        seq_len=hints.get("seq_len"),
        head_dim=hints.get("head_dim"),
        heads=hints.get("heads"),
        rung=str(hints.get("rung") or ""),
    )


def parallelize(
    model,
    chain: DeviceChain | Sequence[tuple[str, float]],
    config: ParallelConfig | None = None,
    *,
    pipeline_spec: Any = None,
    plan_hints: Mapping[str, Any] | None = None,
) -> ParallelModel | Any:
    """Wrap ``model`` for parallel execution over ``chain``.

    Returns a ``ParallelModel``; on an unusable chain (empty, or total percentage <= 0)
    returns ``model`` unchanged, exactly like the reference's abort paths
    (1019-1027, 1037-1042).

    Strategy selection (round 18, parallel/planner.py): with ``PA_PLANNER``
    on (the default) and an open decision — single-platform chain,
    ``weight_sharding="replicate"``, no explicit tensor_parallel — the
    roofline-scored planner enumerates (mesh dp×tp × weight mode ×
    stage-carve × attention) candidates, prunes HBM-infeasible ones against
    the residency budget, and routes through the best predicted plan; an
    explicit ``weight_sharding="stream"`` pins the mode but still searches
    the stage carve. ``plan_hints`` feeds the cost model measured facts
    (``flops``/``bytes_accessed``/``batch``/``seq_len``/``head_dim``/
    ``rung`` — bench.py passes its rung's step cost). ``PA_PLANNER=0``
    restores the hand routing ladder below bitwise; ``PA_PLANNER=shadow``
    records the decision but enacts the hand plan.

    Re-entrant: passing an existing ``ParallelModel`` tears down its placements and
    rebuilds from the retained host params with the new chain/config — the
    reference's cleanup-then-rebuild on repeated setup_parallel calls (1006-1013,
    which runs *before* the weight-normalization abort at 1019-1027, so an unusable
    chain still leaves the previous setup torn down; the returned model keeps
    executing via its single-device path).
    """
    config = config or ParallelConfig()
    if not isinstance(chain, DeviceChain):
        chain = DeviceChain.from_pairs(chain)
    # An explicit ``pipeline_spec`` is the segments hint for models that cannot
    # carry one as an attribute — (apply, params) tuples wrapping third-party
    # code (the wrap-anything parity of the reference's name-based block
    # discovery, any_device_parallel.py:1156; see models/generic.py for the
    # flax auto-derivation).
    if isinstance(model, ParallelModel):
        apply_fn, params = model._apply, model._host_params
        if pipeline_spec is None:
            pipeline_spec = model._pipeline_spec
        wrapped_config = model.model_config
        sampler_prefs = getattr(model, "sampler_prefs", None)
        model.cleanup()
    else:
        apply_fn, params = _unwrap_model(model)
        if pipeline_spec is None:
            pipeline_spec = getattr(model, "pipeline_spec", None)
        wrapped_config = getattr(model, "config", None)
        # Model-level sampling preferences (RescaleCFG and friends) survive
        # wrapping — the stock ordering is patch -> ParallelAnything ->
        # KSampler, and samplers read prefs off whatever MODEL they get.
        sampler_prefs = getattr(model, "sampler_prefs", None)

    chain = chain.validated().deduplicated()
    weights = chain.normalized_weights()
    if not chain or weights is None:
        get_logger().warning("unusable device chain; returning model unchanged")
        return model

    devices = chain.jax_devices()

    user_weights = weights
    if config.auto_memory_balance:
        free = [free_memory_bytes(d) for d in devices]
        weights = blend_memory_weights(weights, free)
    if config.auto_speed_balance:
        weights = blend_speed_weights(weights, _device_step_times(devices))

    # Group consecutive-platform links into homogeneous SPMD sub-programs.
    groups: list[_PlatformGroup] = []
    for dev_str, dev, w, uw in zip(chain.devices, devices, weights, user_weights):
        plat = device_platform(dev_str)
        if groups and groups[-1].platform == plat:
            groups[-1].devices.append(dev)
            groups[-1].device_strs.append(dev_str)
            groups[-1].device_weights.append(w)
            groups[-1].user_weights.append(uw)
        else:
            groups.append(
                _PlatformGroup(
                    platform=plat,
                    devices=[dev],
                    device_strs=[dev_str],
                    device_weights=[w],
                    user_weights=[uw],
                )
            )

    # Weights-don't-fit routing rung (VERDICT r5 next-1): a replicate-mode
    # model whose pytree exceeds the lead device's HBM budget cannot place —
    # on hardware the loop below would OOM deterministically, burn the
    # degradation ladder chip by chip, and still fail on the last one. When
    # the model declares the PipelineSpec staging, route to the
    # weight-streaming executor instead: params stay host-pinned and stream
    # double-buffered through the lead device (parallel/streaming.py).
    stream_mode = config.weight_sharding == "stream"
    if stream_mode and pipeline_spec is None:
        raise ValueError(
            "weight_sharding='stream' requires a model with a PipelineSpec "
            "(the staged decomposition the stream is carved from)"
        )
    if stream_mode and config.tensor_parallel > 1:
        raise ValueError("weight_sharding='stream' does not compose with "
                         "tensor_parallel")

    # Auto-parallel planner (parallel/planner.py): search the plan space
    # where the decision is open. Hybrid multi-group chains keep the hand
    # weighted-scatter rules (one SPMD program per platform is the only
    # shape that exists there), explicit fsdp/tp configs are the user's
    # pinned decision, and PA_PLANNER=0 skips this block entirely — the
    # ladder below then routes bitwise-identically to the pre-planner code.
    plan_decision = None
    plan_enacted = False
    from . import planner as _planner

    if (
        _planner.enabled()
        and len(groups) == 1
        and config.pipeline_microbatches == 0
        and (stream_mode or (config.weight_sharding == "replicate"
                             and config.tensor_parallel <= 1))
    ):
        try:
            plan_decision = _planner.plan(
                _plan_inputs(params, pipeline_spec, devices, config,
                             plan_hints),
                pinned_mode="stream" if stream_mode else None,
            )
        except Exception:  # noqa: BLE001 — planning must never kill a wrap
            get_logger().warning(
                "auto-parallel planner failed; falling back to hand rules",
                exc_info=True,
            )
            plan_decision = None
        if plan_decision is not None and _planner.mode() == "on":
            chosen = plan_decision["chosen"]
            if chosen["mode"] == "stream" and pipeline_spec is not None:
                if not stream_mode and plan_decision["hand"]["mode"] != "stream":
                    log_degradation(
                        "plan-stream",
                        f"planner routed to weight streaming "
                        f"({chosen.get('n_stages')} stage(s), predicted "
                        f"{chosen['predicted_s']:.4g}s vs hand "
                        f"{plan_decision['hand']['predicted_s']:.4g}s)",
                    )
                stream_mode = True
                # A divergent carve enacts its stage count; a hand-equal
                # decision keeps the budget-cap carve byte-for-byte.
                if plan_decision["divergent"] and chosen.get("n_stages"):
                    config = dataclasses.replace(
                        config, stream_stages=int(chosen["n_stages"])
                    )
                plan_enacted = True
            elif chosen["mode"] == "fsdp":
                config = dataclasses.replace(config, weight_sharding="fsdp")
                plan_enacted = True
            elif chosen["mode"] == "tp" and chosen["tp"] > 1:
                config = dataclasses.replace(
                    config, tensor_parallel=int(chosen["tp"])
                )
                plan_enacted = True
            elif chosen["mode"] == "replicate":
                plan_enacted = True

    if (
        not plan_enacted
        and not stream_mode
        and config.weight_sharding == "replicate"
        and config.tensor_parallel <= 1
        and pipeline_spec is not None
    ):
        from ..devices.memory import usable_hbm_bytes
        from ..models.loader import params_nbytes

        budget = config.hbm_budget_bytes or usable_hbm_bytes(devices[0])
        total = params_nbytes(params)
        if budget and total > budget:
            log_degradation(
                "weights-dont-fit",
                f"{total / 2**30:.2f} GiB of weights vs {budget / 2**30:.2f} "
                "GiB HBM budget; routing to the weight-streaming executor",
            )
            stream_mode = True

    # Place params on each group's mesh, degrading on OOM: drop the last chain device
    # and retry (reference drops the failing device and renormalizes, 1114-1128).
    # Stream mode skips placement entirely — groups carry no params and the
    # lazily-built StreamingRunner owns all device residency.
    while not stream_mode:
        try:
            for g in groups:
                if g.params is None:
                    g.mesh = _group_mesh(g.devices, config)
                    g.params, desc = _place_for(config, params, g.mesh)
                    log_placement(f"{g.platform}×{len(g.devices)}", desc)
            break
        except Exception as e:  # noqa: BLE001
            if not _is_resource_exhausted(e):
                raise
            g = groups[-1]
            tp = max(1, config.tensor_parallel)
            if len(g.devices) > tp:
                # Drop enough trailing devices that the survivor count still
                # divides the tensor_parallel degree (always exactly 1 for tp=1).
                dropped = [g.drop_last_device()]
                while len(g.devices) % tp:
                    dropped.append(g.drop_last_device())
                log_degradation("setup-oom", f"dropped {dropped}, retrying")
            elif len(groups) > 1:
                groups.pop()
                log_degradation("setup-oom", f"dropped platform group {g.platform}")
            else:
                raise
            aggressive_cleanup(clear_compile_cache=True)

    # Rebuild the chain/weights views from the survivors so introspection and split
    # arithmetic agree with what was actually placed (renormalize-survivors parity).
    surviving = [(s, w) for g in groups for s, w in zip(g.device_strs, g.device_weights)]
    final_weights = normalize_weights([w for _, w in surviving])
    assert final_weights is not None
    chain = DeviceChain(
        tuple(DeviceLink(s, w * 100.0) for (s, _), w in zip(surviving, final_weights))
    )

    if stream_mode:
        mode = "stream"
    elif len(groups) == 1:
        mode = "spmd"
    else:
        mode = "hybrid"
    log_setup_summary(chain.devices, final_weights, mode)

    return ParallelModel(
        apply_fn=apply_fn,
        params=params,
        chain=chain,
        config=config,
        groups=groups,
        weights=final_weights,
        pipeline_spec=pipeline_spec,
        model_config=wrapped_config,
        sampler_prefs=sampler_prefs,
        streaming=stream_mode,
        plan=plan_decision,
    )
