"""Pipeline (batch==1) block-placement mode.

Reference (any_device_parallel.py:1152-1198, 24-87): for batch==1 the model's block
lists (``double_blocks``/``single_blocks``/``transformer_blocks``/``layers``) are split
into contiguous ranges proportional to device weights; each block is wrapped so its
args hop to the owning device, run there, and the last block's output returns to the
lead device. This is layer *placement* (memory-style pipelining), not microbatched
throughput pipelining (SURVEY §2e).

TPU-native design: block ranges map to per-stage placements of parameter sub-pytrees;
activations hop between stages via ``jax.device_put`` over ICI. Fleshed out with the
staged-model protocol in models/ (see build plan step 5); until a model declares its
stages this returns None and the router falls back to single-device, which matches the
reference when no known block list is found (1156-1166).
"""

from __future__ import annotations

from collections.abc import Mapping, Sequence
from typing import Any, Callable

import jax

from .split import block_ranges  # noqa: F401  (stage math lives here)


def build_pipeline_runner(
    apply_fn: Callable[..., Any],
    params: Any,
    devices: Sequence[jax.Device],
    weights: Sequence[float],
    block_lists: Mapping[str, Sequence[str]],
) -> Callable[..., Any] | None:
    del apply_fn, params, devices, weights, block_lists
    return None
