"""Pipeline (batch==1) block-placement mode.

Reference (any_device_parallel.py:1152-1198, 24-87): for batch==1 the model's block
lists (``double_blocks``/``single_blocks``/``transformer_blocks``/``layers``) are split
into contiguous ranges proportional to device weights; each block is wrapped so its
args hop to the owning device, run there, and the last block's output returns to the
lead device. This is layer *placement* (memory-style pipelining), not microbatched
throughput pipelining (SURVEY §2e).

TPU-native design: a model declares a ``PipelineSpec`` (models/api.py) — a staged
decomposition of its forward into prepare → per-block segments → finalize. The runner:

- assigns contiguous segment ranges to devices proportional to weights (the same
  arithmetic as the reference's 1168-1178, via the largest-remainder fix);
- places each stage's parameter sub-pytree on its owning device once, at build time
  (the analogue of ParallelBlock.peers holding each replica's block weights, 1182-1186);
- jit-compiles ONE program per stage that runs all of that stage's blocks back-to-back
  (the reference pays a Python-level wrapper call per block, 65-87; here XLA fuses a
  whole stage);
- hops the activation carry between stages with ``jax.device_put`` — ICI transfers,
  dispatched asynchronously, replacing the reference's per-block ``.to(owner_device)``
  over PCIe (77-78);
- runs prepare and finalize pinned to the lead device, exactly like the reference's
  non-block layers (embeddings, final norm/projection) which always run on the lead
  (SURVEY §3.4).

Devices whose weight rounds to zero blocks hold no stage and are skipped (parity:
zero-length ranges are valid, split.block_ranges).
"""

from __future__ import annotations

import dataclasses
from collections.abc import Sequence
from typing import Any, Callable

import jax

from ..models.api import PipelineSpec
from ..utils.logging import log_placement
from ..utils.telemetry import instrument_jit
from .split import block_ranges, partition_kwargs, static_kwargs_key


@dataclasses.dataclass
class _Stage:
    device: jax.Device
    params: Any  # placed sub-pytree for this stage's segments
    fn: Callable[[Any, dict], dict]  # jitted: runs the stage's segments in order
    labels: tuple[str, ...]


class PipelineRunner:
    """Callable ``(x, timesteps, context=None, **kwargs) -> output`` executing the
    staged forward across devices. Built once per (spec, devices, weights)."""

    def __init__(
        self,
        spec: PipelineSpec,
        params: Any,
        devices: Sequence[jax.Device],
        weights: Sequence[float],
        ranges: Sequence[tuple[int, int]] | None = None,
    ):
        self.lead = devices[0]
        self._spec = spec
        n = len(spec.segments)
        if ranges is None:
            # Weight-proportional carve (reference parity, 1168-1178). An
            # explicit ``ranges`` is the planner's byte-balanced stage
            # carve (parallel/planner.py pipeline axis) — contiguous,
            # covering [0, n), at most one range per device.
            ranges = block_ranges(n, weights)

        def subset(keys):
            missing = [k for k in keys if k not in params]
            if missing:
                raise KeyError(
                    f"pipeline spec references param keys not in the pytree: {missing}"
                )
            return {k: params[k] for k in keys}

        self._prepare_params = jax.device_put(subset(spec.prepare_keys), self.lead)
        self._finalize_params = jax.device_put(subset(spec.finalize_keys), self.lead)
        # Per-static-kwargs jit cache for prepare (non-array kwargs are compile-time
        # baked — the orchestrator's kwargs contract, parallel/split.py) and a
        # per-output-shape cache for finalize (the head needs only static geometry,
        # not the input array — passing x itself would drag a foreign-device array
        # into a lead-committed computation).
        self._prepare_jits: dict[tuple, Any] = {}
        self._finalize_jits: dict[tuple, Any] = {}

        self.stages: list[_Stage] = []
        for (s, e), dev in zip(ranges, devices):
            if s == e:
                continue  # zero-weight device holds no pipeline stage
            keys = []
            for i in range(s, e):
                for k in spec.segments[i].param_keys:
                    if k not in keys:
                        keys.append(k)
            seg_fns = [spec.segments[i].fn for i in range(s, e)]

            def stage_fn(stage_params, carry, _fns=tuple(seg_fns)):
                for f in _fns:
                    carry = f(stage_params, carry)
                return carry

            self.stages.append(
                _Stage(
                    device=dev,
                    params=jax.device_put(subset(keys), dev),
                    # palint: allow[recompile-hazard] the stage range IS
                    # program identity, bounded by the pipeline carve
                    fn=instrument_jit(stage_fn, f"pipeline-stage[{s}:{e})"),
                    labels=tuple(spec.segments[i].label for i in range(s, e)),
                )
            )
            log_placement(
                str(dev), f"pipeline stage: segments [{s}, {e}) ({e - s} blocks)"
            )

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    def _prepare_for(self, static: dict):
        """Jitted prepare with non-array kwargs baked in (one compile per distinct
        static combination)."""
        key = static_kwargs_key(static)
        fn = self._prepare_jits.get(key)
        if fn is None:
            prepare = self._spec.prepare
            bound = dict(static)

            def wrapped(params, x, t, context, traced):
                return prepare(params, x, t, context, **traced, **bound)

            fn = instrument_jit(wrapped, "pipeline-prepare")
            self._prepare_jits[key] = fn
        return fn

    def _finalize_for(self, out_shape: tuple[int, ...]):
        """Jitted finalize with the static output geometry baked in."""
        fn = self._finalize_jits.get(out_shape)
        if fn is None:
            finalize = self._spec.finalize

            def wrapped(params, carry):
                return finalize(params, carry, out_shape)

            fn = instrument_jit(wrapped, "pipeline-finalize")
            self._finalize_jits[out_shape] = fn
        return fn

    def __call__(self, x, timesteps, context=None, **kwargs):
        from ..ops.attention import sequence_ctx_key

        if sequence_ctx_key() is not None:
            # Stage programs are jitted once per runner and pinned to single
            # devices; a seq-mesh shard_map cannot live inside them. The
            # orchestrator routes batch==1 to single-device under an active
            # context — reaching here means the runner was invoked directly.
            raise ValueError(
                "pipeline block placement does not compose with an active "
                "sequence_parallel context; run the model through the "
                "orchestrator (which falls back to single-device) or exit "
                "the context"
            )
        traced, static = partition_kwargs(kwargs)
        carry = self._prepare_for(static)(
            self._prepare_params,
            jax.device_put(x, self.lead),
            jax.device_put(timesteps, self.lead),
            jax.device_put(context, self.lead) if context is not None else None,
            {k: jax.device_put(v, self.lead) for k, v in traced.items()},
        )
        for stage in self.stages:
            carry = jax.device_put(carry, stage.device)  # ICI activation hop
            carry = stage.fn(stage.params, carry)
        carry = jax.device_put(carry, self.lead)  # last block → lead (parity 83-85)
        return self._finalize_for(tuple(x.shape))(self._finalize_params, carry)


def build_pipeline_runner(
    spec: PipelineSpec | None,
    params: Any,
    devices: Sequence[jax.Device],
    weights: Sequence[float],
    ranges: Sequence[tuple[int, int]] | None = None,
) -> PipelineRunner | None:
    """Build the batch==1 runner; None when the model declares no pipeline spec — the
    router then falls back to single-device, matching the reference when no known
    block list is found (1156-1166). ``ranges`` overrides the
    weight-proportional carve with an explicit stage partition (the
    planner's byte-balanced carve)."""
    if spec is None or not spec.segments or len(devices) <= 1:
        return None
    return PipelineRunner(spec, params, devices, weights, ranges=ranges)
