from .chain import DeviceLink, DeviceChain
from .split import (
    normalize_weights,
    largest_remainder_split,
    weighted_batch_split,
    blend_memory_weights,
    blend_speed_weights,
    block_ranges,
    batch_size_of,
    split_tree,
    split_kwargs,
    concat_results,
)
from .mesh import (
    build_mesh,
    mesh_axis_names,
    fsdp_spec,
    place_params,
    place_params_fsdp,
)
from .sequence import sequence_parallel_attention
from .pipeline import PipelineRunner, build_pipeline_runner
from .streaming import StreamingRunner, build_streaming_runner
from .multihost import (
    initialize_distributed,
    is_multihost,
    hybrid_mesh,
    host_local_batch,
)

__all__ = [
    "sequence_parallel_attention",
    "PipelineRunner",
    "build_pipeline_runner",
    "StreamingRunner",
    "build_streaming_runner",
    "fsdp_spec",
    "place_params",
    "place_params_fsdp",
    "initialize_distributed",
    "is_multihost",
    "hybrid_mesh",
    "host_local_batch",
    "DeviceLink",
    "DeviceChain",
    "normalize_weights",
    "largest_remainder_split",
    "weighted_batch_split",
    "blend_memory_weights",
    "blend_speed_weights",
    "block_ranges",
    "batch_size_of",
    "split_tree",
    "split_kwargs",
    "concat_results",
    "build_mesh",
    "mesh_axis_names",
]
