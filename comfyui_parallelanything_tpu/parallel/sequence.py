"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Absent in the reference — its only split axis is batch dim0 (torch.split at
any_device_parallel.py:1224/1256; SURVEY §5.7) — but first-class here: the reference's
own flagship workloads (FLUX 1024² ⇒ 4096 image tokens, WAN-class video ⇒ tens of
thousands) make sequence length the natural second sharding axis on TPU, and the mesh
vocabulary already reserves ``seq`` for it (parallel/mesh.py).

Two standard schemes, both SPMD via ``shard_map`` over a ``seq`` mesh axis:

- **Ring attention** (blockwise attention with a k/v ring): q stays put; k/v shards
  rotate around the ring with ``lax.ppermute`` while a flash-style online softmax
  accumulates (running max / normalizer), so no device ever holds the full sequence.
  ICI-bandwidth-friendly: each step moves one k/v block to the next neighbor.
- **Ulysses** (all-to-all head scatter): ``lax.all_to_all`` re-shards tokens→heads,
  each device runs *full-sequence* attention for its head slice (hitting the fused
  single-device kernel), then all-to-all back. Needs num_heads % n_shards == 0.

Both compute attention identically to ``ops.attention`` (same f32 softmax) up to
floating-point reduction order.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6: top-level shard_map with check_vma
    from jax import shard_map
except ImportError:  # older jax: experimental namespace, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_compat(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

from .mesh import AXIS_SEQ

Method = Literal["ring", "ulysses"]


# --------------------------------------------------------------------------------------
# Ring attention (per-shard body; runs inside shard_map)
# --------------------------------------------------------------------------------------


def _ring_attention_local(q, k, v, *, axis_name: str, n_shards: int, scale: float):
    """Local shard body: q (B, Sq, H, D) fixed; k/v (B, Sk, H, D) rotate the ring.

    Online-softmax accumulation in f32 (flash-attention recurrence): running max
    ``m``, normalizer ``l``, weighted value accumulator ``acc``.
    """
    B, Sq, H, D = q.shape
    qf = q.astype(jnp.float32)

    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def step(carry, _):
        k_blk, v_blk, m, l, acc = carry
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32)
        ) * scale  # (B, H, Sq, Sk)
        blk_max = jnp.max(logits, axis=-1)  # (B, H, Sq)
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)  # (B, H, Sq)
        p = jnp.exp(logits - new_m[..., None])  # (B, H, Sq, Sk)
        l = l * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, new_m, l, acc), None

    m0 = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    (_, _, _, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), None, length=n_shards
    )
    out = acc / l[..., None]  # (B, H, Sq, D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B, Sq, H, D)


def _ulysses_local(q, k, v, *, axis_name: str, scale: float):
    """Local shard body: re-shard tokens→heads, full-seq attention, shard back.

    In: (B, S/n, H, D). all_to_all(split H, concat S) → (B, S, H/n, D).
    ``attention_local`` (not ``attention``) — the dispatching wrapper would re-enter
    the sequence-parallel route inside this shard_map body.
    """
    from ..ops.attention import attention_local

    def scatter(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def gather(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    out = attention_local(scatter(q), scatter(k), scatter(v), scale=scale)
    return gather(out)


# --------------------------------------------------------------------------------------
# Public entry
# --------------------------------------------------------------------------------------


def sequence_parallel_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    axis: str = AXIS_SEQ,
    method: Method = "ring",
    scale: float | None = None,
):
    """Attention over (B, S, H, D) inputs with S sharded on ``mesh`` axis ``axis``.

    Inputs may be unsharded host arrays (they are constrained into the sequence
    sharding) or already sharded; output carries the same sequence sharding.
    ``method="ring"`` rotates k/v blocks over ICI; ``method="ulysses"`` does two
    all-to-alls and computes full-sequence attention per head slice.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    _validate_shapes(q, k, mesh.shape[axis], method)
    fn = _compiled_attention(mesh, axis, method, float(scale))
    sharding = NamedSharding(mesh, P(None, axis, None, None))
    q, k, v = (lax.with_sharding_constraint(t, sharding) for t in (q, k, v))
    return fn(q, k, v)


@functools.lru_cache(maxsize=64)
def _sharded_attention_fn(mesh: Mesh, axis: str, method: str, scale: float):
    """The shard_map-wrapped (un-jitted) attention program — traceable, so it can be
    inlined inside a larger jitted model forward (the sequence_parallel context)."""
    n_shards = mesh.shape[axis]
    spec = P(None, axis, None, None)  # (B, S, H, D), S sharded
    if method == "ring":
        body = functools.partial(
            _ring_attention_local, axis_name=axis, n_shards=n_shards, scale=scale
        )
    elif method == "ulysses":
        body = functools.partial(_ulysses_local, axis_name=axis, scale=scale)
    else:
        raise ValueError(f"unknown sequence-parallel method {method!r}")
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )


@functools.lru_cache(maxsize=64)
def _compiled_attention(mesh: Mesh, axis: str, method: str, scale: float):
    """One jitted shard_map program per (mesh, axis, method, scale) — jit caches are
    keyed by function object, so rebuilding the closure per call would retrace and
    recompile on every sampler step."""
    return jax.jit(_sharded_attention_fn(mesh, axis, method, scale))


def _validate_shapes(q, k, n_shards: int, method: str) -> None:
    """Clear errors instead of opaque shard_map tracing failures. Both q's and k/v's
    sequence lengths must shard (cross-attention k/v carries the *text* length — e.g.
    77 CLIP tokens won't shard 4-way; pad the context to a multiple)."""
    for name, t in (("q", q), ("k/v", k)):
        if t.shape[1] % n_shards:
            raise ValueError(
                f"sequence-parallel attention: {name} sequence length {t.shape[1]} "
                f"not divisible by the seq mesh axis ({n_shards}); pad it to a "
                f"multiple"
            )
    if method == "ulysses" and q.shape[2] % n_shards:
        raise ValueError(
            f"ulysses needs num_heads ({q.shape[2]}) divisible by the "
            f"sequence-shard count ({n_shards})"
        )


def sharded_attention_inline(q, k, v, mesh: Mesh, axis: str, method: str, scale: float):
    """Sequence-parallel attention usable *inside* a traced model forward: constrains
    q/k/v to the sequence sharding and inlines the shard_map program (no nested
    dispatch). Used by ops.attention when a ``sequence_parallel`` context is active."""
    _validate_shapes(q, k, mesh.shape[axis], method)
    sharding = NamedSharding(mesh, P(None, axis, None, None))
    q, k, v = (lax.with_sharding_constraint(t, sharding) for t in (q, k, v))
    return _sharded_attention_fn(mesh, axis, method, float(scale))(q, k, v)
