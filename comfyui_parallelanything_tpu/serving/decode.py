"""Batched tail decode: VAE decodes leave the prompt workers' inline path
and batch into shared compiled decode dispatches.

The serving tier co-batches the denoise loop (scheduler/bucket), but until
round 17 every prompt's VAE decode ran inline on its own worker thread —
serializing on the device behind the next prompt's denoise dispatches, one
compiled decode per prompt even when four prompts finish the same lockstep
step and decode the same latent shape. This module is the scheduler-tail
analogue of the step bucket for the decode stage:

- **submit/ticket**: ``TPUVAEDecode`` routes eligible work (untiled image
  latents) here when a queue is installed (the server installs one alongside
  the scheduler); the worker blocks on its ticket exactly as a sampler run
  blocks on its serving ticket. Ineligible work (tiled decode, video VAE,
  odd ranks) returns ``None`` and the caller decodes inline unchanged — the
  queue can only ADD batching, never change results.
- **width-bucketed batching**: compatible latents — same VAE object, same
  per-request latent shape/dtype — concatenate on the batch axis, padded to
  the fixed bucket width (``PA_DECODE_WIDTH``), so ANY 1..W group runs ONE
  compiled program per (vae, shape) and traffic mix can't recompile (the
  step bucket's key discipline). Results are sliced back per ticket;
  per-sample independence of the decoder makes a padded row inert.
- **linger window**: a group dispatches when it reaches the width OR when
  its oldest ticket has waited ``PA_DECODE_LINGER_S`` — decodes from prompts
  retiring off the same lockstep dispatch arrive within milliseconds, which
  is the batching opportunity; a solo prompt pays at most the linger.
- **metered**: ``pa_decode_dispatch_total`` / ``pa_decode_requests_total``
  counters, ``pa_decode_batched_fraction`` gauge (requests served in
  shared dispatches / total — the loadgen ``decode_batched_fraction``
  field), ``pa_decode_queue_depth`` gauge, wait/step histograms, and a
  ``decode-dispatch`` span per dispatch.

Correctness: batched-vs-solo decode is allclose at bf16 tolerances (the
batch dim changes the XLA program, same as any width change — CLAUDE.md's
matmul-precision note), pinned by ``tests/test_reuse.py``.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import uuid
from typing import Any

from ..utils import slo, tracing
from ..utils.metrics import registry

_installed: "DecodeQueue | None" = None
_install_lock = threading.Lock()

# Process-wide batched-decode accounting (the bucket.py _batch_stats twin):
# requests decoded in dispatches carrying >1 request, over all requests.
_stats = {"total": 0, "shared": 0}
_stats_lock = threading.Lock()


def get_decode_queue() -> "DecodeQueue | None":
    """The process-wide decode queue TPUVAEDecode consults, or None
    (inline decode)."""
    return _installed


def record_decode_occupancy(occupancy: int) -> None:
    with _stats_lock:
        _stats["total"] += occupancy
        if occupancy > 1:
            _stats["shared"] += occupancy
        frac = _stats["shared"] / max(1, _stats["total"])
    registry.gauge(
        "pa_decode_batched_fraction", frac,
        help="decode requests served via shared dispatch / total",
    )


def batched_fraction() -> float:
    with _stats_lock:
        return _stats["shared"] / max(1, _stats["total"])


def _vae_token(vae) -> str:
    """Lifetime-unique token per VAE object — the group key's model
    component (one shared idiom: models/embed_cache.lifetime_token)."""
    from ..models.embed_cache import lifetime_token

    return lifetime_token(vae, "_pa_decode_token")


@dataclasses.dataclass
class DecodeTicket:
    """One latent handed to the decode tail; the submitting worker blocks in
    ``result()`` for exactly the queue wait + shared dispatch."""

    vae: Any
    z: Any
    submit_ts: float = dataclasses.field(default_factory=time.monotonic)
    prompt_id: Any = None
    trace_tid: Any = None
    trace_id: Any = None  # distributed trace identity (see ServeRequest)
    rid: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex)

    def __post_init__(self):
        self._done = threading.Event()
        self._result = None
        self._error: BaseException | None = None

    def resolve(self, result=None, error: BaseException | None = None) -> None:
        self._result, self._error = result, error
        self._done.set()

    def result(self, timeout: float | None = 300.0):
        if not self._done.wait(timeout):
            raise TimeoutError(f"decode ticket {self.rid} still in flight")
        if self._error is not None:
            raise self._error
        return self._result


class DecodeQueue:
    """Width-bucketed batching of tail decodes with a linger window.

    ``auto=True`` runs a dispatcher thread; ``auto=False`` exposes the same
    round as a manual ``pump()`` for deterministic tests (the scheduler's
    discipline)."""

    def __init__(self, width: int | None = None, linger_s: float | None = None,
                 auto: bool = True, max_waiting: int = 256):
        self.width = max(1, int(
            width if width is not None
            else os.environ.get("PA_DECODE_WIDTH", "4")
        ))
        self.linger_s = float(
            linger_s if linger_s is not None
            else os.environ.get("PA_DECODE_LINGER_S", "0.01")
        )
        self.max_waiting = max_waiting
        # group key -> [DecodeTicket] in arrival order.
        self._groups: dict[tuple, list] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._stop = False
        self._thread = None
        if auto:
            self._thread = threading.Thread(
                target=self._loop, name="pa-decode-dispatcher", daemon=True
            )
            self._thread.start()

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> "DecodeQueue":
        global _installed
        with _install_lock:
            _installed = self
        return self

    def uninstall(self) -> None:
        global _installed
        with _install_lock:
            if _installed is self:
                _installed = None

    def shutdown(self, timeout: float = 10.0) -> None:
        """Stop the dispatcher and resolve every waiting ticket with an
        error — no submitter may be left blocked on a dead queue."""
        self.uninstall()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        with self._lock:
            groups = list(self._groups.values())
            self._groups.clear()
        for tickets in groups:
            for t in tickets:
                t.resolve(error=RuntimeError("decode queue shutdown"))

    # -- submission ---------------------------------------------------------

    def submit(self, vae, z, tile: int = 0) -> DecodeTicket | None:
        """Admit one decode, or None when it cannot share a program (caller
        decodes inline): tiled decodes host-accumulate their own schedule,
        and only rank-4 image latents through a jit-decode VAE batch on
        dim 0."""
        if self._stop or tile:
            return None
        if getattr(z, "ndim", 0) != 4:
            return None
        if not hasattr(vae, "decode") or not hasattr(vae, "params"):
            return None
        # decode_tiled would have been chosen by decode_maybe_tiled only via
        # `tile`, but a large latent through vae.decode is the caller's
        # existing behavior — eligibility mirrors it exactly.
        key = (_vae_token(vae), tuple(z.shape), str(z.dtype))
        ticket = DecodeTicket(
            vae=vae, z=z,
            prompt_id=tracing.current_prompt_id() if tracing.on() else None,
            trace_tid=threading.get_ident() if tracing.on() else None,
            trace_id=tracing.current_trace_id() if tracing.on() else None,
        )
        with self._lock:
            if self._stop:
                # Re-checked under the lock: a shutdown() that completed
                # between the entry check and here has already resolved and
                # dropped every ticket — appending now would strand this
                # one's waiter for its full result() timeout. Inline decode
                # instead.
                return None
            waiting = sum(len(v) for v in self._groups.values())
            if waiting >= self.max_waiting:
                return None  # backpressure: shed to the inline path
            self._groups.setdefault(key, []).append(ticket)
            registry.gauge("pa_decode_queue_depth", waiting + 1,
                           help="latents waiting for a shared decode")
            self._cond.notify_all()
        return ticket

    # -- dispatch -----------------------------------------------------------

    def _ready(self, now: float) -> list[tuple]:  # palint: holds _lock
        """Group keys ripe for dispatch: width reached, or oldest ticket
        past the linger window."""
        out = []
        for key, tickets in self._groups.items():
            if not tickets:
                continue
            if len(tickets) >= self.width \
                    or now - tickets[0].submit_ts >= self.linger_s:
                out.append(key)
        return out

    def pump(self, force: bool = False) -> bool:
        """One dispatch round: run every ripe group (``force`` dispatches
        everything waiting — the manual-test / drain path). Returns whether
        anything dispatched."""
        did = False
        while True:
            with self._lock:
                now = time.monotonic()
                keys = list(self._groups) if force else self._ready(now)
                batch = None
                for key in keys:
                    tickets = self._groups.get(key) or []
                    take, rest = tickets[:self.width], tickets[self.width:]
                    if rest:
                        self._groups[key] = rest
                    else:
                        self._groups.pop(key, None)
                    if take:
                        batch = (key, take)
                        break
                if batch is None:
                    registry.gauge(
                        "pa_decode_queue_depth",
                        sum(len(v) for v in self._groups.values()),
                    )
                    return did
            self._dispatch(*batch)
            did = True

    def _dispatch(self, key: tuple, tickets: list) -> None:
        import jax
        import jax.numpy as jnp

        now = time.monotonic()
        for t in tickets:
            wait = now - t.submit_ts
            registry.histogram("pa_decode_wait_seconds", wait,
                               help="submit-to-dispatch decode queue wait")
            slo.observe_stage("decode_wait", wait)
        vae = tickets[0].vae
        k = len(tickets)
        t0_us = tracing.now_us() if tracing.on() else 0.0
        t0 = time.perf_counter()
        try:
            # Pad to the fixed width bucket with inert rows (the decoder is
            # per-sample independent), so 1..W requests share ONE compiled
            # program per (vae, per-request shape) — no recompiles from mix.
            zs = [t.z for t in tickets]
            pad = self.width - k
            if pad:
                zs = zs + [jnp.zeros_like(zs[0])] * pad
            stacked = jnp.concatenate(zs, axis=0)
            out = vae.decode(stacked)
            # palint: allow[host-sync] the completion boundary: the decode
            # histogram must include device time (the StepTimer discipline)
            jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001 — no waiter may hang
            for t in tickets:
                t.resolve(error=e)
            return
        dt = time.perf_counter() - t0
        b = tickets[0].z.shape[0]
        registry.counter("pa_decode_dispatch_total",
                         help="shared compiled decode dispatches")
        registry.counter("pa_decode_requests_total", inc=k,
                         help="decode requests served — batching numerator")
        registry.histogram("pa_decode_step_seconds", dt,
                           help="wall time of one shared decode dispatch")
        record_decode_occupancy(k)
        if tracing.on() and t0_us:
            dur_us = tracing.now_us() - t0_us
            tracing.record(
                "decode-dispatch", t0_us, dur_us, cat="serving",
                occupancy=k, masked=self.width - k, width=self.width,
            )
            for t in tickets:
                tracing.record(
                    "decode", t0_us, dur_us, cat="serving",
                    tid=t.trace_tid, prompt_id=t.prompt_id, rid=t.rid,
                    occupancy=k,
                    **({"trace_id": t.trace_id} if t.trace_id else {}),
                )
        for i, t in enumerate(tickets):
            t.resolve(result=out[i * b:(i + 1) * b])

    def drain(self, timeout: float = 60.0) -> None:
        """Pump until nothing is waiting (manual mode helper)."""
        t0 = time.monotonic()
        while True:
            with self._lock:
                if not any(self._groups.values()):
                    return
            self.pump(force=True)
            if time.monotonic() - t0 > timeout:
                raise TimeoutError("decode drain timed out")

    def stats(self) -> dict:
        """The /health ``reuse.decode`` section."""
        with self._lock:
            waiting = sum(len(v) for v in self._groups.values())
        return {
            "width": self.width,
            "linger_s": self.linger_s,
            "waiting": waiting,
            "batched_fraction": batched_fraction(),
        }

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                if not any(self._groups.values()):
                    self._cond.wait(timeout=0.2)
                    continue
                now = time.monotonic()
                if not self._ready(now):
                    # Sleep until the oldest group's linger lapses (bounded
                    # below so a clock hiccup can't busy-spin).
                    oldest = min(
                        t[0].submit_ts for t in self._groups.values() if t
                    )
                    delay = max(0.001, self.linger_s - (now - oldest))
                    self._cond.wait(timeout=delay)
                    continue
            try:
                self.pump()
            except Exception:  # noqa: BLE001 — the dispatcher must survive
                time.sleep(0.05)
