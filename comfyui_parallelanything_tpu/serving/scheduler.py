"""Continuous-batching scheduler: step-boundary batched scheduling of
concurrent sampler runs.

The seam: every model eval is an identical compiled dispatch, so sampler
runs that agree on (model, latent shape, cfg-mode) — running ANY sampler in
the LaneStepSpec registry — can share ONE step program: a request joins the
shared batch at the next step boundary, runs its own schedule (and its own
per-lane sampler state machine) in its own lane, and retires when its own
eval count completes (serving/bucket.py). This module is the glue between
the callers (sampling/runner.py routes eligible ``run_sampler`` work here
when a scheduler is installed; server.py installs one when it runs multiple
prompt workers) and the buckets:

- **shape-bucketed admission**: incoming work keyed by (model id, latent
  shape/dtype, prediction, cfg-mode, static/traced kwarg shapes) — NOT the
  sampler, which rides per-lane (round 10) — and
  routed to the matching bucket, created on first sight with a width the
  model itself bounds (``ParallelModel.serving_bucket_width`` — stream-mode
  chains stay width-1, mesh chains round to the data-axis width); within a
  bucket, requests aliasing ONE cond object (same-prompt siblings via the
  embed cache) seat against a shared broadcast cond tensor (round 17,
  serving/bucket.py shared-cond mode; ``reuse_stats()`` surfaces the
  per-bucket mode on /health);
- **policy**: FIFO-within-priority admission with bounded depth
  (serving/policy.py), per-request deadline, cancel — wired to the per-thread
  cooperative interrupt scope (utils/progress.py), so a prompt's Cancel frees
  its lane at the next boundary without touching its neighbors;
- **dispatcher**: one thread owns every compiled dispatch (one accelerator —
  lockstep is the schedule), round-robining buckets; ``auto=False`` exposes
  the same loop as a manual ``pump()`` for deterministic tests.

Ineligible work (unknown sampler, odd kwarg shapes, full queue) is never
queued: ``maybe_submit`` returns None and the caller runs inline exactly as
before — the scheduler can only ever ADD batching, not change results.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

import numpy as np

from ..utils.metrics import registry
from ..utils.progress import (
    Interrupted,
    clear_interrupt,
    current_progress_hook,
    current_scope,
    interrupt_requested,
)
from .bucket import ServeRequest, StepBucket
from .policy import ServingRejected

# Samplers the stateful-lane program family implements (round 10): every
# registered LaneStepSpec (sampling/lane_specs.py) — history-carrying,
# two-eval, and stochastic families included. Stochastic lanes are
# occupancy-deterministic because the per-step noise key is fold_in(rng, i)
# on every path; tests/test_serving.py's registry-driven equivalence matrix
# gates additions (a wired-but-unverified sampler fails the build).
from ..sampling.lane_specs import LANE_SPECS

BATCHABLE_SAMPLERS = frozenset(LANE_SPECS)

_installed: "ContinuousBatchingScheduler | None" = None
_install_lock = threading.Lock()
_hints = threading.local()


def get_scheduler() -> "ContinuousBatchingScheduler | None":
    """The process-wide scheduler run_sampler consults, or None (inline)."""
    return _installed


@contextlib.contextmanager
def serving_hints(priority: int = 0, deadline_s: float | None = None):
    """Per-thread policy hints for sampler work submitted inside the block
    (the server worker sets these from POST /prompt extra_data)."""
    prev = getattr(_hints, "value", None)
    _hints.value = {
        "priority": int(priority),
        "deadline": (
            None if deadline_s is None else time.monotonic() + float(deadline_s)
        ),
    }
    try:
        yield
    finally:
        _hints.value = prev


def _current_hints() -> dict:
    return getattr(_hints, "value", None) or {"priority": 0, "deadline": None}


def _kwarg_sig(tree: dict, batch: int):
    """Hashable (name, shape, dtype) signature of a traced-kwargs dict, or
    None if any leaf lacks the per-request batch dim (ineligible — lanes
    stack kwargs along a new axis, so every leaf must be per-request)."""
    sig = []
    for k in sorted(tree):
        v = tree[k]
        if getattr(v, "ndim", 0) < 1 or v.shape[0] != batch:
            return None
        sig.append((k, tuple(v.shape), str(v.dtype)))
    return tuple(sig)


class ContinuousBatchingScheduler:
    """Owns the buckets, the admission policy, and the dispatcher thread."""

    def __init__(self, max_width: int | None = None, max_waiting: int = 64,
                 samplers=BATCHABLE_SAMPLERS, auto: bool = True):
        self.max_width = int(
            max_width if max_width is not None
            else os.environ.get("PA_SERVING_WIDTH", "4")
        )
        self.max_waiting = max_waiting
        self.samplers = frozenset(samplers)
        self.buckets: dict[tuple, StepBucket] = {}  # guarded-by: _lock
        # Degradation-ladder width caps (utils/degrade.py "lane-width-halve"):
        # bucket-key-prefix (the key minus its width component) → the widest
        # lane count the ladder still allows after a dispatch OOM. Applied to
        # every later submission for the same shape, so the shed width stays
        # shed until the process restarts (an OOM is a property of the shape
        # on this device, not of one request).
        self._width_caps: dict[tuple, int] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._pump_lock = threading.Lock()
        self._stop = False
        self._thread = None
        if auto:
            self._thread = threading.Thread(
                target=self._loop, name="pa-serving-dispatcher", daemon=True
            )
            self._thread.start()

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> "ContinuousBatchingScheduler":
        global _installed
        with _install_lock:
            _installed = self
        return self

    def uninstall(self) -> None:
        global _installed
        with _install_lock:
            if _installed is self:
                _installed = None

    def shutdown(self, timeout: float = 10.0) -> None:
        """Stop the dispatcher and resolve every outstanding request with
        Interrupted — no submitter may be left blocked on a dead scheduler."""
        self.uninstall()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        with self._lock:
            buckets = list(self.buckets.values())
            self.buckets.clear()
        for b in buckets:
            while True:
                req = b.queue.pop()
                if req is None:
                    break
                req.resolve(error=Interrupted("scheduler shutdown"))
            for i in b.active_lanes():
                b.lanes[i].req.resolve(error=Interrupted("scheduler shutdown"))
                b.lanes[i] = None

    # -- submission ---------------------------------------------------------

    def maybe_submit(
        self, *, model, x, sigmas, context, sampler, cfg_scale,
        uncond_context, uncond_kwargs, alphas_cumprod, prediction,
        cfg_rescale, model_kwargs, rng=None,
        latent_mask=None, mask_init=None, mask_noise=None,
        extra_conds=(), cond_area=None, cond_area_pct=None, cond_mask=None,
        cond_strength=1.0, cond_mask_strength=1.0, lora=None,
    ) -> ServeRequest | None:
        """Admit one sampler run, or return None when it cannot share a step
        program (caller runs inline). Called from run_sampler with the fully
        prepared (noised x, schedule, conditioning) — the serving layer never
        re-derives sampler semantics; per-step sampler math comes from the
        sampler's LaneStepSpec. ``rng`` is the stochastic base key (the same
        one the eager loop would fold per step).

        Capability state (round 16) rides the request as per-lane data, NOT
        the bucket key — a denoise mask, extra conds, a delegated ControlNet,
        or LoRA factors never fragment buckets, so mixed traffic shares one
        dispatch stream. Eligibility here only checks what the lane program
        cannot absorb (shape/(L,D)/pooled-y mismatches → inline)."""
        if self._stop or sampler not in self.samplers:
            return None
        spec_entry = LANE_SPECS.get(sampler)
        if spec_entry is None:
            return None
        if prediction == "flow" and not spec_entry.flow_ok:
            return None
        if spec_entry.needs_rng and rng is None:
            return None
        from ..utils.progress import current_preview_hook

        if current_preview_hook() is not None:
            # Latent previews are emitted by the inline loops' report_progress
            # (the only preview call site); a lane has no preview channel, so
            # a preview-enabled prompt must keep the inline path.
            return None
        from ..parallel.split import partition_kwargs, static_kwargs_key
        from ..sampling.compiled import trace_spec_of

        b = int(x.shape[0])
        traced, static = partition_kwargs(model_kwargs or {})
        t_sig = _kwarg_sig(traced, b)
        if t_sig is None:
            return None
        use_cfg = uncond_context is not None and cfg_scale != 1.0
        u_traced: dict = {}
        u_sig: tuple = ()
        if use_cfg:
            if getattr(uncond_context, "shape", None) != tuple(context.shape):
                return None
            u_traced, _ = partition_kwargs(uncond_kwargs or {})
            u_sig = _kwarg_sig(u_traced, b)
            if u_sig is None:
                return None
        if context is not None and (
            getattr(context, "ndim", 0) < 1 or context.shape[0] != b
        ):
            return None
        # -- capability eligibility (round 16) --------------------------------
        # ControlNet delegation: an apply_control composition buckets on the
        # BASE model (so control lanes co-batch with plain txt2img of the same
        # UNet) and the control trunk rides the request. Chained compositions
        # publish no delegate (models/controlnet.py) and stay opaque.
        eager_model = None
        control = None
        delegate = getattr(model, "control_delegate", None)
        if delegate is not None and getattr(x, "ndim", 0) == 4:
            base = delegate["base"]
            if trace_spec_of(base) is not None:
                hint = delegate["hint"]
                hb = 1 if getattr(hint, "ndim", 3) == 3 else int(hint.shape[0])
                if hb not in (1, b):
                    # apply_control rejects per-sample hint batches in-graph;
                    # inline surfaces that same ValueError to the caller.
                    return None
                control = {
                    "apply": delegate["ctrl_apply"],
                    "params": delegate["ctrl_params"],
                    "hint": hint,
                    "strength": delegate["strength"],
                    "start": delegate["start"],
                    "end": delegate["end"],
                }
                eager_model = model  # width-1 eager twin keeps the merged net
                model = base
        # Denoise-mask lanes need both blend references (the runner's inline
        # loop derives them; a bare mask cannot reconstruct the keep region).
        if latent_mask is not None:
            if mask_init is None or mask_noise is None:
                return None
            try:
                for ref in (latent_mask, mask_init, mask_noise):
                    if np.broadcast_shapes(
                        tuple(getattr(ref, "shape", ())), tuple(x.shape)
                    ) != tuple(x.shape):
                        return None
            except ValueError:
                return None
        # Multi-cond extras must pin to the primary cond's (L, D) — the lane
        # program stacks every role row in one eval; a different sequence
        # length cannot share the block. Pooled extras need ``y`` in the
        # traced kwargs (the bucket key already carries its shape via t_sig).
        extra_conds = tuple(extra_conds or ())
        if extra_conds:
            if context is None or getattr(context, "ndim", 0) != 3:
                return None
            for e in extra_conds:
                ec = e.get("context")
                if ec is None or getattr(ec, "ndim", 0) != 3:
                    return None
                if tuple(ec.shape[1:]) != tuple(context.shape[1:]):
                    return None
                if int(ec.shape[0]) not in (1, b):
                    return None
                pooled = e.get("pooled")
                if pooled is not None:
                    y = traced.get("y")
                    if (
                        y is None
                        or getattr(pooled, "ndim", 0) != 2
                        or int(pooled.shape[-1]) != int(y.shape[-1])
                        or int(pooled.shape[0]) not in (1, b)
                    ):
                        return None
        spec = trace_spec_of(model)
        # Per-lane LoRA: factors must address the param tree the lane program
        # evals (models/lora.py signature check — None means a path/shape
        # mismatch). Width-1 eager lanes gain nothing over the inline merge.
        lora_factors = None
        if lora:
            if spec is None:
                return None
            from ..models.lora import lora_signature

            sig = lora_signature(lora, spec.params)
            if sig is None:
                return None
            if sig:
                lora_factors = dict(lora)
        width = self.max_width
        bound = getattr(model, "serving_bucket_width", None)
        if callable(bound):
            width = bound(width)
        elif spec is None:
            width = 1
        if spec is not None and spec.mesh is not None:
            n = spec.mesh.shape[spec.data_axis]
            width = max(n, (width // n) * n)
        acp = alphas_cumprod
        if acp is None:
            acp_fp = None
        else:
            # Fingerprint interior samples too, not just the endpoints: two
            # custom schedules agreeing on length and range must not share a
            # bucket (the bucket's log-sigma table comes from the FIRST
            # request's schedule).
            a = np.asarray(acp, np.float64)
            stride = max(1, a.shape[0] // 7)
            acp_fp = (a.shape[0],) + tuple(
                float(v) for v in a[::stride]
            ) + (float(a[-1]),)
        # The sampler is NOT part of the key (round 10): per-lane sampler
        # state/updates ride the lane axis, so lanes running different
        # samplers share one bucket — and one compiled dispatch stream.
        key_prefix = (
            id(model), prediction, use_cfg, float(cfg_rescale),
            tuple(x.shape), str(x.dtype),
            None if context is None
            else (tuple(context.shape), str(context.dtype)),
            static_kwargs_key(static), t_sig, u_sig, acp_fp,
        )
        cap = self._width_caps.get(key_prefix)
        if cap is not None:
            width = min(width, cap)
        key = key_prefix + (width,)
        from ..utils import tracing

        req = ServeRequest(
            x=x, sigmas=np.asarray(sigmas, np.float32), context=context,
            sampler=sampler, rng=rng,
            uncond_context=uncond_context if use_cfg else None,
            traced_kwargs=traced, static_kwargs=static, u_traced=u_traced,
            uncond_kwargs=uncond_kwargs if use_cfg else None,
            cfg_scale=float(cfg_scale), cfg_rescale=float(cfg_rescale),
            prediction=prediction, acp=acp,
            latent_mask=latent_mask, mask_init=mask_init,
            mask_noise=mask_noise, extra_conds=extra_conds,
            cond_area=cond_area, cond_area_pct=cond_area_pct,
            cond_mask=cond_mask, cond_strength=float(cond_strength),
            cond_mask_strength=float(cond_mask_strength),
            control=control, lora=lora_factors, eager_model=eager_model,
            progress_hook=current_progress_hook(),
            interrupt_event=(
                current_scope().interrupt_event
                if current_scope() is not None else None
            ),
            # Trace correlation captured on the SUBMITTING thread: its
            # prompt, its tid (the dispatcher records this request's
            # lane-wait/step/lane spans onto that timeline), its submit time
            # on the trace clock.
            prompt_id=tracing.current_prompt_id() if tracing.on() else None,
            trace_tid=threading.get_ident() if tracing.on() else None,
            trace_submit_us=tracing.now_us() if tracing.on() else None,
            trace_id=tracing.current_trace_id() if tracing.on() else None,
            **_current_hints(),
        )
        with self._lock:
            bucket = self.buckets.get(key)
            if bucket is None:
                name = getattr(model, "name", None) or type(model).__name__
                # No sampler in the label either — a bucket serves the whole
                # k-sampler family in one dispatch stream.
                label = (
                    f"{name}:{prediction}:"
                    f"{'x'.join(str(d) for d in x.shape)}"
                )
                bucket = StepBucket(
                    key, label, width=width, model=model, spec=spec,
                    max_waiting=self.max_waiting,
                )
                self.buckets[key] = bucket
            try:
                bucket.queue.push(req)
            except ServingRejected:
                registry.counter("pa_serving_rejected_total",
                                 labels={"bucket": bucket.label},
                                 help="admissions refused (queue depth bound)")
                return None
            self._cond.notify_all()
        return req

    def cancel(self, rid: str) -> bool:
        """Cancel one request by id — queued entries resolve at the next
        admission sweep, a seated lane frees its slot at the next boundary."""
        with self._lock:
            buckets = list(self.buckets.values())
        for b in buckets:
            req = b.queue.remove(rid)
            if req is not None:
                req.cancel_event.set()
                req.resolve(error=Interrupted("cancelled while queued"))
                return True
            for i in b.active_lanes():
                if b.lanes[i].req.rid == rid:
                    b.lanes[i].req.cancel_event.set()
                    self.kick()
                    return True
        return False

    def kick(self) -> None:
        """Wake the dispatcher (a cancel/interrupt should take effect at the
        next boundary, not the next poll)."""
        with self._cond:
            self._cond.notify_all()

    # -- dispatch -----------------------------------------------------------

    def total_dispatches(self) -> int:
        with self._lock:
            return sum(b.dispatch_count for b in self.buckets.values())

    def reuse_stats(self) -> dict:
        """Sibling-seed cond sharing view (round 17) — the /health
        ``reuse.serving`` section: how many occupied buckets currently run
        the shared-cond broadcast program vs stacked per-lane rows (the
        seat/dispatch totals live on the labeled
        ``pa_serving_{shared_cond_seats,cond_broadcast}_total`` counters)."""
        with self._lock:
            buckets = list(self.buckets.values())
        modes = [b._cond_mode for b in buckets if b.active_lanes()]
        return {
            "buckets_shared_cond": sum(1 for m in modes if m == "shared"),
            "buckets_stacked_cond": sum(1 for m in modes if m == "stacked"),
        }

    def _has_work(self) -> bool:
        return any(not b.idle() for b in self.buckets.values())

    def pump(self) -> bool:
        """One scheduling round: sweep cancels, admit at the boundary, and
        run ONE lockstep dispatch per non-empty bucket. Returns whether any
        bucket dispatched. The dispatcher thread calls this in a loop;
        ``auto=False`` tests call it directly for step-deterministic control."""
        did = False
        with self._pump_lock:
            with self._lock:
                buckets = list(self.buckets.values())
            if interrupt_requested() and any(
                b.active_lanes() or len(b.queue) for b in buckets
            ):
                # Process-wide Cancel (POST /interrupt semantics): every lane
                # and queued request stops at this boundary; the flag is
                # consumed exactly as the inline loops' check_interrupt would.
                clear_interrupt()
                for b in buckets:
                    while True:
                        req = b.queue.pop()
                        if req is None:
                            break
                        req.resolve(error=Interrupted("interrupted while queued"))
                    for i in b.active_lanes():
                        b.lanes[i].req.cancel_event.set()
            for b in buckets:
                b.sweep_cancelled()
                b.admit()
            for b in buckets:
                try:
                    did = b.dispatch() or did
                except Exception as e:  # noqa: BLE001 — no waiter may hang
                    if self._degrade_bucket(b, e):
                        continue  # ladder absorbed it (requests re-seated
                        #           or shed to the inline path)
                    # Resolve EVERY request the dying bucket holds — seated
                    # lanes AND the waiting line — before dropping it, or
                    # their submitters block forever in ticket.result().
                    # (Pop-then-drain, same ordering discipline as the
                    # ladder: no new submission can land in the doomed
                    # bucket after the pop.)
                    with self._lock:
                        self.buckets.pop(b.key, None)
                    for req in self._drain_bucket(b):
                        req.resolve(error=e)
            # Drained buckets release their stacked device arrays (lane
            # state rebuilds from the next admitted request) so an idle
            # serving layer holds no latents/contexts in device memory
            # between bursts.
            for b in buckets:
                if b.idle():
                    b.release_state()
            self._trim_buckets()
        return did

    # -- degradation ladder (utils/degrade.py) -------------------------------

    def _drain_bucket(self, b: StepBucket) -> list:
        """Every request the bucket holds (seated lanes first, then the
        waiting line), with the bucket emptied. Seated requests restart from
        step 0 when re-seated — exactly the fleet-failover replay discipline,
        bitwise-safe by the fold_in RNG contract."""
        reqs = []
        for i in b.active_lanes():
            reqs.append(b.lanes[i].req)
            b.lanes[i] = None
        while True:
            req = b.queue.pop()
            if req is None:
                break
            reqs.append(req)
        return reqs

    def _reseat(self, reqs, model, spec, label: str, key_prefix: tuple,
                width: int) -> None:
        """Park drained requests in a (new) bucket at ``width``; anything the
        admission bound refuses is shed to the inline path rather than lost."""
        from ..utils.degrade import DegradedToInline

        key = key_prefix + (width,)
        with self._lock:
            bucket = self.buckets.get(key)
            if bucket is None:
                bucket = StepBucket(key, label, width=width, model=model,
                                    spec=spec, max_waiting=self.max_waiting)
                self.buckets[key] = bucket
            for req in reqs:
                try:
                    bucket.queue.push(req)
                except ServingRejected as e:
                    req.resolve(error=DegradedToInline(
                        f"re-seat after degradation refused: {e}"
                    ))
            self._cond.notify_all()

    def _degrade_bucket(self, b: StepBucket, e: BaseException) -> bool:
        """The serving OOM/compile ladder: width halve → attn-chunk shrink →
        inline fallback (OOM), or straight to inline on a compile failure.
        Returns True when the ladder absorbed the error (every request the
        bucket held is re-seated or shed — none resolves with ``e``); False
        hands the error back to the caller's resolve-everything path."""
        from ..utils.degrade import (
            DegradedToInline,
            is_compile_failure,
            record_rung,
        )
        from ..utils.telemetry import looks_like_oom

        oom = looks_like_oom(e)
        if not oom and not is_compile_failure(e):
            return False
        # Pop BEFORE draining, under the submit lock: maybe_submit resolves
        # the bucket and pushes inside one lock hold, so after this pop no
        # new request can land in the doomed bucket's queue (a push that
        # raced in earlier is drained below).
        with self._lock:
            self.buckets.pop(b.key, None)
        reqs = self._drain_bucket(b)
        key_prefix = b.key[:-1]
        if not oom:
            # Compile failure on the lane program: the eager inline loop is
            # the fallback program — DegradedToInline routes each submitter
            # there (run_sampler records the compile-eager rung's sibling,
            # inline-fallback, when it lands).
            record_rung("compile-eager",
                        f"bucket {b.label}: lane program compile failed "
                        f"({type(e).__name__}) — requests shed to inline",
                        bucket=b.label)
            for req in reqs:
                req.resolve(error=DegradedToInline(
                    f"lane program compile failure in bucket {b.label}: {e}"
                ))
            return True
        min_width = 1
        if b.spec is not None and b.spec.mesh is not None:
            min_width = b.spec.mesh.shape[b.spec.data_axis]
        new_width = max(min_width, b.width // 2)
        if new_width < b.width:
            record_rung("lane-width-halve",
                        f"bucket {b.label}: {type(e).__name__} at width "
                        f"{b.width} → {new_width}; requests re-seated",
                        bucket=b.label, width_before=b.width,
                        width_after=new_width)
            with self._lock:
                self._width_caps[key_prefix] = new_width
            self._reseat(reqs, b.model, b.spec, b.label, key_prefix, new_width)
            return True
        from ..ops.attention import shrink_chunk_threshold
        from ..sampling.compiled import clear_compiled_loops

        new_chunk = shrink_chunk_threshold()
        if new_chunk is not None:
            # Smaller attention blocks only help once the cached lane
            # programs (traced at the old threshold) are rebuilt.
            clear_compiled_loops()
            record_rung("attn-chunk-shrink",
                        f"bucket {b.label}: width already {b.width}; "
                        f"attention chunk → {new_chunk} elems, programs "
                        f"rebuilt",
                        bucket=b.label, chunk_elems=new_chunk)
            self._reseat(reqs, b.model, b.spec, b.label, key_prefix, b.width)
            return True
        # Ladder spent: shed to the inline path (graceful — the prompts
        # still complete; run_sampler records the inline-fallback rung).
        for req in reqs:
            req.resolve(error=DegradedToInline(
                f"serving OOM ladder exhausted for bucket {b.label}: {e}"
            ))
        return True

    def drain(self, timeout: float = 120.0) -> None:
        """Pump until every bucket is idle (manual mode helper)."""
        t0 = time.monotonic()
        while self._has_work():
            self.pump()
            if time.monotonic() - t0 > timeout:
                raise TimeoutError("serving drain timed out")

    def _trim_buckets(self, keep: int = 32) -> None:
        with self._lock:
            if len(self.buckets) <= keep:
                return
            for key in [k for k, b in self.buckets.items() if b.idle()]:
                if len(self.buckets) <= keep:
                    break
                self.buckets.pop(key)

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                if not self._has_work():
                    self._cond.wait(timeout=0.2)
                    continue
            try:
                self.pump()
            except Exception:  # noqa: BLE001 — the dispatcher must survive
                time.sleep(0.05)
