"""Continuous-batching serving subsystem (round 7; stateful lanes round 10).

Sits between ``server.PromptQueue`` and ``sampling/runner.py``: concurrent
prompts' sampler runs that agree on (model, shape, cfg-mode) — running ANY
sampler in the LaneStepSpec registry, stochastic families included — share
ONE compiled dispatch stream, joining and leaving the fixed-width batch at
step boundaries. See serving/scheduler.py for the architecture overview and
sampling/lane_specs.py for the per-lane step-program family.
"""

from .bucket import ServeRequest, StepBucket, batched_fraction
from .decode import DecodeQueue, DecodeTicket, get_decode_queue
from .policy import AdmissionQueue, DeadlineExceeded, ServingRejected
from .scheduler import (
    BATCHABLE_SAMPLERS,
    ContinuousBatchingScheduler,
    get_scheduler,
    serving_hints,
)

__all__ = [
    "AdmissionQueue",
    "BATCHABLE_SAMPLERS",
    "ContinuousBatchingScheduler",
    "DeadlineExceeded",
    "DecodeQueue",
    "DecodeTicket",
    "ServeRequest",
    "ServingRejected",
    "StepBucket",
    "batched_fraction",
    "get_decode_queue",
    "get_scheduler",
    "serving_hints",
]
