"""Continuous-batching serving subsystem (round 7).

Sits between ``server.PromptQueue`` and ``sampling/runner.py``: concurrent
prompts' sampler runs that agree on (model, shape, sampler, cfg-mode) share
ONE compiled step program, joining and leaving the fixed-width batch at step
boundaries. See serving/scheduler.py for the architecture overview.
"""

from .bucket import ServeRequest, StepBucket
from .policy import AdmissionQueue, DeadlineExceeded, ServingRejected
from .scheduler import (
    BATCHABLE_SAMPLERS,
    ContinuousBatchingScheduler,
    get_scheduler,
    serving_hints,
)

__all__ = [
    "AdmissionQueue",
    "BATCHABLE_SAMPLERS",
    "ContinuousBatchingScheduler",
    "DeadlineExceeded",
    "ServeRequest",
    "ServingRejected",
    "StepBucket",
    "get_scheduler",
    "serving_hints",
]
