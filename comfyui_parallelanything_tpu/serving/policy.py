"""Admission policy for the continuous-batching scheduler.

The reference serves one workflow at a time through ComfyUI's queue (a plain
FIFO, any_device_parallel.py's host); a shared-batch scheduler needs an actual
policy layer: who joins a bucket's next free lane (FIFO within priority),
when a request is refused instead of queued (bounded depth — the 429 surface
``POST /prompt`` exposes), and when a queued request is abandoned (deadline
expiry, client cancel). Pure host-side bookkeeping: nothing here touches jax.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time


class ServingRejected(RuntimeError):
    """Admission refused (bounded queue depth) — the scheduler's caller falls
    back to inline execution; the HTTP layer maps its own depth bound to 429."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before (or while) it held a lane."""


class AdmissionQueue:
    """Priority-FIFO waiting line with a depth bound.

    Ordering: higher ``priority`` first, FIFO (submit order) within a
    priority — the heap key is ``(-priority, seq)``. ``max_waiting`` bounds
    the line; ``push`` raises ServingRejected beyond it (backpressure must be
    explicit — an unbounded line turns overload into silent latency)."""

    _seq = itertools.count()

    def __init__(self, max_waiting: int = 64):
        self.max_waiting = max_waiting
        self._heap: list[tuple[float, int, object]] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)

    def push(self, request) -> None:
        with self._lock:
            if len(self._heap) >= self.max_waiting:
                raise ServingRejected(
                    f"admission queue full ({self.max_waiting} waiting)"
                )
            heapq.heappush(
                self._heap,
                (-float(getattr(request, "priority", 0)), next(self._seq), request),
            )

    def pop(self):
        """Highest-priority oldest request, or None when empty."""
        with self._lock:
            if not self._heap:
                return None
            return heapq.heappop(self._heap)[2]

    def remove(self, rid: str):
        """Remove (and return) the queued request with this id, or None."""
        with self._lock:
            for i, (_, _, req) in enumerate(self._heap):
                if req.rid == rid:
                    entry = self._heap[i]
                    self._heap[i] = self._heap[-1]
                    self._heap.pop()
                    if i < len(self._heap):
                        heapq.heapify(self._heap)
                    return entry[2]
        return None

    def expired(self, now: float | None = None):
        """Pop every queued request whose deadline has passed (resolved by the
        caller with DeadlineExceeded)."""
        now = time.monotonic() if now is None else now
        out = []
        with self._lock:
            keep = []
            for entry in self._heap:
                req = entry[2]
                dl = getattr(req, "deadline", None)
                (out if dl is not None and now >= dl else keep).append(entry)
            if out:
                self._heap = [e for e in keep]
                heapq.heapify(self._heap)
        return [e[2] for e in out]
