"""Shape-bucketed step batches: fixed-width lanes advancing in lockstep.

One ``StepBucket`` owns everything needed to run ONE compiled step program
over a fixed-width batch of lanes (padded, masked), where each lane is one
request at its own position in its own sigma schedule:

- stacked device state ``x[W, b, ...]`` plus per-lane host bookkeeping
  (schedule, step index, request handle) — the "per-lane step state" the
  continuous-batching seam needs;
- step-boundary join/leave: a request enters by ``x.at[lane].set(...)`` at a
  boundary and retires (its slice extracted, its waiter resolved) the moment
  its own schedule completes, while other lanes keep running — ragged
  schedules, lockstep dispatches;
- masking: retired/empty lanes ride along with ``sigma`` pinned to 1 and the
  update ``jnp.where``-selected away, so occupancy can never perturb a live
  lane's values (the model is per-sample independent; the select guarantees
  even a NaN in a pad lane stays in the pad lane).

Two execution modes share the bookkeeping: a compiled per-lane step program
(sampling/compiled.py ``lane_step_program`` — single-program models, width N)
and a width-1 eager mode for models that can never be one XLA program
(weight-streaming / hybrid chains, parallel/orchestrator.py) — those still
gain step-boundary scheduling, cancel, and metrics, just not co-batching.

Bitwise discipline: the Euler math here IS k_samplers.sample_euler with the
scalar sigma generalized per-lane; ``tests/test_serving.py`` pins serial vs
in-batch equivalence at bf16 tolerances on CPU and the 8-device mesh.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Any, Callable, Optional

import numpy as np

from ..utils import tracing
from ..utils.metrics import registry
from ..utils.progress import Interrupted
from .policy import AdmissionQueue, DeadlineExceeded


@dataclasses.dataclass
class ServeRequest:
    """One sampler run handed to the scheduler — the (x, sigmas, conditioning)
    triple run_sampler would otherwise have fed its own eager Euler loop,
    plus the policy/bookkeeping the serving layer adds."""

    x: Any                      # noised start latent [b, ...]
    sigmas: np.ndarray          # (n_steps+1,) descending, host-side
    context: Any
    uncond_context: Any
    traced_kwargs: dict
    static_kwargs: dict
    u_traced: dict
    uncond_kwargs: dict | None
    cfg_scale: float
    cfg_rescale: float
    prediction: str
    acp: Any                    # alphas_cumprod or None (default schedule)
    priority: int = 0
    deadline: float | None = None          # time.monotonic() deadline
    progress_hook: Optional[Callable[[int, int], None]] = None
    interrupt_event: Optional[threading.Event] = None
    # Trace correlation (utils/tracing.py), captured at submit: the prompt the
    # request serves, the submitting thread's tid (the request's spans land on
    # ITS timeline — it is blocked in result() for exactly that interval), and
    # the submit timestamp on the trace clock (lane-wait span start).
    prompt_id: Optional[str] = None
    trace_tid: Optional[int] = None
    trace_submit_us: Optional[float] = None
    rid: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex)
    submit_ts: float = dataclasses.field(default_factory=time.monotonic)

    def __post_init__(self):
        self.cancel_event = threading.Event()
        self._done = threading.Event()
        self._result: Any = None
        self._error: BaseException | None = None

    @property
    def n_steps(self) -> int:
        return len(self.sigmas) - 1

    def cancelled(self) -> bool:
        return self.cancel_event.is_set() or (
            self.interrupt_event is not None and self.interrupt_event.is_set()
        )

    def resolve(self, result=None, error: BaseException | None = None) -> None:
        self._result, self._error = result, error
        self._done.set()

    def result(self, timeout: float | None = None):
        """Block the submitting thread until its lane retires; re-raises the
        lane's error (Interrupted propagates exactly as the inline sampler's
        cooperative check would have raised it)."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"serving request {self.rid} still in flight")
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass
class _Lane:
    req: ServeRequest
    idx: int = 0  # next step to run (sigmas[idx] -> sigmas[idx+1])
    # Width-1 eager mode only: the lane's own latent + denoiser (program mode
    # keeps lane latents stacked in the bucket's device state instead).
    x_eager: Any = None
    denoiser: Any = None
    seat_us: float = 0.0  # trace-clock admission time (the lane span start)


class StepBucket:
    """Fixed-width lockstep batch for one (model, shape, sampler-config) key."""

    def __init__(self, key, label: str, *, width: int, model, spec,
                 max_waiting: int = 64):
        import jax.numpy as jnp

        from ..sampling.k_samplers import model_sigmas
        from ..sampling.schedules import scaled_linear_schedule

        self.key, self.label = key, label
        self.width = max(1, int(width))
        self.model, self.spec = model, spec
        self.queue = AdmissionQueue(max_waiting=max_waiting)
        self.lanes: list[_Lane | None] = [None] * self.width
        self.dispatch_count = 0
        self._program = None
        self._log_sigmas = None
        self._acp_default = None
        # Stacked device state, built from the first admitted request's shapes.
        self._x = None
        self._ctx = None
        self._uctx = None
        self._kw = None
        self._ukw = None
        self._jnp = jnp
        self._model_sigmas = model_sigmas
        self._default_schedule = scaled_linear_schedule
        self._labels = {"bucket": label}

    # -- occupancy ----------------------------------------------------------

    def active_lanes(self) -> list[int]:
        return [i for i, l in enumerate(self.lanes) if l is not None]

    def idle(self) -> bool:
        return not self.active_lanes() and len(self.queue) == 0

    def release_state(self) -> None:
        """Drop the stacked device arrays while idle — an idle serving layer
        must not pin width×batch latents/contexts in device memory between
        bursts. Rebuilt by ``_ensure_state`` on the next admission (the
        compiled step program itself stays in the bounded loop-jit cache)."""
        self._x = self._ctx = self._uctx = self._kw = self._ukw = None

    def _gauges(self) -> None:
        registry.gauge("pa_serving_occupancy", len(self.active_lanes()),
                       labels=self._labels,
                       help="live lanes in the bucket's step batch")
        registry.gauge("pa_serving_queue_depth", len(self.queue),
                       labels=self._labels,
                       help="requests waiting for a lane")

    # -- state assembly -----------------------------------------------------

    def _zeros_stack(self, template):
        """[W, *template.shape] zeros matching the template's dtype, lane-axis
        sharded when the bucket runs over a mesh (composes with the chain's
        data sharding: the lane axis IS the batch axis the orchestrator
        shards)."""
        import jax

        jnp = self._jnp

        def leaf(l):
            z = jnp.zeros((self.width,) + tuple(l.shape), l.dtype)
            if self.spec is not None and self.spec.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                z = jax.device_put(
                    z, NamedSharding(self.spec.mesh, P(self.spec.data_axis))
                )
            return z

        return jax.tree.map(leaf, template)

    def _ensure_state(self, req: ServeRequest) -> None:
        if self.spec is None or self._x is not None:
            return
        self._x = self._zeros_stack(req.x)
        self._ctx = (
            None if req.context is None else self._zeros_stack(req.context)
        )
        self._uctx = (
            None if req.uncond_context is None
            else self._zeros_stack(req.uncond_context)
        )
        self._kw = self._zeros_stack(req.traced_kwargs) if req.traced_kwargs else None
        self._ukw = self._zeros_stack(req.u_traced) if req.u_traced else None
        if req.prediction != "flow":
            acp = req.acp if req.acp is not None else self._default_schedule()
            self._log_sigmas = self._jnp.log(self._model_sigmas(acp))
        from ..sampling.compiled import lane_step_program

        self._program = lane_step_program(
            self.spec,
            prediction=req.prediction,
            use_cfg=req.uncond_context is not None and req.cfg_scale != 1.0,
            cfg_rescale=req.cfg_rescale,
            static_kwargs=req.static_kwargs,
        )

    def _set_lane(self, i: int, req: ServeRequest) -> None:
        import jax

        self._ensure_state(req)
        lane = _Lane(req)
        if self.spec is not None:
            self._x = self._x.at[i].set(req.x)
            if self._ctx is not None:
                self._ctx = self._ctx.at[i].set(req.context)
            if self._uctx is not None:
                self._uctx = self._uctx.at[i].set(req.uncond_context)
            if self._kw is not None:
                self._kw = jax.tree.map(
                    lambda stack, v: stack.at[i].set(v),
                    self._kw, req.traced_kwargs,
                )
            if self._ukw is not None:
                self._ukw = jax.tree.map(
                    lambda stack, v: stack.at[i].set(v), self._ukw, req.u_traced
                )
        else:
            from ..sampling.k_samplers import EpsDenoiser

            lane.x_eager = req.x
            lane.denoiser = EpsDenoiser(
                self.model, req.context, cfg_scale=req.cfg_scale,
                uncond_context=req.uncond_context,
                uncond_kwargs=req.uncond_kwargs,
                alphas_cumprod=req.acp, prediction=req.prediction,
                cfg_rescale=req.cfg_rescale,
                **req.traced_kwargs, **req.static_kwargs,
            )
        self.lanes[i] = lane

    # -- scheduling ---------------------------------------------------------

    def admit(self, now: float | None = None) -> int:
        """Fill free lanes from the waiting line (policy order), resolving
        expired/cancelled entries instead of seating them. Returns how many
        joined — always at a step boundary (the dispatcher calls this between
        dispatches, never mid-step)."""
        now = time.monotonic() if now is None else now
        for req in self.queue.expired(now):
            req.resolve(error=DeadlineExceeded(
                f"deadline passed after {now - req.submit_ts:.3f}s waiting"
            ))
            registry.counter("pa_serving_expired_total", labels=self._labels)
        joined = 0
        for i in range(self.width):
            if self.lanes[i] is not None:
                continue
            req = self.queue.pop()
            if req is None:
                break
            if req.cancelled():
                req.resolve(error=Interrupted("cancelled while queued"))
                registry.counter("pa_serving_cancelled_total", labels=self._labels)
                continue
            self._set_lane(i, req)
            joined += 1
            registry.histogram(
                "pa_serving_lane_wait_seconds", now - req.submit_ts,
                labels=self._labels,
                help="submit-to-lane admission wait",
            )
            if tracing.on():
                # admission→lane-assign on the submitter's timeline: one
                # completed span from submit to seat (both trace-clock).
                self.lanes[i].seat_us = tracing.now_us()
                if req.trace_submit_us is not None:
                    tracing.record(
                        "lane-wait", req.trace_submit_us,
                        self.lanes[i].seat_us - req.trace_submit_us,
                        cat="serving", tid=req.trace_tid,
                        prompt_id=req.prompt_id, bucket=self.label, lane=i,
                        rid=req.rid, queue_depth=len(self.queue),
                    )
        if joined:
            self._gauges()
        return joined

    def _retire(self, i: int, result=None, error=None) -> None:
        lane = self.lanes[i]
        self.lanes[i] = None
        if tracing.on() and lane.seat_us:
            # lane-assign→retire on the submitter's timeline; the per-step
            # spans recorded by dispatch() nest inside this interval.
            tracing.record(
                "lane", lane.seat_us, tracing.now_us() - lane.seat_us,
                cat="serving", tid=lane.req.trace_tid,
                prompt_id=lane.req.prompt_id, bucket=self.label, lane=i,
                rid=lane.req.rid, steps_run=lane.idx,
                outcome="error" if error is not None else "completed",
            )
        lane.req.resolve(result=result, error=error)
        registry.counter(
            "pa_serving_cancelled_total" if error is not None
            else "pa_serving_completed_total",
            labels=self._labels,
        )

    def sweep_cancelled(self) -> int:
        """Retire lanes whose request was cancelled (client cancel, per-prompt
        interrupt, deadline) — frees the slot at the boundary WITHOUT touching
        the stacked state: the lane goes inactive-masked, so neighbors are
        untouched by construction."""
        now = time.monotonic()
        swept = 0
        for i in self.active_lanes():
            req = self.lanes[i].req
            if req.cancelled():
                self._retire(i, error=Interrupted(
                    f"cancelled mid-batch at step {self.lanes[i].idx}"
                ))
                swept += 1
            elif req.deadline is not None and now >= req.deadline:
                self._retire(i, error=DeadlineExceeded(
                    f"deadline passed at step {self.lanes[i].idx}"
                ))
                swept += 1
        if swept:
            self._gauges()
        return swept

    def dispatch(self) -> bool:
        """Run ONE lockstep step for every active lane (one compiled dispatch
        in program mode), advance per-lane indices, fire per-lane progress
        hooks, retire finished lanes. Returns False when there was nothing to
        run."""
        active = self.active_lanes()
        if not active:
            return False
        import jax

        jnp = self._jnp
        t0_us = tracing.now_us() if tracing.on() else 0.0
        t0 = time.perf_counter()
        if self._program is not None:
            sig = np.ones((self.width,), np.float32)
            sig_next = np.ones((self.width,), np.float32)
            act = np.zeros((self.width,), np.float32)
            cfg = np.ones((self.width,), np.float32)
            for i in active:
                lane = self.lanes[i]
                sig[i] = lane.req.sigmas[lane.idx]
                sig_next[i] = lane.req.sigmas[lane.idx + 1]
                act[i] = 1.0
                cfg[i] = lane.req.cfg_scale
            self._x = self._program(
                self.spec.params, self._x, jnp.asarray(sig),
                jnp.asarray(sig_next), jnp.asarray(act), jnp.asarray(cfg),
                self._ctx, self._uctx, self._kw, self._ukw, self._log_sigmas,
            )
            jax.block_until_ready(self._x)
        else:
            # Width-1 eager mode (streaming/hybrid models): the exact
            # sample_euler step per lane, one model call each.
            for i in active:
                lane = self.lanes[i]
                s = jnp.float32(lane.req.sigmas[lane.idx])
                s_next = jnp.float32(lane.req.sigmas[lane.idx + 1])
                x0 = lane.denoiser(lane.x_eager, s)
                d = (lane.x_eager - x0) / s
                lane.x_eager = lane.x_eager + d * (s_next - s)
            jax.block_until_ready([self.lanes[i].x_eager for i in active])
        dt = time.perf_counter() - t0
        self.dispatch_count += 1
        registry.counter("pa_serving_dispatch_total", labels=self._labels,
                         help="compiled lockstep step dispatches")
        registry.histogram("pa_serving_step_seconds", dt, labels=self._labels,
                           help="wall time of one lockstep dispatch")
        if tracing.on() and t0_us:
            # (t0_us guards the enable-raced-mid-dispatch case: never emit a
            # span whose start predates the trace.)
            dur_us = tracing.now_us() - t0_us
            # One dispatcher-side span (per-dispatch occupancy + masked-lane
            # count) ...
            tracing.record(
                "serving-dispatch", t0_us, dur_us, cat="serving",
                bucket=self.label, occupancy=len(active),
                masked_lanes=self.width - len(active), width=self.width,
            )
            # ... and one step span per live lane on its OWN prompt's
            # timeline (the submitter is blocked in result() for exactly this
            # interval, so per-tid nesting holds). The dispatch already
            # blocked on the step output above — the duration is honest, and
            # tracing added no sync of its own.
            for i in active:
                lane = self.lanes[i]
                tracing.record(
                    "step", t0_us, dur_us, cat="serving",
                    tid=lane.req.trace_tid, prompt_id=lane.req.prompt_id,
                    bucket=self.label, lane=i, step=lane.idx + 1,
                    of=lane.req.n_steps, occupancy=len(active),
                )
        for i in active:
            lane = self.lanes[i]
            lane.idx += 1
            hook = lane.req.progress_hook
            if hook is not None:
                try:
                    hook(lane.idx, lane.req.n_steps)
                except Exception:  # noqa: BLE001 — a UI hook must not kill lanes
                    pass
            if lane.idx >= lane.req.n_steps:
                result = (
                    self._x[i] if self._program is not None else lane.x_eager
                )
                self._retire(i, result=result)
        self._gauges()
        return True
