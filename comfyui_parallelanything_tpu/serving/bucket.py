"""Shape-bucketed step batches: fixed-width stateful lanes in lockstep.

One ``StepBucket`` owns everything needed to run ONE compiled step program
over a fixed-width batch of lanes (padded, masked), where each lane is one
request at its own position in its own sigma schedule, running its OWN
sampler (round 10 — the dispatch unit is one batched model eval, not one
sampler's step):

- stacked device state ``(x, xe, h1, h2)[W, b, ...]`` — latent, next eval
  input, and two history slots (the lane form of the fused-loop carries,
  e.g. dpmpp_2m's ``old_x0``) — plus per-lane host bookkeeping (the
  sampler's eval-ordered ``StepPlan`` list from sampling/lane_specs.py, a
  plan counter, precomputed per-step noise-key table, request handle);
- step-boundary join/leave: a request enters by ``x.at[lane].set(...)`` at a
  boundary (history slots zeroed — the lane state-pytree init) and retires
  (its slice extracted, its waiter resolved) the moment its own EVAL count
  completes, while other lanes keep running — ragged schedules, mixed
  sampler families, lockstep dispatches;
- masking: retired/empty lanes ride along with ``sigma`` pinned to 1,
  identity update coefficients, and the ``jnp.where`` select, so occupancy
  can never perturb a live lane's values (the model is per-sample
  independent; the select guarantees even a NaN in a pad lane stays there);
- stochastic lanes: the step-``i`` key is ``fold_in(request rng, i)`` —
  keys are precomputed per request at seat time, so noise is a pure
  function of (request, step) and output is bit-identical alone vs
  co-batched (the occupancy-determinism contract);
- sibling-seed cond sharing (round 17): a fresh cond epoch runs SHARED —
  every lane references ONE cond tensor broadcast on the lane axis inside
  the program (``lane_step_program(broadcast_cond=True)``) instead of
  stacked per lane, so an N-seed fanout of one prompt (whose requests
  alias one cond object via the embed cache) costs one cond in HBM and
  ceil(N/width) dispatches per eval; the first foreign cond demotes to
  stacked rows (a mode change, never a value change — siblings' rows
  refill from the shared ref), and an idle release resets the epoch;
- numerics quarantine (round 11, utils/numerics.py): with the sentinel on,
  every dispatch also emits per-lane non-finite counts and bf16 latent
  digests as on-device aux outputs; a lane whose state goes NaN/Inf is
  retired at that boundary through the SAME select-mask discipline (its
  submitter gets :class:`~..utils.numerics.NonFiniteLatent`, survivors are
  untouched by construction), with a ``write_postmortem`` bundle naming the
  first offending block (PipelineSpec bisection re-run), step, and σ. The
  reference's only numeric-failure story is whole-run OOM degradation
  (any_device_parallel.py:1114-1128, 1435-1448) — here one poisoned lane
  costs one lane.

Two execution modes share the bookkeeping: a compiled per-lane step program
(sampling/compiled.py ``lane_step_program`` — single-program models, width N)
and a width-1 eager mode for models that can never be one XLA program
(weight-streaming / hybrid chains, parallel/orchestrator.py) — those walk
the SAME StepPlans against their own denoiser, gaining step-boundary
scheduling, the full sampler family, cancel, and metrics, just not
co-batching.

Bitwise discipline: the update math here IS each sampler's ``k_samplers``
twin with the schedule-derived scalars host-lifted per lane;
``tests/test_serving.py`` pins the full registry's lane-vs-solo equivalence
at bf16 tolerances on CPU and the 8-device mesh.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
import uuid
from typing import Any, Callable, Optional

import numpy as np

from ..sampling.lane_specs import LANE_SPECS, StepPlan, plan_schedule
from ..utils import numerics, slo, tracing
from ..utils.metrics import registry
from ..utils.progress import Interrupted
from .policy import AdmissionQueue, DeadlineExceeded

# Identity update for padded/retired lanes: x'=x, xe'=xe, h1'=h1, h2'=h2 —
# the host-side twin of the program's active-mask select.
_IDENTITY_COEF = np.zeros((4, 6), np.float32)
for _j, _k in ((0, 0), (1, 1), (2, 3), (3, 4)):
    _IDENTITY_COEF[_j, _k] = 1.0
del _j, _k

# Process-wide shared-dispatch accounting: lane-steps served in dispatches
# with occupancy > 1, over all lane-steps — the pa_serving_batched_fraction
# gauge (ISSUE 5 satellite; surfaced in GET /health and loadgen output).
_batch_stats = {"total": 0, "shared": 0}
_batch_lock = threading.Lock()


def record_dispatch_occupancy(occupancy: int) -> None:
    """Account one dispatch's lane-steps and refresh the fraction gauge."""
    with _batch_lock:
        _batch_stats["total"] += occupancy
        if occupancy > 1:
            _batch_stats["shared"] += occupancy
        frac = _batch_stats["shared"] / max(1, _batch_stats["total"])
    registry.gauge(
        "pa_serving_batched_fraction", frac,
        help="lane-steps served via shared dispatch / total lane-steps",
    )


def batched_fraction() -> float:
    """Lane-steps served via shared (occupancy>1) dispatch / total."""
    with _batch_lock:
        return _batch_stats["shared"] / max(1, _batch_stats["total"])


@dataclasses.dataclass
class ServeRequest:
    """One sampler run handed to the scheduler — the (x, sigmas, conditioning)
    triple run_sampler would otherwise have fed its own eager Euler loop,
    plus the policy/bookkeeping the serving layer adds."""

    x: Any                      # noised start latent [b, ...]
    sigmas: np.ndarray          # (n_steps+1,) descending, host-side
    context: Any
    uncond_context: Any
    traced_kwargs: dict
    static_kwargs: dict
    u_traced: dict
    uncond_kwargs: dict | None
    cfg_scale: float
    cfg_rescale: float
    prediction: str
    acp: Any                    # alphas_cumprod or None (default schedule)
    sampler: str = "euler"      # LaneStepSpec registry name
    rng: Any = None             # stochastic base key (None → deterministic)
    # Capability state (round 16, universal lane batching) — everything a
    # feature-carrying request needs rides the request itself, so a
    # degradation-ladder re-seat (_drain_bucket → _reseat) reconstructs the
    # full per-lane state from step 0, not just (x, xe, h1, h2).
    latent_mask: Any = None     # denoise mask (img2img/inpaint), 1 = denoise
    mask_init: Any = None       # keep-region init latent reference
    mask_noise: Any = None      # keep-region unit-noise reference
    extra_conds: tuple = ()     # multi-cond CFG extras (EpsDenoiser schema)
    cond_area: Any = None       # primary-cond scoping (SetArea family)
    cond_area_pct: Any = None
    cond_mask: Any = None
    cond_strength: float = 1.0
    cond_mask_strength: float = 1.0
    control: dict | None = None  # {"apply", "params", "hint", "strength",
                                 #  "start", "end"} from model.control_delegate
    lora: dict | None = None    # {param_path: (a, b)} — W_eff = W + b @ a
    eager_model: Any = None     # width-1 eager twin (merged control/LoRA)
    priority: int = 0
    deadline: float | None = None          # time.monotonic() deadline
    progress_hook: Optional[Callable[[int, int], None]] = None
    interrupt_event: Optional[threading.Event] = None
    # Trace correlation (utils/tracing.py), captured at submit: the prompt the
    # request serves, the submitting thread's tid (the request's spans land on
    # ITS timeline — it is blocked in result() for exactly that interval), and
    # the submit timestamp on the trace clock (lane-wait span start).
    prompt_id: Optional[str] = None
    trace_tid: Optional[int] = None
    trace_submit_us: Optional[float] = None
    # Distributed trace identity captured on the submitting thread (the
    # fleet traceparent's trace_id) — the dispatcher stamps it onto this
    # request's lane-wait/step/lane spans, same rule as trace_tid.
    trace_id: Optional[str] = None
    rid: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex)
    submit_ts: float = dataclasses.field(default_factory=time.monotonic)

    def __post_init__(self):
        self.cancel_event = threading.Event()
        self._done = threading.Event()
        self._result: Any = None
        self._error: BaseException | None = None

    @property
    def n_steps(self) -> int:
        return len(self.sigmas) - 1

    def cancelled(self) -> bool:
        return self.cancel_event.is_set() or (
            self.interrupt_event is not None and self.interrupt_event.is_set()
        )

    def resolve(self, result=None, error: BaseException | None = None) -> None:
        self._result, self._error = result, error
        self._done.set()

    def result(self, timeout: float | None = None):
        """Block the submitting thread until its lane retires; re-raises the
        lane's error (Interrupted propagates exactly as the inline sampler's
        cooperative check would have raised it)."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"serving request {self.rid} still in flight")
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass
class _Lane:
    req: ServeRequest
    idx: int = 0   # σ-intervals completed (progress unit)
    pc: int = 0    # next StepPlan to run (the eval unit — 2/interval for
                   # second-order samplers)
    plans: list = dataclasses.field(default_factory=list)
    keys: Any = None  # [n_steps, 2, key_width] uint32 noise-key table or None
    # Width-1 eager mode only: the lane's own state pytree + denoiser
    # (program mode keeps lane state stacked in the bucket's device arrays).
    x_eager: Any = None
    xe_eager: Any = None
    h1_eager: Any = None
    h2_eager: Any = None
    denoiser: Any = None
    seat_us: float = 0.0  # trace-clock admission time (the lane span start)
    # Numerics sentinel (utils/numerics.py): per-eval bf16 digests of this
    # lane's latent — the (request, step) fingerprint stack, recorded into
    # the sentinel's ring at retirement. Empty when the sentinel is off.
    digests: list = dataclasses.field(default_factory=list)

    def plan(self) -> StepPlan:
        return self.plans[self.pc]

    def done(self) -> bool:
        return self.pc >= len(self.plans)


def _lane_key_table(rng, n_steps: int, split: bool):
    """[n_steps, 2, key_width] uint32 per-step key data under the fold_in
    discipline; columns are the ``split(fold_in(rng, i))`` halves when
    ``split`` (dpmpp_sde's mid/end draws), else both the per-step key. One
    tiny vmapped dispatch per admission — the whole table is then host-side
    numpy, indexed per dispatch with zero device work."""
    import jax
    import jax.numpy as jnp

    if rng is None or n_steps <= 0:
        return None
    base = rng
    if not jnp.issubdtype(jnp.asarray(base).dtype, jax.dtypes.prng_key):
        base = jax.random.wrap_key_data(jnp.asarray(base, jnp.uint32))
    ks = jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(n_steps))
    if split:
        data = jax.random.key_data(jax.vmap(jax.random.split)(ks))
    else:
        d = jax.random.key_data(ks)
        data = jnp.stack([d, d], axis=1)
    return np.asarray(data)


def _noise_key_row(lane: "_Lane", plan: StepPlan):
    """The lane's key data for this plan's draw, or None when no draw."""
    if plan.noise is None or lane.keys is None:
        return None
    col = 1 if plan.noise == "sde_end" else 0
    return lane.keys[plan.step, col]


class StepBucket:
    """Fixed-width lockstep batch for one (model, shape, sampler-config) key."""

    def __init__(self, key, label: str, *, width: int, model, spec,
                 max_waiting: int = 64):
        import jax.numpy as jnp

        from ..sampling.k_samplers import model_sigmas
        from ..sampling.schedules import scaled_linear_schedule

        self.key, self.label = key, label
        self.width = max(1, int(width))
        self.model, self.spec = model, spec
        self.queue = AdmissionQueue(max_waiting=max_waiting)
        self.lanes: list[_Lane | None] = [None] * self.width
        self.dispatch_count = 0
        self._program = None
        self._prog_kw = None
        # Sentinel state captured at program build (the stats/digest aux
        # outputs are part of the compiled signature); width-1 eager mode
        # reads numerics.on() live instead.
        self._emit_stats = False
        self._log_sigmas = None
        self._acp_default = None
        # Stacked device state, built from the first admitted request's
        # shapes: latent, eval input, and the two per-lane history slots.
        self._x = None
        self._xe = None
        self._h1 = None
        self._h2 = None
        self._ctx = None
        self._uctx = None
        self._kw = None
        self._ukw = None
        # Sibling-seed cond sharing (round 17): a fresh cond epoch starts
        # in "shared" mode — every lane references ONE cond tensor,
        # broadcast over the lane axis inside the program
        # (sampling/compiled.py broadcast_cond) instead of stacked per
        # lane, so an N-seed fanout of one prompt costs one cond in HBM.
        # The first seat whose cond is a DIFFERENT object demotes the
        # bucket to "stacked" (per-lane rows) until the state releases.
        # Identity is the sharing signal: the embed cache returns one
        # object per (model, text), so same-prompt requests alias by
        # construction.
        self._cond_mode = None        # "shared" | "stacked"
        self._ctx_ref = None          # identity refs (original objects)
        self._uctx_ref = None
        self._ctx_dev = None          # placed shared copies (mesh: replicated)
        self._uctx_dev = None
        # Traced-kwargs sharing (PR 12 remainder): the SAME state machine
        # for the traced kwarg trees — pooled ``y`` vectors, ``guidance``,
        # and the negative-prompt/uncond extras (``u_traced``) — which a
        # sibling-seed fanout also aliases by object identity. Tracked
        # independently of the cond mode: siblings that share the prompt
        # cond but carry per-request kwargs still ride the broadcast-cond
        # program with stacked kwargs, and vice versa.
        self._kw_mode = None          # "shared" | "stacked"
        self._kw_ref = None           # identity refs (original trees)
        self._ukw_ref = None
        self._kw_dev = None           # placed shared copies (mesh: replicated)
        self._ukw_dev = None
        # Capability overlays (round 16, universal lane batching). The
        # denoise-mask axis is ALWAYS-ON — zero stacks built with the state,
        # no program variant, so any txt2img/img2img mix shares ONE program
        # bitwise. Multi-cond / ControlNet / LoRA overlays materialize
        # lazily the first time a carrying request seats: each
        # materialization swaps the program variant once per bucket epoch
        # (the PR 12 shared→stacked demotion precedent), after which any
        # traffic mix rides the variant without recompiling. Every overlay
        # keeps zero rows structurally inert (zero mask gate / zero weight
        # map / zero residual gain / zero factors), so non-carrying lanes
        # pass through bitwise.
        self._mask = None             # [W, b, ...] f32 denoise masks
        self._mask_init = None        # [W, b, ...] keep-region init latents
        self._mask_noise = None       # [W, b, ...] keep-region unit noise
        self._mask_has = np.zeros(self.width, bool)   # host gate source
        self._mc_k = None             # None → overlay off; else bucket max K
        self._mc_has_y = False
        self._mc_w0 = None            # [W, b, ..., 1] primary weight maps
        self._mc_ctx = None           # [W, K, b, L, D] extra cond rows
        self._mc_w = None             # [W, K, b, ..., 1] extra weight maps
        self._mc_y = None             # [W, K, b, Y] pooled rows (has_y only)
        self._mc_win = None           # host [W, K, 2] progress windows
        self._ctrl = None             # {"apply", "params", "params_ref"}
        self._ctrl_hint = None        # [W, b, H8, W8, C] hint stack
        self._ctrl_strength = np.zeros(self.width, np.float32)
        self._ctrl_win = np.tile(
            np.asarray([0.0, 1.0], np.float32), (self.width, 1)
        )
        self._lora_sig = ()           # ordered ((path, m, k), ...)
        self._lora_rmax = 0
        self._lora_ab = []            # per path: (a[W,r,k], b[W,m,r]) stacks
        self._jnp = jnp
        self._model_sigmas = model_sigmas
        self._default_schedule = scaled_linear_schedule
        self._labels = {"bucket": label}

    # -- occupancy ----------------------------------------------------------

    def active_lanes(self) -> list[int]:
        return [i for i, l in enumerate(self.lanes) if l is not None]

    def idle(self) -> bool:
        return not self.active_lanes() and len(self.queue) == 0

    def release_state(self) -> None:
        """Drop the stacked device arrays while idle — an idle serving layer
        must not pin width×batch latents/contexts in device memory between
        bursts. Rebuilt by ``_ensure_state`` on the next admission (the
        compiled step program itself stays in the bounded loop-jit cache).
        Also resets the cond mode: the next burst re-enters shared-cond
        from scratch."""
        self._x = self._xe = self._h1 = self._h2 = None
        self._ctx = self._uctx = self._kw = self._ukw = None
        self._cond_mode = None
        self._ctx_ref = self._uctx_ref = None
        self._ctx_dev = self._uctx_dev = None
        self._kw_mode = None
        self._kw_ref = self._ukw_ref = None
        self._kw_dev = self._ukw_dev = None
        # Capability overlays drop with the state: the next burst re-enters
        # the overlay-free (cheapest) program variant from scratch.
        self._mask = self._mask_init = self._mask_noise = None
        self._mask_has = np.zeros(self.width, bool)
        self._mc_k = None
        self._mc_has_y = False
        self._mc_w0 = self._mc_ctx = self._mc_w = self._mc_y = None
        self._mc_win = None
        self._ctrl = None
        self._ctrl_hint = None
        self._ctrl_strength = np.zeros(self.width, np.float32)
        self._ctrl_win = np.tile(
            np.asarray([0.0, 1.0], np.float32), (self.width, 1)
        )
        self._lora_sig = ()
        self._lora_rmax = 0
        self._lora_ab = []
        self._program = None

    def _gauges(self) -> None:
        registry.gauge("pa_serving_occupancy", len(self.active_lanes()),
                       labels=self._labels,
                       help="live lanes in the bucket's step batch")
        registry.gauge("pa_serving_queue_depth", len(self.queue),
                       labels=self._labels,
                       help="requests waiting for a lane")

    # -- state assembly -----------------------------------------------------

    def _zeros_stack(self, template):
        """[W, *template.shape] zeros matching the template's dtype, lane-axis
        sharded when the bucket runs over a mesh (composes with the chain's
        data sharding: the lane axis IS the batch axis the orchestrator
        shards)."""
        import jax

        jnp = self._jnp

        def leaf(l):
            z = jnp.zeros((self.width,) + tuple(l.shape), l.dtype)
            if self.spec is not None and self.spec.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                z = jax.device_put(
                    z, NamedSharding(self.spec.mesh, P(self.spec.data_axis))
                )
            return z

        return jax.tree.map(leaf, template)

    def _ensure_state(self, req: ServeRequest) -> None:
        if self.spec is None or self._x is not None:
            return
        self._x = self._zeros_stack(req.x)
        self._xe = self._zeros_stack(req.x)
        self._h1 = self._zeros_stack(req.x)
        self._h2 = self._zeros_stack(req.x)
        # Denoise-mask stacks are always-on (the mask axis has no program
        # variant): zero rows + a zero host gate make maskless lanes a
        # structural where-pass-through inside the program.
        self._mask = self._zeros_stack(
            self._jnp.zeros(req.x.shape, self._jnp.float32)
        )
        self._mask_init = self._zeros_stack(req.x)
        self._mask_noise = self._zeros_stack(req.x)
        # Traced-kwargs stacks build lazily: a fresh epoch enters SHARED
        # kwargs mode (_seat_kwargs), so the [W, ...] stacks only exist
        # after a foreign-kwargs demotion.
        if req.prediction != "flow":
            acp = req.acp if req.acp is not None else self._default_schedule()
            self._log_sigmas = self._jnp.log(self._model_sigmas(acp))
        # Program meta (bucket-key constants) banked once; the program
        # itself builds lazily per cond mode (_ensure_program) — a
        # shared→stacked demotion swaps the broadcast_cond variant, and
        # both live in the bounded loop-jit cache.
        self._emit_stats = numerics.on()
        self._prog_kw = dict(
            prediction=req.prediction,
            use_cfg=req.uncond_context is not None and req.cfg_scale != 1.0,
            cfg_rescale=req.cfg_rescale,
            static_kwargs=req.static_kwargs,
        )

    def _ensure_program(self) -> None:
        if self._program is not None or self.spec is None:
            return
        from ..sampling.compiled import lane_step_program

        self._program = lane_step_program(
            self.spec,
            emit_stats=self._emit_stats,
            broadcast_cond=self._cond_mode == "shared",
            broadcast_kwargs=self._kw_mode == "shared",
            n_extra=self._mc_k,
            mc_has_y=self._mc_has_y,
            control_apply=None if self._ctrl is None else self._ctrl["apply"],
            lora_sig=self._lora_sig,
            **self._prog_kw,
        )

    def _place_shared(self, arr):
        """The shared cond tensor as the program input: replicated over the
        mesh when the bucket runs on one (the lane-axis sharding belongs to
        the state stacks; the broadcast happens inside the program)."""
        if arr is None:
            return None
        if self.spec is not None and self.spec.mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(arr, NamedSharding(self.spec.mesh, P()))
        return arr

    def _seat_cond(self, i: int, req: ServeRequest) -> None:
        """Seat lane ``i``'s conditioning. Fresh epochs (no other live lane)
        enter SHARED mode: the request's cond objects become the bucket's
        refs and every sibling whose cond is the SAME object (the embed
        cache's same-prompt aliasing) rides the broadcast program. The
        first foreign cond demotes to STACKED per-lane rows — re-filling
        the seated siblings' rows from the shared refs, so demotion is a
        mode change, never a value change."""
        others = [j for j in self.active_lanes() if j != i]
        if not others:
            self._cond_mode = "shared"
            self._ctx_ref = req.context
            self._uctx_ref = req.uncond_context
            self._ctx_dev = self._place_shared(req.context)
            self._uctx_dev = self._place_shared(req.uncond_context)
            self._ctx = self._uctx = None
            self._program = None
            return
        if self._cond_mode == "shared":
            if req.context is self._ctx_ref \
                    and req.uncond_context is self._uctx_ref:
                registry.counter(
                    "pa_serving_shared_cond_seats_total",
                    labels=self._labels,
                    help="lanes seated against an already-shared cond "
                         "tensor (sibling-seed reuse)",
                )
                return
            self._cond_mode = "stacked"
            self._ctx = (
                None if self._ctx_ref is None
                else self._zeros_stack(self._ctx_ref)
            )
            self._uctx = (
                None if self._uctx_ref is None
                else self._zeros_stack(self._uctx_ref)
            )
            for j in others:
                if self._ctx is not None:
                    self._ctx = self._ctx.at[j].set(self.lanes[j].req.context)
                if self._uctx is not None:
                    self._uctx = self._uctx.at[j].set(
                        self.lanes[j].req.uncond_context
                    )
            self._ctx_ref = self._uctx_ref = None
            self._ctx_dev = self._uctx_dev = None
            self._program = None
        if self._ctx is not None:
            self._ctx = self._ctx.at[i].set(req.context)
        if self._uctx is not None:
            self._uctx = self._uctx.at[i].set(req.uncond_context)

    def _place_shared_tree(self, tree):
        if not tree:
            return None
        import jax

        return jax.tree.map(self._place_shared, tree)

    @staticmethod
    def _same_tree(a, b) -> bool:
        """Leaf-for-leaf OBJECT identity — the sharing signal (the embed
        cache / node layer hands siblings the same arrays)."""
        if a is b:
            return True
        if a is None or b is None:
            return False
        import jax

        la, ta = jax.tree.flatten(a)
        lb, tb = jax.tree.flatten(b)
        return ta == tb and all(x is y for x, y in zip(la, lb))

    def _seat_kwargs(self, i: int, req: ServeRequest) -> None:
        """Seat lane ``i``'s traced kwargs under the same shared/stacked
        state machine as ``_seat_cond`` (PR 12 remainder): fresh epochs
        share the request's kwarg trees — ``traced_kwargs`` AND the
        negative-prompt/uncond ``u_traced`` — as ONE broadcast program
        input; the first seat whose trees are not the same objects
        leaf-for-leaf demotes to stacked per-lane rows, refilled from the
        seated siblings' own requests (a mode change, never a value
        change)."""
        import jax

        kw = req.traced_kwargs or None
        ukw = req.u_traced or None
        others = [j for j in self.active_lanes() if j != i]
        if not others:
            self._kw_mode = "shared"
            self._kw_ref = kw
            self._ukw_ref = ukw
            self._kw_dev = self._place_shared_tree(kw)
            self._ukw_dev = self._place_shared_tree(ukw)
            self._kw = self._ukw = None
            self._program = None
            return
        if self._kw_mode == "shared":
            if self._same_tree(kw, self._kw_ref) \
                    and self._same_tree(ukw, self._ukw_ref):
                registry.counter(
                    "pa_serving_shared_kwargs_seats_total",
                    labels=self._labels,
                    help="lanes seated against already-shared traced "
                         "kwargs (sibling-seed reuse, uncond included)",
                )
                return
            self._kw_mode = "stacked"
            self._kw = (
                None if self._kw_ref is None
                else self._zeros_stack(self._kw_ref)
            )
            self._ukw = (
                None if self._ukw_ref is None
                else self._zeros_stack(self._ukw_ref)
            )
            for j in others:
                jr = self.lanes[j].req
                if self._kw is not None:
                    self._kw = jax.tree.map(
                        lambda stack, v, _j=j: stack.at[_j].set(v),
                        self._kw, jr.traced_kwargs,
                    )
                if self._ukw is not None:
                    self._ukw = jax.tree.map(
                        lambda stack, v, _j=j: stack.at[_j].set(v),
                        self._ukw, jr.u_traced,
                    )
            self._kw_ref = self._ukw_ref = None
            self._kw_dev = self._ukw_dev = None
            self._program = None
        if self._kw is not None:
            self._kw = jax.tree.map(
                lambda stack, v: stack.at[i].set(v),
                self._kw, req.traced_kwargs,
            )
        if self._ukw is not None:
            self._ukw = jax.tree.map(
                lambda stack, v: stack.at[i].set(v), self._ukw, req.u_traced
            )

    # -- capability overlays (round 16) -------------------------------------

    def _mc_map(self, req: ServeRequest, w):
        """One cond's weight (scalar / [1,H,W,1] / [b,H,W,1] from
        ``area_weight``) materialized to the bucket's FIXED full per-sample
        map shape — [b, *spatial, 1] for 4-D latents, [b, 1, ...] otherwise —
        so scalar-weight and masked lanes share one stack."""
        jnp = self._jnp
        b = req.x.shape[0]
        if req.x.ndim == 4:
            tgt = (b,) + tuple(req.x.shape[1:-1]) + (1,)
        else:
            tgt = (b,) + (1,) * (req.x.ndim - 1)
        return jnp.broadcast_to(jnp.asarray(w, jnp.float32), tgt)

    def _ensure_mc(self, req: ServeRequest) -> None:
        """Materialize / grow the multi-cond overlay (bucket-key discipline:
        the extra count K only grows within an epoch — pad-to-max — and the
        pooled-y leg switches on at most once; either change swaps the
        program variant and refills every seated lane's rows from its own
        request, a mode change never a value change)."""
        k_req = len(req.extra_conds or ())
        if not k_req and self._mc_k is None:
            return
        need_y = self._mc_has_y or any(
            e.get("pooled") is not None for e in (req.extra_conds or ())
        )
        if self._mc_k is not None and k_req <= self._mc_k \
                and need_y == self._mc_has_y:
            return
        jnp = self._jnp
        k_new = max(k_req, self._mc_k or 0)
        map_t = self._mc_map(req, jnp.float32(0.0))
        self._mc_w0 = self._zeros_stack(map_t)
        self._mc_w = self._zeros_stack(
            jnp.zeros((k_new,) + tuple(map_t.shape), jnp.float32)
        )
        self._mc_ctx = self._zeros_stack(
            jnp.zeros((k_new,) + tuple(req.context.shape), req.context.dtype)
        )
        self._mc_y = None
        if need_y:
            y = req.traced_kwargs["y"]
            self._mc_y = self._zeros_stack(
                jnp.zeros((k_new,) + tuple(y.shape), y.dtype)
            )
        self._mc_win = np.zeros((self.width, k_new, 2), np.float32)
        self._mc_win[:, :, 1] = 1.0
        self._mc_k, self._mc_has_y = k_new, need_y
        self._program = None
        for j in self.active_lanes():
            self._write_mc_row(j, self.lanes[j].req)

    def _write_mc_row(self, i: int, req: ServeRequest) -> None:
        """Lane ``i``'s multi-cond rows: primary weight map + per-extra
        (cond rows, weight map, pooled row, progress window), zero rows /
        identity windows for non-carrying lanes AND for pad slots beyond the
        lane's own extra count — a reused slot never inherits its
        predecessor's maps."""
        if self._mc_k is None:
            return
        jnp = self._jnp
        self._mc_w0 = self._mc_w0.at[i].set(0.0)
        self._mc_w = self._mc_w.at[i].set(0.0)
        self._mc_ctx = self._mc_ctx.at[i].set(0.0)
        if self._mc_y is not None:
            self._mc_y = self._mc_y.at[i].set(0.0)
        self._mc_win[i, :, 0] = 0.0
        self._mc_win[i, :, 1] = 1.0
        extras = req.extra_conds or ()
        if not extras:
            return
        from ..sampling.k_samplers import area_weight, broadcast_cond_batch

        b = req.x.shape[0]
        self._mc_w0 = self._mc_w0.at[i].set(self._mc_map(req, area_weight(
            req.cond_area, req.cond_strength, req.x.shape,
            mask=req.cond_mask, mask_strength=req.cond_mask_strength,
            area_pct=req.cond_area_pct,
        )))
        y_fill = (req.traced_kwargs or {}).get("y")
        for k, e in enumerate(extras):
            self._mc_ctx = self._mc_ctx.at[i, k].set(
                broadcast_cond_batch(e["context"], b)
            )
            self._mc_w = self._mc_w.at[i, k].set(self._mc_map(
                req, area_weight(
                    e.get("area"), float(e.get("strength", 1.0)), req.x.shape,
                    mask=e.get("mask"),
                    mask_strength=float(e.get("mask_strength", 1.0)),
                    area_pct=e.get("area_pct"),
                )
            ))
            tr = e.get("timestep_range")
            if tr is not None:
                self._mc_win[i, k] = (float(tr[0]), float(tr[1]))
            if self._mc_y is not None:
                pooled = e.get("pooled")
                y_row = y_fill if pooled is None else broadcast_cond_batch(
                    pooled, b
                )
                if y_row is not None:
                    self._mc_y = self._mc_y.at[i, k].set(
                        jnp.broadcast_to(
                            jnp.asarray(y_row), self._mc_y.shape[2:]
                        )
                    )

    def _ctrl_hint_norm(self, req: ServeRequest):
        """apply_control's hint normalization, host-side at seat: rank-4,
        repeated to the request batch, bilinear-resized to 8× the latent
        grid (models/controlnet.py apply does the same ops in-graph; the
        scheduler's eligibility check already rejected per-sample hint
        batches, mirroring apply_control's guard)."""
        import jax

        jnp = self._jnp
        hint = jnp.asarray(req.control["hint"], jnp.float32)
        if hint.ndim == 3:
            hint = hint[None]
        b = req.x.shape[0]
        if hint.shape[0] != b:
            hint = jnp.repeat(hint[:1], b, axis=0)
        want = (req.x.shape[1] * 8, req.x.shape[2] * 8)
        if hint.shape[1:3] != want:
            hint = jax.image.resize(
                hint, (b, *want, hint.shape[-1]), method="bilinear"
            )
        return hint

    def _ensure_ctrl(self, req: ServeRequest) -> None:
        """Materialize the ControlNet overlay on the first carrying seat:
        ONE control-trunk identity per bucket epoch (conflicting nets are
        bounced to inline at admission, before any state mutates)."""
        if req.control is None or self._ctrl is not None:
            return
        params = req.control["params"]
        placed = self._place_shared_tree(params)
        self._ctrl = {
            "apply": req.control["apply"],
            "params_ref": params,
            "params": params if placed is None else placed,
        }
        self._ctrl_hint = self._zeros_stack(self._ctrl_hint_norm(req))
        self._ctrl_strength = np.zeros(self.width, np.float32)
        self._ctrl_win = np.tile(
            np.asarray([0.0, 1.0], np.float32), (self.width, 1)
        )
        self._program = None

    def _ctrl_conflict(self, req: ServeRequest) -> bool:
        """True when the request carries a DIFFERENT control trunk than the
        one this bucket epoch already runs (identity on apply + params)."""
        return (
            self.spec is not None
            and req.control is not None
            and self._ctrl is not None
            and (req.control["apply"] is not self._ctrl["apply"]
                 or req.control["params"] is not self._ctrl["params_ref"])
        )

    def _ensure_lora(self, req: ServeRequest) -> None:
        """Materialize / grow the LoRA overlay: the target-path union and
        rank max only grow within an epoch; a growth rebuilds the factor
        stacks (zero-padded) and refills every seated lane's rows — rank
        padding is structural (zero slots give a bitwise-zero delta)."""
        if not req.lora:
            return
        from ..models.lora import get_path

        jnp = self._jnp
        paths = sorted(set(req.lora) | {p for (p, _, _) in self._lora_sig})
        r_req = max(int(a.shape[0]) for (a, _b) in req.lora.values())
        r_new = max(r_req, self._lora_rmax)
        if tuple(p for (p, _, _) in self._lora_sig) == tuple(paths) \
                and r_new == self._lora_rmax:
            return
        sig = []
        for p in paths:
            w = get_path(self.spec.params, p)
            # nd targets (head-split attention kernels, conv): the factor
            # pair addresses the (shape[0], prod(rest)) flattening and the
            # merge reshapes the delta back (models/lora.py contract).
            sig.append((p, int(w.shape[0]),
                        int(math.prod(w.shape[1:]))))
        self._lora_sig = tuple(sig)
        self._lora_rmax = r_new
        self._lora_ab = [
            (self._zeros_stack(jnp.zeros((r_new, k), jnp.float32)),
             self._zeros_stack(jnp.zeros((m, r_new), jnp.float32)))
            for (_p, m, k) in sig
        ]
        self._program = None
        for j in self.active_lanes():
            self._write_lora_row(j, self.lanes[j].req)

    def _write_lora_row(self, i: int, req: ServeRequest) -> None:
        if not self._lora_sig:
            return
        from ..models.lora import pad_rank

        factors = req.lora or {}
        for idx, (path, _m, _k) in enumerate(self._lora_sig):
            a_s, b_s = self._lora_ab[idx]
            pair = factors.get(path)
            if pair is None:
                a_s, b_s = a_s.at[i].set(0.0), b_s.at[i].set(0.0)
            else:
                a_, b_ = pad_rank(
                    self._jnp.asarray(pair[0], a_s.dtype),
                    self._jnp.asarray(pair[1], b_s.dtype),
                    self._lora_rmax,
                )
                a_s, b_s = a_s.at[i].set(a_), b_s.at[i].set(b_)
            self._lora_ab[idx] = (a_s, b_s)

    def _seat_caps(self, i: int, req: ServeRequest) -> None:
        """Seat lane ``i``'s capability state. The mask axis is always-on
        (row writes + a host gate flag); the other overlays materialize on
        the first carrying seat. A reused slot ALWAYS rewrites its rows in
        every active overlay, so a lane can never inherit its predecessor's
        factors/hints/maps."""
        jnp = self._jnp
        kinds = []
        if req.latent_mask is not None:
            self._mask = self._mask.at[i].set(jnp.broadcast_to(
                jnp.asarray(req.latent_mask, jnp.float32), req.x.shape
            ))
            self._mask_init = self._mask_init.at[i].set(
                jnp.broadcast_to(jnp.asarray(req.mask_init), req.x.shape)
                .astype(self._mask_init.dtype)
            )
            self._mask_noise = self._mask_noise.at[i].set(
                jnp.broadcast_to(jnp.asarray(req.mask_noise), req.x.shape)
                .astype(self._mask_noise.dtype)
            )
            self._mask_has[i] = True
            kinds.append("img2img_mask")
        else:
            # Gate off suffices: the program's where-select never reads a
            # zero-gated lane's mask rows, so no device clear is needed.
            self._mask_has[i] = False
        if req.extra_conds:
            kinds.append("multi_cond")
        self._ensure_mc(req)
        self._write_mc_row(i, req)
        if req.control is not None:
            self._ensure_ctrl(req)
            kinds.append("controlnet")
        if self._ctrl is not None:
            if req.control is not None:
                self._ctrl_hint = self._ctrl_hint.at[i].set(
                    self._ctrl_hint_norm(req)
                )
                self._ctrl_strength[i] = float(req.control["strength"])
                self._ctrl_win[i] = (
                    float(req.control["start"]), float(req.control["end"])
                )
            else:
                # Zero gain → exact zero residual trees (additive no-op);
                # a stale hint row only ever feeds the zeroed trunk output.
                self._ctrl_strength[i] = 0.0
                self._ctrl_win[i] = (0.0, 1.0)
        if req.lora:
            self._ensure_lora(req)
            kinds.append("lora")
        self._write_lora_row(i, req)
        for kind in (kinds or ["txt2img"]):
            registry.counter(
                "pa_serving_lane_capability_total",
                labels={**self._labels, "kind": kind},
                help="lanes seated, by capability carried (a multi-"
                     "capability lane counts once per capability; plain "
                     "lanes count as txt2img)",
            )

    def _set_lane(self, i: int, req: ServeRequest) -> bool:
        import jax

        if self._ctrl_conflict(req):
            # One control trunk per bucket epoch: a different net cannot
            # join this program — bounce to the inline path (the runner
            # catches DegradedToInline and falls back) BEFORE any stacked
            # state mutates.
            from ..utils.degrade import DegradedToInline

            req.resolve(error=DegradedToInline(
                f"bucket {self.label} already carries a different "
                "ControlNet this epoch; re-submit inline"
            ))
            registry.counter(
                "pa_serving_ctrl_conflict_total", labels=self._labels,
                help="seats bounced to inline: a second ControlNet identity "
                     "arrived within one bucket epoch",
            )
            return False
        self._ensure_state(req)
        lane = _Lane(req)
        # The lane's whole schedule compiles to an eval-ordered plan list at
        # seat time (host float64 — one pass per request, not per dispatch);
        # stochastic lanes also bank their fold_in key table here.
        lane.plans = plan_schedule(req.sampler, req.sigmas, req.prediction)
        spec_entry = LANE_SPECS[req.sampler]
        if spec_entry.needs_rng:
            lane.keys = _lane_key_table(
                req.rng, req.n_steps, spec_entry.split_keys
            )
        if self.spec is not None:
            # State-pytree init: latent and eval input seed from the request,
            # history slots zero — a reused lane must never see its
            # predecessor's carries.
            self._x = self._x.at[i].set(req.x)
            self._xe = self._xe.at[i].set(req.x)
            self._h1 = self._h1.at[i].set(0.0)
            self._h2 = self._h2.at[i].set(0.0)
            self._seat_cond(i, req)
            self._seat_kwargs(i, req)
            self._seat_caps(i, req)
        else:
            from ..sampling.k_samplers import EpsDenoiser

            jnp = self._jnp
            lane.x_eager = req.x
            lane.xe_eager = req.x
            lane.h1_eager = jnp.zeros_like(req.x)
            lane.h2_eager = jnp.zeros_like(req.x)
            # Width-1 eager capability twin: multi-cond rides the denoiser's
            # own _combine_conds; ControlNet/LoRA ride the pre-merged
            # ``eager_model``; the denoise mask is a post-completion blend
            # in dispatch() (the masked_callback formula).
            model_lane = (
                req.eager_model if req.eager_model is not None else self.model
            )
            lane.denoiser = EpsDenoiser(
                model_lane, req.context, cfg_scale=req.cfg_scale,
                uncond_context=req.uncond_context,
                uncond_kwargs=req.uncond_kwargs,
                alphas_cumprod=req.acp, prediction=req.prediction,
                cfg_rescale=req.cfg_rescale,
                extra_conds=req.extra_conds or None,
                cond_area=req.cond_area, cond_area_pct=req.cond_area_pct,
                cond_mask=req.cond_mask, cond_strength=req.cond_strength,
                cond_mask_strength=req.cond_mask_strength,
                **req.traced_kwargs, **req.static_kwargs,
            )
        self.lanes[i] = lane
        return True

    # -- scheduling ---------------------------------------------------------

    def admit(self, now: float | None = None) -> int:
        """Fill free lanes from the waiting line (policy order), resolving
        expired/cancelled entries instead of seating them. Returns how many
        joined — always at a step boundary (the dispatcher calls this between
        dispatches, never mid-step)."""
        now = time.monotonic() if now is None else now
        for req in self.queue.expired(now):
            req.resolve(error=DeadlineExceeded(
                f"deadline passed after {now - req.submit_ts:.3f}s waiting"
            ))
            registry.counter("pa_serving_expired_total", labels=self._labels)
        joined = 0
        for i in range(self.width):
            if self.lanes[i] is not None:
                continue
            req = self.queue.pop()
            if req is None:
                break
            if req.cancelled():
                req.resolve(error=Interrupted("cancelled while queued"))
                registry.counter("pa_serving_cancelled_total", labels=self._labels)
                continue
            if req.deadline is not None and now >= req.deadline:
                # Deadline-vs-admission race: a deadline that lapses between
                # the expired() sweep above and this pop (or was pushed
                # already-expired) must reject with the deadline error, not
                # seat for step 0 — seating would spend a dispatch on work
                # whose client has already given up.
                req.resolve(error=DeadlineExceeded(
                    f"deadline passed after {now - req.submit_ts:.3f}s "
                    "waiting (caught at admission)"
                ))
                registry.counter("pa_serving_expired_total",
                                 labels=self._labels)
                continue
            if not self._set_lane(i, req):
                # Bounced (capability conflict) — the request resolved with
                # DegradedToInline; the slot refills on the next sweep.
                continue
            joined += 1
            registry.histogram(
                "pa_serving_lane_wait_seconds", now - req.submit_ts,
                labels=self._labels,
                help="submit-to-lane admission wait",
            )
            # SLO lane_wait stage: the same clock, bucket-label-free — the
            # decomposition view of the per-bucket histogram above.
            slo.observe_stage("lane_wait", now - req.submit_ts)
            if tracing.on():
                # admission→lane-assign on the submitter's timeline: one
                # completed span from submit to seat (both trace-clock).
                self.lanes[i].seat_us = tracing.now_us()
                if req.trace_submit_us is not None:
                    tracing.record(
                        "lane-wait", req.trace_submit_us,
                        self.lanes[i].seat_us - req.trace_submit_us,
                        cat="serving", tid=req.trace_tid,
                        prompt_id=req.prompt_id, bucket=self.label, lane=i,
                        rid=req.rid, queue_depth=len(self.queue),
                        **({"trace_id": req.trace_id}
                           if req.trace_id else {}),
                    )
        if joined:
            self._gauges()
        return joined

    def _retire(self, i: int, result=None, error=None) -> None:
        lane = self.lanes[i]
        self.lanes[i] = None
        if lane.digests:
            # The lane's per-eval fingerprint stack (numerics sentinel):
            # invariant to occupancy/width/sharding by the digest's
            # construction, so any drift here IS a numerics change.
            numerics.sentinel.record_fingerprints(
                rid=lane.req.rid, sampler=lane.req.sampler, bucket=self.label,
                steps=lane.idx, digests=list(lane.digests),
            )
        if tracing.on() and lane.seat_us:
            # lane-assign→retire on the submitter's timeline; the per-step
            # spans recorded by dispatch() nest inside this interval.
            tracing.record(
                "lane", lane.seat_us, tracing.now_us() - lane.seat_us,
                cat="serving", tid=lane.req.trace_tid,
                prompt_id=lane.req.prompt_id, bucket=self.label, lane=i,
                rid=lane.req.rid, steps_run=lane.idx,
                outcome="error" if error is not None else "completed",
                **({"trace_id": lane.req.trace_id}
                   if lane.req.trace_id else {}),
            )
        lane.req.resolve(result=result, error=error)
        registry.counter(
            "pa_serving_cancelled_total" if error is not None
            else "pa_serving_completed_total",
            labels=self._labels,
        )

    def _quarantine(self, i: int, plan: StepPlan, stats_vec, xe_lane,
                    occupancy: int = 0) -> None:
        """Non-finite quarantine (numerics sentinel): retire lane ``i`` via
        the existing select-mask discipline — the stacked state is NOT
        touched, so co-batched neighbors are bit-identical to their solo
        runs by construction — and dump a ``write_postmortem`` bundle whose
        extras name the first non-finite block/step/σ. The block comes from
        :func:`utils.numerics.bisect_nonfinite`: a re-run of the failing
        eval input through the model's PipelineSpec stages (prepare →
        per-block segments → finalize); the step/σ come from the lane's own
        StepPlan — this dispatch IS the first non-finite one, because every
        emitting dispatch is checked."""
        lane = self.lanes[i]
        req = lane.req
        err = numerics.NonFiniteLatent(
            f"lane {i} ({req.sampler}) went non-finite at step {plan.step} "
            f"(σ_eval={plan.sigma_eval:.6g}) in bucket {self.label}; lane "
            f"quarantined, postmortem bundle written"
        )
        forensics = {
            "bucket": self.label, "lane": i, "rid": req.rid,
            "sampler": req.sampler, "step": int(plan.step),
            "sigma": float(plan.sigma_eval), "pc": lane.pc,
            "occupancy": occupancy, "prompt_id": req.prompt_id,
            "stats": numerics.stats_to_dict(stats_vec),
        }
        log_sig = self._log_sigmas
        if log_sig is None and lane.denoiser is not None:
            log_sig = getattr(lane.denoiser, "log_sigmas", None)
        try:
            bisect = numerics.bisect_nonfinite(
                self.model, xe_lane, plan.sigma_eval, req.prediction,
                log_sig, req.context,
                {**req.traced_kwargs, **req.static_kwargs},
            )
        except Exception as e:  # noqa: BLE001 — forensics never blocks retire
            bisect = {"block": None, "bisect_error": f"{type(e).__name__}: {e}"}
        forensics["first_nonfinite"] = {
            "step": int(plan.step), "sigma": float(plan.sigma_eval), **bisect,
        }
        bundle = None
        try:
            from ..utils.telemetry import write_postmortem

            bundle = write_postmortem(
                f"numerics-{self.label}-lane{i}", error=err, extra=forensics
            )
        except Exception:  # noqa: BLE001
            pass
        numerics.sentinel.record_event(
            "serving-lane", bucket=self.label, lane=i, step=int(plan.step),
            sampler=req.sampler,
        )
        numerics.sentinel.record_quarantine(**forensics, bundle=bundle)
        self._retire(i, error=err)

    def sweep_cancelled(self) -> int:
        """Retire lanes whose request was cancelled (client cancel, per-prompt
        interrupt, deadline) — frees the slot at the boundary WITHOUT touching
        the stacked state: the lane goes inactive-masked, so neighbors are
        untouched by construction."""
        now = time.monotonic()
        swept = 0
        for i in self.active_lanes():
            req = self.lanes[i].req
            if req.cancelled():
                self._retire(i, error=Interrupted(
                    f"cancelled mid-batch at step {self.lanes[i].idx}"
                ))
                swept += 1
            elif req.deadline is not None and now >= req.deadline:
                self._retire(i, error=DeadlineExceeded(
                    f"deadline passed at step {self.lanes[i].idx}"
                ))
                swept += 1
        if swept:
            self._gauges()
        return swept

    def dispatch(self) -> bool:
        """Run ONE lockstep model eval for every active lane (one compiled
        dispatch in program mode), apply each lane's own sampler update,
        advance per-lane plan counters, fire per-lane progress hooks at
        σ-interval boundaries, retire finished lanes. Returns False when
        there was nothing to run."""
        active = self.active_lanes()
        if not active:
            return False
        import jax

        jnp = self._jnp
        t0_us = tracing.now_us() if tracing.on() else 0.0
        t0 = time.perf_counter()
        plans = {i: self.lanes[i].plan() for i in active}
        # Numerics sentinel (utils/numerics.py): (stats, digests, xe-of-lane)
        # when this dispatch emitted them — read below, AFTER the block the
        # dispatch already performs AND after the step clock stops, so the
        # sentinel adds no sync of its own and its (tiny) device→host stats
        # readback never lands in pa_serving_step_seconds (the host-sync
        # discipline palint enforces: this window is timed).
        quarantine_src = None
        stats_dev = None      # program mode: deferred (st, dg, xe_of) refs
        eager_stats = None    # eager mode: deferred xe-inputs map
        if self.spec is not None:
            self._ensure_program()
            sig = np.ones((self.width,), np.float32)
            act = np.zeros((self.width,), np.float32)
            cfg = np.ones((self.width,), np.float32)
            coef = np.broadcast_to(
                _IDENTITY_COEF, (self.width, 4, 6)
            ).copy()
            key_width = next(
                (self.lanes[i].keys.shape[-1] for i in active
                 if self.lanes[i].keys is not None), 2,
            )
            keys = np.zeros((self.width, key_width), np.uint32)
            # Denoise-mask mix (always-on capability axis): per dispatch,
            # per lane, (gate, keep_a, keep_b) — gate only on σ-interval
            # completion of a masked lane; the keep coefficients are the
            # masked_callback formula per prediction family at the lane's
            # own σ_next (eps/v: init + σ'·noise; flow: (1−σ')·init +
            # σ'·noise). All-zero rows make the blend a structural no-op.
            mask_mix = np.zeros((self.width, 3), np.float32)
            for i in active:
                lane, plan = self.lanes[i], plans[i]
                sig[i] = plan.sigma_eval
                act[i] = 1.0
                cfg[i] = lane.req.cfg_scale
                coef[i] = plan.coef
                row = _noise_key_row(lane, plan)
                if row is not None:
                    keys[i] = row
                if self._mask_has[i] and plan.completes:
                    # palint: allow[host-sync] req.sigmas is host-side
                    # np.ndarray by ServeRequest contract — no device sync
                    s_next = float(lane.req.sigmas[plan.step + 1])
                    if lane.req.prediction == "flow":
                        mask_mix[i] = (1.0, 1.0 - s_next, s_next)
                    else:
                        mask_mix[i] = (1.0, 1.0, s_next)
            xe_prev = None
            if self._emit_stats:
                inj = numerics.take_injection(active)
                if inj is not None:
                    # PA_FAIL_INJECT=nan:<lane> rehearsal: poison ONE element
                    # of the seated lane's next eval input, once — the
                    # quarantine path below must catch it at this dispatch.
                    idx = (inj,) + (0,) * (self._xe.ndim - 1)
                    self._xe = self._xe.at[idx].set(jnp.nan)
                # emit mode keeps xe UNdonated (lane_step_program) so the
                # failing eval input survives for the per-block bisection.
                xe_prev = self._xe
            shared = self._cond_mode == "shared"
            ctx_arg = self._ctx_dev if shared else self._ctx
            uctx_arg = self._uctx_dev if shared else self._uctx
            if shared:
                registry.counter(
                    "pa_serving_cond_broadcast_total", labels=self._labels,
                    help="dispatches whose cond rode the lane axis as ONE "
                         "broadcast tensor (sibling-seed sharing)",
                )
            kw_shared = self._kw_mode == "shared"
            kw_arg = self._kw_dev if kw_shared else self._kw
            ukw_arg = self._ukw_dev if kw_shared else self._ukw
            if kw_shared and (self._kw_ref is not None
                              or self._ukw_ref is not None):
                registry.counter(
                    "pa_serving_kwargs_broadcast_total", labels=self._labels,
                    help="dispatches whose traced kwargs (uncond extras "
                         "included) rode the lane axis as ONE broadcast "
                         "tree (sibling-seed sharing)",
                )
            # Capability overlay inputs (only the materialized ones — the
            # program variant was built with the matching signature).
            cap_kw = {}
            if self._mc_k is not None:
                cap_kw.update(
                    mc_w0=self._mc_w0, mc_ctx=self._mc_ctx, mc_w=self._mc_w,
                    mc_win=jnp.asarray(self._mc_win), mc_y=self._mc_y,
                )
            if self._ctrl is not None:
                cap_kw.update(
                    ctrl_params=self._ctrl["params"],
                    ctrl_hint=self._ctrl_hint,
                    ctrl_strength=jnp.asarray(self._ctrl_strength),
                    ctrl_win=jnp.asarray(self._ctrl_win),
                )
            if self._lora_sig:
                cap_kw["lora_ab"] = tuple(
                    (a_s, b_s) for (a_s, b_s) in self._lora_ab
                )
            outs = self._program(
                self.spec.params, self._x, self._xe, self._h1, self._h2,
                jnp.asarray(sig), jnp.asarray(act), jnp.asarray(cfg),
                jnp.asarray(coef), jnp.asarray(keys),
                ctx_arg, uctx_arg, kw_arg, ukw_arg, self._log_sigmas,
                self._mask, self._mask_init, self._mask_noise,
                jnp.asarray(mask_mix), **cap_kw,
            )
            if self._emit_stats:
                (self._x, self._xe, self._h1, self._h2, st_dev, dg_dev) = outs
            else:
                self._x, self._xe, self._h1, self._h2 = outs
            # palint: allow[host-sync] the completion boundary: the step
            # histogram must include device time (the StepTimer discipline)
            jax.block_until_ready(self._x)
            if self._emit_stats:
                stats_dev = (st_dev, dg_dev, lambda i, _xe=xe_prev: _xe[i])
        else:
            # Width-1 eager mode (streaming/hybrid models): the SAME StepPlan
            # walk against the lane's own denoiser — full sampler family,
            # one model call per eval.
            emit_eager = numerics.on()
            xe_inputs: dict[int, Any] = {}
            if emit_eager:
                inj = numerics.take_injection(active)
                if inj is not None:
                    lane0 = self.lanes[inj]
                    idx = (0,) * lane0.xe_eager.ndim
                    lane0.xe_eager = lane0.xe_eager.at[idx].set(jnp.nan)
            for i in active:
                lane, plan = self.lanes[i], plans[i]
                if emit_eager:
                    xe_inputs[i] = lane.xe_eager
                x0e = lane.denoiser(
                    lane.xe_eager, jnp.float32(plan.sigma_eval)
                )
                row = _noise_key_row(lane, plan)
                noise = None
                if row is not None:
                    noise = jax.random.normal(
                        jax.random.wrap_key_data(jnp.asarray(row)),
                        lane.x_eager.shape, lane.x_eager.dtype,
                    )
                basis = (lane.x_eager, lane.xe_eager, x0e,
                         lane.h1_eager, lane.h2_eager, noise)

                def _combine(row_c, like):
                    acc = None
                    for c, term in zip(row_c, basis):
                        if float(c) == 0.0 or term is None:
                            continue
                        part = float(c) * term
                        acc = part if acc is None else acc + part
                    if acc is None:
                        return jnp.zeros_like(like)
                    return acc.astype(like.dtype)

                lane.x_eager, lane.xe_eager, lane.h1_eager, lane.h2_eager = (
                    _combine(plan.coef[0], lane.x_eager),
                    _combine(plan.coef[1], lane.xe_eager),
                    _combine(plan.coef[2], lane.h1_eager),
                    _combine(plan.coef[3], lane.h2_eager),
                )
                if plan.completes and lane.req.latent_mask is not None:
                    # Eager twin of the program's mask_mix blend: re-pin the
                    # keep region on σ-interval completion (histories stay
                    # untouched, as inline's post-step callback never sees
                    # sampler history either).
                    rq = lane.req
                    # palint: allow[host-sync] rq.sigmas is host-side
                    # np.ndarray by ServeRequest contract — no device sync
                    s_next = float(rq.sigmas[plan.step + 1])
                    if rq.prediction == "flow":
                        keep = (
                            (1.0 - s_next) * rq.mask_init
                            + s_next * rq.mask_noise
                        )
                    else:
                        keep = rq.mask_init + s_next * rq.mask_noise
                    mk = jnp.asarray(rq.latent_mask, jnp.float32)
                    lane.x_eager = (
                        lane.x_eager * mk + keep * (1.0 - mk)
                    ).astype(lane.x_eager.dtype)
                    lane.xe_eager = (
                        lane.xe_eager * mk + keep * (1.0 - mk)
                    ).astype(lane.xe_eager.dtype)
            # palint: allow[host-sync] the completion boundary: the step
            # histogram must include device time (the StepTimer discipline)
            jax.block_until_ready([self.lanes[i].x_eager for i in active])
            if emit_eager:
                eager_stats = xe_inputs
        dt = time.perf_counter() - t0
        # Sentinel readback AFTER the clock stopped (the outputs are ready —
        # the blocks above — so these transfers cost microseconds and, now,
        # zero booked step time).
        if stats_dev is not None:
            st_dev, dg_dev, xe_of = stats_dev
            # palint: allow[host-sync] stats readback at the boundary —
            # post-block, post-clock; the sentinel adds no sync of its own
            quarantine_src = (np.asarray(st_dev), np.asarray(dg_dev), xe_of)
        elif eager_stats is not None:
            st_rows, dg_rows = {}, {}
            for i in active:
                lane = self.lanes[i]
                # palint: allow[host-sync] stats readback at the boundary —
                # post-block, post-clock; the sentinel adds no sync of its own
                st_rows[i] = np.asarray(numerics.lane_stats(
                    lane.x_eager[None], extra=lane.xe_eager[None]
                ))[0]
                # palint: allow[host-sync] digest readback, same boundary
                dg_rows[i] = int(np.asarray(numerics.digest(lane.x_eager)))
            quarantine_src = (
                st_rows, dg_rows, lambda i, _xs=eager_stats: _xs[i]
            )
        self.dispatch_count += 1
        registry.counter("pa_serving_dispatch_total", labels=self._labels,
                         help="compiled lockstep step dispatches")
        registry.counter("pa_serving_lane_steps_total", inc=len(active),
                         labels=self._labels,
                         help="lane-steps served (occupancy summed over "
                              "dispatches) — amortization numerator")
        record_dispatch_occupancy(len(active))
        registry.histogram("pa_serving_step_seconds", dt, labels=self._labels,
                           help="wall time of one lockstep dispatch")
        if tracing.on() and t0_us:
            # (t0_us guards the enable-raced-mid-dispatch case: never emit a
            # span whose start predates the trace.)
            dur_us = tracing.now_us() - t0_us
            # One dispatcher-side span (per-dispatch occupancy + masked-lane
            # count) ...
            tracing.record(
                "serving-dispatch", t0_us, dur_us, cat="serving",
                bucket=self.label, occupancy=len(active),
                masked_lanes=self.width - len(active), width=self.width,
            )
            # ... and one step span per live lane on its OWN prompt's
            # timeline (the submitter is blocked in result() for exactly this
            # interval, so per-tid nesting holds). The dispatch already
            # blocked on the step output above — the duration is honest, and
            # tracing added no sync of its own.
            for i in active:
                lane = self.lanes[i]
                tracing.record(
                    "step", t0_us, dur_us, cat="serving",
                    tid=lane.req.trace_tid, prompt_id=lane.req.prompt_id,
                    bucket=self.label, lane=i, step=lane.idx + 1,
                    of=lane.req.n_steps, occupancy=len(active),
                    **({"trace_id": lane.req.trace_id}
                       if lane.req.trace_id else {}),
                )
        if quarantine_src is not None:
            # Sentinel boundary: the per-lane stats/digests this dispatch
            # emitted (surfaced at the same boundary the progress hooks
            # fire). A non-finite lane is quarantined BEFORE its plan
            # counter advances — its slot goes inactive-masked (the select
            # discipline), so survivors stay bit-identical to solo runs.
            st, dg, xe_of = quarantine_src
            for i in active:
                lane = self.lanes[i]
                lane.digests.append(int(dg[i]))
                # palint: allow[host-sync] st is host-side numpy here
                # (converted once at the post-clock boundary above)
                if float(st[i][0]) > 0:
                    self._quarantine(i, plans[i], st[i], xe_of(i),
                                     occupancy=len(active))
        for i in active:
            lane, plan = self.lanes[i], plans[i]
            if lane is None:
                continue  # quarantined at this boundary — already retired
            lane.pc += 1
            if plan.completes:
                # The σ-interval finished (second-order lanes take two evals
                # to get here) — the progress unit the hooks report.
                lane.idx += 1
                hook = lane.req.progress_hook
                if hook is not None:
                    try:
                        hook(lane.idx, lane.req.n_steps)
                    except Exception:  # noqa: BLE001 — a UI hook must not kill lanes
                        pass
            if lane.done():
                result = (
                    self._x[i] if self.spec is not None else lane.x_eager
                )
                self._retire(i, result=result)
        self._gauges()
        return True
