"""End-to-end text→image pipelines: tokenize → encode → (parallel) denoise → decode.

The reference is a node pack inside a host app that owns this outer loop (ComfyUI
wires CLIPTextEncode → KSampler → VAEDecode around the reference's wrapped MODEL;
the reference only accelerates the per-step ``diffusion_model.forward``,
any_device_parallel.py:1287). Standalone, this module IS that outer loop. The
diffusion model slot accepts either a bare ``DiffusionModel`` or the
``ParallelModel`` returned by ``parallelize`` — every sampler step then routes
through the same DP/pipeline scheduler the reference's KSampler steps do.

TPU shape discipline: everything is fixed-shape per (batch, size, steps) combo —
the step loops re-enter the same compiled forward; only the scalars (t, sigma)
change. CFG doubles the batch inside one forward (feeding the DP path) instead of
running two forwards.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from .models.vae import vae_output_to_images as _to_images
from .sampling.runner import run_sampler


def _match_negatives(prompts: list[str], negative_prompt) -> list[str]:
    """Broadcast a str negative to the batch; validate list lengths at the API
    boundary (a mismatch otherwise surfaces as a cross-attention shape error deep
    inside the model)."""
    if isinstance(negative_prompt, str):
        return [negative_prompt] * len(prompts)
    negatives = list(negative_prompt)
    if len(negatives) != len(prompts):
        raise ValueError(
            f"negative_prompt list has {len(negatives)} entries for "
            f"{len(prompts)} prompts"
        )
    return negatives


def _encode_init_image(vae, init_image, denoise: float, batch: int,
                       height: int, width: int):
    """img2img entry shared by the pipelines: encode ``init_image`` (floats in
    [0, 1]) to the latent ``run_sampler`` starts from when ``denoise < 1``."""
    if init_image is None:
        if denoise < 1.0:
            raise ValueError(
                "denoise < 1 without an init_image — partial strength needs an "
                "image (or latent) to preserve; pass init_image or drop denoise"
            )
        return None
    if denoise >= 1.0:
        raise ValueError("init_image given but denoise=1.0 — lower denoise "
                         "(strength) so the image actually seeds the sampler")
    from .models.vae import images_to_vae_input

    if init_image.shape[1:3] != (height, width):
        raise ValueError(
            f"init_image is {init_image.shape[1:3]}, pipeline is "
            f"({height}, {width})"
        )
    z = vae.encode(images_to_vae_input(init_image))
    if z.shape[0] == 1 and batch > 1:
        z = jnp.repeat(z, batch, axis=0)
    return z


@dataclasses.dataclass
class StableDiffusionPipeline:
    """SD1.5 (clip only) / SDXL (clip + clip_g) text→image.

    ``unet`` may be a DiffusionModel or a ParallelModel (wrap with ``parallelize``
    first to run each denoise step across the device chain)."""

    unet: Any
    vae: Any
    clip: Any  # CLIP-L TextEncoder
    tokenizer: Any  # prompts -> (ids, mask)
    clip_g: Any = None  # SDXL second tower (OpenCLIP-G)
    tokenizer_g: Any = None

    @property
    def is_sdxl(self) -> bool:
        return self.clip_g is not None

    def encode_prompt(self, prompts: list[str], height: int, width: int):
        """Prompts → (context, y) conditioning for the UNet family in use."""
        ids, _ = self.tokenizer(prompts)
        last, penultimate, _pooled = self.clip(jnp.asarray(ids, jnp.int32))
        if not self.is_sdxl:
            return last, None
        from .models.text_encoders import sdxl_text_conditioning

        ids_g, _ = (self.tokenizer_g or self.tokenizer)(prompts)
        _, pen_g, pooled_g = self.clip_g(jnp.asarray(ids_g, jnp.int32))
        return sdxl_text_conditioning(
            penultimate, pen_g, pooled_g, width=width, height=height
        )

    def __call__(
        self,
        prompt: str | list[str],
        negative_prompt: str | list[str] = "",
        *,
        steps: int = 30,
        cfg_scale: float = 7.5,
        height: int = 512,
        width: int = 512,
        rng=None,
        sampler: str = "dpmpp_2m",
        karras: bool = True,
        callback=None,
        init_image: jnp.ndarray | None = None,
        denoise: float = 1.0,
    ) -> jnp.ndarray:
        """Returns float images (B, height, width, 3) in [0, 1]. img2img: pass
        ``init_image`` (B or 1, height, width, 3 floats in [0, 1]) with
        ``denoise < 1`` — the sampler starts from the encoded image noised to
        the truncated schedule's head instead of pure noise."""
        prompts = [prompt] if isinstance(prompt, str) else list(prompt)
        negatives = _match_negatives(prompts, negative_prompt)
        if rng is None:
            rng = jax.random.key(0)
        f = self.vae.spatial_factor
        if height % f or width % f:
            raise ValueError(f"height/width must be multiples of {f}")

        context, y = self.encode_prompt(prompts, height, width)
        use_cfg = cfg_scale != 1.0
        uncond_context = None
        uncond_kwargs = None
        if use_cfg:
            # The uncond half uses the negative prompt's own pooled y (SDXL) —
            # ComfyUI/diffusers semantics, carried via uncond_kwargs.
            uncond_context, uncond_y = self.encode_prompt(negatives, height, width)
            if uncond_y is not None:
                uncond_kwargs = {"y": uncond_y}

        B = len(prompts)
        zc = self.vae.cfg.z_channels
        noise = jax.random.normal(
            rng, (B, height // f, width // f, zc), jnp.float32
        )
        kwargs = {} if y is None else {"y": y}
        if sampler == "flow_euler":
            raise ValueError("flow_euler belongs to FluxPipeline, not the SD family")
        init_latent = _encode_init_image(
            self.vae, init_image, denoise, B, height, width
        )
        latents = run_sampler(
            self.unet,
            noise,
            context,
            init_latent=init_latent,
            denoise=denoise,
            sampler=sampler,
            steps=steps,
            cfg_scale=cfg_scale if use_cfg else 1.0,
            uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs,
            rng=rng,
            karras=karras,
            callback=callback,
            **kwargs,
        )
        return _to_images(self.vae.decode(latents))


@dataclasses.dataclass
class FluxPipeline:
    """FLUX / Z-Image flow-matching text→image: T5 context + CLIP-L pooled vec."""

    dit: Any  # FLUX-class DiffusionModel or ParallelModel
    vae: Any  # 16-channel autoencoder
    clip: Any  # CLIP-L TextEncoder (pooled y)
    t5: Any  # T5 TextEncoder (context)
    tokenizer: Any  # CLIP tokenizer
    t5_tokenizer: Any

    def encode_prompt(self, prompts: list[str]):
        ids, _ = self.tokenizer(prompts)
        _, _, pooled = self.clip(jnp.asarray(ids, jnp.int32))
        t5_ids, t5_mask = self.t5_tokenizer(prompts)
        context = self.t5(jnp.asarray(t5_ids, jnp.int32), mask=jnp.asarray(t5_mask))
        return context, pooled

    def __call__(
        self,
        prompt: str | list[str],
        *,
        steps: int = 20,
        guidance: float | None = 3.5,
        shift: float = 1.15,
        height: int = 1024,
        width: int = 1024,
        rng=None,
        negative_prompt: str | list[str] | None = None,
        cfg_scale: float = 1.0,
        callback=None,
        init_image: jnp.ndarray | None = None,
        denoise: float = 1.0,
    ) -> jnp.ndarray:
        """Returns float images (B, height, width, 3) in [0, 1]. ``guidance`` is
        the dev-family distilled guidance embed (None for schnell); true CFG runs
        only when ``negative_prompt``+``cfg_scale>1`` are given."""
        prompts = [prompt] if isinstance(prompt, str) else list(prompt)
        if rng is None:
            rng = jax.random.key(0)
        f = self.vae.spatial_factor
        from .parallel.orchestrator import model_config_of

        patch = getattr(model_config_of(self.dit), "patch_size", 2)
        unit = f * patch  # VAE factor x DiT patchify
        if height % unit or width % unit:
            raise ValueError(f"height/width must be multiples of {unit}")
        context, pooled = self.encode_prompt(prompts)
        uncond_context = None
        uncond_kwargs = None
        kwargs: dict[str, Any] = {"y": pooled}
        use_cfg = cfg_scale != 1.0 and negative_prompt is not None
        if use_cfg:
            negatives = _match_negatives(prompts, negative_prompt)
            uncond_context, uncond_pooled = self.encode_prompt(negatives)
            uncond_kwargs = {"y": uncond_pooled}

        B = len(prompts)
        zc = self.vae.cfg.z_channels
        noise = jax.random.normal(
            rng, (B, height // f, width // f, zc), jnp.float32
        )
        init_latent = _encode_init_image(
            self.vae, init_image, denoise, B, height, width
        )
        latents = run_sampler(
            self.dit,
            noise,
            context,
            sampler="flow_euler",
            steps=steps,
            shift=shift,
            guidance=guidance,
            cfg_scale=cfg_scale if use_cfg else 1.0,
            uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs,
            callback=callback,
            init_latent=init_latent,
            denoise=denoise,
            **kwargs,
        )
        return _to_images(self.vae.decode(latents))


@dataclasses.dataclass
class WanVideoPipeline:
    """WAN text→video: UMT5-class context + flow matching + causal 3D VAE.

    The reference's WAN2.2 workload (/root/reference/README.md:5) runs this loop
    inside ComfyUI with the wrapped denoiser; standalone, this drives the same
    per-step parallel routing over a video latent (batch=1 video is exactly the
    reference's pipeline-mode shape, any_device_parallel.py:1295-1305 — here the
    temporal token axis keeps the MXU fed instead)."""

    dit: Any  # WAN-class DiffusionModel or ParallelModel
    vae: Any  # VideoVAE (causal 3D)
    t5: Any  # UMT5/T5 TextEncoder (context)
    t5_tokenizer: Any
    # WAN2.2 A14B: a second low-noise expert makes ``dit`` the high-noise one
    # and every step routes by flow time (models/experts.py).
    dit_low_noise: Any = None
    boundary: float | None = None

    def encode_prompt(self, prompts: list[str]):
        ids, mask = self.t5_tokenizer(prompts)
        return self.t5(jnp.asarray(ids, jnp.int32), mask=jnp.asarray(mask))

    def __call__(
        self,
        prompt: str | list[str],
        negative_prompt: str | list[str] = "",
        *,
        steps: int = 30,
        cfg_scale: float = 5.0,
        shift: float = 5.0,
        height: int = 480,
        width: int = 832,
        frames: int = 81,
        rng=None,
        decode_tile: int = 0,
        callback=None,
        init_video: jnp.ndarray | None = None,
        denoise: float = 1.0,
    ) -> jnp.ndarray:
        """Returns float video (B, frames, height, width, 3) in [0, 1]. WAN uses
        true CFG (cfg_scale>1 with the negative prompt) and a large flow shift;
        ``frames`` must be ≡ 1 mod the VAE's temporal factor (81 by convention).
        video2video: pass ``init_video`` (B or 1, frames, height, width, 3 in
        [0, 1]) with ``denoise < 1`` — same truncated-schedule semantics as the
        image pipelines."""
        prompts = [prompt] if isinstance(prompt, str) else list(prompt)
        if rng is None:
            rng = jax.random.key(0)
        denoiser = self.dit
        if self.dit_low_noise is not None:
            from .models.experts import WAN22_T2V_BOUNDARY, TimestepExpertSwitch

            denoiser = TimestepExpertSwitch(
                self.dit, self.dit_low_noise,
                self.boundary if self.boundary is not None else WAN22_T2V_BOUNDARY,
            )
        f = self.vae.spatial_factor
        from .parallel.orchestrator import model_config_of

        patch = getattr(model_config_of(denoiser), "patch_size", (1, 2, 2))
        unit_h, unit_w = f * patch[1], f * patch[2]
        if height % unit_h or width % unit_w:
            raise ValueError(
                f"height/width must be multiples of {unit_h}/{unit_w}"
            )
        t_lat = self.vae.cfg.latent_frames(frames)  # validates the 4k+1 schedule
        if t_lat % patch[0]:
            raise ValueError(
                f"latent frame count {t_lat} not divisible by temporal patch "
                f"{patch[0]}"
            )

        context = self.encode_prompt(prompts)
        use_cfg = cfg_scale != 1.0
        uncond_context = None
        if use_cfg:
            uncond_context = self.encode_prompt(
                _match_negatives(prompts, negative_prompt)
            )

        B = len(prompts)
        zc = self.vae.cfg.z_channels
        noise = jax.random.normal(
            rng, (B, t_lat, height // f, width // f, zc), jnp.float32
        )
        init_latent = None
        if init_video is None:
            if denoise < 1.0:
                raise ValueError(
                    "denoise < 1 without an init_video — partial strength needs "
                    "a clip to preserve; pass init_video or drop denoise"
                )
        else:
            if denoise >= 1.0:
                raise ValueError(
                    "init_video given but denoise=1.0 — lower denoise "
                    "(strength) so the clip actually seeds the sampler"
                )
            if init_video.shape[1:4] != (frames, height, width):
                raise ValueError(
                    f"init_video is {init_video.shape[1:4]}, pipeline is "
                    f"({frames}, {height}, {width})"
                )
            from .models.vae import images_to_vae_input

            init_latent = self.vae.encode(images_to_vae_input(init_video))
            if init_latent.shape[0] == 1 and B > 1:
                init_latent = jnp.repeat(init_latent, B, axis=0)
        latents = run_sampler(
            denoiser,
            noise,
            context,
            sampler="flow_euler",
            steps=steps,
            shift=shift,
            guidance=None,
            cfg_scale=cfg_scale if use_cfg else 1.0,
            uncond_context=uncond_context,
            callback=callback,
            init_latent=init_latent,
            denoise=denoise,
        )
        from .models.vae import decode_maybe_tiled

        return _to_images(decode_maybe_tiled(self.vae, latents, decode_tile))
