"""End-to-end text→image pipelines: tokenize → encode → (parallel) denoise → decode.

The reference is a node pack inside a host app that owns this outer loop (ComfyUI
wires CLIPTextEncode → KSampler → VAEDecode around the reference's wrapped MODEL;
the reference only accelerates the per-step ``diffusion_model.forward``,
any_device_parallel.py:1287). Standalone, this module IS that outer loop. The
diffusion model slot accepts either a bare ``DiffusionModel`` or the
``ParallelModel`` returned by ``parallelize`` — every sampler step then routes
through the same DP/pipeline scheduler the reference's KSampler steps do.

TPU shape discipline: everything is fixed-shape per (batch, size, steps) combo —
the step loops re-enter the same compiled forward; only the scalars (t, sigma)
change. CFG doubles the batch inside one forward (feeding the DP path) instead of
running two forwards.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from .models.vae import vae_output_to_images as _to_images
from .sampling.runner import run_sampler


def _match_negatives(prompts: list[str], negative_prompt) -> list[str]:
    """Broadcast a str negative to the batch; validate list lengths at the API
    boundary (a mismatch otherwise surfaces as a cross-attention shape error deep
    inside the model)."""
    if isinstance(negative_prompt, str):
        return [negative_prompt] * len(prompts)
    negatives = list(negative_prompt)
    if len(negatives) != len(prompts):
        raise ValueError(
            f"negative_prompt list has {len(negatives)} entries for "
            f"{len(prompts)} prompts"
        )
    return negatives


def _encode_init(vae, init, denoise: float, batch: int,
                 expect: tuple[int, ...], what: str = "init_image",
                 allow_full_denoise: bool = False):
    """Strength-seeded sampling entry shared by ALL pipelines (img2img and
    video2video): validate the (denoise, init) pairing, check the pixel shape
    against ``expect`` (the dims after batch), encode, and broadcast a batch-1
    init to the prompt batch. ``allow_full_denoise`` lifts the denoise<1
    requirement (inpainting keeps regions via the mask even at full strength)."""
    if init is None:
        if denoise < 1.0:
            raise ValueError(
                f"denoise < 1 without an {what} — partial strength needs "
                f"something to preserve; pass {what} or drop denoise"
            )
        return None
    if denoise >= 1.0 and not allow_full_denoise:
        raise ValueError(
            f"{what} given but denoise=1.0 — lower denoise (strength) so it "
            "actually seeds the sampler"
        )
    from .models.vae import images_to_vae_input

    got = init.shape[1 : 1 + len(expect)]
    if tuple(got) != tuple(expect):
        raise ValueError(f"{what} is {got}, pipeline is {tuple(expect)}")
    z = vae.encode(images_to_vae_input(init))
    if z.shape[0] == 1 and batch > 1:
        z = jnp.repeat(z, batch, axis=0)
    return z


def _latent_mask_for(mask, init, f: int, height: int, width: int,
                     t_lat: int | None = None, what: str = "init_image"):
    """Inpainting mask → latent-resolution blend mask (1 = regenerate), shared
    by ALL pipelines so mask semantics cannot drift. Image masks are
    (B, H, W[, 1]); with ``t_lat`` set (video), masks are (B, T, H, W[, 1]) and
    the time axis resizes to the pipeline's latent frame count."""
    if mask is None:
        return None
    if init is None:
        raise ValueError(f"mask (inpainting) requires {what}")
    m = jnp.asarray(mask, jnp.float32)
    want_rank = 4 if t_lat is None else 5
    if m.ndim == want_rank - 1:
        m = m[..., None]
    if m.ndim != want_rank:
        raise ValueError(
            f"mask rank {jnp.asarray(mask).ndim} does not fit a "
            f"{'video' if t_lat is not None else 'image'} latent"
        )
    target = (
        (m.shape[0], height // f, width // f, 1)
        if t_lat is None
        else (m.shape[0], t_lat, height // f, width // f, 1)
    )
    return jax.image.resize(m, target, method="bilinear")


@dataclasses.dataclass
class StableDiffusionPipeline:
    """SD1.5 (clip only) / SDXL (clip + clip_g) text→image.

    ``unet`` may be a DiffusionModel or a ParallelModel (wrap with ``parallelize``
    first to run each denoise step across the device chain)."""

    unet: Any
    vae: Any
    clip: Any  # CLIP-L (SD1.5) or OpenCLIP-H (SD2.x) TextEncoder
    tokenizer: Any  # prompts -> (ids, mask)
    clip_g: Any = None  # SDXL second tower (OpenCLIP-G)
    tokenizer_g: Any = None
    # SD2.x conditions on the encoder's penultimate layer ("penultimate" —
    # with open_clip_h_config the tower already applies SD2's ln_final to it);
    # SD1.5 on the final layer-normed stream ("last").
    clip_layer: str = "last"

    @property
    def is_sdxl(self) -> bool:
        return self.clip_g is not None

    def encode_prompt(self, prompts: list[str], height: int, width: int):
        """Prompts → (context, y) conditioning for the UNet family in use."""
        ids, _ = self.tokenizer(prompts)
        last, penultimate, _pooled = self.clip(jnp.asarray(ids, jnp.int32))
        if not self.is_sdxl:
            if self.clip_layer not in ("last", "penultimate"):
                raise ValueError(
                    f"clip_layer must be 'last' or 'penultimate', got "
                    f"{self.clip_layer!r}"
                )
            return (penultimate if self.clip_layer == "penultimate" else last), None
        from .models.text_encoders import sdxl_text_conditioning

        ids_g, _ = (self.tokenizer_g or self.tokenizer)(prompts)
        _, pen_g, pooled_g = self.clip_g(jnp.asarray(ids_g, jnp.int32))
        return sdxl_text_conditioning(
            penultimate, pen_g, pooled_g, width=width, height=height
        )

    def __call__(
        self,
        prompt: str | list[str],
        negative_prompt: str | list[str] = "",
        *,
        steps: int = 30,
        cfg_scale: float = 7.5,
        height: int = 512,
        width: int = 512,
        rng=None,
        sampler: str = "dpmpp_2m",
        karras: bool = True,
        scheduler: str | None = None,
        callback=None,
        init_image: jnp.ndarray | None = None,
        denoise: float = 1.0,
        mask: jnp.ndarray | None = None,
        compile_loop: bool = False,
    ) -> jnp.ndarray:
        """Returns float images (B, height, width, 3) in [0, 1]. img2img: pass
        ``init_image`` (B or 1, height, width, 3 floats in [0, 1]) with
        ``denoise < 1`` — the sampler starts from the encoded image noised to
        the truncated schedule's head instead of pure noise. Inpainting: add
        ``mask`` (B or 1, height, width[, 1]; 1 = regenerate, 0 = keep the
        init_image region) — works at any denoise, including 1.0."""
        prompts = [prompt] if isinstance(prompt, str) else list(prompt)
        negatives = _match_negatives(prompts, negative_prompt)
        if rng is None:
            rng = jax.random.key(0)
        f = self.vae.spatial_factor
        if height % f or width % f:
            raise ValueError(f"height/width must be multiples of {f}")

        context, y = self.encode_prompt(prompts, height, width)
        use_cfg = cfg_scale != 1.0
        uncond_context = None
        uncond_kwargs = None
        if use_cfg:
            # The uncond half uses the negative prompt's own pooled y (SDXL) —
            # ComfyUI/diffusers semantics, carried via uncond_kwargs.
            uncond_context, uncond_y = self.encode_prompt(negatives, height, width)
            if uncond_y is not None:
                uncond_kwargs = {"y": uncond_y}

        B = len(prompts)
        zc = self.vae.cfg.z_channels
        noise = jax.random.normal(
            rng, (B, height // f, width // f, zc), jnp.float32
        )
        kwargs = {} if y is None else {"y": y}
        if sampler == "flow_euler":
            raise ValueError("flow_euler belongs to FluxPipeline, not the SD family")
        # Inpainting runs at any strength (mask keeps regions even at full
        # denoise) — one validated encode path either way.
        latent_mask = _latent_mask_for(mask, init_image, f, height, width)
        init_latent = _encode_init(
            self.vae, init_image, denoise, B, (height, width),
            allow_full_denoise=mask is not None,
        )
        from .parallel.orchestrator import model_config_of

        latents = run_sampler(
            self.unet,
            noise,
            context,
            init_latent=init_latent,
            denoise=denoise,
            latent_mask=latent_mask,
            prediction=getattr(model_config_of(self.unet), "prediction", "eps"),
            sampler=sampler,
            steps=steps,
            cfg_scale=cfg_scale if use_cfg else 1.0,
            uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs,
            rng=rng,
            karras=karras,
            scheduler=scheduler,
            callback=callback,
            compile_loop=compile_loop,
            **kwargs,
        )
        return _to_images(self.vae.decode(latents))


@dataclasses.dataclass
class FluxPipeline:
    """FLUX / Z-Image flow-matching text→image: T5 context + CLIP-L pooled vec."""

    dit: Any  # FLUX-class DiffusionModel or ParallelModel
    vae: Any  # 16-channel autoencoder
    clip: Any  # CLIP-L TextEncoder (pooled y)
    t5: Any  # T5 TextEncoder (context)
    tokenizer: Any  # CLIP tokenizer
    t5_tokenizer: Any

    def encode_prompt(self, prompts: list[str]):
        ids, _ = self.tokenizer(prompts)
        _, _, pooled = self.clip(jnp.asarray(ids, jnp.int32))
        t5_ids, t5_mask = self.t5_tokenizer(prompts)
        context = self.t5(jnp.asarray(t5_ids, jnp.int32), mask=jnp.asarray(t5_mask))
        return context, pooled

    def __call__(
        self,
        prompt: str | list[str],
        *,
        steps: int = 20,
        sampler: str = "flow_euler",
        guidance: float | None = 3.5,
        shift: float = 1.15,
        height: int = 1024,
        width: int = 1024,
        rng=None,
        negative_prompt: str | list[str] | None = None,
        cfg_scale: float = 1.0,
        callback=None,
        init_image: jnp.ndarray | None = None,
        denoise: float = 1.0,
        mask: jnp.ndarray | None = None,
        compile_loop: bool = False,
    ) -> jnp.ndarray:
        """Returns float images (B, height, width, 3) in [0, 1]. ``guidance`` is
        the dev-family distilled guidance embed (None for schnell); true CFG runs
        only when ``negative_prompt``+``cfg_scale>1`` are given."""
        prompts = [prompt] if isinstance(prompt, str) else list(prompt)
        if rng is None:
            rng = jax.random.key(0)
        f = self.vae.spatial_factor
        from .parallel.orchestrator import model_config_of

        patch = getattr(model_config_of(self.dit), "patch_size", 2)
        unit = f * patch  # VAE factor x DiT patchify
        if height % unit or width % unit:
            raise ValueError(f"height/width must be multiples of {unit}")
        context, pooled = self.encode_prompt(prompts)
        uncond_context = None
        uncond_kwargs = None
        kwargs: dict[str, Any] = {"y": pooled}
        use_cfg = cfg_scale != 1.0 and negative_prompt is not None
        if use_cfg:
            negatives = _match_negatives(prompts, negative_prompt)
            uncond_context, uncond_pooled = self.encode_prompt(negatives)
            uncond_kwargs = {"y": uncond_pooled}

        B = len(prompts)
        zc = self.vae.cfg.z_channels
        noise = jax.random.normal(
            rng, (B, height // f, width // f, zc), jnp.float32
        )
        latent_mask = _latent_mask_for(mask, init_image, f, height, width)
        init_latent = _encode_init(
            self.vae, init_image, denoise, B, (height, width),
            allow_full_denoise=mask is not None,
        )
        latents = run_sampler(
            self.dit,
            noise,
            context,
            sampler=sampler,
            prediction="flow",
            steps=steps,
            shift=shift,
            guidance=guidance,
            cfg_scale=cfg_scale if use_cfg else 1.0,
            uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs,
            callback=callback,
            compile_loop=compile_loop,
            init_latent=init_latent,
            denoise=denoise,
            latent_mask=latent_mask,
            **kwargs,
        )
        return _to_images(self.vae.decode(latents))


@dataclasses.dataclass
class WanVideoPipeline:
    """WAN text→video: UMT5-class context + flow matching + causal 3D VAE.

    The reference's WAN2.2 workload (/root/reference/README.md:5) runs this loop
    inside ComfyUI with the wrapped denoiser; standalone, this drives the same
    per-step parallel routing over a video latent (batch=1 video is exactly the
    reference's pipeline-mode shape, any_device_parallel.py:1295-1305 — here the
    temporal token axis keeps the MXU fed instead)."""

    dit: Any  # WAN-class DiffusionModel or ParallelModel
    vae: Any  # VideoVAE (causal 3D)
    t5: Any  # UMT5/T5 TextEncoder (context)
    t5_tokenizer: Any
    # WAN2.2 A14B: a second low-noise expert makes ``dit`` the high-noise one
    # and every step routes by flow time (models/experts.py).
    dit_low_noise: Any = None
    boundary: float | None = None

    def encode_prompt(self, prompts: list[str]):
        ids, mask = self.t5_tokenizer(prompts)
        return self.t5(jnp.asarray(ids, jnp.int32), mask=jnp.asarray(mask))

    def __call__(
        self,
        prompt: str | list[str],
        negative_prompt: str | list[str] = "",
        *,
        steps: int = 30,
        sampler: str = "flow_euler",
        cfg_scale: float = 5.0,
        shift: float = 5.0,
        height: int = 480,
        width: int = 832,
        frames: int = 81,
        rng=None,
        decode_tile: int = 0,
        callback=None,
        init_video: jnp.ndarray | None = None,
        denoise: float = 1.0,
        image: jnp.ndarray | None = None,
        mask: jnp.ndarray | None = None,
        clip_vision_output: Any | None = None,
        compile_loop: bool = False,
    ) -> jnp.ndarray:
        """Returns float video (B, frames, height, width, 3) in [0, 1]. WAN uses
        true CFG (cfg_scale>1 with the negative prompt) and a large flow shift;
        ``frames`` must be ≡ 1 mod the VAE's temporal factor (81 by convention).
        video2video: pass ``init_video`` (B or 1, frames, height, width, 3 in
        [0, 1]) with ``denoise < 1`` — same truncated-schedule semantics as the
        image pipelines. image→video: pass ``image`` (B or 1, height, width, 3
        in [0, 1]) — WAN2.2-style channel-concat conditioning (the i2v DiT's
        extra in-channels carry a frame mask + the encoded first frame).
        WAN2.1-style i2v checkpoints (config ``img_dim`` set) additionally
        take ``clip_vision_output`` (a CLIP_VISION_OUTPUT dict or a raw
        (B|1, 257, img_dim) penultimate-states array) routed through the
        model's img_emb branch. Video inpainting: ``mask``
        (B or 1, frames, height, width[, 1]; 1 = regenerate) with
        ``init_video`` re-pins keep regions per step at any denoise."""
        prompts = [prompt] if isinstance(prompt, str) else list(prompt)
        if rng is None:
            rng = jax.random.key(0)
        denoiser = self.dit
        if self.dit_low_noise is not None:
            from .models.experts import (
                WAN22_I2V_BOUNDARY,
                WAN22_T2V_BOUNDARY,
                TimestepExpertSwitch,
            )

            default_boundary = (
                WAN22_I2V_BOUNDARY if image is not None else WAN22_T2V_BOUNDARY
            )
            denoiser = TimestepExpertSwitch(
                self.dit, self.dit_low_noise,
                self.boundary if self.boundary is not None else default_boundary,
            )
        f = self.vae.spatial_factor
        from .parallel.orchestrator import model_config_of

        patch = getattr(model_config_of(denoiser), "patch_size", (1, 2, 2))
        unit_h, unit_w = f * patch[1], f * patch[2]
        if height % unit_h or width % unit_w:
            raise ValueError(
                f"height/width must be multiples of {unit_h}/{unit_w}"
            )
        t_lat = self.vae.cfg.latent_frames(frames)  # validates the 4k+1 schedule
        if t_lat % patch[0]:
            raise ValueError(
                f"latent frame count {t_lat} not divisible by temporal patch "
                f"{patch[0]}"
            )

        context = self.encode_prompt(prompts)
        use_cfg = cfg_scale != 1.0
        uncond_context = None
        if use_cfg:
            uncond_context = self.encode_prompt(
                _match_negatives(prompts, negative_prompt)
            )

        B = len(prompts)
        zc = self.vae.cfg.z_channels
        noise = jax.random.normal(
            rng, (B, t_lat, height // f, width // f, zc), jnp.float32
        )
        latent_mask = _latent_mask_for(
            mask, init_video, f, height, width, t_lat=t_lat, what="init_video"
        )
        init_latent = _encode_init(
            self.vae, init_video, denoise, B, (frames, height, width),
            what="init_video", allow_full_denoise=mask is not None,
        )
        if image is not None:
            denoiser = self._i2v_conditioned(
                denoiser, image, B, frames, height, width, t_lat, zc,
                clip_vision_output=clip_vision_output,
            )
        elif clip_vision_output is not None:
            raise ValueError(
                "clip_vision_output without `image` — the CLIP branch rides "
                "the i2v conditioning; pass the start image too"
            )
        latents = run_sampler(
            denoiser,
            noise,
            context,
            sampler=sampler,
            prediction="flow",
            steps=steps,
            shift=shift,
            guidance=None,
            cfg_scale=cfg_scale if use_cfg else 1.0,
            uncond_context=uncond_context,
            callback=callback,
            compile_loop=compile_loop,
            init_latent=init_latent,
            denoise=denoise,
            latent_mask=latent_mask,
        )
        from .models.vae import decode_maybe_tiled

        return _to_images(decode_maybe_tiled(self.vae, latents, decode_tile))

    def _i2v_conditioned(
        self, denoiser, image, B, frames, height, width, t_lat, zc,
        clip_vision_output=None,
    ):
        """Wrap ``denoiser`` with WAN i2v channel-concat conditioning: the DiT's
        extra in-channels carry [frame mask (4ch) ‖ encoded first-frame latent]
        alongside the noisy latent. The cond tensor is fixed across steps, so
        one wrapper closure serves every sampler call (and every expert)."""
        from .models.vae import images_to_vae_input
        from .parallel.orchestrator import model_config_of

        cfg = model_config_of(denoiser)
        expect = zc + 4 + zc
        got_in = getattr(cfg, "in_channels", None)
        if got_in is not None and got_in != expect:
            raise ValueError(
                f"image→video needs an i2v checkpoint with in_channels="
                f"{expect} (latent {zc} + mask 4 + cond {zc}); this model has "
                f"{got_in} — load the i2v variant or drop `image`"
            )
        if image.shape[1:3] != (height, width):
            raise ValueError(
                f"image is {image.shape[1:3]}, pipeline is ({height}, {width})"
            )
        if image.shape[0] == 1 and B > 1:
            image = jnp.repeat(image, B, axis=0)
        # Conditioning clip: the image as frame 0, zeros after — encoded by the
        # same causal VAE, so the first latent frame holds the image.
        clip = jnp.concatenate(
            [
                images_to_vae_input(image)[:, None],
                jnp.zeros((B, frames - 1, height, width, image.shape[-1])),
            ],
            axis=1,
        )
        cond_latent = self.vae.encode(clip)
        # 4-channel frame mask (one channel per pixel frame a latent frame
        # folds): first latent frame = given, rest = generated.
        h, w = cond_latent.shape[2], cond_latent.shape[3]
        mask = jnp.zeros((B, t_lat, h, w, 4)).at[:, 0].set(1.0)
        cond = jnp.concatenate([mask, cond_latent], axis=-1)

        clip_fea = None
        if clip_vision_output is not None:
            if getattr(cfg, "img_dim", None) is None:
                raise ValueError(
                    "clip_vision_output needs a WAN2.1-style i2v checkpoint "
                    "with the img_emb branch (config img_dim) — this model "
                    "has none (WAN2.2 i2v conditions by channel-concat only); "
                    "drop clip_vision_output"
                )
            clip_fea = (
                clip_vision_output["penultimate"]
                if isinstance(clip_vision_output, dict)
                else jnp.asarray(clip_vision_output)
            )
            if clip_fea.shape[0] == 1 and B > 1:
                clip_fea = jnp.repeat(clip_fea, B, axis=0)

        def conditioned(x, t, context=None, **kw):
            c = cond
            fea = clip_fea
            if x.shape[0] != c.shape[0]:
                # CFG doubles the batch (cond ‖ uncond in one forward) — the
                # conditioning rides along for both halves.
                reps = x.shape[0] // c.shape[0]
                c = jnp.tile(c, (reps, 1, 1, 1, 1))
                if fea is not None:
                    fea = jnp.tile(fea, (reps, 1, 1))
            if fea is not None:
                kw = {**kw, "clip_fea": fea}
            return denoiser(jnp.concatenate([x, c], axis=-1), t, context, **kw)

        return conditioned


@dataclasses.dataclass
class Sd3Pipeline:
    """SD3/SD3.5 flow-matching text→image: CLIP-L + CLIP-G joint stream padded
    into the T5 context, L⊕G pooled vector, true CFG, large flow shift."""

    dit: Any  # MMDiT-class DiffusionModel or ParallelModel
    vae: Any  # 16-channel SD3 autoencoder
    clip: Any  # CLIP-L TextEncoder
    clip_g: Any  # OpenCLIP-G TextEncoder
    tokenizer: Any
    tokenizer_g: Any = None
    t5: Any = None  # optional (SD3 runs without T5 at reduced quality)
    t5_tokenizer: Any = None

    def encode_prompt(self, prompts: list[str]):
        from .models.text_encoders import sd3_text_conditioning
        from .parallel.orchestrator import model_config_of

        ids, _ = self.tokenizer(prompts)
        _, pen_l, pooled_l = self.clip(jnp.asarray(ids, jnp.int32))
        ids_g, _ = (self.tokenizer_g or self.tokenizer)(prompts)
        _, pen_g, pooled_g = self.clip_g(jnp.asarray(ids_g, jnp.int32))
        t5_ctx = None
        if self.t5 is not None:
            if self.t5_tokenizer is None:
                raise ValueError(
                    "t5 encoder set without t5_tokenizer — the CLIP BPE "
                    "tokenizer's ids are meaningless to the T5 vocab"
                )
            t5_ids, t5_mask = self.t5_tokenizer(prompts)
            t5_ctx = self.t5(
                jnp.asarray(t5_ids, jnp.int32), mask=jnp.asarray(t5_mask)
            )
        ctx_dim = getattr(model_config_of(self.dit), "context_in_dim", 4096)
        return sd3_text_conditioning(
            pen_l, pen_g, pooled_l, pooled_g, t5_ctx, context_dim=ctx_dim
        )

    def __call__(
        self,
        prompt: str | list[str],
        negative_prompt: str | list[str] = "",
        *,
        steps: int = 28,
        sampler: str = "flow_euler",
        cfg_scale: float = 4.5,
        shift: float = 3.0,
        height: int = 1024,
        width: int = 1024,
        rng=None,
        callback=None,
        init_image: jnp.ndarray | None = None,
        denoise: float = 1.0,
        mask: jnp.ndarray | None = None,
        compile_loop: bool = False,
    ) -> jnp.ndarray:
        """Returns float images (B, height, width, 3) in [0, 1]; same
        img2img/inpaint contract as the other image pipelines."""
        prompts = [prompt] if isinstance(prompt, str) else list(prompt)
        if rng is None:
            rng = jax.random.key(0)
        f = self.vae.spatial_factor
        from .parallel.orchestrator import model_config_of

        patch = getattr(model_config_of(self.dit), "patch_size", 2)
        unit = f * patch
        if height % unit or width % unit:
            raise ValueError(f"height/width must be multiples of {unit}")

        context, y = self.encode_prompt(prompts)
        use_cfg = cfg_scale != 1.0
        uncond_context = None
        uncond_kwargs = None
        if use_cfg:
            uncond_context, uncond_y = self.encode_prompt(
                _match_negatives(prompts, negative_prompt)
            )
            uncond_kwargs = {"y": uncond_y}

        B = len(prompts)
        zc = self.vae.cfg.z_channels
        noise = jax.random.normal(
            rng, (B, height // f, width // f, zc), jnp.float32
        )
        latent_mask = _latent_mask_for(mask, init_image, f, height, width)
        init_latent = _encode_init(
            self.vae, init_image, denoise, B, (height, width),
            allow_full_denoise=mask is not None,
        )
        latents = run_sampler(
            self.dit,
            noise,
            context,
            sampler=sampler,
            prediction="flow",
            steps=steps,
            shift=shift,
            cfg_scale=cfg_scale if use_cfg else 1.0,
            uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs,
            callback=callback,
            compile_loop=compile_loop,
            init_latent=init_latent,
            denoise=denoise,
            latent_mask=latent_mask,
            y=y,
        )
        return _to_images(self.vae.decode(latents))
